"""Distributed optimizer for PyTorch.

Same machinery as the reference (reference: horovod/torch/optimizer.py):
per-parameter post-accumulate-grad hooks fire an async (optionally
grouped) allreduce the moment each gradient is ready, overlapping
communication with the rest of backward; `synchronize()` drains the
handles and installs the reduced gradients before `step()`.

Differences from the reference are TPU-motivated only: the wire runs over
the horovod_tpu core (XLA/TCP data plane) instead of NCCL, and a bf16
compressor is available alongside fp16.
"""
from __future__ import annotations

import warnings
from contextlib import contextmanager

import torch

from .. import Adasum, Average, Sum
from .compression import Compression
from .mpi_ops import allreduce_async, grouped_allreduce_async, size


class _DistributedOptimizer(torch.optim.Optimizer):
    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step=1,
                 op=Average,
                 gradient_predivide_factor=1.0,
                 groups=None,
                 sparse_as_dense=False):
        # super() here is the wrapped optimizer class (SGD/Adam/...);
        # param_groups dicts carry every option, so its defaults are
        # never consulted.
        super(self.__class__, self).__init__(params)
        self._compression = Compression.resolve(compression)
        # Codec marker classes (int8/uint4) delegate the actual
        # quantization to the runtime's data planes; the wire_codec tag
        # rides every allreduce this optimizer fires.
        self._wire_codec = getattr(self._compression, "wire_codec", None)
        self.op = op
        self.gradient_predivide_factor = gradient_predivide_factor
        self.backward_passes_per_step = backward_passes_per_step
        self._sparse_as_dense = sparse_as_dense

        named_parameters = list(named_parameters or [])
        if named_parameters:
            if not all(isinstance(k, str) for k, _ in named_parameters):
                raise ValueError(
                    "named_parameters should be a sequence of (name, "
                    "parameter) tuples")
            all_param_ids = {id(v) for group in self.param_groups
                            for v in group["params"]}
            named_ids = {id(v) for _, v in named_parameters}
            unnamed = all_param_ids - named_ids
            if unnamed:
                raise ValueError(
                    f"{len(unnamed)} parameters were not named; name all "
                    "parameters passed to DistributedOptimizer")
            self._parameter_names = {v: k for k, v in named_parameters}
        else:
            self._parameter_names = {
                v: f"allreduce.noname.{i}.{j}"
                for i, group in enumerate(self.param_groups)
                for j, v in enumerate(group["params"])}

        self._handles: dict = {}
        self._grad_accs: list = []
        self._requires_update: set = set()
        self._synchronized = False
        self._should_synchronize = True
        self._allreduce_delay = {}
        self._groups = self._build_groups(groups)
        self._group_counts: dict[int, int] = {}
        if size() > 1:
            self._register_hooks()

    # -- grouping (reference: optimizer.py groups argument) ----------------
    def _build_groups(self, groups):
        params = [v for group in self.param_groups for v in group["params"]
                  if v.requires_grad]
        if groups is None:
            return None
        if isinstance(groups, int):
            if groups <= 0:
                return None
            buckets: list[list] = [[] for _ in range(min(groups,
                                                         len(params)))]
            for i, p in enumerate(params):
                buckets[i % len(buckets)].append(p)
            groups = buckets
        group_of = {}
        for gi, group in enumerate(groups):
            for p in group:
                group_of[p] = gi
        self._group_members = [list(g) for g in groups]
        return group_of

    # -- hooks (reference: optimizer.py:128-171,219-247) -------------------
    def _register_hooks(self):
        for param_group in self.param_groups:
            for p in param_group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = self.backward_passes_per_step
                    acc = p.register_post_accumulate_grad_hook(
                        self._make_hook(p))
                    self._grad_accs.append(acc)

    def _make_hook(self, p):
        def hook(*_):
            if p in self._handles and self._handles[p][0] is not None:
                if self._allreduce_delay[p] <= 0:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before call to "
                        "step(). Increase backward_passes_per_step to "
                        "accumulate gradients locally.")
            assert not p.grad.requires_grad
            assert self._allreduce_delay[p] > 0
            self._allreduce_delay[p] -= 1
            if self._allreduce_delay[p] == 0:
                if self._groups is not None and p in self._groups:
                    self._grouped_allreduce_maybe(p)
                else:
                    handle, ctx = self._allreduce_grad_async(p)
                    self._handles[p] = (handle, ctx)
        return hook

    def _grouped_allreduce_maybe(self, p):
        gi = self._groups[p]
        self._handles[p] = (None, None)
        self._group_counts[gi] = self._group_counts.get(gi, 0) + 1
        members = [q for q in self._group_members[gi]
                   if q in self._requires_update]
        if self._group_counts[gi] == len(members):
            self._group_counts[gi] = 0
            handle, ctxs = self._grouped_allreduce_grad_async(members)
            for q in members:
                self._handles[q] = (handle, ctxs)

    def _grad_for_wire(self, p) -> torch.Tensor:
        grad = p.grad
        if grad.is_sparse:
            if not self._sparse_as_dense:
                raise ValueError(
                    "Sparse gradients inside grouped allreduce require "
                    "sparse_as_dense=True; the per-parameter path handles "
                    "them via gather-based sparse_allreduce.")
            grad = grad.to_dense()
        return grad

    def _scale_factors(self):
        if self.gradient_predivide_factor != 1.0:
            # Average == pre/size ∘ post·size: splitting the division
            # controls overflow for fp16 wires
            # (reference: optimizer.py gradient_predivide_factor).
            prescale = 1.0 / self.gradient_predivide_factor
            postscale = self.gradient_predivide_factor / size() \
                if self.op == Average else self.gradient_predivide_factor
            return prescale, postscale, Sum
        return 1.0, 1.0, self.op

    def _allreduce_grad_async(self, p):
        name = self._parameter_names.get(p)
        if p.grad is not None and p.grad.is_sparse and \
                not self._sparse_as_dense:
            # Gather-based sparse reduction (reference: optimizer.py
            # sparse path → mpi_ops.sparse_allreduce_async); synchronous
            # by nature, so the result installs immediately and
            # synchronize() has nothing to wait on.
            from .mpi_ops import sparse_allreduce
            p.grad = sparse_allreduce(p.grad, name=f"sparse.{name}",
                                      op=self.op)
            return None, None
        tensor_compressed, ctx = self._compression.compress(
            self._grad_for_wire(p))
        prescale, postscale, op = self._scale_factors()
        handle = allreduce_async(tensor_compressed, name=name, op=op,
                                 prescale_factor=prescale,
                                 postscale_factor=postscale,
                                 compression=self._wire_codec)
        return handle, (tensor_compressed, ctx)

    def _grouped_allreduce_grad_async(self, ps):
        name = self._parameter_names.get(ps[0])
        compressed = [self._compression.compress(self._grad_for_wire(p))
                      for p in ps]
        tensors = [t for t, _ in compressed]
        prescale, postscale, op = self._scale_factors()
        handle = grouped_allreduce_async(
            tensors, name=f"group.{name}", op=op,
            prescale_factor=prescale, postscale_factor=postscale,
            compression=self._wire_codec)
        return handle, compressed

    # -- synchronize / step (reference: optimizer.py:249-332) --------------
    def synchronize(self):
        if size() <= 1:
            self._synchronized = True
            return
        # Fire allreduce for any parameter whose hook never ran (e.g. grad
        # not produced this step but set manually).
        missing = [p for p in self._requires_update
                   if p not in self._handles]
        for p in missing:
            if p.grad is None:
                continue
            handle, ctx = self._allreduce_grad_async(p)
            self._handles[p] = (handle, ctx)

        done_handles = set()
        for p, (handle, ctx) in list(self._handles.items()):
            if handle is None:
                continue
            if id(handle) in done_handles:
                continue
            done_handles.add(id(handle))
            handle.wait().raise_if_error()

        installed = set()
        for p, (handle, ctx) in self._handles.items():
            if handle is not None and id(handle) not in installed:
                installed.add(id(handle))
                if isinstance(ctx, list):      # grouped: ctx per member
                    members = [q for q in
                               self._group_members[self._groups[p]]
                               if q in self._requires_update]
                    outputs = handle.outputs()
                    for q, (tc, c), out in zip(members, ctx, outputs):
                        self._install_grad(q, tc, c, out)
                else:
                    tc, c = ctx
                    self._install_grad(p, tc, c, handle.outputs()[0])
            self._allreduce_delay[p] = self.backward_passes_per_step
        self._handles.clear()
        self._synchronized = True

    def _install_grad(self, p, tensor_compressed, c, out_np):
        out = torch.from_numpy(out_np.copy()).view_as(tensor_compressed) \
            .type(tensor_compressed.dtype)
        grad = self._compression.decompress(out, c)
        p.grad = grad.type(p.dtype).view_as(p.grad if not p.grad.is_sparse
                                            else grad)

    @contextmanager
    def skip_synchronize(self):
        """Use when calling `synchronize()` manually before `step()`
        (reference: optimizer.py skip_synchronize)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                warnings.warn(
                    "optimizer.step() called without triggering a new "
                    "backward pass; called synchronize() twice?")
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize(). "
                "This is prohibited as it can cause a race condition.")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


class _DistributedAdasumOptimizer(torch.optim.Optimizer):
    """Adasum delta-optimizer (reference: torch/optimizer.py:335-503).

    Protocol per parameter, per communication step: snapshot the starting
    value, run the WRAPPED optimizer locally (p becomes start - lr·f(g)),
    ship the parameter delta through a scale-adaptive Adasum allreduce,
    then apply the combined delta to the starting point.  Unlike gradient
    averaging this composes the per-rank optimizer updates themselves, so
    it tolerates per-rank learning-rate scale (the Adasum paper's headline
    property).

    The communication happens at grad-ready time via per-parameter hooks
    (overlapping with the rest of backward); parameters are restored to
    their starting values until ``step()`` installs the combined delta, so
    the model never observes a half-applied local update."""

    def __init__(self, params, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step=1):
        super(self.__class__, self).__init__(params)
        self._compression = Compression.resolve(compression)
        if getattr(self._compression, "wire_codec", None) in \
                ("int8", "uint4"):
            raise ValueError(
                "op=Adasum does not compose with quantized compression "
                "(int8/uint4); use none, fp16 or bf16.")
        self.backward_passes_per_step = backward_passes_per_step

        named_parameters = list(named_parameters or [])
        if named_parameters:
            if not all(isinstance(k, str) for k, _ in named_parameters):
                raise ValueError(
                    "named_parameters should be a sequence of (name, "
                    "parameter) tuples")
            all_param_ids = {id(v) for group in self.param_groups
                             for v in group["params"]}
            named_ids = {id(v) for _, v in named_parameters}
            unnamed = all_param_ids - named_ids
            if unnamed:
                raise ValueError(
                    f"{len(unnamed)} parameters were not named; name all "
                    "parameters passed to DistributedOptimizer")
            self._parameter_names = {v: k for k, v in named_parameters}
        else:
            self._parameter_names = {
                v: f"adasum.noname.{i}.{j}"
                for i, group in enumerate(self.param_groups)
                for j, v in enumerate(group["params"])}

        self._handles: dict = {}
        self._grad_accs: list = []
        self._requires_update: set = set()
        self._allreduce_delay = {}
        self._starting = {}
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = backward_passes_per_step
                    self._starting[p] = torch.zeros_like(
                        p, requires_grad=False)
                    if size() > 1:
                        acc = p.register_post_accumulate_grad_hook(
                            self._make_hook(p))
                        self._grad_accs.append(acc)

    def _make_hook(self, p):
        def hook(*_):
            assert self._allreduce_delay[p] > 0
            self._allreduce_delay[p] -= 1
            if self._allreduce_delay[p] == 0:
                self._handles[p] = self._delta_allreduce_async(p)
        return hook

    def _delta_allreduce_async(self, p):
        """Local inner-optimizer step on `p` alone → async Adasum of the
        resulting delta; `p` is rolled back to its starting value."""
        name = self._parameter_names.get(p)
        start = self._starting[p]
        start.copy_(p.detach())

        stashed = []
        for group in self.param_groups:
            stashed.append(group["params"])
            group["params"] = [p] if any(p is v for v in group["params"]) \
                else []
        try:
            super(self.__class__, self).step()
        finally:
            for params, group in zip(stashed, self.param_groups):
                group["params"] = params

        delta = p.detach() - start
        p.data.copy_(start)
        tensor_compressed, ctx = self._compression.compress(delta)
        handle = allreduce_async(tensor_compressed, name=f"adasum.{name}",
                                 op=Adasum)
        return handle, (tensor_compressed, ctx)

    def synchronize(self):
        """No-op: Adasum synchronization is fused into step() (reference:
        _DistributedAdasumOptimizer.synchronize)."""

    @contextmanager
    def skip_synchronize(self):
        raise AssertionError(
            "Skipping synchronization is not supported when using the "
            "Adasum optimizer.")

    def step(self, closure=None):
        loss = closure() if closure is not None else None
        if size() <= 1:
            super(self.__class__, self).step()
            return loss
        for p in self._requires_update - set(self._handles):
            self._handles[p] = self._delta_allreduce_async(p)
        for p, (handle, (tensor_compressed, ctx)) in \
                list(self._handles.items()):
            handle.wait().raise_if_error()
            out = torch.from_numpy(handle.outputs()[0].copy()) \
                .view_as(tensor_compressed).type(tensor_compressed.dtype)
            delta = self._compression.decompress(out, ctx).type(p.dtype)
            start = self._starting[p]
            start.add_(delta.view_as(start))
            p.data.copy_(start)
            self._allreduce_delay[p] = self.backward_passes_per_step
        self._handles.clear()
        return loss

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize(). "
                "This is prohibited as it can cause a race condition.")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         op=Average,
                         gradient_predivide_factor=1.0,
                         groups=None,
                         sparse_as_dense=False):
    """Wrap a torch optimizer for data-parallel training
    (reference: horovod/torch/optimizer.py DistributedOptimizer).

    The returned object is an instance of a dynamically created subclass
    of the input optimizer's class, so isinstance checks and LR schedulers
    keep working.  ``op=Adasum`` returns the delta-optimizer variant
    (reference: torch/optimizer.py:335-503).
    """
    if op == Adasum:
        if gradient_predivide_factor != 1.0:
            raise ValueError(
                "gradient_predivide_factor is not supported with "
                "op=Adasum (the delta, not the gradient, is reduced)")
        if groups is not None:
            raise ValueError("groups are not supported with op=Adasum")
        cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
                   dict(_DistributedAdasumOptimizer.__dict__))
        obj = cls.__new__(cls)
        _DistributedAdasumOptimizer.__init__(
            obj, optimizer.param_groups, named_parameters, compression,
            backward_passes_per_step)
        obj.load_state_dict(optimizer.state_dict())
        return obj
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    obj = cls.__new__(cls)
    _DistributedOptimizer.__init__(
        obj, optimizer.param_groups, named_parameters, compression,
        backward_passes_per_step, op, gradient_predivide_factor, groups,
        sparse_as_dense)
    obj.load_state_dict(optimizer.state_dict())
    return obj
