"""Block-wise affine quantization for gradient wire compression (numpy).

The EQuARX shape (PAPERS.md, arxiv 2506.17615): a flat fp buffer is split
into fixed-size blocks; each block is quantized independently with an
affine map

    q = round((x - zero_point) / scale),   scale = (max - min) / (L - 1)

where ``L`` is the number of levels (256 for the int8 codec, 16 for
uint4).  Per-block scaling bounds the element-wise reconstruction error by
``scale / 2`` — i.e. half the block's dynamic range divided by (L-1) —
so one outlier only degrades its own block, not the whole buffer (the
property that makes block quantization viable for gradients, where a few
large entries coexist with a sea of small ones).

This module is the HOST-side implementation shared by the eager planes
(tcp/shm/xla); the compiled grad_sync path uses the pure-jax twin in
``compress/jax_ops.py`` with identical semantics (same rounding, same
scale rule) so all planes land inside the same documented error bound.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from . import CompressionCodec, codec_levels

# Per-block wire overhead: one fp32 scale + one fp32 zero point.
_BLOCK_META_BYTES = 8


def num_blocks(n: int, block_size: int) -> int:
    return -(-n // block_size) if n else 0


def payload_nbytes(n: int, codec: CompressionCodec) -> int:
    """Quantized-value bytes for ``n`` elements (uint4 packs two per byte)."""
    if codec == CompressionCodec.UINT4:
        return (n + 1) // 2
    return n


def serialized_nbytes(n: int, codec: CompressionCodec,
                      block_size: int) -> int:
    """Total wire bytes: scales || zero_points || payload."""
    return num_blocks(n, block_size) * _BLOCK_META_BYTES \
        + payload_nbytes(n, codec)


@dataclasses.dataclass
class QuantizedBlocks:
    """One quantized flat buffer: per-block scale/zero-point + packed
    values.  ``n`` is the ORIGINAL element count (payload may carry a pad
    nibble for odd-length uint4 buffers)."""
    codec: CompressionCodec
    n: int
    block_size: int
    scales: np.ndarray        # fp32 [nb]
    zero_points: np.ndarray   # fp32 [nb]
    payload: np.ndarray       # uint8 [payload_nbytes(n, codec)]

    def nbytes(self) -> int:
        return self.scales.nbytes + self.zero_points.nbytes \
            + self.payload.nbytes


def quantize(flat, codec: CompressionCodec,
             block_size: int) -> QuantizedBlocks:
    """Quantize a flat floating buffer blockwise.  Always computes in
    fp32 (the accumulation dtype contract shared with the planes)."""
    x = np.asarray(flat, dtype=np.float32).reshape(-1)
    n = x.size
    levels = codec_levels(codec)
    nb = num_blocks(n, block_size)
    if nb == 0:
        return QuantizedBlocks(codec, 0, block_size,
                               np.zeros(0, np.float32),
                               np.zeros(0, np.float32),
                               np.zeros(0, np.uint8))
    pad = nb * block_size - n
    if pad:
        # Pad with the last element so the tail block's min/max (and
        # therefore its scale) is not polluted by synthetic zeros.
        x = np.concatenate([x, np.full(pad, x[-1], np.float32)])
    blocks = x.reshape(nb, block_size)
    lo = blocks.min(axis=1)
    hi = blocks.max(axis=1)
    scales = (hi - lo) / np.float32(levels - 1)
    scales = np.where(scales > 0, scales, np.float32(1.0)).astype(np.float32)
    q = np.rint((blocks - lo[:, None]) / scales[:, None])
    q = np.clip(q, 0, levels - 1).astype(np.uint8).reshape(-1)[:n]
    if codec == CompressionCodec.UINT4:
        if n % 2:
            q = np.concatenate([q, np.zeros(1, np.uint8)])
        payload = (q[0::2] << 4) | q[1::2]
    else:
        payload = q
    return QuantizedBlocks(codec, n, block_size, scales,
                           lo.astype(np.float32), payload)


def dequantize(qb: QuantizedBlocks, dtype=np.float32) -> np.ndarray:
    """Reconstruct the flat buffer: x̂ = q·scale + zero_point (fp32 math,
    cast to ``dtype`` at the end)."""
    n = qb.n
    if n == 0:
        return np.zeros(0, dtype=dtype)
    if qb.codec == CompressionCodec.UINT4:
        q = np.empty(qb.payload.size * 2, np.uint8)
        q[0::2] = qb.payload >> 4
        q[1::2] = qb.payload & 0x0F
        q = q[:n]
    else:
        q = qb.payload
    scales = np.repeat(qb.scales, qb.block_size)[:n]
    zps = np.repeat(qb.zero_points, qb.block_size)[:n]
    out = q.astype(np.float32) * scales + zps
    return out.astype(dtype, copy=False)


def to_bytes(qb: QuantizedBlocks) -> bytes:
    """Wire encoding: scales || zero_points || payload.  Sizes are fully
    derivable from (n, codec, block_size), which every rank knows from the
    negotiated Response — no header needed."""
    return qb.scales.tobytes() + qb.zero_points.tobytes() \
        + qb.payload.tobytes()


def from_bytes(raw, n: int, codec: CompressionCodec,
               block_size: int) -> QuantizedBlocks:
    buf = np.frombuffer(raw, dtype=np.uint8)
    nb = num_blocks(n, block_size)
    meta = nb * 4
    scales = buf[:meta].view(np.float32)
    zps = buf[meta:2 * meta].view(np.float32)
    payload = buf[2 * meta:2 * meta + payload_nbytes(n, codec)]
    return QuantizedBlocks(codec, n, block_size, scales, zps, payload)


def roundtrip_error_bound(flat, codec: CompressionCodec,
                          block_size: int) -> np.ndarray:
    """Per-element worst-case |x - dequantize(quantize(x))|: half a
    quantization step of the element's block."""
    x = np.asarray(flat, dtype=np.float32).reshape(-1)
    n = x.size
    nb = num_blocks(n, block_size)
    if nb == 0:
        return np.zeros(0, np.float32)
    pad = nb * block_size - n
    if pad:
        x = np.concatenate([x, np.full(pad, x[-1], np.float32)])
    blocks = x.reshape(nb, block_size)
    step = (blocks.max(1) - blocks.min(1)) / np.float32(
        codec_levels(codec) - 1)
    return (np.repeat(step, block_size)[:n] / 2).astype(np.float32)


def chunk_bounds(n: int, size: int) -> np.ndarray:
    """Even element-chunk boundaries for the owner-reduce exchange: chunk
    r = [bounds[r], bounds[r+1]), the first ``rem`` chunks one element
    longer (the same split rule as the ring planes)."""
    base, rem = divmod(n, size)
    sizes = [base + (1 if i < rem else 0) for i in range(size)]
    return np.cumsum([0] + sizes)


def staged_nbytes(n: int, size: int, codec: CompressionCodec,
                  block_size: int) -> tuple[list[int], int]:
    """(per-chunk serialized bytes, total) for a buffer of ``n`` elements
    split into ``size`` owner chunks — the shm plane's region accounting
    and the deterministic chunk offsets every plane shares."""
    bounds = chunk_bounds(n, size)
    per_chunk = [serialized_nbytes(int(bounds[r + 1] - bounds[r]),
                                   codec, block_size)
                 for r in range(size)]
    return per_chunk, sum(per_chunk)
