"""Error-feedback residual accumulators (EF-SGD, Karimireddy et al.).

Quantization is lossy; without compensation the per-step error is simply
discarded and biased codecs stall convergence.  Error feedback keeps a
per-tensor residual ``e``:

    compensated = grad + e
    wire        = quantize(compensated)
    e'          = compensated - dequantize(wire)

so every bit of quantization error re-enters the optimizer on the next
step — the standard result is that EF recovers the uncompressed
convergence rate for arbitrary contractive compressors.

Two forms live here:
- :class:`ErrorFeedback` — a name-keyed numpy store for the eager /
  framework-binding paths (one residual per named gradient).
- the functional jax form is ``compress.jax_ops.quantized_allreduce``
  with ``residual=...`` (state threads through the compiled step).
"""
from __future__ import annotations

import numpy as np

from . import CompressionCodec, default_block_size
from .quantize import dequantize, quantize


class ErrorFeedback:
    """Per-name residual store for eager compression paths."""

    def __init__(self, codec: CompressionCodec,
                 block_size: int | None = None) -> None:
        self.codec = CompressionCodec(codec)
        self.block_size = int(block_size or default_block_size())
        self._residuals: dict[str, np.ndarray] = {}

    def compensate(self, name: str, flat) -> np.ndarray:
        """grad + residual (fp32); call before quantizing."""
        x = np.asarray(flat, dtype=np.float32).reshape(-1)
        res = self._residuals.get(name)
        if res is not None and res.size == x.size:
            x = x + res
        return x

    def update(self, name: str, compensated: np.ndarray) -> np.ndarray:
        """Record the residual left after quantizing ``compensated``;
        returns what the wire actually carries (the dequantized view)."""
        qb = quantize(compensated, self.codec, self.block_size)
        wire = dequantize(qb)
        self._residuals[name] = compensated - wire
        return wire

    def residual(self, name: str) -> np.ndarray | None:
        return self._residuals.get(name)

    def reset(self) -> None:
        self._residuals.clear()
