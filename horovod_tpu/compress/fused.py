"""Single-pass fused codec kernels for the host-side collective legs.

The reference codec path (quantize.py) is correct but allocation-heavy on
the per-chunk hot loop: every arriving contribution pays ``from_bytes``
(view construction) → ``dequantize`` (two ``np.repeat`` expansions + an
``astype`` + a fresh output array) → a deferred list append → a separate
rank-ordered add, and every outgoing chunk pays ``quantize`` (seven
temporaries) → ``to_bytes`` (three ``tobytes`` copies + a bytes concat).
arXiv:2305.06942 (fused computation-collective operations) and
arXiv:2506.17615 (EQuARX) both make the same observation: the codec math
has to execute *inside* the collective pass, not around it.

:class:`FusedKernels` is that fusion for the numpy planes: one kernel
invocation per codec consumes an arriving wire segment and updates the
fp32 accumulator in place (``decode_add``), or emits a ready-to-send
contiguous wire image from the accumulator (``encode``).  Every
intermediate lands in persistent geometry-keyed scratch (grown, never
shrunk), so steady-state legs allocate nothing and no ``np.repeat``
expansion is ever materialized — block metadata is applied by broadcast
over a ``(nb, block_size)`` view.

Numerics contract: the fused kernels execute the SAME IEEE fp32
operations in the SAME order as quantize.py (affine map, round-half-even,
clip, one widening per element), so fused legs are bitwise identical to
the reference path — the property tests/test_fused.py pins.  On TPU the
compiled plane gets this fusion from XLA itself (compress/jax_ops.py is
one jitted program; a Pallas kernel would only re-derive what Mosaic
already fuses), so this module is deliberately numpy-only: it is the CPU
half of the per-plane dispatch (docs/PERFORMANCE.md "Fused
computation-collective kernels").
"""
from __future__ import annotations

import numpy as np

from . import CompressionCodec, codec_levels
from .quantize import num_blocks, payload_nbytes, serialized_nbytes


class FusedKernels:
    """Persistent-scratch fused dequant+accumulate / requantize kernels.

    One instance per channel set (e.g. per TcpCollectives): scratch slots
    are keyed by caller-chosen tags plus dtype/size so concurrent streams
    never share buffers.  NOT thread-safe across concurrent calls on one
    instance — the owning collective serializes its own ops, exactly like
    the channel scratch in runner/network.py.
    """

    __slots__ = ("_f32", "_u8")

    def __init__(self) -> None:
        self._f32: dict = {}
        self._u8: dict = {}

    # -- scratch pools (grown geometrically, never shrunk) ---------------
    def f32(self, key, n: int) -> np.ndarray:
        buf = self._f32.get(key)
        if buf is None or buf.size < n:
            cap = max(n, 0 if buf is None else 2 * buf.size)
            buf = np.empty(cap, np.float32)
            self._f32[key] = buf
        return buf[:n]

    def u8(self, key, n: int) -> np.ndarray:
        buf = self._u8.get(key)
        if buf is None or buf.size < n:
            cap = max(n, 0 if buf is None else 2 * buf.size)
            buf = np.empty(cap, np.uint8)
            self._u8[key] = buf
        return buf[:n]

    # -- fused requantize: fp32 accumulator -> contiguous wire image -----
    def encode(self, x: np.ndarray, codec: CompressionCodec,
               block_size: int, slot) -> np.ndarray:
        """Quantize ``x`` (flat fp32) blockwise straight into a persistent
        wire image ``scales || zero_points || payload`` (the exact
        from_bytes/to_bytes layout, byte-identical to
        ``to_bytes(quantize(x))``).  The returned uint8 array is valid
        until the next ``encode`` on the same ``slot`` — senders must
        flush before the slot is reused (the collectives' op-final flush
        already guarantees it).

        Dispatch: the native single-pass kernel (native/kernels.cc
        hvd_qencode — one blockwise min/max + quantize + pack loop, GIL
        released) when the toolchain built it, else the numpy-vectorized
        form below.  Both are byte-identical to the reference."""
        n = int(x.size)
        levels = codec_levels(codec)
        nb = num_blocks(n, block_size)
        wire = self.u8((slot, "wire"),
                       serialized_nbytes(n, codec, block_size))
        if nb == 0:
            return wire
        if isinstance(x, np.ndarray) and x.dtype == np.float32 \
                and x.flags.c_contiguous:
            from .. import native
            if native.qencode(x, block_size, levels,
                              codec == CompressionCodec.UINT4, wire):
                return wire
        m = nb * block_size
        meta = nb * 4
        scales = wire[:meta].view(np.float32)
        zps = wire[meta:2 * meta].view(np.float32)
        payload = wire[2 * meta:]

        xb = self.f32((slot, "xb"), m)
        xb[:n] = x
        if m > n:
            # Pad with the last element (same rule as quantize.py) so the
            # tail block's scale is not polluted by synthetic zeros.
            xb[n:] = xb[n - 1]
        blocks = xb.reshape(nb, block_size)
        hi = self.f32((slot, "hi"), nb)
        np.max(blocks, axis=1, out=hi)
        np.min(blocks, axis=1, out=zps)
        np.subtract(hi, zps, out=scales)
        scales /= np.float32(levels - 1)
        # ~(scales > 0), not (scales <= 0): quantize.py's np.where rule
        # maps a NaN scale to 1.0 too.
        np.copyto(scales, np.float32(1.0), where=~(scales > 0))

        q32 = self.f32((slot, "q32"), m).reshape(nb, block_size)
        np.subtract(blocks, zps[:, None], out=q32)
        q32 /= scales[:, None]
        np.rint(q32, out=q32)
        np.clip(q32, 0, levels - 1, out=q32)
        qu = self.u8((slot, "q"), m)
        np.copyto(qu, q32.reshape(-1), casting="unsafe")
        if codec == CompressionCodec.UINT4:
            # Zero the pad lanes first so the final half-filled byte
            # matches the reference's zero pad nibble exactly.
            qu[n:] = 0
            packed = self.u8((slot, "pk"), m // 2)
            np.left_shift(qu[0::2], 4, out=packed)
            np.bitwise_or(packed, qu[1::2], out=packed)
            payload[:] = packed[:payload.size]
        else:
            payload[:] = qu[:n]
        return wire

    # -- fused dequantize into a caller-owned destination ----------------
    def _unpacked(self, raw, n: int, codec: CompressionCodec,
                  block_size: int, slot,
                  dest: "np.ndarray | None" = None) -> np.ndarray:
        """Fused dequantize of a wire image: unpack the levels into
        ``dest`` (or persistent scratch) and apply ``q·scale + zp`` in
        place by block-metadata broadcast — no np.repeat expansion, no
        fresh output array.  ``dest`` must be a contiguous fp32 view of
        exactly m = nb·block_size elements."""
        nb = num_blocks(n, block_size)
        m = nb * block_size
        meta = nb * 4
        arr = np.frombuffer(raw, np.uint8,
                            count=serialized_nbytes(n, codec, block_size))
        scales = arr[:meta].view(np.float32)
        zps = arr[meta:2 * meta].view(np.float32)
        pv = arr[2 * meta:2 * meta + payload_nbytes(n, codec)]
        q32 = self.f32((slot, "dq"), m) if dest is None else dest
        if codec == CompressionCodec.UINT4:
            qu = self.u8((slot, "un"), 2 * pv.size)
            np.right_shift(pv, 4, out=qu[0::2])
            np.bitwise_and(pv, 0x0F, out=qu[1::2])
            np.copyto(q32[:n], qu[:n], casting="unsafe")
        else:
            np.copyto(q32[:n], pv, casting="unsafe")
        if m > n:
            q32[n:] = 0          # pad lanes: decoded but never read
        blocks = q32.reshape(nb, block_size)
        np.multiply(blocks, scales[:, None], out=blocks)
        np.add(blocks, zps[:, None], out=blocks)
        return q32

    def _native_decode(self, raw, n: int, codec: CompressionCodec,
                       block_size: int, dst: np.ndarray,
                       accumulate: bool) -> bool:
        """Try the native single-pass decode (hvd_qdecode): dequantize —
        and with ``accumulate``, reduce — in ONE loop over the payload,
        GIL released.  Same IEEE ops as the numpy form (mul, add,
        accumulate-add; -ffp-contract=off), so bitwise identical."""
        if not (dst.dtype == np.float32 and dst.flags.c_contiguous):
            return False
        from .. import native
        wire = np.frombuffer(raw, np.uint8,
                             count=serialized_nbytes(n, codec,
                                                     block_size))
        return native.qdecode(wire, n, block_size,
                              codec == CompressionCodec.UINT4, dst,
                              accumulate)

    def decode_into(self, raw, n: int, codec: CompressionCodec,
                    block_size: int, out: np.ndarray, slot) -> None:
        """Dequantize a wire image straight into ``out`` (fp32 view,
        e.g. the caller's final output slice) — same per-element
        ``q * scale + zero_point`` fp32 math as quantize.dequantize.
        Native kernel when built; otherwise block-aligned chunks decode
        in place in ``out`` itself and ragged tails stage the last
        partial block in scratch."""
        if n == 0:
            return
        if self._native_decode(raw, n, codec, block_size, out, False):
            return
        m = num_blocks(n, block_size) * block_size
        if m == n and out.flags.c_contiguous:
            self._unpacked(raw, n, codec, block_size, slot, dest=out)
            return
        q32 = self._unpacked(raw, n, codec, block_size, slot)
        out[:] = q32[:n]

    def decode_add(self, raw, n: int, codec: CompressionCodec,
                   block_size: int, acc: np.ndarray, slot) -> None:
        """THE fused inner loop: consume an arriving quantized segment and
        accumulate it into the fp32 accumulator in place — one native
        dequant+reduce loop (hvd_qdecode accumulate=1), or one dequant
        pass in scratch + one in-place add on the numpy fallback; zero
        allocations either way."""
        if n == 0:
            return
        if self._native_decode(raw, n, codec, block_size, acc, True):
            return
        q32 = self._unpacked(raw, n, codec, block_size, slot)
        np.add(acc, q32[:n], out=acc)

    # -- fused cast-codec widen+accumulate -------------------------------
    def cast_add(self, raw, wire_dtype: np.dtype, acc: np.ndarray,
                 slot) -> None:
        """Widen an arriving fp16/bf16 segment to fp32 and accumulate in
        place (the cast_allreduce gather-leg kernel): one widening copy
        into scratch + one in-place add — bitwise identical to
        ``acc += segment.astype(np.float32)`` without the allocation."""
        n = acc.size
        if n == 0:
            return
        wv = np.frombuffer(raw, dtype=wire_dtype, count=n)
        s32 = self.f32((slot, "cw"), n)
        np.copyto(s32, wv, casting="unsafe")
        np.add(acc, s32, out=acc)
