"""horovod_tpu.compress — quantized-collective wire compression.

The reference's only wire compression is the fp16 cast
(reference: horovod/torch/compression.py:46-63) — a 2× ceiling.  This
package is the EQuARX-style generalisation (PAPERS.md, arxiv 2506.17615):
a codec registry spanning

  none         passthrough
  fp16 / bf16  wire-dtype cast (subsumes the legacy Compression classes)
  int8         block-wise 8-bit affine quantization (~3.9× wire bytes)
  uint4        block-wise 4-bit affine quantization (~7.5× wire bytes)

negotiated through the controller (a codec mismatch across ranks is a
structured ERROR, never a corrupted reduce), carried by every data plane
(xla / tcp / shm eager, compiled grad_sync), with an EF-SGD style
error-feedback accumulator so quantization error is re-injected into the
next step instead of lost.

Layering:
  quantize.py        numpy block quantization (eager planes)
  jax_ops.py         pure-jax twin + the fused quantized allreduce that
                     XLA schedules around the collective (grad_sync)
  error_feedback.py  residual accumulators (eager keyed store + the
                     functional jax form)
"""
from __future__ import annotations

import enum


class CompressionCodec(enum.IntEnum):
    """Wire codec ids — part of the control-plane wire format
    (common/message.py encodes them on Request/Response)."""
    NONE = 0
    FP16 = 1
    BF16 = 2
    INT8 = 3
    UINT4 = 4


#: Codecs that quantize (block scale + zero point) rather than cast.
QUANTIZED_CODECS = (CompressionCodec.INT8, CompressionCodec.UINT4)

#: Codecs that cast the wire dtype without quantizing.
CAST_CODECS = (CompressionCodec.FP16, CompressionCodec.BF16)

_BY_NAME = {
    "none": CompressionCodec.NONE,
    "fp16": CompressionCodec.FP16,
    "bf16": CompressionCodec.BF16,
    "int8": CompressionCodec.INT8,
    "uint4": CompressionCodec.UINT4,
}


def codec_from_name(name) -> CompressionCodec:
    """Resolve a codec from a user-facing spelling: a name string, a
    CompressionCodec, None, or an object exposing ``wire_codec`` (the
    torch/tf Compression marker classes)."""
    if name is None:
        return CompressionCodec.NONE
    if isinstance(name, CompressionCodec):
        return name
    wire = getattr(name, "wire_codec", None)
    if wire is not None:
        return codec_from_name(wire)
    try:
        return _BY_NAME[str(name).strip().lower()]
    except KeyError:
        raise ValueError(
            f"Unknown compression codec {name!r}; expected one of "
            f"{sorted(_BY_NAME)}") from None


def codec_name(codec: CompressionCodec) -> str:
    return CompressionCodec(codec).name.lower()


def codec_levels(codec: CompressionCodec) -> int:
    """Quantization levels (256 for int8 wire bytes, 16 for uint4)."""
    if codec == CompressionCodec.UINT4:
        return 16
    if codec == CompressionCodec.INT8:
        return 256
    raise ValueError(f"codec {codec!r} is not a quantized codec")


def default_block_size() -> int:
    from ..common import config
    return int(config.COMPRESSION_BLOCK_SIZE.get())


def default_codec() -> CompressionCodec:
    from ..common import config
    return codec_from_name(config.COMPRESSION.get())


from .quantize import (QuantizedBlocks, chunk_bounds, dequantize,  # noqa: E402
                       from_bytes, num_blocks, payload_nbytes, quantize,
                       roundtrip_error_bound, serialized_nbytes,
                       staged_nbytes, to_bytes)
from .error_feedback import ErrorFeedback  # noqa: E402

__all__ = [
    "CompressionCodec", "QUANTIZED_CODECS", "CAST_CODECS",
    "codec_from_name", "codec_name", "codec_levels",
    "default_block_size", "default_codec",
    "QuantizedBlocks", "quantize", "dequantize", "to_bytes", "from_bytes",
    "num_blocks", "payload_nbytes", "serialized_nbytes", "staged_nbytes",
    "chunk_bounds", "roundtrip_error_bound", "ErrorFeedback",
]
