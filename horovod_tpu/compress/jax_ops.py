"""Pure-jax block quantization + the fused quantized allreduce.

The compiled twin of ``compress/quantize.py``: identical scale rule
(affine per-block, round-half-even) expressed as jnp ops so XLA fuses the
quantize/dequantize around the collective — the EQuARX shape (PAPERS.md,
arxiv 2506.17615), where the exchange moves int8/uint4 payloads + small
fp32 block metadata instead of full-width gradients.

``quantized_allreduce`` is the shard_map collective used by
parallel/grad_sync.py:

  1. pad the flat bucket to world × chunk (chunk block-aligned);
  2. quantize each destination chunk independently (per-block scale+zp);
  3. all_to_all the QUANTIZED chunks — every rank receives all ranks'
     contributions for its own chunk (wire: ~n/4 bytes for int8);
  4. dequantize + sum in fp32 (one widening, one rounding: the planes'
     accumulation contract);
  5. requantize the reduced chunk ONCE and all_gather it (wire: ~n/4);
  6. dequantize, strip padding.

Wire volume matches ring allreduce's 2(N-1)/N·bytes structure with
quantized bytes, i.e. ~4× (int8) / ~8× (uint4) less traffic than fp32,
at the cost of one input quantization + one output requantization —
both inside the documented block error bound.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import CompressionCodec, codec_levels


def _combined_size(axes) -> "jax.Array | int":
    world = 1
    for a in axes:
        world = world * lax.psum(1, a)
    return world


def quantize_rows(x: jax.Array, codec: CompressionCodec,
                  block_size: int):
    """Quantize each row of ``x`` [rows, m] blockwise (m % block_size == 0
    — callers pad).  Returns (payload uint8 [rows, pb], scales fp32
    [rows, nb], zero_points fp32 [rows, nb])."""
    rows, m = x.shape
    levels = codec_levels(codec)
    nb = m // block_size
    blocks = x.astype(jnp.float32).reshape(rows, nb, block_size)
    lo = blocks.min(axis=2)
    hi = blocks.max(axis=2)
    scales = (hi - lo) / (levels - 1)
    scales = jnp.where(scales > 0, scales, 1.0)
    q = jnp.round((blocks - lo[..., None]) / scales[..., None])
    q = jnp.clip(q, 0, levels - 1).astype(jnp.uint8).reshape(rows, m)
    if codec == CompressionCodec.UINT4:
        # Pack two nibbles per byte so the collective moves half the
        # bytes (block_size is even by config validation).
        q = (q[:, 0::2] << 4) | q[:, 1::2]
    return q, scales, lo


def dequantize_rows(q: jax.Array, scales: jax.Array, zps: jax.Array,
                    codec: CompressionCodec, block_size: int) -> jax.Array:
    """Inverse of :func:`quantize_rows` → fp32 [rows, m]."""
    rows = q.shape[0]
    if codec == CompressionCodec.UINT4:
        hi = q >> 4
        lo = q & 0x0F
        q = jnp.stack([hi, lo], axis=-1).reshape(rows, -1)
    nb = scales.shape[1]
    blocks = q.astype(jnp.float32).reshape(rows, nb, block_size)
    out = blocks * scales[..., None] + zps[..., None]
    return out.reshape(rows, nb * block_size)


def quantized_allreduce(flat: jax.Array, axes, op: str,
                        codec: CompressionCodec, block_size: int,
                        residual: jax.Array | None = None):
    """Block-quantized allreduce of a flat floating buffer over mesh
    ``axes`` (call inside shard_map).  With ``residual`` (error
    feedback) returns ``(reduced, new_residual)``; without, just
    ``reduced``.  Reduction accumulates in fp32; ``op == "average"``
    divides before the output requantization so the second quantization
    sees the smaller averaged range."""
    codec = CompressionCodec(codec)
    if codec == CompressionCodec.UINT4 and block_size % 2:
        raise ValueError("uint4 compression requires an even block size")
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    n = flat.shape[0]
    in_dtype = flat.dtype
    world = _combined_size(axes)

    chunk = -(-n // world)
    chunk = -(-chunk // block_size) * block_size
    padded_n = chunk * world

    x = flat.astype(jnp.float32)
    if residual is not None:
        x = x + residual.astype(jnp.float32)
    compensated = x
    if padded_n > n:
        x = jnp.concatenate([x, jnp.zeros(padded_n - n, jnp.float32)])
    x = x.reshape(world, chunk)

    # Quantize every destination chunk independently so each owner can
    # dequantize its chunk without the rest of the buffer's metadata.
    q, s, zp = quantize_rows(x, codec, block_size)

    if residual is not None:
        # EF residual: what the wire fails to carry of MY contribution.
        sent = dequantize_rows(q, s, zp, codec, block_size)
        new_residual = (compensated
                        - sent.reshape(-1)[:n]).astype(jnp.float32)

    # Exchange: after tiled all_to_all, row p holds rank p's quantized
    # contribution to THIS rank's chunk.
    q = lax.all_to_all(q, axes, split_axis=0, concat_axis=0, tiled=True)
    s = lax.all_to_all(s, axes, split_axis=0, concat_axis=0, tiled=True)
    zp = lax.all_to_all(zp, axes, split_axis=0, concat_axis=0, tiled=True)

    red = dequantize_rows(q, s, zp, codec, block_size).sum(axis=0)
    if op == "average":
        red = red / world

    # One requantization of the reduced chunk, gathered from every owner.
    qr, sr, zr = quantize_rows(red[None, :], codec, block_size)
    qg = lax.all_gather(qr[0], axes, axis=0, tiled=False)
    sg = lax.all_gather(sr[0], axes, axis=0, tiled=False)
    zg = lax.all_gather(zr[0], axes, axis=0, tiled=False)
    full = dequantize_rows(qg, sg, zg, codec, block_size).reshape(-1)[:n]
    out = full.astype(in_dtype)
    if residual is not None:
        return out, new_residual
    return out
