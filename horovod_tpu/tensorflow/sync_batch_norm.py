"""Cross-rank synchronized batch normalization for TF/Keras.

Reference: horovod/tensorflow/sync_batch_norm.py — a BatchNormalization
subclass whose moments are computed over the GLOBAL batch: per-rank
(sum, sum-of-squares, count) are allreduced, so every replica normalizes
with identical statistics. Gradients of the normalized output flow through
the allreduce's own gradient (the collectives are differentiable graph
ops), matching the reference's distributed-moments construction.
"""
from __future__ import annotations

import tensorflow as tf


class SyncBatchNormalization(tf.keras.layers.BatchNormalization):
    """Drop-in keras BatchNormalization with cross-rank statistics."""

    def __init__(self, *args, **kwargs) -> None:
        if kwargs.pop("fused", None):
            raise ValueError(
                "SyncBatchNormalization does not support fused=True")
        super().__init__(*args, **kwargs)

    def _moments(self, inputs, mask):
        # Keras 3 signature; reduction axes live on the layer.
        from . import Sum, allreduce, size

        mean, variance = super()._moments(inputs, mask)
        if size() <= 1:
            return mean, variance

        # Weight by per-rank element count so uneven local batches still
        # produce exact global moments (reference: sync_batch_norm.py).
        reduction_axes = list(self._reduction_axes)
        shape = tf.shape(inputs)
        count = tf.cast(tf.reduce_prod(
            tf.gather(shape, reduction_axes)), mean.dtype)
        total_count = allreduce(tf.reshape(count, [1]), op=Sum,
                                name="syncbn.count")[0]
        global_mean = allreduce(mean * count, op=Sum,
                                name="syncbn.mean") / total_count
        # var_global = E[x^2] - E[x]^2, from per-rank E[x^2] contributions.
        sq = allreduce((variance + tf.square(mean)) * count, op=Sum,
                       name="syncbn.sq") / total_count
        global_var = sq - tf.square(global_mean)
        return global_mean, global_var
