"""Elastic state for TensorFlow/Keras models.

Reference: horovod/tensorflow/elastic.py:1-221 — ``TensorFlowKerasState``
snapshots model + optimizer weights in host memory on ``commit()``,
restores them after a ``HorovodInternalError``, and ``sync()`` broadcasts
rank 0's weights to the re-formed world.
"""
from __future__ import annotations

import copy
from typing import Any

from ..elastic.state import ObjectState


class _VariablesHandler:
    """Snapshot/restore/broadcast a list of tf.Variables by value."""

    def __init__(self, variables) -> None:
        self.variables = list(variables)
        self._saved = None
        self.save()

    def save(self) -> None:
        self._saved = [v.numpy().copy() for v in self.variables]

    def restore(self) -> None:
        for var, value in zip(self.variables, self._saved):
            var.assign(value)

    def sync(self) -> None:
        from . import broadcast_variables
        broadcast_variables(self.variables, root_rank=0)
        self.save()


class TensorFlowState(ObjectState):
    """Elastic state over explicit tf.Variables
    (reference: tensorflow/elastic.py TensorFlowState)."""

    def __init__(self, variables=None, **kwargs: Any) -> None:
        import tensorflow as tf
        self._handler = _VariablesHandler(
            variables if variables is not None
            else tf.compat.v1.global_variables())
        super().__init__(**kwargs)

    def save(self) -> None:
        self._handler.save()
        super().save()

    def restore(self) -> None:
        self._handler.restore()
        super().restore()

    def sync(self) -> None:
        self._handler.sync()
        super().sync()


class TensorFlowKerasState(ObjectState):
    """Elastic state for a keras model + optimizer
    (reference: tensorflow/elastic.py TensorFlowKerasState)."""

    def __init__(self, model, optimizer=None, **kwargs: Any) -> None:
        self.model = model
        self.optimizer = optimizer or getattr(model, "optimizer", None)
        self._model_weights = copy.deepcopy(model.get_weights())
        self._opt_weights = self._get_opt_weights()
        super().__init__(**kwargs)

    def _get_opt_weights(self):
        if self.optimizer is None:
            return None
        return [v.numpy().copy() for v in self.optimizer.variables]

    def _set_opt_weights(self, weights) -> None:
        if self.optimizer is None or weights is None:
            return
        for var, value in zip(self.optimizer.variables, weights):
            var.assign(value)

    def save(self) -> None:
        self._model_weights = copy.deepcopy(self.model.get_weights())
        self._opt_weights = self._get_opt_weights()
        super().save()

    def restore(self) -> None:
        self.model.set_weights(self._model_weights)
        self._set_opt_weights(self._opt_weights)
        super().restore()

    def sync(self) -> None:
        from . import broadcast_variables
        variables = list(self.model.variables)
        if self.optimizer is not None:
            variables += list(self.optimizer.variables)
        broadcast_variables(variables, root_rank=0)
        self.save()
        super().sync()
