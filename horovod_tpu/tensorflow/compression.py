"""Gradient compression for the TF binding.

Same contract as the reference (reference: horovod/tensorflow/
compression.py): ``compress(tensor) -> (wire_tensor, ctx)`` casts floats
down before the allreduce, ``decompress`` restores the dtype. bf16 is the
TPU-native addition — fp32 exponent range, no loss-scaling needed.
"""
from __future__ import annotations

import tensorflow as tf


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating and tensor.dtype.size > 2:
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tf.cast(tensor, ctx)


class BF16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating and tensor.dtype.size > 2:
            return tf.cast(tensor, tf.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tf.cast(tensor, ctx)


class Int8Compressor(Compressor):
    """Block-wise int8 wire quantization (compress/ subsystem): a
    pass-through marker — the runtime's data planes quantize per fusion
    bucket, so what crosses the wire is int8 payload + per-block
    scale/zero-point, not this graph tensor."""

    wire_codec = "int8"

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class Uint4Compressor(Int8Compressor):
    wire_codec = "uint4"


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    uint4 = Uint4Compressor

    @staticmethod
    def resolve(spec):
        """Accept a Compressor class or a codec name string."""
        if spec is None:
            return Compression.none
        if isinstance(spec, str):
            try:
                return getattr(Compression, spec.strip().lower())
            except AttributeError:
                raise ValueError(
                    f"Unknown compression {spec!r}; expected one of "
                    "none/fp16/bf16/int8/uint4") from None
        return spec
