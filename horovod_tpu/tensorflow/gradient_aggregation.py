"""Local gradient aggregation for ``backward_passes_per_step``.

Reference: horovod/tensorflow/gradient_aggregation.py (graph, 274 LoC) +
gradient_aggregation_eager.py (155 LoC). One implementation here serves
both eager and ``tf.function`` callers: tf.Variable accumulators + a step
counter, ``tf.cond`` on the counter so the traced graph contains both the
accumulate-only and the allreduce-and-apply branches.
"""
from __future__ import annotations

from typing import Callable, Sequence

import tensorflow as tf


class LocalGradientAggregationHelper:
    """Accumulate gradients locally for N backward passes, then allreduce
    once and apply — cutting allreduce traffic N× for small-batch regimes
    (reference: gradient_aggregation.py LocalGradientAggregationHelper)."""

    def __init__(self, backward_passes_per_step: int,
                 allreduce_func: Callable[[tf.Tensor, int], tf.Tensor],
                 average_aggregated_gradients: bool = True) -> None:
        if backward_passes_per_step < 1:
            raise ValueError("backward_passes_per_step must be >= 1")
        self.backward_passes_per_step = backward_passes_per_step
        self.allreduce_func = allreduce_func
        self.average_aggregated_gradients = average_aggregated_gradients
        self.counter: tf.Variable | None = None
        self._accum: list[tf.Variable] = []

    def _init_state(self, grads: Sequence[tf.Tensor]) -> None:
        if self.counter is None:
            self.counter = tf.Variable(0, dtype=tf.int32, trainable=False,
                                       name="hvd_agg_counter")
        if not self._accum:
            self._accum = [
                tf.Variable(tf.zeros_like(g), trainable=False,
                            name=f"hvd_agg_{i}")
                for i, g in enumerate(grads)]

    def apply_gradients(self, grads: Sequence[tf.Tensor],
                        variables: Sequence[tf.Variable],
                        apply_fn: Callable[[list], object]):
        """Accumulate; on the Nth pass allreduce the sums and run
        ``apply_fn(grads_and_vars)``. Returns apply_fn's result on apply
        steps (None on accumulate-only steps in eager mode)."""
        n = self.backward_passes_per_step
        if n == 1:
            reduced = [g if g is None else self.allreduce_func(g, i)
                       for i, g in enumerate(grads)]
            return apply_fn(list(zip(reduced, variables)))

        dense_grads = [g if g is not None else tf.zeros_like(v)
                       for g, v in zip(grads, variables)]
        self._init_state(dense_grads)
        for acc, g in zip(self._accum, dense_grads):
            acc.assign_add(g)
        self.counter.assign_add(1)

        def _apply():
            scale = float(n) if self.average_aggregated_gradients else 1.0
            reduced = [self.allreduce_func(acc / scale, i)
                       for i, acc in enumerate(self._accum)]
            result = apply_fn(list(zip(reduced, variables)))
            for acc in self._accum:
                acc.assign(tf.zeros_like(acc))
            self.counter.assign(0)
            return result

        if tf.executing_eagerly():
            if int(self.counter.numpy()) >= n:
                return _apply()
            return None
        # Graph mode: both branches live in the trace; tf.cond picks one
        # at run time (branch outputs must match, so apply's result is
        # dropped and a did-apply flag returned instead).
        return tf.cond(
            self.counter >= n,
            lambda: (_apply(), tf.constant(True))[1],
            lambda: tf.constant(False))
