"""TensorFlow binding (reference: horovod/tensorflow/__init__.py).

TensorFlow is optional; when importable this module exposes the
Horovod-compatible TF surface over the shared eager runtime. Collectives
are built from ``tf.py_function`` bridges wrapped in ``tf.custom_gradient``
so they survive ``tf.function`` tracing and compiled ``model.fit`` loops —
the role the reference's AsyncOpKernels + RegisterGradient play
(reference: tensorflow/mpi_ops.cc:422-921, tensorflow/mpi_ops.py:125-334).
IndexedSlices (sparse) gradients fall back to an allgather of values and
indices, mirroring reference __init__.py:54-155.

The native TPU path for new code is the JAX SPMD Trainer — this binding
exists so reference TF scripts keep a migration path.
"""
from __future__ import annotations

import itertools
from typing import Any

try:
    import tensorflow as tf  # noqa: F401
    _TF_AVAILABLE = True
except ImportError:
    _TF_AVAILABLE = False

from .. import (Adasum, Average, Sum, allgather as _allgather_np,
                allreduce as _allreduce_np, alltoall as _alltoall_np,
                broadcast as _broadcast_np, broadcast_object, init,
                is_initialized, join, local_rank, local_size, rank,
                reducescatter as _reducescatter_np, shutdown, size)

__all__ = ["init", "shutdown", "rank", "size", "local_rank", "local_size",
           "allreduce", "allgather", "broadcast", "alltoall",
           "reducescatter", "join",
           "broadcast_object", "broadcast_variables",
           "DistributedGradientTape", "DistributedOptimizer",
           "BroadcastGlobalVariablesCallback", "Average", "Sum", "Adasum",
           "Compression", "SyncBatchNormalization", "is_initialized"]

_name_counter = itertools.count()


def _require_tf() -> None:
    if not _TF_AVAILABLE:
        raise ImportError(
            "horovod_tpu.tensorflow requires tensorflow, which is not "
            "installed in this environment. The JAX-native path "
            "(horovod_tpu.training.Trainer) is the supported TPU surface.")


def _auto_name(prefix: str, name: str | None) -> str:
    """Stable per-trace name: ranks trace identical programs in identical
    order, so the counter assigns every collective the same name on every
    rank (the negotiation key, reference: controller.cc ConstructResponse)."""
    return name or f"{prefix}.{next(_name_counter)}"


def _py_collective(fn, inp, out_dtype, out_shape=None):
    """Run a numpy collective inside the TF graph via tf.py_function."""
    out = tf.py_function(func=fn, inp=inp, Tout=out_dtype)
    if out_shape is not None:
        out.set_shape(out_shape)
    return out


# ---------------------------------------------------------------------------
# Collectives (graph-safe, differentiable)
# ---------------------------------------------------------------------------
def allreduce(tensor, average: bool | None = None, op=None,
              name: str | None = None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0, compression=None):
    _require_tf()
    if isinstance(tensor, tf.IndexedSlices):
        # Sparse fallback: allgather values+indices; averaging divides by
        # size (reference: tensorflow/__init__.py:54-155).
        nm = _auto_name("sparse_ar", name)
        values = allgather(tensor.values, name=f"{nm}.values")
        indices = allgather(tensor.indices, name=f"{nm}.indices")
        if op in (None, Average) and average is not False and op is not Sum:
            values = values / size()
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)

    nm = _auto_name("allreduce", name)
    compressor = Compression.resolve(compression)
    # Codec markers (compression="int8"/"uint4", or the marker classes)
    # delegate quantization to the runtime's data planes.
    wire_codec = getattr(compressor, "wire_codec", None)
    the_op = op if op is not None else (
        Sum if average is False else Average)

    @tf.custom_gradient
    def _allreduce(t):
        compressed, ctx = compressor.compress(t)

        def _run(x):
            return _allreduce_np(x.numpy(), op=the_op, name=nm,
                                 prescale_factor=prescale_factor,
                                 postscale_factor=postscale_factor,
                                 compression=wire_codec)

        out = _py_collective(_run, [compressed], compressed.dtype, t.shape)
        out = compressor.decompress(out, ctx)

        def grad(dy):
            # Gradient of an allreduce is an allreduce with the same op
            # (reference: tensorflow/mpi_ops.py:125-143).
            return allreduce(dy, op=the_op, name=f"{nm}.grad")

        return out, grad

    return _allreduce(tf.convert_to_tensor(tensor))


def allgather(tensor, name: str | None = None):
    _require_tf()
    nm = _auto_name("allgather", name)

    @tf.custom_gradient
    def _allgather(t):
        def _run(x):
            return _allgather_np(x.numpy(), name=nm)

        out = _py_collective(_run, [t], t.dtype,
                             tf.TensorShape([None]).concatenate(
                                 t.shape[1:]))

        def grad(dy):
            # d(allgather)/dt = our slice of the summed upstream grad
            # (reference: tensorflow/mpi_ops.py allgather grad).
            d0 = tf.shape(t)[0]
            sizes = allgather(tf.reshape(d0, [1]), name=f"{nm}.gsizes")
            offset = tf.reduce_sum(sizes[:rank()])
            summed = allreduce(dy, op=Sum, name=f"{nm}.grad")
            return summed[offset:offset + d0]

        return out, grad

    return _allgather(tf.convert_to_tensor(tensor))


def reducescatter(tensor, op=None, name: str | None = None):
    """Reduce across ranks and return this rank's dim-0 slice (op=None
    averages). Differentiable: the gradient is this rank's slice
    allgathered back to the full shape."""
    _require_tf()
    nm = _auto_name("reducescatter", name)

    @tf.custom_gradient
    def _reducescatter(t):
        def _run(x):
            return _reducescatter_np(x.numpy(), name=nm, op=op)

        out = _py_collective(_run, [t], t.dtype,
                             tf.TensorShape([None]).concatenate(
                                 t.shape[1:]))

        def grad(dy):
            # d(reduce_scatter)/dt: gather the slices back; averaging in
            # the forward scales the gradient by 1/size.
            full = allgather(dy, name=f"{nm}.grad")
            if op in (None, Average):
                full = full / size()
            return full

        return out, grad

    return _reducescatter(tf.convert_to_tensor(tensor))


def broadcast(tensor, root_rank: int = 0, name: str | None = None):
    _require_tf()
    nm = _auto_name("broadcast", name)

    @tf.custom_gradient
    def _broadcast(t):
        def _run(x):
            return _broadcast_np(x.numpy(), root_rank, name=nm)

        out = _py_collective(_run, [t], t.dtype, t.shape)

        def grad(dy):
            # Root accumulates every rank's gradient; others contribute
            # zero (reference: tensorflow/mpi_ops.py broadcast grad).
            summed = allreduce(dy, op=Sum, name=f"{nm}.grad")
            if rank() == root_rank:
                return summed
            return tf.zeros_like(dy)

        return out, grad

    return _broadcast(tf.convert_to_tensor(tensor))


def alltoall(tensor, splits=None, name: str | None = None):
    _require_tf()
    nm = _auto_name("alltoall", name)
    if splits is None:
        def _run_even(x):
            return _alltoall_np(x.numpy(), None, name=nm)
        return _py_collective(_run_even, [tensor], tensor.dtype,
                              tensor.shape)

    def _run(x, s):
        out, recv = _alltoall_np(x.numpy(), s.numpy(), name=nm)
        return out, recv.astype("int32") if hasattr(recv, "astype") \
            else tf.constant(recv, tf.int32)

    out, recv_splits = tf.py_function(
        func=_run, inp=[tensor, splits], Tout=[tensor.dtype, tf.int32])
    out.set_shape(tf.TensorShape([None]).concatenate(tensor.shape[1:]))
    recv_splits.set_shape([None])
    return out, recv_splits


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable its root-rank value
    (reference: tensorflow/__init__.py broadcast_global_variables)."""
    _require_tf()
    for i, var in enumerate(variables):
        # Index-keyed names: keras-3 variable names ("kernel") are not
        # unique, and the tensor-queue rejects duplicate in-flight names.
        var.assign(broadcast(tf.convert_to_tensor(var), root_rank,
                             name=f"bcast.{i}"))


def broadcast_global_variables(root_rank: int = 0) -> None:
    _require_tf()
    broadcast_variables(tf.compat.v1.global_variables(), root_rank)


class DistributedGradientTape:
    """Wrap tf.GradientTape so gradient() allreduces the grads
    (reference: tensorflow/__init__.py:726-816). Works inside
    ``tf.function`` — the collectives are graph ops."""

    def __init__(self, tape, op=None, prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0, compression=None) -> None:
        _require_tf()
        self._tape = tape
        self._op = op
        self._pre = prescale_factor
        self._post = postscale_factor
        self._compression = compression

    def __getattr__(self, item: str) -> Any:
        return getattr(self._tape, item)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        # tf returns a single gradient for a single (non-sequence) source.
        single = not isinstance(grads, (list, tuple))
        grad_list = [grads] if single else grads
        reduced = [None if g is None else
                   allreduce(g, op=self._op, name=f"grad.{i}",
                             prescale_factor=self._pre,
                             postscale_factor=self._post,
                             compression=self._compression)
                   for i, g in enumerate(grad_list)]
        return reduced[0] if single else reduced


def _make_adasum_optimizer(optimizer, compression,
                           backward_passes_per_step: int):
    """Adasum delta-optimizer, TF2/Keras idiom (reference:
    tensorflow/__init__.py:504-598 _DistributedAdasumOptimizer).

    Every apply runs the wrapped optimizer locally; every
    ``backward_passes_per_step``-th apply ships the parameter delta since
    the last communication through a scale-adaptive Adasum allreduce and
    resets the variables to start + combined delta.  State lives in
    per-variable ``delta_start`` slots plus a step counter, created
    lazily on first apply (keras slot-variable style)."""
    base = optimizer.__class__
    comp = Compression.resolve(compression)
    if getattr(comp, "wire_codec", None) in ("int8", "uint4"):
        raise ValueError(
            "op=Adasum does not compose with quantized compression "
            "(int8/uint4); use none, fp16 or bf16.")
    state = {"starts": None, "step": None, "initialized": None,
             "bps": int(backward_passes_per_step)}

    class _DistributedAdasum(base):
        def apply_gradients(self, grads_and_vars, **apply_kwargs):
            gv = list(grads_and_vars)
            variables = [v for _, v in gv]
            st = self._hvd_adasum
            if st["starts"] is None:
                st["starts"] = {}
                with tf.init_scope():
                    st["step"] = tf.Variable(0, dtype=tf.int64,
                                             trainable=False)
                    st["initialized"] = tf.Variable(False, trainable=False)
            # delta_start slots key by VARIABLE REF, not call position: a
            # loop that filters None grads or reorders grads_and_vars
            # between steps must still pair each var with its own slot.
            for v in variables:
                if v.ref() not in st["starts"]:
                    with tf.init_scope():
                        st["starts"][v.ref()] = tf.Variable(
                            tf.zeros_like(v), trainable=False,
                            name=f"delta_start_{len(st['starts'])}")
            starts = [st["starts"][v.ref()] for v in variables]

            def _init_starts():
                for s, v in zip(starts, variables):
                    s.assign(v)
                return tf.constant(True)

            tf.cond(st["initialized"], lambda: tf.constant(True),
                    _init_starts)
            st["initialized"].assign(True)

            result = super(_DistributedAdasum, self).apply_gradients(
                gv, **apply_kwargs)
            st["step"].assign_add(1)

            def _communicate():
                for i, (s, v) in enumerate(zip(starts, variables)):
                    combined = allreduce(v - s, op=Adasum,
                                         compression=comp,
                                         name=f"adasum_delta.{i}")
                    s.assign_add(combined)
                    v.assign(s)
                return tf.constant(True)

            tf.cond(
                tf.equal(st["step"] % st["bps"], 0),
                _communicate, lambda: tf.constant(False))
            return result

    _DistributedAdasum.__name__ = f"DistributedAdasum{base.__name__}"
    optimizer.__class__ = _DistributedAdasum
    optimizer._hvd_adasum = state
    return optimizer


def DistributedOptimizer(optimizer, name: str | None = None,
                         compression=None,
                         backward_passes_per_step: int = 1,
                         op=None, **kwargs):
    """Wrap a keras optimizer: gradients are locally aggregated for
    ``backward_passes_per_step`` steps, then allreduced before apply
    (reference: tensorflow/__init__.py:427-502 + gradient_aggregation.py).
    ``op=Adasum`` returns the delta-optimizer variant (reference:
    tensorflow/__init__.py:504-598).

    The SAME instance is returned with its class swapped, preserving slot
    variables and iteration counters."""
    _require_tf()
    if op is Adasum:
        return _make_adasum_optimizer(optimizer, compression,
                                      backward_passes_per_step)
    from .gradient_aggregation import LocalGradientAggregationHelper

    base = optimizer.__class__
    helper = LocalGradientAggregationHelper(
        backward_passes_per_step=backward_passes_per_step,
        allreduce_func=lambda g, i: allreduce(
            g, op=op, name=f"opt_grad.{i}", compression=compression),
    )

    class _Distributed(base):
        def apply_gradients(self, grads_and_vars, **apply_kwargs):
            grads_and_vars = list(grads_and_vars)
            grads = [g for g, _ in grads_and_vars]
            variables = [v for _, v in grads_and_vars]
            return self._hvd_helper.apply_gradients(
                grads, variables,
                lambda gv: super(_Distributed, self).apply_gradients(
                    gv, **apply_kwargs))

    _Distributed.__name__ = f"Distributed{base.__name__}"
    optimizer.__class__ = _Distributed
    optimizer._hvd_helper = helper
    return optimizer


if _TF_AVAILABLE:
    from .compression import Compression  # noqa: E402
    from .elastic import TensorFlowKerasState, TensorFlowState  # noqa: E402,F401
    from .sync_batch_norm import SyncBatchNormalization  # noqa: E402

    class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
        """Keras callback: broadcast initial variables from root on the
        first batch — after the optimizer has created its slots
        (reference: tensorflow/__init__.py BroadcastGlobalVariablesHook /
        _keras/callbacks.py BroadcastGlobalVariablesCallback)."""

        def __init__(self, root_rank: int = 0) -> None:
            super().__init__()
            self.root_rank = root_rank
            self._done = False

        def on_train_batch_begin(self, batch, logs=None) -> None:
            if self._done or self.model is None:
                return
            variables = list(self.model.variables)
            opt = getattr(self.model, "optimizer", None)
            if opt is not None:
                variables += list(opt.variables)
            broadcast_variables(variables, self.root_rank)
            self._done = True
else:  # gated stubs so `import horovod_tpu.tensorflow` always works
    class Compression:  # type: ignore[no-redef]
        none = None
        fp16 = None
        bf16 = None
        int8 = None
        uint4 = None

    def SyncBatchNormalization(*_a, **_k):  # type: ignore[no-redef]
        _require_tf()

    class BroadcastGlobalVariablesCallback:  # type: ignore[no-redef]
        def __init__(self, *_a, **_k) -> None:
            _require_tf()
