"""TensorFlow binding (reference: horovod/tensorflow/__init__.py).

TensorFlow is optional; when it is importable this module exposes the
Horovod-compatible TF surface over the shared eager runtime: collectives on
TF tensors (via numpy interop), ``DistributedGradientTape``, and
``broadcast_variables``.  The native TPU path for new code is the JAX SPMD
Trainer — this binding exists so reference TF scripts keep a migration
path.
"""
from __future__ import annotations

from typing import Any

try:
    import tensorflow as tf  # noqa: F401
    _TF_AVAILABLE = True
except ImportError:
    _TF_AVAILABLE = False

from .. import (Adasum, Average, Sum, allgather as _allgather_np,
                allreduce as _allreduce_np, alltoall as _alltoall_np,
                broadcast as _broadcast_np, broadcast_object, init,
                is_initialized, join, local_rank, local_size, rank,
                shutdown, size)

__all__ = ["init", "shutdown", "rank", "size", "local_rank", "local_size",
           "allreduce", "allgather", "broadcast", "alltoall", "join",
           "broadcast_object", "broadcast_variables",
           "DistributedGradientTape", "Average", "Sum", "Adasum",
           "is_initialized"]


def _require_tf() -> None:
    if not _TF_AVAILABLE:
        raise ImportError(
            "horovod_tpu.tensorflow requires tensorflow, which is not "
            "installed in this environment. The JAX-native path "
            "(horovod_tpu.training.Trainer) is the supported TPU surface.")


def _to_tf(value, like):
    import tensorflow as tf
    return tf.convert_to_tensor(value, dtype=like.dtype)


def allreduce(tensor, average: bool | None = None, op=None,
              name: str | None = None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0):
    _require_tf()
    out = _allreduce_np(tensor.numpy(), average=average, op=op, name=name,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor)
    return _to_tf(out, tensor)


def allgather(tensor, name: str | None = None):
    _require_tf()
    return _to_tf(_allgather_np(tensor.numpy(), name=name), tensor)


def broadcast(tensor, root_rank: int = 0, name: str | None = None):
    _require_tf()
    return _to_tf(_broadcast_np(tensor.numpy(), root_rank, name=name),
                  tensor)


def alltoall(tensor, splits=None, name: str | None = None):
    _require_tf()
    result = _alltoall_np(tensor.numpy(),
                          None if splits is None else splits.numpy(),
                          name=name)
    if splits is None:
        return _to_tf(result, tensor)
    out, recv_splits = result
    import tensorflow as tf
    return _to_tf(out, tensor), tf.convert_to_tensor(recv_splits)


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign every variable its root-rank value
    (reference: tensorflow/__init__.py broadcast_global_variables)."""
    _require_tf()
    for i, var in enumerate(variables):
        var.assign(_to_tf(_broadcast_np(var.numpy(), root_rank,
                                        name=f"bcast_var.{i}"), var))


class DistributedGradientTape:
    """Wrap tf.GradientTape so gradient() allreduces the grads
    (reference: tensorflow/__init__.py:726-816)."""

    def __init__(self, tape, op=None, prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0) -> None:
        _require_tf()
        self._tape = tape
        self._op = op
        self._pre = prescale_factor
        self._post = postscale_factor

    def __getattr__(self, item: str) -> Any:
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        # tf returns a single gradient for a single (non-sequence) source.
        single = not isinstance(grads, (list, tuple))
        grad_list = [grads] if single else grads
        reduced = [None if g is None else
                   allreduce(g, op=self._op, name=f"grad.{i}",
                             prescale_factor=self._pre,
                             postscale_factor=self._post)
                   for i, g in enumerate(grad_list)]
        return reduced[0] if single else reduced
