"""Keras binding (reference: horovod/keras/__init__.py + callbacks.py).

Gated on tensorflow/keras being importable.  The callback surface
(`MetricAverageCallback`, `LearningRateWarmupCallback`,
`BestModelCheckpoint`, …) is shared with the framework-neutral
implementations in :mod:`horovod_tpu.callbacks`, which also serve the JAX
Trainer fit loop.
"""
from __future__ import annotations

from .. import init, is_initialized, join, local_rank, local_size, rank, \
    shutdown, size  # noqa: F401  (reference surface re-exports)
from ..callbacks import (BestModelCheckpoint, LearningRateScheduleCallback,
                         LearningRateWarmupCallback, MetricAverageCallback)

__all__ = ["init", "shutdown", "rank", "size", "local_rank", "local_size",
           "join", "is_initialized", "DistributedOptimizer",
           "MetricAverageCallback", "LearningRateWarmupCallback",
           "LearningRateScheduleCallback", "BestModelCheckpoint",
           "broadcast_global_variables",
           "BroadcastGlobalVariablesCallback", "TensorFlowKerasState"]


def __getattr__(item: str):
    # TF-backed surfaces resolve lazily so importing horovod_tpu.keras
    # never requires tensorflow.
    if item in ("BroadcastGlobalVariablesCallback", "TensorFlowKerasState",
                "SyncBatchNormalization", "Compression"):
        from .. import tensorflow as htf
        return getattr(htf, item)
    raise AttributeError(item)


def _require_keras():
    try:
        import tensorflow as tf  # noqa: F401
        return tf.keras
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.keras requires tensorflow/keras, which is not "
            "installed in this environment. Use horovod_tpu.callbacks with "
            "the JAX Trainer, or horovod_tpu.torch for PyTorch.") from exc


def DistributedOptimizer(optimizer, name: str | None = None,
                         compression=None,
                         backward_passes_per_step: int = 1, **kwargs):
    """Wrap a keras optimizer so apply_gradients allreduces first
    (reference: keras/__init__.py DistributedOptimizer — a thin veneer
    over the tensorflow implementation, as in the reference).

    The SAME instance is returned with its class swapped to a dynamic
    subclass — slot variables, iteration counters and every other piece of
    optimizer state survive intact (rebuilding from ``get_config()``
    would silently drop them). Collectives are graph ops, so compiled
    ``model.fit`` works."""
    _require_keras()
    from .. import tensorflow as htf
    return htf.DistributedOptimizer(
        optimizer, name=name, compression=compression,
        backward_passes_per_step=backward_passes_per_step, **kwargs)


def broadcast_global_variables(root_rank: int = 0) -> None:
    _require_keras()
    import tensorflow as tf
    from ..tensorflow import broadcast_variables
    broadcast_variables(tf.compat.v1.global_variables(), root_rank)
