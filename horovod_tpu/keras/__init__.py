"""Keras binding (reference: horovod/keras/__init__.py + callbacks.py).

Gated on tensorflow/keras being importable.  The callback surface
(`MetricAverageCallback`, `LearningRateWarmupCallback`,
`BestModelCheckpoint`, …) is shared with the framework-neutral
implementations in :mod:`horovod_tpu.callbacks`, which also serve the JAX
Trainer fit loop.
"""
from __future__ import annotations

from .. import init, is_initialized, join, local_rank, local_size, rank, \
    shutdown, size  # noqa: F401  (reference surface re-exports)
from ..callbacks import (BestModelCheckpoint, LearningRateScheduleCallback,
                         LearningRateWarmupCallback, MetricAverageCallback)

__all__ = ["init", "shutdown", "rank", "size", "local_rank", "local_size",
           "join", "is_initialized", "DistributedOptimizer",
           "MetricAverageCallback", "LearningRateWarmupCallback",
           "LearningRateScheduleCallback", "BestModelCheckpoint",
           "broadcast_global_variables"]


def _require_keras():
    try:
        import tensorflow as tf  # noqa: F401
        return tf.keras
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.keras requires tensorflow/keras, which is not "
            "installed in this environment. Use horovod_tpu.callbacks with "
            "the JAX Trainer, or horovod_tpu.torch for PyTorch.") from exc


def DistributedOptimizer(optimizer, name: str | None = None, **kwargs):
    """Wrap a keras optimizer so apply_gradients allreduces first
    (reference: keras/__init__.py DistributedOptimizer).

    The SAME instance is returned with its class swapped to a dynamic
    subclass — slot variables, iteration counters and every other piece of
    optimizer state survive intact (rebuilding from ``get_config()``
    would silently drop them)."""
    _require_keras()
    from ..tensorflow import allreduce

    base = optimizer.__class__

    class _Distributed(base):
        def apply_gradients(self, grads_and_vars, **apply_kwargs):
            grads_and_vars = [
                (g if g is None else allreduce(g, name=f"grad.{i}"), v)
                for i, (g, v) in enumerate(grads_and_vars)]
            return super().apply_gradients(grads_and_vars, **apply_kwargs)

    _Distributed.__name__ = f"Distributed{base.__name__}"
    optimizer.__class__ = _Distributed
    return optimizer


def broadcast_global_variables(root_rank: int = 0) -> None:
    _require_keras()
    import tensorflow as tf
    from ..tensorflow import broadcast_variables
    broadcast_variables(tf.compat.v1.global_variables(), root_rank)
