"""MXNet collective ops over the horovod_tpu core.

Reference: horovod/mxnet/mpi_ops.py:66-405 — NDArray collectives bound
through the MXNet engine's async callbacks.  TPU-native redesign: NDArrays
stage through host numpy into the same core enqueue API the torch binding
uses (the engine-callback machinery has no analogue here; ops complete
through Handle futures, and in-place variants copy back on completion).
``priority`` is accepted for API compatibility and advisory only — the
controller's response ordering is negotiated, not caller-priority driven.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from .. import (Adasum, Average, Sum)  # noqa: F401
from .. import (allgather_async as _allgather_async,
                allreduce_async as _allreduce_async,
                alltoall_async as _alltoall_async,
                broadcast_async as _broadcast_async,
                grouped_allreduce_async as _grouped_allreduce_async)
from ..core import Handle  # noqa: F401


def _mx():
    try:
        import mxnet
        return mxnet
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.mxnet ops require mxnet (end-of-life upstream and "
            "not installed in this image). The binding itself is complete; "
            "install mxnet or use horovod_tpu.torch / the JAX Trainer."
        ) from exc


def _to_np(tensor) -> np.ndarray:
    return tensor.asnumpy()


def _from_np(out: np.ndarray):
    mx = _mx()
    return mx.nd.array(out, dtype=out.dtype)


def _copy_out(target, out: np.ndarray):
    target[:] = _from_np(out.astype(np.dtype(target.dtype), copy=False))
    return target


def _wait(handle: Handle) -> list[np.ndarray]:
    status = handle.wait()
    status.raise_if_error()
    return [e.output for e in handle.entries]


# -- allreduce ---------------------------------------------------------------
def allreduce(tensor, average=True, name=None, priority=0,
              prescale_factor=1.0, postscale_factor=1.0):
    """Reference: mxnet/mpi_ops.py:66-108 (out-of-place, returns new
    NDArray)."""
    handle = _allreduce_async(_to_np(tensor), average, name, None,
                              prescale_factor, postscale_factor)
    return _from_np(_wait(handle)[0])


def allreduce_(tensor, average=True, name=None, priority=0,
               prescale_factor=1.0, postscale_factor=1.0):
    """In-place variant (reference: mpi_ops.py:111-147)."""
    handle = _allreduce_async(_to_np(tensor), average, name, None,
                              prescale_factor, postscale_factor)
    return _copy_out(tensor, _wait(handle)[0])


def grouped_allreduce(tensors: Sequence, average=True, name=None,
                      priority=0, prescale_factor=1.0,
                      postscale_factor=1.0):
    handle = _grouped_allreduce_async([_to_np(t) for t in tensors],
                                      average, name, None, prescale_factor,
                                      postscale_factor)
    return [_from_np(o) for o in _wait(handle)]


def grouped_allreduce_(tensors: Sequence, average=True, name=None,
                       priority=0, prescale_factor=1.0,
                       postscale_factor=1.0):
    handle = _grouped_allreduce_async([_to_np(t) for t in tensors],
                                      average, name, None, prescale_factor,
                                      postscale_factor)
    return [_copy_out(t, o) for t, o in zip(tensors, _wait(handle))]


# -- allgather / broadcast / alltoall ---------------------------------------
def allgather(tensor, name=None, priority=0):
    """Concatenate every rank's tensor along dim 0; first dims may differ
    (reference: mpi_ops.py:242-279)."""
    handle = _allgather_async(_to_np(tensor), name)
    return _from_np(_wait(handle)[0])


def broadcast(tensor, root_rank, name=None, priority=0):
    handle = _broadcast_async(_to_np(tensor), root_rank, name)
    return _from_np(_wait(handle)[0])


def broadcast_(tensor, root_rank, name=None, priority=0):
    handle = _broadcast_async(_to_np(tensor), root_rank, name)
    return _copy_out(tensor, _wait(handle)[0])


def alltoall(tensor, splits=None, name=None, priority=0):
    """Distribute dim-0 slices to every rank (reference:
    mpi_ops.py:358-405)."""
    if splits is not None and not isinstance(splits, np.ndarray):
        splits = _to_np(splits) if hasattr(splits, "asnumpy") \
            else np.asarray(splits)
    handle = _alltoall_async(_to_np(tensor), splits, name)
    return _from_np(_wait(handle)[0])
