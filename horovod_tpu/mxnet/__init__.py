"""MXNet binding (reference: horovod/mxnet/__init__.py:40-215).

A complete, import-gated binding: the collective ops and the two training
wrappers carry the reference's semantics (gradient sum + rescale_grad
normalization, predivide split, grouped enqueue), staged through the same
eager core as the torch binding.  MXNet itself is end-of-life upstream and
not installed in this image, so the wrapper *classes* are built lazily on
first access (PEP 562) — importing this module, and everything that only
needs rank/size bookkeeping, works without mxnet; touching
DistributedOptimizer/DistributedTrainer requires it (the test battery
substitutes a stub module).
"""
from __future__ import annotations

from collections import OrderedDict

from .. import init, is_initialized, local_rank, local_size, rank, \
    shutdown, size  # noqa: F401
from .mpi_ops import (Adasum, Average, Sum, allgather, allreduce,  # noqa: F401
                      allreduce_, alltoall, broadcast, broadcast_,
                      grouped_allreduce, grouped_allreduce_)

__all__ = ["init", "shutdown", "rank", "size", "local_rank", "local_size",
           "is_initialized", "allreduce", "allreduce_", "grouped_allreduce",
           "grouped_allreduce_", "allgather", "broadcast", "broadcast_",
           "alltoall", "DistributedOptimizer", "DistributedTrainer",
           "broadcast_parameters", "Average", "Sum", "Adasum"]


def _split_list(xs, parts: int):
    """Near-even contiguous split (reference: common/util split_list)."""
    base, rem = divmod(len(xs), parts)
    out, start = [], 0
    for i in range(parts):
        n = base + (1 if i < rem else 0)
        if n:
            out.append(xs[start:start + n])
        start += n
    return out


def _append_broadcast_init(param, root_rank: int, name: str) -> None:
    """Deferred-init gluon param: broadcast right after shape inference
    materializes it (reference: mxnet/__init__.py:183-189)."""
    init_impl = param._init_impl

    def wrapped(*args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(param.data(), root_rank, name=name)

    param._init_impl = wrapped


def broadcast_parameters(params, root_rank: int = 0, prefix: str = None):
    """Sync initial parameters from root (reference:
    mxnet/__init__.py:191-215; accepts a dict or gluon ParameterDict).
    Deferred-init params are broadcast after their first forward pass
    infers shapes — skipping them would silently leave each rank training
    its own random init."""
    prefix = prefix or ""
    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        raise ValueError("invalid params of type: %s" % type(params))
    for name, p in items:
        tag = f"{prefix}param.{name}"
        if not hasattr(p, "data"):
            broadcast_(p, root_rank, name=tag)
            continue
        try:
            tensor = p.data()
        except Exception as exc:
            if type(exc).__name__ == "DeferredInitializationError":
                _append_broadcast_init(p, root_rank, tag)
                continue
            raise
        broadcast_(tensor, root_rank, name=tag)


def _build_distributed_optimizer():
    mx = _require_mxnet()

    class DistributedOptimizer(mx.optimizer.Optimizer):
        """Wrap any mx optimizer: allreduce-sum each gradient at update
        time, fold the 1/size average into rescale_grad (reference:
        mxnet/__init__.py:40-93)."""

        def __init__(self, optimizer, gradient_predivide_factor=1.0,
                     num_groups=0):
            self._optimizer = optimizer
            # Average = sum-allreduce + rescale_grad/size, the reference's
            # preferred split (better than dividing on the wire).
            self._optimizer.rescale_grad *= \
                gradient_predivide_factor / size()
            self._gradient_predivide_factor = gradient_predivide_factor
            self._num_groups = num_groups

        def __getattr__(self, item):
            return getattr(self._optimizer, item)

        def create_state_multi_precision(self, index, weight):
            return self._optimizer.create_state_multi_precision(index,
                                                                weight)

        def _do_allreduce(self, index, grad):
            if size() == 1:
                return
            pre = 1.0 / self._gradient_predivide_factor
            if isinstance(index, (tuple, list)):
                if self._num_groups > 0:
                    grad_split = _split_list(grad, self._num_groups)
                    index_split = _split_list(index, self._num_groups)
                    for grads, indices in zip(grad_split, index_split):
                        grouped_allreduce_(
                            tensors=grads, average=False,
                            name=f"{indices[0]}:{indices[-1]}",
                            prescale_factor=pre)
                else:
                    for i in range(len(index)):
                        allreduce_(grad[i], average=False,
                                   name=str(index[i]), prescale_factor=pre)
            else:
                allreduce_(grad, average=False, name=str(index),
                           prescale_factor=pre)

        def update(self, index, weight, grad, state):
            self._do_allreduce(index, grad)
            self._optimizer.update(index, weight, grad, state)

        def update_multi_precision(self, index, weight, grad, state):
            self._do_allreduce(index, grad)
            self._optimizer.update_multi_precision(index, weight, grad,
                                                   state)

        def set_learning_rate(self, lr):
            self._optimizer.set_learning_rate(lr)

        def set_lr_mult(self, args_lr_mult):
            self._optimizer.set_lr_mult(args_lr_mult)

        def set_wd_mult(self, args_wd_mult):
            self._optimizer.set_wd_mult(args_wd_mult)

    return DistributedOptimizer


def _build_distributed_trainer():
    mx = _require_mxnet()

    class DistributedTrainer(mx.gluon.Trainer):
        """gluon Trainer whose gradient reduction rides these collectives
        instead of kvstore, averaging via the _scale fold (reference:
        mxnet/__init__.py:102-180)."""

        def __init__(self, params, optimizer, optimizer_params=None,
                     gradient_predivide_factor=1.0, prefix=None,
                     num_groups=0):
            if type(optimizer).__name__ == "DistributedOptimizer":
                optimizer = optimizer._optimizer
            if isinstance(params, dict):
                params = OrderedDict(params)
            elif isinstance(params, (list, tuple)):
                # Deterministic cross-rank order; keyed by name because
                # gluon Parameters define no ordering of their own. The
                # "" fallback + stable sort keeps unnamed params in the
                # caller's list order (identical across ranks) rather
                # than falling back to per-process repr addresses.
                params = sorted(params,
                                key=lambda p: getattr(p, "name", ""))
            super().__init__(params, optimizer,
                             optimizer_params=optimizer_params,
                             kvstore=None)
            self._scale *= gradient_predivide_factor / size()
            self._gradient_predivide_factor = gradient_predivide_factor
            self._prefix = prefix if prefix else ""
            self._num_groups = num_groups

        def _allreduce_grads(self):
            if size() == 1:
                return
            pre = 1.0 / self._gradient_predivide_factor
            live = [(i, p) for i, p in enumerate(self._params)
                    if p.grad_req != "null"]
            if self._num_groups > 0:
                pairs = [(p.list_grad()[0], self._prefix + str(i))
                         for i, p in live]
                for group in _split_list(pairs, self._num_groups):
                    # Enqueue per dtype within the group (reference:
                    # __init__.py:160-170).
                    by_dtype = OrderedDict()
                    for grad, name in group:
                        by_dtype.setdefault(str(grad.dtype),
                                            []).append((grad, name))
                    for entries in by_dtype.values():
                        grads, names = zip(*entries)
                        grouped_allreduce_(
                            tensors=list(grads), average=False,
                            name=f"{names[0]}:{names[-1]}",
                            prescale_factor=pre)
            else:
                for i, p in live:
                    allreduce_(p.list_grad()[0], average=False,
                               name=self._prefix + str(i),
                               prescale_factor=pre)

    return DistributedTrainer


def _require_mxnet():
    try:
        import mxnet
        return mxnet
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.mxnet wrappers require mxnet (end-of-life "
            "upstream, not installed here). The binding is complete; "
            "install mxnet, or use horovod_tpu.torch / the JAX Trainer."
        ) from exc


_lazy_cache: dict = {}


def __getattr__(name: str):
    """PEP 562: build the mx-subclassing wrappers only when touched."""
    if name in ("DistributedOptimizer", "DistributedTrainer"):
        if name not in _lazy_cache:
            builder = (_build_distributed_optimizer
                       if name == "DistributedOptimizer"
                       else _build_distributed_trainer)
            _lazy_cache[name] = builder()
        return _lazy_cache[name]
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
