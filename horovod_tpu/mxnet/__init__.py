"""MXNet binding surface (reference: horovod/mxnet/__init__.py).

MXNet reached end-of-life upstream and is not part of this image; the
module exists so reference imports fail with actionable guidance instead of
a bare ModuleNotFoundError.  The collective semantics MXNet users need
(DistributedOptimizer-style gradient averaging) are available through
:mod:`horovod_tpu.torch` or the JAX Trainer.
"""
from __future__ import annotations

from .. import init, is_initialized, local_rank, local_size, rank, \
    shutdown, size  # noqa: F401

__all__ = ["init", "shutdown", "rank", "size", "local_rank", "local_size",
           "is_initialized", "DistributedOptimizer", "DistributedTrainer",
           "broadcast_parameters"]

_MSG = ("horovod_tpu.mxnet requires mxnet, which is end-of-life and not "
        "installed in this environment. Use horovod_tpu.torch "
        "(DistributedOptimizer) or the JAX-native Trainer instead.")


def _require_mxnet():
    try:
        import mxnet  # noqa: F401
        return mxnet
    except ImportError as exc:
        raise ImportError(_MSG) from exc


def DistributedOptimizer(optimizer, *args, **kwargs):
    _require_mxnet()
    raise NotImplementedError(_MSG)


def DistributedTrainer(params, optimizer, *args, **kwargs):
    _require_mxnet()
    raise NotImplementedError(_MSG)


def broadcast_parameters(params, root_rank: int = 0):
    _require_mxnet()
    raise NotImplementedError(_MSG)
