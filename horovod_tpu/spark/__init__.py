"""Spark integration surface (reference: horovod/spark/runner.py:47-426).

``run(fn)`` (gated on pyspark) launches one Spark task per slot, each task
registers its hostname, the driver computes the HOROVOD_RANK/LOCAL/CROSS
contract from host hashes, starts a rendezvous server, and every task runs
``fn`` with the eager runtime env set — the same protocol the reference's
spark driver/task services implement.  The Estimator API
(:mod:`.estimator` — TorchEstimator/KerasEstimator over a
:class:`.store.FilesystemStore`) works on pandas DataFrames without
pyspark and rides Spark executors when a SparkContext is live.
"""
from __future__ import annotations

import socket
from collections import OrderedDict
from typing import Any, Callable

from ..runner.hosts import HostInfo, get_host_assignments
from .store import (FilesystemStore, KVBlobClient,  # noqa: F401
                    RemoteBlobStore, Store)

__all__ = ["run", "claim_slot", "Store", "FilesystemStore",
           "RemoteBlobStore", "KVBlobClient",
           "TorchEstimator", "TorchModel", "KerasEstimator", "KerasModel",
           "LightningEstimator"]


def __getattr__(item: str):
    # Estimators import torch/tf lazily — resolve on first touch.
    if item in ("TorchEstimator", "TorchModel", "KerasEstimator",
                "KerasModel", "LightningEstimator"):
        from . import estimator
        return getattr(estimator, item)
    raise AttributeError(item)


def claim_slot(host: str, rendezvous_addr: str, rendezvous_port: int,
               pool: dict[str, list], task_key: str = ""):
    """Atomically claim one distinct slot on ``host`` through the driver's
    rendezvous counter — never derived from the partition index, which is
    global and collides when partition placement drifts between the
    discovery job and the run job (reference: spark tasks register with a
    driver service for exactly this reason, spark/runner.py:47-426).

    ``task_key`` identifies the logical task (partition id): a retried or
    speculatively re-executed task re-presents the same key and gets its
    original slot back instead of stealing a fresh one."""
    from ..runner.network import RendezvousClient

    client = RendezvousClient(rendezvous_addr, rendezvous_port)
    local_idx = client.claim("sparkslots", host, task_key=task_key)
    env_slots = pool.get(host, [])
    if local_idx >= len(env_slots):
        raise RuntimeError(
            f"host {host} claimed slot #{local_idx} but only "
            f"{len(env_slots)} slots were discovered there — task "
            "placement drifted between the discovery and run jobs")
    return env_slots[local_idx]


def _require_spark():
    try:
        import pyspark
        return pyspark
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.spark requires pyspark, which is not installed in "
            "this environment. Use horovod_tpu.run() or the horovodrun-tpu "
            "CLI instead.") from exc


def run(fn: Callable, args: tuple = (), kwargs: dict | None = None,
        num_proc: int | None = None, verbose: bool = False) -> list:
    """Run ``fn`` on ``num_proc`` Spark tasks (reference: spark/runner.py
    horovod.spark.run)."""
    pyspark = _require_spark()
    kwargs = kwargs or {}
    spark = pyspark.sql.SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    num_proc = num_proc or sc.defaultParallelism

    # Phase 1: discover task placement (hostname per partition).
    hostnames = sc.parallelize(range(num_proc), num_proc).map(
        lambda _: socket.gethostname()).collect()
    by_host: "OrderedDict[str, int]" = OrderedDict()
    for h in hostnames:
        by_host[h] = by_host.get(h, 0) + 1
    hosts = [HostInfo(hostname=h, slots=n) for h, n in by_host.items()]
    slots = get_host_assignments(hosts, num_proc)

    from ..runner.network import RendezvousServer
    server = RendezvousServer()
    port = server.start()
    addr = socket.getfqdn()

    pool: dict[str, list] = {}
    for slot in slots:
        pool.setdefault(slot.hostname, []).append(slot)

    def task(index: int):
        import os
        host = socket.gethostname()
        slot = claim_slot(host, addr, port, pool,
                          task_key=f"partition{index}")
        os.environ.update(slot.to_env())
        os.environ.update({
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": addr,
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
            "HOROVOD_CONTROLLER": "tcp",
        })
        return slot.rank, fn(*args, **kwargs)

    try:
        results = sc.parallelize(range(num_proc), num_proc) \
            .mapPartitionsWithIndex(
                lambda i, _: iter([task(i)])).collect()
    finally:
        server.stop()
    return [value for _rank, value in sorted(results)]
