"""Spark-ML-style Estimator API: fit(df) -> Model -> transform(df).

Reference: horovod/spark/torch/estimator.py:91-328 (TorchEstimator),
spark/keras/estimator.py (KerasEstimator), spark/common/estimator.py.
The reference materializes the DataFrame to parquet via petastorm and
launches `horovod.spark.run` over the cluster's executors; here the data
path is numpy shards in a :class:`FilesystemStore` and training runs under
``horovod_tpu.run`` (local forked workers) — or ``horovod_tpu.spark.run``
when a live SparkContext is available. Accepts pandas DataFrames directly
(a Spark DataFrame is converted via ``toPandas()``), so the API works in
this image where pyspark is absent.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Sequence

import numpy as np

from .store import FilesystemStore, Store

__all__ = ["TorchEstimator", "TorchModel", "KerasEstimator", "KerasModel",
           "LightningEstimator"]


def _unpack_configure_optimizers(ret):
    """Normalize every configure_optimizers() return shape PL documents
    to (optimizer, [(scheduler, interval)]): a bare Optimizer,
    [optimizer], ([optimizer], [schedulers]), or {"optimizer": ...,
    "lr_scheduler": ...}; scheduler entries may themselves be
    {"scheduler": s, "interval": "epoch"|"step", ...} config dicts.
    Exactly one optimizer is supported — multi-optimizer (GAN-style)
    setups raise rather than silently dropping optimizers whose
    parameters would then never step."""
    def _sched(s):
        if isinstance(s, dict):
            return s["scheduler"], s.get("interval", "epoch")
        return s, "epoch"

    def _single(opts):
        if len(opts) != 1:
            raise NotImplementedError(
                f"configure_optimizers() returned {len(opts)} optimizers; "
                "this estimator supports exactly one (multi-optimizer "
                "modules would silently leave parameters untrained).")
        return opts[0]

    if isinstance(ret, dict):
        sched = ret.get("lr_scheduler")
        return ret["optimizer"], ([_sched(sched)] if sched is not None
                                  else [])
    if isinstance(ret, (tuple, list)):
        if len(ret) == 2 and isinstance(ret[0], (tuple, list)):
            opts, scheds = ret
            return _single(list(opts)), [_sched(s) for s in scheds]
        return _single(list(ret)), []
    return ret, []


def _lightning_train_fn(store: Store, run_id: str, model_bytes: bytes,
                        batch_size: int, epochs: int) -> dict:
    """Per-rank loop driving the LightningModule protocol
    (reference: spark/lightning/remote.py).  This runtime IS the
    strategy: the module's own training_step/configure_optimizers run
    inside our distributed loop, with the gradient allreduce supplied by
    the torch DistributedOptimizer wrapper."""
    import io

    import torch

    import horovod_tpu as hvd
    import horovod_tpu.torch as hvt

    hvd.init()
    try:
        rank, world = hvd.rank(), hvd.size()
        xs, ys = _load_equal_shard(store, run_id, rank, world)
        xs, ys = torch.from_numpy(xs), torch.from_numpy(ys)

        model = torch.load(io.BytesIO(model_bytes), weights_only=False)
        opt, schedulers = _unpack_configure_optimizers(
            model.configure_optimizers())
        opt = hvt.DistributedOptimizer(
            opt, named_parameters=model.named_parameters())
        # The wrapper copies param_groups (its load_state_dict makes
        # fresh dicts), so schedulers created against the raw optimizer
        # must be rebound or their lr writes land in dicts the training
        # optimizer never reads.
        for sched, _interval in schedulers:
            sched.optimizer = opt
        epoch_scheds = [s for s, iv in schedulers if iv != "step"]
        step_scheds = [s for s, iv in schedulers if iv == "step"]

        def step(xb, yb, idx):
            out = model.training_step((xb, yb), idx)
            return out["loss"] if isinstance(out, dict) else out

        def batch_end():
            for sched in step_scheds:
                sched.step()

        def epoch_end():
            for sched in epoch_scheds:
                sched.step()
            if hasattr(model, "on_train_epoch_end"):
                model.on_train_epoch_end()

        history = _train_loop(xs, ys, batch_size, epochs, opt, step,
                              epoch_end=epoch_end, batch_end=batch_end,
                              loss_name="pl_epoch_loss")
        _save_model_if_root(store, run_id, model, rank)
        return {"rank": rank, "history": history}
    finally:
        hvd.shutdown()


class LightningEstimator:
    """fit(df) -> TorchModel for LightningModule-style models
    (reference: spark/lightning/estimator.py:118-420).

    TPU-native design: the LightningModule *protocol* —
    ``training_step(batch, idx)`` + ``configure_optimizers()`` (+
    optional ``on_train_epoch_end``) — is duck-typed on any
    torch.nn.Module, so no pytorch_lightning import is required at all;
    a real LightningModule satisfies it as-is, and this runtime plays
    the role PL's Trainer/strategy stack plays in the reference."""

    def __init__(self, model,
                 feature_cols: Sequence[str] = ("features",),
                 label_cols: Sequence[str] = ("label",),
                 batch_size: int = 32, epochs: int = 1,
                 num_proc: int = 1, store: Store | None = None,
                 run_id: str | None = None) -> None:
        for method in ("training_step", "configure_optimizers"):
            if not callable(getattr(model, method, None)):
                raise ValueError(
                    f"LightningEstimator needs a model with {method}() "
                    "(the LightningModule protocol); plain nn.Modules "
                    "belong with TorchEstimator.")
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.store = store or FilesystemStore(".horovod_tpu_store")
        self.run_id = run_id

    def fit(self, df) -> "TorchModel":
        return _fit_distributed(self, df, _lightning_train_fn,
                                (self.batch_size, self.epochs))


def _to_pandas(df):
    if hasattr(df, "toPandas"):          # pyspark DataFrame
        return df.toPandas()
    return df                            # already pandas


def _extract(df, feature_cols: Sequence[str], label_cols: Sequence[str]):
    pdf = _to_pandas(df)
    x = np.stack([np.asarray(pdf[c].tolist(), dtype=np.float32)
                  for c in feature_cols], axis=-1)
    if x.ndim > 2 and x.shape[-1] == 1:
        x = x[..., 0]
    y = np.stack([np.asarray(pdf[c].tolist(), dtype=np.float32)
                  for c in label_cols], axis=-1)
    if y.shape[-1] == 1:
        y = y[..., 0]
    return x, y


def _load_equal_shard(store: Store, run_id: str, rank: int, world: int):
    """Load the run's training blob and take this rank's shard: strided
    assignment with wrap-around padding (the DistributedSampler contract,
    elastic/sampler.py) so every rank holds exactly ceil(n/world) samples.
    Equal counts are a correctness requirement, not an optimization —
    ranks with different batch counts enqueue different numbers of
    gradient collectives and deadlock the negotiation."""
    blob = store.load_npz(
        store.join(store.get_train_data_path(run_id), "train.npz"))
    X, Y = blob["x"], blob["y"]
    n = X.shape[0]
    if n == 0:
        raise ValueError("estimator fit() received an empty DataFrame")
    per = (n + world - 1) // world
    idx = np.array([(rank + k * world) % n for k in range(per)])
    return X[idx], Y[idx]


def _save_model_if_root(store: Store, run_id: str, model, rank: int) -> None:
    import io

    import torch

    if rank == 0:
        buf = io.BytesIO()
        torch.save(model, buf)
        store.write_bytes(
            store.join(store.get_checkpoint_path(run_id), "model.pt"),
            buf.getvalue())


def _train_loop(xs, ys, batch_size: int, epochs: int, opt,
                step: Callable, epoch_end: Callable | None = None,
                batch_end: Callable | None = None,
                loss_name: str = "epoch_loss") -> list[float]:
    """Shared epoch loop: per-batch `step(xb, yb, idx) -> loss`, backward,
    optimizer step, cross-rank epoch-loss average.  Shards are equalized
    (_load_equal_shard) so every rank runs the same batch count."""
    import horovod_tpu as hvd

    history = []
    for _ in range(epochs):
        epoch_loss, batches = 0.0, 0
        for idx, start in enumerate(range(0, len(xs), batch_size)):
            xb = xs[start:start + batch_size]
            yb = ys[start:start + batch_size]
            opt.zero_grad()
            loss = step(xb, yb, idx)
            loss.backward()
            opt.step()
            if batch_end is not None:
                batch_end()
            epoch_loss += float(loss.detach())
            batches += 1
        if epoch_end is not None:
            epoch_end()
        avg = hvd.allreduce(
            np.array([epoch_loss / max(batches, 1)], np.float32),
            name=loss_name)
        history.append(float(np.asarray(avg)[0]))
    return history


def _fit_distributed(est, df, train_fn: Callable, args_tail: tuple):
    """Shared fit plumbing for the torch-family estimators: persist data
    + model through the store, run train_fn over the workers (Spark
    executors when pyspark is importable, local forked workers
    otherwise), reload the rank-0 checkpoint."""
    import io

    import torch

    import horovod_tpu as hvd

    run_id = est.run_id or est.store.new_run_id()
    x, y = _extract(df, est.feature_cols, est.label_cols)
    est.store.save_npz(
        est.store.join(est.store.get_train_data_path(run_id), "train.npz"),
        x=x, y=y)
    buf = io.BytesIO()
    torch.save(est.model, buf)
    args = (est.store, run_id, buf.getvalue()) + args_tail

    # Only the availability probe sits in the try: an ImportError raised
    # BY the spark run itself is a real configuration error and must
    # surface, not silently retrain on local forks.
    try:
        import pyspark  # noqa: F401
        has_spark = True
    except ImportError:
        has_spark = False
    if has_spark:
        from . import run as spark_run
        results = spark_run(train_fn, args=args, num_proc=est.num_proc)
    else:
        results = hvd.run(train_fn, args=args, np=est.num_proc)

    trained = torch.load(
        io.BytesIO(est.store.read_bytes(
            est.store.join(est.store.get_checkpoint_path(run_id),
                           "model.pt"))),
        weights_only=False)
    history = results[0]["history"] if results else []
    return TorchModel(trained, feature_cols=est.feature_cols,
                      label_cols=est.label_cols, run_id=run_id,
                      history=history)


def _torch_train_fn(store: Store, run_id: str, model_bytes: bytes,
                    opt_factory: Callable, loss_name: str, batch_size: int,
                    epochs: int) -> dict:
    """Per-rank training loop (reference: spark/torch/remote.py).  All
    artifact IO goes through the (pickled) store, so remote blob stores
    work without a shared filesystem."""
    import io

    import torch

    import horovod_tpu as hvd
    import horovod_tpu.torch as hvt

    hvd.init()
    try:
        rank, world = hvd.rank(), hvd.size()
        xs, ys = _load_equal_shard(store, run_id, rank, world)
        xs, ys = torch.from_numpy(xs), torch.from_numpy(ys)

        model = torch.load(io.BytesIO(model_bytes), weights_only=False)
        loss_fn = {"mse": torch.nn.MSELoss(),
                   "l1": torch.nn.L1Loss(),
                   "cross_entropy": torch.nn.CrossEntropyLoss()}[loss_name]
        opt = hvt.DistributedOptimizer(
            opt_factory(model.parameters()),
            named_parameters=model.named_parameters())
        hvt.broadcast_parameters(model.state_dict(), root_rank=0)

        def step(xb, yb, _idx):
            out = model(xb)
            if out.shape != yb.shape and out.dim() == yb.dim() + 1 \
                    and out.shape[-1] == 1:
                out = out[..., 0]
            return loss_fn(out, yb)

        history = _train_loop(xs, ys, batch_size, epochs, opt, step)
        _save_model_if_root(store, run_id, model, rank)
        return {"rank": rank, "history": history}
    finally:
        hvd.shutdown()


class TorchEstimator:
    """fit(df) -> TorchModel (reference: spark/torch/estimator.py:91-328).

    Parameters mirror the reference's Param surface where meaningful:
    model, optimizer (factory ``params -> torch.optim.Optimizer``), loss
    ("mse" | "l1" | "cross_entropy"), feature_cols, label_cols,
    batch_size, epochs, num_proc, store.
    """

    def __init__(self, model, optimizer: Callable | None = None,
                 loss: str = "mse",
                 feature_cols: Sequence[str] = ("features",),
                 label_cols: Sequence[str] = ("label",),
                 batch_size: int = 32, epochs: int = 1,
                 num_proc: int = 1, store: Store | None = None,
                 run_id: str | None = None) -> None:
        import functools

        import torch

        self.model = model
        # Factory must be picklable (it travels to spawned workers):
        # functools.partial of the optimizer class, not a lambda.
        self.optimizer = optimizer or functools.partial(torch.optim.SGD,
                                                        lr=0.1)
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.store = store or FilesystemStore(".horovod_tpu_store")
        self.run_id = run_id

    def fit(self, df) -> "TorchModel":
        return _fit_distributed(self, df, _torch_train_fn,
                                (self.optimizer, self.loss,
                                 self.batch_size, self.epochs))


class TorchModel:
    """transform(df) appends prediction columns
    (reference: spark/torch/estimator.py TorchModel)."""

    def __init__(self, model, feature_cols: Sequence[str],
                 label_cols: Sequence[str], run_id: str | None = None,
                 history: list | None = None) -> None:
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.run_id = run_id
        self.history = history or []

    def transform(self, df):
        import torch

        pdf = _to_pandas(df).copy()
        x = np.stack([np.asarray(pdf[c].tolist(), dtype=np.float32)
                      for c in self.feature_cols], axis=-1)
        if x.ndim > 2 and x.shape[-1] == 1:
            x = x[..., 0]
        with torch.no_grad():
            pred = self.model(torch.from_numpy(x)).numpy()
        if pred.ndim == 1 or pred.shape[-1] == 1:
            pdf[f"{self.label_cols[0]}__output"] = pred.reshape(-1)
        else:
            for j in range(pred.shape[-1]):
                pdf[f"{self.label_cols[0]}__output_{j}"] = pred[:, j]
        return pdf


def _keras_train_fn(store: Store, run_id: str, model_bytes: bytes,
                    compile_kwargs: dict, batch_size: int,
                    epochs: int) -> dict:
    """Per-rank keras loop (reference: spark/keras/remote.py)."""
    import tempfile

    import horovod_tpu as hvd
    import horovod_tpu.tensorflow as htf

    hvd.init()
    try:
        import tensorflow as tf

        rank, world = hvd.rank(), hvd.size()
        xs, ys = _load_equal_shard(store, run_id, rank, world)

        # keras (de)serializes via real files: stage through local tmp,
        # ship bytes through the store.
        with tempfile.TemporaryDirectory() as tmpdir:
            path = os.path.join(tmpdir, "model_in.keras")
            with open(path, "wb") as f:
                f.write(model_bytes)
            model = tf.keras.models.load_model(path)
        opt = htf.DistributedOptimizer(
            tf.keras.optimizers.get(compile_kwargs.get("optimizer", "sgd")))
        model.compile(optimizer=opt,
                      loss=compile_kwargs.get("loss", "mse"))
        hist = model.fit(
            xs, ys, batch_size=batch_size, epochs=epochs, verbose=0,
            shuffle=False,
            callbacks=[htf.BroadcastGlobalVariablesCallback(0)])
        if rank == 0:
            # Weights only: the full model would embed the dynamic
            # Distributed* optimizer class, which cannot deserialize
            # outside a worker.
            with tempfile.TemporaryDirectory() as tmpdir:
                wpath = os.path.join(tmpdir, "model.weights.h5")
                model.save_weights(wpath)
                with open(wpath, "rb") as f:
                    store.write_bytes(
                        store.join(store.get_checkpoint_path(run_id),
                                   "model.weights.h5"), f.read())
        return {"rank": rank, "history": hist.history}
    finally:
        hvd.shutdown()


class KerasEstimator:
    """fit(df) -> KerasModel (reference: spark/keras/estimator.py)."""

    def __init__(self, model, optimizer: Any = "sgd", loss: str = "mse",
                 feature_cols: Sequence[str] = ("features",),
                 label_cols: Sequence[str] = ("label",),
                 batch_size: int = 32, epochs: int = 1,
                 num_proc: int = 1, store: Store | None = None) -> None:
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.store = store or FilesystemStore(".horovod_tpu_store")

    def fit(self, df) -> "KerasModel":
        import horovod_tpu as hvd

        import tempfile

        run_id = self.store.new_run_id()
        data_path = self.store.get_train_data_path(run_id)
        ckpt_path = self.store.get_checkpoint_path(run_id)
        x, y = _extract(df, self.feature_cols, self.label_cols)
        self.store.save_npz(self.store.join(data_path, "train.npz"),
                            x=x, y=y)

        with tempfile.TemporaryDirectory() as tmpdir:
            tmp = os.path.join(tmpdir, "model_in.keras")
            self.model.save(tmp)
            with open(tmp, "rb") as f:
                model_bytes = f.read()

        compile_kwargs = {"optimizer": self.optimizer, "loss": self.loss}
        args = (self.store, run_id, model_bytes, compile_kwargs,
                self.batch_size, self.epochs)
        # Probe-only try (same pattern as _fit_distributed): an
        # ImportError raised BY the spark run is a real configuration
        # error and must surface, not silently retrain on local forks.
        try:
            import pyspark  # noqa: F401
            has_spark = True
        except ImportError:
            has_spark = False
        if has_spark:
            from . import run as spark_run
            results = spark_run(_keras_train_fn, args=args,
                                num_proc=self.num_proc)
        else:
            results = hvd.run(_keras_train_fn, args=args,
                              np=self.num_proc)

        with tempfile.TemporaryDirectory() as tmpdir:
            wpath = os.path.join(tmpdir, "model.weights.h5")
            with open(wpath, "wb") as f:
                f.write(self.store.read_bytes(
                    self.store.join(ckpt_path, "model.weights.h5")))
            self.model.load_weights(wpath)
        trained = self.model
        history = results[0]["history"] if results else {}
        return KerasModel(trained, feature_cols=self.feature_cols,
                          label_cols=self.label_cols, run_id=run_id,
                          history=history)


class KerasModel:
    def __init__(self, model, feature_cols: Sequence[str],
                 label_cols: Sequence[str], run_id: str | None = None,
                 history: dict | None = None) -> None:
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.run_id = run_id
        self.history = history or {}

    def transform(self, df):
        pdf = _to_pandas(df).copy()
        x = np.stack([np.asarray(pdf[c].tolist(), dtype=np.float32)
                      for c in self.feature_cols], axis=-1)
        if x.ndim > 2 and x.shape[-1] == 1:
            x = x[..., 0]
        pred = self.model.predict(x, verbose=0)
        if pred.ndim == 1 or pred.shape[-1] == 1:
            pdf[f"{self.label_cols[0]}__output"] = pred.reshape(-1)
        else:
            for j in range(pred.shape[-1]):
                pdf[f"{self.label_cols[0]}__output_{j}"] = pred[:, j]
        return pdf
