"""Spark-ML-style Estimator API: fit(df) -> Model -> transform(df).

Reference: horovod/spark/torch/estimator.py:91-328 (TorchEstimator),
spark/keras/estimator.py (KerasEstimator), spark/common/estimator.py.
The reference materializes the DataFrame to parquet via petastorm and
launches `horovod.spark.run` over the cluster's executors; here the data
path is numpy shards in a :class:`FilesystemStore` and training runs under
``horovod_tpu.run`` (local forked workers) — or ``horovod_tpu.spark.run``
when a live SparkContext is available. Accepts pandas DataFrames directly
(a Spark DataFrame is converted via ``toPandas()``), so the API works in
this image where pyspark is absent.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Sequence

import numpy as np

from .store import FilesystemStore, Store

__all__ = ["TorchEstimator", "TorchModel", "KerasEstimator", "KerasModel",
           "LightningEstimator"]


class LightningEstimator:
    """Intentional scope cut (reference: spark/lightning/estimator.py).

    pytorch-lightning is not part of the TPU image, and its training loop
    duplicates what :class:`TorchEstimator` already runs over this
    runtime; see README "Scope cuts" for the rationale.  Constructing one
    states the migration path instead of silently failing later."""

    def __init__(self, *_args, **_kwargs) -> None:
        raise ImportError(
            "LightningEstimator is an intentional scope cut of the TPU "
            "build (pytorch_lightning is not in the image). Port the "
            "LightningModule's training_step into a torch.nn.Module and "
            "use TorchEstimator (same store/num_proc surface), or run "
            "lightning yourself inside horovod_tpu.run workers.")


def _to_pandas(df):
    if hasattr(df, "toPandas"):          # pyspark DataFrame
        return df.toPandas()
    return df                            # already pandas


def _extract(df, feature_cols: Sequence[str], label_cols: Sequence[str]):
    pdf = _to_pandas(df)
    x = np.stack([np.asarray(pdf[c].tolist(), dtype=np.float32)
                  for c in feature_cols], axis=-1)
    if x.ndim > 2 and x.shape[-1] == 1:
        x = x[..., 0]
    y = np.stack([np.asarray(pdf[c].tolist(), dtype=np.float32)
                  for c in label_cols], axis=-1)
    if y.shape[-1] == 1:
        y = y[..., 0]
    return x, y


def _torch_train_fn(store: Store, run_id: str, model_bytes: bytes,
                    opt_factory: Callable, loss_name: str, batch_size: int,
                    epochs: int) -> dict:
    """Per-rank training loop (reference: spark/torch/remote.py).  All
    artifact IO goes through the (pickled) store, so remote blob stores
    work without a shared filesystem."""
    import io

    import torch

    import horovod_tpu as hvd
    import horovod_tpu.torch as hvt

    hvd.init()
    try:
        rank, world = hvd.rank(), hvd.size()
        blob = store.load_npz(
            store.join(store.get_train_data_path(run_id), "train.npz"))
        X = torch.from_numpy(blob["x"])
        Y = torch.from_numpy(blob["y"])
        # Contiguous shard per rank (reference: petastorm row-group shard).
        n = X.shape[0]
        per = (n + world - 1) // world
        xs, ys = X[rank * per:(rank + 1) * per], Y[rank * per:(rank + 1) * per]

        model = torch.load(io.BytesIO(model_bytes), weights_only=False)
        loss_fn = {"mse": torch.nn.MSELoss(),
                   "l1": torch.nn.L1Loss(),
                   "cross_entropy": torch.nn.CrossEntropyLoss()}[loss_name]
        opt = hvt.DistributedOptimizer(
            opt_factory(model.parameters()),
            named_parameters=model.named_parameters())
        hvt.broadcast_parameters(model.state_dict(), root_rank=0)

        history = []
        for _ in range(epochs):
            epoch_loss = 0.0
            batches = 0
            for i in range(0, len(xs), batch_size):
                xb, yb = xs[i:i + batch_size], ys[i:i + batch_size]
                if not len(xb):
                    continue
                opt.zero_grad()
                out = model(xb)
                if out.shape != yb.shape and out.dim() == yb.dim() + 1 \
                        and out.shape[-1] == 1:
                    out = out[..., 0]
                loss = loss_fn(out, yb)
                loss.backward()
                opt.step()
                epoch_loss += float(loss.detach())
                batches += 1
            avg = hvd.allreduce(
                np.array([epoch_loss / max(batches, 1)], np.float32),
                name="epoch_loss")
            history.append(float(np.asarray(avg)[0]))

        if rank == 0:
            buf = io.BytesIO()
            torch.save(model, buf)
            store.write_bytes(
                store.join(store.get_checkpoint_path(run_id), "model.pt"),
                buf.getvalue())
        return {"rank": rank, "history": history}
    finally:
        hvd.shutdown()


class TorchEstimator:
    """fit(df) -> TorchModel (reference: spark/torch/estimator.py:91-328).

    Parameters mirror the reference's Param surface where meaningful:
    model, optimizer (factory ``params -> torch.optim.Optimizer``), loss
    ("mse" | "l1" | "cross_entropy"), feature_cols, label_cols,
    batch_size, epochs, num_proc, store.
    """

    def __init__(self, model, optimizer: Callable | None = None,
                 loss: str = "mse",
                 feature_cols: Sequence[str] = ("features",),
                 label_cols: Sequence[str] = ("label",),
                 batch_size: int = 32, epochs: int = 1,
                 num_proc: int = 1, store: Store | None = None,
                 run_id: str | None = None) -> None:
        import functools

        import torch

        self.model = model
        # Factory must be picklable (it travels to spawned workers):
        # functools.partial of the optimizer class, not a lambda.
        self.optimizer = optimizer or functools.partial(torch.optim.SGD,
                                                        lr=0.1)
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.store = store or FilesystemStore(".horovod_tpu_store")
        self.run_id = run_id

    def fit(self, df) -> "TorchModel":
        import io

        import torch

        import horovod_tpu as hvd

        run_id = self.run_id or self.store.new_run_id()
        data_path = self.store.get_train_data_path(run_id)
        ckpt_path = self.store.get_checkpoint_path(run_id)

        x, y = _extract(df, self.feature_cols, self.label_cols)
        self.store.save_npz(self.store.join(data_path, "train.npz"),
                            x=x, y=y)

        buf = io.BytesIO()
        torch.save(self.model, buf)

        args = (self.store, run_id, buf.getvalue(), self.optimizer,
                self.loss, self.batch_size, self.epochs)
        try:
            import pyspark  # noqa: F401
            from . import run as spark_run
            results = spark_run(_torch_train_fn, args=args,
                                num_proc=self.num_proc)
        except ImportError:
            results = hvd.run(_torch_train_fn, args=args, np=self.num_proc)

        trained = torch.load(
            io.BytesIO(self.store.read_bytes(
                self.store.join(ckpt_path, "model.pt"))),
            weights_only=False)
        history = results[0]["history"] if results else []
        return TorchModel(trained, feature_cols=self.feature_cols,
                          label_cols=self.label_cols, run_id=run_id,
                          history=history)


class TorchModel:
    """transform(df) appends prediction columns
    (reference: spark/torch/estimator.py TorchModel)."""

    def __init__(self, model, feature_cols: Sequence[str],
                 label_cols: Sequence[str], run_id: str | None = None,
                 history: list | None = None) -> None:
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.run_id = run_id
        self.history = history or []

    def transform(self, df):
        import torch

        pdf = _to_pandas(df).copy()
        x = np.stack([np.asarray(pdf[c].tolist(), dtype=np.float32)
                      for c in self.feature_cols], axis=-1)
        if x.ndim > 2 and x.shape[-1] == 1:
            x = x[..., 0]
        with torch.no_grad():
            pred = self.model(torch.from_numpy(x)).numpy()
        if pred.ndim == 1 or pred.shape[-1] == 1:
            pdf[f"{self.label_cols[0]}__output"] = pred.reshape(-1)
        else:
            for j in range(pred.shape[-1]):
                pdf[f"{self.label_cols[0]}__output_{j}"] = pred[:, j]
        return pdf


def _keras_train_fn(store: Store, run_id: str, model_bytes: bytes,
                    compile_kwargs: dict, batch_size: int,
                    epochs: int) -> dict:
    """Per-rank keras loop (reference: spark/keras/remote.py)."""
    import tempfile

    import horovod_tpu as hvd
    import horovod_tpu.tensorflow as htf

    hvd.init()
    try:
        import tensorflow as tf

        rank, world = hvd.rank(), hvd.size()
        blob = store.load_npz(
            store.join(store.get_train_data_path(run_id), "train.npz"))
        X, Y = blob["x"], blob["y"]
        n = X.shape[0]
        per = (n + world - 1) // world
        xs, ys = X[rank * per:(rank + 1) * per], Y[rank * per:(rank + 1) * per]

        # keras (de)serializes via real files: stage through local tmp,
        # ship bytes through the store.
        with tempfile.TemporaryDirectory() as tmpdir:
            path = os.path.join(tmpdir, "model_in.keras")
            with open(path, "wb") as f:
                f.write(model_bytes)
            model = tf.keras.models.load_model(path)
        opt = htf.DistributedOptimizer(
            tf.keras.optimizers.get(compile_kwargs.get("optimizer", "sgd")))
        model.compile(optimizer=opt,
                      loss=compile_kwargs.get("loss", "mse"))
        hist = model.fit(
            xs, ys, batch_size=batch_size, epochs=epochs, verbose=0,
            shuffle=False,
            callbacks=[htf.BroadcastGlobalVariablesCallback(0)])
        if rank == 0:
            # Weights only: the full model would embed the dynamic
            # Distributed* optimizer class, which cannot deserialize
            # outside a worker.
            with tempfile.TemporaryDirectory() as tmpdir:
                wpath = os.path.join(tmpdir, "model.weights.h5")
                model.save_weights(wpath)
                with open(wpath, "rb") as f:
                    store.write_bytes(
                        store.join(store.get_checkpoint_path(run_id),
                                   "model.weights.h5"), f.read())
        return {"rank": rank, "history": hist.history}
    finally:
        hvd.shutdown()


class KerasEstimator:
    """fit(df) -> KerasModel (reference: spark/keras/estimator.py)."""

    def __init__(self, model, optimizer: Any = "sgd", loss: str = "mse",
                 feature_cols: Sequence[str] = ("features",),
                 label_cols: Sequence[str] = ("label",),
                 batch_size: int = 32, epochs: int = 1,
                 num_proc: int = 1, store: Store | None = None) -> None:
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.store = store or FilesystemStore(".horovod_tpu_store")

    def fit(self, df) -> "KerasModel":
        import horovod_tpu as hvd

        import tempfile

        run_id = self.store.new_run_id()
        data_path = self.store.get_train_data_path(run_id)
        ckpt_path = self.store.get_checkpoint_path(run_id)
        x, y = _extract(df, self.feature_cols, self.label_cols)
        self.store.save_npz(self.store.join(data_path, "train.npz"),
                            x=x, y=y)

        with tempfile.TemporaryDirectory() as tmpdir:
            tmp = os.path.join(tmpdir, "model_in.keras")
            self.model.save(tmp)
            with open(tmp, "rb") as f:
                model_bytes = f.read()

        compile_kwargs = {"optimizer": self.optimizer, "loss": self.loss}
        args = (self.store, run_id, model_bytes, compile_kwargs,
                self.batch_size, self.epochs)
        try:
            import pyspark  # noqa: F401
            from . import run as spark_run
            results = spark_run(_keras_train_fn, args=args,
                                num_proc=self.num_proc)
        except ImportError:
            results = hvd.run(_keras_train_fn, args=args, np=self.num_proc)

        with tempfile.TemporaryDirectory() as tmpdir:
            wpath = os.path.join(tmpdir, "model.weights.h5")
            with open(wpath, "wb") as f:
                f.write(self.store.read_bytes(
                    self.store.join(ckpt_path, "model.weights.h5")))
            self.model.load_weights(wpath)
        trained = self.model
        history = results[0]["history"] if results else {}
        return KerasModel(trained, feature_cols=self.feature_cols,
                          label_cols=self.label_cols, run_id=run_id,
                          history=history)


class KerasModel:
    def __init__(self, model, feature_cols: Sequence[str],
                 label_cols: Sequence[str], run_id: str | None = None,
                 history: dict | None = None) -> None:
        self.model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.run_id = run_id
        self.history = history or {}

    def transform(self, df):
        pdf = _to_pandas(df).copy()
        x = np.stack([np.asarray(pdf[c].tolist(), dtype=np.float32)
                      for c in self.feature_cols], axis=-1)
        if x.ndim > 2 and x.shape[-1] == 1:
            x = x[..., 0]
        pred = self.model.predict(x, verbose=0)
        if pred.ndim == 1 or pred.shape[-1] == 1:
            pdf[f"{self.label_cols[0]}__output"] = pred.reshape(-1)
        else:
            for j in range(pred.shape[-1]):
                pdf[f"{self.label_cols[0]}__output_{j}"] = pred[:, j]
        return pdf
