"""Training artifact store for the Estimator API.

Reference: horovod/spark/common/store.py:36-533 — a `Store` abstracts
where intermediate training data, checkpoints and logs live
(FilesystemStore / HDFSStore / DBFSLocalStore).  Two families here:

- :class:`FilesystemStore` — local/NFS directories (the reference's
  FilesystemStore; also covers DBFS-mounted paths, which are plain
  directories on Databricks hosts);
- :class:`RemoteBlobStore` — the HDFSStore equivalent: artifacts live
  behind a byte-blob client instead of a shared filesystem.  The bundled
  :class:`KVBlobClient` rides this framework's rendezvous HTTP KV server
  (runner/network.py), so estimator workers on hosts WITHOUT a shared
  filesystem still exchange data/checkpoints over the network.

Stores are picklable (they travel to spawned/remote estimator workers)
and mediate all artifact IO through ``read_bytes``/``write_bytes`` so the
estimators never assume a shared filesystem.
"""
from __future__ import annotations

import io
import os
import pickle
import shutil
import uuid
from typing import Any


class Store:
    """Base interface (reference: store.py Store)."""

    # -- logical layout ---------------------------------------------------
    def new_run_id(self) -> str:
        return uuid.uuid4().hex[:12]

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_train_data_path(self, run_id: str) -> str:
        raise NotImplementedError

    # -- byte-level IO (workers use ONLY these + the path getters) --------
    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    # -- convenience ------------------------------------------------------
    def join(self, *parts: str) -> str:
        return "/".join(p.strip("/") if i else p.rstrip("/")
                        for i, p in enumerate(parts))

    def save_object(self, path: str, obj: Any) -> None:
        self.write_bytes(path, pickle.dumps(obj))

    def load_object(self, path: str) -> Any:
        return pickle.loads(self.read_bytes(path))

    def save_npz(self, path: str, **arrays) -> None:
        import numpy as np
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        self.write_bytes(path, buf.getvalue())

    def load_npz(self, path: str):
        import numpy as np
        return np.load(io.BytesIO(self.read_bytes(path)))

    def cleanup_run(self, run_id: str) -> None:
        pass

    @staticmethod
    def create(prefix_path: str) -> "Store":
        """Dispatch on URL scheme (reference: store.py Store.create):
        ``kv://host:port/prefix`` → :class:`RemoteBlobStore` over the
        rendezvous KV server; anything else → :class:`FilesystemStore`.
        ``hdfs://`` is an intentional scope cut (no HDFS client in the
        TPU image; use an NFS/GCS-FUSE mount via FilesystemStore)."""
        if prefix_path.startswith("kv://"):
            rest = prefix_path[len("kv://"):]
            hostport, _, prefix = rest.partition("/")
            host, _, port = hostport.partition(":")
            if not host or not port.isdigit():
                raise ValueError(
                    f"malformed kv store URL {prefix_path!r}: expected "
                    "kv://host:port[/prefix]")
            return RemoteBlobStore(KVBlobClient(host, int(port)),
                                   prefix or "store")
        if prefix_path.startswith("hdfs://"):
            raise ValueError(
                "hdfs:// stores are not supported in the TPU build (no "
                "HDFS client in the image); mount the data (NFS/GCS-FUSE) "
                "and use a filesystem path, or use kv://host:port for the "
                "network blob store")
        return FilesystemStore(prefix_path)


class FilesystemStore(Store):
    """Local/NFS directory store (reference: store.py FilesystemStore)."""

    def __init__(self, prefix_path: str) -> None:
        self.prefix_path = os.path.abspath(prefix_path)
        os.makedirs(self.prefix_path, exist_ok=True)

    def get_run_path(self, run_id: str) -> str:
        path = os.path.join(self.prefix_path, "runs", run_id)
        os.makedirs(path, exist_ok=True)
        return path

    def get_checkpoint_path(self, run_id: str) -> str:
        path = os.path.join(self.get_run_path(run_id), "checkpoints")
        os.makedirs(path, exist_ok=True)
        return path

    def get_train_data_path(self, run_id: str) -> str:
        path = os.path.join(self.get_run_path(run_id), "data")
        os.makedirs(path, exist_ok=True)
        return path

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def cleanup_run(self, run_id: str) -> None:
        shutil.rmtree(os.path.join(self.prefix_path, "runs", run_id),
                      ignore_errors=True)


class KVBlobClient:
    """Byte-blob client over the rendezvous HTTP KV server
    (runner/network.py) — the transport the launcher already runs, so a
    remote store needs no extra infrastructure.  Lazily (re)connects after
    pickling to worker processes."""

    def __init__(self, host: str, port: int) -> None:
        self.host, self.port = host, port
        self._client = None

    def __getstate__(self):
        return {"host": self.host, "port": self.port}

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._client = None

    def _kv(self):
        if self._client is None:
            from ..runner.network import RendezvousClient
            self._client = RendezvousClient(self.host, self.port,
                                            timeout=60.0)
        return self._client

    def put(self, key: str, data: bytes) -> None:
        self._kv().put("blobstore", key, data)

    def get(self, key: str) -> bytes | None:
        return self._kv().get("blobstore", key)


class RemoteBlobStore(Store):
    """Network-backed store (the HDFSStore slot, reference:
    store.py:228-533): artifact "paths" are logical keys resolved through
    a blob client, so estimator workers need no shared filesystem."""

    def __init__(self, client, prefix: str = "store") -> None:
        self.client = client
        self.prefix = prefix.strip("/")

    def get_run_path(self, run_id: str) -> str:
        return f"{self.prefix}/runs/{run_id}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return f"{self.prefix}/runs/{run_id}/checkpoints"

    def get_train_data_path(self, run_id: str) -> str:
        return f"{self.prefix}/runs/{run_id}/data"

    def read_bytes(self, path: str) -> bytes:
        data = self.client.get(path)
        if data is None:
            raise FileNotFoundError(f"remote store has no blob {path!r}")
        return data

    def write_bytes(self, path: str, data: bytes) -> None:
        self.client.put(path, data)

    def exists(self, path: str) -> bool:
        return self.client.get(path) is not None
