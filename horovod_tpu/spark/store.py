"""Training artifact store for the Estimator API.

Reference: horovod/spark/common/store.py:36-533 — a `Store` abstracts
where intermediate training data, checkpoints and logs live
(FilesystemStore/HDFSStore/DBFSLocalStore). Scoped here to the local
filesystem (petastorm/HDFS are out of scope for the TPU build; the data
path is numpy shards, not parquet row groups).
"""
from __future__ import annotations

import os
import pickle
import shutil
import uuid
from typing import Any


class Store:
    """Base interface (reference: store.py Store)."""

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_checkpoint_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_train_data_path(self, run_id: str) -> str:
        raise NotImplementedError

    def save_object(self, path: str, obj: Any) -> None:
        raise NotImplementedError

    def load_object(self, path: str) -> Any:
        raise NotImplementedError

    @staticmethod
    def create(prefix_path: str) -> "FilesystemStore":
        return FilesystemStore(prefix_path)


class FilesystemStore(Store):
    """Local/NFS directory store (reference: store.py FilesystemStore)."""

    def __init__(self, prefix_path: str) -> None:
        self.prefix_path = os.path.abspath(prefix_path)
        os.makedirs(self.prefix_path, exist_ok=True)

    def new_run_id(self) -> str:
        return uuid.uuid4().hex[:12]

    def get_run_path(self, run_id: str) -> str:
        path = os.path.join(self.prefix_path, "runs", run_id)
        os.makedirs(path, exist_ok=True)
        return path

    def get_checkpoint_path(self, run_id: str) -> str:
        path = os.path.join(self.get_run_path(run_id), "checkpoints")
        os.makedirs(path, exist_ok=True)
        return path

    def get_train_data_path(self, run_id: str) -> str:
        path = os.path.join(self.get_run_path(run_id), "data")
        os.makedirs(path, exist_ok=True)
        return path

    def save_object(self, path: str, obj: Any) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            pickle.dump(obj, f)
        os.replace(tmp, path)

    def load_object(self, path: str) -> Any:
        with open(path, "rb") as f:
            return pickle.load(f)

    def cleanup_run(self, run_id: str) -> None:
        shutil.rmtree(os.path.join(self.prefix_path, "runs", run_id),
                      ignore_errors=True)
