"""telemetry/ — per-rank metrics, cross-rank straggler aggregation,
Prometheus/JSON exposition (ISSUE 4; docs/observability.md).

Module surface:

- :func:`metrics` — the process registry.  A real
  :class:`~.registry.MetricsRegistry` under ``HOROVOD_METRICS=on``, the
  shared no-op :data:`~.registry.NULL_REGISTRY` otherwise (zero hot-path
  cost when off).
- :func:`configure` — (re)build the registry from the environment; called
  by ``core.init`` so workers that set knobs before ``hvd.init()`` get
  them honored.
- :class:`~.exporter.MetricsExporter` / :func:`~.exporter.dump_json` —
  Prometheus scrape endpoint on ``HOROVOD_METRICS_PORT + rank`` and the
  shutdown JSON dump to ``HOROVOD_METRICS_FILE``.
- :class:`~.straggler.StragglerAggregator` — coordinator-side windowed
  negotiation-skew statistics naming the slowest rank.
- ``python -m horovod_tpu.telemetry.report`` — offline summarizer for
  dumps and timeline traces.
- :mod:`.flight` — the always-on failure flight recorder
  (``HOROVOD_FLIGHT``): bounded ring of recent trace events dumped on
  every structured failure (ISSUE 7).
- ``python -m horovod_tpu.telemetry.trace`` — cross-rank trace merge
  (flow-linked Perfetto output, clock offsets applied) and
  ``--critical-path`` step attribution.
- :mod:`.perfmodel` / ``python -m horovod_tpu.telemetry.perf`` /
  ``python -m horovod_tpu.telemetry.perfcheck`` — perfscope (ISSUE 19):
  the algorithm-aware roofline cost model, the rank-merged PERF.json
  busbw/MFU ledger, and the regression gate over the bench trajectory.
"""
from __future__ import annotations

from ..common import config
from . import flight
from .exporter import MetricsExporter, dump_json, resolve_dump_path
from .registry import (NULL_METRIC, NULL_REGISTRY, Counter, Gauge,
                       Histogram, MetricsRegistry, NullRegistry)
from .straggler import StragglerAggregator

_registry: MetricsRegistry | NullRegistry | None = None


def enabled_in_env() -> bool:
    return bool(config.METRICS.get())


def configure(rank: int = 0):
    """(Re)build the process registry from the environment.  Called by
    ``core.init``; safe to call again (tests, elastic restarts) — a fresh
    enabled registry starts empty."""
    global _registry
    _registry = MetricsRegistry(rank) if enabled_in_env() \
        else NULL_REGISTRY
    return _registry


def metrics():
    """The process metrics registry (never None; Null when off)."""
    global _registry
    if _registry is None:
        _registry = configure()
    return _registry


def summary() -> dict:
    """Compact end-of-run digest for bench payloads: total wire bytes,
    response-cache hit rate, and per-stream busy time — the counters the
    perf trajectory wants next to each latency number."""
    reg = metrics()
    if not reg.enabled:
        return {}
    sent = recv = 0.0
    hits = misses = 0.0
    streams: dict[str, float] = {}
    collective_bytes = 0.0
    shm_staged = 0.0
    for entry in reg.snapshot()["metrics"]:
        name = entry["name"]
        if entry["type"] not in ("counter", "gauge"):
            continue
        value = entry["value"]
        if name == "horovod_tcp_bytes_sent_total":
            sent += value
        elif name == "horovod_tcp_bytes_received_total":
            recv += value
        elif name == "horovod_controller_cache_hit_total":
            hits += value
        elif name == "horovod_controller_cache_miss_total":
            misses += value
        elif name == "horovod_collective_bytes_total":
            collective_bytes += value
        elif name == "horovod_shm_staged_bytes_total":
            shm_staged += value
        elif name == "horovod_stream_busy_ms_total":
            streams[entry["labels"].get("stream", "0")] = value
    out: dict = {
        "wire_bytes_sent": sent,
        "wire_bytes_received": recv,
        "shm_staged_bytes": shm_staged,
        "collective_bytes": collective_bytes,
        "cache_hit_rate": hits / (hits + misses) if hits + misses else 0.0,
    }
    if streams:
        total = sum(streams.values())
        out["stream_busy_ms"] = streams
        out["stream_utilization"] = {
            s: (v / total if total else 0.0)
            for s, v in sorted(streams.items())}
    # perfscope stamp (ISSUE 19): the single-rank busbw/MFU ledger, so
    # every bench payload carries the numbers perfcheck gates against.
    from . import perfmodel
    ledger = perfmodel.build_ledger([reg.snapshot()])
    if ledger.get("busbw") or ledger.get("step"):
        out["perf"] = ledger
    return out
