"""perfscope — the algorithm-aware roofline cost model (ISSUE 19).

Pure functions only: everything here is deterministic arithmetic over
the collective-algorithm vocabulary (common/topology.ALGO_NAMES), the
snapshot schema (telemetry/registry.py) and the model configs in
models/.  Three layers share it:

- **core dispatch** (core._observe_collective) folds each executed
  response's measured latency into a bus-bandwidth observation —
  ``busbw = algbw x op_factor(N)``, the nccl-tests convention, so the
  number is comparable across world sizes and algorithms;
- **the perf CLI** (``python -m horovod_tpu.telemetry.perf``) merges
  rank dumps into the PERF.json ledger: per (plane, op, codec, algo,
  size-bucket) busbw with roofline-relative efficiency, where the
  roofline is the peak link bandwidth (HOROVOD_PERF_PEAK_MBPS, or
  self-calibrated to the best cell in the window) discounted by each
  algorithm's wire-byte overhead versus the bandwidth-optimal ring;
- **MFU accounting**: analytic FLOPs for TransformerLM (train and
  paged/dense decode) and the conv models, against the per-chip peak
  (arXiv:1909.09756 attributes MLPerf scaling exactly this way).

Reference formulas (S = payload bytes, N = ranks):

=============  =========================  ====================
algo           critical-path wire bytes   hops
=============  =========================  ====================
ring           2(N-1)/N * S               2(N-1)
tree           2*ceil(log2 N) * S         2*ceil(log2 N)
rhd            2(N-1)/N * S               2*ceil(log2 N)
torus (RxC)    2(N-1)/N * S               2(C-1) + 2(R-1)
hierarchical   sum_i 2(l_i-1)/l_i * S_i   sum_i 2(l_i-1)
=============  =========================  ====================

(two-phase torus: per-row ring reduce-scatter (C-1)/C * S + per-column
allreduce of the row shard 2(R-1)/(RC) * S + row allgather — the total
telescopes to exactly 2(N-1)/N * S, i.e. torus is bandwidth-optimal;
its win is the hop count, every hop a grid-neighbor link.  N-level
hierarchical: level i moves 2(l_i-1)/l_i of the shard S_i =
S / prod(levels[:i]) surviving the inner levels.)
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

# ---------------------------------------------------------------------------
# Size buckets — the ledger's third axis.  Power-of-16 boundaries from
# 4 KiB keep the label set small while separating the latency-bound,
# crossover and bandwidth-bound regimes the algo selector distinguishes
# (backend/tcp._select_algo; bench_eager's ladder sizes 4KiB/64KiB/1MiB
# land in three distinct buckets).
# ---------------------------------------------------------------------------
_BUCKET_BOUNDS = ((4 << 10, "4KiB"), (64 << 10, "64KiB"),
                  (1 << 20, "1MiB"), (16 << 20, "16MiB"),
                  (256 << 20, "256MiB"))
SIZE_BUCKETS = tuple(label for _, label in _BUCKET_BOUNDS) + ("huge",)


def size_bucket(nbytes: float) -> str:
    """Ledger bucket label of a payload size (upper-bound buckets)."""
    for bound, label in _BUCKET_BOUNDS:
        if nbytes <= bound:
            return label
    return "huge"


# ---------------------------------------------------------------------------
# Peak dense bf16 FLOP/s per chip, by substring of device_kind.
# Public numbers from cloud.google.com/tpu/docs (v2-v6e system
# architecture pages).  Order matters: first match wins.  (Moved here
# from bench.py so the Trainer, the serving replica and the bench all
# read one table.)
# ---------------------------------------------------------------------------
PEAK_FLOPS_TABLE = (
    ("v6", 918e12),       # Trillium / v6e
    ("v5p", 459e12),
    ("v5 lite", 197e12),  # v5e reports device_kind "TPU v5 lite"
    ("v5e", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# Unknown device kinds (CPU runs, emulators) get a nominal 1 TFLOP/s so
# the MFU *trajectory* is still populated and comparable run-over-run;
# only runs on a recognized TPU kind report an absolute utilization.
NOMINAL_PEAK_FLOPS = 1e12


def peak_flops(device_kind: str) -> float:
    """Peak dense FLOP/s for a device kind; NOMINAL_PEAK_FLOPS when the
    kind is unknown (override via HOROVOD_PERF_PEAK_FLOPS)."""
    from ..common import config
    knob = float(config.PERF_PEAK_FLOPS.get())
    if knob > 0.0:
        return knob
    kind = (device_kind or "").lower()
    for key, peak in PEAK_FLOPS_TABLE:
        if key in kind:
            return peak
    return NOMINAL_PEAK_FLOPS


# ---------------------------------------------------------------------------
# Wire cost per algorithm
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class WireCost:
    """Critical-path cost of one collective under one algorithm."""
    wire_bytes: float     # bytes crossing any single rank's links
    hops: int             # serialized link traversals (latency terms)


def _hierarchical_cost(nbytes: float, levels: list[int]) -> WireCost:
    wire = 0.0
    hops = 0
    shard = float(nbytes)
    for size in levels:
        if size <= 1:
            continue
        wire += 2.0 * (size - 1) / size * shard
        hops += 2 * (size - 1)
        shard /= size
    return WireCost(wire, hops)


def wire_cost(algo: str, nbytes: float, topology: Any) -> WireCost:
    """Expected critical-path (wire bytes, hops) of one allreduce of
    ``nbytes`` under ``algo`` on ``topology`` (common/topology.Topology
    or anything with .size/.rows/.cols/.levels())."""
    n = max(int(getattr(topology, "size", 1)), 1)
    if n <= 1:
        return WireCost(0.0, 0)
    log2n = int(math.ceil(math.log2(n)))
    ring_bytes = 2.0 * (n - 1) / n * nbytes
    if algo == "tree":
        return WireCost(2.0 * log2n * nbytes, 2 * log2n)
    if algo == "rhd":
        return WireCost(ring_bytes, 2 * log2n)
    if algo == "torus" and getattr(topology, "kind", "") == "torus":
        rows = max(int(getattr(topology, "rows", 1)), 1)
        cols = max(int(getattr(topology, "cols", 1)), 1)
        return WireCost(ring_bytes, 2 * (cols - 1) + 2 * (rows - 1))
    if algo in ("hier", "hierarchical"):
        levels = topology.levels() if hasattr(topology, "levels") else [n]
        return _hierarchical_cost(nbytes, levels)
    # ring, auto, torus-on-flat, and unknown labels: the bandwidth-
    # optimal ring schedule is the reference cost.
    return WireCost(ring_bytes, 2 * (n - 1))


def algo_overhead(algo: str, topology: Any) -> float:
    """Wire-byte overhead of ``algo`` versus the bandwidth-optimal ring:
    >= 1.0; the roofline divisor (tree at 4 MiB can at best reach
    peak / overhead)."""
    ring = wire_cost("ring", 1.0, topology).wire_bytes
    mine = wire_cost(algo, 1.0, topology).wire_bytes
    if ring <= 0.0 or mine <= 0.0:
        return 1.0
    return max(mine / ring, 1.0)


# ---------------------------------------------------------------------------
# Bus bandwidth (nccl-tests convention)
# ---------------------------------------------------------------------------
def busbw_factor(op: str, n: int) -> float:
    """busbw = algbw x factor: the hardware-normalized multiplier that
    makes measured bandwidth comparable across ops and world sizes
    (nccl-tests PERFORMANCE.md convention)."""
    if n <= 1:
        return 1.0
    if op in ("allreduce", "adasum"):
        return 2.0 * (n - 1) / n
    if op in ("allgather", "reducescatter", "alltoall"):
        return float(n - 1) / n
    return 1.0     # broadcast / barrier-ish ops move S end to end


def busbw_mbps(op: str, nbytes: float, latency_ms: float, n: int) -> float:
    """Measured bus bandwidth in MB/s of one executed collective."""
    if latency_ms <= 0.0 or nbytes <= 0.0:
        return 0.0
    algbw = nbytes / (latency_ms / 1e3)          # bytes/s
    return algbw * busbw_factor(op, n) / 1e6


def expected_ms(algo: str, nbytes: float, topology: Any,
                peak_mbps: float, hop_us: float = 25.0) -> float:
    """Roofline time of one allreduce: critical-path wire bytes at peak
    link bandwidth plus the serialized hop latency."""
    if peak_mbps <= 0.0:
        return 0.0
    cost = wire_cost(algo, nbytes, topology)
    return cost.wire_bytes / (peak_mbps * 1e6) * 1e3 \
        + cost.hops * hop_us / 1e3


# ---------------------------------------------------------------------------
# Analytic FLOPs — TransformerLM
# ---------------------------------------------------------------------------
def param_count(params: Any) -> int:
    """Total parameter count of a (possibly nested) param tree."""
    import jax
    return sum(int(getattr(leaf, "size", 0))
               for leaf in jax.tree_util.tree_leaves(params))


def transformer_param_count(cfg: Any) -> int:
    """Analytic parameter count of a TransformerLM config (embed +
    per-block attention/MLP/norms + final norm; the LM head shares the
    embedding)."""
    d, L = cfg.d_model, cfg.num_layers
    attn = 4 * d * d
    if getattr(cfg, "moe_experts", 0) > 0:
        mlp = cfg.moe_experts * 3 * d * cfg.ff_dim + d * cfg.moe_experts
    else:
        mlp = 3 * d * cfg.ff_dim       # SwiGLU: gate, up, down
    return cfg.vocab_size * d + L * (attn + mlp + 2 * d) + d


def transformer_train_flops(cfg: Any, batch: int, seq: int,
                            n_params: int | None = None) -> float:
    """FLOPs of ONE train step (fwd+bwd): 6*P per token of matmul work
    plus the attention term 12*L*d*S (halved causal), the PaLM-appendix
    accounting MFU reports are defined against."""
    p = n_params if n_params else transformer_param_count(cfg)
    tokens = batch * seq
    attn = 12.0 * cfg.num_layers * cfg.d_model * seq \
        * (0.5 if getattr(cfg, "causal", True) else 1.0)
    return tokens * (6.0 * p + attn)


def transformer_decode_flops(cfg: Any, context_len: float,
                             n_params: int | None = None) -> float:
    """FLOPs of ONE generated token at KV context ``context_len``
    (forward only: 2*P matmul + 4*L*d*ctx attention reads — identical
    for the dense and paged KV layouts, which move the same bytes)."""
    p = n_params if n_params else transformer_param_count(cfg)
    return 2.0 * p + 4.0 * cfg.num_layers * cfg.d_model * context_len


# ---------------------------------------------------------------------------
# Analytic FLOPs — the conv models in models/
# ---------------------------------------------------------------------------
def _conv_flops(c_in: int, c_out: int, k: int, hw: float) -> float:
    return 2.0 * k * k * c_in * c_out * hw * hw


def vgg_forward_flops(stages, image_size: int = 224,
                      num_classes: int = 1000) -> float:
    """Walk VGG.stages: 3x3 SAME convs, 2x2 pool after each stage, then
    the two 4096 Dense layers and the head."""
    hw = float(image_size)
    c_in, total = 3, 0.0
    for n_convs, filters in stages:
        for _ in range(n_convs):
            total += _conv_flops(c_in, filters, 3, hw)
            c_in = filters
        hw /= 2.0
    flat = c_in * hw * hw
    total += 2.0 * (flat * 4096 + 4096 * 4096 + 4096 * num_classes)
    return total


def resnet_forward_flops(stage_sizes, bottleneck: bool = True,
                         num_filters: int = 64, image_size: int = 224,
                         num_classes: int = 1000) -> float:
    """Walk the ResNet stage plan (models/resnet.py): 7x7/2 stem, /2
    pool, stages with stride-2 first blocks, global pool, Dense head."""
    hw = image_size / 2.0
    total = _conv_flops(3, num_filters, 7, hw)
    hw /= 2.0                                   # max_pool /2
    c_in = num_filters
    for i, block_count in enumerate(stage_sizes):
        f = num_filters * 2 ** i
        c_out = 4 * f if bottleneck else f
        for j in range(block_count):
            if j == 0 and i > 0:
                hw /= 2.0                       # stride-2 first block
            if bottleneck:
                total += _conv_flops(c_in, f, 1, hw) \
                    + _conv_flops(f, f, 3, hw) \
                    + _conv_flops(f, c_out, 1, hw)
            else:
                total += _conv_flops(c_in, f, 3, hw) \
                    + _conv_flops(f, f, 3, hw)
            if j == 0 and c_in != c_out:
                total += _conv_flops(c_in, c_out, 1, hw)  # projection
            c_in = c_out
    return total + 2.0 * c_in * num_classes


# InceptionV3 at 299x299 is ~5.7e9 multiply-adds (the published figure
# for the V3 layer plan models/inception.py implements); conv work
# scales with spatial area.
_INCEPTION3_FWD_FLOPS_299 = 2.0 * 5.7e9


def inception3_forward_flops(image_size: int = 299) -> float:
    return _INCEPTION3_FWD_FLOPS_299 * (image_size / 299.0) ** 2


def model_step_flops(model: Any, batch: int, *, seq: int = 0,
                     image_size: int = 224, train: bool = True,
                     n_params: int | None = None) -> float:
    """Analytic FLOPs of one step for any model this tree ships,
    dispatched on the model's own config attributes (train = 3x forward:
    the standard fwd+bwd accounting)."""
    cfg = getattr(model, "cfg", None)
    if cfg is not None and hasattr(cfg, "num_layers"):   # TransformerLM
        if train:
            return transformer_train_flops(cfg, batch, max(seq, 1),
                                           n_params)
        return batch * transformer_decode_flops(cfg, max(seq, 1),
                                                n_params)
    if hasattr(model, "stages"):                          # VGG
        fwd = batch * vgg_forward_flops(model.stages, image_size)
    elif hasattr(model, "stage_sizes"):                   # ResNet
        bottleneck = "Bottleneck" in getattr(
            getattr(model, "block_cls", None), "__name__", "Bottleneck")
        fwd = batch * resnet_forward_flops(
            model.stage_sizes, bottleneck,
            getattr(model, "num_filters", 64), image_size)
    else:                                                 # InceptionV3
        fwd = batch * inception3_forward_flops(image_size)
    return 3.0 * fwd if train else fwd


def mfu(flops_per_step: float, step_seconds: float,
        peak: float) -> float:
    """Model FLOPs utilization: achieved / peak."""
    if step_seconds <= 0.0 or peak <= 0.0:
        return 0.0
    return flops_per_step / step_seconds / peak


# ---------------------------------------------------------------------------
# Ledger construction — merge rank snapshots into the PERF.json tables
# ---------------------------------------------------------------------------
BUSBW_METRIC = "horovod_collective_busbw_mbps"

_LEDGER_LABELS = ("plane", "op", "codec", "algo", "size_bucket")


def _merged_quantile(buckets: list[list[float]], q: float) -> float:
    """Geometric-interpolated quantile over merged [bound, count] bucket
    lists (the snapshot schema; same math as Histogram.quantile without
    the min/max clamp, which does not survive a merge)."""
    count = sum(n for _, n in buckets)
    if count == 0:
        return 0.0
    target = q * count
    cum = 0.0
    for bound, n in sorted(buckets):
        prev, cum = cum, cum + n
        if cum >= target:
            frac = (target - prev) / n
            lo = bound / 2.0
            return lo * (bound / lo) ** frac
    return sorted(buckets)[-1][0]


def _fold_histograms(snapshots: list[dict], name: str) -> dict[tuple, dict]:
    """label-tuple -> merged {count, sum, buckets} across rank dumps."""
    cells: dict[tuple, dict] = {}
    for snap in snapshots:
        for entry in snap.get("metrics", ()):
            if entry.get("name") != name \
                    or entry.get("type") != "histogram":
                continue
            labels = entry.get("labels", {})
            key = tuple(labels.get(k, "") for k in _LEDGER_LABELS)
            cell = cells.setdefault(
                key, {"count": 0, "sum": 0.0, "buckets": {}})
            cell["count"] += int(entry.get("count", 0))
            cell["sum"] += float(entry.get("sum", 0.0))
            for bound, n in entry.get("buckets", ()):
                cell["buckets"][bound] = cell["buckets"].get(bound, 0) + n
    return cells


def _gauge_value(snapshots: list[dict], name: str) -> float | None:
    """Max of a gauge across rank dumps (None when absent everywhere)."""
    values = [float(e.get("value", 0.0))
              for snap in snapshots for e in snap.get("metrics", ())
              if e.get("name") == name and e.get("type") == "gauge"]
    return max(values) if values else None


def build_ledger(snapshots: list[dict], topology: Any = None, *,
                 peak_mbps: float = 0.0, min_samples: int = 1) -> dict:
    """Merge rank metric snapshots into the perf ledger.

    ``peak_mbps`` <= 0 self-calibrates: the best measured cell IS the
    roofline, so every efficiency lands in (0, 1] and the table answers
    "how far below the best this fabric demonstrated is each cell"
    without needing the link spec.  An explicit peak answers the
    absolute question instead."""
    if topology is None:
        from ..common.topology import Topology
        topology = Topology(size=max(len(snapshots), 1))
    cells = _fold_histograms(snapshots, BUSBW_METRIC)
    rows = []
    for key in sorted(cells):
        cell = cells[key]
        if cell["count"] < max(min_samples, 1):
            continue
        labels = dict(zip(_LEDGER_LABELS, key))
        buckets = [[b, n] for b, n in cell["buckets"].items()]
        rows.append({
            **labels,
            "samples": cell["count"],
            "busbw_mbps": cell["sum"] / cell["count"],
            "p50_mbps": _merged_quantile(buckets, 0.5),
            "algo_overhead": algo_overhead(labels["algo"], topology),
        })
    calibrated = peak_mbps
    if calibrated <= 0.0:
        calibrated = max((r["busbw_mbps"] for r in rows), default=0.0)
    for r in rows:
        roofline = calibrated / r["algo_overhead"]
        r["roofline_mbps"] = roofline
        # Fabric efficiency: against the peak itself — the number the
        # smoke battery bounds to (0, 1.05] and perfcheck trends.
        r["efficiency"] = r["busbw_mbps"] / calibrated \
            if calibrated > 0.0 else 0.0
        # Schedule efficiency: against what THIS algo can at best do;
        # > 1 here means the analytic overhead model is pessimistic for
        # this fabric (informational, never gated).
        r["algo_efficiency"] = r["busbw_mbps"] / roofline \
            if roofline > 0.0 else 0.0
    ledger: dict = {
        "schema": 1,
        "world": {"ranks": int(getattr(topology, "size", len(snapshots))
                               or len(snapshots)),
                  "dumps": len(snapshots),
                  "topology": topology.describe()
                  if hasattr(topology, "describe") else "flat"},
        "peak_mbps": calibrated,
        "peak_source": "knob" if peak_mbps > 0.0 else "self-calibrated",
        "busbw": rows,
    }
    step = {}
    for gauge, field in (("horovod_train_mfu", "train_mfu"),
                         ("horovod_train_step_flops", "train_step_flops"),
                         ("horovod_serve_tokens_per_sec",
                          "serve_tokens_per_sec"),
                         ("horovod_serve_flops_per_token",
                          "serve_flops_per_token"),
                         ("horovod_serve_mfu", "serve_mfu")):
        value = _gauge_value(snapshots, gauge)
        if value is not None:
            step[field] = value
    if step:
        ledger["step"] = step
    return ledger


def ledger_summary(ledger: dict, top: int = 6) -> list[str]:
    """Compact human lines for console/report rendering."""
    rows = ledger.get("busbw", [])
    if not rows:
        return ["no busbw samples (HOROVOD_METRICS off, or no "
                "collectives executed)"]
    out = [f"peak {ledger.get('peak_mbps', 0.0):.1f} MB/s "
           f"({ledger.get('peak_source', '?')}), "
           f"{len(rows)} cells, "
           f"world {ledger.get('world', {}).get('ranks', '?')}"]
    ranked = sorted(rows, key=lambda r: -r["samples"])[:top]
    for r in ranked:
        out.append(f"  {r['plane']}/{r['op']}/{r['algo']}"
                   f"@{r['size_bucket']}: "
                   f"{r['busbw_mbps']:.1f} MB/s "
                   f"eff={r['efficiency']:.2f} "
                   f"(n={r['samples']})")
    step = ledger.get("step", {})
    if step:
        out.append("  step: " + " ".join(
            f"{k}={v:.4g}" for k, v in sorted(step.items())))
    return out
