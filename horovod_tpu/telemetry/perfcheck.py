"""perfscope regression gate: current ledger vs a committed baseline.

CLI::

    python -m horovod_tpu.telemetry.perfcheck PERF.json \
        --baseline BASELINE.json [BENCH_r01.json ...] \
        [--tolerance-pct 10]

Compares the current perf ledger (``telemetry.perf`` output, or any
bench payload carrying a stamped ``perf`` ledger) against a baseline
window and exits 1 with a STRUCTURED finding — metric, delta, and the
first offending (plane, algo, size-bucket) — when bus bandwidth or MFU
dropped past the tolerance.  The comparison folds each algorithm into
its (plane, op, size-bucket) cell first, so a run that *switched* to a
slower algorithm (a forced ``HOROVOD_ALGO=tree`` at 4 MiB, a
chaos-delayed rank) is caught even though the per-algo cells have no
baseline counterpart; the finding names the dominant current algorithm
of the regressed cell.

Baselines are read permissively: a PERF.json ledger, a bench payload
with a stamped ledger, a list of either, or the repo's BENCH_r*.json /
BASELINE.json trajectory wrappers.  A baseline with no comparable perf
cells passes with a note (the gate cannot regress against nothing) —
the trajectory starts gating from the first ledger-stamped round.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from ..common import config

# Cells whose baseline busbw sits below this floor are noise (a probe
# that barely ran), not a reference worth gating against.
_MIN_GATE_MBPS = 1e-6


def _extract_ledgers(payload) -> list[dict]:
    """Every perf ledger reachable inside an arbitrary JSON payload:
    the ledger itself, a bench payload's ``perf`` stamp, the repo's
    {"n", "cmd", "rc", "tail"} round wrappers (no ledger inside — the
    tail truncates), or lists of any of these."""
    if isinstance(payload, list):
        return [led for item in payload for led in _extract_ledgers(item)]
    if not isinstance(payload, dict):
        return []
    if "busbw" in payload or "step" in payload:
        return [payload]
    if isinstance(payload.get("perf"), dict):
        return _extract_ledgers(payload["perf"])
    return []


def _fold_cells(ledger: dict) -> dict[tuple, dict]:
    """(plane, op, size_bucket) -> {busbw (sample-weighted), samples,
    dominant algo} — the algo-independent trend perfcheck trends."""
    cells: dict[tuple, dict] = {}
    for row in ledger.get("busbw", ()):
        key = (row.get("plane", ""), row.get("op", ""),
               row.get("size_bucket", ""))
        cell = cells.setdefault(
            key, {"weighted": 0.0, "samples": 0, "algos": {}})
        n = int(row.get("samples", 0))
        cell["weighted"] += float(row.get("busbw_mbps", 0.0)) * n
        cell["samples"] += n
        cell["algos"][row.get("algo", "")] = \
            cell["algos"].get(row.get("algo", ""), 0) + n
    out = {}
    for key, cell in cells.items():
        if not cell["samples"]:
            continue
        out[key] = {
            "busbw_mbps": cell["weighted"] / cell["samples"],
            "samples": cell["samples"],
            "algo": max(cell["algos"], key=lambda a: cell["algos"][a]),
        }
    return out


def compare(current: dict, baselines: list[dict],
            tolerance_pct: float) -> list[dict]:
    """Structured findings: every (plane, op, size-bucket) busbw cell
    and step-ledger metric that dropped past the tolerance versus the
    best baseline value (the window's high-water mark, so a lucky round
    does not ratchet the gate DOWN on the next merge)."""
    findings: list[dict] = []
    cur_cells = _fold_cells(current)
    base_cells: dict[tuple, dict] = {}
    for led in baselines:
        for key, cell in _fold_cells(led).items():
            best = base_cells.get(key)
            if best is None or cell["busbw_mbps"] > best["busbw_mbps"]:
                base_cells[key] = cell
    for key in sorted(base_cells):
        base = base_cells[key]
        cur = cur_cells.get(key)
        if cur is None or base["busbw_mbps"] <= _MIN_GATE_MBPS:
            continue
        delta_pct = (cur["busbw_mbps"] - base["busbw_mbps"]) \
            / base["busbw_mbps"] * 100.0
        if delta_pct < -tolerance_pct:
            plane, op, bucket = key
            findings.append({
                "metric": "busbw_mbps",
                "plane": plane, "op": op, "size_bucket": bucket,
                "algo": cur["algo"],
                "baseline_algo": base["algo"],
                "baseline": base["busbw_mbps"],
                "current": cur["busbw_mbps"],
                "delta_pct": delta_pct,
                "tolerance_pct": tolerance_pct,
            })
    base_step: dict[str, float] = {}
    for led in baselines:
        for k, v in led.get("step", {}).items():
            base_step[k] = max(base_step.get(k, v), v)
    for k in sorted(base_step):
        cur_v = current.get("step", {}).get(k)
        if cur_v is None or base_step[k] <= 0.0:
            continue
        delta_pct = (cur_v - base_step[k]) / base_step[k] * 100.0
        if delta_pct < -tolerance_pct:
            findings.append({
                "metric": k,
                "baseline": base_step[k], "current": cur_v,
                "delta_pct": delta_pct,
                "tolerance_pct": tolerance_pct,
            })
    return findings


def _load_json(path: str):
    return json.loads(Path(path).read_text())


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.telemetry.perfcheck",
        description="Gate the current perf ledger against a committed "
                    "baseline window; exit 1 with a structured finding "
                    "on regression (docs/observability.md).")
    parser.add_argument("current",
                        help="current PERF.json (telemetry.perf output "
                             "or a ledger-stamped bench payload)")
    parser.add_argument("--baseline", nargs="+", required=True,
                        help="baseline files: PERF.json ledgers, "
                             "ledger-stamped BENCH_r*.json payloads, "
                             "and/or BASELINE.json")
    parser.add_argument("--tolerance-pct", type=float, default=0.0,
                        help="allowed drop before failing (default: "
                             "HOROVOD_PERF_TOLERANCE_PCT)")
    args = parser.parse_args(argv)
    tolerance = args.tolerance_pct \
        or float(config.PERF_TOLERANCE_PCT.get())

    try:
        current_ledgers = _extract_ledgers(_load_json(args.current))
    except (OSError, ValueError) as exc:
        sys.stderr.write(f"perfcheck: cannot read {args.current}: "
                         f"{exc}\n")
        return 2
    if not current_ledgers:
        sys.stderr.write(f"perfcheck: {args.current} carries no perf "
                         "ledger\n")
        return 2
    baselines: list[dict] = []
    unreadable: list[str] = []
    for path in args.baseline:
        try:
            baselines.extend(_extract_ledgers(_load_json(path)))
        except (OSError, ValueError):
            unreadable.append(path)
    report: dict = {"tolerance_pct": tolerance,
                    "baseline_ledgers": len(baselines)}
    if unreadable:
        report["unreadable"] = unreadable
    if not baselines:
        report["findings"] = []
        report["note"] = ("no comparable perf cells in the baseline "
                          "window — gating starts at the first "
                          "ledger-stamped round")
        sys.stdout.write(json.dumps(report, indent=1, sort_keys=True)
                         + "\n")
        return 0
    findings = compare(current_ledgers[0], baselines, tolerance)
    report["findings"] = findings
    sys.stdout.write(json.dumps(report, indent=1, sort_keys=True) + "\n")
    if findings:
        worst = min(findings, key=lambda f: f["delta_pct"])
        cell = "/".join(str(worst.get(k)) for k in
                        ("plane", "algo", "size_bucket")
                        if worst.get(k) is not None)
        sys.stderr.write(
            f"perfcheck: REGRESSION {worst['metric']}"
            f"{' at ' + cell if cell else ''}: "
            f"{worst['baseline']:.4g} -> {worst['current']:.4g} "
            f"({worst['delta_pct']:+.1f}% vs -{tolerance:g}% "
            f"tolerance); {len(findings)} finding(s)\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
