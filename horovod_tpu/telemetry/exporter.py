"""Metrics exposition: Prometheus scrape endpoint + shutdown JSON dump.

Both run OFF the hot path by construction: the HTTP server serves scrapes
from its own daemon thread pool (renders a snapshot under the registry's
metric locks only long enough to read each value), and the JSON dump
happens once, at shutdown, after the background loop has exited.

Port layout: each rank tries ``HOROVOD_METRICS_PORT + rank`` (launchers
ship one identical environment to every rank on a host); if that port is
taken it falls back to an ephemeral port and logs the actual one.  The
bound port is always available as ``MetricsExporter.port``.

Bind address: ``HOROVOD_METRICS_BIND``, default ``127.0.0.1`` — metrics
name tensors, hosts, and failure details, so serving them off-host must
be an explicit decision (the pre-fix ``("", port)`` bind silently
exposed every rank's registry on all interfaces).  Set it to ``0.0.0.0``
(or empty) for a real Prometheus scrape deployment.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..common import config
from ..common.logging import logger


class _MetricsHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # no stderr chatter per scrape
        pass

    def do_GET(self):
        if self.path not in ("/", "/metrics"):
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        body = self.server.registry.render_prometheus().encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class MetricsExporter:
    """Prometheus text-format endpoint for one rank's registry."""

    def __init__(self, registry, rank: int, base_port: int,
                 bind: str | None = None) -> None:
        self.registry = registry
        self.rank = rank
        if bind is None:
            bind = config.METRICS_BIND.get()
        self.bind = bind
        want = base_port + rank
        try:
            self._httpd = ThreadingHTTPServer((bind, want),
                                              _MetricsHandler)
        except OSError:
            # Port taken (another world on this host, or a low base):
            # fall back to an ephemeral port rather than failing init.
            self._httpd = ThreadingHTTPServer((bind, 0), _MetricsHandler)
            logger.info("telemetry: port %d busy; metrics for rank %d on "
                        "port %d instead", want, rank,
                        self._httpd.server_address[1])
        self._httpd.registry = registry
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="hvd-metrics")
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def close(self) -> None:
        """shutdown() wakes the serve loop, server_close() releases the
        listening socket, and the join reaps the serve thread — without
        it one hvd-metrics thread (and its poll loop) leaked per
        elastic world cycle (hvdlife HVD704: the exporter is rebuilt by
        every core.init when the port knob is set)."""
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def resolve_dump_path(path: str, rank: int) -> str:
    """Per-rank dump path: ``{rank}`` substitutes; otherwise the rank is
    suffixed before the extension (``m.json`` -> ``m.r3.json``) so a
    launcher-wide identical HOROVOD_METRICS_FILE never self-clobbers."""
    if "{rank}" in path:
        return path.format(rank=rank)
    root, dot, ext = path.rpartition(".")
    if dot:
        return f"{root}.r{rank}.{ext}"
    return f"{path}.r{rank}"


def dump_json(registry, path: str, rank: int) -> str:
    """Write the registry snapshot as JSON; returns the resolved path."""
    resolved = resolve_dump_path(path, rank)
    snap = registry.snapshot()
    with open(resolved, "w") as f:  # hvdlint: disable=HVD1002 -- shutdown-path exporter write: runs once after the background loop exited, never during dispatch
        json.dump(snap, f, indent=1)
    return resolved
