"""Cross-rank trace stitching and critical-path step attribution.

CLI::

    python -m horovod_tpu.telemetry.trace r0.json r1.json ... \
        [-o merged.json] [--critical-path] [--window N]

Input: the per-rank Chrome-trace files the Timeline writes when
``HOROVOD_TIMELINE`` is set (rank 0 keeps the configured path, rank *r*
writes ``path.r<r>.json`` — ``common/timeline.rank_path``).  Each file
carries a ``horovod_clock_sync`` metadata event with the rank, the
recording window's monotonic base, and the rank's clock offset against
the coordinator (round-trip probes at init,
``tcp_transport.estimate_clock_offset``).

Merge output (``-o``): one Chrome/Perfetto trace with

- ``pid`` = rank (plus ``process_name`` / ``process_sort_index``
  metadata), per-rank clock offsets **applied** so spans line up on the
  coordinator's clock;
- flow events (``"ph":"s"`` / ``"f"``) linking each collective's op
  spans across ranks by the coordinator-assigned trace id
  (``Response.trace_cycle`` / ``trace_seq`` riding span ``args.trace``)
  — click one allreduce, see it on every rank.

``--critical-path``: attributes each collective's wall time to phases —
queue wait (enqueue→dispatch), negotiate, wire legs (``TCP_``/``SHM_``/
``XLA_``/hierarchical sub-spans), codec/staging (``MEMCPY_*``),
framework dispatch, and callback — and names the bottleneck rank and
its dominant phase per window of collectives: the rank whose op span
*starts last* on the aligned clock is the one the rest of the world
waited for (the same last-arrival semantics as the coordinator's
straggler gauges, ``telemetry/straggler.py`` — cross-check
``horovod_controller_straggler_rank`` against this report; the two
measure the same skew from opposite ends of the wire).
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from dataclasses import dataclass, field
from pathlib import Path

# Response-type op spans (core._execute_response) — the per-collective
# anchor spans that get flow-linked across ranks.
OP_SPAN_NAMES = frozenset({
    "ALLREDUCE", "ALLGATHER", "BROADCAST", "ALLTOALL", "REDUCESCATTER",
    "ADASUM", "BARRIER", "JOIN", "ERROR",
})
# Backend sub-activity prefixes = bytes actually moving on a data plane.
WIRE_PREFIXES = ("TCP_", "SHM_", "XLA_", "LOCAL_", "CROSS_", "BASIC_")
# Staging/codec copies.
CODEC_PREFIXES = ("MEMCPY_",)

PHASES = ("queue_wait", "negotiate", "wire", "codec", "framework",
          "callback")

_RANK_SUFFIX_RE = re.compile(r"\.r(\d+)(?:\.[^.]+)?$")


@dataclass
class RankTrace:
    """One rank's loaded timeline plus its stitching metadata."""
    path: str
    rank: int
    events: list
    start_us: float = 0.0        # recording window's monotonic base
    clock_offset_us: float = 0.0  # coordinator clock - local clock
    clock_rtt_us: float = 0.0
    shift_us: float = 0.0        # merge-time additive ts shift


@dataclass
class _OpRecord:
    """Per-(trace id, rank) phase decomposition, µs (aligned clock)."""
    rank: int
    op_start: float = 0.0
    op_end: float = 0.0
    queue_start: float | None = None
    queue_end: float | None = None
    phases: dict = field(default_factory=lambda: dict.fromkeys(PHASES,
                                                               0.0))


def load_rank_file(path: str) -> RankTrace:
    events = json.loads(Path(path).read_text())
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome-trace event array "
                         f"(is this a metrics dump?)")
    rt = RankTrace(path=path, rank=-1, events=events)
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "horovod_clock_sync":
            args = e.get("args", {})
            # Last one wins: the init-time event may predate the clock
            # probe; set_clock_sync re-emits with the offset filled in.
            rt.rank = int(args.get("rank", rt.rank))
            rt.start_us = float(args.get("start_us", rt.start_us))
            rt.clock_offset_us = float(args.get("clock_offset_us",
                                                rt.clock_offset_us))
            rt.clock_rtt_us = float(args.get("clock_rtt_us",
                                             rt.clock_rtt_us))
    if rt.rank < 0:
        m = _RANK_SUFFIX_RE.search(path)
        rt.rank = int(m.group(1)) if m else 0
    return rt


def load(paths: list[str]) -> list[RankTrace]:
    traces = sorted((load_rank_file(p) for p in paths),
                    key=lambda t: t.rank)
    seen: dict[int, str] = {}
    for t in traces:
        if t.rank in seen:
            raise ValueError(f"duplicate rank {t.rank}: {seen[t.rank]} "
                             f"and {t.path}")
        seen[t.rank] = t.path
    # Align to the coordinator's clock: a rank's event at local ts
    # corresponds to coordinator-monotonic (ts + start_us + offset_us).
    # Subtract the global minimum so the merged trace starts near 0.
    bases = [t.start_us + t.clock_offset_us for t in traces]
    base0 = min(bases) if bases else 0.0
    for t, b in zip(traces, bases):
        t.shift_us = b - base0
    return traces


# ---------------------------------------------------------------------------
# Merge
# ---------------------------------------------------------------------------
def _op_span_starts(traces: list[RankTrace]) -> dict[str, list]:
    """trace id -> [(aligned ts, rank, tid)] of each rank's first op
    span for that collective."""
    out: dict[str, dict[int, tuple[float, int]]] = {}
    for t in traces:
        for e in t.events:
            if e.get("ph") != "B" or e.get("name") not in OP_SPAN_NAMES:
                continue
            trace_id = (e.get("args") or {}).get("trace")
            if trace_id is None:
                continue
            ts = e.get("ts", 0) + t.shift_us
            best = out.setdefault(trace_id, {}).get(t.rank)
            if best is None or ts < best[0]:
                out[trace_id][t.rank] = (ts, e.get("tid", 0))
    return {tid: sorted((ts, rank, lane)
                        for rank, (ts, lane) in ranks.items())
            for tid, ranks in out.items()}


def merge(traces: list[RankTrace]) -> list[dict]:
    """One flow-linked multi-process trace, offsets applied."""
    merged: list[dict] = []
    for t in traces:
        merged.append({"name": "process_name", "ph": "M", "pid": t.rank,
                       "args": {"name": f"rank {t.rank}"}})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": t.rank, "args": {"sort_index": t.rank}})
        for e in t.events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                continue   # replaced by the pid-correct one above
            e2 = dict(e)
            e2["pid"] = t.rank
            if "ts" in e2:
                e2["ts"] = int(e2["ts"] + t.shift_us)
            merged.append(e2)
    # Flow events: source = earliest op span; one "f" (bind point
    # "enclosing slice") on every other rank's span.
    for trace_id, spans in _op_span_starts(traces).items():
        if len(spans) < 2:
            continue
        ts0, rank0, lane0 = spans[0]
        merged.append({"name": "collective", "cat": "xrank", "ph": "s",
                       "id": trace_id, "ts": int(ts0) + 1, "pid": rank0,
                       "tid": lane0})
        for ts, rank, lane in spans[1:]:
            merged.append({"name": "collective", "cat": "xrank",
                           "ph": "f", "bp": "e", "id": trace_id,
                           "ts": int(ts) + 1, "pid": rank, "tid": lane})
    merged.sort(key=lambda e: e.get("ts", 0))
    return merged


# ---------------------------------------------------------------------------
# Critical path
# ---------------------------------------------------------------------------
def _classify(name: str) -> str | None:
    if name.startswith("NEGOTIATE_"):
        return "negotiate"
    if name.startswith(CODEC_PREFIXES):
        return "codec"
    if name.startswith(WIRE_PREFIXES):
        return "wire"
    if name in OP_SPAN_NAMES:
        return "op"
    return None


def _rank_records(t: RankTrace) -> dict[str, _OpRecord]:
    """trace id -> phase decomposition for one rank (aligned µs)."""
    records: dict[str, _OpRecord] = {}

    def rec(trace_id: str) -> _OpRecord:
        r = records.get(trace_id)
        if r is None:
            r = records[trace_id] = _OpRecord(rank=t.rank)
        return r

    # Per-lane stacks for B/E spans; the E event's args (where the
    # NEGOTIATE span's trace id rides) merge into the span's.
    stacks: dict[tuple, list[dict]] = {}
    spans: list[tuple[str, float, float, dict]] = []
    queue_open: dict = {}
    for e in t.events:
        ph = e.get("ph")
        if ph == "B":
            stacks.setdefault((e.get("pid"), e.get("tid")), []).append(e)
        elif ph == "E":
            stack = stacks.get((e.get("pid"), e.get("tid")))
            if stack:
                b = stack.pop()
                args = dict(b.get("args") or {})
                args.update(e.get("args") or {})
                spans.append((b.get("name", ""), b.get("ts", 0),
                              e.get("ts", 0), args))
        elif ph == "b" and e.get("cat") == "op_queue":
            queue_open[e.get("id")] = e
        elif ph == "e" and e.get("cat") == "op_queue":
            b = queue_open.pop(e.get("id"), None)
            if b is None:
                continue
            trace_id = (e.get("args") or {}).get("trace")
            if trace_id is None:
                continue
            r = rec(trace_id)
            # First queue begin / last queue end across a fused
            # response's entries bound the waiter-visible latency.
            qs = b.get("ts", 0) + t.shift_us
            qe = e.get("ts", 0) + t.shift_us
            if r.queue_start is None or qs < r.queue_start:
                r.queue_start = qs
            if r.queue_end is None or qe > r.queue_end:
                r.queue_end = qe

    for name, ts0, ts1, args in spans:
        trace_id = args.get("trace")
        if trace_id is None:
            continue
        kind = _classify(name)
        if kind is None:
            continue
        r = rec(trace_id)
        dur = max(ts1 - ts0, 0)
        if kind == "op":
            start = ts0 + t.shift_us
            end = ts1 + t.shift_us
            if r.op_start == 0.0 or start < r.op_start:
                r.op_start = start
            r.op_end = max(r.op_end, end)
        else:
            r.phases[kind] += dur

    for r in records.values():
        if r.op_end <= r.op_start:
            continue
        op_dur = r.op_end - r.op_start
        # Framework = op-span time not spent on a wire or staging copy.
        r.phases["framework"] = max(
            op_dur - r.phases["wire"] - r.phases["codec"], 0.0)
        if r.queue_start is not None:
            r.phases["queue_wait"] = max(
                r.op_start - r.queue_start - r.phases["negotiate"], 0.0)
        if r.queue_end is not None:
            r.phases["callback"] = max(r.queue_end - r.op_end, 0.0)
    return records


def _sort_key(trace_id: str) -> tuple[int, int]:
    try:
        cycle, seq = trace_id.split(".", 1)
        return int(cycle), int(seq)
    except ValueError:
        return (1 << 62, 0)


def collective_records(traces: list[RankTrace]
                       ) -> dict[str, dict[int, _OpRecord]]:
    """trace id -> rank -> phase record, for collectives that executed
    on at least one rank."""
    out: dict[str, dict[int, _OpRecord]] = {}
    for t in traces:
        for trace_id, r in _rank_records(t).items():
            if r.op_end > r.op_start:
                out.setdefault(trace_id, {})[t.rank] = r
    return out


def critical_path_report(traces: list[RankTrace], window: int = 32) -> str:
    """Per-window attribution: which rank was the critical path, and
    which of its phases dominated."""
    records = collective_records(traces)
    multi = sorted((tid for tid, ranks in records.items()
                    if len(ranks) >= 2), key=_sort_key)
    if not multi:
        return ("critical path: no cross-rank collectives found — were "
                "all ranks' timeline files passed, and did the run set "
                "HOROVOD_TIMELINE on every rank?")
    window = max(int(window), 1)
    lines = []
    overall_votes: dict[int, int] = {}
    overall_phases: dict[int, dict[str, float]] = {}
    for w0 in range(0, len(multi), window):
        chunk = multi[w0:w0 + window]
        votes: dict[int, int] = {}
        phase_sums: dict[int, dict[str, float]] = {}
        span_us = 0.0
        for tid in chunk:
            ranks = records[tid]
            # Last op-span START on the aligned clock = the rank the
            # rest of the world waited for (arrival-lag semantics, the
            # straggler gauges' counterpart).
            bottleneck = max(ranks, key=lambda r: ranks[r].op_start)
            votes[bottleneck] = votes.get(bottleneck, 0) + 1
            sums = phase_sums.setdefault(
                bottleneck, dict.fromkeys(PHASES, 0.0))
            for k, v in ranks[bottleneck].phases.items():
                sums[k] += v
            span_us += (max(r.op_end for r in ranks.values())
                        - min(r.op_start for r in ranks.values()))
        rank = max(votes, key=lambda r: votes[r])
        sums = phase_sums[rank]
        phase = max(sums, key=lambda k: sums[k])
        overall_votes[rank] = overall_votes.get(rank, 0) + votes[rank]
        tot = overall_phases.setdefault(rank, dict.fromkeys(PHASES, 0.0))
        for k, v in sums.items():
            tot[k] += v
        lines.append(
            f"window {w0 // window}: {len(chunk)} collectives, "
            f"{span_us / 1e3:.2f} ms total span; bottleneck rank {rank} "
            f"({votes[rank]}/{len(chunk)}), dominant phase {phase} "
            f"({sums[phase] / 1e3:.2f} ms)")
        lines.append("  phases on rank %d: %s" % (rank, "  ".join(
            f"{k}={sums[k] / 1e3:.2f}ms" for k in PHASES)))
    rank = max(overall_votes, key=lambda r: overall_votes[r])
    sums = overall_phases[rank]
    phase = max(sums, key=lambda k: sums[k])
    lines.append(f"critical path: rank {rank}, phase {phase} "
                 f"(cross-check horovod_controller_straggler_rank — "
                 f"docs/observability.md)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.telemetry.trace",
        description="Merge per-rank HOROVOD_TIMELINE files into one "
                    "flow-linked Chrome/Perfetto trace (clock offsets "
                    "applied) and attribute the critical path "
                    "(docs/observability.md).")
    parser.add_argument("paths", nargs="+",
                        help="per-rank timeline files (rank 0's path + "
                             "the .r<rank> siblings)")
    parser.add_argument("-o", "--output",
                        help="write the merged trace JSON here")
    parser.add_argument("--critical-path", action="store_true",
                        help="print per-window bottleneck rank + phase "
                             "attribution")
    parser.add_argument("--window", type=int, default=32,
                        help="collectives per attribution window "
                             "(default 32)")
    args = parser.parse_args(argv)
    try:
        traces = load(args.paths)
    except (OSError, ValueError) as exc:
        sys.stderr.write(f"trace: {exc}\n")
        return 1
    for t in traces:
        sys.stderr.write(
            f"trace: rank {t.rank} <- {t.path} "
            f"(clock offset {t.clock_offset_us:+.0f} us, "
            f"rtt {t.clock_rtt_us:.0f} us, {len(t.events)} events)\n")
    if args.output:
        merged = merge(traces)
        Path(args.output).write_text(json.dumps(merged))
        sys.stderr.write(f"trace: wrote {len(merged)} events to "
                         f"{args.output}\n")
    if args.critical_path:
        sys.stdout.write(critical_path_report(traces,
                                              args.window) + "\n")
    elif not args.output:
        sys.stdout.write(json.dumps(merge(traces)) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
