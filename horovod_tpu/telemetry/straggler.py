"""Coordinator-side cross-rank straggler aggregation.

The TPU-v3 pod scaling study (arxiv 1909.09756, PAPERS.md) observes that
at scale the dominant performance failure is a *straggler rank* — one
rank arriving late at every synchronization point — which no single-rank
trace can reveal.  The stall inspector only fires after 60 s of total
silence; this module surfaces millisecond-scale skew continuously.

Two signals, both riding the existing negotiation protocol (no extra
collectives, no extra sockets):

1. **Per-tensor readiness lag** (coordinator-only): when the coordinator
   pops a globally-ready tensor, the spread between the first and last
   rank's request arrival is that tensor's negotiation skew, and the
   last-arriving rank is its straggler.  Aggregated over a window of
   ``HOROVOD_METRICS_WINDOW`` released tensors into min/mean/max/p99
   gauges; a rank whose mean lag exceeds
   ``HOROVOD_STRAGGLER_THRESHOLD_MS`` is named in a structured warning
   and a gauge — long before it would ever trip the stall inspector.

2. **Per-rank self-reported snapshots** (bounded: four scalars) ride each
   worker's RequestList (message.py ``tm_*`` fields): cycle count, summed
   cycle wall time, summed control-plane sync wait, and queue depth.  The
   coordinator re-exports them as per-rank gauges so a scrape of rank 0
   shows the whole world's controller health.

Visibility caveat (documented in docs/observability.md): readiness lag is
observed when tensors *negotiate*.  In response-cache steady state the
control plane ships only bitvectors; skew then surfaces on the next
natural negotiation (new tensor, cache invalidation, autotune heartbeat)
or every cycle under ``HOROVOD_FINGERPRINT=strict``.
"""
from __future__ import annotations

from ..common import config
from ..common.logging import logger


class StragglerAggregator:
    """Windowed cross-rank negotiation-skew statistics (coordinator)."""

    def __init__(self, size: int, registry, window: int | None = None,
                 threshold_ms: float | None = None) -> None:
        self.size = size
        self.registry = registry
        self.window = int(window if window is not None
                          else config.METRICS_WINDOW.get())
        if self.window <= 0:
            self.window = 1
        self.threshold_ms = float(
            threshold_ms if threshold_ms is not None
            else config.STRAGGLER_THRESHOLD_MS.get())
        # Exposed for tests and for the structured warning.
        self.last_straggler = -1
        self.last_skew_ms = 0.0
        self.windows_completed = 0
        # Window accumulators.
        self._lag_sum = [0.0] * size
        self._lag_count = [0] * size
        self._lag_samples: list[float] = []
        self._tensors_seen = 0
        # Gauges (created once; labels stat= keeps one metric family).
        g = registry.gauge
        self._g_stats = {
            stat: g("horovod_controller_negotiation_lag_ms",
                    "Cross-rank request-arrival lag per window "
                    "(ms behind the first-arriving rank)",
                    labels={"stat": stat})
            for stat in ("min", "mean", "max", "p99")}
        self._g_rank = g("horovod_controller_straggler_rank",
                         "Rank with the highest mean negotiation lag in "
                         "the last window (-1 = none)")
        self._g_lag = g("horovod_controller_straggler_lag_ms",
                        "Mean lag of the straggler rank in the last "
                        "window, ms behind the fastest rank")
        self._c_windows = registry.counter(
            "horovod_controller_straggler_windows_total",
            "Windows whose straggler exceeded "
            "HOROVOD_STRAGGLER_THRESHOLD_MS")
        self._g_rank.set(-1.0)
        self._rank_gauges: dict[tuple[str, int], object] = {}

    # -- signal 1: per-tensor readiness lag ------------------------------
    def observe_tensor(self, arrival_times: dict[int, float]) -> None:
        """``arrival_times``: rank -> monotonic time the coordinator saw
        that rank's request for one now-ready tensor."""
        if len(arrival_times) < 2:
            return
        first = min(arrival_times.values())
        for rank, t in arrival_times.items():
            lag_ms = (t - first) * 1e3
            if 0 <= rank < self.size:
                self._lag_sum[rank] += lag_ms
                self._lag_count[rank] += 1
            self._lag_samples.append(lag_ms)
        self._tensors_seen += 1
        if self._tensors_seen >= self.window:
            self._finalize_window()

    def _finalize_window(self) -> None:
        samples = self._lag_samples
        samples.sort()
        n = len(samples)
        if n:
            stats = {
                "min": samples[0],
                "mean": sum(samples) / n,
                "max": samples[-1],
                "p99": samples[min(n - 1, int(0.99 * (n - 1)))],
            }
            for stat, gauge in self._g_stats.items():
                gauge.set(stats[stat])
        means = [self._lag_sum[r] / self._lag_count[r]
                 if self._lag_count[r] else 0.0 for r in range(self.size)]
        straggler = max(range(self.size), key=lambda r: means[r])
        skew = means[straggler] - min(means)
        self.windows_completed += 1
        if skew > self.threshold_ms:
            self.last_straggler = straggler
            self.last_skew_ms = skew
            self._g_rank.set(float(straggler))
            self._g_lag.set(skew)
            self._c_windows.inc()
            logger.warning(
                "telemetry: rank %d is the slowest rank this window — its "
                "collective submissions arrive %.1f ms (mean) behind the "
                "fastest rank over %d negotiated tensors (window lag "
                "min/mean/max/p99 = %.1f/%.1f/%.1f/%.1f ms). A persistent "
                "straggler caps the whole pod at its pace (arxiv "
                "1909.09756); profile that rank (input pipeline, host "
                "contention, thermal throttle) — see docs/observability.md.",
                straggler, skew, self._tensors_seen,
                stats["min"], stats["mean"], stats["max"], stats["p99"])
        else:
            self._g_rank.set(-1.0)
            self._g_lag.set(skew)
        self._lag_sum = [0.0] * self.size
        self._lag_count = [0] * self.size
        self._lag_samples = []
        self._tensors_seen = 0

    # -- signal 2: per-rank self-reported snapshots ----------------------
    def _rank_gauge(self, family: str, rank: int, help_: str):
        key = (family, rank)
        g = self._rank_gauges.get(key)
        if g is None:
            g = self.registry.gauge(family, help_,
                                    labels={"rank": str(rank)})
            self._rank_gauges[key] = g
        return g

    def observe_snapshots(self, gathered) -> None:
        """Fold the tm_* snapshot fields of every rank's RequestList
        (index = rank) into per-rank gauges."""
        for rank, rl in enumerate(gathered):
            if rl is None or rl.tm_cycles <= 0:
                continue
            cycles = rl.tm_cycles
            self._rank_gauge(
                "horovod_rank_cycle_ms", rank,
                "Per-rank mean background-cycle wall time over the last "
                "reported window").set(rl.tm_cycle_ms / cycles)
            self._rank_gauge(
                "horovod_rank_sync_wait_ms", rank,
                "Per-rank mean control-plane sync wait per cycle (a "
                "straggler's peers wait; the straggler itself does "
                "not)").set(rl.tm_sync_wait_ms / cycles)
            self._rank_gauge(
                "horovod_rank_queue_depth", rank,
                "Per-rank tensor-queue depth at its last negotiation"
            ).set(float(rl.tm_queue_depth))
