"""Lock-light per-rank metrics registry: counters, gauges, log2 histograms.

The in-process half of the observability layer (ISSUE 4; the Horovod paper
leans on exactly this counter+timeline introspection to find fusion and
negotiation bottlenecks, arxiv 1802.05799 §5).  Design constraints:

- **Lock-light.**  Each metric owns one uncontended ``threading.Lock``
  taken only for the few instructions of its own update — there is no
  registry-wide lock on the update path, so stream workers, sender lanes
  and the background loop never serialize on each other.  Metric lookup
  (``counter()``/``gauge()``/``histogram()``) takes the registry lock and
  is meant for init-time caching; hot paths hold the metric object.
- **Zero cost when off.**  ``HOROVOD_METRICS=off`` (the default) yields a
  :class:`NullRegistry` whose metrics are shared no-op singletons: no
  locks, no syscalls, no allocation on any hot path.
- **Bounded.**  Histograms are fixed-size log2 bucket arrays (64 buckets
  spanning ~1e-6..1e13), so snapshots that ride the negotiation wire or
  the Prometheus scrape never grow with run length.
"""
from __future__ import annotations

import math
import threading

# Histogram buckets: bucket k holds observations in (2^(k-1+_LOW), 2^(k+_LOW)]
# with everything below 2^_LOW in bucket 0.  _LOW=-20 puts the smallest
# bound near 1e-6 (sub-microsecond) and the largest near 1.7e13 (bytes of
# a 17 TB transfer / ms of a 544-year stall) — wide enough for every unit
# this tree observes (ms, bytes, ratios).
_NBUCKETS = 64
_LOW = -20


def _bucket_index(value: float) -> int:
    if value <= 0.0:
        return 0
    idx = int(math.ceil(math.log2(value))) - _LOW
    return min(max(idx, 0), _NBUCKETS - 1)


def bucket_upper_bound(index: int) -> float:
    """Inclusive upper bound of bucket ``index``."""
    return 2.0 ** (index + _LOW)


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: dict[str, str]) -> None:
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("name", "labels", "_value")

    def __init__(self, name: str, labels: dict[str, str]) -> None:
        self.name = name
        self.labels = labels
        self._value = 0.0

    def set(self, value: float) -> None:
        # A single attribute store — atomic under the GIL, no lock needed.
        self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-size log2-bucketed histogram with sum/count/min/max."""

    __slots__ = ("name", "labels", "_buckets", "_count", "_sum",
                 "_min", "_max", "_lock")

    def __init__(self, name: str, labels: dict[str, str]) -> None:
        self.name = name
        self.labels = labels
        self._buckets = [0] * _NBUCKETS
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = _bucket_index(value)
        with self._lock:
            self._buckets[idx] += 1
            self._count += 1
            self._sum += value
            if value < self._min:
                self._min = value
            if value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def percentile(self, p: float) -> float:
        """Approximate percentile: the upper bound of the bucket holding
        the p-quantile observation (log2 resolution — factor-of-two
        accuracy, which is what "where did the milliseconds go" needs)."""
        with self._lock:
            count = self._count
            buckets = list(self._buckets)
        if count == 0:
            return 0.0
        target = p / 100.0 * count
        cum = 0
        for i, n in enumerate(buckets):
            cum += n
            if cum >= target:
                return bucket_upper_bound(i)
        return bucket_upper_bound(_NBUCKETS - 1)

    def quantile(self, q: float) -> float:
        """Interpolated quantile, ``q`` in [0, 1]: geometric (log-space)
        interpolation within the log2 bucket holding the q-th
        observation, clamped to the observed min/max so single-bucket
        histograms and extreme quantiles report a value that was
        actually plausible rather than a power-of-two bound.  This is
        the one quantile path serving SLO reports (p50/p99/p999) and
        training step-time summaries share (ISSUE 9)."""
        with self._lock:
            count = self._count
            buckets = list(self._buckets)
            lo_obs, hi_obs = self._min, self._max
        if count == 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        target = q * count
        cum = 0
        value = bucket_upper_bound(_NBUCKETS - 1)
        for i, n in enumerate(buckets):
            if not n:
                continue
            prev, cum = cum, cum + n
            if cum >= target:
                frac = (target - prev) / n
                hi = bucket_upper_bound(i)
                lo = hi / 2.0
                value = lo * (hi / lo) ** frac
                break
        return min(max(value, lo_obs), hi_obs)

    def nonzero_buckets(self) -> list[tuple[float, int]]:
        """(upper bound, count) for populated buckets, ascending."""
        return [(bucket_upper_bound(i), n)
                for i, n in enumerate(self._buckets) if n]


class _NullMetric:
    """Shared no-op stand-in for every metric type when metrics are off."""

    __slots__ = ()
    name = ""
    labels: dict[str, str] = {}
    value = 0.0
    count = 0
    sum = 0.0
    mean = 0.0

    def inc(self, value: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def percentile(self, p: float) -> float:
        return 0.0

    def quantile(self, q: float) -> float:
        return 0.0

    def nonzero_buckets(self):
        return []


NULL_METRIC = _NullMetric()


def _label_key(labels: dict[str, str] | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


def _format_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class MetricsRegistry:
    """Process-wide registry; one per rank (see telemetry.configure)."""

    enabled = True

    def __init__(self, rank: int = 0) -> None:
        self.rank = rank
        self._lock = threading.Lock()
        self._metrics: dict[tuple, Counter | Gauge | Histogram] = {}
        self._help: dict[str, str] = {}

    # -- get-or-create (init-time; hot paths cache the returned object) --
    def _get(self, cls, name: str, help_: str,
             labels: dict[str, str] | None):
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, dict(labels or {}))
                self._metrics[key] = m
                if help_:
                    self._help.setdefault(name, help_)
            return m

    def counter(self, name: str, help: str = "",
                labels: dict[str, str] | None = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: dict[str, str] | None = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: dict[str, str] | None = None) -> Histogram:
        return self._get(Histogram, name, help, labels)

    def _sorted_metrics(self):
        with self._lock:
            return sorted(self._metrics.items(), key=lambda kv: kv[0])

    # -- exposition ------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        out: list[str] = []
        seen_header: set[str] = set()
        for (name, _), m in self._sorted_metrics():
            kind = {Counter: "counter", Gauge: "gauge",
                    Histogram: "histogram"}[type(m)]
            if name not in seen_header:
                seen_header.add(name)
                help_ = self._help.get(name, "")
                if help_:
                    out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} {kind}")
            if isinstance(m, Histogram):
                cum = 0
                for bound, n in m.nonzero_buckets():
                    cum += n
                    lab = _format_labels({**m.labels, "le": f"{bound:g}"})
                    out.append(f"{name}_bucket{lab} {cum}")
                lab = _format_labels({**m.labels, "le": "+Inf"})
                out.append(f"{name}_bucket{lab} {m.count}")
                base = _format_labels(m.labels)
                out.append(f"{name}_sum{base} {m.sum:g}")
                out.append(f"{name}_count{base} {m.count}")
                # Interpolated p50/p99 as summary-style series: serving
                # SLO dashboards and training step times read the same
                # quantile path (Histogram.quantile, ISSUE 9).
                for q in (0.5, 0.99):
                    lab = _format_labels({**m.labels, "quantile": f"{q:g}"})
                    out.append(f"{name}{lab} {m.quantile(q):g}")
            else:
                out.append(
                    f"{name}{_format_labels(m.labels)} {m.value:g}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """JSON-able dump of every metric (the HOROVOD_METRICS_FILE
        payload and the bench.py metrics attachment)."""
        metrics = []
        for (name, _), m in self._sorted_metrics():
            entry: dict = {"name": name, "labels": m.labels}
            if isinstance(m, Counter):
                entry["type"] = "counter"
                entry["value"] = m.value
            elif isinstance(m, Gauge):
                entry["type"] = "gauge"
                entry["value"] = m.value
            else:
                entry["type"] = "histogram"
                entry["count"] = m.count
                entry["sum"] = m.sum
                entry["mean"] = m.mean
                entry["p50"] = m.quantile(0.5)
                entry["p99"] = m.quantile(0.99)
                entry["buckets"] = [[b, n] for b, n in m.nonzero_buckets()]
            metrics.append(entry)
        return {"rank": self.rank, "metrics": metrics}


class NullRegistry:
    """HOROVOD_METRICS=off: every lookup returns the shared no-op metric —
    the hot path sees no new locks, syscalls, or allocations."""

    enabled = False
    rank = -1

    def counter(self, name: str, help: str = "",
                labels: dict[str, str] | None = None):
        return NULL_METRIC

    def gauge(self, name: str, help: str = "",
              labels: dict[str, str] | None = None):
        return NULL_METRIC

    def histogram(self, name: str, help: str = "",
                  labels: dict[str, str] | None = None):
        return NULL_METRIC

    def render_prometheus(self) -> str:
        return ""

    def snapshot(self) -> dict:
        return {"rank": self.rank, "metrics": []}


NULL_REGISTRY = NullRegistry()
