"""Failure flight recorder: an always-on bounded ring of recent trace
events per rank, dumped as rank-stamped JSON the moment a structured
failure fires (ISSUE 7; docs/observability.md).

The gap this closes: HOROVOD_TIMELINE answers "where did the time go"
only when the user presciently enabled it *before* the run died.  The
RanksFailedError / fingerprint-divergence / deadline-poison conversions
(PR 2, PR 5) tell you *that* the world failed and who is blamed — but
not what every survivor was doing in the seconds before.  The recorder
keeps the last ``HOROVOD_FLIGHT_EVENTS`` trace events (enqueue,
dispatch, completion, failure conversions) in a ``collections.deque``
ring — one GIL-atomic append per event, **no locks, no threads, no
file I/O** until a failure actually fires — and every structured
failure path dumps it, so each surviving rank ships evidence whose tail
names the in-flight op.

Zero-overhead off mode (``HOROVOD_FLIGHT=0``): every instrumentation
point resolves to the shared :data:`NULL_FLIGHT` no-op recorder, no
SIGTERM handler is installed, and the process thread census is
byte-identical either way (the recorder never owns a thread).

Dump triggers (all convert an in-flight failure into evidence):

- the controller's RanksFailedError conversion
  (``Controller._poison_response_list`` — covers local detection,
  received poison frames, and coordinator-side drains);
- a data-plane RanksFailedError surfacing through response execution
  (``core._execute_response``);
- a fingerprint-divergence structured ERROR
  (``Controller._check_fingerprints``);
- SIGTERM (preemption notice), chained in front of any existing
  handler.

Under ``HOROVOD_SAN=1`` the hvdsan runtime witness
(``analysis/hvdsan/san.py``) also records each first-observed
lock-acquisition-order edge into this ring (kind ``lock-order``), so a
failure dump shows which lock orders the dying rank had exercised.
"""
from __future__ import annotations

import collections
import json
import os
import signal
import threading
import time

from ..common import config
from ..common.logging import logger
from .exporter import resolve_dump_path

__all__ = ["NULL_FLIGHT", "FlightRecorder", "NullFlightRecorder",
           "configure", "recorder"]


class NullFlightRecorder:
    """Shared no-op recorder: the HOROVOD_FLIGHT=0 posture."""

    enabled = False

    def record(self, kind: str, name: str = "", trace=None,
               detail: str = "") -> None:
        pass

    def dump(self, reason: str = "") -> None:
        return None

    def snapshot(self) -> list:
        return []

    def set_metadata(self, **kv) -> None:
        pass


NULL_FLIGHT = NullFlightRecorder()


class FlightRecorder:
    """Lock-light bounded ring of recent trace events for one rank."""

    enabled = True

    def __init__(self, rank: int, capacity: int, path: str) -> None:
        self.rank = rank
        self.path = path
        # deque.append with maxlen is one GIL-atomic operation — the
        # recording hot path takes no lock (the dump lock below guards
        # only the failure path's file write).
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(capacity), 8))
        self._meta: dict = {}
        self._dump_lock = threading.Lock()
        self.dumps = 0
        self.last_dump_path: str | None = None

    # -- hot path --------------------------------------------------------
    def record(self, kind: str, name: str = "", trace=None,
               detail: str = "") -> None:
        """Append one trace event: (monotonic ts, kind, name, trace id,
        detail).  Callers pre-format strings only under
        ``if recorder.enabled`` so the off mode pays one attribute
        test."""
        self._ring.append((time.monotonic(), kind, name, trace, detail))

    def set_metadata(self, **kv) -> None:
        """Rank-level stitching metadata (clock offset, world size, …)
        included in every dump."""
        self._meta.update(kv)

    # -- failure path ----------------------------------------------------
    def snapshot(self) -> list[dict]:
        return [{"ts": ts, "kind": kind, "name": name, "trace": trace,
                 "detail": detail}
                for ts, kind, name, trace, detail in list(self._ring)]

    def dump(self, reason: str = "") -> str | None:
        """Write the rank-stamped JSON dump; returns the path (None on
        an unwritable target — evidence must never mask the original
        failure)."""
        with self._dump_lock:
            payload = {
                "rank": self.rank,
                "reason": reason,
                "dumped_wall_time": time.time(),
                "dumped_monotonic": time.monotonic(),
                "meta": dict(self._meta),
                "events": self.snapshot(),
            }
            try:
                # Write-then-rename: a concurrent reader (another
                # thread's conversion, a test, an operator tailing the
                # evidence) never sees a half-written dump.
                tmp = f"{self.path}.tmp{os.getpid()}"
                with open(tmp, "w") as f:  # hvdlint: disable=HVD1002 -- failure-path dump: runs only when a structured failure already fired, never during healthy dispatch
                    json.dump(payload, f, indent=1)
                os.replace(tmp, self.path)
            except OSError as exc:
                logger.warning("flight: dump to %s failed: %s",
                               self.path, exc)
                return None
            self.dumps += 1
            self.last_dump_path = self.path
            return self.path


_lock = threading.Lock()
_recorder: FlightRecorder | NullFlightRecorder | None = None
_sigterm_chained = False
_prev_sigterm = None


def _sigterm_handler(signum, frame):
    rec = _recorder
    if rec is not None and rec.enabled:
        rec.record("sigterm")
        rec.dump(reason="SIGTERM")
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
    else:
        # Default disposition: re-deliver so the process still dies with
        # the SIGTERM exit status the launcher expects.
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _chain_sigterm() -> None:
    global _sigterm_chained, _prev_sigterm
    if _sigterm_chained:
        return
    if threading.current_thread() is not threading.main_thread():
        return   # signal.signal is main-thread-only; workers skip
    try:
        _prev_sigterm = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, _sigterm_handler)
        _sigterm_chained = True
    except (ValueError, OSError):   # exotic embedding: recorder still works
        logger.debug("flight: SIGTERM handler not installed",
                     exc_info=True)


def configure(rank: int = 0):
    """(Re)build the process recorder from the environment (core.init);
    safe to call again across elastic/retry re-inits — the SIGTERM
    chain installs once, and the previous enabled ring's events CARRY
    OVER into the new recorder (bounded by the new capacity): the
    membership transitions recorded just before a world rebuild
    (``grow``/``shrink``/``departed``/...) are exactly what the hvdmc
    trace witness replays from an end-of-run dump, and what a
    post-rebuild failure dump needs for cross-epoch context."""
    global _recorder
    with _lock:
        prev = _recorder
        if not config.FLIGHT.get():
            _recorder = NULL_FLIGHT
            return _recorder
        _recorder = FlightRecorder(
            rank, config.FLIGHT_EVENTS.get(),
            resolve_dump_path(config.FLIGHT_FILE.get(), rank))
        if isinstance(prev, FlightRecorder):
            _recorder._ring.extend(prev._ring)
        _chain_sigterm()
        return _recorder


def recorder():
    """The process flight recorder (never None; Null when off)."""
    global _recorder
    if _recorder is None:
        _recorder = configure()
    return _recorder
