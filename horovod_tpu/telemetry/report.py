"""Offline summarizer for metrics dumps and timeline traces.

CLI::

    python -m horovod_tpu.telemetry.report DUMP_OR_TIMELINE.json [...]

Accepts either artifact the runtime produces and answers "where did the
milliseconds go" as a per-activity table:

- a **metrics dump** (HOROVOD_METRICS_FILE JSON): counters/gauges as-is,
  histograms as count/mean/p50/p99/max rows;
- a **Chrome-trace timeline** (HOROVOD_TIMELINE JSON): per-activity
  total/mean/max span durations aggregated over every tensor lane, plus
  the final value of each counter track ("ph":"C").

Output goes to stdout as aligned plain text (one table per input file).
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def _fmt_table(rows: list[list[str]], header: list[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    def line(cells):
        return "  ".join(str(c).ljust(w) for c, w in zip(cells, widths))
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(header), sep] + [line(r) for r in rows])


def _label_str(labels: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))


def _collective_tables(payload: dict) -> list[str]:
    """The perfscope view of one dump: per-activity latency broken out
    by the algo label (one row per plane/op/codec/algo instead of one
    collapsed labels blob), and the busbw/efficiency rows the roofline
    ledger is built from (telemetry/perfmodel.py)."""
    lat_rows: list[list[str]] = []
    bus_rows: list[list[str]] = []
    eff = {}
    for m in payload.get("metrics", []):
        labels = m.get("labels", {})
        if m["name"] == "horovod_collective_efficiency":
            key = (labels.get("plane", ""), labels.get("algo", ""),
                   labels.get("size_bucket", ""))
            eff[key] = m.get("value", 0.0)
    for m in payload.get("metrics", []):
        if m.get("type") != "histogram":
            continue
        labels = m.get("labels", {})
        if m["name"] == "horovod_collective_latency_ms":
            lat_rows.append([
                labels.get("plane", ""), labels.get("op", ""),
                labels.get("codec", ""), labels.get("algo", ""),
                str(m["count"]), f"{m['p50']:.3f}", f"{m['p99']:.3f}"])
        elif m["name"] == "horovod_collective_busbw_mbps":
            key = (labels.get("plane", ""), labels.get("algo", ""),
                   labels.get("size_bucket", ""))
            bus_rows.append([
                labels.get("plane", ""), labels.get("op", ""),
                labels.get("algo", ""), labels.get("size_bucket", ""),
                str(m["count"]), f"{m['mean']:.1f}", f"{m['p50']:.1f}",
                f"{eff[key]:.2f}" if key in eff else "-"])
    parts = []
    if lat_rows:
        parts.append(_fmt_table(
            sorted(lat_rows),
            ["plane", "op", "codec", "algo", "count", "p50_ms",
             "p99_ms"]))
    if bus_rows:
        parts.append(_fmt_table(
            sorted(bus_rows),
            ["plane", "op", "algo", "size_bucket", "samples",
             "busbw_mbps", "p50_mbps", "efficiency"]))
    return parts


def summarize_dump(payload: dict) -> str:
    """Per-metric table for a HOROVOD_METRICS_FILE snapshot."""
    scalar_rows: list[list[str]] = []
    hist_rows: list[list[str]] = []
    for m in payload.get("metrics", []):
        name = m["name"]
        labels = _label_str(m.get("labels", {}))
        if m["type"] == "histogram":
            hist_rows.append([
                name, labels, str(m["count"]), f"{m['mean']:.3f}",
                f"{m['p50']:.3f}", f"{m['p99']:.3f}", f"{m['sum']:.1f}"])
        else:
            scalar_rows.append([name, labels, m["type"],
                                f"{m['value']:g}"])
    parts = [f"metrics dump (rank {payload.get('rank', '?')})"]
    if scalar_rows:
        parts.append(_fmt_table(scalar_rows,
                                ["metric", "labels", "type", "value"]))
    if hist_rows:
        parts.append(_fmt_table(
            hist_rows,
            ["histogram", "labels", "count", "mean", "p50", "p99", "sum"]))
    parts.extend(_collective_tables(payload))
    if not scalar_rows and not hist_rows:
        parts.append("(no metrics recorded — was HOROVOD_METRICS=on?)")
    return "\n\n".join(parts)


def summarize_timeline(events: list[dict]) -> str:
    """Per-activity duration table for a Chrome-trace timeline."""
    # Span matching: per (pid, tid) lane, a stack of open B events; an E
    # closes the innermost span (the format Timeline emits).
    stacks: dict[tuple, list[tuple[str, int]]] = {}
    totals: dict[str, list[float]] = {}
    counters: dict[str, dict] = {}
    for e in events:
        ph = e.get("ph")
        if ph == "C":
            counters[e.get("name", "")] = e.get("args", {})
            continue
        if ph not in ("B", "E"):
            continue
        key = (e.get("pid"), e.get("tid"))
        if ph == "B":
            stacks.setdefault(key, []).append(
                (e.get("name", ""), e.get("ts", 0)))
        else:
            stack = stacks.get(key)
            if stack:
                name, ts0 = stack.pop()
                totals.setdefault(name, []).append(
                    (e.get("ts", 0) - ts0) / 1e3)
    rows = []
    for name, spans in sorted(totals.items(),
                              key=lambda kv: -sum(kv[1])):
        rows.append([name, str(len(spans)), f"{sum(spans):.2f}",
                     f"{sum(spans) / len(spans):.3f}",
                     f"{max(spans):.3f}"])
    parts = []
    if rows:
        parts.append(_fmt_table(
            rows, ["activity", "spans", "total_ms", "mean_ms", "max_ms"]))
    else:
        parts.append("(no spans in trace)")
    if counters:
        crow = [[name, _label_str(args)]
                for name, args in sorted(counters.items())]
        parts.append(_fmt_table(crow, ["counter", "final value"]))
    return "\n\n".join(parts)


def summarize_file(path: str) -> str:
    payload = json.loads(Path(path).read_text())
    if isinstance(payload, list):
        body = summarize_timeline(payload)
        kind = "timeline"
    else:
        body = summarize_dump(payload)
        kind = "metrics"
    return f"== {path} ({kind}) ==\n{body}\n"


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.telemetry.report",
        description="Summarize a HOROVOD_METRICS_FILE dump or a "
                    "HOROVOD_TIMELINE trace into per-activity tables "
                    "(docs/observability.md).")
    parser.add_argument("paths", nargs="+",
                        help="metrics dump(s) and/or timeline file(s)")
    args = parser.parse_args(argv)
    rc = 0
    for path in args.paths:
        try:
            sys.stdout.write(summarize_file(path) + "\n")
        except (OSError, ValueError) as exc:
            sys.stderr.write(f"report: cannot summarize {path}: {exc}\n")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
