"""Metric-name catalog: every metric the runtime can register, scanned
from source, and the docs-completeness gate over it (ISSUE 19).

The registry (registry.py) has no central declaration site — call sites
register metrics ad hoc (``tm.counter("horovod_x_total", ...)``), some
through wrappers that take the name as a plain argument.  So the
catalog is an AST sweep: every string constant that (a) looks like a
metric name (``horovod_[a-z0-9_]+``, no trailing underscore — those
are prefixes being concatenated) and (b) appears as an argument of some
call, anywhere under ``horovod_tpu/``.  That over-approximates (any
horovod_-shaped string constant passed to any function qualifies), so
non-metric names go in :data:`ALLOWLIST` rather than weakening the
pattern.

:func:`undocumented_metrics` is the ``analysis.rules.undocumented_rules``
contract for metrics: every cataloged name must appear in a table row of
docs/observability.md (as `` `name` `` or `` `name{labels}` ``), and CI
asserts the result is empty (tests/test_lint_clean.py) — a new metric
cannot land undocumented.
"""
from __future__ import annotations

import ast
import os
import re
from pathlib import Path

# String constants that match the metric shape but are not metrics:
# logger/package names and similar call arguments.
ALLOWLIST = frozenset({"horovod_tpu", "horovod_tpu_init"})

_METRIC_RE = re.compile(r"^horovod_[a-z0-9_]+$")

_PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _names_in_source(source: str) -> set[str]:
    names: set[str] = set()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return names
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str) \
                    and _METRIC_RE.match(arg.value) \
                    and not arg.value.endswith("_") \
                    and arg.value not in ALLOWLIST:
                names.add(arg.value)
    return names


def registered_metric_names(root: str | None = None) -> set[str]:
    """Every metric name any module under ``root`` (default: the
    horovod_tpu package) can register, by static AST scan."""
    base = Path(root or _PACKAGE_ROOT)
    names: set[str] = set()
    for path in sorted(base.rglob("*.py")):
        try:
            names |= _names_in_source(path.read_text())
        except OSError:
            continue
    return names


def undocumented_metrics(doc_text: str,
                         root: str | None = None) -> list[str]:
    """Metric names with no table row in the given documentation text
    (docs/observability.md's metric tables; names render backticked,
    optionally with an attached ``{label,...}`` set) — the same contract
    as analysis.rules.undocumented_rules: CI asserts this returns []."""
    rows = "\n".join(line for line in doc_text.splitlines()
                     if line.lstrip().startswith("|"))
    return sorted(name for name in registered_metric_names(root)
                  if f"`{name}`" not in rows and f"`{name}{{" not in rows)
