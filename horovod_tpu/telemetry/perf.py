"""perfscope CLI: merge rank metric dumps into the PERF.json ledger.

CLI::

    python -m horovod_tpu.telemetry.perf DUMP.r*.json -o PERF.json \
        [--topology torus:2x4] [--size N] [--peak-mbps X] \
        [--timeline T.json T.json.r1 ...]

Inputs are ``HOROVOD_METRICS_FILE`` snapshots (one per rank; a
directory argument loads every ``*.json`` under it).  The ledger
(telemetry/perfmodel.py) carries:

- **busbw table**: bus bandwidth per (plane, op, codec, algo,
  size-bucket), merged across ranks, with roofline-relative efficiency
  (peak from ``--peak-mbps`` / HOROVOD_PERF_PEAK_MBPS, else
  self-calibrated to the best cell);
- **step ledger**: train MFU / serve throughput gauges when the dumps
  carry them;
- **lost time**: with ``--timeline``, the PR 7 critical-path phases
  attribute straggler time (telemetry/trace.py) into the ledger.

The merged ledger is what ``telemetry.perfcheck`` gates against and
what bench.py stamps into every BENCH payload (docs/observability.md).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

from ..common import config
from ..common.topology import parse as parse_topology
from . import perfmodel


def load_snapshots(paths: list[str]) -> tuple[list[dict], list[str]]:
    """Load metric-dump snapshots ({"rank", "metrics"} shape) from files
    and/or directories; unreadable or non-dump payloads are skipped and
    reported, never fatal (the console/sources.py posture)."""
    snapshots: list[dict] = []
    skipped: list[str] = []
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(os.path.join(p, name)
                         for name in sorted(os.listdir(p))
                         if name.endswith(".json"))
        else:
            files.append(p)
    for f in files:
        try:
            payload = json.loads(Path(f).read_text())
        except (OSError, ValueError):
            skipped.append(f)
            continue
        if isinstance(payload, dict) and "metrics" in payload:
            snapshots.append(payload)
        else:
            skipped.append(f)
    return snapshots, skipped


def _lost_time(timeline_paths: list[str]) -> dict | None:
    """Straggler-attributed lost time from per-rank timeline files: per
    collective, the span between the earliest and latest rank's op
    window is time the fast ranks spent waiting (the critical-path
    phases' cross-rank counterpart)."""
    from .trace import collective_records, critical_path_report, load
    try:
        traces = load(timeline_paths)
    except (OSError, ValueError) as exc:
        return {"error": f"cannot load timelines: {exc}"}
    records = collective_records(traces)
    lost_us = 0.0
    span_us = 0.0
    by_rank: dict[int, float] = {}
    multi = {tid: ranks for tid, ranks in records.items()
             if len(ranks) >= 2}
    for ranks in multi.values():
        start = min(r.op_start for r in ranks.values())
        end = max(r.op_end for r in ranks.values())
        span_us += end - start
        last = max(ranks, key=lambda r: ranks[r].op_start)
        wait = ranks[last].op_start - start
        lost_us += wait * (len(ranks) - 1)
        by_rank[last] = by_rank.get(last, 0.0) + wait
    if not multi:
        return None
    return {
        "collectives": len(multi),
        "span_ms": span_us / 1e3,
        "lost_rank_ms": lost_us / 1e3,
        "waited_on_ms": {str(r): v / 1e3
                         for r, v in sorted(by_rank.items())},
        "critical_path": critical_path_report(traces).splitlines()[-1],
    }


def build(paths: list[str], *, topology_spec: str = "",
          size: int = 0, peak_mbps: float = 0.0,
          min_samples: int = 0,
          timeline_paths: list[str] | None = None) -> tuple[dict, int]:
    """Assemble the full PERF.json payload; returns (payload, rc)."""
    snapshots, skipped = load_snapshots(paths)
    world = size or max((int(s.get("rank", 0)) for s in snapshots),
                        default=-1) + 1
    topo = parse_topology(topology_spec or config.TOPOLOGY.get(),
                          size=max(world, 1))
    ledger = perfmodel.build_ledger(
        snapshots, topo,
        peak_mbps=peak_mbps or float(config.PERF_PEAK_MBPS.get()),
        min_samples=min_samples or int(config.PERF_MIN_SAMPLES.get()))
    if skipped:
        ledger["skipped"] = skipped
    if timeline_paths:
        lost = _lost_time(timeline_paths)
        ledger["lost_time"] = lost if lost is not None else \
            {"note": "no cross-rank collectives in the timelines"}
    return ledger, 0 if snapshots else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.telemetry.perf",
        description="Merge per-rank HOROVOD_METRICS_FILE dumps into the "
                    "roofline-attributed perf ledger, PERF.json "
                    "(docs/observability.md).")
    parser.add_argument("paths", nargs="+",
                        help="rank metric dumps and/or directories of "
                             "them")
    parser.add_argument("-o", "--output", default="",
                        help="write the ledger JSON here (default: "
                             "stdout)")
    parser.add_argument("--topology", default="",
                        help="fabric layout spec (HOROVOD_TOPOLOGY "
                             "syntax; default: the env knob)")
    parser.add_argument("--size", type=int, default=0,
                        help="world size (default: max dump rank + 1)")
    parser.add_argument("--peak-mbps", type=float, default=0.0,
                        help="roofline peak bus bandwidth (default: "
                             "HOROVOD_PERF_PEAK_MBPS, else "
                             "self-calibrated)")
    parser.add_argument("--min-samples", type=int, default=0,
                        help="samples a cell needs to enter the table "
                             "(default: HOROVOD_PERF_MIN_SAMPLES)")
    parser.add_argument("--timeline", nargs="*", default=[],
                        help="per-rank HOROVOD_TIMELINE files for "
                             "straggler lost-time attribution")
    parser.add_argument("--summary", action="store_true",
                        help="also print the compact human summary to "
                             "stderr")
    args = parser.parse_args(argv)

    ledger, rc = build(args.paths, topology_spec=args.topology,
                       size=args.size, peak_mbps=args.peak_mbps,
                       min_samples=args.min_samples,
                       timeline_paths=args.timeline)
    text = json.dumps(ledger, indent=1, sort_keys=True)
    if args.output:
        Path(args.output).write_text(text + "\n")
    else:
        sys.stdout.write(text + "\n")
    if args.summary:
        sys.stderr.write("\n".join(
            perfmodel.ledger_summary(ledger)) + "\n")
    if rc:
        sys.stderr.write("perf: no readable metric dumps among the "
                         "inputs\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
