"""Evidence loaders for the operator console.

Two modes feed the same renderer:

- **Post-hoc**: a directory of rank-stamped dumps from a finished (or
  crashed) episode — flight-recorder rings (``flight.r*.json``),
  telemetry snapshots (``metrics.r*.json``), ``/.ctl`` role-probe
  timelines (``ctl_roles.r*.json``), fleetsim summaries
  (``summary.r*.json``) and serving loadgen reports
  (``SERVE_r*.json``).  Files are classified by PAYLOAD SHAPE, not
  filename, so dumps renamed by collection tooling still load.
- **Live**: Prometheus text scraped from each rank's metrics exporter
  (telemetry/exporter.py) plus the rendezvous replicas' ``/.ctl/role``
  keys, re-assembled into the same snapshot schema the post-hoc dumps
  use.

Everything here is best-effort: an unreadable file or unreachable
endpoint degrades to an absent section, never an exception — the
console is the tool you reach for when the fleet is already broken.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from urllib import request as urlrequest

__all__ = ["Episode", "load_dump_dir", "live_snapshot",
           "parse_prometheus", "probe_ctl", "scrape_metrics"]


@dataclasses.dataclass
class Episode:
    """One episode's evidence, whatever subset of it was found."""
    source: str
    flights: list = dataclasses.field(default_factory=list)
    metrics: list = dataclasses.field(default_factory=list)
    ctl_roles: list = dataclasses.field(default_factory=list)
    summaries: list = dataclasses.field(default_factory=list)
    serve_reports: list = dataclasses.field(default_factory=list)
    skipped: list = dataclasses.field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not (self.flights or self.metrics or self.ctl_roles
                    or self.summaries or self.serve_reports)


def _classify(payload) -> str | None:
    """Dump kind by shape (see module docstring)."""
    if not isinstance(payload, dict):
        return None
    if "fleetsim_summary" in payload:
        return "summary"
    if str(payload.get("schema", "")).startswith(
            "horovod_tpu.serving.loadgen"):
        return "serve"
    if "events" in payload and "reason" in payload:
        return "flight"
    if "probes" in payload:
        return "ctl"
    if "metrics" in payload and "rank" in payload:
        return "metrics"
    return None


def load_dump_dir(path: str) -> Episode:
    """Load every classifiable ``*.json`` under ``path`` (one level)."""
    ep = Episode(source=path)
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return ep
    buckets = {"flight": ep.flights, "metrics": ep.metrics,
               "ctl": ep.ctl_roles, "summary": ep.summaries,
               "serve": ep.serve_reports}
    for name in names:
        if not name.endswith(".json"):
            continue
        full = os.path.join(path, name)
        try:
            with open(full) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            ep.skipped.append(name)
            continue
        kind = _classify(payload)
        if kind is None:
            ep.skipped.append(name)
            continue
        payload.setdefault("_file", name)
        buckets[kind].append(payload)
    return ep


# ---------------------------------------------------------------------------
# Live scrape
# ---------------------------------------------------------------------------
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_prometheus(text: str) -> list[dict]:
    """Prometheus text format -> ``[{"name", "labels", "value"}]``.
    Unparsable lines are skipped (scrape-side truncation happens)."""
    samples = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, raw_labels, raw_value = m.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels = dict(_LABEL_RE.findall(raw_labels or ""))
        samples.append({"name": name, "labels": labels, "value": value})
    return samples


def samples_to_snapshot(samples: list[dict], rank: int) -> dict:
    """Re-assemble flat scrape samples into the ``dump_json`` snapshot
    schema (telemetry/exporter.py): counters/gauges keep their value,
    histogram series (``_count``/``_sum``/``quantile=``) fold back into
    one entry with count/sum/p50/p99."""
    plain: list[dict] = []
    hists: dict[tuple, dict] = {}

    def _hist(base: str, labels: dict) -> dict:
        key = (base, tuple(sorted(labels.items())))
        return hists.setdefault(
            key, {"name": base, "labels": dict(labels),
                  "type": "histogram", "count": 0, "sum": 0.0,
                  "p50": 0.0, "p99": 0.0})

    for s in samples:
        name, labels, value = s["name"], dict(s["labels"]), s["value"]
        q = labels.pop("quantile", None)
        if q is not None:
            h = _hist(name, labels)
            if q == "0.5":
                h["p50"] = value
            elif q == "0.99":
                h["p99"] = value
            continue
        if name.endswith("_bucket") and "le" in labels:
            continue    # quantiles carry what the console renders
        if name.endswith("_count"):
            _hist(name[:-len("_count")], labels)["count"] = int(value)
            continue
        if name.endswith("_sum"):
            _hist(name[:-len("_sum")], labels)["sum"] = value
            continue
        kind = "counter" if name.endswith("_total") else "gauge"
        plain.append({"name": name, "labels": labels, "type": kind,
                      "value": value})
    return {"rank": rank, "metrics": plain + list(hists.values())}


def scrape_metrics(endpoint: str, timeout: float = 2.0) -> list[dict]:
    """GET ``/metrics`` from one exporter; [] when unreachable."""
    try:
        with urlrequest.urlopen(f"http://{endpoint}/metrics",
                                timeout=timeout) as resp:
            return parse_prometheus(resp.read().decode(errors="replace"))
    except OSError:
        return []


def probe_ctl(endpoint: str, key: str = "role",
              timeout: float = 1.0) -> str:
    """GET one ``/.ctl/<key>`` from a rendezvous replica."""
    try:
        with urlrequest.urlopen(f"http://{endpoint}/.ctl/{key}",
                                timeout=timeout) as resp:
            return resp.read().decode(errors="replace")
    except OSError:
        return "unreachable"


def live_snapshot(metric_endpoints: list[str],
                  ctl_endpoints: list[str]) -> Episode:
    """One live scrape pass across the fleet, shaped like a dump dir."""
    ep = Episode(source="live:" + ",".join(metric_endpoints
                                           + ctl_endpoints))
    for i, endpoint in enumerate(metric_endpoints):
        samples = scrape_metrics(endpoint)
        if samples:
            snap = samples_to_snapshot(samples, rank=i)
            snap["_endpoint"] = endpoint
            ep.metrics.append(snap)
        else:
            ep.skipped.append(endpoint)
    if ctl_endpoints:
        probes = [{"t": 0.0, "endpoint": endpoint,
                   "role": probe_ctl(endpoint)}
                  for endpoint in ctl_endpoints]
        ep.ctl_roles.append({"probes": probes,
                             "endpoints": list(ctl_endpoints)})
    return ep
