"""Deterministic text rendering of one episode's evidence.

The renderer is a pure function of the :class:`~.sources.Episode` —
same files in, same characters out — so the golden-fixture test
(tests/test_console.py) can assert the full summary byte-for-byte.
Sections degrade independently: evidence a given episode never produced
(no autoscaler, no chaos, no control-plane probes) renders as an
explicit ``none`` line rather than vanishing, so an operator can tell
"feature idle" from "dump missing".
"""
from __future__ import annotations

from .sources import Episode

__all__ = ["render", "summary_lines"]

# Flight kinds that narrate the membership story, in the order the
# fleetsim harness emits them (vrank.py / harness.py).
_MEMBERSHIP_KINDS = (
    "fleet-start", "join-announce", "join-entered", "preempt-notice",
    "departed", "fleet-vkill", "fleet-desync", "fleet-step-fail",
    "grow", "shrink", "autoscale", "fleet-end",
)


def _fmt(value) -> str:
    if isinstance(value, float):
        return f"{value:.1f}"
    return str(value)


def _summary(ep: Episode) -> dict | None:
    """The lowest-rank fleetsim summary (normally there is one)."""
    best = None
    for payload in ep.summaries:
        rank = payload.get("rank", 0)
        if best is None or rank < best[0]:
            best = (rank, payload["fleetsim_summary"])
    return best[1] if best else None


def _metric_entries(ep: Episode, name: str) -> list[dict]:
    out = []
    for snap in ep.metrics:
        for entry in snap.get("metrics", ()):
            if entry.get("name") == name:
                out.append(entry)
    return out


def _counter_by_label(ep: Episode, name: str, label: str) -> dict:
    folded: dict[str, float] = {}
    for entry in _metric_entries(ep, name):
        key = entry.get("labels", {}).get(label, "")
        folded[key] = folded.get(key, 0.0) + float(entry.get("value", 0))
    return folded


def _counter_total(ep: Episode, name: str) -> float:
    return sum(float(e.get("value", 0))
               for e in _metric_entries(ep, name))


def _membership_events(ep: Episode) -> list[tuple[float, int, dict]]:
    """Merge membership-narrative flight events across ranks, on each
    dump's own relative clock (monotonic clocks don't compare across
    processes)."""
    merged = []
    for dump in ep.flights:
        events = dump.get("events", ())
        if not events:
            continue
        t0 = min(e.get("ts", 0.0) for e in events)
        rank = dump.get("rank", 0)
        for e in events:
            if e.get("kind") in _MEMBERSHIP_KINDS:
                merged.append((round(e.get("ts", 0.0) - t0, 3), rank, e))
    merged.sort(key=lambda item: (item[0], item[1],
                                  item[2].get("kind", ""),
                                  item[2].get("name", "")))
    return merged


def _role_timeline(ep: Episode) -> tuple[list[dict], list[str]]:
    """(all probes time-ordered, distinct primaries first-seen)."""
    probes = []
    for dump in ep.ctl_roles:
        probes.extend(dump.get("probes", ()))
    probes.sort(key=lambda p: (p.get("t", 0.0), p.get("endpoint", "")))
    primaries = []
    for p in probes:
        if str(p.get("role", "")).startswith("primary") \
                and p.get("endpoint") not in primaries:
            primaries.append(p["endpoint"])
    return probes, primaries


def _transitions(probes: list[dict]) -> list[str]:
    """Role-change edges per endpoint (the promotion/demotion story)."""
    last: dict[str, str] = {}
    edges = []
    for p in probes:
        endpoint = p.get("endpoint", "?")
        role = str(p.get("role", "?")).split("|")[0]
        if last.get(endpoint) not in (None, role):
            edges.append(f"t={_fmt(p.get('t', 0.0))}s {endpoint}: "
                         f"{last[endpoint]} -> {role}")
        last[endpoint] = role
    return edges


# ---------------------------------------------------------------------------
# Sections
# ---------------------------------------------------------------------------
def _sec_fleet(ep: Episode, lines: list[str]) -> None:
    s = _summary(ep)
    lines.append("== fleet ==")
    if s is None:
        world = _metric_entries(ep, "horovod_fleetsim_world_size")
        steps = _counter_total(ep, "horovod_fleetsim_steps_total")
        if world or steps:
            size = max((e.get("value", 0) for e in world), default=0)
            lines.append(f"world={int(size)} rank_steps={int(steps)} "
                         "(no summary dump)")
        else:
            lines.append("no fleet summary")
        return
    lines.append(f"ranks={s['ranks']} steps={s['steps']} "
                 f"rank_steps={s['total_rank_steps']} "
                 f"failed_steps={s['failed_steps']}")
    outcomes = " ".join(f"{k}={v}"
                        for k, v in sorted(s["outcomes"].items()))
    lines.append(f"outcomes: {outcomes or 'none'}")
    world = s.get("final_world", [])
    shown = ",".join(map(str, world[:16]))
    more = f" (+{len(world) - 16} more)" if len(world) > 16 else ""
    lines.append(f"final_world[{len(world)}]: {shown}{more}")


def _sec_controlplane(ep: Episode, lines: list[str], topk: int) -> None:
    lines.append("== control plane ==")
    probes, primaries = _role_timeline(ep)
    if not probes:
        lines.append("role probes: none")
    else:
        lines.append(f"role probes: {len(probes)}  "
                     f"primaries: {','.join(primaries) or 'none'}  "
                     f"failovers: {max(len(primaries) - 1, 0)}")
        for edge in _transitions(probes)[:topk]:
            lines.append(f"  {edge}")
    batches = _counter_total(
        ep, "horovod_rendezvous_wal_commit_batches_total")
    records = _counter_total(ep, "horovod_rendezvous_wal_records_total")
    if records:
        ratio = records / batches if batches else 0.0
        lines.append(f"wal: records={int(records)} "
                     f"fsync_batches={int(batches)} "
                     f"coalescing=x{ratio:.1f}")
    else:
        lines.append("wal: no counters (server ran out of process)")


def _sec_membership(ep: Episode, lines: list[str], topk: int) -> None:
    lines.append("== membership ==")
    s = _summary(ep)
    if s is not None:
        departures = " ".join(f"{k}={v}" for k, v
                              in sorted(s["departures"].items()))
        lines.append(f"transitions={s['transitions']} "
                     f"joins={s['joins']} "
                     f"departures: {departures or 'none'}")
    events = _membership_events(ep)
    if not events:
        lines.append("flight events: none")
        return
    shown = events if len(events) <= 2 * topk \
        else events[:topk] + events[-topk:]
    for t, rank, e in shown:
        detail = f" {e['detail']}" if e.get("detail") else ""
        lines.append(f"  [r{rank} +{t:.3f}s] {e['kind']} "
                     f"{e.get('name', '')}{detail}")
    if len(events) > len(shown):
        lines.append(f"  ... {len(events) - len(shown)} more events")


def _sec_straggler(ep: Episode, lines: list[str]) -> None:
    lines.append("== straggler ==")
    s = _summary(ep)
    rank = lag = None
    if s is not None:
        rank, lag = s.get("straggler_rank"), s.get("straggler_lag_ms")
    else:
        for e in _metric_entries(ep, "horovod_controller_straggler_rank"):
            rank = int(e.get("value", -1))
        for e in _metric_entries(
                ep, "horovod_controller_straggler_lag_ms"):
            lag = e.get("value")
    if rank is None or rank < 0:
        lines.append("none flagged")
        return
    lines.append(f"rank={rank} lag_ms={_fmt(lag or 0.0)}")
    stats = {e["labels"].get("stat", ""): e.get("value", 0.0)
             for e in _metric_entries(
                 ep, "horovod_controller_negotiation_lag_ms")}
    if stats:
        lines.append("negotiation lag: "
                     + " ".join(f"{k}={_fmt(v)}"
                                for k, v in sorted(stats.items())))


def _sec_autoscale(ep: Episode, lines: list[str], topk: int) -> None:
    lines.append("== autoscale ==")
    s = _summary(ep)
    decisions = (s or {}).get("autoscale_decisions") or []
    if decisions:
        for d in decisions[:topk]:
            lines.append(f"  {d}")
        if len(decisions) > topk:
            lines.append(f"  ... {len(decisions) - topk} more")
        return
    by_dir = _counter_by_label(ep, "horovod_autoscale_decisions_total",
                               "direction")
    if by_dir:
        lines.append("decisions: "
                     + " ".join(f"{k}={int(v)}"
                                for k, v in sorted(by_dir.items())))
    else:
        lines.append("no decisions")


def _sec_kv(ep: Episode, lines: list[str]) -> None:
    lines.append("== rendezvous kv latency (ms) ==")
    s = _summary(ep)
    if s is not None and s.get("kv_latency_ms"):
        table = s["kv_latency_ms"]
    else:
        table = {}
        for e in _metric_entries(ep,
                                 "horovod_rendezvous_kv_latency_ms"):
            verb = e.get("labels", {}).get("verb", "?")
            table[verb] = {"count": e.get("count", 0),
                           "p50": e.get("p50", 0.0),
                           "p99": e.get("p99", 0.0)}
    if not table:
        lines.append("no kv traffic observed")
        return
    lines.append(f"  {'verb':<10} {'count':>7} {'p50':>9} {'p99':>9}")
    for verb in sorted(table):
        row = table[verb]
        lines.append(f"  {verb:<10} {row['count']:>7} "
                     f"{row['p50']:>9.1f} {row['p99']:>9.1f}")


def _sec_perf(ep: Episode, lines: list[str], topk: int) -> None:
    """perfscope panel (ISSUE 19): the roofline-attributed busbw cells
    and the train/serve step ledger, built from whatever metric evidence
    the episode carries — full histograms from dumps, count/sum-only
    snapshots from a live scrape (the p50 column degrades to 0 there)."""
    lines.append("== perf ==")
    from ..telemetry import perfmodel
    ledger = perfmodel.build_ledger(ep.metrics)
    if not ledger.get("busbw") and not ledger.get("step"):
        lines.append("no busbw/MFU evidence (HOROVOD_METRICS off, or "
                     "no collectives executed)")
        return
    lines.extend(perfmodel.ledger_summary(ledger, top=topk))


def _sec_admission(ep: Episode, lines: list[str]) -> None:
    lines.append("== admission ==")
    outcomes = _counter_by_label(ep, "horovod_serve_requests_total",
                                 "outcome")
    if not outcomes:
        lines.append("no admission traffic")
        return
    lines.append(" ".join(f"{k}={int(v)}"
                          for k, v in sorted(outcomes.items())))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def render(ep: Episode, topk: int = 8) -> str:
    """The full console view; one deterministic string."""
    if ep.empty:
        return (f"horovod_tpu console: no episode evidence in "
                f"{ep.source!r}\n(expected flight/metrics/ctl_roles/"
                "summary dumps, or reachable scrape endpoints)\n")
    lines = [f"horovod_tpu operator console — {ep.source}",
             f"dumps: flight={len(ep.flights)} "
             f"metrics={len(ep.metrics)} ctl={len(ep.ctl_roles)} "
             f"summary={len(ep.summaries)} "
             f"skipped={len(ep.skipped)}"]
    _sec_fleet(ep, lines)
    _sec_controlplane(ep, lines, topk)
    _sec_membership(ep, lines, topk)
    _sec_straggler(ep, lines)
    _sec_autoscale(ep, lines, topk)
    _sec_kv(ep, lines)
    _sec_admission(ep, lines)
    _sec_perf(ep, lines, topk)
    return "\n".join(lines) + "\n"


def summary_lines(ep: Episode) -> list[str]:
    """The compact golden-testable episode summary: what happened, in
    order, with the numbers that decide pass/fail."""
    if ep.empty:
        return ["empty episode"]
    out = []
    s = _summary(ep)
    if s is not None:
        out.append(f"fleet ranks={s['ranks']} steps={s['steps']} "
                   f"rank_steps={s['total_rank_steps']} "
                   f"failed={s['failed_steps']}")
        out.append("outcomes "
                   + " ".join(f"{k}={v}" for k, v
                              in sorted(s["outcomes"].items())))
        departures = " ".join(f"{k}={v}" for k, v
                              in sorted(s["departures"].items()))
        out.append(f"membership transitions={s['transitions']} "
                   f"joins={s['joins']} "
                   f"departures {departures or 'none'}")
        out.append(f"straggler rank={s['straggler_rank']}")
    primaries = _role_timeline(ep)[1]
    out.append(f"controlplane primaries={len(primaries)} "
               f"failovers={max(len(primaries) - 1, 0)}")
    events = _membership_events(ep)
    kinds: dict[str, int] = {}
    for _t, _r, e in events:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    out.append("events "
               + (" ".join(f"{k}={v}" for k, v in sorted(kinds.items()))
                  or "none"))
    return out
