"""Deterministic text rendering of one episode's evidence.

The renderer is a pure function of the :class:`~.sources.Episode` —
same files in, same characters out — so the golden-fixture test
(tests/test_console.py) can assert the full summary byte-for-byte.
Sections degrade independently: evidence a given episode never produced
(no autoscaler, no chaos, no control-plane probes) renders as an
explicit ``none`` line rather than vanishing, so an operator can tell
"feature idle" from "dump missing".
"""
from __future__ import annotations

import json
import re

from .sources import Episode

__all__ = ["render", "summary_lines"]

# Flight kinds that narrate the membership story, in the order the
# fleetsim harness emits them (vrank.py / harness.py).
_MEMBERSHIP_KINDS = (
    "fleet-start", "join-announce", "join-entered", "preempt-notice",
    "departed", "fleet-vkill", "fleet-desync", "fleet-step-fail",
    "grow", "shrink", "autoscale", "fleet-end",
)


def _fmt(value) -> str:
    if isinstance(value, float):
        return f"{value:.1f}"
    return str(value)


def _summary(ep: Episode) -> dict | None:
    """The lowest-rank fleetsim summary (normally there is one)."""
    best = None
    for payload in ep.summaries:
        rank = payload.get("rank", 0)
        if best is None or rank < best[0]:
            best = (rank, payload["fleetsim_summary"])
    return best[1] if best else None


def _metric_entries(ep: Episode, name: str) -> list[dict]:
    out = []
    for snap in ep.metrics:
        for entry in snap.get("metrics", ()):
            if entry.get("name") == name:
                out.append(entry)
    return out


def _counter_by_label(ep: Episode, name: str, label: str) -> dict:
    folded: dict[str, float] = {}
    for entry in _metric_entries(ep, name):
        key = entry.get("labels", {}).get(label, "")
        folded[key] = folded.get(key, 0.0) + float(entry.get("value", 0))
    return folded


def _counter_total(ep: Episode, name: str) -> float:
    return sum(float(e.get("value", 0))
               for e in _metric_entries(ep, name))


def _membership_events(ep: Episode,
                       kinds=_MEMBERSHIP_KINDS
                       ) -> list[tuple[float, int, dict]]:
    """Merge narrative flight events across ranks, on each dump's own
    relative clock (monotonic clocks don't compare across
    processes)."""
    merged = []
    for dump in ep.flights:
        events = dump.get("events", ())
        if not events:
            continue
        t0 = min(e.get("ts", 0.0) for e in events)
        rank = dump.get("rank", 0)
        for e in events:
            if e.get("kind") in kinds:
                merged.append((round(e.get("ts", 0.0) - t0, 3), rank, e))
    merged.sort(key=lambda item: (item[0], item[1],
                                  item[2].get("kind", ""),
                                  item[2].get("name", "")))
    return merged


def _count(value) -> int:
    """Loadgen world fields carry either a count or the list of
    transition records; the panel wants the count."""
    if isinstance(value, (list, tuple)):
        return len(value)
    try:
        return int(value)
    except (TypeError, ValueError):
        return 0


def _fleet_version(name) -> int | None:
    """``v{n}`` flight-event names (fleet-publish / -pull / -swap)."""
    try:
        return int(str(name).lstrip("v"))
    except ValueError:
        return None


def _fleet_evidence(ep: Episode) -> dict:
    """Everything the fleet subsystem (fleet/controller.py +
    fleet/deploy.py) left behind: migration-journal flight events, the
    weight-deployment events, and the serving front's loadgen report."""
    migration = _membership_events(
        ep, kinds=("fleet-migrate", "fleet-depart", "fleet-join"))
    pubs = _membership_events(ep, kinds=("fleet-publish",))
    pulls = _membership_events(ep, kinds=("fleet-pull",))
    swaps = _membership_events(ep, kinds=("fleet-swap",))
    fronts = sorted((r for r in ep.serve_reports
                     if r.get("rank", 0) == 0),
                    key=lambda r: r.get("_file", ""))
    mids = sorted({e.get("name", "?") for _t, _r, e in migration
                   if e.get("kind") == "fleet-migrate"})
    outcomes: dict[str, int] = {}
    for _t, _r, e in migration:
        if e.get("kind") == "fleet-migrate":
            what = str(e.get("detail", "?")).split(" ", 1)[0]
            outcomes[what] = outcomes.get(what, 0) + 1
    head = max((v for v in (_fleet_version(e.get("name"))
                            for _t, _r, e in pubs) if v is not None),
               default=None)
    front: dict[int, int] = {}
    for _t, rank, e in swaps:
        v = _fleet_version(e.get("name"))
        if v is not None:
            front[rank] = max(front.get(rank, 0), v)
    return {"migration": migration, "pubs": pubs, "pulls": pulls,
            "swaps": swaps, "fronts": fronts, "mids": mids,
            "outcomes": outcomes, "head": head, "front": front}


def _role_timeline(ep: Episode) -> tuple[list[dict], list[str]]:
    """(all probes time-ordered, distinct primaries first-seen)."""
    probes = []
    for dump in ep.ctl_roles:
        probes.extend(dump.get("probes", ()))
    probes.sort(key=lambda p: (p.get("t", 0.0), p.get("endpoint", "")))
    primaries = []
    for p in probes:
        if str(p.get("role", "")).startswith("primary") \
                and p.get("endpoint") not in primaries:
            primaries.append(p["endpoint"])
    return probes, primaries


def _transitions(probes: list[dict]) -> list[str]:
    """Role-change edges per endpoint (the promotion/demotion story)."""
    last: dict[str, str] = {}
    edges = []
    for p in probes:
        endpoint = p.get("endpoint", "?")
        role = str(p.get("role", "?")).split("|")[0]
        if last.get(endpoint) not in (None, role):
            edges.append(f"t={_fmt(p.get('t', 0.0))}s {endpoint}: "
                         f"{last[endpoint]} -> {role}")
        last[endpoint] = role
    return edges


# ---------------------------------------------------------------------------
# Sections
# ---------------------------------------------------------------------------
def _sec_fleet(ep: Episode, lines: list[str], topk: int = 8) -> None:
    s = _summary(ep)
    lines.append("== fleet ==")
    if s is None:
        world = _metric_entries(ep, "horovod_fleetsim_world_size")
        steps = _counter_total(ep, "horovod_fleetsim_steps_total")
        if world or steps:
            size = max((e.get("value", 0) for e in world), default=0)
            lines.append(f"world={int(size)} rank_steps={int(steps)} "
                         "(no summary dump)")
        else:
            lines.append("no fleet summary")
        _sec_fleetctl(ep, lines, topk)
        return
    lines.append(f"ranks={s['ranks']} steps={s['steps']} "
                 f"rank_steps={s['total_rank_steps']} "
                 f"failed_steps={s['failed_steps']}")
    outcomes = " ".join(f"{k}={v}"
                        for k, v in sorted(s["outcomes"].items()))
    lines.append(f"outcomes: {outcomes or 'none'}")
    world = s.get("final_world", [])
    shown = ",".join(map(str, world[:16]))
    more = f" (+{len(world) - 16} more)" if len(world) > 16 else ""
    lines.append(f"final_world[{len(world)}]: {shown}{more}")
    _sec_fleetctl(ep, lines, topk)


def _world_counts(ev: dict) -> list[str]:
    """Per-world rank counts across each completed migration: the
    donor's side from the journal record (its departing rank was
    ``size - 1``), the destination's from the mover's joined mark."""
    joins = {e.get("name"): e for _t, _r, e in ev["migration"]
             if e.get("kind") == "fleet-join"}
    moves = []
    for _t, _r, e in ev["migration"]:
        if e.get("kind") != "fleet-migrate":
            continue
        detail = str(e.get("detail", ""))
        if not detail.startswith("done "):
            continue
        m = re.search(r"(\w+)->(\w+) rank=(\d+)", detail)
        if m is None:
            continue
        donor, dest, rank = m.group(1), m.group(2), int(m.group(3))
        dest_part = dest
        joined = joins.get(e.get("name"))
        if joined is not None:
            try:
                size = int(json.loads(
                    joined.get("detail", "{}")).get("size"))
                dest_part = f"{dest} {size - 1}->{size}"
            except (TypeError, ValueError):
                pass
        moves.append(f"{e.get('name', '?')} {donor} "
                     f"{rank + 1}->{rank}, {dest_part}")
    return moves


def _sec_fleetctl(ep: Episode, lines: list[str], topk: int) -> None:
    """The train+serve controller story (fleet/, docs/fleet.md):
    migration-journal timeline, weight-rollout front, and the serving
    front's goodput phases — everything an operator needs to answer
    "did the move land, and did the push reach every replica"."""
    ev = _fleet_evidence(ep)
    if not (ev["migration"] or ev["pubs"] or ev["swaps"]
            or ev["fronts"]):
        lines.append("controller: no migrations / rollouts")
        return
    outcomes = " ".join(f"{k}={v}"
                        for k, v in sorted(ev["outcomes"].items()))
    lines.append(f"migrations: {len(ev['mids'])} "
                 f"({outcomes or 'no journal events'})")
    shown = ev["migration"][:topk]
    for t, rank, e in shown:
        detail = f" {e['detail']}" if e.get("detail") else ""
        lines.append(f"  [r{rank} +{t:.3f}s] {e['kind']} "
                     f"{e.get('name', '')}{detail}")
    if len(ev["migration"]) > len(shown):
        lines.append(f"  ... {len(ev['migration']) - len(shown)} "
                     "more events")
    for move in _world_counts(ev)[:topk]:
        lines.append(f"world counts: {move}")
    if ev["pubs"] or ev["swaps"]:
        head = f"v{ev['head']}" if ev["head"] is not None else "?"
        front = " ".join(f"r{r}=v{v}"
                         for r, v in sorted(ev["front"].items()))
        lines.append(f"rollout: published={len(ev['pubs'])} "
                     f"head={head} pulled={len(ev['pulls'])}; "
                     f"swap front: {front or 'none'}")
    else:
        lines.append("rollout: none published")
    for rep in ev["fronts"]:
        world = rep.get("world", {})
        lines.append(f"serve world: size={world.get('size', '?')} "
                     f"grows={_count(world.get('grows', 0))} "
                     f"shrinks={_count(world.get('shrinks', 0))} "
                     f"offered={rep.get('offered', 0)} "
                     f"served={rep.get('served', 0)} "
                     f"lost={rep.get('lost_on_failure', 0)}")
        phases = rep.get("goodput_phases")
        if phases:
            lines.append("goodput phases: " + " ".join(
                f"{key}={_fmt(phases.get(key, 0.0))}"
                for key in ("before_rps", "during_rps", "after_rps",
                            "window_s")))
        weights = rep.get("weights")
        if weights:
            mix = " ".join(
                f"v{k}={v}" for k, v
                in sorted(weights.get("versions", {}).items(),
                          key=lambda kv: str(kv[0])))
            lines.append(
                f"weights: final=v{weights.get('final_version', 0)} "
                f"mix {mix or 'none'} max_staleness="
                f"{weights.get('max_staleness_steps', 0)} steps")


def _sec_controlplane(ep: Episode, lines: list[str], topk: int) -> None:
    lines.append("== control plane ==")
    probes, primaries = _role_timeline(ep)
    if not probes:
        lines.append("role probes: none")
    else:
        lines.append(f"role probes: {len(probes)}  "
                     f"primaries: {','.join(primaries) or 'none'}  "
                     f"failovers: {max(len(primaries) - 1, 0)}")
        for edge in _transitions(probes)[:topk]:
            lines.append(f"  {edge}")
    batches = _counter_total(
        ep, "horovod_rendezvous_wal_commit_batches_total")
    records = _counter_total(ep, "horovod_rendezvous_wal_records_total")
    if records:
        ratio = records / batches if batches else 0.0
        lines.append(f"wal: records={int(records)} "
                     f"fsync_batches={int(batches)} "
                     f"coalescing=x{ratio:.1f}")
    else:
        lines.append("wal: no counters (server ran out of process)")


def _sec_membership(ep: Episode, lines: list[str], topk: int) -> None:
    lines.append("== membership ==")
    s = _summary(ep)
    if s is not None:
        departures = " ".join(f"{k}={v}" for k, v
                              in sorted(s["departures"].items()))
        lines.append(f"transitions={s['transitions']} "
                     f"joins={s['joins']} "
                     f"departures: {departures or 'none'}")
    events = _membership_events(ep)
    if not events:
        lines.append("flight events: none")
        return
    shown = events if len(events) <= 2 * topk \
        else events[:topk] + events[-topk:]
    for t, rank, e in shown:
        detail = f" {e['detail']}" if e.get("detail") else ""
        lines.append(f"  [r{rank} +{t:.3f}s] {e['kind']} "
                     f"{e.get('name', '')}{detail}")
    if len(events) > len(shown):
        lines.append(f"  ... {len(events) - len(shown)} more events")


def _sec_straggler(ep: Episode, lines: list[str]) -> None:
    lines.append("== straggler ==")
    s = _summary(ep)
    rank = lag = None
    if s is not None:
        rank, lag = s.get("straggler_rank"), s.get("straggler_lag_ms")
    else:
        for e in _metric_entries(ep, "horovod_controller_straggler_rank"):
            rank = int(e.get("value", -1))
        for e in _metric_entries(
                ep, "horovod_controller_straggler_lag_ms"):
            lag = e.get("value")
    if rank is None or rank < 0:
        lines.append("none flagged")
        return
    lines.append(f"rank={rank} lag_ms={_fmt(lag or 0.0)}")
    stats = {e["labels"].get("stat", ""): e.get("value", 0.0)
             for e in _metric_entries(
                 ep, "horovod_controller_negotiation_lag_ms")}
    if stats:
        lines.append("negotiation lag: "
                     + " ".join(f"{k}={_fmt(v)}"
                                for k, v in sorted(stats.items())))


def _sec_autoscale(ep: Episode, lines: list[str], topk: int) -> None:
    lines.append("== autoscale ==")
    s = _summary(ep)
    decisions = (s or {}).get("autoscale_decisions") or []
    if decisions:
        for d in decisions[:topk]:
            lines.append(f"  {d}")
        if len(decisions) > topk:
            lines.append(f"  ... {len(decisions) - topk} more")
        return
    by_dir = _counter_by_label(ep, "horovod_autoscale_decisions_total",
                               "direction")
    if by_dir:
        lines.append("decisions: "
                     + " ".join(f"{k}={int(v)}"
                                for k, v in sorted(by_dir.items())))
    else:
        lines.append("no decisions")


def _sec_kv(ep: Episode, lines: list[str]) -> None:
    lines.append("== rendezvous kv latency (ms) ==")
    s = _summary(ep)
    if s is not None and s.get("kv_latency_ms"):
        table = s["kv_latency_ms"]
    else:
        table = {}
        for e in _metric_entries(ep,
                                 "horovod_rendezvous_kv_latency_ms"):
            verb = e.get("labels", {}).get("verb", "?")
            table[verb] = {"count": e.get("count", 0),
                           "p50": e.get("p50", 0.0),
                           "p99": e.get("p99", 0.0)}
    if not table:
        lines.append("no kv traffic observed")
        return
    lines.append(f"  {'verb':<10} {'count':>7} {'p50':>9} {'p99':>9}")
    for verb in sorted(table):
        row = table[verb]
        lines.append(f"  {verb:<10} {row['count']:>7} "
                     f"{row['p50']:>9.1f} {row['p99']:>9.1f}")


def _sec_perf(ep: Episode, lines: list[str], topk: int) -> None:
    """perfscope panel (ISSUE 19): the roofline-attributed busbw cells
    and the train/serve step ledger, built from whatever metric evidence
    the episode carries — full histograms from dumps, count/sum-only
    snapshots from a live scrape (the p50 column degrades to 0 there)."""
    lines.append("== perf ==")
    from ..telemetry import perfmodel
    ledger = perfmodel.build_ledger(ep.metrics)
    if not ledger.get("busbw") and not ledger.get("step"):
        lines.append("no busbw/MFU evidence (HOROVOD_METRICS off, or "
                     "no collectives executed)")
        return
    lines.extend(perfmodel.ledger_summary(ledger, top=topk))


def _sec_admission(ep: Episode, lines: list[str]) -> None:
    lines.append("== admission ==")
    outcomes = _counter_by_label(ep, "horovod_serve_requests_total",
                                 "outcome")
    if not outcomes:
        lines.append("no admission traffic")
        return
    lines.append(" ".join(f"{k}={int(v)}"
                          for k, v in sorted(outcomes.items())))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def render(ep: Episode, topk: int = 8) -> str:
    """The full console view; one deterministic string."""
    if ep.empty:
        return (f"horovod_tpu console: no episode evidence in "
                f"{ep.source!r}\n(expected flight/metrics/ctl_roles/"
                "summary dumps, or reachable scrape endpoints)\n")
    lines = [f"horovod_tpu operator console — {ep.source}",
             f"dumps: flight={len(ep.flights)} "
             f"metrics={len(ep.metrics)} ctl={len(ep.ctl_roles)} "
             f"summary={len(ep.summaries)} "
             f"skipped={len(ep.skipped)}"]
    _sec_fleet(ep, lines, topk)
    _sec_controlplane(ep, lines, topk)
    _sec_membership(ep, lines, topk)
    _sec_straggler(ep, lines)
    _sec_autoscale(ep, lines, topk)
    _sec_kv(ep, lines)
    _sec_admission(ep, lines)
    _sec_perf(ep, lines, topk)
    return "\n".join(lines) + "\n"


def summary_lines(ep: Episode) -> list[str]:
    """The compact golden-testable episode summary: what happened, in
    order, with the numbers that decide pass/fail."""
    if ep.empty:
        return ["empty episode"]
    out = []
    s = _summary(ep)
    if s is not None:
        out.append(f"fleet ranks={s['ranks']} steps={s['steps']} "
                   f"rank_steps={s['total_rank_steps']} "
                   f"failed={s['failed_steps']}")
        out.append("outcomes "
                   + " ".join(f"{k}={v}" for k, v
                              in sorted(s["outcomes"].items())))
        departures = " ".join(f"{k}={v}" for k, v
                              in sorted(s["departures"].items()))
        out.append(f"membership transitions={s['transitions']} "
                   f"joins={s['joins']} "
                   f"departures {departures or 'none'}")
        out.append(f"straggler rank={s['straggler_rank']}")
    primaries = _role_timeline(ep)[1]
    out.append(f"controlplane primaries={len(primaries)} "
               f"failovers={max(len(primaries) - 1, 0)}")
    events = _membership_events(ep)
    kinds: dict[str, int] = {}
    for _t, _r, e in events:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1
    out.append("events "
               + (" ".join(f"{k}={v}" for k, v in sorted(kinds.items()))
                  or "none"))
    ev = _fleet_evidence(ep)
    if ev["migration"] or ev["pubs"] or ev["swaps"] or ev["fronts"]:
        outcomes = " ".join(f"{k}={v}" for k, v
                            in sorted(ev["outcomes"].items()))
        out.append(f"fleetctl migrations={len(ev['mids'])} "
                   + (outcomes or "none"))
        head = f"v{ev['head']}" if ev["head"] is not None else "?"
        front = " ".join(f"r{r}=v{v}"
                         for r, v in sorted(ev["front"].items()))
        out.append(f"rollout published={len(ev['pubs'])} head={head} "
                   f"pulled={len(ev['pulls'])} "
                   f"front {front or 'none'}")
        for rep in ev["fronts"]:
            weights = rep.get("weights") or {}
            world = rep.get("world", {})
            out.append(f"serve size={world.get('size', '?')} "
                       f"grows={_count(world.get('grows', 0))} "
                       f"offered={rep.get('offered', 0)} "
                       f"served={rep.get('served', 0)} "
                       f"lost={rep.get('lost_on_failure', 0)} "
                       f"final=v{weights.get('final_version', 0)}")
    return out
