"""console — the live/post-hoc operator view of a fleet.

``python -m horovod_tpu.console --dumps DIR`` replays a finished (or
crashed) episode from its rank-stamped evidence — flight rings, metrics
snapshots, ``/.ctl`` role probes, fleetsim summaries — and ``--scrape``
/ ``--ctl`` fuse the same view live from each rank's Prometheus
exporter and the rendezvous replicas' control endpoints.  One fused
screen answers the first three incident questions: who is primary, who
left the fleet and why, and where the time is going (straggler +
rendezvous-KV verb latency).  See docs/observability.md.
"""
from .render import render, summary_lines
from .sources import Episode, live_snapshot, load_dump_dir

__all__ = ["Episode", "live_snapshot", "load_dump_dir", "render",
           "summary_lines"]
