"""CLI: ``python -m horovod_tpu.console``.

Post-hoc:  ``--dumps DIR`` renders a recorded episode once.
Live:      ``--scrape host:port,...`` (metrics exporters) and/or
           ``--ctl host:port,...`` (rendezvous replicas) render one
           scrape pass; add ``--watch`` to refresh every
           HOROVOD_CONSOLE_REFRESH_S seconds until interrupted.
``--summary`` prints the compact golden-testable lines instead of the
full view (what tests/test_console.py pins).
"""
from __future__ import annotations

import argparse
import sys
import time

from ..common import config
from .render import render, summary_lines
from .sources import live_snapshot, load_dump_dir


def _split(raw: str) -> list[str]:
    return [part.strip() for part in raw.split(",") if part.strip()]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m horovod_tpu.console",
        description="operator console: fused fleet view from dumps or "
                    "live scrapes")
    parser.add_argument("--dumps", default="",
                        help="directory of rank-stamped episode dumps "
                             "(post-hoc mode)")
    parser.add_argument("--scrape", default="",
                        help="comma-separated metrics-exporter "
                             "endpoints (live mode)")
    parser.add_argument("--ctl", default="",
                        help="comma-separated rendezvous replica "
                             "endpoints for /.ctl/role probes")
    parser.add_argument("--watch", action="store_true",
                        help="live mode: refresh until interrupted")
    parser.add_argument("--refresh", type=float,
                        default=config.CONSOLE_REFRESH_S.get(),
                        help="watch refresh period in seconds")
    parser.add_argument("--topk", type=int,
                        default=config.CONSOLE_TOPK.get(),
                        help="rows per truncated section")
    parser.add_argument("--summary", action="store_true",
                        help="print the compact episode summary only")
    args = parser.parse_args(argv)

    scrape = _split(args.scrape)
    ctl = _split(args.ctl)
    if not args.dumps and not scrape and not ctl:
        parser.error("one of --dumps or --scrape/--ctl is required")
    if args.dumps and args.watch:
        parser.error("--watch is for live mode; --dumps renders once")

    def _load():
        if args.dumps:
            return load_dump_dir(args.dumps)
        return live_snapshot(scrape, ctl)

    def _show(ep) -> None:
        if args.summary:
            print("\n".join(summary_lines(ep)))
        else:
            print(render(ep, topk=args.topk), end="")

    episode = _load()
    if not args.watch:
        _show(episode)
        return 0 if not episode.empty else 1
    try:
        while True:
            # ANSI home+clear keeps the view in place like `watch(1)`.
            sys.stdout.write("\x1b[H\x1b[2J")
            _show(episode)
            time.sleep(max(args.refresh, 0.2))
            episode = _load()
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
