"""hvdflow tests (analysis/hvdflow/): interprocedural rank-divergence
dataflow — effect summaries, the taint engine (sources, propagation,
sanitizers, world-symmetric names), HVD601-604 on the seeded fixtures,
suppressions, the CLI and the lint --flow driver integration."""
import ast
import json
import os
import subprocess
import sys

from horovod_tpu.analysis.hvdflow.flow import (FLOW_RULE_IDS,
                                               FlowProgram, analyze_flow,
                                               analyze_paths)
from horovod_tpu.analysis.hvdflow.flow import main as flow_main
from horovod_tpu.analysis.hvdsan.lockgraph import Program
from horovod_tpu.analysis.lint import LintConfig, lint_paths_timed

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TREE = os.path.join(REPO, "horovod_tpu")
FLOW = os.path.join(REPO, "tests", "fixtures", "lint", "flow")


def _analyze(src: str, path: str = "x.py"):
    program = Program()
    flow = FlowProgram()
    tree = ast.parse(src, filename=path)
    program.collect_source(path, src, tree)
    flow.collect_source(path, src, tree)
    return analyze_flow(program, flow)


def _rules(findings):
    return [f.rule.id for f in findings]


# --- seeded fixtures: flagged/clean pairs ------------------------------------
def test_fixture_divergent_flagged():
    out = analyze_paths([os.path.join(FLOW, "divergent.py")])
    assert _rules(out) == ["HVD601"] * 3
    assert {f.line for f in out} == {7, 25, 31}


def test_fixture_divergent_interprocedural_three_deep():
    """The collective is three calls below the gate — invisible to the
    per-line HVD101, named (with its stream) by hvdflow."""
    out = analyze_paths([os.path.join(FLOW, "divergent.py")])
    inter = next(f for f in out if f.line == 25)
    assert "allreduce(buried)" in inter.message
    assert "(empty)" in inter.message
    # the collective's real site rides along as an anchor
    assert any(ln == 13 for _p, ln in inter.sites)


def test_fixture_divergent_carries_fingerprint_diff():
    """Each HVD601 carries the would-be fingerprint stream of BOTH arms
    and pinpoints the first divergent op — the static twin of the
    runtime divergence ERROR's evidence."""
    out = analyze_paths([os.path.join(FLOW, "divergent.py")])
    arms = next(f for f in out if f.line == 31)
    assert "allreduce(even)" in arms.message
    assert "allgather(odd)" in arms.message
    assert "first divergent op #1" in arms.message


def test_fixture_divergent_clean_idioms():
    """rank-0-only logging, sequence-equal arms, branches on exchanged
    views and world-symmetric sizes all stay silent."""
    out = analyze_paths([os.path.join(FLOW, "divergent_clean.py")])
    assert out == [], "\n".join(f.text() for f in out)


def test_fixture_loop_trip_flagged_and_clean():
    out = analyze_paths([os.path.join(FLOW, "loop_trip.py")])
    assert _rules(out) == ["HVD602"] * 2
    assert {f.line for f in out} == {6, 12}
    assert analyze_paths([os.path.join(FLOW, "loop_trip_clean.py")]) == []


def test_fixture_serve_wait_flagged_and_clean():
    out = analyze_paths([os.path.join(FLOW, "serving",
                                      "serve_wait.py")])
    assert _rules(out) == ["HVD603"] * 2
    assert all("serve_loop" in f.message for f in out)
    assert any("'get'" in f.message for f in out)
    assert any("'recv'" in f.message for f in out)
    assert analyze_paths([os.path.join(FLOW, "serving",
                                       "serve_wait_clean.py")]) == []


def test_fixture_knob_read_flagged_and_clean():
    out = analyze_paths([os.path.join(FLOW, "knob_read.py")])
    assert _rules(out) == ["HVD604"] * 3
    assert {f.message.split("'")[1] for f in out} == {
        "HOROVOD_TOTALLY_UNDECLARED", "HOROVOD_ALSO_UNDECLARED",
        "HOROVOD_UNDECLARED_THREE"}
    assert analyze_paths([os.path.join(FLOW, "knob_read_clean.py")]) == []


def test_all_flow_fixtures_flagged_together():
    """Whole-directory walk (the CI shape): every seeded rule surfaces,
    the clean twins stay silent."""
    out = analyze_paths([FLOW])
    found = set(_rules(out))
    assert found == {"HVD601", "HVD602", "HVD603", "HVD604"}
    flagged_files = {os.path.basename(f.path) for f in out}
    assert not flagged_files & {"divergent_clean.py",
                                "loop_trip_clean.py",
                                "serve_wait_clean.py",
                                "knob_read_clean.py"}


# --- taint engine units ------------------------------------------------------
def test_taint_through_parameters():
    """A caller passing hvd.rank() taints the callee's parameter; the
    callee's gated collective is then flagged IN the callee."""
    src = ("import horovod_tpu as hvd\n"
           "def gated(t, who):\n"
           "    if who == 0:\n"
           "        hvd.allreduce(t, name='x')\n"
           "def caller(t):\n"
           "    gated(t, hvd.rank())\n")
    out = _analyze(src)
    assert _rules(out) == ["HVD601"]
    assert out[0].line == 3


def test_taint_through_returns():
    src = ("import horovod_tpu as hvd\n"
           "def my_rank():\n"
           "    return hvd.rank()\n"
           "def f(t):\n"
           "    r = my_rank()\n"
           "    if r == 0:\n"
           "        hvd.barrier()\n")
    out = _analyze(src)
    assert _rules(out) == ["HVD601"]
    assert out[0].line == 6


def test_collective_results_are_sanitizers():
    """allgather/broadcast results are identical on every rank:
    branching on them is the membership-agreement idiom, never a
    divergence."""
    src = ("import horovod_tpu as hvd\n"
           "def f(t):\n"
           "    views = hvd.allgather_object(hvd.rank(), name='v')\n"
           "    if max(views) > 2:\n"
           "        hvd.allreduce(t, name='agreed')\n")
    assert _analyze(src) == []


def test_world_symmetric_names_never_carry_taint():
    src = ("import horovod_tpu as hvd\n"
           "def world():\n"
           "    return hvd.rank(), 4\n"
           "def f(t):\n"
           "    rank, size = world()\n"
           "    if size > 1:\n"
           "        hvd.allreduce(t, name='multi')\n"
           "    if rank > 1:\n"
           "        hvd.allreduce(t, name='gated')\n")
    out = _analyze(src)
    assert _rules(out) == ["HVD601"]
    assert out[0].line == 8          # the rank gate, not the size gate


def test_rank_attribute_manifest_sources():
    src = ("import horovod_tpu as hvd\n"
           "def f(self, t):\n"
           "    if self._rank == 0:\n"
           "        hvd.allreduce(t, name='x')\n")
    assert _rules(_analyze(src)) == ["HVD601"]


def test_equal_arm_streams_are_legal():
    src = ("import horovod_tpu as hvd\n"
           "def f(t, rank):\n"
           "    if rank == 0:\n"
           "        hvd.allreduce(t, name='s')\n"
           "    else:\n"
           "        hvd.allreduce(t, name='s')\n")
    assert _analyze(src) == []


def test_suppression_at_branch_site_with_why():
    src = ("import horovod_tpu as hvd\n"
           "def f(t, rank):\n"
           "    if rank == 0:  # hvdlint: disable=HVD601 -- "
           "single-process tool, never negotiates\n"
           "        hvd.allreduce(t, name='x')\n")
    assert _analyze(src) == []


def test_hvd602_comprehension_loop():
    src = ("import horovod_tpu as hvd\n"
           "def f(t, rank):\n"
           "    return [hvd.allreduce(t, name='c')"
           " for _ in range(rank)]\n")
    assert _rules(_analyze(src)) == ["HVD602"]


# --- HVD603 specifics --------------------------------------------------------
def test_serve_wait_guard_anywhere_on_path_bounds():
    src = ("from horovod_tpu.resilience import deadline_scope\n"
           "def serve_loop(ch):\n"
           "    _leg(ch)\n"
           "def _leg(ch):\n"
           "    with deadline_scope(1.0):\n"
           "        _deep(ch)\n"
           "def _deep(ch):\n"
           "    return ch.recv()\n")
    assert _analyze(src, "horovod_tpu/serving/x.py") == []


def test_serve_wait_stops_at_world_formation_boundary():
    """reinit/init are governed by the gloo/fault-tolerance timeouts,
    not a request SLO: the walk must not descend into them."""
    out = analyze_paths([TREE])
    assert [f for f in out if f.rule.id == "HVD603"] == []


# --- HVD604 registry ---------------------------------------------------------
def test_knob_registry_covers_every_tree_read():
    """The tree itself performs no unregistered HOROVOD_* reads — the
    satellite that forced the 14 launcher/compat knobs into the typed
    registry."""
    out = analyze_paths([TREE])
    assert [f for f in out if f.rule.id == "HVD604"] == []


def test_knob_registry_declared_names_are_typed():
    from horovod_tpu.common import config
    knobs = config.all_knobs()
    assert len(knobs) >= 98
    for name, k in knobs.items():
        assert name.startswith("HOROVOD_")
        assert callable(k.parser)
        assert k.doc.strip(), f"{name} has no doc line"
    # The previously-unregistered family is now declared.
    for name in ("HOROVOD_RENDEZVOUS_EPOCH", "HOROVOD_GLOO_IFACE",
                 "HOROVOD_SECRET_KEY", "HOROVOD_DRIVER_ADDR",
                 "HOROVOD_SHM_BARRIER_TIMEOUT_SECONDS",
                 "HOROVOD_STREAMING_CE_MIN_ELEMENTS",
                 "HOROVOD_TPU_DISABLE_NATIVE"):
        assert name in knobs, name


# --- CLI + driver integration ------------------------------------------------
def test_cli_json(capsys):
    rc = flow_main([os.path.join(FLOW, "divergent.py"),
                    "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in payload["flow"]] == ["HVD601"] * 3
    assert payload["wall_ms"] > 0


def test_cli_clean_exit(capsys):
    rc = flow_main([os.path.join(FLOW, "divergent_clean.py")])
    capsys.readouterr()
    assert rc == 0


def test_cli_sarif(capsys):
    rc = flow_main([os.path.join(FLOW, "loop_trip.py"),
                    "--format", "sarif"])
    assert rc == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    results = sarif["runs"][0]["results"]
    assert [r["ruleId"] for r in results] == ["HVD602"] * 2
    assert all(r["level"] == "error" for r in results)


def test_cli_module_entrypoint():
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.hvdflow",
         os.path.join(FLOW, "knob_read.py"), "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert [f["rule"] for f in payload["flow"]] == ["HVD604"] * 3


def test_lint_driver_flow_rides_same_parse():
    """`lint --flow` runs hvdflow over the same single parse; findings
    carry the flow rule ids and respect --select/--ignore."""
    cfg = LintConfig()
    _v, findings, stats = lint_paths_timed(
        [os.path.join(FLOW, "divergent.py")], cfg, flow=True)
    assert [f.rule.id for f in findings] == ["HVD601"] * 3
    assert stats["files"] == 1
    cfg = LintConfig(ignore={"HVD601"})
    _v, findings, _s = lint_paths_timed(
        [os.path.join(FLOW, "divergent.py")], cfg, flow=True)
    assert findings == []


def test_flow_rule_ids_registered():
    from horovod_tpu.analysis.rules import RULES
    assert FLOW_RULE_IDS == {"HVD601", "HVD602", "HVD603", "HVD604"}
    for rid in FLOW_RULE_IDS:
        assert rid in RULES
    assert RULES["HVD601"].slug == "divergent-collective"
    assert RULES["HVD602"].slug == "divergent-loop-trip"
    assert RULES["HVD603"].slug == "unbounded-serve-wait"
    assert RULES["HVD604"].slug == "unregistered-knob-read"
