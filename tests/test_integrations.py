"""Programmatic run API, mpirun command builder, and the gated framework
integration surfaces (tensorflow/keras/mxnet/spark/ray)."""
from __future__ import annotations

import numpy as np
import pytest

from horovod_tpu.runner import mpi_run


# ---------------------------------------------------------------------------
# horovod_tpu.run()
# ---------------------------------------------------------------------------
def _allreduce_fn(scale):
    import numpy as np

    import horovod_tpu as hvd
    hvd.init()
    out = hvd.allreduce(np.ones(8, np.float32) * scale, average=False,
                        name="r")
    result = (hvd.rank(), hvd.size(), float(out[0]))
    hvd.shutdown()
    return result


def _failing_fn():
    import horovod_tpu as hvd
    hvd.init()
    if hvd.rank() == 1:
        raise RuntimeError("intentional worker failure")
    hvd.shutdown()
    return "ok"


class TestRunApi:
    def test_run_collects_rank_ordered_results(self):
        import horovod_tpu as hvd
        results = hvd.run(_allreduce_fn, args=(3.0,), np=2)
        assert [r[0] for r in results] == [0, 1]
        assert all(r[1] == 2 for r in results)
        assert all(r[2] == 6.0 for r in results)   # 2 ranks x 3.0

    def test_run_remote_hosts_via_ssh_path(self, monkeypatch):
        """Remote-host programmatic run (VERDICT r2 item 9; reference:
        runner/__init__.py:92-210): loopback aliases act as remote hosts
        and a local shell substitutes for the ssh binary (no sshd in CI),
        so the full remote codepath — env exports over the command line,
        pickled function over stdin, results through the rendezvous KV —
        is exercised end to end."""
        import os
        import horovod_tpu as hvd
        from horovod_tpu.runner import run_api

        monkeypatch.setattr(
            run_api, "_ssh_argv",
            lambda hostname, script: ["/bin/sh", "-c", script])
        tests_dir = os.path.dirname(os.path.abspath(__file__))
        repo = os.path.dirname(tests_dir)
        # Keep the conftest _cpusite shim first: this env REPLACES the
        # inherited PYTHONPATH on the exported remote command line, and
        # without the shim the workers would re-register any ambient
        # accelerator plugin despite JAX_PLATFORMS=cpu.
        shim = os.path.join(tests_dir, "_cpusite")
        env = {"PYTHONPATH": f"{shim}:{repo}:{tests_dir}",
               "JAX_PLATFORMS": "cpu"}
        # Non-loopback names: loopback aliases count as LOCAL everywhere
        # (runner.hosts.is_local_host), so the remote path needs real-
        # looking hostnames; the patched transport runs them locally.
        results = hvd.run(_allreduce_fn, args=(2.0,),
                          hosts="localhost:1,nodea:1,nodeb:1",
                          env=env)
        assert [r[0] for r in results] == [0, 1, 2]
        assert all(r[1] == 3 for r in results)
        assert all(r[2] == 6.0 for r in results)   # 3 ranks x 2.0
        assert all(r[2] == 6.0 for r in results)

    def test_run_surfaces_worker_failure(self):
        import horovod_tpu as hvd
        with pytest.raises(RuntimeError, match="intentional worker"):
            hvd.run(_failing_fn, np=2)

    def test_run_remote_launch_failure_fails_fast(self):
        """A dead remote launch (here: no ssh binary / unreachable host)
        surfaces as a worker-failure error quickly — the result collector
        consults the launch exit code instead of waiting out the full KV
        timeout."""
        import time

        import horovod_tpu as hvd
        t0 = time.time()
        with pytest.raises(RuntimeError, match="worker failures"):
            hvd.run(_allreduce_fn, args=(1.0,),
                    hosts="localhost:1,unreachable-host:1",
                    start_timeout=10.0)
        assert time.time() - t0 < 120


# ---------------------------------------------------------------------------
# mpi_run
# ---------------------------------------------------------------------------
class TestMpiRun:
    @pytest.mark.parametrize("text,expected", [
        ("mpirun (Open MPI) 4.1.4", "openmpi"),
        ("IBM Spectrum MPI 10.3", "spectrum"),
        ("HYDRA build details:", "mpich"),
        ("Intel(R) MPI Library 2021", "intel"),
        ("something else", "unknown"),
    ])
    def test_flavor_detection(self, text, expected):
        assert mpi_run.flavor(version_text=text) == expected

    def test_openmpi_command(self):
        env = {"HOROVOD_FUSION_THRESHOLD": "1024", "PATH": "/usr/bin",
               "SECRET": "x"}
        cmd = mpi_run.build_mpi_command(
            ["python", "train.py"], np=8, hosts="h1:4,h2:4", env=env,
            mpi_flavor="openmpi", ssh_port=2222)
        joined = " ".join(cmd)
        assert joined.startswith("mpirun")
        assert "-np 8" in joined
        assert "-H h1:4,h2:4" in joined
        assert "-bind-to none -map-by slot" in joined
        assert "-x HOROVOD_FUSION_THRESHOLD" in joined
        assert "-x PATH" in joined
        assert "-x SECRET" not in joined
        assert "plm_rsh_args" in joined and "-p 2222" in joined
        assert joined.endswith("python train.py")

    def test_mpich_command_uses_genvlist(self):
        cmd = mpi_run.build_mpi_command(
            ["python", "t.py"], np=2, env={"HOROVOD_CYCLE_TIME": "5"},
            mpi_flavor="mpich")
        joined = " ".join(cmd)
        assert "-genvlist HOROVOD_CYCLE_TIME" in joined
        assert "-bind-to" not in joined

    def test_extra_args_appended(self):
        cmd = mpi_run.build_mpi_command(
            ["python", "t.py"], np=2, env={}, mpi_flavor="openmpi",
            extra_mpi_args="--tag-output")
        assert "--tag-output" in cmd


# ---------------------------------------------------------------------------
# Gated integrations
# ---------------------------------------------------------------------------
class TestGatedIntegrations:
    def test_modules_import_without_deps(self):
        import horovod_tpu.keras    # noqa: F401
        import horovod_tpu.mxnet    # noqa: F401
        import horovod_tpu.ray      # noqa: F401
        import horovod_tpu.spark    # noqa: F401
        import horovod_tpu.tensorflow  # noqa: F401

    def test_tensorflow_surface_gated(self):
        import horovod_tpu.tensorflow as htf
        if htf._TF_AVAILABLE:
            pytest.skip("tensorflow installed; gate not applicable")
        with pytest.raises(ImportError, match="JAX-native"):
            htf.allreduce(None)

    def test_keras_optimizer_gated(self):
        import horovod_tpu.keras as hk
        try:
            import tensorflow  # noqa: F401
            pytest.skip("tensorflow installed; gate not applicable")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="callbacks"):
            hk.DistributedOptimizer(object())

    def test_keras_reexports_callbacks(self):
        import horovod_tpu.keras as hk
        from horovod_tpu.callbacks import MetricAverageCallback
        assert hk.MetricAverageCallback is MetricAverageCallback

    def test_mxnet_gated(self):
        import horovod_tpu.mxnet as hmx
        with pytest.raises(ImportError, match="end-of-life"):
            hmx.DistributedOptimizer(object())

    def test_ray_gated(self):
        import horovod_tpu.ray as hray
        try:
            import ray  # noqa: F401
            pytest.skip("ray installed; gate not applicable")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="horovodrun-tpu"):
            hray.RayExecutor(2)

    def test_spark_slot_claim_is_atomic_per_host(self):
        """Regression (ADVICE r1): two tasks on one host must claim
        DISTINCT slots regardless of their global partition indices."""
        from horovod_tpu.runner.hosts import HostInfo, get_host_assignments
        from horovod_tpu.runner.network import RendezvousServer
        from horovod_tpu.spark import claim_slot

        hosts = [HostInfo(hostname="hostA", slots=2),
                 HostInfo(hostname="hostB", slots=2)]
        slots = get_host_assignments(hosts, 4)
        pool: dict[str, list] = {}
        for s in slots:
            pool.setdefault(s.hostname, []).append(s)

        server = RendezvousServer()
        port = server.start()
        try:
            # Partitions 1 and 3 both landed on hostA (the collision case:
            # both have index % 2 == 1 under the old scheme).
            a1 = claim_slot("hostA", "127.0.0.1", port, pool,
                            task_key="partition1")
            a2 = claim_slot("hostA", "127.0.0.1", port, pool,
                            task_key="partition3")
            assert {a1.rank, a2.rank} == {s.rank for s in pool["hostA"]}
            assert a1.local_rank != a2.local_rank
            # A retried task (same partition) gets its ORIGINAL slot back,
            # never a duplicate of a live peer's.
            retry = claim_slot("hostA", "127.0.0.1", port, pool,
                               task_key="partition1")
            assert retry.rank == a1.rank
            # A genuinely new claimant on a full 2-slot host = placement
            # drift → loud error.
            with pytest.raises(RuntimeError, match="drift"):
                claim_slot("hostA", "127.0.0.1", port, pool,
                           task_key="partition9")
        finally:
            server.stop()

    def test_keras_optimizer_preserves_instance_state(self):
        """Regression (VERDICT r1 weak #4): DistributedOptimizer must keep
        the optimizer instance (slot variables, iterations) — not rebuild
        from config."""
        tf = pytest.importorskip("tensorflow")
        import horovod_tpu as hvd
        import horovod_tpu.keras as hk

        hvd.init()
        try:
            opt = tf.keras.optimizers.SGD(learning_rate=0.2, momentum=0.9)
            v = tf.Variable([1.0, 2.0])
            # Create slot/iteration state before wrapping.
            opt.apply_gradients([(tf.constant([0.1, 0.1]), v)])
            iterations_before = int(opt.iterations.numpy())
            n_vars_before = len(opt.variables)
            assert iterations_before == 1

            wrapped = hk.DistributedOptimizer(opt)
            assert wrapped is opt                      # same instance
            assert int(wrapped.iterations.numpy()) == iterations_before
            assert len(wrapped.variables) == n_vars_before
            # Still steps correctly through the allreduce path (size 1).
            wrapped.apply_gradients([(tf.constant([0.1, 0.1]), v)])
            assert int(wrapped.iterations.numpy()) == 2
        finally:
            hvd.shutdown()

    def test_spark_gated(self):
        import horovod_tpu.spark as hspark
        try:
            import pyspark  # noqa: F401
            pytest.skip("pyspark installed; gate not applicable")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="horovodrun-tpu"):
            hspark.run(lambda: None)


class TestMxnetGate:
    """The mxnet binding is complete but import-gated: module import and
    op-surface access work without mxnet; touching the mx-subclassing
    wrappers without mxnet raises with guidance, and with the stub they
    build real subclasses."""

    def test_import_without_mxnet(self):
        import horovod_tpu.mxnet as hmx
        assert callable(hmx.allreduce)
        assert callable(hmx.broadcast_parameters)

    def test_wrappers_require_mxnet(self, monkeypatch):
        import sys
        import horovod_tpu.mxnet as hmx
        monkeypatch.setattr(hmx, "_lazy_cache", {})
        monkeypatch.setitem(sys.modules, "mxnet", None)
        with pytest.raises(ImportError, match="mxnet"):
            hmx.DistributedOptimizer
        with pytest.raises(ImportError, match="mxnet"):
            hmx.DistributedTrainer

    def test_wrappers_build_with_stub(self, monkeypatch):
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import mxnet_stub
        import horovod_tpu.mxnet as hmx
        monkeypatch.setattr(hmx, "_lazy_cache", {})
        mx = mxnet_stub.install()
        try:
            opt_cls = hmx.DistributedOptimizer
            tr_cls = hmx.DistributedTrainer
            assert issubclass(opt_cls, mx.optimizer.Optimizer)
            assert issubclass(tr_cls, mx.gluon.Trainer)
        finally:
            for name in list(sys.modules):
                if name == "mxnet" or name.startswith("mxnet."):
                    del sys.modules[name]
