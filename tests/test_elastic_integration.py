"""End-to-end elastic integration test: real worker processes under the
elastic launcher, with a scripted mid-training node failure.

Mirrors the reference's test/integration/test_elastic_*.py approach
(SURVEY §4): "hosts" are localhost aliases, failure is a scheduled hard
exit inside the training script, and survival is verified through the
committed-state markers workers write at completion.
"""
from __future__ import annotations

import argparse
import glob
import os
import sys

import pytest

from horovod_tpu.elastic.launcher import launch_elastic

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "elastic_worker.py")


def _args(**overrides) -> argparse.Namespace:
    defaults = dict(
        num_proc=None, hosts=None, hostfile=None, network_interface=None,
        ssh_port=None, ssh_identity_file=None, verbose=False,
        disable_cache=False, start_timeout=30.0, check_build=False,
        min_np=None, max_np=None, host_discovery_script=None,
        reset_limit=None, slots=None, elastic_timeout=60.0,
        fusion_threshold_mb=None, cycle_time_ms=None, cache_capacity=None,
        hierarchical_allreduce=False, hierarchical_allgather=False,
        autotune=False, autotune_log_file=None, timeline_filename=None,
        timeline_mark_cycles=False, no_stall_check=True,
        stall_check_warning_time_seconds=None,
        stall_check_shutdown_time_seconds=None, log_level=None,
        config_file=None, command=[])
    defaults.update(overrides)
    return argparse.Namespace(**defaults)


def test_elastic_run_completes(tmp_path):
    """Happy path: 2 local workers train to completion elastically."""
    env = {"TEST_ELASTIC_OUT": str(tmp_path), "TEST_ELASTIC_TARGET": "3",
           "TEST_ELASTIC_FAIL_HOST": ""}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rc = launch_elastic(
            _args(num_proc=2, min_np=2, hosts="localhost:2"),
            [sys.executable, _WORKER])
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
    assert rc == 0
    markers = sorted(glob.glob(str(tmp_path / "done.*")))
    assert len(markers) == 2
    for m in markers:
        epochs, size, _rank = open(m).read().split()
        assert epochs == "3"
        assert size == "2"


def _jax_recoverability_available() -> bool:
    """Surviving a peer's death requires jax_enable_recoverability —
    without it jaxlib's coordination client LOG(FATAL)s the survivors
    from C++ (client.h:80), which no Python-side handling can soften
    (see multihost.shutdown_jax_distributed).  Older jaxlibs lack the
    knob entirely, making elastic-reform untestable there."""
    import jax
    try:
        jax.config.update("jax_enable_recoverability",
                          jax.config.jax_enable_recoverability)
        return True
    except AttributeError:
        return False


@pytest.mark.skipif(not _jax_recoverability_available(),
                    reason="this jax lacks jax_enable_recoverability; "
                           "survivors of a peer death are killed by "
                           "jaxlib's fatal-error path")
def test_elastic_xla_world_reforms(tmp_path):
    """Elastic x XLA (VERDICT r2 item 5): three loopback "hosts" with the
    XLA device plane active; one dies mid-training; the two survivors must
    tear down the multi-process JAX world, re-initialize it IN-PROCESS at
    size 2 (jax.distributed shutdown → clear_backends → initialize, the
    SURVEY §7 hard part), and finish with collectives still riding the
    device plane (asserted inside the worker each epoch)."""
    env = {"TEST_ELASTIC_OUT": str(tmp_path),
           "TEST_ELASTIC_TARGET": "4",
           "TEST_ELASTIC_FAIL_HOST": "127.0.0.2",
           "TEST_ELASTIC_FAIL_EPOCH": "2",
           "TEST_ELASTIC_XLA": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rc = launch_elastic(
            _args(num_proc=3, min_np=2, max_np=3, start_timeout=180.0,
                  elastic_timeout=180.0,
                  hosts="localhost:1,127.0.0.1:1,127.0.0.2:1"),
            [sys.executable, _WORKER])
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
    markers = sorted(glob.glob(str(tmp_path / "done.*")))
    assert rc == 0
    assert len(markers) == 2          # both survivors finish
    for m in markers:
        assert "127.0.0.2" not in os.path.basename(m)
        epochs, size, _rank = open(m).read().split()
        assert epochs == "4"
        assert size == "2"            # the re-formed world


def test_elastic_node_failure_recovers(tmp_path):
    """One "host" dies mid-training; the survivor restores committed state,
    re-rendezvouses at size 1, and finishes all epochs."""
    env = {"TEST_ELASTIC_OUT": str(tmp_path), "TEST_ELASTIC_TARGET": "5",
           "TEST_ELASTIC_FAIL_HOST": "127.0.0.1",
           "TEST_ELASTIC_FAIL_EPOCH": "2"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rc = launch_elastic(
            _args(num_proc=2, min_np=1, max_np=2,
                  hosts="localhost:1,127.0.0.1:1"),
            [sys.executable, _WORKER])
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
    assert rc == 0
    markers = sorted(glob.glob(str(tmp_path / "done.*")))
    # Only the survivor writes a marker.
    assert len(markers) == 1
    assert "localhost" in os.path.basename(markers[0])
    epochs, size, rank = open(markers[0]).read().split()
    assert epochs == "5"
    assert size == "1"
    assert rank == "0"


def test_programmatic_elastic_run(monkeypatch):
    """Reference parity: horovod.run(func, min_np=...) launches the
    elastic driver over a pickled fn (runner/__init__.py:92-210); results
    come back keyed by final rank."""
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    monkeypatch.setenv(
        "PYTHONPATH",
        tests_dir + os.pathsep + os.environ.get("PYTHONPATH", ""))
    monkeypatch.delenv("HOROVOD_RENDEZVOUS_EPOCH", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    from elastic_fn import allreduce_identity

    import horovod_tpu as hvd

    results = hvd.run(allreduce_identity, args=(3.0,),
                      hosts="localhost:2", min_np=2, max_np=2,
                      elastic_timeout=60.0,
                      env={"TEST_ELASTIC_RUN_MARKER": "propagated"})
    assert set(results) == {0, 1}
    for rank, value in results.items():
        assert value["rank"] == rank
        assert value["size"] == 2
        assert value["sum"] == 6.0
        assert value["marker"] == "propagated"   # env= reaches workers


def test_elastic_only_params_rejected_on_static_path():
    import horovod_tpu as hvd
    with pytest.raises(ValueError, match="elastic mode"):
        hvd.run(len, args=([1],), np=1, reset_limit=3)
