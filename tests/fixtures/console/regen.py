"""Regenerate the committed console golden fixture.

Runs a small deterministic in-process fleet episode — 4 virtual ranks,
6 steps, one orderly preemption (v2 at step 3) and one injected
straggler (v3, +60 ms) — dumps its rank-stamped evidence into
``episode4/`` and records ``summary_lines`` of the rendered episode as
``episode4.summary.txt``.

Run from the repo root after changing dump formats or the renderer::

    JAX_PLATFORMS=cpu python tests/fixtures/console/regen.py

The committed dump dir is the test input and the summary file the
golden; ``tests/test_console.py`` renders the former and byte-compares
against the latter (no fleet run at test time).
"""
import os
import shutil
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.abspath(os.path.join(HERE, "..", "..", "..")))
EPISODE = os.path.join(HERE, "episode4")
GOLDEN = os.path.join(HERE, "episode4.summary.txt")


def main() -> int:
    os.environ["HOROVOD_METRICS"] = "on"
    os.environ["HOROVOD_CHAOS"] = "preempt:rank=2,op=3"
    os.environ["HOROVOD_FLIGHT_FILE"] = os.path.join(EPISODE,
                                                     "flight.json")
    from horovod_tpu import telemetry
    from horovod_tpu.telemetry import flight
    from horovod_tpu.fleetsim import FleetConfig, FleetSim
    from horovod_tpu.console import load_dump_dir, summary_lines
    from horovod_tpu.runner.network import RendezvousServer

    telemetry.configure()
    flight.configure(0)
    shutil.rmtree(EPISODE, ignore_errors=True)
    os.makedirs(EPISODE)
    server = RendezvousServer()
    port = server.start()
    try:
        cfg = FleetConfig(ranks=4, steps=6, step_ms=2.0,
                          heartbeat_s=0.2, fault_timeout_s=10.0,
                          step_timeout_s=30.0, host_group=4,
                          straggler_vid=3, straggler_ms=60.0,
                          epoch="golden", dump_dir=EPISODE,
                          endpoints=f"127.0.0.1:{port}")
        report = FleetSim(cfg).run()
    finally:
        server.stop()
    assert report.failed_steps == 0, report
    assert report.outcomes == {"finished": 3, "preempted": 1}, report
    assert report.straggler_rank == 3, report

    lines = summary_lines(load_dump_dir(EPISODE))
    with open(GOLDEN, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    print("\n".join(lines))
    print(f"\nwrote {EPISODE}/ and {GOLDEN}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
