"""Regenerate the committed fleet-controller console fixture.

Runs the REAL 4-rank fleet acceptance battery (tests/mp_worker.py
``battery_fleet`` — launch ranks 0-2 train, launch rank 3 serves, one
traffic-driven train->serve migration plus a continuous weight rollout)
and harvests its evidence into ``fleet4/``:

- each launch rank's end-of-battery flight dump (the same files the
  hvdmc witness replays) becomes ``flight.r{r}.json``;
- the serving front's loadgen report (goodput phases, weight-version
  mix, staleness) becomes ``SERVE_r0.json``.

``summary_lines`` of the rendered episode is recorded as
``fleet4.summary.txt``.  Run from the repo root after changing the
fleet dump formats or the renderer::

    JAX_PLATFORMS=cpu python tests/fixtures/console/regen_fleet.py

The committed dump dir is the test input and the summary file the
golden; ``tests/test_console.py`` renders the former and byte-compares
against the latter (no battery run at test time).
"""
import glob
import os
import shutil
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.abspath(os.path.join(HERE, "..", "..", "..")))
sys.path.insert(0, os.path.abspath(os.path.join(HERE, "..", "..")))
EPISODE = os.path.join(HERE, "fleet4")
GOLDEN = os.path.join(HERE, "fleet4.summary.txt")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from test_multiprocess import _run_world
    from test_statesync import _witness_env
    from horovod_tpu.console import load_dump_dir, summary_lines

    shutil.rmtree(EPISODE, ignore_errors=True)
    os.makedirs(EPISODE)
    extra = _witness_env("fleet", 4)
    extra["HOROVOD_FLEET_DUMP_DIR"] = EPISODE
    _run_world(4, "fleet", timeout=360.0, extra_env=extra)
    for dump in sorted(glob.glob("/tmp/hvd_witness_fleet4"
                                 ".launch*.json")):
        launch = dump.rsplit(".launch", 1)[1].split(".", 1)[0]
        shutil.copy(dump, os.path.join(EPISODE,
                                       f"flight.r{launch}.json"))
    ep = load_dump_dir(EPISODE)
    assert ep.flights and ep.serve_reports, \
        "battery left no console evidence"
    with open(GOLDEN, "w") as fh:
        fh.write("\n".join(summary_lines(ep)) + "\n")
    print(f"regenerated {EPISODE} and {GOLDEN}:")
    print(open(GOLDEN).read())
    return 0


if __name__ == "__main__":
    sys.exit(main())
