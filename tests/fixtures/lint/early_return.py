"""Seeded hvdlint violation: collective after a rank-gated early return
(HVD102): non-zero ranks exit before ever reaching the barrier."""
import horovod_tpu as hvd
from horovod_tpu.parallel import multihost


def broken_early_return(state):
    if hvd.rank() != 0:
        return state
    multihost.kv_barrier("early-return-fixture")      # HVD102
    return state


def broken_assert(tensor):
    assert hvd.rank() == 0, "coordinator only"
    return hvd.allreduce(tensor, name="grad")         # HVD102
