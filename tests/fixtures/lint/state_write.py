"""Seeded hvdlint violation: shared-state write outside the owning module
(HVD401). Mutating the controller's fields from a user thread races the
background coordination cycle."""
from horovod_tpu import core


def broken_threshold_override(threshold):
    st = core.global_state()
    st.controller.tensor_fusion_threshold = threshold      # HVD401
    core._global.cycle_time_ms = 0.5                       # HVD401
