"""Seeded HVD1002 fixture: blocking I/O inside dispatch/backend hot-path
functions (and a clean control in a non-hot function)."""


def allreduce(response, entries):
    print("executing", response)            # HVD1002: terminal write
    with open("/tmp/hvd_trace.log", "a") as f:   # HVD1002: file open
        f.write("allreduce\n")
    return entries


def _execute_response(state, response):
    state.sock.sendall(b"payload")          # HVD1002: raw socket send
    return response


def load_config(path):
    # Not a hot-path function: formation/CLI I/O stays legal.
    with open(path) as f:
        return f.read()
