"""Seeded HVD505 (optional-field gate, sp_* group): the sharding-spec
wire field encoded/decoded OUTSIDE a feature-bit gate — the
rolling-upgrade hazard: a peer that negotiated FEATURE_SHARDING away
cannot skip the field, so every frame after it decodes garbage."""


class UngatedShardRequest:
    """Symmetric codec (no sequence drift) with the sp_* optional field
    unconditionally on the wire on both sides."""

    def __init__(self, tensor_name="", sp_spec="", device=0):
        self.tensor_name = tensor_name
        self.sp_spec = sp_spec
        self.device = device

    def encode(self, enc, features=0):
        (enc.string(self.tensor_name)
            .string(self.sp_spec)       # HVD505: not behind a feature bit
            .uvarint(self.device))

    @classmethod
    def decode(cls, dec, features=0):
        return cls(tensor_name=dec.string(),
                   sp_spec=dec.string(),   # HVD505: symmetric, same bug
                   device=dec.uvarint())


class GatedShardRequest:
    """The sanctioned form: both sides gate the sp_* group identically
    on the negotiated FEATURE_SHARDING bit."""

    FEATURE_SHARDING = 8

    def __init__(self, tensor_name="", sp_spec="", device=0):
        self.tensor_name = tensor_name
        self.sp_spec = sp_spec
        self.device = device

    def encode(self, enc, features=0):
        enc.string(self.tensor_name)
        enc.uvarint(self.device)
        if features & self.FEATURE_SHARDING:
            enc.string(self.sp_spec)

    @classmethod
    def decode(cls, dec, features=0):
        req = cls(tensor_name=dec.string(), device=dec.uvarint())
        if features & cls.FEATURE_SHARDING:
            req.sp_spec = dec.string()
        return req
