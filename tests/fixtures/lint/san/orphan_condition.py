"""Seeded HVD503: a Condition some thread waits on but no code path
ever notifies — the predicate is written by no other thread, so the
wait can only end by timeout (or never)."""
import threading


class ResultBox:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._value = None

    def wait_value(self, timeout=None):
        with self._cond:
            while self._value is None:
                self._cond.wait(timeout)              # HVD503: no notify
            return self._value

    def set_value(self, value):
        with self._lock:
            self._value = value                       # forgot notify_all()
