"""Seeded HVD501: two code paths take the same pair of locks in
opposite orders — the classic AB/BA deadlock the moment two threads
interleave.  hvdsan must report one lock-order-inversion cycle."""
import threading

_submit_lock = threading.Lock()
_drain_lock = threading.Lock()


def submit(item, queue):
    with _submit_lock:
        with _drain_lock:            # order: submit -> drain
            queue.append(item)


def drain(queue):
    with _drain_lock:
        with _submit_lock:           # order: drain -> submit (inverted)
            return queue.pop()
