"""Seeded HVD505: wire-schema drift between pack and unpack — the
fp_*/tm_*/trace_* growth pattern with one side forgotten (trailing
drift), plus a swapped-field pair (order drift)."""


class DriftRequest:
    """encode writes a trailing field decode never reads."""

    def __init__(self, rank=0, name="", scale=1.0):
        self.rank = rank
        self.name = name
        self.scale = scale

    def encode(self, enc):
        (enc.uvarint(self.rank)
            .string(self.name)
            .f64(self.scale))

    @classmethod
    def decode(cls, dec):
        return cls(rank=dec.uvarint(),
                   name=dec.string())       # HVD505: scale never read


class SwappedResponse:
    """decode reads the same primitives in a different field order."""

    def __init__(self, error="", detail=""):
        self.error = error
        self.detail = detail

    def encode(self, enc):
        (enc.string(self.error)
            .string(self.detail))

    @classmethod
    def decode(cls, dec):
        return cls(detail=dec.string(),     # HVD505: fields swapped
                   error=dec.string())
