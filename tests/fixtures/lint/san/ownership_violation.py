"""Seeded HVD504: a background thread (declared via
threading.Thread(name=...)) writes controller-owned state — the
manifest (analysis/hvdsan/ownership.py) names hvd-background as that
domain's owner, so the write races the coordination cycle."""
import threading


class CacheWatcher:
    def __init__(self, state):
        self.state = state
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fixture-watcher")
        self._thread.start()

    def _loop(self):
        # HVD504: controller state written from the fixture-watcher
        # thread (owner: hvd-background).
        self.state.controller.cache_capacity = 0
