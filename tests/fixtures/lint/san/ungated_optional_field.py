"""Seeded HVD505 (optional-field gate): an fp_* optional wire field
encoded/decoded OUTSIDE a feature-bit gate — the rolling-upgrade
hazard: a peer that negotiated FEATURE_FINGERPRINT away cannot skip
the field, so every frame after it decodes garbage."""


class UngatedRequestList:
    """Symmetric codec (no sequence drift) with the optional field
    unconditionally on the wire on both sides."""

    def __init__(self, shutdown=False, fp_seq=0, count=0):
        self.shutdown = shutdown
        self.fp_seq = fp_seq
        self.count = count

    def to_bytes(self, enc, features=0):
        (enc.bool_(self.shutdown)
            .uvarint(self.fp_seq)       # HVD505: not behind a feature bit
            .uvarint(self.count))

    @classmethod
    def from_bytes(cls, dec, features=0):
        return cls(shutdown=dec.bool_(),
                   fp_seq=dec.uvarint(),   # HVD505: symmetric, same bug
                   count=dec.uvarint())


class GatedRequestList:
    """The sanctioned form: both sides gate the group identically."""

    FEATURE_FINGERPRINT = 1

    def __init__(self, shutdown=False, fp_seq=0, count=0):
        self.shutdown = shutdown
        self.fp_seq = fp_seq
        self.count = count

    def to_bytes(self, enc, features=0):
        enc.bool_(self.shutdown)
        if features & self.FEATURE_FINGERPRINT:
            enc.uvarint(self.fp_seq)
        enc.uvarint(self.count)

    @classmethod
    def from_bytes(cls, dec, features=0):
        shutdown = dec.bool_()
        fp_seq = 0
        if features & cls.FEATURE_FINGERPRINT:
            fp_seq = dec.uvarint()
        return cls(shutdown=shutdown, fp_seq=fp_seq,
                   count=dec.uvarint())
