"""Seeded HVD502: a lock held across a collective and across a
blocking socket receive, each through a call — invisible to the
per-line HVD301 rule, found by hvdsan's interprocedural held-locks
computation."""
import threading

_state_lock = threading.Lock()


def _sync_helper(tensor):
    # The collective lives one call away from the lock.
    return allreduce(tensor, name="fixture")          # noqa: F821


def _recv_helper(sock, view):
    return sock.recv_into(view)


def flush_gradients(tensor):
    with _state_lock:
        return _sync_helper(tensor)                   # HVD502 (collective)


def pull_remote(sock, view):
    with _state_lock:
        return _recv_helper(sock, view)               # HVD502 (blocking)
