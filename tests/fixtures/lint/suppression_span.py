"""Suppression statement-range regression fixture: a suppression
anchors to the whole statement (lineno..end_lineno), so a comment on
the CLOSING line of a multi-line call — or on the ``def`` line of a
decorated function — still covers the violation reported at the
statement's first line.  This file must lint clean."""
import horovod_tpu as hvd


def multi_line_call(t, rank):
    if rank == 0:
        hvd.allreduce(
            t,
            name="spanned")  # hvdlint: disable=HVD101 -- single-rank tool path, never negotiates; regression: suppression on the closing line of a multi-line statement


def _gate(cond):
    def deco(fn):
        return fn
    return deco


@_gate(0 == hvd.rank() and hvd.barrier())
def decorated(t, rank):  # hvdlint: disable=HVD101 -- regression: a suppression on the def line covers its decorators
    return t
