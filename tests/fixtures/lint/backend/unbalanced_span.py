# Seeded HVD1005 fixture: Timeline span-open calls in a backend/ module
# without a finally-guarded close.  The clean shapes below (start inside
# a guarded try, start immediately followed by a guarded try, the
# conditional-start idiom, and the forwarding helper) must stay silent.


def bad_unguarded(self, entries, buf):
    self._act_start(entries, "TCP_RING_ALLREDUCE")   # flagged: no finally
    out = buf.sum()
    self._act_end(entries)
    return out


def bad_except_only(self, entries, buf):
    self._act_start(entries, "SHM_ALLREDUCE")   # flagged: end not in finally
    try:
        return buf.sum()
    except ValueError:
        self._act_end(entries)
        raise


def bad_direct_timeline(self, tl, buf):
    tl.activity_start("t0", "XLA_ALLREDUCE")   # flagged: no finally
    return buf.sum()


def good_start_then_try(self, entries, buf):
    self._act_start(entries, "TCP_RING_ALLREDUCE")
    try:
        return buf.sum()
    finally:
        self._act_end(entries)


def good_start_inside_try(self, entries, buf):
    try:
        self._act_start(entries, "SHM_ALLGATHER")
        return buf.sum()
    finally:
        self._act_end(entries)


def good_conditional_start(self, entries, buf):
    if len(entries) > 1:
        self._act_start(entries, "MEMCPY_OUT_FUSION_BUFFER")
    try:
        return buf.sum()
    finally:
        if len(entries) > 1:
            self._act_end(entries)


def _act_start(self, entries, activity):
    # The forwarding helper is the primitive: callers own the balance.
    self.timeline.activity_start_all(entries, activity)


def good_suppressed(self, entries, buf):
    self._act_start(entries, "TCP_BCAST")  # hvdlint: disable=unbalanced-span -- fixture: the next ring step's recv closes the span
    return buf.sum()
