"""Seeded HVD1001 fixture: thread construction in a backend/ hot path.

Lives under a `backend/` directory on purpose — the rule is scoped to
data-plane modules (the persistent channel workers in runner/network.py
are outside that scope, which is the allowlist).
"""
import threading


def sendrecv(mesh, to_rank, payload, from_rank, timeout):
    t = threading.Thread(target=mesh.send, args=(to_rank, payload))  # HVD1001
    t.start()
    data = mesh.recv(from_rank, timeout=timeout)   # bounded: no HVD1003
    t.join(timeout)                                # bounded: no HVD1003
    return data


def broadcast_star(mesh, size, payload):
    threads = [threading.Thread(target=mesh.send, args=(p, payload))  # HVD1001
               for p in range(size)]
    for t in threads:
        t.start()


def fine_async(mesh, to_rank, payload):
    # The persistent-lane API is the sanctioned path — no violation.
    mesh.send_async(to_rank, payload)


def fine_suppressed(mesh, fn):
    return threading.Thread(target=fn)  # hvdlint: disable=thread-spawn-in-backend -- channel worker test double, constructed once
