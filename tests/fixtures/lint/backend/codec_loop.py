"""HVD1004 fixture: per-segment Python-level codec chains in a backend/
module.  Lines flagged: the loop-body dequantize/from_bytes chain, the
list-comprehension quantize, and the loop-body to_bytes; the fused-kernel
call and the straight-line (non-loop) codec call stay clean."""
import numpy as np

from horovod_tpu.compress import dequantize, from_bytes, quantize, to_bytes


def gather_leg_reference(chunks, n, codec, block_size):
    acc = np.zeros(n, np.float32)
    for raw in chunks:
        acc += dequantize(from_bytes(raw, n, codec, block_size))
    return acc


def scatter_leg_reference(x, bounds, codec, block_size):
    wires = [to_bytes(quantize(x[bounds[j]:bounds[j + 1]], codec,
                               block_size))
             for j in range(len(bounds) - 1)]
    return wires


def fused_leg_ok(fk, chunks, n, codec, block_size, acc):
    # Fused single-pass kernels inside the loop are the fix, not a hit.
    for raw in chunks:
        fk.decode_add(raw, n, codec, block_size, acc, ("in",))
    return acc


def straight_line_ok(x, codec, block_size):
    # A one-shot codec call outside any loop is fine (e.g. the xla
    # plane's single input quantization).
    return to_bytes(quantize(x, codec, block_size))
