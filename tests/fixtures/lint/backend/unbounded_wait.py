"""Seeded HVD1003 fixture: unbounded blocking waits in a backend/
module (the deadlock class the resilience/ subsystem converts into
RanksFailedError), plus bounded/exempt controls that must stay clean.
"""
from urllib.request import urlopen


def drain(mesh, sock, peer, worker, store):
    raw = mesh.recv(peer)                       # HVD1003: no deadline
    sock.recv_into(raw)                         # HVD1003: no deadline
    worker.join()                               # HVD1003: no deadline
    store.wait("scope", "key")                  # HVD1003: no deadline
    urlopen("http://coordinator/health")        # HVD1003: no deadline
    return raw


def drain_bounded(mesh, sock, peer, worker, store, timeout, res):
    mesh.recv(peer, timeout=timeout)            # keyword bound
    worker.join(timeout)                        # positional bound by name
    store.wait("scope", "key", res.op_deadline)  # deadline-named bound
    urlopen("http://coordinator/health", timeout=5)
    ", ".join(["strings", "are", "exempt"])
    import os
    return os.path.join("path", "join", "is", "exempt")


def drain_justified(worker):
    worker.join()  # hvdlint: disable=unbounded-blocking-wait -- queue poisoned first; worker provably exits on the sentinel
