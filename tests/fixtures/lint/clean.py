"""False-positive guard: symmetric patterns hvdlint must NOT flag."""
import horovod_tpu as hvd
from horovod_tpu.parallel import multihost


def symmetric_allreduce(tensor):
    # Every rank submits the same op unconditionally: fine.
    return hvd.allreduce(tensor, name="grad")


def rank_gated_logging(metrics):
    # Rank-gated NON-collective work is the supported idiom.
    if hvd.rank() == 0:
        print(metrics)
    return metrics


def unique_barrier():
    multihost.kv_barrier("clean-fixture-unique")
    return True


def rank_scaled_but_symmetric(tensor):
    # A rank-dependent VALUE feeding a symmetric call is fine: every rank
    # still submits the collective.
    scale = 1.0 / (hvd.rank() + 1)
    return hvd.allreduce(tensor * scale, name="scaled")


def justified_suppression(tensor):
    if hvd.rank() == 0:
        hvd.allreduce(tensor, name="solo")  # hvdlint: disable=rank-gated-collective -- fixture: exercised only in a single-process world, never negotiates
    return tensor
