"""Seeded hvdlint violations: kv_barrier tag discipline (HVD201/HVD202)."""
import horovod_tpu as hvd
from horovod_tpu.parallel import multihost


def phase_one():
    multihost.kv_barrier("checkpoint")                # first site: OK


def phase_two():
    multihost.kv_barrier("checkpoint")                # HVD201: duplicate tag


def broken_dynamic_tag(step):
    multihost.kv_barrier(f"step-{step}")              # HVD202: dynamic tag


def broken_rank_tag():
    multihost.kv_barrier("sync-%d" % hvd.rank())      # HVD202: dynamic tag
