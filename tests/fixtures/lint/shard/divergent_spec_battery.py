"""The shared seed of the 2-rank shard battery (test_multiprocess
test_shard_spec_divergence_caught_static_and_runtime): the SAME
spec-divergent collective below is caught

- statically by hvdshard — HVD803 names the tainted branch in
  ``spec_gated_step`` whose arms agree on the op sequence
  (negotiation proceeds) but disagree on the sharding spec
  ([allreduce(shard_step|(dp,*))] vs [allreduce(shard_step|(tp,*))]),
  and
- at runtime by op×name×dtype×dims×spec collective fingerprinting —
  the seeded rank folds a different sp_spec token for the same op, and
  every rank receives the structured divergence ERROR naming the first
  spec-divergent op within one strict-mode negotiation cycle.
"""


def spec_gated_step(hvd, t, rank, seed_rank):
    if rank == seed_rank:
        out = hvd.allreduce(t, name="shard_step", spec="(dp,*)")
    else:
        out = hvd.allreduce(t, name="shard_step", spec="(tp,*)")
    return out
