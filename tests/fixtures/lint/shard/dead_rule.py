"""Seeded HVD801 fixtures: a partition rule matching no reachable
parameter path, and a sibling path falling through to replicated while
its neighbour is sharded (the forgotten-family-member hole)."""
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import ShardingRules

DEFAULT_AXES = ("dp", "tp")


class Attention(nn.Module):
    def setup(self):
        self.wq = nn.Dense(64, name="attn/wq")
        self.wk = nn.Dense(64, name="attn/wk")


RULES = ShardingRules([
    # Dead: the harvested name vocabulary has no decoder token.
    (r"decoder/.*kernel", P(None, "tp")),
    # attn/wq is sharded; sibling attn/wk falls through to replicated.
    (r"attn/wq", P(None, "tp")),
])
