"""Clean twin of divergent_spec.py: the spec is rank-invariant — both
arms carry the same token, and a genuinely local branch (not
rank-tainted) may spec freely."""
import horovod_tpu as hvd


def rank_gated_same_spec(t, rank):
    if rank == 0:
        hvd.allreduce(t, name="grads/w", spec="(tp,*)")
    else:
        hvd.allreduce(t, name="grads/w", spec="(tp,*)")
    return hvd.allreduce(t, name="step")


def untainted_branch(t, use_tp):
    if use_tp:
        hvd.allreduce(t, name="grads/w", spec="(tp,*)")
    else:
        hvd.allreduce(t, name="grads/w", spec="(dp,*)")
