"""Clean twin of axis_mismatch.py: every spec axis is in the harvested
mesh vocabulary, including a multi-axis dim (dp+tp)."""
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import build_mesh
from horovod_tpu.parallel.sharding import constrain

DEFAULT_AXES = ("dp", "tp")


def build():
    return build_mesh(dp=4, tp=2)


def place(x, mesh):
    return constrain(x, mesh, P(("dp", "tp"), None))
