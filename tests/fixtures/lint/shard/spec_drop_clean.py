"""Clean twin of spec_drop.py: the layout a producer applied rides the
collective as its spec= — identity stays op×name×dtype×dims×spec."""
import horovod_tpu as hvd
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import shard_params
from horovod_tpu.parallel.sharding import constrain


def sync_sharded_params(params, mesh, rules):
    placed = shard_params(params, mesh, rules)
    return hvd.allreduce(placed, name="params", spec="(dp,*)")


def gather_constrained(x, mesh):
    y = constrain(x, mesh, P("dp"))
    return hvd.allgather(y, name="acts", spec=P("dp"))
