"""Seeded HVD802 fixture: a spec naming a mesh axis the harvested axis
vocabulary (DEFAULT_AXES / Mesh literals / build_mesh keywords) does not
carry — raises only when applied at runtime, or silently replicates."""
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import build_mesh
from horovod_tpu.parallel.sharding import constrain

DEFAULT_AXES = ("dp", "tp")


def build():
    return build_mesh(dp=4, tp=2)


def place(x, mesh):
    # 'model' is Megatron vocabulary, not this mesh's.
    return constrain(x, mesh, P("model", None))
