"""Seeded HVD804 fixture: a value carrying a sharding layout flows into
a collective that serializes its dims and bytes but discards the spec —
collective identity degrades to the 5-column form for exactly the
tensors whose layout most needs witnessing."""
import horovod_tpu as hvd
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.parallel import shard_params
from horovod_tpu.parallel.sharding import constrain


def sync_sharded_params(params, mesh, rules):
    placed = shard_params(params, mesh, rules)
    # spec= omitted: the layout shard_params just applied is dropped.
    return hvd.allreduce(placed, name="params")


def gather_constrained(x, mesh):
    y = constrain(x, mesh, P("dp"))
    return hvd.allgather(y, name="acts")


def put_with_layout(x, mesh):
    z = jax.device_put(x, NamedSharding(mesh, P("tp")))
    return hvd.broadcast(z, root_rank=0, name="init")


def put_without_layout(x, device):
    # device_put with no sharding ctor produces no layout: not a drop.
    w = jax.device_put(x, device)
    return hvd.allreduce(w, name="plain")
