"""Clean twin of dead_rule.py: every rule matches a synthesized path
and the sharded family is covered whole (w[qk]), so no path replicates
while a sibling shards."""
import flax.linen as nn
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import ShardingRules

DEFAULT_AXES = ("dp", "tp")


class Attention(nn.Module):
    def setup(self):
        self.wq = nn.Dense(64, name="attn/wq")
        self.wk = nn.Dense(64, name="attn/wk")


RULES = ShardingRules([
    (r"attn/w[qk]", P(None, "tp")),
])
