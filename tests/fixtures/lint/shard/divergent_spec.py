"""Seeded HVD803 fixture: a rank-tainted branch whose arms agree on the
collective op sequence (negotiation proceeds) but disagree on the
sharding spec — the data plane then folds differently-partitioned bytes
into one reduction."""
import horovod_tpu as hvd


def rank_gated_spec(t, rank):
    if rank == 0:
        hvd.allreduce(t, name="grads/w", spec="(tp,*)")
    else:
        hvd.allreduce(t, name="grads/w", spec="(dp,*)")
    return hvd.allreduce(t, name="step")


def deep_spec(t):
    if hvd.rank() % 2 == 0:
        _leg(t, "(dp)")
    else:
        _leg(t, "(tp)")


def _leg(t, sp):
    # Dynamic spec harvests as '' on both arms — equal, NOT a finding:
    # imprecision loses columns, never invents divergence.
    return hvd.allgather(t, name="acts", spec=sp)
