"""Seeded hvdlint violation: collective invoked while holding a lock
(HVD301). The background loop's completion callback takes the same lock
to publish results -> classic lock-ordering deadlock."""
import threading

import horovod_tpu as hvd

_state_lock = threading.Lock()
_results = {}


def broken_locked_allreduce(tensor):
    with _state_lock:
        _results["grad"] = hvd.allreduce(tensor, name="grad")   # HVD301
    return _results["grad"]


class Worker:
    def __init__(self):
        self._mutex = threading.Lock()

    def broken_locked_barrier(self):
        with self._mutex:
            hvd.enqueue_barrier()                               # HVD301
