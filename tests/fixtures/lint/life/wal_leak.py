"""Seeded hvdlife fixture: HVD702/HVD704 — a rendezvous replica that
opens the WAL group-commit lane and the log-tail replicator per world
epoch and never releases either: the WAL fd + fsync thread and the
tail thread survive every reinit_world cycle (one leaked fd + two
threads per elastic transition)."""
from horovod_tpu.runner.controlplane import Replicator, WalWriter


class LeakyReplica:
    def __init__(self, path):
        self.wal = WalWriter(path)                            # HVD702
        self.tail = Replicator(self)                          # HVD702

    def close(self):
        self.wal = None     # drops both handles, never .close()
        self.tail = None


def reinit_world(rank, size):
    """Epoch root: one leaked WAL lane + replicator per cycle."""
    replica = LeakyReplica(f"/tmp/wal-{rank}")                # HVD704
    return replica
