"""Seeded hvdlife fixture: HVD701 unjoined-thread — a Thread and a
Timer bound to owner fields with a teardown that releases neither, plus
the fire-and-forget shape that keeps no handle at all."""
import threading


class Monitor:
    def __init__(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fx-monitor")    # HVD701
        self._thread.start()
        self._timer = threading.Timer(5.0, self._fire)        # HVD701
        self._timer.start()

    def _loop(self):
        while not getattr(self, "_done", False):
            pass

    def _fire(self):
        pass

    def close(self):
        self._done = True        # flips the flag, reaps nothing


def fire_and_forget(work):
    threading.Thread(target=work, daemon=True).start()        # HVD701
