"""Seeded hvdlife fixture: HVD704 epoch-scoped-leak — AND the runtime
census seed.

The module mimics the world-transition shape: ``init`` acquires a
per-epoch staging handle, ``reinit_world`` re-forms the world by
calling it again (interprocedurally — the acquisition itself is one
hop below the epoch root), and ``shutdown`` tears down *nothing*.
Statically this is exactly HVD704: the acquisition is reachable from
the formation path with no release reachable from the teardown half.

The same file is IMPORTED by the 4-rank grow-shrink battery
(tests/mp_worker.py, ``life_census``) with the leak armed: each elastic
transition then pins one more real socket fd, and the runtime census
witness catches the identical leak the static rule names — the two
halves of the acceptance criterion fire on one seed.
"""
import socket

_scratch_by_epoch = {}
_epoch = 0


def init():
    """Acquire this epoch's staging handle (and never release the
    previous epoch's — the seeded leak)."""
    global _epoch
    _epoch += 1
    _scratch_by_epoch[_epoch] = socket.socket()               # HVD704


def reinit_world():
    init()


def shutdown():
    pass                        # no close anywhere: the leak


def leaked_count() -> int:
    return len(_scratch_by_epoch)


def release_all():
    """Test epilogue only (never reachable from shutdown, so the
    static finding stands)."""
    for sock in _scratch_by_epoch.values():
        sock.close()
    _scratch_by_epoch.clear()
