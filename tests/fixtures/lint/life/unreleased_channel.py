"""Seeded hvdlife fixture: HVD702 unreleased-channel — sockets bound
to owner fields with a teardown that never closes them."""
import socket


class Lane:
    def __init__(self, addr):
        self._sock = socket.create_connection(addr)           # HVD702
        self._listener = socket.socket()                      # HVD702

    def stop(self):
        self._connected = False    # forgets both sockets
