"""hvdlife clean fixture: every sanctioned lifecycle shape must report
ZERO findings — with-managed acquisition, registration into the
resources drain, same-function formation release, loop release over
the owning container, local-alias release, poison-then-join through a
helper (the interprocedural release-via-helper case), a cancelled
timer, and a justified suppression."""
import mmap
import queue
import socket
import threading


class CleanOwner:
    """Poison-first teardown, with the actual releases one call DEEPER
    than the teardown root (close -> _teardown): the pass must prove
    reachability through the call graph, not just scan close()."""

    def __init__(self, path):
        self._q = queue.Queue(maxsize=8)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fx-clean")
        self._thread.start()
        self._sock = socket.socket()
        self._log = open(path, "a")
        self._timer = threading.Timer(1.0, self._fire)
        self._timer.start()

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return

    def _fire(self):
        pass

    def close(self):
        self._q.put(None)            # poison first (the HVD705 wakeup)
        self._timer.cancel()
        self._teardown()

    def _teardown(self):
        self._thread.join(timeout=5.0)
        self._sock.close()
        self._log.close()


class CleanMesh:
    """Container-held sockets released by iterating the container."""

    def __init__(self, n):
        self._socks = {}
        for peer in range(n):
            self._socks[peer] = socket.socket()

    def close(self):
        for sock in self._socks.values():
            sock.close()
        self._socks.clear()


class CleanRegion:
    """Local-alias release: the teardown swaps the field out first."""

    def __init__(self, fd):
        self._map = mmap.mmap(fd, 4096)

    def close(self):
        mm, self._map = self._map, None
        if mm is not None:
            mm.close()


def managed(path):
    with open(path) as f:            # context manager: auto-released
        return f.read()


def registered(world):
    world.resources.append(socket.socket())   # drained by shutdown


def formation():
    listener = socket.socket()       # same-function formation release
    port = listener.getsockname()
    listener.close()
    return port


class Documented:
    def __init__(self):
        self._beacon = socket.socket()  # hvdlint: disable=HVD702 -- fixture: documenting the suppression form; the beacon rides the process lifetime by design
