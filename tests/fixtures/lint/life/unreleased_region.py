"""Seeded hvdlife fixture: HVD703 unreleased-region — an mmap region
and an opened file whose owner teardown releases neither."""
import mmap


class Region:
    def __init__(self, fd, path):
        self._map = mmap.mmap(fd, 4096)                       # HVD703
        self._log = open(path, "a")                           # HVD703

    def close(self):
        self._attached = False     # drops neither the map nor the file
