"""Seeded hvdlife fixture: HVD705 blocking-thread-without-wakeup —
the wedged-sender shape: the worker blocks on an unbounded queue get
and the owner's teardown only joins (no poison pill, no close/shutdown
to unblock it), so stop() waits out its grace and leaks the thread."""
import queue
import threading


class Pump:
    def __init__(self):
        self._q = queue.Queue(maxsize=8)
        self._thread = threading.Thread(target=self._loop,
                                        daemon=True)          # HVD705
        self._thread.start()

    def _loop(self):
        while True:
            item = self._q.get()     # unbounded; nothing ever wakes it
            if item is Ellipsis:
                return

    def stop(self):
        self._thread.join(timeout=10.0)   # join-without-poison
