"""Seeded hvdlife fixture: HVD702/HVD704 — a serving-style executor
that builds a paged KV-block pool per world epoch and never releases
it: the pool's residency accounting (and the HBM rows its block ids
index in the model cache) survives every reinit_world cycle."""
from horovod_tpu.serving.kvpool import KVBlockPool


class LeakyExecutor:
    def __init__(self):
        self.pool = KVBlockPool(32, 16)                       # HVD702

    def close(self):
        self.pool = None    # drops the handle, never pool.close()


def reinit_world(rank, size):
    """Epoch root: one leaked pool per elastic cycle (HVD704)."""
    ex = LeakyExecutor()                                      # HVD704
    return ex
