"""Seeded hvdlint violation: rank-gated collective (HVD101).

Classic broken pattern: only rank 0 submits the allreduce, every other
rank hangs in negotiation forever (ADVICE.md's kv_barrier seq-drift
stall is the same failure class).
"""
import horovod_tpu as hvd


def broken_conditional(tensor):
    if hvd.rank() == 0:
        return hvd.allreduce(tensor, name="grad")     # HVD101
    return tensor


def broken_guard(tensor, ctrl):
    return ctrl.is_coordinator and hvd.allgather(tensor)   # HVD101


def broken_loop(tensor):
    while hvd.local_rank() != 0:
        hvd.broadcast(tensor, root_rank=0)            # HVD101
