"""Seeded HVD1006 violations: unbounded queues / blocking handoffs in a
serving/ hot path (tests/test_lint_clean.py asserts exactly these)."""
import queue


def ingress_unbounded():
    return queue.Queue()                       # line 7: no maxsize


def drain_forever(work_queue):
    return work_queue.get()                    # line 11: no timeout


def buffer_forever(q, item):
    q.put(item)                                # line 15: no timeout


def no_bound_at_all():
    return queue.SimpleQueue()                 # line 19: unboundable


def bounded_and_shedding(q, item, deadline):
    ok = queue.Queue(maxsize=128)              # bounded ctor: clean
    q.put(item, timeout=deadline)              # deadline-bounded: clean
    q.put_nowait(item)                         # non-blocking: clean
    try:
        return ok, q.get(block=False)          # shedding pop: clean
    except queue.Empty:
        return ok, None


def not_a_queue(labels, knob):
    # dict.get / config-knob .get() must never trip the rule.
    return labels.get("peer", "0"), knob.get()
