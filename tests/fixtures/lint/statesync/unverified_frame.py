"""Seeded HVD1007 violations: streamed-state reads that bypass the
digest/epoch verification (and the clean forms that pass)."""
import numpy as np


def unflatten_state(buf, template):   # consumption primitive: exempt
    return np.frombuffer(buf, dtype=np.float32)


def apply_streamed_state(image, template):
    # BAD: the image came off the wire and nothing verified it.
    return unflatten_state(image, template)          # <- HVD1007


def apply_chunk_blind(frame, image):
    # BAD: payload written into live state without a stamp check.
    consume_payload(frame, image)                    # <- HVD1007


def consume_payload(frame, image):   # primitive: exempt by name
    image[frame["o"]:frame["o"] + frame["n"]] = frame["payload"]


def apply_verified_state(image, stamp, template):
    # OK: digest checked in the same scope before the read.
    if state_digest(image) != stamp.digest:
        raise ValueError("stale or torn snapshot rejected")
    return unflatten_state(image, template)


def pull_and_apply(puller, template):
    # OK: pull_round digest-verifies before returning.
    image, _stamp = puller.pull_round(0)
    return unflatten_state(image, template)


def state_digest(image):
    return 0
