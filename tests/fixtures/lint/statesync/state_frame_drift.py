"""Seeded HVD505 fixture: a statesync-style STATE_MAGIC frame codec
whose pack and unpack halves drifted apart — every check the rule makes
fires once (header struct format, header field order, magic prefix,
duplicate frame-kind wire values).  Never imported; parsed by
tests/test_hvdsan.py."""
import struct

MAGIC_A = b"\xffFIXSTATE\xff"
MAGIC_B = b"\xffFIXDRIFT\xff"
_HDR_A = struct.Struct(">BI")
_HDR_B = struct.Struct(">IB")

STATE_PING = 1
STATE_PONG = 1          # duplicate wire value: PONG frames dispatch as PING
STATE_DONE = 3


def pack_state_frame(kind, meta, payload=b""):
    meta_raw = bytes(meta)
    head = MAGIC_A + _HDR_A.pack(kind, len(meta_raw)) + meta_raw
    return head + bytes(payload)


def unpack_state_frame(raw):
    view = memoryview(raw)
    n_magic = len(MAGIC_B)
    if bytes(view[:n_magic]) != MAGIC_B:       # wrong magic
        raise ValueError("not a state frame")
    # swapped header fields vs the pack side, via a different struct
    meta_len, kind = _HDR_B.unpack_from(view, n_magic)
    meta_start = n_magic + _HDR_B.size
    meta = bytes(view[meta_start:meta_start + meta_len])
    return kind, meta, view[meta_start + meta_len:]
