"""Legal idioms hvdflow must NOT flag: rank-0-only non-collective work,
sequence-equal arms, and branches on exchanged (rank-symmetric) values."""
import horovod_tpu as hvd


def rank0_logging(t, rank):
    if rank == 0:
        print("step done", t.shape)
    return hvd.allreduce(t, name="ok")


def equal_arms(t, rank):
    if rank == 0:
        out = hvd.allreduce(t, name="same")
    else:
        out = hvd.allreduce(t, name="same")
    return out


def symmetric_views(t):
    # allgather results are identical on every rank: branching on them
    # is the sanctioned membership-agreement idiom, not a divergence.
    views = hvd.allgather_object({"x": hvd.rank()}, name="views")
    if max(v["x"] for v in views) > 2:
        hvd.allreduce(t, name="agreed")


def world_sized(t, rank, size):
    # `size` is world-symmetric even when it arrives through the same
    # tuple as a rank: branching on it cannot diverge the stream.
    rank, size = _resolve_world()
    if size > 1:
        hvd.allreduce(t, name="multi")


def _resolve_world():
    return hvd.rank(), 4
