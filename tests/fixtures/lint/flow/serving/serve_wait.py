"""Seeded HVD603 fixture: the serve dispatch loop reaches unbounded
blocking waits (a queue handoff and a transport recv, one call deep)
with no deadline_scope/op_scope/op_timeout anywhere on the path."""


def serve_loop(q, ch):
    while True:
        plan = _next_plan(q)
        _dispatch(ch, plan)


def _next_plan(q):
    return q.get()


def _dispatch(ch, plan):
    ch.send(plan)
    return ch.recv()
