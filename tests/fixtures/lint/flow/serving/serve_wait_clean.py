"""HVD603 clean twin: the same shape with the waits bounded — the queue
pop carries a timeout and the dispatch leg runs under deadline_scope,
so every wait on the path inherits a request-derived bound."""
from horovod_tpu.resilience import deadline_scope


def serve_loop(q, ch, slo_s):
    while True:
        plan = q.get(timeout=0.1)
        with deadline_scope(slo_s):
            _dispatch(ch, plan)


def _dispatch(ch, plan):
    ch.send(plan)
    return ch.recv()
