"""Loops hvdflow must NOT flag: fixed and world-symmetric trip counts."""
import horovod_tpu as hvd


def fixed_rounds(t):
    for _ in range(4):
        hvd.allreduce(t, name="fixed")


def world_rounds(t, size):
    for _ in range(size):
        hvd.allreduce(t, name="world")
