"""Seeded HVD604 fixtures: raw environment reads of HOROVOD_* names the
typed registry (common/config.py) does not declare."""
import os


def bad_get():
    return os.environ.get("HOROVOD_TOTALLY_UNDECLARED")


def bad_subscript():
    return os.environ["HOROVOD_ALSO_UNDECLARED"]


def bad_getenv():
    return os.getenv("HOROVOD_UNDECLARED_THREE", "0")
