"""HVD604 clean twin: registered reads, child-env writes (launchers
assembling a worker environment are not reads), and non-HOROVOD vars."""
import os


def registered_read():
    return os.environ.get("HOROVOD_FUSION_THRESHOLD")


def launcher_write(env):
    env["HOROVOD_RANK"] = "0"
    os.environ["HOROVOD_NOT_A_KNOB_BUT_A_WRITE"] = "1"


def non_horovod():
    return os.environ.get("PATH", "")
