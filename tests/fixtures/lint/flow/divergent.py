"""Seeded HVD601 fixtures: rank-gated collective streams, direct and
buried three calls deep (the case per-line hvdlint cannot see)."""
import horovod_tpu as hvd


def direct(t, rank):
    if rank == 0:
        hvd.allreduce(t, name="extra")
    return hvd.allreduce(t, name="step")


def _deep3(t):
    return hvd.allreduce(t, name="buried")


def _deep2(t):
    return _deep3(t)


def _deep1(t):
    return _deep2(t)


def interprocedural(t):
    if hvd.rank() == 0:
        _deep1(t)
    return hvd.allreduce(t, name="after")


def asymmetric_arms(t, rank):
    if rank % 2 == 0:
        hvd.allreduce(t, name="even")
    else:
        hvd.allgather(t, name="odd")
