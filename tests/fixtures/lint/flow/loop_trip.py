"""Seeded HVD602 fixtures: collectives inside rank-tainted loop trips."""
import horovod_tpu as hvd


def per_rank_rounds(t, rank):
    for _ in range(rank):
        hvd.allreduce(t, name="per")


def while_rank(t):
    r = hvd.rank()
    while r > 0:
        hvd.broadcast(t, root_rank=0)
        r -= 1
