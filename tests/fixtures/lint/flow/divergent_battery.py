"""The shared seed of the 4-rank flow battery (test_multiprocess
test_flow_divergence_caught_static_and_runtime): the SAME rank-gated
collective below is caught

- statically by hvdflow — HVD601 names the tainted branch in
  ``rank_gated_step`` and carries the would-be fingerprint stream of
  both arms ([allreduce(flow_extra)] vs []), and
- at runtime by collective fingerprinting — the seeded rank submits
  ``flow_extra`` while its peers submit ``flow_step``, and every rank
  receives the structured divergence ERROR within one strict-mode
  negotiation cycle.
"""


def _extra_sync(hvd, t):
    hvd.allreduce(t, name="flow_extra")


def rank_gated_step(hvd, t, rank, seed_rank):
    if rank == seed_rank:
        _extra_sync(hvd, t)
    return hvd.allreduce(t, name="flow_step")
