"""Multi-process parallel tests: N real worker processes on localhost
against one rendezvous server — the reference's `mpirun -np 2 pytest`
pattern without MPI (SURVEY §4 "multi-node-without-a-cluster trick")."""
import os
import subprocess
import sys

import pytest

from horovod_tpu.runner.network import RendezvousServer

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "mp_worker.py")


def _run_world(size: int, battery: str, timeout: float = 90.0,
               expected_rcs: dict | None = None,
               extra_env: dict | None = None) -> list[str]:
    """Spawn `size` workers against one rendezvous server; assert each
    rank's exit code (0 by default; override per rank via expected_rcs,
    e.g. {1: 37} for a fault-injection battery). Returns per-rank
    output."""
    server = RendezvousServer()
    port = server.start()
    env = dict(os.environ)
    env.pop("HOROVOD_RANK", None)
    env.pop("HOROVOD_SIZE", None)
    # A stale seed list inherited from the outer environment would point
    # workers at a dead control plane; mp_worker defaults to localhost
    # and replicated harnesses pass their seed list via extra_env.
    env.pop("HOROVOD_GLOO_RENDEZVOUS_ADDR", None)
    env["HOROVOD_RENDEZVOUS_EPOCH"] = f"{battery}{size}"
    env.update(extra_env or {})
    procs = [
        subprocess.Popen([sys.executable, _WORKER, str(r), str(size),
                          str(port), battery],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT)
        for r in range(size)
    ]
    failed = []
    outputs = []
    try:
        for r, p in enumerate(procs):
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                failed.append((r, "timeout"))
            outputs.append(f"--- rank {r} (rc={p.returncode}) ---\n"
                           + out.decode(errors="replace"))
            if p.returncode != (expected_rcs or {}).get(r, 0):
                failed.append((r, p.returncode))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
    assert not failed, "worker failures: %s\n%s" % (failed, "\n".join(outputs))
    return outputs


@pytest.mark.parametrize("size", [2, 3])
def test_collectives(size):
    _run_world(size, "collectives")


@pytest.mark.parametrize("size", [2, 3])
def test_semantic_matrix(size):
    """Reference-scale dtype x op sweep (VERDICT r2 item 6; size 3 also
    exercises the non-power-of-2 ring schedule)."""
    _run_world(size, "matrix", timeout=180.0)


def test_error_handling():
    _run_world(2, "errors")


def test_stall_inspector_aborts_stalled_world():
    """Reference test/integration/test_stall.py analogue: a one-sided
    collective must abort with a structured error, not hang."""
    _run_world(2, "stall", timeout=120.0)


def test_join_uneven_data():
    _run_world(2, "join")


def test_telemetry_observability_4rank():
    """ISSUE 4 acceptance: a 4-rank HOROVOD_METRICS=on world produces a
    Prometheus scrape (asserted in-battery over real HTTP) and a JSON
    dump containing per-plane collective-latency histograms, per-peer
    byte counters and the coordinator straggler-skew gauge; with rank 3
    delayed 50 ms/step the coordinator names it within two windows."""
    import json
    import glob
    for stale in glob.glob("/tmp/hvd_tm_telemetry4.r*.json"):
        os.unlink(stale)
    _run_world(4, "telemetry", timeout=240.0)
    path = "/tmp/hvd_tm_telemetry4.r0.json"
    assert os.path.exists(path), "rank 0 never wrote its metrics dump"
    with open(path) as f:
        snap = json.load(f)
    metrics = snap["metrics"]
    names = {m["name"] for m in metrics}
    # Per-plane collective-latency histograms…
    assert any(m["name"] == "horovod_collective_latency_ms"
               and m["labels"].get("plane") == "tcp"
               and m["count"] > 0 for m in metrics), names
    # …per-peer byte counters…
    peers = {m["labels"]["peer"] for m in metrics
             if m["name"] == "horovod_tcp_bytes_sent_total"
             and m["value"] > 0}
    assert {"1", "2", "3"} <= peers, peers
    # …and the coordinator straggler gauge naming rank 3.
    straggler = next(m for m in metrics
                     if m["name"] == "horovod_controller_straggler_rank")
    assert straggler["value"] == 3.0, straggler
    lag = next(m for m in metrics
               if m["name"] == "horovod_controller_straggler_lag_ms")
    assert lag["value"] > 20.0, lag
    # Every rank dumped (identical env, rank-suffixed paths).
    for r in range(4):
        assert os.path.exists(f"/tmp/hvd_tm_telemetry4.r{r}.json"), r
    # The report CLI summarizes the dump into the per-activity table.
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.telemetry.report", path],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "horovod_collective_latency_ms" in proc.stdout
    assert "horovod_controller_straggler_rank" in proc.stdout


def test_trace_merge_and_critical_path_4rank():
    """ISSUE 7 acceptance: a 4-rank world writes one timeline file per
    rank; the merged trace contains flow-linked spans for the same
    collective on all four ranks with per-rank clock-offset metadata,
    and --critical-path names the chaos-delayed rank (freeze injection,
    PR 5) and its dominant phase."""
    import glob
    import json

    from horovod_tpu.telemetry import trace as trace_mod

    for stale in glob.glob("/tmp/hvd_trace_trace4*.json"):
        os.unlink(stale)
    _run_world(4, "trace", timeout=240.0)
    base = "/tmp/hvd_trace_trace4.json"
    paths = [base] + [f"/tmp/hvd_trace_trace4.r{r}.json"
                      for r in (1, 2, 3)]
    for p in paths:
        assert os.path.exists(p), f"missing per-rank timeline {p}"

    traces = trace_mod.load(paths)
    assert [t.rank for t in traces] == [0, 1, 2, 3]
    for t in traces[1:]:
        # Clock-offset metadata from the init-time round-trip probes.
        assert t.clock_rtt_us > 0.0, (t.rank, t.clock_rtt_us)

    merged = trace_mod.merge(traces)
    flows: dict = {}
    for e in merged:
        if e.get("ph") in ("s", "f"):
            flows.setdefault(e["id"], []).append(e)
    # The same collective is flow-linked on ALL four ranks for most of
    # the tr_* steps (one 's' source + three 'f' bind points).
    full = [i for i, evs in flows.items()
            if sorted(e["ph"] for e in evs) == ["f", "f", "f", "s"]]
    assert len(full) >= 8, {i: len(v) for i, v in flows.items()}

    report = trace_mod.critical_path_report(traces, window=16)
    assert "critical path: rank 3, phase negotiate" in report, report

    # The report CLI drives the same path end to end.
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.telemetry.trace",
         *paths, "-o", "/tmp/hvd_trace_trace4_merged.json",
         "--critical-path", "--window", "16"],
        capture_output=True, text=True, timeout=60,
        cwd=os.path.dirname(_WORKER) + "/..")
    assert proc.returncode == 0, proc.stderr
    assert "critical path: rank 3" in proc.stdout, proc.stdout
    merged_file = json.load(open("/tmp/hvd_trace_trace4_merged.json"))
    assert any(e.get("ph") == "s" for e in merged_file)


@pytest.mark.parametrize("size", [2, 4])
def test_lock_witness_matches_static_graph(size):
    """ISSUE 8 acceptance: 2/4-rank worlds under HOROVOD_SAN=1 dump
    their observed lock-order graphs at shutdown; every observed edge
    must exist in hvdsan's static graph (an edge missing there means
    the analyzer is unsound on an exercised path -> fail the build),
    the controller<->transport edges are present and identity-mapped
    on every rank, and static cycles never observed demote to
    warnings."""
    import glob
    import json

    from horovod_tpu.analysis.hvdsan import san as san_mod

    for stale in glob.glob(f"/tmp/hvd_san_san{size}*.json"):
        os.unlink(stale)
    _run_world(size, "san", timeout=180.0)
    paths = [f"/tmp/hvd_san_san{size}.json"] + \
        [f"/tmp/hvd_san_san{size}.r{r}.json" for r in range(1, size)]
    payloads = []
    for p in paths:
        assert os.path.exists(p), f"missing witness dump {p}"
        with open(p) as f:
            payloads.append(json.load(f))

    analysis = san_mod.analyze(["horovod_tpu"])
    problems = san_mod.witness_diff(analysis, payloads)
    assert problems == [], "\n".join(problems)

    site_map = analysis.site_to_lock()
    for rank, payload in enumerate(payloads):
        assert payload["rank"] == rank
        observed = {(site_map[e["src"]], site_map[e["dst"]])
                    for e in payload["edges"]}
        # init held core._init_lock while the clock probes crossed the
        # ctrl mesh (controller<->transport), and while the tensor
        # queue reset (controller<->queue).
        assert ("core._init_lock",
                "runner.network.PeerMesh._lock") in observed, \
            (rank, sorted(observed))
        assert ("core._init_lock",
                "common.tensor_queue.TensorQueue._mutex") in observed
    # Demotion pass: at head there are no static cycles, so the error
    # set stays empty with the witness applied.
    san_mod.apply_witness(analysis, payloads)
    assert [f for f in analysis.findings
            if f.severity == "error"] == []


@pytest.mark.parametrize("size", [2, 4])
def test_multistream_dispatch(size):
    """HOROVOD_NUM_STREAMS=2 over the TCP plane: independent responses
    of one cycle execute concurrently on per-stream channel sets with
    deterministic rank-symmetric assignment (ISSUE 3 tentpole); results
    exact, both streams carry traffic, steady state spawns no threads."""
    _run_world(size, "streams", timeout=120.0)


@pytest.mark.parametrize("size", [2, 3])
def test_shm_data_plane(size):
    """Same-host shared-memory allreduce plane: selection, flat-path
    results, capacity fall-through, mixed-op lockstep (size 3 exercises
    the chunked reduce, size 2 the fused-sum fast path)."""
    _run_world(size, "shm", timeout=120.0)


@pytest.mark.parametrize("local_plane", ["shm", "tcp"])
def test_hierarchical_collectives(local_plane):
    """Eager two-level allreduce/allgather over local/cross sub-meshes:
    4 ranks as 2 hosts x 2 slots (VERDICT r3 item 3; reference:
    nccl_operations.cc:187-398).  The intra-host legs ride the per-host
    shm world when one forms, TCP loopback otherwise — both planes must
    produce flat-path results."""
    _run_world(4, "hierarchical" if local_plane == "shm"
               else "hierarchical_tcp", timeout=120.0)


@pytest.mark.parametrize("size", [
    # The size-2 battery imports torch AND tensorflow in every worker
    # (the serialization bottleneck noted below) for the framework
    # delta-optimizer glue; the numpy-only size-4 twin keeps the
    # two-level VHDD pairing algorithm in tier-1 and the torch/tf
    # binding surfaces stay via test_torch_full_2rank /
    # test_tensorflow_full_2rank (tier-1 wall clock, round 6).
    pytest.param(2, marks=pytest.mark.slow),
    4,
])
def test_adasum(size):
    # Generous timeout: workers import torch AND tensorflow for the
    # delta-optimizer checks, which serializes badly under CI load — so
    # the framework halves run at size 2 only, and size 4 covers the
    # two-level VHDD pairing numpy-only.
    _run_world(size, "adasum" if size == 2 else "adasum_np",
               timeout=300.0)


@pytest.mark.parametrize("size", [2, 4])
def test_xla_data_plane(size):
    """Eager collectives ride XLA device collectives when the JAX world
    spans the ranks (VERDICT r1 item 3)."""
    _run_world(size, "xla", timeout=240.0)


def test_torch_full_2rank():
    """Torch binding battery set — DistributedOptimizer, dtype×variant
    grid, sparse gather path, sync-BN — in ONE 2-rank world: the
    per-rank torch import dominated four separate worlds' wall clock
    (reference CI groups framework tests per container the same way)."""
    _run_world(2, "torch_all", timeout=420.0)


@pytest.mark.slow
def test_torch_distributed_optimizer_4rank():
    """4-rank scale-out of the DistributedOptimizer battery.  The
    2-rank torch_all world and the 3-rank binding grid keep the
    optimizer surface in tier-1; the 4x torch-import world is
    scale-redundant there, so it rides the slow tier."""
    _run_world(4, "torch", timeout=120.0)


def test_tensorflow_full_2rank():
    """TF binding battery set — eager ops, dtype grid, tf.function graph
    mode / model.fit / gradient aggregation / Keras elastic — in ONE
    2-rank world (TF import is the dominant per-world cost)."""
    pytest.importorskip("tensorflow")
    _run_world(2, "tensorflow_all", timeout=600.0)


def test_mxnet_binding():
    """MXNet surface over the eager core with the stub module
    (reference: test/parallel/test_mxnet1.py patterns)."""
    _run_world(2, "mxnet")


def test_peer_death_surfaces_not_hangs():
    """A rank dying mid-run (os._exit) must surface as
    HorovodInternalError on the survivor within the timeout — the
    verify-skill probe as a regression test (SURVEY §5.3). Timeout is
    2x the worker transport timeout so a legitimate slow detection
    reports through the assertion path, not a raw TimeoutExpired."""
    outputs = _run_world(2, "peerdeath", timeout=180.0,
                         expected_rcs={1: 37})
    assert "HorovodInternalError" in outputs[0]


def test_torch_binding_grid_3rank():
    """Torch surface dtype x variant sweep at size 3 (uneven shards;
    reference: test/parallel/test_torch.py grid).  The 2-rank sweep runs
    inside test_torch_full_2rank's shared world."""
    _run_world(3, "torch_grid", timeout=180.0)


def test_flow_divergence_caught_static_and_runtime():
    """ISSUE 12 acceptance: ONE seeded rank-gated collective
    (tests/fixtures/lint/flow/divergent_battery.py) is caught BOTH

    - statically: hvdflow HVD601 names the tainted branch site and
      carries the would-be fingerprint stream of the two arms, and
    - at runtime: a 4-rank HOROVOD_FINGERPRINT=strict world answers the
      same gated collective with the structured divergence ERROR on
      EVERY rank, naming the divergent op.
    """
    from horovod_tpu.analysis.hvdflow.flow import analyze_paths
    fixture = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures", "lint", "flow",
                           "divergent_battery.py")
    findings = analyze_paths([fixture])
    assert [f.rule.id for f in findings] == ["HVD601"]
    finding = findings[0]
    with open(fixture) as f:
        lines = f.read().splitlines()
    gate_line = next(i for i, ln in enumerate(lines, start=1)
                     if "if rank == seed_rank:" in ln)
    assert finding.line == gate_line          # names the branch site
    # …and carries the fingerprint stream diff of the two arms.
    assert "allreduce(flow_extra)" in finding.message
    assert "(empty)" in finding.message
    assert "HOROVOD_FINGERPRINT" in finding.message

    outputs = _run_world(4, "flow", timeout=120.0,
                         extra_env={"HOROVOD_FINGERPRINT": "strict",
                                    "HOROVOD_FLOW_SEED_RANK": "2"})
    for r, out in enumerate(outputs):
        assert "FLOW_DIVERGENCE_CAUGHT" in out, \
            f"rank {r} missed the divergence ERROR:\n{out}"


def test_shard_spec_divergence_caught_static_and_runtime():
    """ISSUE 17 acceptance: ONE seeded spec-divergent collective
    (tests/fixtures/lint/shard/divergent_spec_battery.py) is caught
    BOTH

    - statically: hvdshard HVD803 names the tainted branch whose arms
      agree on the op sequence but disagree on sharding spec, carrying
      both arms' spec-annotated streams, and
    - at runtime: a 2-rank HOROVOD_FINGERPRINT=strict world folds
      op×name×dtype×dims×spec identity and answers the same gated
      collective with the structured divergence ERROR on EVERY rank,
      naming the first spec-divergent op and its spec tokens.
    """
    from horovod_tpu.analysis.hvdshard.shard import analyze_paths
    fixture = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures", "lint", "shard",
                           "divergent_spec_battery.py")
    findings = analyze_paths([fixture])
    assert [f.rule.id for f in findings] == ["HVD803"]
    finding = findings[0]
    with open(fixture) as f:
        lines = f.read().splitlines()
    gate_line = next(i for i, ln in enumerate(lines, start=1)
                     if "if rank == seed_rank:" in ln)
    assert finding.line == gate_line          # names the branch site
    # …and carries the spec-annotated stream diff of the two arms.
    assert "allreduce(shard_step|(dp,*))" in finding.message
    assert "allreduce(shard_step|(tp,*))" in finding.message
    assert "HOROVOD_FINGERPRINT" in finding.message

    outputs = _run_world(2, "shard", timeout=120.0,
                         extra_env={"HOROVOD_FINGERPRINT": "strict",
                                    "HOROVOD_SHARD_SEED_RANK": "1"})
    for r, out in enumerate(outputs):
        assert "SHARD_DIVERGENCE_CAUGHT" in out, \
            f"rank {r} missed the spec-divergence ERROR:\n{out}"


def test_shard_mixed_world_negotiates_spec_off_and_stays_green():
    """ISSUE 17 mixed-world leg: with rank 1 pinned to the pre-sharding
    wire proto (HOROVOD_PROTO_COMPAT=2), every mesh negotiates
    FEATURE_SHARDING off — the SAME spec-divergent step that kills the
    native world completes fingerprint-green with correct numerics on
    both ranks (5-column identity everywhere; no half-folded world)."""
    outputs = _run_world(2, "shard_compat", timeout=120.0,
                         extra_env={"HOROVOD_FINGERPRINT": "strict"})
    for r, out in enumerate(outputs):
        assert "SHARD_COMPAT_GREEN" in out, \
            f"rank {r} not green in the proto-2 world:\n{out}"
