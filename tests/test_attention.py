"""Attention kernels and sequence parallelism: numerical equivalence of
flash (Pallas, interpreted), ring (ppermute over "sp"), and Ulysses
(all_to_all over "sp") against dense softmax attention — forward AND
gradients (SURVEY §5.7: long-context support is TPU-native, not ported).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.common.jax_compat import shard_map

from horovod_tpu.ops.flash_attention import (flash_attention,
                                             flash_attention_with_lse,
                                             mha_reference)
from horovod_tpu.parallel import MeshSpec, build_mesh
from horovod_tpu.parallel.ring_attention import ring_attention
from horovod_tpu.parallel.ulysses import ulysses_attention

B, T, H, D = 2, 64, 4, 32


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.key(0)
    return tuple(
        jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D),
                          jnp.float32) for i in range(3))


@pytest.mark.parametrize("causal", [False, True])
class TestFlashAttention:
    def test_forward_matches_reference(self, qkv, causal):
        q, k, v = qkv
        ref = mha_reference(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=16,
                              block_k=16, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_gradients_match_reference(self, qkv, causal):
        q, k, v = qkv

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()

        ref_fn = loss(partial(mha_reference, causal=causal))
        fl_fn = loss(partial(flash_attention, causal=causal, block_q=16,
                             block_k=16, interpret=True))
        g_ref = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(fl_fn, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-5)

    def test_bf16_forward_and_grads_match_dense(self, qkv, causal):
        # Pins the bf16 MXU-input path: on TPU the kernels feed the dots
        # bf16 operands with fp32 accumulation and downcast p/ds between
        # the two matmuls (p.astype(v.dtype), ds.astype(k.dtype)). The
        # fp32 tests above make every one of those casts a no-op; this
        # runs the identical kernel code on bf16 inputs (interpret mode)
        # so a misplaced cast — e.g. exp() in bf16, or accumulation
        # without preferred_element_type — shows up here, not as silent
        # loss degradation on hardware.
        q, k, v = (x.astype(jnp.bfloat16) for x in qkv)
        ref = mha_reference(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=16,
                              block_k=16, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            atol=0.05)   # bf16 has ~3 decimal digits

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v).astype(jnp.float32)
                                    ** 2).sum()

        g_ref = jax.grad(loss(partial(mha_reference, causal=causal)),
                         argnums=(0, 1, 2))(q, k, v)
        g_fl = jax.grad(loss(partial(flash_attention, causal=causal,
                                     block_q=16, block_k=16,
                                     interpret=True)),
                        argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_fl):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            rel = np.linalg.norm(b - a) / (1e-6 + np.linalg.norm(a))
            assert rel < 0.03, rel

    def test_lse_consistent(self, qkv, causal):
        q, k, v = qkv
        o, lse = flash_attention_with_lse(q, k, v, causal=causal,
                                          block_q=16, block_k=16,
                                          interpret=True)
        assert lse.shape == (B, H, T)
        # lse is the log-normalizer: exp(s - lse) sums to 1 per row.
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
        if causal:
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        sums = jnp.sum(jnp.exp(s - lse[..., None]), axis=-1)
        np.testing.assert_allclose(np.asarray(sums), 1.0, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
class TestRingAttention:
    def test_matches_dense(self, qkv, causal):
        q, k, v = qkv
        mesh = build_mesh(MeshSpec(dp=1, sp=8))
        ring = jax.jit(shard_map(
            partial(ring_attention, axis="sp", causal=causal, axis_size=8),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp")))
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(ring(q, k, v)),
                                   np.asarray(ref), atol=2e-5)

    def test_gradients_match_dense(self, qkv, causal):
        q, k, v = qkv
        mesh = build_mesh(MeshSpec(dp=1, sp=8))
        ring = shard_map(
            partial(ring_attention, axis="sp", causal=causal, axis_size=8),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"))
        g_ref = jax.grad(
            lambda q, k, v: (mha_reference(q, k, v, causal=causal)
                             ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.grad(
            lambda q, k, v: (ring(q, k, v) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_ring):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-5)

    def test_dp_sp_composition(self, qkv, causal):
        """Ring over sp composes with a dp-sharded batch."""
        q, k, v = qkv
        mesh = build_mesh(MeshSpec(dp=2, sp=4))
        ring = jax.jit(shard_map(
            partial(ring_attention, axis="sp", causal=causal, axis_size=4),
            mesh=mesh, in_specs=(P("dp", "sp"),) * 3,
            out_specs=P("dp", "sp")))
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(ring(q, k, v)),
                                   np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(qkv, causal):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(dp=2, sp=4))
    uly = jax.jit(shard_map(
        partial(ulysses_attention, axis="sp", causal=causal, axis_size=4),
        mesh=mesh, in_specs=(P("dp", "sp"),) * 3,
        out_specs=P("dp", "sp")))
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(uly(q, k, v)), np.asarray(ref),
                               atol=2e-5)


def test_ulysses_rejects_indivisible_heads(qkv):
    q, k, v = qkv    # H=4 heads
    mesh = build_mesh(MeshSpec(dp=1, sp=8))
    uly = shard_map(
        partial(ulysses_attention, axis="sp", axis_size=8),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(None, "sp"))
    with pytest.raises(ValueError, match="heads not divisible"):
        jax.jit(uly)(q, k, v)
