"""hvdmc — explicit-state model checking of the membership protocols
(ISSUE 11).

Spec DSL validation, the explicit-state kernel (BFS to fixpoint,
counterexample reconstruction, the AG-EF resolution check), the four
machines at head (zero violations with fault injection), the two
seeded spec mutations the acceptance demands (drop the torn-stamp
reject; ack a boundary before the digest verifies) with
rank-interleaved traces, the byte-for-byte golden counterexample of
the deliberately broken toy spec, the HVD506 spec<->code conformance
pass in both drift directions, the trace witness, and the CLI.

The mp-battery witness replay acceptance lives in
tests/test_statesync.py (_replay_witness).
"""
import json
import os
import shutil
import subprocess
import sys

import pytest

from horovod_tpu.analysis.hvdmc import (MUTATIONS, GrowModel,
                                        PreemptModel, ShrinkModel,
                                        ToyTornModel, all_specs,
                                        check_tree, explore,
                                        render_trace, witness_check)
from horovod_tpu.analysis.hvdmc.machines import toy_spec
from horovod_tpu.resilience.specs import shrink_spec
from horovod_tpu.statesync.specs import (grow_spec, preempt_spec,
                                         stream_spec)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TREE = os.path.join(REPO, "horovod_tpu")
GOLDEN = os.path.join(REPO, "tests", "fixtures", "mc",
                      "toy_torn_trace.txt")
FLEET_GOLDEN = os.path.join(REPO, "tests", "fixtures", "mc",
                            "fleet_swap_trace.txt")


# --- spec DSL ---------------------------------------------------------------
def test_all_specs_validate():
    specs = all_specs()
    assert {sp.name for sp in specs} == {
        "statesync-grow", "statesync-stream", "statesync-preempt",
        "resilience-shrink", "rendezvous-failover", "fleet-handoff"}
    for sp in specs + (toy_spec(),):
        assert sp.validate() == [], sp.name
        # Every transition id is unique across the registry too.
    tids = [t.tid for sp in specs for t in sp.transitions]
    assert len(tids) == len(set(tids))


def test_spec_observe_map_and_reachability():
    sp = preempt_spec()
    obs = sp.observed_map()
    assert obs["departed"] == ("pre.depart",)
    assert obs["sigterm-grace"] == ("pre.sigterm",)
    reach = sp.role_reachability("preemptee")
    assert "departed" in reach["run"]          # run ->* departed
    assert "run" not in reach["departed"]      # departure is final


def test_spec_validation_catches_malformed():
    from horovod_tpu.analysis.hvdmc.spec import (ProtocolSpec,
                                                 Transition)
    bad = ProtocolSpec(
        name="bad", doc="", roles=("a",), states={"a": ("s1",)},
        transitions=(
            Transition("t1", "a", "s1", "missing", "internal:x"),
            Transition("t1", "a", "s1", "s1", "recv:NOPE"),
        ))
    problems = bad.validate()
    assert any("missing" in p for p in problems)
    assert any("duplicate" in p for p in problems)
    assert any("NOPE" in p for p in problems)


# --- the checker at head ----------------------------------------------------
def test_grow_model_explores_to_fixpoint_with_zero_violations():
    """ISSUE 11 acceptance: the 3-rank grow protocol with fault
    injection (boundary-flag drop, chunk corruption, donor death
    mid-stream, joiner crash) explores to a fixpoint with a reported
    state count and zero safety/progress violations at head."""
    r = explore(GrowModel(3))
    assert r.fixpoint and r.violations == []
    assert r.states > 5000, r.states          # faults genuinely explored
    assert {"inc.boundary-admit", "inc.boundary-grow", "join.enter",
            "join.torn-reject", "net.flag-drop", "net.chunk-corrupt",
            "net.donor-death", "inc.formation-timeout"} <= r.fired


def test_preempt_and_shrink_models_clean_at_head():
    for model in (PreemptModel(3), ShrinkModel(3)):
        r = explore(model)
        assert r.fixpoint and r.violations == [], model.name
        assert r.states > 100, (model.name, r.states)
    r = explore(PreemptModel(3))
    assert {"pre.sigterm", "pre.depart", "sur.proactive-shrink",
            "pre.wedge", "pre.backstop", "sur.converge-shrink"} \
        <= r.fired
    r = explore(ShrinkModel(3))
    assert {"vic.crash", "vic.freeze", "sur.reraise-suspect",
            "sur.confirm-shrink", "sur.resync"} <= r.fired


def test_no_faults_mode_shrinks_the_space():
    full = explore(GrowModel(3)).states
    clean = explore(GrowModel(3, faults=False)).states
    assert clean < full


# --- seeded mutations (the checker must bite) -------------------------------
def test_mutation_drop_torn_reject_caught_with_trace():
    """Dropping the torn-stamp reject lets a boundary-flag drop commit
    a mixed-stamp image: the checker reports torn-commit with a
    rank-interleaved trace bound to the code sites."""
    m = GrowModel(3, mutations=("drop-torn-reject",))
    r = explore(m)
    assert r.fixpoint
    props = {v.prop for v in r.violations}
    assert "torn-commit" in props, props
    v = next(v for v in r.violations if v.prop == "torn-commit")
    trace = render_trace(m, v)
    assert "net.flag-drop" in trace
    assert "join.enter" in trace
    assert "statesync.service.StateSyncService._start_donation" in trace
    assert "statesync.stream.JoinerPuller._collect_metas" in trace
    # Rank-interleaved: several distinct actors appear.
    assert "rank 0" in trace and "joiner" in trace and "net" in trace


def test_mutation_early_ready_ack_caught_with_trace():
    """Acking the boundary before the digest verifies lets incumbents
    commit the grow boundary over an unverified image."""
    m = GrowModel(3, mutations=("early-ready-ack",))
    r = explore(m)
    assert r.fixpoint
    props = {v.prop for v in r.violations}
    assert "premature-boundary-ack" in props, props
    v = next(v for v in r.violations
             if v.prop == "premature-boundary-ack")
    trace = render_trace(m, v)
    assert "join.post-ready" in trace
    assert "inc.boundary-grow" in trace
    assert "statesync.service.StateSyncService._transition_grow" in trace


def test_unknown_mutation_rejected():
    with pytest.raises(ValueError):
        GrowModel(3, mutations=("no-such-guard",))
    assert set(MUTATIONS) == {"drop-torn-reject", "early-ready-ack",
                              "accept-stale-lease",
                              "swap-before-verify"}


# --- rendezvous failover (ISSUE 15) -----------------------------------------
def test_failover_model_clean_at_head():
    """The election protocol at head: leader death and the
    lease-lapse-then-return pause both explore to a fixpoint with no
    two-leaders state, no lost committed write, and every state able
    to reach all-writes-acked (clients converge, AG EF)."""
    from horovod_tpu.analysis.hvdmc.machines import FailoverModel

    r = explore(FailoverModel(3))
    assert r.fixpoint and r.violations == []
    assert r.states > 300, r.states
    assert {"pri.pause", "pri.die", "pri.resume-fenced",
            "pri.resume-reclaim", "sb.lapse", "sb.promote", "sb.lose",
            "cli.write", "cli.failover", "cli.converge",
            "pri.commit"} <= r.fired


def test_mutation_accept_stale_lease_caught_with_trace():
    """ISSUE 15 acceptance: dropping the epoch-fence re-verification
    (a resumed primary keeps serving on its stale lease) produces the
    two-leaders counterexample AND a committed write the promotion's
    replay drops — each with a trace bound to the control-plane code
    sites."""
    from horovod_tpu.analysis.hvdmc.machines import FailoverModel

    m = FailoverModel(3, mutations=("accept-stale-lease",))
    r = explore(m)
    assert r.fixpoint
    props = {v.prop for v in r.violations}
    assert "two-leaders" in props, props
    assert "committed-write-lost" in props, props
    v = next(v for v in r.violations if v.prop == "two-leaders")
    trace = render_trace(m, v)
    assert "sb.promote" in trace
    assert "pri.resume-reclaim" in trace
    assert "runner.controlplane.ControlPlane._try_promote" in trace
    assert "runner.controlplane.ControlPlane._reverify_lease" in trace
    lost = next(v for v in r.violations
                if v.prop == "committed-write-lost")
    lost_trace = render_trace(m, lost)
    assert "cli.write" in lost_trace and "pri.commit" in lost_trace
    assert "runner.network._kv_apply" in lost_trace


# --- fleet handoff (ISSUE 20) -----------------------------------------------
def test_fleet_model_clean_at_head():
    """The train<->serve handoff at head: migration journaling across a
    controller failover plus the publish/pull/verify/swap deployment
    pipeline (with the shard-corrupt fault live) explores to a fixpoint
    with zero violations — every journaled migration resolves and no
    unverified image is ever swapped in."""
    from horovod_tpu.analysis.hvdmc.machines import FleetModel

    r = explore(FleetModel(2))
    assert r.fixpoint and r.violations == []
    assert r.states > 100, r.states
    assert {"ctl.plan", "ctl.direct", "ctl.complete", "ctl.resume",
            "ctl.abort-planned", "mov.depart", "mov.join", "mov.arrive",
            "pub.head", "rep.verify-stage", "rep.verify-reject",
            "rep.swap", "net.failover",
            "net.shard-corrupt"} <= r.fired


def test_fleet_mutation_swap_before_verify_caught_with_golden_trace():
    """ISSUE 20 acceptance: dropping the digest-verify-before-stage
    guard lets the shard-corrupt fault drive a corrupt image through
    the staging path and into a plan-boundary swap.  The shortest
    counterexample is deterministic; the rendering is asserted
    byte-for-byte against the checked-in fixture."""
    from horovod_tpu.analysis.hvdmc.machines import FleetModel

    m = FleetModel(2, mutations=("swap-before-verify",))
    r = explore(m)
    assert r.fixpoint
    assert [v.prop for v in r.violations] == ["swap-verified"]
    trace = render_trace(m, r.violations[0])
    assert "net.shard-corrupt" in trace
    assert "rep.swap" in trace
    assert "fleet.deploy.WeightPuller.poll_once" in trace
    assert "serving.replica.ReplicaExecutor._apply_plan" in trace
    with open(FLEET_GOLDEN, "rb") as f:
        assert (trace + "\n").encode() == f.read()


def test_fleet_spec_binds_real_functions():
    from horovod_tpu.analysis.hvdsan.lockgraph import Program
    from horovod_tpu.fleet.specs import fleet_spec

    program = Program()
    program.collect_paths([TREE])
    missing = [(tr.tid, key) for tr in fleet_spec().transitions
               for key in tr.binds if key not in program.functions]
    assert missing == []


# --- golden counterexample --------------------------------------------------
def test_toy_torn_golden_trace_byte_for_byte():
    """The deliberately broken toy spec (torn commit reachable) yields
    a stable shortest counterexample; the rendering is asserted
    byte-for-byte against the checked-in fixture."""
    m = ToyTornModel()
    r = explore(m)
    assert r.fixpoint
    assert [v.prop for v in r.violations] == ["torn-commit"]
    rendered = render_trace(m, r.violations[0]) + "\n"
    with open(GOLDEN, "rb") as f:
        assert rendered.encode() == f.read()


# --- HVD506 conformance -----------------------------------------------------
def test_tree_is_spec_conformant():
    assert check_tree([TREE]) == []


def _mutated_tree(tmp_path, edit):
    """Copy the spec-bound statesync files under a fake horovod_tpu/
    root, apply `edit` (src -> src), and return the root path."""
    root = tmp_path / "horovod_tpu"
    for rel in ("statesync/service.py", "statesync/stream.py",
                "common/tcp_transport.py", "resilience/policy.py",
                "serving/replica.py"):
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(os.path.join(TREE, *rel.split("/")), dst)
    edit(root)
    return str(root)


def test_conformance_catches_removed_handler(tmp_path):
    """spec -> code: deleting the BYE send from JoinerPuller.close
    drifts from the stream spec's join.bye transition."""
    def edit(root):
        p = root / "statesync" / "stream.py"
        src = p.read_text().replace(
            "mesh.send(d, pack_state_frame(STATE_BYE, {}))", "pass")
        p.write_text(src)
    findings = check_tree([_mutated_tree(tmp_path, edit)])
    msgs = [f.message for f in findings]
    assert any("join.bye" in m and "STATE_BYE" in m for m in msgs), msgs
    assert all(f.rule.id == "HVD506" for f in findings)


def test_conformance_catches_unspecced_verb_and_handler(tmp_path):
    """code -> spec: a new frame verb + handler branch the specs do
    not know is drift in the other direction."""
    def edit(root):
        p = root / "common" / "tcp_transport.py"
        p.write_text(p.read_text() + "\nSTATE_GOSSIP = 9\n")
        q = root / "statesync" / "stream.py"
        src = q.read_text().replace(
            "elif kind == STATE_BYE:",
            "elif kind == STATE_GOSSIP:\n"
            "                    pass\n"
            "                elif kind == STATE_BYE:")
        q.write_text(src)
    findings = check_tree([_mutated_tree(tmp_path, edit)])
    msgs = [f.message for f in findings]
    assert any("STATE_GOSSIP" in m and "vocabulary" in m
               for m in msgs), msgs
    assert any("STATE_GOSSIP" in m and "dispatches" in m
               for m in msgs), msgs


def test_conformance_catches_missing_required_call(tmp_path):
    """spec -> code: the grow transition must reinit the world."""
    def edit(root):
        p = root / "statesync" / "service.py"
        src = p.read_text().replace(
            "core.reinit_world(rank=old_rank, size=new_size,"
            " epoch=new_epoch)",
            "pass")
        p.write_text(src)
    findings = check_tree([_mutated_tree(tmp_path, edit)])
    msgs = [f.message for f in findings]
    assert any("inc.boundary-grow" in m and "reinit_world" in m
               for m in msgs), msgs


def test_conformance_inactive_without_anchor_modules(tmp_path):
    """Single-fixture runs never see tree-wide drift errors."""
    p = tmp_path / "loose.py"
    p.write_text("STATE_WHATEVER = 42\n")
    assert check_tree([str(p)]) == []


# --- trace witness ----------------------------------------------------------
def _payload(rank, kinds):
    return {"rank": rank,
            "events": [{"kind": k, "name": ""} for k in kinds]}


def test_witness_accepts_battery_shaped_logs():
    report = witness_check([
        _payload(0, ["enqueue", "shrink", "donate", "dispatch",
                     "grow", "done"]),
        _payload(3, ["join-announce", "join-ready", "join-entered"]),
        _payload(1, ["sigterm-grace", "departed"]),
    ])
    assert report.problems == []
    assert report.observed["grow"] == 1
    # Kinds never replayed demote to coverage warnings.
    assert any("sigterm-grace-expired" in w for w in report.warnings)


def test_witness_fails_on_unknown_protocol_kind():
    report = witness_check([_payload(0, ["membership-mystery"])])
    assert report.problems and "unsound" in report.problems[0]
    assert not report.ok


def test_witness_fails_on_impossible_order():
    report = witness_check([_payload(0, ["departed", "sigterm-grace"])])
    assert any("contradicts the spec" in p for p in report.problems)


def test_witness_ignores_generic_kinds():
    report = witness_check([_payload(0, ["enqueue", "dispatch", "done",
                                         "error", "lock-order",
                                         "autoscale", "sigterm"])])
    assert report.problems == [] and report.observed == {}


def test_witness_fired_gate():
    """A spec transition the model semantics never reach is unsound."""
    report = witness_check([_payload(0, ["grow"])], fired=set())
    assert any("never fires" in p for p in report.problems)


# --- CLI --------------------------------------------------------------------
def _mc(*argv, timeout=240):
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.mc", *argv],
        capture_output=True, text=True, cwd=REPO, timeout=timeout)


def test_cli_default_explores_all_protocols_clean():
    proc = _mc("--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    protos = payload["protocols"]
    assert set(protos) == {"statesync-grow", "statesync-preempt",
                           "resilience-shrink", "rendezvous-failover",
                           "fleet-handoff"}
    for name, rec in protos.items():
        assert rec["fixpoint"] and rec["violations"] == [], name
        assert rec["states"] > 0
    assert protos["statesync-grow"]["states"] > 5000


def test_cli_mutation_exits_nonzero_with_trace():
    proc = _mc("--protocol", "grow", "--mutate", "drop-torn-reject")
    assert proc.returncode == 1
    assert "torn-commit" in proc.stdout
    assert "hvdmc counterexample" in proc.stdout
    assert "net.flag-drop" in proc.stdout


def test_cli_check_tree_gate():
    proc = _mc("--check-tree", "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["conformance"] == []


def test_cli_sarif_shape():
    proc = _mc("--check-tree", "--format", "sarif")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    sarif = json.loads(proc.stdout)
    assert sarif["version"] == "2.1.0"
    assert sarif["runs"][0]["results"] == []


def test_cli_witness_replay(tmp_path):
    good = tmp_path / "w0.json"
    good.write_text(json.dumps(_payload(0, ["sigterm-grace",
                                            "departed"])))
    proc = _mc("--check-tree", "--witness", str(good))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    bad = tmp_path / "w1.json"
    bad.write_text(json.dumps(_payload(1, ["membership-mystery"])))
    proc = _mc("--check-tree", "--witness", str(bad))
    assert proc.returncode == 1
    assert "UNSOUND" in proc.stdout


def test_cli_toy_protocol_reproduces_golden():
    proc = _mc("--protocol", "toy", "--ranks", "2")
    assert proc.returncode == 1
    with open(GOLDEN) as f:
        assert f.read().strip() in proc.stdout


# --- taxonomy sync gate -----------------------------------------------------
def test_every_observable_kind_is_emitted_or_generic():
    """The flight-event kinds the specs claim and the generic taxonomy
    must stay disjoint (a generic kind would silently shadow a
    protocol transition in the witness)."""
    from horovod_tpu.analysis.hvdmc.witness import GENERIC_KINDS
    claimed = {t.observe for sp in all_specs()
               for t in sp.transitions if t.observe}
    assert claimed
    assert not (claimed & GENERIC_KINDS)


def test_grow_spec_covers_state_verbs():
    """Spec vocabulary == wire vocabulary (the conformance pass proves
    it against the AST; this pins the python-side constants too)."""
    from horovod_tpu.common import tcp_transport as t
    consts = {n for n in dir(t)
              if n.startswith("STATE_") and
              isinstance(getattr(t, n), int)}
    claimed = {v.const for v in stream_spec().verbs}
    assert claimed == consts


def test_shrink_and_grow_specs_bind_real_functions():
    """Every bind in every spec resolves against the real tree (the
    conformance gate proves this too; kept as a direct unit so a
    rename fails fast with a readable diff)."""
    from horovod_tpu.analysis.hvdsan.lockgraph import Program
    program = Program()
    program.collect_paths([TREE])
    missing = []
    for sp in (grow_spec(), stream_spec(), preempt_spec(),
               shrink_spec()):
        for tr in sp.transitions:
            for key in tr.binds:
                if key not in program.functions:
                    missing.append((sp.name, tr.tid, key))
    assert missing == []
