"""Pipelined zero-copy TCP data plane battery (ISSUE 3).

Covers the three tentpole layers and their contracts:

- segmented comm/compute overlap is BIT-IDENTICAL to the monolithic path
  for every codec (fp32 ring, bf16 cast, int8/uint4 quantized) on 2- and
  4-rank worlds — same elementwise adds, same rank-order accumulation;
- the transport spawns NO per-step threads: sender lanes are persistent
  per-peer workers (census counts every Thread constructed while a
  12-op mixed workload runs);
- per-stream channel isolation: concurrent responses on separate meshes
  account their bytes on their own counters, exactly;
- the binomial broadcast delivers from every root at every world size;
- the selectors-based arrival-order drain returns the fast peer first;
- (slow) the 4-rank >=1 MiB fused-allreduce A/B: pipelined wall clock
  beats the pre-pipeline thread-per-step/tobytes path.

Multi-stream dispatch through the full core runtime rides the
`streams` battery in tests/test_multiprocess.py / mp_worker.py.
"""
from __future__ import annotations

import os
import sys
import threading
import time

import numpy as np
import pytest

import horovod_tpu.native as native
from horovod_tpu.backend.tcp import TcpCollectives
from horovod_tpu.compress import CompressionCodec
from horovod_tpu.runner.network import PeerMesh

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def kv():
    from horovod_tpu.runner.network import (RendezvousClient,
                                            RendezvousServer)
    server = RendezvousServer()
    port = server.start()
    yield RendezvousClient("127.0.0.1", port, 15.0)
    server.stop()


def _threaded(n, fn, timeout=90.0):
    results: list = [None] * n
    errors: list = []

    def worker(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "rank thread hung"
    if errors:
        raise errors[0]
    return results


def _world(kv, size, scope, fn, meshes=None, timeout=90.0):
    """Form a PeerMesh world and run fn(coll, rank) on every rank."""
    owned = meshes is None
    meshes = meshes if meshes is not None else [None] * size

    def worker(r):
        if meshes[r] is None:
            meshes[r] = PeerMesh(r, size, kv, scope=scope, timeout=15.0)
        return fn(TcpCollectives(meshes[r]), r)

    try:
        return _threaded(size, worker, timeout=timeout)
    finally:
        if owned:
            for m in meshes:
                if m is not None:
                    m.close()


# ---------------------------------------------------------------------------
# Segmented pipeline parity: bit-identical to the monolithic path
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("size", [2, 4])
@pytest.mark.parametrize("codec", ["fp32", "bf16", "int8", "uint4"])
def test_segmented_parity_bitwise(kv, codec, size, monkeypatch):
    """The acceptance contract: segmented allreduce == serial ring,
    bitwise, for every codec on 2- and 4-rank worlds.  The fp32 case
    pins the Python ring (the native kernel has its own internal
    segmentation and handles fp32 otherwise)."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    rng = np.random.default_rng(1234 + size)
    n = 12345            # odd => uneven chunk split exercised
    data = (rng.standard_normal((size, n)) * 5).astype(np.float32)

    def op(coll, r):
        if codec == "fp32":
            return coll.allreduce(data[r].copy())
        if codec == "bf16":
            import ml_dtypes
            return coll.cast_allreduce(data[r].copy(),
                                       np.dtype(ml_dtypes.bfloat16))
        qc = CompressionCodec.INT8 if codec == "int8" \
            else CompressionCodec.UINT4
        return coll.quantized_allreduce(data[r].copy(), qc, 128)

    def run(scope, segment_bytes):
        def fn(coll, r):
            coll.segment_bytes = segment_bytes
            return op(coll, r)
        return _world(kv, size, scope, fn)

    mono = run(f"par-{codec}-{size}-m", 0)       # today's monolithic path
    seg = run(f"par-{codec}-{size}-s", 128)      # many tiny segments
    for r in range(size):
        np.testing.assert_array_equal(np.asarray(mono[r]),
                                      np.asarray(seg[r]))
    # All ranks agree with each other too (the symmetric-result contract).
    for r in range(1, size):
        np.testing.assert_array_equal(np.asarray(mono[0]),
                                      np.asarray(mono[r]))


def test_segmented_reduce_scatter_parity(kv, monkeypatch):
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    from horovod_tpu.backend.base import dim0_row_bounds
    size, n = 3, 10007
    rng = np.random.default_rng(7)
    data = rng.standard_normal((size, n)).astype(np.float32)
    bounds = np.asarray(dim0_row_bounds(n, size))

    def run(scope, segment_bytes):
        def fn(coll, r):
            coll.segment_bytes = segment_bytes
            return coll.reduce_scatter(data[r].copy(), bounds)
        return _world(kv, size, scope, fn)

    mono = run("rs-par-m", 0)
    seg = run("rs-par-s", 256)
    for r in range(size):
        np.testing.assert_array_equal(mono[r], seg[r])


# ---------------------------------------------------------------------------
# Thread census: persistent lanes only, zero per-step spawn
# ---------------------------------------------------------------------------
def test_no_per_step_thread_spawn(kv, monkeypatch):
    """Every Thread constructed anywhere in the process is counted while
    a 12-op mixed workload runs: after the warmup op has spun up the
    persistent per-peer sender lanes, the count must not move (the old
    _sendrecv spawned 2(N-1) threads per fused buffer per allreduce)."""
    size = 3
    spawned: list[str] = []
    orig_init = threading.Thread.__init__

    def counting_init(self, *args, **kwargs):
        spawned.append(kwargs.get("name") or "anon")
        orig_init(self, *args, **kwargs)

    monkeypatch.setattr(threading.Thread, "__init__", counting_init)

    sync = threading.Barrier(size)
    marker: dict[str, int] = {}
    rng = np.random.default_rng(3)
    data = rng.standard_normal((size, 50000)).astype(np.float32)

    def fn(coll, r):
        coll.segment_bytes = 4096
        # Warmup touches EVERY peer channel (quantized is all-pairs), so
        # all lazy sender lanes exist before the census window opens.
        coll.quantized_allreduce(data[r].copy(), CompressionCodec.INT8, 128)
        sync.wait()
        if r == 0:
            marker["before"] = len(spawned)
        sync.wait()
        for i in range(4):
            coll.allreduce(data[r].copy())
            coll.quantized_allreduce(data[r].copy(),
                                     CompressionCodec.INT8, 128)
            coll.broadcast(data[r][:1000].copy(), i % size, 4000,
                           np.dtype(np.float32), (1000,))
        sync.wait()
        if r == 0:
            marker["after"] = len(spawned)
        return True

    _world(kv, size, "census", fn)
    assert marker["after"] == marker["before"], \
        (f"{marker['after'] - marker['before']} thread(s) spawned during "
         f"steady-state collectives: {spawned[marker['before']:]}")
    # The lanes themselves are named and bounded: at most one per peer.
    lanes = [n for n in spawned if n.startswith("hvd-send-")]
    assert 0 < len(lanes) <= size * (size - 1)


# ---------------------------------------------------------------------------
# Stream isolation: concurrent ops on separate channel sets
# ---------------------------------------------------------------------------
def test_stream_isolation_byte_counters(kv, monkeypatch):
    """Two concurrent allreduces on two per-stream meshes: both produce
    exact results and each mesh's counters account exactly its own ring
    volume — streams never interleave bytes on a shared socket."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size, n = 2, 40000                      # even => equal n/2 chunks
    meshes = [[None] * size for _ in range(2)]

    def form(r):
        meshes[0][r] = PeerMesh(r, size, kv, scope="iso-s0", timeout=15.0)
        meshes[1][r] = PeerMesh(r, size, kv, scope="iso-s1", timeout=15.0)

    _threaded(size, form)
    data = [(np.arange(n, dtype=np.float32) + 10 * s) for s in range(2)]

    def fn(r):
        outs = [None, None]

        def run_stream(s):
            outs[s] = TcpCollectives(meshes[s][r]).allreduce(
                data[s].copy())

        # Two streams live on two threads per rank, exactly like the
        # dispatcher's stream workers.
        ts = [threading.Thread(target=run_stream, args=(s,), daemon=True)
              for s in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
            assert not t.is_alive()
        return outs

    try:
        results = _threaded(size, fn)
        for s in range(2):
            expected = data[s] * size
            for r in range(size):
                np.testing.assert_array_equal(results[r][s], expected)
        # Exact per-channel accounting: a 2-rank ring moves 2(N-1)/N =
        # one full payload per rank per op on each stream's own mesh.
        for s in range(2):
            for r in range(size):
                assert meshes[s][r].bytes_sent == n * 4, \
                    (s, r, meshes[s][r].bytes_sent)
                assert meshes[s][r].bytes_received == n * 4
    finally:
        for row in meshes:
            for m in row:
                if m is not None:
                    m.close()


# ---------------------------------------------------------------------------
# Binomial broadcast
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("size", [2, 3, 4, 5])
def test_binomial_broadcast_all_roots(kv, size):
    payload = np.arange(4097, dtype=np.float64)   # odd length, > 1 chunk

    def fn(coll, r):
        outs = []
        for root in range(size):
            buf = payload * (r + 1)
            outs.append(coll.broadcast(buf, root, payload.nbytes,
                                       payload.dtype, payload.shape))
        return outs

    results = _world(kv, size, f"btree{size}", fn)
    for r in range(size):
        for root in range(size):
            np.testing.assert_array_equal(results[r][root],
                                          payload * (root + 1))


# ---------------------------------------------------------------------------
# Arrival-order negotiation drain
# ---------------------------------------------------------------------------
def test_recv_in_arrival_order_fast_peer_first(kv):
    """Rank 0 must see the fast peer's message while the slow peer is
    still asleep — the fixed rank-order drain would block on rank 1."""
    size = 3
    order: list[int] = []

    def fn(coll, r):
        if r == 0:
            for peer, raw in coll.mesh.recv_in_arrival_order([1, 2]):
                order.append(peer)
                assert raw == bytes([peer])
            return order
        if r == 1:
            time.sleep(0.5)                  # the slow rank
        coll.mesh.send(0, bytes([r]))
        return None

    _world(kv, size, "arrival", fn)
    assert order == [2, 1], order


# ---------------------------------------------------------------------------
# Autotuner pipeline sweep + wire plumbing
# ---------------------------------------------------------------------------
def test_autotune_pipeline_sweep(monkeypatch):
    """HOROVOD_AUTOTUNE_PIPELINE: every (segment x streams) candidate is
    proposed for one sample window, then the best-scoring one is pinned
    through controller.pending_tuned_pipeline."""
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "0")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_PIPELINE", "1")
    monkeypatch.setenv("HOROVOD_NUM_STREAMS", "2")
    from horovod_tpu.common.parameter_manager import ParameterManager

    class Ctrl:
        tensor_fusion_threshold = 1 << 26
        pending_tuned_params = None
        pending_tuned_codec = None
        pending_tuned_pipeline = None

    ctrl = Ctrl()
    pm = ParameterManager(ctrl, active=True)
    candidates = list(pm._pipeline_candidates)
    assert len(candidates) == 8              # 4 segment sizes x 2 widths
    proposals = []
    for _ in range(len(candidates) + 1):
        pm.observe(["t"], 1 << 20)
        assert ctrl.pending_tuned_pipeline is not None
        proposals.append(ctrl.pending_tuned_pipeline)
        ctrl.pending_tuned_pipeline = None
    assert proposals[:-1] == candidates      # each swept exactly once
    assert proposals[-1] in candidates       # then the winner re-pinned
    assert not pm._pipeline_candidates


def test_tuned_pipeline_rides_response_list_wire():
    from horovod_tpu.common.message import ResponseList
    rl = ResponseList(tuned_segment_bytes=1 << 18, tuned_num_streams=3)
    decoded = ResponseList.from_bytes(rl.to_bytes())
    assert decoded.tuned_segment_bytes == 1 << 18
    assert decoded.tuned_num_streams == 3
    # Defaults mean "unchanged" on every rank.
    empty = ResponseList.from_bytes(ResponseList().to_bytes())
    assert empty.tuned_segment_bytes == -1
    assert empty.tuned_num_streams == -1


# ---------------------------------------------------------------------------
# The 4-rank fused-allreduce microbenchmark (acceptance item)
# ---------------------------------------------------------------------------
def _serial_allreduce(coll, buf):
    """The pre-pipeline data path, verbatim: thread-per-ring-step
    send+recv, tobytes/frombuffer staging on both directions.  Kept here
    as the A/B baseline the pipelined plane is measured against."""
    n, rank, size = buf.size, coll.rank, coll.size
    acc = buf.astype(np.float32, copy=True)
    base, rem = divmod(n, size)
    sizes = [base + (1 if i < rem else 0) for i in range(size)]
    bounds = np.cumsum([0] + sizes)
    nxt, prv = (rank + 1) % size, (rank - 1) % size

    def sendrecv(payload):
        err: list[BaseException] = []

        def _send():
            try:
                coll.mesh.send(nxt, payload)
            except BaseException as e:  # noqa: BLE001
                err.append(e)

        t = threading.Thread(target=_send, daemon=True)
        t.start()
        data = coll.mesh.recv(prv)
        t.join()
        if err:
            raise err[0]
        return data

    for step in range(size - 1):
        send_idx = (rank - step) % size
        recv_idx = (rank - step - 1) % size
        data = sendrecv(acc[bounds[send_idx]:bounds[send_idx + 1]].tobytes())
        acc[bounds[recv_idx]:bounds[recv_idx + 1]] += \
            np.frombuffer(data, dtype=acc.dtype)
    for step in range(size - 1):
        send_idx = (rank + 1 - step) % size
        recv_idx = (rank - step) % size
        data = sendrecv(acc[bounds[send_idx]:bounds[send_idx + 1]].tobytes())
        acc[bounds[recv_idx]:bounds[recv_idx + 1]] = \
            np.frombuffer(data, dtype=acc.dtype)
    return acc


@pytest.mark.slow
def test_pipelined_beats_serial_4rank_4mib(kv, monkeypatch):
    """4 ranks, 4 MiB fp32 fused buffer: the pipelined zero-copy ring
    must finish in measurably fewer wall-clock seconds than the serial
    thread-per-step path, with a steady-state thread count independent
    of ring steps."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size, n, reps = 4, 1 << 20, 5            # 4 MiB per rank
    rng = np.random.default_rng(42)
    data = rng.standard_normal((size, n)).astype(np.float32)
    spawned: list[str] = []
    orig_init = threading.Thread.__init__

    def counting_init(self, *args, **kwargs):
        spawned.append(kwargs.get("name") or "anon")
        orig_init(self, *args, **kwargs)

    sync = threading.Barrier(size)
    timings: dict[str, list[float]] = {"serial": [], "pipelined": []}
    census: dict[str, int] = {}

    def fn(coll, r):
        coll.segment_bytes = 256 * 1024
        # Warm both paths (lane spawn, scratch growth, cache effects).
        _serial_allreduce(coll, data[r])
        coll.allreduce(data[r].copy())
        for mode in ("serial", "pipelined"):
            for _ in range(reps):
                sync.wait()
                t0 = time.perf_counter()
                if mode == "serial":
                    out = _serial_allreduce(coll, data[r])
                else:
                    out = coll.allreduce(data[r].copy())
                sync.wait()
                if r == 0:
                    timings[mode].append(time.perf_counter() - t0)
            np.testing.assert_allclose(out, data.sum(0), atol=1e-3)
        sync.wait()
        if r == 0:
            census["baseline"] = len(spawned)
        sync.wait()
        coll.allreduce(data[r].copy())       # steady-state op
        sync.wait()
        if r == 0:
            census["after_op"] = len(spawned)
        return True

    monkeypatch.setattr(threading.Thread, "__init__", counting_init)
    _world(kv, size, "bench4", fn, timeout=300.0)

    serial_t = sorted(timings["serial"])[reps // 2]
    pipe_t = sorted(timings["pipelined"])[reps // 2]
    print(f"\n4-rank 4 MiB fused allreduce: serial {serial_t * 1e3:.1f} ms "
          f"-> pipelined {pipe_t * 1e3:.1f} ms "
          f"({serial_t / pipe_t:.2f}x)")
    assert pipe_t < serial_t, (pipe_t, serial_t)
    # Ring steps spawn nothing: the steady-state op created zero threads
    # (the serial baseline above spawned 2(N-1) per op per rank).
    assert census["after_op"] == census["baseline"], \
        spawned[census["baseline"]:]
