"""Timeline artifact test (reference: test/parallel/test_timeline.py):
run a real 2-process world with HOROVOD_TIMELINE set and validate the
chrome-trace JSON the coordinator writes."""
from __future__ import annotations

import json


def _timeline_fn():
    import numpy as np

    import horovod_tpu as hvd
    hvd.init()
    for step in range(3):
        hvd.allreduce(np.ones(16, np.float32), name=f"grad_{step}")
    hvd.allgather(np.ones((2, 2), np.float32), name="gather0")
    hvd.shutdown()
    return hvd is not None


def test_timeline_writes_chrome_trace(tmp_path):
    import horovod_tpu as hvd

    path = tmp_path / "timeline.json"
    results = hvd.run(_timeline_fn, np=2,
                      env={"HOROVOD_TIMELINE": str(path)})
    assert all(results)

    events = json.loads(path.read_text())
    assert isinstance(events, list) and events
    names = {e.get("name", "") for e in events}
    # Negotiation phase markers and the op activity must both appear.
    assert any(n.startswith("NEGOTIATE_") for n in names), names
    assert "ALLREDUCE" in names
    assert "ALLGATHER" in names
    # Begin/End events balance per (pid, tid).
    opens: dict[tuple, int] = {}
    for e in events:
        key = (e.get("pid"), e.get("tid"))
        if e.get("ph") == "B":
            opens[key] = opens.get(key, 0) + 1
        elif e.get("ph") == "E":
            opens[key] = opens.get(key, 0) - 1
            assert opens[key] >= 0
