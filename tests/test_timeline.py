"""Timeline tests (reference: test/parallel/test_timeline.py + the
timeline.cc activity machinery): chrome-trace artifact from real worlds,
backend sub-activities, dynamic start/stop, cached-steady-state phases,
and writer-thread shutdown."""
from __future__ import annotations

import json

from horovod_tpu.common.timeline import Timeline


def _events(path) -> list[dict]:
    events = json.loads(path.read_text())
    assert isinstance(events, list) and events
    return events


def _assert_balanced(events: list[dict]) -> None:
    """Begin/End events balance and never go negative per (pid, tid)."""
    opens: dict[tuple, int] = {}
    for e in events:
        key = (e.get("pid"), e.get("tid"))
        if e.get("ph") == "B":
            opens[key] = opens.get(key, 0) + 1
        elif e.get("ph") == "E":
            opens[key] = opens.get(key, 0) - 1
            assert opens[key] >= 0
    assert all(v == 0 for v in opens.values()), opens


def _timeline_fn():
    import numpy as np

    import horovod_tpu as hvd
    hvd.init()
    for step in range(3):
        hvd.allreduce(np.ones(16, np.float32), name=f"grad_{step}")
    # Grouped op: exercises the fused pack/unpack sub-activities.
    hvd.grouped_allreduce([np.ones(4, np.float32), np.ones(5, np.float32)],
                          name="fused")
    hvd.allgather(np.ones((2, 2), np.float32), name="gather0")
    hvd.shutdown()
    return hvd is not None


def test_timeline_writes_chrome_trace(tmp_path):
    import horovod_tpu as hvd

    path = tmp_path / "timeline.json"
    results = hvd.run(_timeline_fn, np=2,
                      env={"HOROVOD_TIMELINE": str(path)})
    assert all(results)

    events = _events(path)
    names = {e.get("name", "") for e in events}
    # Negotiation phase markers and the op activity must both appear.
    assert any(n.startswith("NEGOTIATE_") for n in names), names
    assert "ALLREDUCE" in names
    assert "ALLGATHER" in names
    _assert_balanced(events)


def test_timeline_backend_sub_activities(tmp_path):
    """Pack / collective / unpack are separable in the trace (VERDICT r3
    item 6; reference: MEMCPY_IN_FUSION_BUFFER etc. emitted from inside
    ops, nccl_operations.cc:143)."""
    import horovod_tpu as hvd

    path = tmp_path / "timeline_sub.json"
    results = hvd.run(_timeline_fn, np=2,
                      env={"HOROVOD_TIMELINE": str(path)})
    assert all(results)

    events = _events(path)
    names = {e.get("name", "") for e in events}
    # The grouped allreduce stages through the fusion buffer...
    assert "MEMCPY_IN_FUSION_BUFFER" in names, names
    # ...and the data plane identifies itself inside the op span (the
    # same-host test world rides shm for allreduce AND allgather).
    assert "SHM_ALLREDUCE" in names or "TCP_RING_ALLREDUCE" in names, names
    assert "SHM_ALLGATHER" in names or "TCP_ALLGATHERV" in names, names
    _assert_balanced(events)

    # Sub-activities nest INSIDE the op span on each tensor's lane:
    # between an ALLREDUCE B and its E the depth stays >= 1.
    by_tid: dict = {}
    for e in events:
        if e.get("ph") in ("B", "E"):
            by_tid.setdefault(e.get("tid"), []).append(e)
    saw_nested = False
    for lane in by_tid.values():
        depth = 0
        for e in lane:
            if e["ph"] == "B":
                depth += 1
                if e.get("name") in ("SHM_ALLREDUCE",
                                     "TCP_RING_ALLREDUCE",
                                     "MEMCPY_IN_FUSION_BUFFER"):
                    assert depth >= 2, e   # nested under the op span
                    saw_nested = True
            else:
                depth -= 1
    assert saw_nested


def _dynamic_fn():
    import numpy as np

    import horovod_tpu as hvd
    import os
    hvd.init()
    hvd.allreduce(np.ones(4, np.float32), name="before")   # not recorded
    hvd.start_timeline(os.environ["TEST_TIMELINE_PATH"])
    hvd.allreduce(np.ones(4, np.float32), name="during")
    hvd.stop_timeline()
    hvd.allreduce(np.ones(4, np.float32), name="after")    # not recorded
    hvd.shutdown()
    return True


def test_timeline_dynamic_start_stop(tmp_path):
    """HOROVOD_TIMELINE=DYNAMIC starts stopped; start/stop_timeline flip
    recording at runtime (reference: operations.cc:740-769)."""
    import horovod_tpu as hvd

    path = tmp_path / "dyn.json"
    results = hvd.run(_dynamic_fn, np=2,
                      env={"HOROVOD_TIMELINE": "DYNAMIC",
                           "TEST_TIMELINE_PATH": str(path)})
    assert all(results)

    events = _events(path)
    blob = json.dumps(events)
    assert "during" in blob
    assert "before" not in blob and "after" not in blob
    _assert_balanced(events)


def _steady_fn():
    import numpy as np

    import horovod_tpu as hvd
    hvd.init()
    for _ in range(10):
        hvd.allreduce(np.ones(8, np.float32), name="steady")
    hvd.shutdown()
    return True


def test_timeline_cached_steady_state(tmp_path):
    """Response-cache steady state still records one op span per executed
    collective, and the spans stay balanced under reuse of one tensor
    lane."""
    import horovod_tpu as hvd

    path = tmp_path / "steady.json"
    results = hvd.run(_steady_fn, np=2,
                      env={"HOROVOD_TIMELINE": str(path)})
    assert all(results)

    events = _events(path)
    op_spans = [e for e in events
                if e.get("ph") == "B" and e.get("name") == "ALLREDUCE"]
    assert len(op_spans) == 10, len(op_spans)
    _assert_balanced(events)


def test_timeline_writer_shutdown(tmp_path):
    """stop() drains the queue, joins the writer thread, closes the file
    as valid JSON, and later emissions are dropped silently."""
    path = tmp_path / "unit.json"
    tl = Timeline(str(path))
    tl.negotiate_start("t0", "ALLREDUCE")
    tl.negotiate_end("t0")
    tl.activity_start("t0", "ALLREDUCE")
    tl.activity_end("t0")
    tl.stop()
    assert not tl.enabled
    assert tl._writer is None or not tl._writer.is_alive()
    events = _events(path)
    _assert_balanced(events)
    # Emissions after stop are no-ops, not crashes or file writes.
    tl.activity_start("t0", "LATE")
    tl.activity_end("t0")
    assert "LATE" not in path.read_text()
    # Double stop is harmless.
    tl.stop()


def test_timeline_unit_events(tmp_path):
    """Unit-level event shape: per-tensor lanes get thread_name metadata,
    mark_cycle is gated on the flag, and events carry timestamps."""
    path = tmp_path / "unit2.json"
    tl = Timeline(str(path), mark_cycles=False)
    tl.mark_cycle()                      # flag off: nothing emitted
    tl.activity_start("alpha", "ALLREDUCE")
    tl.activity_end("alpha")
    tl._mark_cycles = True
    tl.mark_cycle()
    tl.stop()
    events = _events(path)
    metas = [e for e in events if e.get("ph") == "M"]
    assert any(e["args"].get("name") == "alpha" for e in metas)
    cycles = [e for e in events if e.get("name") == "CYCLE"]
    assert len(cycles) == 1
    assert all("ts" in e for e in events if e.get("ph") in ("B", "E"))


def test_timeline_negotiate_state_machine(tmp_path):
    """A request resubmitted across cycles (local cache hit whose bit
    didn't survive the global AND) must not open a second NEGOTIATE span,
    and an end without a start (joined-rank stand-in) must be a no-op —
    the reference's per-tensor phase machine (timeline.cc)."""
    path = tmp_path / "sm.json"
    tl = Timeline(str(path))
    tl.negotiate_start("t", "ALLREDUCE")
    tl.negotiate_start("t", "ALLREDUCE")   # resubmission: ignored
    tl.negotiate_end("t")
    tl.negotiate_end("t")                  # double end: ignored
    tl.negotiate_end("ghost")              # never negotiated: ignored
    tl.negotiate_start("t", "ALLREDUCE")   # new op on same tensor: fine
    tl.negotiate_end("t")
    tl.stop()
    events = _events(path)
    begins = [e for e in events if e.get("ph") == "B"]
    ends = [e for e in events if e.get("ph") == "E"]
    assert len(begins) == 2 and len(ends) == 2, events
    _assert_balanced(events)


def test_timeline_restart_resets_timestamp_origin(tmp_path):
    """A DYNAMIC stop/start recording window begins at ts~0, not minutes
    into the process: start() re-anchors _start (ISSUE 7 satellite)."""
    import time

    tl = Timeline("DYNAMIC")
    time.sleep(0.12)                       # process runs "for a while"
    p1 = tmp_path / "w1.json"
    tl.start(str(p1))
    tl.activity_start("t", "ALLREDUCE")
    tl.activity_end("t")
    tl.stop()
    first = next(e for e in _events(p1) if e.get("ph") == "B")
    assert first["ts"] < 100_000, first    # µs; well under the 120ms sleep

    # Second window after more wall time: origin resets again.
    time.sleep(0.12)
    p2 = tmp_path / "w2.json"
    tl.start(str(p2))
    tl.activity_start("t", "ALLREDUCE")
    tl.activity_end("t")
    tl.stop()
    first = next(e for e in _events(p2) if e.get("ph") == "B")
    assert first["ts"] < 100_000, first
    # The window's monotonic base is carried in the clock-sync metadata
    # so cross-rank stitching still has the absolute anchor.
    sync = [e for e in _events(p2)
            if e.get("name") == "horovod_clock_sync"]
    assert sync and sync[-1]["args"]["start_us"] > 0


def test_timeline_rank_suffix_and_trace_args(tmp_path):
    """Rank r > 0 writes path.r<r>.json (rank 0 keeps the exact path);
    span args carry the trace id and queue spans are async b/e pairs."""
    from horovod_tpu.common.timeline import rank_path

    assert rank_path("/x/t.json", 0) == "/x/t.json"
    assert rank_path("/x/t.json", 3) == "/x/t.r3.json"
    assert rank_path("/x/t_{rank}.json", 2) == "/x/t_2.json"
    assert rank_path("/x/t", 1) == "/x/t.r1"

    p = tmp_path / "tr.json"
    tl = Timeline(str(p), rank=1)
    assert tl._path == str(tmp_path / "tr.r1.json")
    tl.set_clock_sync(1500.0, 80.0)
    tl.queue_start("g")
    tl.activity_start("g", "ALLREDUCE", trace="7.0")
    tl.activity_end("g")
    tl.queue_end("g", trace="7.0")
    tl.stop()
    events = _events(tmp_path / "tr.r1.json")
    op = next(e for e in events
              if e.get("ph") == "B" and e["name"] == "ALLREDUCE")
    assert op["args"]["trace"] == "7.0"
    qb = [e for e in events if e.get("ph") == "b"]
    qe = [e for e in events if e.get("ph") == "e"]
    assert len(qb) == 1 and len(qe) == 1
    assert qb[0]["id"] == qe[0]["id"]
    assert qe[0]["args"]["trace"] == "7.0"
    sync = [e for e in events if e.get("name") == "horovod_clock_sync"]
    assert sync[-1]["args"]["clock_offset_us"] == 1500.0
    assert sync[-1]["args"]["rank"] == 1
    _assert_balanced(events)


def test_timeline_dynamic_env_starts_stopped(tmp_path):
    """HOROVOD_TIMELINE=DYNAMIC must not create a file until started."""
    tl = Timeline("DYNAMIC")
    assert not tl.enabled
    tl.activity_start("x", "Y")          # dropped, no crash
    path = tmp_path / "d2.json"
    tl.start(str(path))
    assert tl.enabled
    tl.activity_start("x", "ALLREDUCE")
    tl.activity_end("x")
    tl.stop()
    _assert_balanced(_events(path))
