"""Module-level function for the programmatic elastic run() test
(pickled by reference into elastic_run_worker bootstraps)."""
import numpy as np


def allreduce_identity(scale: float):
    import os

    import horovod_tpu as hvd

    hvd.init()
    try:
        out = hvd.allreduce(np.ones(4, np.float32) * scale, op=hvd.Sum,
                            name="elastic_fn")
        return {"rank": hvd.rank(), "sum": float(np.asarray(out)[0]),
                "size": hvd.size(),
                "marker": os.environ.get("TEST_ELASTIC_RUN_MARKER")}
    finally:
        hvd.shutdown()
