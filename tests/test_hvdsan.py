"""hvdsan — whole-program concurrency verification (ISSUE 8).

Static half: seeded fixtures for every rule (HVD501-505), suppression
plumbing, the lock/thread/edge model over the real tree.  Runtime half:
the HOROVOD_SAN lock-wrapper witness records acquisition-order edges
in-process, survives the Condition save/restore protocol, and diffs
against the static graph.  The multiprocess acceptance battery lives in
tests/test_multiprocess.py (test_lock_witness_matches_static_graph).
"""
import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from horovod_tpu.analysis.hvdsan import san
from horovod_tpu.analysis.hvdsan.lockgraph import (_spine, analyze_paths,
                                                   module_label)
from horovod_tpu.analysis.hvdsan.ownership import (LOCK_HOLD_ALLOWED,
                                                   MANIFEST,
                                                   domain_for_write,
                                                   owner_module_suffixes)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TREE = os.path.join(REPO, "horovod_tpu")
SAN_FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint", "san")


def _fixture(name: str):
    return analyze_paths([os.path.join(SAN_FIXTURES, name)])


def _slugs(analysis):
    return [f.rule.slug for f in analysis.findings]


@pytest.fixture(scope="module")
def tree_analysis():
    """One whole-tree analysis shared by the model tests (~0.5 s)."""
    return analyze_paths([TREE])


# --- seeded fixtures: every rule detected -----------------------------------
def test_fixture_inversion_cycle_hvd501():
    a = _fixture("inversion_cycle.py")
    assert _slugs(a) == ["lock-order-inversion"]
    f = a.findings[0]
    assert f.severity == "error"
    assert "_submit_lock" in f.message and "_drain_lock" in f.message
    # Both edge sites ride the finding for suppression anchoring.
    assert len(f.sites) == 2


def test_fixture_held_lock_collective_hvd502():
    a = _fixture("held_lock_collective.py")
    assert _slugs(a) == ["lock-held-across-blocking"] * 2
    msgs = " ".join(f.message for f in a.findings)
    assert "collective allreduce" in msgs       # interprocedural collective
    assert "recv_into" in msgs                  # interprocedural blocking
    assert all(f.severity == "error" for f in a.findings)


def test_fixture_orphan_condition_hvd503():
    a = _fixture("orphan_condition.py")
    assert _slugs(a) == ["orphan-condition-wait"]
    assert "_cond" in a.findings[0].message
    # The condition aliases its wrapped lock in the identity model.
    cond = a.locks["orphan_condition.ResultBox._cond"]
    assert cond.kind == "condition"
    assert cond.canonical == "orphan_condition.ResultBox._lock"


def test_fixture_ownership_violation_hvd504():
    a = _fixture("ownership_violation.py")
    assert _slugs(a) == ["cross-thread-write"]
    f = a.findings[0]
    assert "fixture-watcher" in f.message
    assert "hvd-background" in f.message


def test_fixture_wire_drift_hvd505():
    a = _fixture("wire_drift.py")
    assert _slugs(a) == ["wire-schema-drift"] * 2
    msgs = [f.message for f in a.findings]
    assert any("trailing field" in m and "scale" in m for m in msgs)
    assert any("swapped" in m for m in msgs)


def test_fixture_ungated_optional_field_hvd505():
    """ISSUE 15 satellite: every optional wire field (fp_*/tm_*/
    trace_*) must sit behind a feature-bit gate on BOTH codec sides —
    the compile-time half of the versioned HELLO handshake.  The
    fixture's ungated class is flagged once per side; the gated class
    next to it is clean."""
    a = _fixture("ungated_optional_field.py")
    assert _slugs(a) == ["wire-schema-drift"] * 2
    msgs = [f.message for f in a.findings]
    assert all("feature-bit gate" in m and "fp_seq" in m for m in msgs)
    assert {f.message.split(".")[0].rsplit(" ", 1)[-1]
            for f in a.findings} == {"UngatedRequestList"}


def test_fixture_ungated_sp_field_hvd505():
    """ISSUE 17 satellite: the sp_* sharding-spec group joins the
    optional-field prefix table, so an sp_spec string encoded or
    decoded outside a FEATURE_SHARDING gate is flagged once per codec
    side; the gated twin next to it is clean."""
    a = _fixture("ungated_sp_field.py")
    assert _slugs(a) == ["wire-schema-drift"] * 2
    msgs = [f.message for f in a.findings]
    assert all("feature-bit gate" in m and "sp_spec" in m for m in msgs)
    assert {f.message.split(".")[0].rsplit(" ", 1)[-1]
            for f in a.findings} == {"UngatedShardRequest"}


def test_fixture_state_frame_drift_hvd505():
    """ISSUE 11 satellite: HVD505 extended over the statesync
    STATE_MAGIC frame codec — the seeded fixture drifts every check
    once (duplicate verb wire value, header struct format, magic
    prefix, header field order)."""
    a = analyze_paths([os.path.join(REPO, "tests", "fixtures", "lint",
                                    "statesync",
                                    "state_frame_drift.py")])
    assert _slugs(a) == ["wire-schema-drift"] * 4
    msgs = " | ".join(f.message for f in a.findings)
    assert "share wire value" in msgs
    assert "header drift" in msgs and "'>BI'" in msgs
    assert "magic drift" in msgs
    assert "field-order drift" in msgs and "'kind'" in msgs


def test_tree_state_frame_codec_in_sync(tree_analysis):
    """common/tcp_transport.py's pack/unpack_state_frame agree (the
    statesync half of test_tree_wire_schemas_in_sync)."""
    assert len(tree_analysis.program.state_frames) == 2
    assert {r["side"] for r in tree_analysis.program.state_frames} \
        == {"pack", "unpack"}
    assert not [f for f in tree_analysis.findings
                if f.rule.id == "HVD505"]


def test_all_san_fixtures_detected_together():
    a = analyze_paths([SAN_FIXTURES])
    assert {"lock-order-inversion", "lock-held-across-blocking",
            "orphan-condition-wait", "cross-thread-write",
            "wire-schema-drift"} <= set(_slugs(a))


# --- suppressions -----------------------------------------------------------
def test_cycle_suppression_on_edge_site(tmp_path):
    src = open(os.path.join(SAN_FIXTURES, "inversion_cycle.py")).read()
    src = src.replace(
        "with _drain_lock:            # order: submit -> drain",
        "with _drain_lock:  # hvdlint: disable=HVD501 -- fixture: "
        "external barrier orders submit before drain")
    p = tmp_path / "inversion_suppressed.py"
    p.write_text(src)
    a = analyze_paths([str(p)])
    assert _slugs(a) == []


def test_hvd502_suppression_at_call_site(tmp_path):
    src = open(os.path.join(SAN_FIXTURES,
                            "held_lock_collective.py")).read()
    src = src.replace(
        "return _sync_helper(tensor)                   # HVD502 (collective)",
        "return _sync_helper(tensor)  # hvdlint: disable=HVD502 -- "
        "fixture: single-process tool")
    p = tmp_path / "held_suppressed.py"
    p.write_text(src)
    a = analyze_paths([str(p)])
    assert _slugs(a) == ["lock-held-across-blocking"]   # recv one remains


# --- the model over the real tree -------------------------------------------
def test_tree_lock_identities(tree_analysis):
    locks = tree_analysis.locks
    assert "core._init_lock" in locks
    assert "common.tensor_queue.TensorQueue._mutex" in locks
    assert "runner.network.PeerMesh._lock" in locks
    assert "telemetry.flight._lock" in locks
    # Stable creation sites key the witness diff.
    assert locks["core._init_lock"].site.startswith(
        "horovod_tpu/core.py:")
    # elastic driver's Condition aliases its wrapped lock.
    cond = locks["elastic.driver.ElasticDriver._round_cond"]
    assert cond.canonical == "elastic.driver.ElasticDriver._lock"


def test_tree_thread_roots(tree_analysis):
    names = set(tree_analysis.thread_roots.values())
    assert {"hvd-background", "hvd-timeline", "hvd-send-*",
            "hvd-heartbeat"} <= names
    # ISSUE 11 satellite: PR 10's threads are named roots (watcher via
    # Thread(target=), autoscale via the manifest — Thread subclass —
    # and the preempt backstop via Timer detection + manifest).
    assert {"hvd-statesync-watch", "hvd-autoscale",
            "hvd-preempt-backstop"} <= names


def test_thread_roots_manifest_resolves(tree_analysis):
    """Every manifest-declared root names a real function, carries a
    justification, and reaches the HVD504 reachability set."""
    from horovod_tpu.analysis.hvdsan.ownership import THREAD_ROOTS
    for name, (funckey, why) in THREAD_ROOTS.items():
        assert funckey in tree_analysis.program.functions, funckey
        assert len(why) > 20, name
        assert tree_analysis.thread_roots[funckey] == name
        assert name in tree_analysis.thread_reach[funckey]


def test_tree_init_lock_edges(tree_analysis):
    """The init/shutdown chains the runtime witness observes must be in
    the static graph (soundness on the exercised paths)."""
    edges = tree_analysis.edge_keys()
    for dst in ("telemetry.flight._lock",
                "resilience.chaos._lock",
                "runner.network.PeerMesh._lock",
                "common.tensor_queue.TensorQueue._mutex",
                "parallel.multihost._lock"):
        assert ("core._init_lock", dst) in edges, dst


def test_tree_has_no_unsuppressed_errors(tree_analysis):
    errors = [f for f in tree_analysis.findings
              if f.severity == "error"]
    assert errors == [], "\n".join(f.text() for f in errors)


def test_tree_wire_schemas_in_sync(tree_analysis):
    assert not [f for f in tree_analysis.findings
                if f.rule.id == "HVD505"]


def test_manifest_shape():
    assert {d.name for d in MANIFEST} >= {
        "controller", "tensor-queue", "global-state", "timeline",
        "telemetry", "flight"}
    assert "core.py" in owner_module_suffixes()
    assert domain_for_write(("st", "controller", "cache")).name == \
        "controller"
    assert domain_for_write(("x", "y")) is None
    # Every documented hold allowance names a real lock in the tree and
    # carries a justification.
    a = analyze_paths([TREE])
    for key, why in LOCK_HOLD_ALLOWED.items():
        assert key in a.locks, key
        assert len(why) > 20, key


# --- helpers ----------------------------------------------------------------
def test_module_label_and_spine():
    assert module_label("horovod_tpu/runner/network.py") == \
        "runner.network"
    assert module_label("horovod_tpu/analysis/__init__.py") == "analysis"
    assert module_label("tests/fixtures/lint/san/x.py") == "x"
    import ast
    expr = ast.parse("self._channels[peer].send_sync").body[0].value
    assert _spine(expr) == ("self", "_channels", "[]", "send_sync")
    expr = ast.parse("self._tm_peer(a).inc").body[0].value
    assert _spine(expr) == ("self", "_tm_peer", "()", "inc")


def test_sarif_payload_levels():
    a = _fixture("inversion_cycle.py")
    a.findings[0].severity = "warning"
    sarif = san.sarif_payload(a.findings)
    assert sarif["runs"][0]["results"][0]["level"] == "warning"
    assert sarif["runs"][0]["tool"]["driver"]["rules"][0]["id"] == \
        "HVD501"


# --- runtime witness --------------------------------------------------------
_FAKE_PATH = os.path.join(REPO, "horovod_tpu", "_san_witness_fixture.py")


def _exec_package_module(source: str) -> dict:
    """Execute source under a fake horovod_tpu/ filename so the witness
    treats its lock creations as package locks."""
    ns: dict = {"threading": threading}
    exec(compile(textwrap.dedent(source), _FAKE_PATH, "exec"), ns)
    return ns


@pytest.fixture
def witness():
    was = san.enabled()
    w = san.enable()
    w.reset()
    yield w
    w.reset()
    if not was:
        san.disable()


def test_witness_records_nested_acquisition_edges(witness):
    ns = _exec_package_module("""
        a = threading.Lock()
        b = threading.Lock()
        def nested():
            with a:
                with b:
                    pass
        def reversed_order():
            with b:
                with a:
                    pass
    """)
    ns["nested"]()
    ns["nested"]()
    ns["reversed_order"]()
    snap = witness.snapshot()
    fixture_locks = [s for s in snap["locks"]
                     if s.startswith("horovod_tpu/_san_witness_fixture")]
    assert len(fixture_locks) == 2
    edges = {(e["src"], e["dst"]): e for e in snap["edges"]}
    assert len(edges) == 2
    (ab, ba) = sorted(edges.values(), key=lambda e: -e["count"])
    assert ab["count"] == 2 and ba["count"] == 1
    assert ab["src"] == ba["dst"] and ab["dst"] == ba["src"]
    assert all(e["src"].startswith(
        "horovod_tpu/_san_witness_fixture.py:")
        for e in snap["edges"])
    assert "MainThread" in ab["threads"]


def test_witness_ignores_non_package_locks(witness):
    plain = threading.Lock()          # created from tests/ -> raw lock
    assert type(plain).__name__ != "_SanLock"
    with plain:
        pass
    assert witness.snapshot()["edges"] == []


def test_witness_condition_roundtrip_and_full_release(witness):
    """Condition(lock) through the wrappers: wait releases every
    recursion level (save/restore protocol), notify wakes the waiter,
    and the held-stack bookkeeping survives — the exact machinery a
    HOROVOD_SAN=1 elastic driver exercises."""
    ns = _exec_package_module("""
        lock = threading.Lock()
        cond = threading.Condition(lock)
        outer = threading.Lock()
        state = {"ready": False, "seen": False}
        def waiter():
            with cond:
                while not state["ready"]:
                    cond.wait(5.0)
                state["seen"] = True
        def notifier():
            with cond:
                state["ready"] = True
                cond.notify_all()
        def nested_probe():
            with outer:
                with lock:
                    pass
    """)
    t = threading.Thread(target=ns["waiter"], daemon=True)
    t.start()
    import time
    time.sleep(0.1)
    ns["notifier"]()
    t.join(timeout=5)
    assert not t.is_alive() and ns["state"]["seen"]
    ns["nested_probe"]()
    snap = witness.snapshot()
    pairs = {(e["src"], e["dst"]) for e in snap["edges"]}
    # outer -> lock observed; cond shares lock's identity (same site
    # object), so no self-edges appeared from the wait re-acquire.
    assert any(s != d for s, d in pairs)
    assert all(s != d for s, d in pairs)


def test_witness_dump_and_rank_path(witness, tmp_path, monkeypatch):
    ns = _exec_package_module("""
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
    """)
    assert ns
    monkeypatch.setenv("HOROVOD_RANK", "3")
    out = san.dump_witness(str(tmp_path / "wit.json"))
    assert out == str(tmp_path / "wit.r3.json")
    payload = json.load(open(out))
    assert payload["rank"] == 3
    assert len(payload["edges"]) == 1


def test_witness_diff_and_demotion(witness):
    fixture = os.path.join(SAN_FIXTURES, "inversion_cycle.py")
    a = analyze_paths([fixture])
    site = {v.canonical: v.site for v in a.locks.values()}
    sub = site["inversion_cycle._submit_lock"]
    drn = site["inversion_cycle._drain_lock"]
    # Observed edge present in the static graph: sound.
    ok = {"rank": 0, "edges": [
        {"src": sub, "dst": drn, "count": 1, "threads": ["MainThread"]}]}
    assert san.witness_diff(a, [ok]) == []
    # Observed lock the analyzer never saw: unsound.
    bad = {"rank": 1, "edges": [
        {"src": "horovod_tpu/ghost.py:1", "dst": drn, "count": 1,
         "threads": ["t"]}]}
    problems = san.witness_diff(a, [bad])
    assert problems and "no static identity" in problems[0]
    # Cycle edge observed at runtime: the HVD501 stays an error.
    a2 = analyze_paths([fixture])
    san.apply_witness(a2, [ok])
    assert [f.severity for f in a2.findings
            if f.rule.id == "HVD501"] == ["error"]
    # Never observed: demoted to a warning, message says why.
    a3 = analyze_paths([fixture])
    san.apply_witness(a3, [{"rank": 0, "edges": []}])
    f = [f for f in a3.findings if f.rule.id == "HVD501"][0]
    assert f.severity == "warning"
    assert "never observed" in f.message or "demoted" in f.message


def test_maybe_enable_off_by_default(monkeypatch):
    monkeypatch.delenv("HOROVOD_SAN", raising=False)
    assert san.maybe_enable() is False


# --- CLI --------------------------------------------------------------------
def test_cli_report_mode_on_fixtures():
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.hvdsan",
         SAN_FIXTURES, "--graph"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 1
    assert "HVD501" in proc.stdout and "HVD505" in proc.stdout
    assert "lock inversion_cycle._submit_lock" in proc.stdout


def test_cli_tree_is_clean_and_json():
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.hvdsan", TREE,
         "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=180)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["unsound"] == []
    assert payload["wall_ms"] > 0
    assert "core._init_lock" in payload["graph"]["locks"]
    assert payload["graph"]["threads"]
