"""Streaming (vocab-chunked) cross entropy == dense log_softmax CE.

The streaming op only engages above the training._ce_threshold() size in the
trainer path; these tests call it directly on small shapes so the
chunked math (online logsumexp, chunked backward, label smoothing) is
pinned against the dense reference at test scale.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.loss import (_pick_chunk,
                                  streaming_softmax_cross_entropy)
from horovod_tpu.training import cross_entropy_loss


def _dense_ce(logits, labels, smoothing=0.0):
    num_classes = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
    if smoothing:
        onehot = (1.0 - smoothing) * onehot + smoothing / num_classes
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


def test_pick_chunk():
    assert _pick_chunk(50304, 8192) == 6288      # 8 chunks
    assert _pick_chunk(4096, 8192) == 4096       # fits whole
    # no useful divisor (prime / only tiny divisors): one vocab-wide
    # chunk, never a degenerate chunk=1 scan
    assert _pick_chunk(50023, 8192) == 50023     # prime
    assert _pick_chunk(2 * 25013, 8192) == 50026  # 2 x prime
    assert _pick_chunk(100, 30) == 25


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_streaming_matches_dense(dtype, smoothing):
    key = jax.random.key(0)
    T, V = 48, 96   # chunk_target=32 -> 3 chunks of 32
    logits = (jax.random.normal(key, (T, V), jnp.float32) * 4).astype(dtype)
    labels = jax.random.randint(jax.random.key(1), (T,), 0, V)

    got = streaming_softmax_cross_entropy(logits, labels, smoothing,
                                          chunk_target=32)
    want = _dense_ce(logits, labels, smoothing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-6, atol=2e-6)

    # gradients: same fp32 math, emitted in the logits dtype
    g_got = jax.grad(lambda l: streaming_softmax_cross_entropy(
        l, labels, smoothing, chunk_target=32))(logits)
    g_want = jax.grad(lambda l: _dense_ce(l, labels, smoothing))(logits)
    assert g_got.dtype == dtype
    # bf16 grads are independently-rounded results of different fp32
    # reduction orders: compare at the dtype's own precision.
    tol = 2e-6 if dtype == jnp.float32 else 8e-3
    np.testing.assert_allclose(np.asarray(g_got, np.float32),
                               np.asarray(g_want.astype(dtype), np.float32),
                               rtol=tol, atol=tol)


def test_streaming_handles_batch_dims():
    logits = jax.random.normal(jax.random.key(2), (4, 6, 64), jnp.float32)
    labels = jax.random.randint(jax.random.key(3), (4, 6), 0, 64)
    got = streaming_softmax_cross_entropy(logits, labels, chunk_target=16)
    want = _dense_ce(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_trainer_loss_dispatches_below_threshold():
    # Small logits keep the dense path (no scan in the jaxpr).
    logits = jnp.ones((8, 32), jnp.float32)
    labels = jnp.zeros((8,), jnp.int32)
    jaxpr = jax.make_jaxpr(cross_entropy_loss)(logits, labels)
    assert "scan" not in str(jaxpr)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_ignore_index_matches_dense(smoothing):
    # Out-of-range labels (-1 padding) must follow one_hot semantics in
    # BOTH branches: zero one-hot mass, uniform eps/V target only.
    T, V = 24, 64
    logits = jax.random.normal(jax.random.key(5), (T, V), jnp.float32) * 3
    labels = jax.random.randint(jax.random.key(6), (T,), 0, V)
    labels = labels.at[::3].set(-1)
    got = streaming_softmax_cross_entropy(logits, labels, smoothing,
                                          chunk_target=16)
    want = _dense_ce(logits, labels, smoothing)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    g_got = jax.grad(lambda l: streaming_softmax_cross_entropy(
        l, labels, smoothing, chunk_target=16))(logits)
    g_want = jax.grad(lambda l: _dense_ce(l, labels, smoothing))(logits)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                               rtol=1e-5, atol=1e-6)
    if not smoothing:
        # ignored rows get exactly zero gradient
        assert np.all(np.asarray(g_got)[::3] == 0.0)


def test_extreme_logits_stable():
    # Online logsumexp must not overflow where naive exp would.
    logits = jnp.array([[1e4, -1e4, 0.0, 5e3]] * 2, jnp.float32)
    labels = jnp.array([0, 3])
    got = streaming_softmax_cross_entropy(logits, labels, chunk_target=2)
    want = _dense_ce(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    assert np.isfinite(float(got))
