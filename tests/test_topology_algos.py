"""Topology-aware collective algorithms battery (ISSUE 18).

Covers the tentpole layers and their contracts:

- topology declaration (common/topology.py): HOROVOD_TOPOLOGY parsing,
  torus boustrophedon / host-grouped ring orders, hierarchy levels, and
  the launcher-uniform degradation to flat on invalid specs;
- per-size algorithm selection (_select_algo) is a pure, rank-symmetric
  function of the negotiated payload size and the tuned/launcher knobs,
  with symmetric feasibility fallbacks (pow-2 for halving/doubling,
  declared torus, 2-rank degeneration);
- 2/4-rank parity for the tree / recursive-halving-doubling / two-phase
  torus legs across fp32, int32, bf16-cast and int8/uint4 quantized
  wires — BITWISE against the flat ring wherever rank-order fp32
  accumulation is preserved (ints; codec paths with block-aligned chunk
  bounds), documented last-ulp fp32 tolerance where the reduction tree
  legitimately re-associates (plain fp32 tree/rhd/torus);
- topology-ordered rings produce the identical result as the identity
  order (chunk ownership follows ring POSITION, not rank);
- the ResponseList tuned_algo / tuned_tree_threshold wire round-trip
  and the autotuner's algo×threshold sweep mechanics;
- the transport spawns NO per-step threads on any of the new legs
  (thread census across a tree+rhd+torus workload);
- the bench probe watcher's 2-strike definitive-absent verdict reaches
  CPU fallback in seconds, honoring the registry-typed
  HOROVOD_BENCH_PROBE_BUDGET_S knob, and every bench payload is stamped
  with the declared topology/algo;
- (slow) 8-rank parity and the 4-rank A/B: the small-tensor tree beats
  the flat ring at <=64 KiB, and auto selection costs the segmented
  ring nothing measurable at >=4 MiB.

The negotiated end-to-end path (tuned_algo broadcast -> applied before
dispatch on every rank) rides the `algotune` battery in
tests/test_multiprocess.py / mp_worker.py.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import types

import numpy as np
import pytest

import horovod_tpu.native as native
from horovod_tpu.backend.tcp import TcpCollectives
from horovod_tpu.common import topology
from horovod_tpu.common.message import ResponseList
from horovod_tpu.compress import CompressionCodec
from horovod_tpu.runner.network import PeerMesh

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def kv():
    from horovod_tpu.runner.network import (RendezvousClient,
                                            RendezvousServer)
    server = RendezvousServer()
    port = server.start()
    yield RendezvousClient("127.0.0.1", port, 15.0)
    server.stop()


def _threaded(n, fn, timeout=90.0):
    results: list = [None] * n
    errors: list = []

    def worker(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "rank thread hung"
    if errors:
        raise errors[0]
    return results


def _world(kv, size, scope, fn, coll_kwargs=None, timeout=90.0):
    """Form a PeerMesh world and run fn(coll, rank) on every rank;
    `coll_kwargs` go to every rank's TcpCollectives (algo / torus /
    ring_order are launcher-uniform knobs, so identical per rank)."""
    meshes: list = [None] * size
    kwargs = coll_kwargs or {}

    def worker(r):
        meshes[r] = PeerMesh(r, size, kv, scope=scope, timeout=15.0)
        return fn(TcpCollectives(meshes[r], **kwargs), r)

    try:
        return _threaded(size, worker, timeout=timeout)
    finally:
        for m in meshes:
            if m is not None:
                m.close()


# ---------------------------------------------------------------------------
# Topology declaration: parse, ring orders, levels
# ---------------------------------------------------------------------------
def test_parse_torus_and_snake_ring_order():
    topo = topology.parse("torus:2x3", size=6)
    assert topo.kind == "torus" and (topo.rows, topo.cols) == (2, 3)
    # Boustrophedon: row 0 left-to-right, row 1 right-to-left — every
    # ring hop lands on a grid neighbor.
    assert topo.ring_order() == [0, 1, 2, 5, 4, 3]
    assert topo.levels() == [3, 2]          # cols (fast) first
    assert topo.describe() == "torus:2x3"


def test_parse_torus_shape_mismatch_degrades_to_flat():
    for spec in ("torus:2x3", "torus:0x4", "torus:nonsense", "torus:2"):
        topo = topology.parse(spec, size=8)
        assert topo.kind == "flat", spec
        assert topo.ring_order() == list(range(8))
        assert topo.levels() == [8]


def test_parse_host_grouping_and_explicit_map():
    topo = topology.parse("host", size=8, local_size=4)
    assert topo.kind == "host"
    assert topo.levels() == [4, 2]
    assert topo.describe() == "host:2x4"
    # Homogeneous host-major launch: already grouped, identity order.
    assert topo.ring_order() == list(range(8))
    # Explicit elastic slot map: ranks regroup by host, stably.
    mapped = topology.parse("host", size=4, local_size=2,
                            hosts=(1, 0, 1, 0))
    assert mapped.ring_order() == [1, 3, 0, 2]
    # No multi-slot hosts -> flat (identity, single level).
    assert topology.parse("host", size=4, local_size=1).kind == "flat"


def test_parse_auto_and_unknown():
    auto = topology.parse("", size=8, local_size=4, cross_size=2)
    assert auto.kind == "host" and auto.levels() == [4, 2]
    assert topology.parse("", size=8).kind == "flat"
    assert topology.parse("wormhole", size=8).kind == "flat"
    assert topology.parse("flat", size=8).describe() == "flat"


def test_parse_auto_uses_explicit_host_map_on_uneven_layouts():
    """An uneven slot layout (1+3) defeats the homogeneous local x cross
    product test, but an explicit HOROVOD_HOST_IDS map still groups the
    ring by host; local_size stays pinned to 1 so every rank builds the
    IDENTICAL Topology (per-rank local_size differs across hosts here)
    and the level ladder stays flat (hierarchy needs homogeneity)."""
    topo = topology.parse("", size=4, local_size=1, cross_size=1,
                          hosts=(0, 1, 1, 1))
    assert topo.kind == "host" and topo.local_size == 1
    assert topo.ring_order() == [0, 1, 2, 3]
    assert topo.levels() == [4]
    regrouped = topology.parse("", size=4, hosts=(1, 0, 1, 0))
    assert regrouped.ring_order() == [1, 3, 0, 2]
    # Degenerate maps change nothing: single host, all-distinct hosts,
    # or a length mismatch (stale env across an elastic resize).
    assert topology.parse("", size=4, hosts=(0, 0, 0, 0)).kind == "flat"
    assert topology.parse("", size=4, hosts=(0, 1, 2, 3)).kind == "flat"
    assert topology.parse("", size=4, hosts=(0, 1)).kind == "flat"


def test_host_ids_env_is_rank_ordered_and_first_appearance_indexed():
    from horovod_tpu.runner.hosts import (get_host_assignments,
                                          host_ids_env, parse_hosts)
    ids = host_ids_env(get_host_assignments(parse_hosts("a:1,b:3"), 4))
    assert ids == "0,1,1,1"
    # Host indices follow first appearance in rank order regardless of
    # the assignment list's ordering.
    slots = get_host_assignments(parse_hosts("x:2,y:2"), 4)
    assert host_ids_env(list(reversed(slots))) == "0,0,1,1"


def test_resolve_reads_knob(monkeypatch):
    monkeypatch.setenv("HOROVOD_TOPOLOGY", "torus:2x2")
    assert topology.resolve(4).kind == "torus"
    monkeypatch.setenv("HOROVOD_TOPOLOGY", "flat")
    assert topology.resolve(4).kind == "flat"


def test_algo_vocabulary_wire_indices():
    for name in topology.ALGO_NAMES:
        assert topology.algo_name(topology.algo_index(name)) == name
    # Out-of-range indices (a newer peer's vocabulary) degrade to auto.
    assert topology.algo_name(-1) == "auto"
    assert topology.algo_name(99) == "auto"


# ---------------------------------------------------------------------------
# Per-size selection: pure function of rank-symmetric inputs
# ---------------------------------------------------------------------------
def _selector(size, algo="auto", tree_threshold=64 * 1024, torus=None):
    stub = types.SimpleNamespace(size=size, algo=algo,
                                 tree_threshold=tree_threshold,
                                 _torus=torus)
    return lambda nbytes: TcpCollectives._select_algo(stub, nbytes)


def test_select_algo_matrix():
    sel = _selector(4)
    assert sel(1024) == "tree"              # small -> latency-bound
    assert sel(64 * 1024) == "tree"         # threshold is inclusive
    assert sel(64 * 1024 + 1) == "ring"     # past crossover -> bandwidth
    # Declared torus: large tensors take the two-phase schedule.
    sel = _selector(4, torus=(2, 2))
    assert sel(1024) == "tree"
    assert sel(1 << 20) == "torus"
    # Threshold 0 disables the tree leg entirely.
    assert _selector(4, tree_threshold=0)(8) == "ring"
    # Explicit knobs pin the algorithm regardless of size...
    assert _selector(4, algo="ring")(8) == "ring"
    assert _selector(4, algo="tree")(1 << 24) == "tree"
    # ...with SYMMETRIC feasibility fallbacks: halving/doubling needs a
    # power-of-two world, torus needs a declared torus.
    assert _selector(4, algo="rhd")(1 << 20) == "rhd"
    assert _selector(6, algo="rhd")(1 << 20) == "tree"
    assert _selector(4, algo="torus")(1 << 20) == "ring"
    # Two ranks: every schedule degenerates to one exchange; keep the
    # ring's native fast path.
    for algo in ("tree", "rhd", "torus", "auto"):
        assert _selector(2, algo=algo, torus=(1, 2))(8) == "ring"


def test_tuned_algo_wire_roundtrip():
    rl = ResponseList(tuned_algo=topology.algo_index("tree"),
                      tuned_tree_threshold=1 << 16)
    back = ResponseList.from_bytes(rl.to_bytes())
    assert back.tuned_algo == topology.algo_index("tree")
    assert back.tuned_tree_threshold == 1 << 16
    # Defaults (-1 = unchanged) survive the trip too.
    back = ResponseList.from_bytes(ResponseList().to_bytes())
    assert back.tuned_algo == -1 and back.tuned_tree_threshold == -1


# ---------------------------------------------------------------------------
# Autotuner algo x threshold sweep mechanics
# ---------------------------------------------------------------------------
def test_algo_sweep_proposes_then_pins_winner(monkeypatch):
    from horovod_tpu.common.parameter_manager import ParameterManager
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "0")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_PIPELINE", "1")
    ctl = types.SimpleNamespace(
        tensor_fusion_threshold=1 << 26, pending_tuned_params=None,
        pending_tuned_codec=None, pending_tuned_pipeline=None,
        pending_tuned_fused=None, pending_tuned_algo=None)
    pm = ParameterManager(ctl, active=True)
    candidates = list(pm._algo_candidates)
    assert candidates and candidates[0][0] == topology.algo_index("ring")
    assert all(0 <= a < len(topology.ALGO_NAMES) for a, _ in candidates)
    # Skip straight to the algo sweep (the earlier sweeps have their own
    # batteries); each observe() closes one sample window.
    pm._codec_candidates = []
    pm._pipeline_candidates = []
    pm._fused_candidates = []
    proposed = []
    for i in range(len(candidates)):
        pm.observe(["t"], 4096 * (i + 1))
        proposed.append(pm._controller.pending_tuned_algo)
    assert proposed == candidates            # every candidate was scored
    pm.observe(["t"], 4096)                  # closes the last window
    winner = pm._controller.pending_tuned_algo
    assert winner in candidates              # the winner is pinned
    assert pm._algo_candidates == []         # sweep complete -> BO next
    assert len(pm._algo_scores) == len(candidates)


# ---------------------------------------------------------------------------
# Parity: tree / rhd / torus vs the flat ring, 2- and 4-rank worlds
# ---------------------------------------------------------------------------
def _run_algo(kv, size, scope, op, coll_kwargs):
    def fn(coll, r):
        return op(coll, r)
    return _world(kv, size, scope, fn, coll_kwargs=coll_kwargs)


ALGO_WORLDS = [
    ("tree", {"algo": "tree"}),
    ("rhd", {"algo": "rhd"}),
    ("torus", {"algo": "torus", "torus": (2, 2)}),
]


@pytest.mark.parametrize("algo,kwargs", ALGO_WORLDS)
def test_algo_parity_fp32(kv, monkeypatch, algo, kwargs):
    """Plain fp32: tree/rhd/torus legitimately re-associate the sum
    (ring reduces chunk-owner order; tree reduces at the root), so the
    contract is the documented last-ulp tolerance — plus exact
    cross-rank agreement within each algorithm (symmetric-result)."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size, n = 4, 12345
    rng = np.random.default_rng(18)
    data = (rng.standard_normal((size, n)) * 5).astype(np.float32)

    def op(coll, r):
        return coll.allreduce(data[r].copy())

    ring = _run_algo(kv, size, f"fp32-ring-{algo}", op, {"algo": "ring"})
    out = _run_algo(kv, size, f"fp32-{algo}", op, kwargs)
    for r in range(size):
        np.testing.assert_allclose(out[r], ring[r], rtol=1e-6, atol=1e-5)
        np.testing.assert_array_equal(out[0], out[r])


@pytest.mark.parametrize("algo,kwargs", ALGO_WORLDS)
def test_algo_parity_int32_bitwise(kv, monkeypatch, algo, kwargs):
    """Integer adds are associative: every schedule must be EXACT."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size, n = 4, 9973
    rng = np.random.default_rng(19)
    data = rng.integers(-1000, 1000, size=(size, n)).astype(np.int32)

    def op(coll, r):
        return coll.allreduce(data[r].copy())

    ring = _run_algo(kv, size, f"i32-ring-{algo}", op, {"algo": "ring"})
    out = _run_algo(kv, size, f"i32-{algo}", op, kwargs)
    for r in range(size):
        np.testing.assert_array_equal(out[r], ring[r])
        np.testing.assert_array_equal(out[0], out[r])


def test_cast_allreduce_tree_bitwise(kv, monkeypatch):
    """bf16 cast wire: both the ring (chunk owners accumulate rank 0..N-1
    in fp32, round once) and the tree (root accumulates rank 0..N-1 in
    fp32, rounds once) preserve rank-order accumulation -> BITWISE."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    import ml_dtypes
    size, n = 4, 12345
    rng = np.random.default_rng(20)
    data = (rng.standard_normal((size, n)) * 5).astype(np.float32)
    wire = np.dtype(ml_dtypes.bfloat16)

    def op(coll, r):
        return coll.cast_allreduce(data[r].copy(), wire)

    ring = _run_algo(kv, size, "bf16-ring", op,
                     {"algo": "ring", "tree_threshold": 0})
    tree = _run_algo(kv, size, "bf16-tree", op,
                     {"algo": "tree", "tree_threshold": 1 << 30})
    for r in range(size):
        np.testing.assert_array_equal(np.asarray(tree[r]),
                                      np.asarray(ring[r]))


@pytest.mark.parametrize("codec,block", [
    (CompressionCodec.INT8, 128), (CompressionCodec.UINT4, 128)])
def test_quantized_allreduce_tree_bitwise_aligned(kv, monkeypatch, codec,
                                                  block):
    """Quantized wires: with n divisible by size*block the ring's chunk
    bounds align to quantization blocks, so the ring's owner-reduce and
    the tree's root-reduce see identical block statistics -> BITWISE.
    (Unaligned n splits blocks across chunk owners; that case carries
    the documented fp32 tolerance and is not asserted bitwise.)"""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size = 4
    n = size * block * 5                     # block-aligned chunk bounds
    rng = np.random.default_rng(21)
    data = (rng.standard_normal((size, n)) * 5).astype(np.float32)

    def op(coll, r):
        return coll.quantized_allreduce(data[r].copy(), codec, block)

    tag = "i8" if codec == CompressionCodec.INT8 else "u4"
    ring = _run_algo(kv, size, f"q-{tag}-ring", op,
                     {"algo": "ring", "tree_threshold": 0})
    tree = _run_algo(kv, size, f"q-{tag}-tree", op,
                     {"algo": "tree", "tree_threshold": 1 << 30})
    for r in range(size):
        np.testing.assert_array_equal(tree[r], ring[r])
        np.testing.assert_array_equal(tree[0], tree[r])


def test_snake_ring_order_matches_identity_bitwise(kv, monkeypatch):
    """Topology-ordered ring: chunk ownership follows ring POSITION, so
    a permuted walk moves the same chunks through the same elementwise
    adds in a different rank rotation — integer-exact either way, and
    every rank still converges on the identical buffer."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size, n = 4, 10007
    rng = np.random.default_rng(22)
    data = rng.integers(-500, 500, size=(size, n)).astype(np.int64)
    snake = topology.Topology(size=size, kind="torus", rows=2,
                              cols=2).ring_order()

    def op(coll, r):
        return coll.allreduce(data[r].copy())

    ident = _run_algo(kv, size, "order-ident", op, {"algo": "ring"})
    perm = _run_algo(kv, size, "order-snake", op,
                     {"algo": "ring", "ring_order": snake})
    for r in range(size):
        np.testing.assert_array_equal(perm[r], ident[r])


def test_two_rank_degeneration_runs_the_ring(kv, monkeypatch):
    """A 2-rank world with algo=tree/rhd/torus must not hang or diverge:
    selection degenerates every schedule to the ring's single exchange."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size, n = 2, 4096
    rng = np.random.default_rng(23)
    data = rng.standard_normal((size, n)).astype(np.float32)
    expect = data.sum(axis=0)

    for algo in ("tree", "rhd"):
        def op(coll, r):
            out = coll.allreduce(data[r].copy())
            assert coll.last_algo == "ring"
            return out
        got = _run_algo(kv, size, f"deg-{algo}", op, {"algo": algo})
        for r in range(size):
            np.testing.assert_allclose(got[r], expect, rtol=1e-6)


def test_last_algo_reflects_selection(kv, monkeypatch):
    """Telemetry's algo= label source: last_algo names what actually
    ran, per size class, on every rank identically."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size = 4
    small = np.ones(64, dtype=np.float32)          # 256 B <= threshold
    large = np.ones(64 * 1024, dtype=np.float32)   # 256 KiB > threshold

    def fn(coll, r):
        seen = []
        coll.allreduce(small.copy())
        seen.append(coll.last_algo)
        coll.allreduce(large.copy())
        seen.append(coll.last_algo)
        return seen

    out = _world(kv, size, "lastalgo", fn,
                 coll_kwargs={"algo": "auto", "tree_threshold": 64 * 1024})
    assert out == [["tree", "ring"]] * size


# ---------------------------------------------------------------------------
# Thread census: the new legs spawn ZERO per-step threads
# ---------------------------------------------------------------------------
def test_no_per_step_thread_spawn_on_new_algos(kv, monkeypatch):
    """Tree, halving/doubling and two-phase torus all ride the persistent
    per-peer sender lanes: after a warmup touches every peer channel,
    a mixed tree+rhd+torus workload constructs no new Thread."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size = 4
    spawned: list[str] = []
    orig_init = threading.Thread.__init__

    def counting_init(self, *args, **kwargs):
        spawned.append(kwargs.get("name") or "anon")
        orig_init(self, *args, **kwargs)

    monkeypatch.setattr(threading.Thread, "__init__", counting_init)

    sync = threading.Barrier(size)
    marker: dict[str, int] = {}
    rng = np.random.default_rng(24)
    data = rng.standard_normal((size, 20000)).astype(np.float32)

    def workload(coll, r):
        for algo in ("tree", "rhd", "torus", "tree"):
            coll.algo = algo
            coll.allreduce(data[r].copy())
        coll.algo = "tree"
        coll.cast_allreduce(data[r][:4096].copy(), np.dtype(np.float16))
        coll.quantized_allreduce(data[r][:2048].copy(),
                                 CompressionCodec.INT8, 128)

    def fn(coll, r):
        # Warmup runs the SAME legs once: every directed peer channel
        # any schedule touches (tree parent/child edges, rhd partners,
        # torus row/column rings) spins up its lazy sender lane before
        # the census window opens.
        workload(coll, r)
        sync.wait()
        if r == 0:
            marker["before"] = len(spawned)
        sync.wait()
        workload(coll, r)
        sync.wait()
        if r == 0:
            marker["after"] = len(spawned)
        return True

    _world(kv, size, "algo-census", fn,
           coll_kwargs={"torus": (2, 2), "tree_threshold": 1 << 30})
    assert marker["after"] == marker["before"], \
        (f"{marker['after'] - marker['before']} thread(s) spawned during "
         f"tree/rhd/torus collectives: {spawned[marker['before']:]}")


# ---------------------------------------------------------------------------
# Bench satellites: probe 2-strike verdict + payload topology stamp
# ---------------------------------------------------------------------------
def _load_bench():
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "bench.py")
    spec = importlib.util.spec_from_file_location("_bench_under_test",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_probe_two_absent_strikes_are_definitive(tmp_path, monkeypatch):
    """An accelerator-free container reaches CPU fallback after exactly
    TWO timed-out probes (no backoff ladder, no full-window re-timeout),
    with the per-probe timeout sourced from the registry-typed
    HOROVOD_BENCH_PROBE_BUDGET_S knob."""
    bench = _load_bench()
    monkeypatch.setenv("HOROVOD_BENCH_STATE_FILE",
                       str(tmp_path / "probe_state.json"))
    monkeypatch.setenv("HOROVOD_BENCH_PROBE_BUDGET_S", "2")
    # The tier-1 env pins JAX_PLATFORMS=cpu, which (correctly) skips the
    # probe loop outright; un-pin it so the watcher path runs.
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)

    probes: list[float] = []
    spawns: list[dict] = []
    emitted: list[dict] = []
    monkeypatch.setattr(
        bench, "_probe_backend_status",
        lambda timeout: (probes.append(timeout), ("absent", None))[1])
    monkeypatch.setattr(
        bench, "_spawn_inner",
        lambda args, extra_env, timeout: (
            spawns.append(dict(extra_env)),
            (0, {"metric": "eager_step", "value": 1.0, "unit": "ms",
                 "vs_baseline": 0.0}, "", False))[1])
    monkeypatch.setattr(bench, "_emit", emitted.append)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    t0 = time.monotonic()
    rc = bench._orchestrate(types.SimpleNamespace(model="eager"))
    assert rc == 0
    assert time.monotonic() - t0 < 30.0      # "under a minute" contract
    # Exactly two probes, each with the knob's 2 s budget, then verdict.
    assert probes == [2.0, 2.0]
    assert spawns == [{"JAX_PLATFORMS": "cpu"}]
    assert len(emitted) == 1
    payload = emitted[0]
    assert payload["backend"] == "cpu-fallback"
    assert payload["attempts"] == 3          # 2 probes + the CPU attempt
    # The verdict checkpoints the watcher state (a re-run resumes the
    # round window instead of restarting the schedule).
    assert os.path.exists(str(tmp_path / "probe_state.json"))


def test_bench_payload_topology_algo_stamp(monkeypatch, capsys):
    """EVERY emitted payload carries the declared topology and algo —
    env-sourced so even failure payloads from processes that never
    imported the package are stamped."""
    bench = _load_bench()
    monkeypatch.setenv("HOROVOD_TOPOLOGY", "torus:2x2")
    monkeypatch.setenv("HOROVOD_ALGO", "tree")
    bench._emit({"metric": "m", "value": 1.0})
    monkeypatch.delenv("HOROVOD_TOPOLOGY")
    monkeypatch.delenv("HOROVOD_ALGO")
    bench._emit({"metric": "m", "value": 1.0})
    # A leg that knows the runtime-selected value wins over the env.
    bench._emit({"metric": "m", "value": 1.0, "algo": "rhd"})
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    assert (lines[0]["topology"], lines[0]["algo"]) == ("torus:2x2",
                                                        "tree")
    assert (lines[1]["topology"], lines[1]["algo"]) == ("flat", "auto")
    assert lines[2]["algo"] == "rhd"


# ---------------------------------------------------------------------------
# Slow: 8-rank parity + the 4-rank latency/bandwidth A/B
# ---------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("algo,kwargs", [
    ("tree", {"algo": "tree"}),
    ("rhd", {"algo": "rhd"}),
    ("torus", {"algo": "torus", "torus": (2, 4)}),
])
def test_algo_parity_eight_ranks(kv, monkeypatch, algo, kwargs):
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size, n = 8, 30011
    rng = np.random.default_rng(25)
    fdata = (rng.standard_normal((size, n)) * 3).astype(np.float32)
    idata = rng.integers(-100, 100, size=(size, n)).astype(np.int32)

    def fop(coll, r):
        return coll.allreduce(fdata[r].copy())

    def iop(coll, r):
        return coll.allreduce(idata[r].copy())

    fring = _run_algo(kv, size, f"8f-ring-{algo}", fop, {"algo": "ring"})
    fout = _run_algo(kv, size, f"8f-{algo}", fop, kwargs)
    iring = _run_algo(kv, size, f"8i-ring-{algo}", iop, {"algo": "ring"})
    iout = _run_algo(kv, size, f"8i-{algo}", iop, kwargs)
    for r in range(size):
        np.testing.assert_allclose(fout[r], fring[r], rtol=1e-6,
                                   atol=1e-5)
        np.testing.assert_array_equal(fout[0], fout[r])
        np.testing.assert_array_equal(iout[r], iring[r])


def _timed_world(kv, size, scope, coll_kwargs, nbytes, reps):
    """Median barrier-synced wall time of one allreduce at rank 0."""
    sync = threading.Barrier(size)
    samples: list[float] = []
    n = nbytes // 4

    def fn(coll, r):
        x = np.ones(n, dtype=np.float32)
        for _ in range(3):                     # warm lanes + buffers
            coll.allreduce(x.copy())
        for _ in range(reps):
            y = x.copy()
            sync.wait()
            t0 = time.perf_counter()
            coll.allreduce(y)
            sync.wait()
            if r == 0:
                samples.append(time.perf_counter() - t0)
        return True

    _world(kv, size, scope, fn, coll_kwargs=coll_kwargs, timeout=240.0)
    return float(np.median(samples))


@pytest.mark.slow
def test_small_tensor_tree_beats_flat_ring(kv, monkeypatch):
    """The acceptance A/B: at <=64 KiB the latency-bound leg (tree) must
    beat the flat ring by >=1.2x on a 4-rank world — the ring pays
    2(N-1)=6 serialized hops per step, the binomial tree 2*log2(N)=4."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    reps, nbytes = 15, 16 * 1024
    ring = _timed_world(kv, 4, "ab-small-ring", {"algo": "ring"},
                        nbytes, reps)
    tree = _timed_world(kv, 4, "ab-small-tree", {"algo": "tree"},
                        nbytes, reps)
    assert ring >= 1.2 * tree, \
        f"tree {tree * 1e6:.0f}us vs ring {ring * 1e6:.0f}us at {nbytes}B"


@pytest.mark.slow
def test_large_tensor_auto_matches_segmented_ring(kv, monkeypatch):
    """At >=4 MiB auto selection must pick the segmented ring and cost
    nothing measurable: within 5% of the explicitly pinned ring.  Both
    settings run INTERLEAVED in the same world so system drift between
    two sequential worlds cannot masquerade as a selection cost."""
    monkeypatch.setattr(native, "ring_allreduce", lambda *a, **k: False)
    size, reps, n = 4, 9, (4 << 20) // 4
    sync = threading.Barrier(size)
    samples: dict[str, list[float]] = {"ring": [], "auto": []}

    def fn(coll, r):
        coll.tree_threshold = 64 * 1024
        x = np.ones(n, dtype=np.float32)
        for _ in range(2):                     # warm lanes + buffers
            coll.allreduce(x.copy())
        for _ in range(reps):
            for algo in ("ring", "auto"):
                coll.algo = algo
                y = x.copy()
                sync.wait()
                t0 = time.perf_counter()
                coll.allreduce(y)
                assert coll.last_algo == "ring"   # auto picked the ring
                sync.wait()
                if r == 0:
                    samples[algo].append(time.perf_counter() - t0)
        return True

    _world(kv, size, "ab-big", fn, timeout=240.0)
    ring = float(np.median(samples["ring"]))
    auto = float(np.median(samples["auto"]))
    assert auto <= 1.05 * ring, \
        f"auto {auto * 1e3:.2f}ms vs ring {ring * 1e3:.2f}ms at 4 MiB"
