"""Launcher unit + integration tests.

Mirrors the reference's test/single/test_run.py (arg parsing, host
parsing, env construction) and test/integration/test_static_run.py
(real end-to-end localhost launch).
"""
import io
import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.runner import launch
from horovod_tpu.runner.hosts import (HostInfo, get_host_assignments,
                                      parse_hosts)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestHosts:
    def test_parse_hosts(self):
        hosts = parse_hosts("a:2,b:4")
        assert hosts == [HostInfo("a", 2), HostInfo("b", 4)]
        assert parse_hosts("justhost") == [HostInfo("justhost", 1)]

    def test_assignments_homogeneous(self):
        slots = get_host_assignments(parse_hosts("a:2,b:2"), 4)
        assert [s.rank for s in slots] == [0, 1, 2, 3]
        assert [s.hostname for s in slots] == ["a", "a", "b", "b"]
        assert [s.local_rank for s in slots] == [0, 1, 0, 1]
        assert [s.cross_rank for s in slots] == [0, 0, 1, 1]
        assert all(s.size == 4 and s.local_size == 2 and s.cross_size == 2
                   for s in slots)

    def test_assignments_heterogeneous_cross_rank(self):
        # host b's local_rank-1 slot is the only one → cross_size 1,
        # cross_rank 0 (reference: hosts.py get_host_assignments).
        slots = get_host_assignments(parse_hosts("a:1,b:2"), 3)
        b1 = [s for s in slots if s.hostname == "b" and s.local_rank == 1][0]
        assert b1.cross_size == 1 and b1.cross_rank == 0
        a0 = [s for s in slots if s.hostname == "a"][0]
        assert a0.cross_size == 2 and a0.cross_rank == 0

    def test_max_np_truncates(self):
        slots = get_host_assignments(parse_hosts("a:4"), 2, 2)
        assert len(slots) == 2

    def test_insufficient_slots(self):
        with pytest.raises(ValueError, match="only 2 slots"):
            get_host_assignments(parse_hosts("a:2"), 3)

    def test_hostfile(self, tmp_path):
        f = tmp_path / "hostfile"
        f.write_text("# comment\nhost1 slots=2\nhost2 slots=4\n")
        from horovod_tpu.runner.hosts import parse_host_files
        assert parse_host_files(str(f)) == "host1:2,host2:4"

    def test_slot_env(self):
        slot = get_host_assignments(parse_hosts("h:2"), 2)[1]
        env = slot.to_env()
        assert env["HOROVOD_RANK"] == "1"
        assert env["HOROVOD_LOCAL_RANK"] == "1"
        assert env["HOROVOD_SIZE"] == "2"


class TestArgs:
    def test_tuning_flags_to_env(self):
        args = launch.parse_args(
            ["-np", "2", "--fusion-threshold-mb", "32",
             "--cycle-time-ms", "5", "--timeline-filename", "/tmp/t.json",
             "--no-stall-check", "--log-level", "debug", "ls"])
        env = launch.args_to_env(args)
        assert env["HOROVOD_FUSION_THRESHOLD"] == str(32 * 1024 * 1024)
        assert env["HOROVOD_CYCLE_TIME"] == "5.0"
        assert env["HOROVOD_TIMELINE"] == "/tmp/t.json"
        assert env["HOROVOD_STALL_CHECK_DISABLE"] == "1"
        assert env["HOROVOD_LOG_LEVEL"] == "debug"

    def test_config_file(self, tmp_path):
        cfg = tmp_path / "cfg.yaml"
        cfg.write_text(textwrap.dedent("""\
            fusion-threshold-mb: 16
            start-timeout: 60
            log-level: info
        """))
        args = launch.parse_args(
            ["-np", "2", "--config-file", str(cfg),
             "--log-level", "error", "ls"])
        assert args.fusion_threshold_mb == 16
        assert args.start_timeout == 60       # default overridden by file
        assert args.log_level == "error"      # CLI wins over file

    def test_check_build_output(self):
        out = io.StringIO()
        launch.check_build(out)
        text = out.getvalue()
        assert "[X] PyTorch" in text
        assert "[X] JAX" in text
        assert "[X] XLA/TPU data plane" in text
        assert "[ ] NCCL" in text


class TestStaticRun:
    def test_end_to_end_localhost(self, tmp_path):
        """Real launch: 2 local workers allreduce through the CLI-started
        rendezvous (reference: test/integration/test_static_run.py)."""
        script = tmp_path / "train.py"
        script.write_text(textwrap.dedent("""\
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            out = hvd.allreduce(np.ones(4, dtype=np.float32), op=hvd.Sum,
                                name="e2e")
            assert out.tolist() == [hvd.size()] * 4, out
            print(f"rank {hvd.rank()} OK")
            hvd.shutdown()
        """))
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        for k in list(env):
            if k.startswith("HOROVOD_"):
                del env[k]
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner.launch",
             "-np", "2", sys.executable, str(script)],
            env=env, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "rank 0 OK" in proc.stdout
        assert "rank 1 OK" in proc.stdout

    def test_nic_discovery_probe(self):
        """Driver/task reachability probing (SURVEY §2.4 driver_service):
        the probe client, run per "host" through a local exec substitute,
        reports which driver addresses it can reach; unreachable decoys
        are filtered out of the intersection."""
        import json as _json
        import subprocess

        from horovod_tpu.runner import driver_service as ds

        addrs = ds.candidate_addresses()
        assert addrs, "no IPv4 interfaces found"

        def local_exec(hostname, argv):
            # Inject a decoy address that nothing listens on: it must be
            # filtered from the intersection. argv = [..., port,
            # addresses, timeout] — the address list is argv[-2].
            argv = list(argv)
            argv[-2] = argv[-2] + ",192.0.2.1"   # TEST-NET, unroutable
            out = subprocess.run(argv, capture_output=True, text=True,
                                 timeout=60)
            assert out.returncode == 0, out.stderr
            return out.stdout

        common = ds.discover_common_interfaces(
            ["hostA", "hostB"], local_exec, timeout=5.0)
        assert common
        assert "192.0.2.1" not in common
        assert set(common) <= set(addrs)

        # The raw probe against a live server sees at least loopback.
        server = ds.ProbeServer()
        try:
            reachable = ds.probe(["127.0.0.1", "192.0.2.1"], server.port,
                                 timeout=2.0)
        finally:
            server.close()
        assert reachable == ["127.0.0.1"]

    def test_advertised_address_pins_interface(self):
        from horovod_tpu.runner.hosts import HostInfo
        from horovod_tpu.runner.launch import _advertised_address

        hosts = [HostInfo("localhost", 2), HostInfo("remote-a", 2)]
        addr = _advertised_address(hosts, network_interface="lo")
        assert addr.startswith("127.")

    def test_failure_propagates(self, tmp_path):
        script = tmp_path / "fail.py"
        script.write_text("import sys; sys.exit(3)\n")
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner.launch",
             "-np", "2", sys.executable, str(script)],
            env=env, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1
        assert "ranks failed" in proc.stderr


class TestJsRun:
    """jsrun/LSF launcher (reference: test/single/test_jsrun.py analogue)."""

    def test_lsf_detection(self):
        from horovod_tpu.runner import js_run
        assert js_run.using_lsf({"LSB_JOBID": "123"})
        assert not js_run.using_lsf({})

    def test_hosts_from_mcpu_drops_launch_node(self):
        from horovod_tpu.runner import js_run
        env = {"LSB_MCPU_HOSTS": "batch1 1 c1 4 c2 4"}
        assert js_run.lsf_hosts_string(env) == "c1:4,c2:4"
        # Explicit request keeps the launch node.
        assert js_run.lsf_hosts_string(
            env, include_launch_node=True) == "batch1:1,c1:4,c2:4"

    def test_hosts_single_host_kept(self):
        from horovod_tpu.runner import js_run
        assert js_run.lsf_hosts_string({"LSB_MCPU_HOSTS": "c1 4"}) == "c1:4"
        # Uniform single-slot hosts are NOT mistaken for launch nodes.
        assert js_run.lsf_hosts_string(
            {"LSB_MCPU_HOSTS": "a 1 b 1"}) == "a:1,b:1"

    def test_hosts_from_hostfile_and_override(self, tmp_path):
        from horovod_tpu.runner import js_run
        hf = tmp_path / "djob"
        hf.write_text("batch1\nc1\nc1\nc2\nc2\n")
        env = {"LSB_DJOB_HOSTFILE": str(hf)}
        assert js_run.lsf_hosts_string(env) == "c1:2,c2:2"
        env[js_run.COMPUTE_HOSTS_ENV] = "x:8"
        assert js_run.lsf_hosts_string(env) == "x:8"

    def test_rankfile_host_major_disjoint_cpus(self, tmp_path):
        from horovod_tpu.runner import js_run
        slots = get_host_assignments(parse_hosts("c1:2,c2:2"), 4)
        path = js_run.generate_jsrun_rankfile(
            slots, cores_per_slot=4, path=str(tmp_path / "rf.erf"))
        text = open(path).read()
        assert "cpu_index_using: logical" in text
        assert "rank: 0: { hostname: c1; cpu: {0-3} }" in text
        assert "rank: 1: { hostname: c1; cpu: {4-7} }" in text
        assert "rank: 2: { hostname: c2; cpu: {0-3} }" in text

    def test_build_command(self, tmp_path):
        from horovod_tpu.runner import js_run
        cmd = js_run.build_jsrun_command(
            ["python", "train.py"], rankfile="rf.erf",
            env_overrides={"HOROVOD_GLOO_RENDEZVOUS_PORT": "1234"},
            output_filename="out.log")
        assert cmd[:3] == ["jsrun", "--erf_input", "rf.erf"]
        assert "-E" in cmd and "HOROVOD_GLOO_RENDEZVOUS_PORT=1234" in cmd
        assert "--stdio_stdout" in cmd
        assert cmd[-2:] == ["python", "train.py"]

    def test_build_command_resource_set_flags(self):
        # Default placement mode: no ERF (needs no compute-node core
        # count); jsrun divides each host's CPUs across resource sets.
        from horovod_tpu.runner import js_run
        cmd = js_run.build_jsrun_command(
            ["python", "t.py"], np=8, rs_per_host=4)
        assert cmd[:7] == ["jsrun", "--nrs", "8", "--tasks_per_rs", "1",
                           "--rs_per_host", "4"]

    def test_rankfile_requires_explicit_cores(self, tmp_path,
                                              monkeypatch):
        # The launch node's cpu_count says nothing about compute nodes;
        # guessing would mis-pin every rank.
        from horovod_tpu.runner import js_run
        monkeypatch.delenv(js_run.CPU_PER_SLOT_ENV, raising=False)
        slots = get_host_assignments(parse_hosts("c1:2"), 2)
        with pytest.raises(ValueError, match="cores per"):
            js_run.generate_jsrun_rankfile(
                slots, path=str(tmp_path / "rf.erf"))

    def test_adopt_jsm_env_bare(self):
        # JSM identity + our control plane but no exported layout:
        # rank/size/local adopted; cross left unset — per-rank division
        # math would give hosts with different slot counts inconsistent
        # cross topologies.
        from horovod_tpu.runner import js_run
        env = {"JSM_NAMESPACE_RANK": "5", "JSM_NAMESPACE_SIZE": "8",
               "JSM_NAMESPACE_LOCAL_RANK": "1",
               "JSM_NAMESPACE_LOCAL_SIZE": "4",
               "HOROVOD_GLOO_RENDEZVOUS_ADDR": "10.0.0.1"}
        assert js_run.adopt_jsm_env(env)
        assert env["HOROVOD_RANK"] == "5" and env["HOROVOD_SIZE"] == "8"
        assert env["HOROVOD_LOCAL_RANK"] == "1"
        assert env["HOROVOD_LOCAL_SIZE"] == "4"
        assert "HOROVOD_CROSS_RANK" not in env
        assert "HOROVOD_CROSS_SIZE" not in env

    def test_adopt_ignores_bare_jsrun(self):
        # Bare `jsrun -n N python eval.py` (no launcher control plane):
        # each process keeps its independent size-1 world, same as the
        # bare-mpirun case.
        from horovod_tpu.runner import js_run
        env = {"JSM_NAMESPACE_RANK": "2", "JSM_NAMESPACE_SIZE": "4"}
        assert not js_run.adopt_jsm_env(env)
        assert "HOROVOD_RANK" not in env

    def test_adopt_never_clobbers_launcher_env(self):
        from horovod_tpu.runner import js_run
        env = {"HOROVOD_RANK": "0", "JSM_NAMESPACE_RANK": "5",
               "JSM_NAMESPACE_SIZE": "8"}
        assert not js_run.adopt_jsm_env(env)
        assert env["HOROVOD_RANK"] == "0"

    def test_adopt_noop_outside_jsm(self):
        from horovod_tpu.runner import js_run
        env = {}
        assert not js_run.adopt_jsm_env(env)
        assert env == {}

    def test_hosts_cyclic_distribution_aggregated(self, tmp_path):
        # Cyclic task placement repeats hostnames non-consecutively; slots
        # must aggregate per host or the topology is wrong.
        from horovod_tpu.runner import js_run
        hf = tmp_path / "djob"
        hf.write_text("batch1\nc1\nc2\nc1\nc2\n")
        assert js_run.lsf_hosts_string(
            {"LSB_DJOB_HOSTFILE": str(hf)}) == "c1:2,c2:2"

    def test_adopt_uses_exported_layout_non_uniform(self):
        # launch_jsrun exports the host layout; workers must derive
        # local/cross ranks with get_host_assignments, not uniform math.
        from horovod_tpu.runner import js_run
        env = {"JSM_NAMESPACE_RANK": "4", "JSM_NAMESPACE_SIZE": "6",
               js_run.JSRUN_HOSTS_ENV: "c1:4,c2:2"}
        assert js_run.adopt_jsm_env(env)
        assert env["HOROVOD_HOSTNAME"] == "c2"
        assert env["HOROVOD_LOCAL_RANK"] == "0"
        assert env["HOROVOD_LOCAL_SIZE"] == "2"
        assert env["HOROVOD_CROSS_RANK"] == "1"
        assert env["HOROVOD_CROSS_SIZE"] == "2"

    def test_adopt_ignores_plain_mpirun(self):
        # Bare OMPI vars without our control-plane env: each process is
        # an independent size-1 world (plain `mpirun python eval.py`).
        from horovod_tpu.runner import js_run
        env = {"OMPI_COMM_WORLD_RANK": "1", "OMPI_COMM_WORLD_SIZE": "4"}
        assert not js_run.adopt_jsm_env(env)
        assert "HOROVOD_RANK" not in env

    def test_adopt_accepts_ompi_with_rendezvous(self):
        # Our mpirun launcher exports the rendezvous env -> adopt.
        from horovod_tpu.runner import js_run
        env = {"OMPI_COMM_WORLD_RANK": "1", "OMPI_COMM_WORLD_SIZE": "2",
               "HOROVOD_GLOO_RENDEZVOUS_ADDR": "10.0.0.1"}
        assert js_run.adopt_jsm_env(env)
        assert env["HOROVOD_RANK"] == "1"

    def test_adopt_detects_placement_mismatch(self):
        # jsrun placed the task off the host-major order the layout
        # assumes -> loud failure, not silently wrong chip binding.
        from horovod_tpu.runner import js_run
        env = {"JSM_NAMESPACE_RANK": "1", "JSM_NAMESPACE_SIZE": "4",
               "JSM_NAMESPACE_LOCAL_RANK": "0",
               js_run.JSRUN_HOSTS_ENV: "c1:2,c2:2"}
        with pytest.raises(RuntimeError, match="placement mismatch"):
            js_run.adopt_jsm_env(env)


class TestMpiLauncher:
    def test_use_mpi_end_to_end(self, tmp_path, monkeypatch):
        """--use-mpi drives one mpirun (stubbed: spawns N local copies
        with OMPI_COMM_WORLD_* env) and workers adopt rank identity from
        the OMPI vars + exported layout, then allreduce correctly."""
        stub = tmp_path / "mpirun"
        stub.write_text(textwrap.dedent("""\
            #!/usr/bin/env python3
            import os, subprocess, sys
            argv = sys.argv[1:]
            if argv and argv[0] == "--version":
                print("Open MPI 4.1.0"); sys.exit(0)
            arity = {"-np": 1, "-H": 1, "-bind-to": 1, "-map-by": 1,
                     "-mca": 2, "-x": 1, "--allow-run-as-root": 0}
            np = 1; i = 0
            while i < len(argv):
                if argv[i] in arity:
                    if argv[i] == "-np":
                        np = int(argv[i + 1])
                    i += 1 + arity[argv[i]]
                else:
                    break
            cmd = argv[i:]
            procs = []
            for r in range(np):
                env = dict(os.environ)
                env["OMPI_COMM_WORLD_RANK"] = str(r)
                env["OMPI_COMM_WORLD_SIZE"] = str(np)
                procs.append(subprocess.Popen(cmd, env=env))
            sys.exit(max(p.wait() for p in procs))
        """))
        stub.chmod(0o755)
        worker = tmp_path / "train.py"
        worker.write_text(textwrap.dedent("""\
            import numpy as np
            import horovod_tpu as hvd
            hvd.init()
            out = hvd.allreduce(np.ones(3, np.float32), op=hvd.Sum,
                                name="mpi_e2e")
            assert hvd.size() == 2 and out[0] == 2.0, (hvd.size(), out)
            print(f"MPI_RANK{hvd.rank()}_OK")
            hvd.shutdown()
        """))
        env = dict(os.environ)
        env["PATH"] = str(tmp_path) + os.pathsep + env["PATH"]
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["HOROVOD_RENDEZVOUS_EPOCH"] = "mpi-e2e"
        for k in list(env):
            if k.startswith("HOROVOD_") and k != "HOROVOD_RENDEZVOUS_EPOCH":
                del env[k]
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.runner.launch",
             "--use-mpi", "-np", "2", sys.executable, str(worker)],
            env=env, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "MPI_RANK0_OK" in proc.stdout
        assert "MPI_RANK1_OK" in proc.stdout

    def test_build_command_flavor_flags(self):
        """--allow-run-as-root is OpenMPI/Spectrum-only: mpich/intel Hydra
        mpirun rejects it at launch (advisor finding); env export style
        also differs per flavor."""
        from horovod_tpu.runner.mpi_run import build_mpi_command

        # 'unknown' (failed version probe) keeps the OpenMPI treatment.
        for flavor in ("openmpi", "spectrum", "unknown"):
            cmd = build_mpi_command(["python", "x.py"], np=2,
                                    hosts="h1:1,h2:1",
                                    mpi_flavor=flavor, env={})
            assert "--allow-run-as-root" in cmd, (flavor, cmd)
            assert "-genvlist" not in cmd
            assert "-H" in cmd and "-hosts" not in cmd, (flavor, cmd)
        for flavor in ("mpich", "intel"):
            cmd = build_mpi_command(["python", "x.py"], np=2,
                                    hosts="h1:1,h2:1",
                                    mpi_flavor=flavor,
                                    env={"HOROVOD_RANK": "0"})
            assert "--allow-run-as-root" not in cmd, (flavor, cmd)
            assert "-genvlist" in cmd
            # Hydra spells the host list -hosts and rejects -H.
            assert "-hosts" in cmd and "-H" not in cmd, (flavor, cmd)

    def test_use_mpi_without_mpirun_errors(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PATH", str(tmp_path))   # no mpirun here
        from horovod_tpu.runner import launch
        rc = launch.main(["--use-mpi", "-np", "2", "true"])
        assert rc == 2
