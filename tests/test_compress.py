"""compress/ subsystem battery: quantize/dequantize round-trip bounds,
wire serialization, the numpy/jax twin parity, error-feedback convergence
on a tiny quadratic, codec negotiation (mismatch -> structured ERROR),
cache invalidation on codec change, and int8 allreduce equivalence across
the eager planes (threaded tcp/shm here; subprocess tcp/shm/xla worlds
via mp_worker batteries) and the compiled grad_sync path."""
from __future__ import annotations

import os
import sys
import threading

import numpy as np
import pytest

from horovod_tpu.compress import (CAST_CODECS, CompressionCodec,
                                  QUANTIZED_CODECS, chunk_bounds,
                                  codec_from_name, codec_name,
                                  dequantize, from_bytes, quantize,
                                  roundtrip_error_bound, serialized_nbytes,
                                  to_bytes)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Quantize / dequantize units
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("codec", [CompressionCodec.INT8,
                                   CompressionCodec.UINT4])
@pytest.mark.parametrize("block_size", [16, 64, 256])
def test_roundtrip_error_bound(codec, block_size):
    rng = np.random.default_rng(0)
    for n in (1, 5, block_size, block_size + 3, 4 * block_size, 10_000):
        x = (rng.standard_normal(n) * rng.uniform(0.1, 30)).astype(
            np.float32)
        qb = quantize(x, codec, block_size)
        xh = dequantize(qb)
        bound = roundtrip_error_bound(x, codec, block_size)
        assert xh.shape == x.shape
        assert np.all(np.abs(x - xh) <= bound + 1e-6), \
            (codec, n, float(np.abs(x - xh).max()))


@pytest.mark.parametrize("codec", [CompressionCodec.INT8,
                                   CompressionCodec.UINT4])
def test_wire_serialization_roundtrip_and_size(codec):
    rng = np.random.default_rng(1)
    for n in (0, 1, 7, 255, 1000):
        x = rng.standard_normal(n).astype(np.float32)
        qb = quantize(x, codec, 64)
        raw = to_bytes(qb)
        assert len(raw) == serialized_nbytes(n, codec, 64)
        qb2 = from_bytes(np.frombuffer(raw, np.uint8), n, codec, 64)
        np.testing.assert_array_equal(dequantize(qb2), dequantize(qb))
    # Wire-byte ratios vs fp32: the whole point of the subsystem.
    n = 1 << 16
    fp32 = n * 4
    assert serialized_nbytes(n, CompressionCodec.INT8, 256) * 3.5 < fp32
    assert serialized_nbytes(n, CompressionCodec.UINT4, 256) * 7.0 < fp32


def test_quantize_edge_cases():
    # Constant blocks: zero range must not divide by zero, and must
    # reconstruct exactly.
    x = np.full(100, 3.25, np.float32)
    np.testing.assert_array_equal(dequantize(quantize(
        x, CompressionCodec.INT8, 32)), x)
    # Tail block shorter than block_size keeps its own scale.
    x = np.concatenate([np.zeros(64, np.float32),
                        np.full(3, 1000.0, np.float32)])
    xh = dequantize(quantize(x, CompressionCodec.INT8, 64))
    np.testing.assert_allclose(xh[:64], 0.0, atol=1e-6)
    np.testing.assert_allclose(xh[64:], 1000.0, rtol=1e-2)


def test_codec_registry():
    assert codec_from_name("int8") == CompressionCodec.INT8
    assert codec_from_name(None) == CompressionCodec.NONE
    assert codec_from_name(CompressionCodec.UINT4) == CompressionCodec.UINT4
    assert codec_name(CompressionCodec.BF16) == "bf16"

    class Marker:
        wire_codec = "uint4"
    assert codec_from_name(Marker) == CompressionCodec.UINT4
    with pytest.raises(ValueError, match="Unknown compression codec"):
        codec_from_name("int7")
    assert set(QUANTIZED_CODECS) | set(CAST_CODECS) | \
        {CompressionCodec.NONE} == set(CompressionCodec)


def test_jax_matches_numpy():
    """The compiled twin must apply the identical scale rule and
    rounding, so planes and grad_sync land in one error bound."""
    import jax.numpy as jnp

    from horovod_tpu.compress import jax_ops

    rng = np.random.default_rng(2)
    for codec in (CompressionCodec.INT8, CompressionCodec.UINT4):
        m, bs = 512, 64
        x = (rng.standard_normal(m) * 5).astype(np.float32)
        qb = quantize(x, codec, bs)
        q, s, zp = jax_ops.quantize_rows(jnp.asarray(x)[None, :], codec, bs)
        np.testing.assert_array_equal(np.asarray(q)[0], qb.payload)
        np.testing.assert_array_equal(np.asarray(s)[0], qb.scales)
        np.testing.assert_array_equal(np.asarray(zp)[0], qb.zero_points)
        deq = jax_ops.dequantize_rows(q, s, zp, codec, bs)
        np.testing.assert_array_equal(np.asarray(deq)[0], dequantize(qb))


# ---------------------------------------------------------------------------
# Error feedback
# ---------------------------------------------------------------------------
def test_error_feedback_store_roundtrip():
    from horovod_tpu.compress import ErrorFeedback

    ef = ErrorFeedback(CompressionCodec.UINT4, block_size=32)
    x = np.random.default_rng(3).standard_normal(200).astype(np.float32)
    comp = ef.compensate("g", x)
    wire = ef.update("g", comp)
    res = ef.residual("g")
    np.testing.assert_allclose(comp, wire + res, rtol=1e-6, atol=1e-6)
    # Second step re-injects the residual.
    comp2 = ef.compensate("g", x)
    np.testing.assert_allclose(comp2, x + res, rtol=1e-6, atol=1e-6)


def test_error_feedback_telescopes():
    """The EF identity: over T steps, sum(wire_t) == sum(grad_t) - e_T —
    no gradient mass is ever lost, only delayed by the final residual."""
    from horovod_tpu.compress import ErrorFeedback

    rng = np.random.default_rng(5)
    ef = ErrorFeedback(CompressionCodec.UINT4, block_size=32)
    grads = rng.standard_normal((20, 128)).astype(np.float32)
    wire_sum = np.zeros(128, np.float32)
    for g in grads:
        wire_sum += ef.update("w", ef.compensate("w", g))
    np.testing.assert_allclose(wire_sum + ef.residual("w"),
                               grads.sum(0), rtol=1e-4, atol=1e-4)


def test_error_feedback_quadratic_convergence():
    """EF-SGD convergence on a tiny heterogeneous quadratic: two ranks
    minimize mean_r 0.5||w - c_r||^2 (optimum = mean(c_r)).  Local
    gradients at the optimum are NONZERO (±(c_0-c_1)/2), so each rank's
    block quantization error has a persistent floor — plain quantized
    gradient descent stalls there, while error feedback re-injects the
    error and keeps descending (the EF-SGD guarantee)."""
    rng = np.random.default_rng(5)
    n, bs = 256, 64
    c = (rng.standard_normal((2, n)) * 50).astype(np.float32)
    w_opt = c.mean(axis=0)
    codec = CompressionCodec.INT8

    def run(use_ef: bool, steps=400, lr=0.2) -> float:
        w = np.zeros(n, np.float32)
        res = np.zeros((2, n), np.float32)
        for _ in range(steps):
            gsum = np.zeros(n, np.float32)
            for r in range(2):
                g = w - c[r]
                if use_ef:
                    comp = g + res[r]
                    wire = dequantize(quantize(comp, codec, bs))
                    res[r] = comp - wire
                    gsum += wire
                else:
                    gsum += dequantize(quantize(g, codec, bs))
            w = w - lr * gsum / 2
        return float(np.linalg.norm(w - w_opt))

    dist_plain = run(False)
    dist_ef = run(True)
    assert dist_ef < 1.0, dist_ef                    # ~0.4 measured
    assert dist_ef * 3 < dist_plain, (dist_ef, dist_plain)   # ~2.5


# ---------------------------------------------------------------------------
# Controller negotiation + cache
# ---------------------------------------------------------------------------
def test_codec_mismatch_structured_error():
    from horovod_tpu.common.message import (Request, RequestType,
                                            ResponseType)
    from util_world import InProcWorld, make_controller, run_ranks

    world = InProcWorld(2)

    def rank_fn(r):
        ctrl = make_controller(r, 2, world)
        ctrl.tensor_queue.push_back_to_queue(Request(
            request_rank=r, request_type=RequestType.ALLREDUCE,
            tensor_name="g", tensor_shape=(4,),
            codec=int(CompressionCodec.INT8) if r == 0 else 0,
            codec_block_size=256 if r == 0 else 0))
        return ctrl.compute_response_list()

    lists = run_ranks(2, rank_fn)
    for rl in lists:
        assert len(rl.responses) == 1
        resp = rl.responses[0]
        assert resp.response_type == ResponseType.ERROR
        assert "codec" in resp.error_message.lower()


def test_codec_negotiated_into_response():
    from horovod_tpu.common.message import (Request, RequestType,
                                            ResponseType)
    from util_world import InProcWorld, make_controller, run_ranks

    world = InProcWorld(2)

    def rank_fn(r):
        ctrl = make_controller(r, 2, world)
        ctrl.tensor_queue.push_back_to_queue(Request(
            request_rank=r, request_type=RequestType.ALLREDUCE,
            tensor_name="g", tensor_shape=(4,),
            codec=int(CompressionCodec.UINT4), codec_block_size=128))
        return ctrl.compute_response_list()

    for rl in run_ranks(2, rank_fn):
        (resp,) = rl.responses
        assert resp.response_type == ResponseType.ALLREDUCE
        assert resp.codec == int(CompressionCodec.UINT4)
        assert resp.codec_block_size == 128


def test_adasum_quantized_rejected():
    from horovod_tpu.common.message import (Request, RequestType,
                                            ResponseType)
    from util_world import InProcWorld, make_controller, run_ranks

    world = InProcWorld(2)

    def rank_fn(r):
        ctrl = make_controller(r, 2, world)
        ctrl.tensor_queue.push_back_to_queue(Request(
            request_rank=r, request_type=RequestType.ADASUM,
            tensor_name="g", tensor_shape=(4,),
            codec=int(CompressionCodec.INT8), codec_block_size=256))
        return ctrl.compute_response_list()

    for rl in run_ranks(2, rank_fn):
        (resp,) = rl.responses
        assert resp.response_type == ResponseType.ERROR
        assert "adasum" in resp.error_message.lower()


def test_response_cache_invalidates_on_codec_change():
    from horovod_tpu.common.message import (Request, RequestType, Response,
                                            ResponseType)
    from horovod_tpu.common.response_cache import CacheState, ResponseCache

    cache = ResponseCache(16)
    req = Request(request_rank=0, request_type=RequestType.ALLREDUCE,
                  tensor_name="g", tensor_shape=(8,),
                  codec=0, codec_block_size=0)
    cache.put(Response(response_type=ResponseType.ALLREDUCE,
                       tensor_names=["g"], tensor_sizes=[8]), req)
    assert cache.cached(req) == CacheState.HIT
    flipped = Request(request_rank=0, request_type=RequestType.ALLREDUCE,
                      tensor_name="g", tensor_shape=(8,),
                      codec=int(CompressionCodec.INT8),
                      codec_block_size=256)
    assert cache.cached(flipped) == CacheState.INVALID


def test_wire_roundtrip_codec_fields():
    from horovod_tpu.common.message import (Request, RequestList,
                                            RequestType, Response,
                                            ResponseList, ResponseType)

    req = Request(request_rank=1, request_type=RequestType.ALLREDUCE,
                  tensor_name="g", tensor_shape=(3, 3),
                  codec=int(CompressionCodec.INT8), codec_block_size=512)
    decoded = RequestList.from_bytes(
        RequestList(requests=[req]).to_bytes()).requests[0]
    assert decoded == req

    resp = Response(response_type=ResponseType.ALLREDUCE,
                    tensor_names=["g"], tensor_sizes=[9],
                    codec=int(CompressionCodec.UINT4),
                    codec_block_size=64)
    rl = ResponseList(responses=[resp], tuned_codec=int(
        CompressionCodec.FP16))
    decoded = ResponseList.from_bytes(rl.to_bytes())
    assert decoded.responses[0] == resp
    assert decoded.tuned_codec == int(CompressionCodec.FP16)


# ---------------------------------------------------------------------------
# Eager planes (threaded in-process worlds)
# ---------------------------------------------------------------------------
@pytest.fixture()
def kv():
    from horovod_tpu.runner.network import (RendezvousClient,
                                            RendezvousServer)
    server = RendezvousServer()
    port = server.start()
    yield RendezvousClient("127.0.0.1", port, 10.0)
    server.stop()


def _threaded(n, fn, timeout=60.0):
    results: list = [None] * n
    errors: list = []

    def worker(r):
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "rank thread hung"
    if errors:
        raise errors[0]
    return results


def _plane_error_bound(data, codec, block_size):
    size = data.shape[0]
    input_bound = sum(roundtrip_error_bound(data[r], codec, block_size)
                     for r in range(size))
    ref = data.sum(axis=0)
    b = chunk_bounds(ref.size, size)
    requant = np.concatenate(
        [roundtrip_error_bound(ref[b[r]:b[r + 1]], codec, block_size)
         for r in range(size)])
    return 2 * input_bound + requant + 1e-5


@pytest.mark.parametrize("codec", [CompressionCodec.INT8,
                                   CompressionCodec.UINT4])
@pytest.mark.parametrize("size", [2, 3])
def test_tcp_quantized_allreduce(kv, codec, size):
    from horovod_tpu.backend.tcp import TcpCollectives
    from horovod_tpu.runner.network import PeerMesh

    rng = np.random.default_rng(10)
    n = 5000
    data = (rng.standard_normal((size, n)) * 3).astype(np.float32)
    meshes: list = [None] * size

    def worker(r):
        mesh = PeerMesh(r, size, kv, scope=f"tq{codec}{size}",
                        timeout=10.0)
        meshes[r] = mesh
        return TcpCollectives(mesh).quantized_allreduce(
            data[r].copy(), codec, 128)

    try:
        outs = _threaded(size, worker)
        for r in range(1, size):
            np.testing.assert_array_equal(outs[0], outs[r])
        bound = _plane_error_bound(data, codec, 128)
        err = np.abs(outs[0].astype(np.float64) - data.sum(0))
        assert np.all(err <= bound), (float(err.max()),)
        # Wire volume: strictly below the fp32 ring's 2(N-1)/N·4n bytes.
        fp32_ring = 2 * (size - 1) * n * 4 // size
        assert meshes[0].bytes_sent < fp32_ring / 2.5
    finally:
        for m in meshes:
            if m is not None:
                m.close()


def test_shm_quantized_matches_tcp_bitwise(kv):
    """Planes interoperate, so their quantized reconstructions must be
    bit-identical (same quantize order, same rank-order fp32 sum)."""
    from horovod_tpu.backend.shm import ShmBackend, ShmWorld
    from horovod_tpu.backend.tcp import TcpCollectives
    from horovod_tpu.common.dtypes import from_any
    from horovod_tpu.common.message import Response, ResponseType
    from horovod_tpu.common.tensor_queue import TensorTableEntry
    from horovod_tpu.runner.network import PeerMesh

    size, n = 3, 3000
    rng = np.random.default_rng(11)
    data = rng.standard_normal((size, n)).astype(np.float32)
    worlds: list = [None] * size

    def form(r):
        worlds[r] = ShmWorld(r, size, kv, scope="sq", capacity=1 << 20,
                             timeout=10.0)
        return worlds[r]

    _threaded(size, form)
    if not all(w.formed for w in worlds):
        pytest.skip("shm world did not form on this host")

    def shm_run(r):
        be = ShmBackend(worlds[r])
        resp = Response(response_type=ResponseType.ALLREDUCE,
                        tensor_names=["x"], tensor_sizes=[n],
                        tensor_type=from_any(np.dtype(np.float32)),
                        codec=int(CompressionCodec.INT8),
                        codec_block_size=128)
        entry = TensorTableEntry(tensor_name="x", tensor=data[r].copy())
        assert be.enabled(resp, [entry])
        assert be.allreduce(resp, [entry]).ok_p()
        return entry.output

    meshes: list = [None] * size

    def tcp_run(r):
        mesh = PeerMesh(r, size, kv, scope="sqt", timeout=10.0)
        meshes[r] = mesh
        return TcpCollectives(mesh).quantized_allreduce(
            data[r].copy(), CompressionCodec.INT8, 128)

    try:
        shm_outs = _threaded(size, shm_run)
        tcp_outs = _threaded(size, tcp_run)
        np.testing.assert_array_equal(shm_outs[0], shm_outs[1])
        np.testing.assert_array_equal(shm_outs[0], tcp_outs[0])
    finally:
        for w in worlds:
            w.close()
        for m in meshes:
            if m is not None:
                m.close()


def test_shm_declines_oversized_quantized(kv):
    """Capacity accounting must use the QUANTIZED staging size and stay
    rank-symmetric: a payload whose staged chunks exceed the region
    falls through to the TCP plane."""
    from horovod_tpu.backend.shm import ShmBackend, ShmWorld
    from horovod_tpu.common.dtypes import from_any
    from horovod_tpu.common.message import Response, ResponseType
    from horovod_tpu.common.tensor_queue import TensorTableEntry
    from horovod_tpu.compress import staged_nbytes

    size = 2
    capacity = 1 << 12
    worlds = _threaded(size, lambda r: ShmWorld(
        r, size, kv, scope="cap", capacity=capacity, timeout=10.0))
    if not all(w.formed for w in worlds):
        pytest.skip("shm world did not form on this host")
    try:
        be = ShmBackend(worlds[0])
        # Quantized int8 fits where fp32 would not (4x), and a payload
        # larger than the quantized budget is declined.
        n_fits = capacity // 2      # 2KB as int8+meta; 8KB as fp32
        per, total = staged_nbytes(n_fits, size, CompressionCodec.INT8,
                                   256)
        assert total + max(per) <= capacity

        def resp(n, codec):
            return Response(response_type=ResponseType.ALLREDUCE,
                            tensor_names=["x"], tensor_sizes=[n],
                            tensor_type=from_any(np.dtype(np.float32)),
                            codec=int(codec), codec_block_size=256)

        entry = TensorTableEntry(
            tensor_name="x", tensor=np.zeros(n_fits, np.float32))
        assert be.enabled(resp(n_fits, CompressionCodec.INT8), [entry])
        assert not be.enabled(resp(n_fits, CompressionCodec.NONE),
                              [entry])
        big = TensorTableEntry(
            tensor_name="x", tensor=np.zeros(4 * capacity, np.float32))
        assert not be.enabled(resp(4 * capacity, CompressionCodec.INT8),
                              [big])
    finally:
        for w in worlds:
            w.close()


# ---------------------------------------------------------------------------
# Compiled grad_sync path (virtual CPU mesh from conftest)
# ---------------------------------------------------------------------------
def _dp_mesh(n=4):
    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:n])
    return Mesh(devices, ("dp",))


def test_grad_sync_int8_matches_fp32_within_bound():
    import jax

    from horovod_tpu.parallel import GradSyncConfig, build_grad_sync

    world = 4
    mesh = _dp_mesh(world)
    rng = np.random.default_rng(20)
    grads = {"w": (rng.standard_normal((world, 33, 7)) * 2).astype(
        np.float32),
        "b": rng.standard_normal((world, 11)).astype(np.float32)}

    ref_fn = build_grad_sync(mesh, GradSyncConfig(op="average"))
    q_fn = build_grad_sync(mesh, GradSyncConfig(
        op="average", compression="int8", compression_block_size=64))
    ref = jax.tree_util.tree_map(np.asarray, ref_fn(grads))
    out = jax.tree_util.tree_map(np.asarray, q_fn(grads))
    for key in grads:
        flat = grads[key].reshape(world, -1)
        bound = _plane_error_bound(flat, CompressionCodec.INT8, 64) / world
        err = np.abs(out[key].reshape(world, -1)[0].astype(np.float64)
                     - ref[key].reshape(world, -1)[0])
        assert np.all(err <= bound.reshape(-1)[:err.size] + 1e-5), \
            (key, float(err.max()))
        # Replicated output: every rank row identical.
        for r in range(1, world):
            np.testing.assert_array_equal(out[key][0], out[key][r])


def test_grad_sync_ef_training_within_5pct_of_fp32():
    """Acceptance criterion: a small training run with compression="int8"
    + error feedback reaches a loss within 5% of the fp32 baseline in the
    same step count.  Linear regression on a fixed dataset, dp=2, the EF
    residual threading through the jitted step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.parallel import (GradSyncConfig, init_error_feedback,
                                      sync_gradients, sync_gradients_ef)

    world = 2
    mesh = _dp_mesh(world)
    rng = np.random.default_rng(21)
    w_true = rng.standard_normal((16, 4)).astype(np.float32)
    X = rng.standard_normal((world, 64, 16)).astype(np.float32)
    Y = np.einsum("rbi,io->rbo", X, w_true).astype(np.float32)

    def make_step(cfg, use_ef):
        def local_step(w, res, x, y):
            def loss_of(w):
                pred = x[0] @ w
                return jnp.mean((pred - y[0]) ** 2)

            loss, g = jax.value_and_grad(loss_of)(w[0])
            if use_ef:
                g, new_res = sync_gradients_ef(g, res[0], cfg)
            else:
                g, new_res = sync_gradients(g, cfg), res[0]
            w = w[0] - 0.05 * g
            return (w[None], new_res[None],
                    jax.lax.pmean(loss, "dp")[None])

        mapped = shard_map(
            local_step, mesh=mesh,
            in_specs=(P("dp"), P("dp"), P("dp"), P("dp")),
            out_specs=(P("dp"), P("dp"), P("dp")),
            check_vma=False)
        return jax.jit(mapped)

    def train(cfg, use_ef, steps=60):
        w = np.zeros((world, 16, 4), np.float32)
        res = np.asarray(jax.tree_util.tree_map(
            lambda z: np.zeros((world,) + z.shape, np.float32),
            init_error_feedback(np.zeros((16, 4), np.float32))))
        step = make_step(cfg, use_ef)
        loss = None
        for _ in range(steps):
            w, res, loss = step(w, res, X, Y)
        return float(np.asarray(loss)[0])

    base = train(GradSyncConfig(op="average"), use_ef=False)
    ef = train(GradSyncConfig(op="average", compression="int8",
                              compression_block_size=64,
                              error_feedback=True), use_ef=True)
    # Same step count, loss within 5% of the fp32 baseline (both are
    # tiny; compare the gap to the initial loss scale to avoid 0/0).
    init_loss = float(np.mean(Y ** 2))
    assert ef <= base + 0.05 * init_loss, (base, ef, init_loss)


def test_grad_sync_adasum_rejects_quantized():
    from horovod_tpu.parallel import GradSyncConfig
    from horovod_tpu.parallel.grad_sync import _sync_impl

    with pytest.raises(ValueError, match="adasum"):
        _sync_impl({"g": np.ones(4, np.float32)},
                   GradSyncConfig(op="adasum", compression="int8"), None)


def test_quantized_allreduce_uint4_requires_even_block():
    import jax.numpy as jnp

    from horovod_tpu.compress import jax_ops

    with pytest.raises(ValueError, match="even block"):
        jax_ops.quantized_allreduce(jnp.zeros(8), ("dp",), "sum",
                                    CompressionCodec.UINT4, 3)


# ---------------------------------------------------------------------------
# Subprocess worlds: eager end-to-end over the real planes
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("size", [2, 3])
def test_eager_compress_tcp_world(size):
    from test_multiprocess import _run_world
    _run_world(size, "compress", timeout=180.0)


def test_eager_compress_shm_world():
    from test_multiprocess import _run_world
    _run_world(2, "compress_shm", timeout=180.0)


def test_eager_compress_xla_world():
    from test_multiprocess import _run_world
    _run_world(2, "compress_xla", timeout=240.0)
