"""Elastic subsystem unit tests.

Modeled on the reference's test/single/test_elastic_driver.py strategy
(SURVEY §4): fake discovery sources + mock workers drive the ElasticDriver
state machine fully in-process, no cluster required.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict

import numpy as np
import pytest

from horovod_tpu.elastic.discovery import (FixedHostDiscovery, HostDiscovery,
                                           HostManager, HostUpdateResult)
from horovod_tpu.elastic.driver import ElasticDriver
from horovod_tpu.elastic.registration import (FAILURE, READY, SUCCESS,
                                              WorkerStateRegistry)
from horovod_tpu.elastic.sampler import ElasticSampler


class SequenceDiscovery(HostDiscovery):
    """Replays a schedule of host dicts; sticks on the last one."""

    def __init__(self, *rounds):
        self._rounds = list(rounds)
        self.calls = 0

    def find_available_hosts_and_slots(self):
        idx = min(self.calls, len(self._rounds) - 1)
        self.calls += 1
        return OrderedDict(self._rounds[idx])


# ---------------------------------------------------------------------------
# HostManager / discovery
# ---------------------------------------------------------------------------
class TestHostManager:
    def test_update_added_removed(self):
        disc = SequenceDiscovery({"a": 2}, {"a": 2, "b": 2}, {"b": 2})
        mgr = HostManager(disc)
        assert mgr.update_available_hosts() == HostUpdateResult.ADDED
        assert mgr.current_hosts == {"a": 2}
        assert mgr.update_available_hosts() == HostUpdateResult.ADDED
        assert set(mgr.current_hosts) == {"a", "b"}
        assert mgr.update_available_hosts() == HostUpdateResult.REMOVED
        assert set(mgr.current_hosts) == {"b"}

    def test_no_update(self):
        mgr = HostManager(FixedHostDiscovery(OrderedDict(a=2)))
        assert mgr.update_available_hosts() == HostUpdateResult.ADDED
        assert mgr.update_available_hosts() == HostUpdateResult.NO_UPDATE

    def test_blacklist_excludes_host(self):
        mgr = HostManager(FixedHostDiscovery(OrderedDict(a=2, b=2)))
        mgr.update_available_hosts()
        mgr.blacklist("a")
        assert mgr.is_blacklisted("a")
        assert set(mgr.current_hosts) == {"b"}
        # Re-discovery never resurrects a blacklisted host.
        mgr.update_available_hosts()
        assert set(mgr.current_hosts) == {"b"}

    def test_slot_count_change_is_update(self):
        disc = SequenceDiscovery({"a": 2}, {"a": 4})
        mgr = HostManager(disc)
        mgr.update_available_hosts()
        assert mgr.update_available_hosts() == HostUpdateResult.MIXED


# ---------------------------------------------------------------------------
# WorkerStateRegistry
# ---------------------------------------------------------------------------
class FakeDriver:
    def __init__(self):
        self.stopped = False
        self.resumed = 0
        self.limit_exceeded = False

    def finished(self):
        return self.stopped

    def stop(self):
        self.stopped = True

    def resume(self):
        self.resumed += 1

    def set_reset_limit_exceeded(self):
        self.limit_exceeded = True


class TestWorkerStateRegistry:
    def _registry(self, size, reset_limit=None):
        driver = FakeDriver()
        mgr = HostManager(FixedHostDiscovery(OrderedDict(a=size)))
        mgr.update_available_hosts()
        reg = WorkerStateRegistry(driver, mgr, reset_limit=reset_limit)
        reg.reset(size)
        return driver, mgr, reg

    def test_all_success_stops_driver(self):
        driver, _, reg = self._registry(2)
        reg.record_success("a", 0)
        assert not driver.stopped
        reg.record_success("a", 1)
        assert driver.stopped
        assert driver.resumed == 0

    def test_failure_blacklists_and_resumes(self):
        driver, mgr, reg = self._registry(2)
        reg.record_failure("a", 0)
        reg.record_ready("a", 1)
        assert driver.resumed == 1
        assert mgr.is_blacklisted("a")

    def test_all_ready_resumes(self):
        driver, _, reg = self._registry(2)
        reg.record_ready("a", 0)
        reg.record_ready("a", 1)
        assert driver.resumed == 1
        assert not driver.stopped

    def test_failure_overrides_ready(self):
        driver, _, reg = self._registry(2)
        reg.record_ready("a", 0)
        assert reg.count(READY) == 1
        reg.record_failure("a", 0)
        assert reg.count(READY) == 0
        assert reg.count(FAILURE) == 1
        # READY never downgrades a terminal state.
        reg.record_ready("a", 0)
        assert reg.count(FAILURE) == 1

    def test_stale_slot_records_ignored(self):
        """A record from a slot outside the current round's assignment must
        not count toward the barrier (e.g. a long-dead worker on a host
        removed rounds ago finally exiting)."""
        driver, _, reg = self._registry(2)
        reg.reset(2, expected_slots=["a[0]", "a[1]"])
        reg.record_failure("zombie", 0)
        reg.record_ready("a", 0)
        assert driver.resumed == 0         # only 1/2 expected recorded
        reg.record_ready("a", 1)
        assert driver.resumed == 1

    def test_ready_bound_to_round(self):
        """A READY targeting an already-resolved round is dropped instead
        of leaking into the next round's barrier."""
        driver, _, reg = self._registry(2)
        current = reg.rendezvous_id
        reg.reset(2)                        # round advances concurrently
        reg.record_ready("a", 0, round_id=current)
        assert reg.count(READY) == 0

    def test_reset_limit(self):
        driver, _, reg = self._registry(2, reset_limit=1)
        reg.record_failure("a", 0)
        reg.record_ready("a", 1)
        assert driver.limit_exceeded
        assert driver.stopped


# ---------------------------------------------------------------------------
# ElasticDriver state machine (mock workers)
# ---------------------------------------------------------------------------
def _idle_worker_fn(stop_events):
    """create_worker_fn whose processes live until their stop event fires."""
    def create(slot):
        ev = threading.Event()
        stop_events[(slot.hostname, slot.local_rank)] = ev
        ev.wait(timeout=30)
        return 0
    return create


class TestElasticDriver:
    def test_initial_round_assignments(self):
        disc = FixedHostDiscovery(OrderedDict(a=2, b=2))
        driver = ElasticDriver(disc, min_np=4, timeout=5)
        stops = {}
        driver.start(4, _idle_worker_fn(stops))
        try:
            got = {}
            for host, slot in [("a", 0), ("a", 1), ("b", 0), ("b", 1)]:
                got[(host, slot)] = driver.get_assignment(host, slot, 0)
            ranks = sorted(a["rank"] for a in got.values())
            assert ranks == [0, 1, 2, 3]
            assert all(a["size"] == 4 for a in got.values())
            assert all(a["epoch"] == 1 for a in got.values())
            assert got[("a", 0)]["cross_size"] == 2
            assert got[("a", 0)]["local_size"] == 2
        finally:
            driver.stop()
            for ev in stops.values():
                ev.set()

    def test_host_added_new_round_preserves_ranks(self):
        disc = SequenceDiscovery({"a": 2}, {"a": 2, "b": 2})
        driver = ElasticDriver(disc, min_np=2, max_np=4, timeout=5)
        stops = {}
        driver.start(2, _idle_worker_fn(stops))
        try:
            first = {(h, s): driver.get_assignment(h, s, 0)
                     for h, s in [("a", 0), ("a", 1)]}
            assert first[("a", 0)]["rank"] == 0
            assert first[("a", 1)]["rank"] == 1

            # Discovery thread picks up host b; existing workers request the
            # next epoch (their READY records), and the new round forms once
            # both report.
            results = {}

            def request(h, s):
                results[(h, s)] = driver.get_assignment(h, s, 2)

            threads = [threading.Thread(target=request, args=hs)
                       for hs in [("a", 0), ("a", 1)]]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert all(not t.is_alive() for t in threads)
            assert results[("a", 0)]["rank"] == 0
            assert results[("a", 0)]["size"] == 4
            # New host's slots were spawned and can fetch the same epoch.
            b0 = driver.get_assignment("b", 0, 2)
            assert b0["size"] == 4
            assert b0["epoch"] == results[("a", 0)]["epoch"]
        finally:
            driver.stop()
            for ev in stops.values():
                ev.set()

    def test_worker_failure_blacklists_host_and_reforms(self):
        disc = FixedHostDiscovery(OrderedDict(a=2, b=2))
        driver = ElasticDriver(disc, min_np=2, max_np=4, timeout=5)
        stops = {}
        fail_b = threading.Event()

        def create(slot):
            if slot.hostname == "b":
                fail_b.wait(timeout=30)
                return 1          # both b workers die
            ev = threading.Event()
            stops[(slot.hostname, slot.local_rank)] = ev
            ev.wait(timeout=30)
            return 0

        driver.start(4, create)
        try:
            assert driver.get_assignment("a", 0, 0)["size"] == 4
            fail_b.set()
            # Survivors request the next epoch; with b blacklisted the new
            # round has only a's two slots.
            results = {}

            def request(h, s):
                results[(h, s)] = driver.get_assignment(h, s, 2)

            threads = [threading.Thread(target=request, args=hs)
                       for hs in [("a", 0), ("a", 1)]]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert all(not t.is_alive() for t in threads)
            assert results[("a", 0)]["size"] == 2
            assert results[("a", 0)]["rank"] == 0
            assert results[("a", 1)]["rank"] == 1
        finally:
            driver.stop()
            for ev in stops.values():
                ev.set()

    def test_dropped_slot_gets_none(self):
        disc = SequenceDiscovery({"a": 1, "b": 1}, {"a": 1})
        driver = ElasticDriver(disc, min_np=1, max_np=2, timeout=5)
        stops = {}
        driver.start(2, _idle_worker_fn(stops))
        try:
            assert driver.get_assignment("b", 0, 0)["size"] == 2
            results = {}

            def request(h, s):
                results[(h, s)] = driver.get_assignment(h, s, 2)

            threads = [threading.Thread(target=request, args=hs)
                       for hs in [("a", 0), ("b", 0)]]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert all(not t.is_alive() for t in threads)
            assert results[("a", 0)]["size"] == 1
            assert results[("b", 0)] is None   # b left the world
        finally:
            driver.stop()
            for ev in stops.values():
                ev.set()

    def test_all_success_finishes_job(self):
        disc = FixedHostDiscovery(OrderedDict(a=2))
        driver = ElasticDriver(disc, min_np=2, timeout=5)

        def create(slot):
            driver.get_assignment(slot.hostname, slot.local_rank, 0)
            return 0

        driver.start(2, create)
        assert driver.join(timeout=10)
        assert driver.finished()
        results = driver.get_results()
        assert all(code == 0 for code, _ in results.values())


# ---------------------------------------------------------------------------
# ElasticSampler
# ---------------------------------------------------------------------------
class TestElasticSampler:
    def test_partitions_evenly(self):
        data = list(range(10))
        s = ElasticSampler(data, shuffle=False)
        assert sorted(s.indices) == data

    def test_reshard_after_processing(self):
        data = list(range(8))
        s = ElasticSampler(data, shuffle=False)
        s.record_indices([0, 1, 2])
        s.reset()
        assert set(s.indices) == {3, 4, 5, 6, 7}
        # Next epoch restores the full dataset.
        s.set_epoch(1)
        assert sorted(set(s.indices)) == data

    def test_state_roundtrip(self):
        s = ElasticSampler(list(range(6)), shuffle=True, seed=3)
        s.record_indices([1, 5])
        s.reset()
        state = s.state_dict()
        s2 = ElasticSampler(list(range(6)), shuffle=True, seed=3)
        s2.load_state_dict(state)
        assert set(s2.indices) == set(s.indices)
        assert s2.processed_indices == {1, 5}


# ---------------------------------------------------------------------------
# State commit/restore (single process, no driver)
# ---------------------------------------------------------------------------
class TestStates:
    def test_object_state_commit_restore(self):
        import horovod_tpu as hvd
        from horovod_tpu.elastic import ObjectState
        hvd.init()
        try:
            state = ObjectState(epoch=0, batch=0)
            state.epoch = 5
            state.commit()
            state.epoch = 9
            state.restore()
            assert state.epoch == 5
            state.sync()     # size-1 world: round-trips through broadcast
            assert state.epoch == 5
        finally:
            hvd.shutdown()

    def test_array_state_commit_restore_sync(self):
        import jax.numpy as jnp
        import horovod_tpu as hvd
        from horovod_tpu.elastic import ArrayState
        hvd.init()
        try:
            params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
            state = ArrayState(trees={"params": params}, epoch=1)
            state.commit()
            state.set_tree("params",
                           {"w": jnp.full((4, 4), 7.0),
                            "b": jnp.full((4,), 7.0)})
            state.restore()
            np.testing.assert_allclose(
                np.asarray(state.tree("params")["w"]), np.ones((4, 4)))
            state.sync()
            np.testing.assert_allclose(
                np.asarray(state.tree("params")["b"]), np.zeros((4,)))
            assert state.epoch == 1
        finally:
            hvd.shutdown()
