"""Shared resource-census helper for tests (ISSUE 13 satellite).

Before this module, thread-census assertions were scattered ad hoc
(test_telemetry's off-mode no-op contract, test_resilience's
monitor start/stop, test_trace's writer-thread checks, the mp_worker
resilience_off battery) — each with its own ``threading.enumerate()``
dance and none covering fds or sockets.  Everything funnels through
here now, on top of the product census (analysis/hvdlife/census.py)
so tests and the runtime witness measure with ONE ruler.
"""
from __future__ import annotations

import threading
import time

from horovod_tpu.analysis.hvdlife.census import (census_diff,  # noqa: F401
                                                 take_census)


def thread_names() -> set:
    """Live thread names, raw (the historical assertion surface)."""
    return {t.name for t in threading.enumerate() if t.is_alive()}


def new_threads(before: set) -> set:
    """Threads alive now that were not in ``before``."""
    return thread_names() - set(before)


def assert_no_new_threads(before: set, allow=frozenset(),
                          context: str = "") -> None:
    """Every thread added since ``before`` must be in ``allow``."""
    extra = new_threads(before) - set(allow)
    assert not extra, (f"unexpected surviving threads "
                       f"{sorted(extra)}"
                       + (f" ({context})" if context else ""))


def assert_thread_absent(substring: str) -> None:
    names = thread_names()
    assert not any(substring in n for n in names), \
        f"thread matching {substring!r} alive: {sorted(names)}"


def snapshot(label: str = "") -> dict:
    """Full census (threads normalized + fds/sockets/shm), the
    baseline-equality surface of the elastic batteries."""
    return take_census(label)


def fd_count() -> int:
    return take_census()["fds"]


def open_sockets() -> int:
    return take_census()["sockets"]


def stable_snapshot(label: str = "", attempts: int = 25,
                    delay: float = 0.08) -> dict:
    """A census confirmed by a second, identical sample one delay
    later — a baseline that happened to catch a transient KV-poll
    socket would poison every later comparison."""
    prev = take_census(label)
    for _ in range(attempts):
        time.sleep(delay)
        now = take_census(label)
        if census_diff(prev, now) == []:
            return now
        prev = now
    return prev


def settle_census(baseline: dict, expect=(), attempts: int = 25,
                  delay: float = 0.08, label: str = "",
                  context: str = "") -> dict:
    """Census with transient tolerance: the statesync watcher and the
    heartbeat monitor open a KV HTTP socket for ~1 ms per poll, so a
    single snapshot can flicker by a socket or two.  Retry until the
    diff against ``baseline`` equals ``expect`` exactly — sound
    because a REAL leak never disappears between attempts — and return
    the settled census.  Raises with the last diff otherwise."""
    last: list | None = None
    for _ in range(attempts):
        now = take_census(label)
        diff = census_diff(baseline, now)
        if diff == list(expect):
            return now
        last = diff
        time.sleep(delay)
    from horovod_tpu.analysis.hvdlife.census import socket_details
    raise AssertionError(
        f"census never settled to {list(expect)!r}"
        + (f" ({context})" if context else "")
        + "; last diff:\n  " + "\n  ".join(last or ["<none>"])
        + "\nlive sockets:\n  " + "\n  ".join(socket_details()))


def assert_census_baseline(baseline: dict, now: dict | None = None,
                           context: str = "") -> None:
    """The grow-shrink acceptance check: the census must have returned
    to its baseline shape (threads by normalized name, sockets, shm
    fds and mappings)."""
    now = now if now is not None else take_census("now")
    problems = census_diff(baseline, now)
    assert not problems, (f"census drifted from baseline"
                          + (f" ({context})" if context else "")
                          + ":\n  " + "\n  ".join(problems))
