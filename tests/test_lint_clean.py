"""hvdlint gate: the tree itself must satisfy the symmetric-collective
contract, and every seeded violation fixture must be detected.

This is the CI half of the analysis subsystem (ISSUE 2 acceptance): new
rank-asymmetric collective usage anywhere under horovod_tpu/ fails this
test at review time instead of hanging a pod at run time.
"""
import importlib.util
import json
import os
import subprocess
import sys

import pytest

from horovod_tpu.analysis.lint import (COLLECTIVE_NAMES, LintConfig,
                                       lint_paths, lint_source, main)
from horovod_tpu.analysis.rules import RULES, parse_suppressions

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TREE = os.path.join(REPO, "horovod_tpu")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")


def _slugs(violations):
    return [v.rule.slug for v in violations]


# --- the gate ---------------------------------------------------------------
def test_horovod_tpu_tree_is_clean():
    violations = lint_paths([TREE])
    assert violations == [], "\n".join(v.text() for v in violations)


def test_horovod_tpu_tree_is_san_clean():
    """ISSUE 8 gate: the hvdsan whole-program concurrency rules
    (HVD501-505) run over the same parse (--san) and report zero
    unsuppressed errors on the tree."""
    from horovod_tpu.analysis.lint import lint_paths_timed
    violations, findings, stats = lint_paths_timed([TREE], san=True)
    assert violations == [], "\n".join(v.text() for v in violations)
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(f.text() for f in errors)
    assert stats["files"] > 50 and stats["wall_ms"] > 0.0


def test_gate_catches_new_violation_in_tree_context():
    """The gate actually bites: a rank-gated collective added to any
    module under horovod_tpu/ would fail test_horovod_tpu_tree_is_clean."""
    bad = ("import horovod_tpu as hvd\n"
           "def f(t):\n"
           "    if hvd.rank() == 0:\n"
           "        hvd.allreduce(t, name='x')\n")
    violations = lint_source(bad, os.path.join(TREE, "hypothetical.py"))
    assert _slugs(violations) == ["rank-gated-collective"]


# --- seeded fixtures: every rule detected, zero false positives -------------
def test_fixture_rank_gated_collective():
    out = lint_paths([os.path.join(FIXTURES, "rank_gated.py")])
    assert _slugs(out) == ["rank-gated-collective"] * 3
    assert {v.line for v in out} == {12, 17, 22}


def test_fixture_rank_gated_early_return():
    out = lint_paths([os.path.join(FIXTURES, "early_return.py")])
    assert _slugs(out) == ["rank-gated-early-return"] * 2


def test_fixture_barrier_tags():
    out = lint_paths([os.path.join(FIXTURES, "barrier_tags.py")])
    assert _slugs(out) == ["duplicate-barrier-tag",
                           "dynamic-barrier-tag", "dynamic-barrier-tag"]
    dup = out[0]
    assert "'checkpoint'" in dup.message and ":7" in dup.message


def test_fixture_lock_held_collective():
    out = lint_paths([os.path.join(FIXTURES, "lock_held.py")])
    assert _slugs(out) == ["collective-under-lock"] * 2


def test_fixture_shared_state_write():
    out = lint_paths([os.path.join(FIXTURES, "state_write.py")])
    assert _slugs(out) == ["shared-state-write"] * 2


def test_fixture_hot_io():
    """HVD1002: blocking I/O inside dispatch/backend hot-path functions
    (ISSUE 4 satellite); the non-hot helper stays clean."""
    out = lint_paths([os.path.join(FIXTURES, "hot_io.py")])
    assert _slugs(out) == ["blocking-io-in-hot-path"] * 3
    assert {"print", "open", "sendall"} == {
        v.message.split("'")[1] for v in out}


def test_fixture_unbounded_wait():
    """HVD1003: recv/join/wait/urlopen without a timeout/deadline in a
    transport/backend module (ISSUE 5 satellite); bounded calls,
    str/os.path join and a justified suppression stay clean."""
    out = lint_paths([os.path.join(FIXTURES, "backend",
                                   "unbounded_wait.py")])
    assert _slugs(out) == ["unbounded-blocking-wait"] * 5
    assert {"recv", "recv_into", "join", "wait", "urlopen"} == {
        v.message.split("'")[1] for v in out}


def test_unbounded_wait_scope_is_transport_modules():
    """The rule bites in backend/, common/tcp_transport.py and
    runner/network.py — and nowhere else (formation/CLI code may block
    on user-facing timeouts of its own)."""
    src = "def f(mesh):\n    return mesh.recv(0)\n"
    assert _slugs(lint_source(src, "horovod_tpu/backend/x.py")) == \
        ["unbounded-blocking-wait"]
    assert _slugs(lint_source(src, "horovod_tpu/common/tcp_transport.py")) \
        == ["unbounded-blocking-wait"]
    assert _slugs(lint_source(src, "horovod_tpu/runner/network.py")) == \
        ["unbounded-blocking-wait"]
    assert lint_source(src, "horovod_tpu/runner/launcher.py") == []
    assert lint_source(src, "horovod_tpu/core.py") == []


def test_fixture_unbounded_queue_serving():
    """HVD1006: Queue() without maxsize, SimpleQueue, and blocking
    put/get without a timeout in serving/ modules (ISSUE 9 satellite);
    bounded ctors, deadline-bounded/non-blocking handoffs and dict/knob
    .get() stay clean."""
    out = lint_paths([os.path.join(FIXTURES, "serving",
                                   "unbounded_queue.py")])
    assert _slugs(out) == ["unbounded-queue-in-serving"] * 4
    assert {v.line for v in out} == {7, 11, 15, 19}


def test_unbounded_queue_scope_is_serving():
    """The rule bites only in serving/ modules — the runner/transport
    layers have their own wait discipline (HVD1003)."""
    src = "def f(q):\n    return q.get()\n"
    assert _slugs(lint_source(src, "horovod_tpu/serving/x.py")) == \
        ["unbounded-queue-in-serving"]
    assert lint_source(src, "horovod_tpu/runner/x.py") == []
    assert lint_source(src, "horovod_tpu/core.py") == []
    # Config-knob constants are not queues.
    knob = "def f():\n    return SERVE_QUEUE_DEPTH.get()\n"
    assert lint_source(knob, "horovod_tpu/serving/x.py") == []


def test_fixture_unbalanced_span():
    """HVD1005: activity_start in backend/ without a finally-guarded
    activity_end (ISSUE 7 satellite); the guarded shapes — start inside
    a try/finally, start immediately followed by one, the
    conditional-start idiom, the forwarding helper, a justified
    suppression — stay clean."""
    out = lint_paths([os.path.join(FIXTURES, "backend",
                                   "unbalanced_span.py")])
    assert _slugs(out) == ["unbalanced-span"] * 3
    assert {v.line for v in out} == {8, 15, 24}


def test_unbalanced_span_scope_is_backend():
    """The rule bites only in backend/ modules — core's op spans close
    in the dispatch epilogue, outside any single lexical scope."""
    src = ("def allreduce(self, entries, buf):\n"
           "    self._act_start(entries, 'X_ALLREDUCE')\n"
           "    return buf.sum()\n")
    assert _slugs(lint_source(src, "horovod_tpu/backend/x.py")) == \
        ["unbalanced-span"]
    assert lint_source(src, "horovod_tpu/core.py") == []
    # start inside a guarded try is the other accepted shape
    good = ("def allreduce(self, entries, buf):\n"
            "    try:\n"
            "        self._act_start(entries, 'X_ALLREDUCE')\n"
            "        return buf.sum()\n"
            "    finally:\n"
            "        self._act_end(entries)\n")
    assert lint_source(good, "horovod_tpu/backend/x.py") == []


def test_telemetry_dir_blocking_io_needs_justification():
    """Any function in a telemetry/ module must justify blocking I/O —
    the tree's single justified suppression (the exporter's shutdown
    dump) is covered by test_horovod_tpu_tree_is_clean."""
    src = ("def serve(path):\n"
           "    with open(path) as f:\n"
           "        return f.read()\n")
    out = lint_source(src, "horovod_tpu/telemetry/fake.py")
    assert _slugs(out) == ["blocking-io-in-hot-path"]
    # Same code outside telemetry/ and outside hot functions: clean.
    assert lint_source(src, "horovod_tpu/runner/fake.py") == []


def test_fixture_clean_has_zero_false_positives():
    out = lint_paths([os.path.join(FIXTURES, "clean.py")])
    assert out == [], "\n".join(v.text() for v in out)


def test_all_fixtures_detected_together():
    """Cross-file duplicate-tag state must survive a whole-directory walk
    and the full seeded set must surface (ISSUE acceptance list)."""
    out = lint_paths([FIXTURES])
    found = set(_slugs(out))
    assert {"rank-gated-collective", "rank-gated-early-return",
            "duplicate-barrier-tag", "dynamic-barrier-tag",
            "collective-under-lock", "shared-state-write"} <= found


# --- suppression machinery --------------------------------------------------
def test_suppression_requires_justification():
    src = ("import horovod_tpu as hvd\n"
           "def f(t, rank):\n"
           "    if rank == 0:\n"
           "        hvd.allreduce(t)  # hvdlint: disable=rank-gated-collective\n")
    out = lint_source(src, "x.py")
    assert _slugs(out) == ["bare-suppression"]


def test_justified_suppression_is_silent():
    src = ("import horovod_tpu as hvd\n"
           "def f(t, rank):\n"
           "    if rank == 0:\n"
           "        hvd.allreduce(t)  # hvdlint: disable=HVD101 -- "
           "single-rank tool, never negotiates\n")
    assert lint_source(src, "x.py") == []


def test_file_wide_suppression():
    src = ("# hvdlint: disable-file=rank-gated-collective -- "
           "generated file, reviewed by hand\n"
           "import horovod_tpu as hvd\n"
           "def f(t, rank):\n"
           "    if rank == 0:\n"
           "        hvd.allreduce(t)\n")
    assert lint_source(src, "x.py") == []


def test_parse_suppressions_both_forms():
    sup = parse_suppressions(
        "x = 1  # hvdlint: disable=HVD101,rank-gated-early-return -- why\n")
    assert sup.by_line[1] == {"HVD101", "rank-gated-early-return"}
    assert sup.bare == []


# --- CLI --------------------------------------------------------------------
def test_cli_json_format_and_exit_codes(capsys):
    rc = main([os.path.join(FIXTURES, "rank_gated.py"),
               "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert all(p["rule"] == "HVD101" for p in payload["violations"])
    # ISSUE 8 satellite: the JSON report carries the gate's wall time.
    assert payload["wall_ms"] > 0.0 and payload["files"] == 1
    rc = main([os.path.join(FIXTURES, "clean.py")])
    assert rc == 0


def test_cli_sarif_format(capsys):
    """--sarif: findings annotate PRs (SARIF 2.1.0, one result per
    violation with rule metadata)."""
    rc = main([os.path.join(FIXTURES, "rank_gated.py"),
               "--format", "sarif"])
    assert rc == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "hvdlint"
    assert len(run["results"]) == 3
    assert all(r["ruleId"] == "HVD101" and r["level"] == "error"
               for r in run["results"])
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == {"HVD101"}


def test_cli_changed_only_smoke(capsys):
    """--changed-only scopes the walk to git-changed files; on an
    untouched fixture dir it lints at most the changed subset and must
    not crash (falls back to the full walk without git)."""
    rc = main([FIXTURES, "--changed-only", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc in (0, 1)
    assert payload["files"] <= len(
        [f for f in os.listdir(FIXTURES) if f.endswith(".py")]) + len(
        [f for root, _, fs in os.walk(FIXTURES) for f in fs])


def test_changed_only_missing_diff_base_falls_back_with_warning(
        capsys):
    """ISSUE 11 satellite: an unusable diff base must degrade to the
    full-tree scan with a STRUCTURED warning — never a crash, never an
    under-checked gate."""
    rc = main([os.path.join(FIXTURES, "clean.py"), "--changed-only",
               "--diff-base", "no-such-ref-xyzzy",
               "--format", "json"])
    captured = capsys.readouterr()
    payload = json.loads(captured.out)
    assert rc == 0
    assert payload["files"] == 1               # full fallback walk ran
    assert payload["warnings"], payload
    assert "no-such-ref-xyzzy" in payload["warnings"][0]
    assert "hvdlint: warning:" in captured.err


def test_changed_only_without_git_falls_back_with_warning(
        capsys, monkeypatch, tmp_path):
    """git unavailable (empty PATH) -> (None, reason) from
    changed_py_files, full walk, structured warning in the JSON."""
    from horovod_tpu.analysis.lint import changed_py_files
    monkeypatch.setenv("PATH", str(tmp_path))   # no git anywhere
    files, warning = changed_py_files([FIXTURES])
    assert files is None
    assert "git" in warning and "full-tree" in warning
    rc = main([os.path.join(FIXTURES, "clean.py"), "--changed-only",
               "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["files"] == 1
    assert any("full-tree" in w for w in payload["warnings"])


def test_changed_only_follows_renames(tmp_path, monkeypatch):
    """A staged rename is linted at its NEW path (git status 'R old ->
    new'; previously --no-renames hid the file entirely)."""
    from horovod_tpu.analysis.lint import changed_py_files
    repo = tmp_path / "r"
    repo.mkdir()

    def git(*argv):
        subprocess.run(["git", "-c", "user.email=t@t",
                        "-c", "user.name=t", *argv],
                       cwd=repo, check=True, capture_output=True)

    git("init", "-q")
    (repo / "old_name.py").write_text("x = 1\n")
    git("add", "old_name.py")
    git("commit", "-qm", "seed")
    git("mv", "old_name.py", "new_name.py")
    monkeypatch.chdir(repo)
    files, warning = changed_py_files(["."])
    assert warning is None
    assert files == ["new_name.py"]
    # --diff-base vs the seed commit reports the rename's new path too.
    files, warning = changed_py_files(["."], diff_base="HEAD")
    assert warning is None and "new_name.py" in files


# --- ISSUE 11: the hvdmc spec-conformance gate -------------------------------
def test_tree_spec_conformance_check_tree_gate():
    """`python -m horovod_tpu.analysis.mc --check-tree` is the CI gate:
    the tree at head is spec-clean, and the JSON shape matches the
    lint/san emitters (list of rule-stamped findings)."""
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.mc",
         "--check-tree", "--format", "json"],
        capture_output=True, text=True, cwd=REPO, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["conformance"] == []
    assert payload["wall_ms"] > 0


def test_san_driver_includes_spec_conformance():
    """HVD506 rides `lint --san` exactly like HVD505: a tree-context
    drift (an unclaimed frame verb) surfaces through lint_paths_timed
    with san=True."""
    from horovod_tpu.analysis.hvdmc.conformance import check_tree
    assert check_tree([TREE]) == []
    assert "HVD506" in RULES and \
        RULES["HVD506"].slug == "spec-conformance"


def test_cli_san_flag_runs_hvdsan(capsys):
    """--san rides the same parse: the seeded inversion fixture yields
    an HVD501 finding through the lint CLI."""
    rc = main([os.path.join(FIXTURES, "san", "inversion_cycle.py"),
               "--san", "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["violations"] == []
    assert [f["rule"] for f in payload["san"]] == ["HVD501"]


def test_cli_select_and_ignore(capsys):
    rc = main([FIXTURES, "--select", "duplicate-barrier-tag"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "HVD201" in out and "HVD101" not in out
    rc = main([os.path.join(FIXTURES, "barrier_tags.py"),
               "--ignore", "HVD201,HVD202"])
    assert rc == 0


def test_cli_module_entrypoint():
    """`python -m horovod_tpu.analysis.lint` is the documented interface."""
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.lint", TREE],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_rule_registry_is_coherent():
    ids = {r.id for r in RULES.values()}
    slugs = {r.slug for r in RULES.values()}
    assert len(ids) == len(slugs)          # bijective id<->slug
    for key, rule in RULES.items():
        assert key in (rule.id, rule.slug)
    assert "kv_barrier" in COLLECTIVE_NAMES


# --- ruff rides along when installed (pyproject [tool.ruff]) ----------------
@pytest.mark.skipif(importlib.util.find_spec("ruff") is None,
                    reason="ruff not installed (optional [lint] extra)")
def test_ruff_clean():
    proc = subprocess.run(
        [sys.executable, "-m", "ruff", "check", "horovod_tpu"],
        capture_output=True, text=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# --- ISSUE 12: suppression statement-range anchoring -------------------------
def test_suppression_covers_multiline_statement():
    """A suppression on the CLOSING line of a multi-line statement
    covers the violation anchored at its first line (previously it
    anchored to one physical line and silently failed)."""
    gated = ("import horovod_tpu as hvd\n"
             "def f(t, rank):\n"
             "    if rank == 0:\n"
             "        hvd.allreduce(\n"
             "            t,\n"
             "            name='x')%s\n")
    assert _slugs(lint_source(gated % "", "x.py")) == \
        ["rank-gated-collective"]
    assert lint_source(
        gated % "  # hvdlint: disable=HVD101 -- tool-only path",
        "x.py") == []


def test_suppression_on_def_covers_decorators():
    """A suppression on the def line covers the decorator lines of the
    same statement — but a suppression inside the BODY does not blanket
    the enclosing def's decorators."""
    src = ("import horovod_tpu as hvd\n"
           "def gate(c):\n"
           "    def deco(fn):\n"
           "        return fn\n"
           "    return deco\n"
           "@gate(0 == rank and hvd.barrier())\n"
           "def f(t, rank):%s\n"
           "    x = 1%s\n"
           "    return t\n")
    assert _slugs(lint_source(src % ("", ""), "x.py")) == \
        ["rank-gated-collective"]
    assert lint_source(
        src % ("  # hvdlint: disable=HVD101 -- reviewed decorator", ""),
        "x.py") == []
    # body-line suppression must NOT cover the decorator
    assert _slugs(lint_source(
        src % ("", "  # hvdlint: disable=HVD101 -- wrong anchor"),
        "x.py")) == ["rank-gated-collective"]


def test_suppression_span_regression_fixture_clean():
    out = lint_paths([os.path.join(FIXTURES, "suppression_span.py")])
    assert out == [], "\n".join(v.text() for v in out)


# --- ISSUE 12: the hvdflow gates --------------------------------------------
def test_horovod_tpu_tree_is_flow_clean():
    """ISSUE 12 acceptance: zero unsuppressed HVD601-604 on the tree —
    hvdflow rides the same single-parse driver run (--flow)."""
    from horovod_tpu.analysis.lint import lint_paths_timed
    violations, findings, stats = lint_paths_timed([TREE], flow=True)
    assert violations == [], "\n".join(v.text() for v in violations)
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(f.text() for f in errors)
    assert stats["files"] > 50


def test_cli_flow_flag_and_sarif_shape(capsys):
    """--flow rides the shared driver with the shared emitters: JSON
    grows a 'flow' list, SARIF results carry the HVD6xx rule ids."""
    flow_fixture = os.path.join(FIXTURES, "flow", "divergent.py")
    rc = main([flow_fixture, "--flow", "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in payload["flow"]] == ["HVD601"] * 3
    # the direct gates are ALSO per-line HVD101s — same parse, both
    # families report, each under its own JSON key
    assert [v["rule"] for v in payload["violations"]] == ["HVD101"] * 3
    assert payload["san"] == []
    rc = main([flow_fixture, "--flow", "--format", "sarif"])
    assert rc == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    results = sarif["runs"][0]["results"]
    assert [r["ruleId"] for r in results
            if r["ruleId"] == "HVD601"] == ["HVD601"] * 3
    assert {r["id"] for r in
            sarif["runs"][0]["tool"]["driver"]["rules"]} == \
        {"HVD101", "HVD601"}


# --- ISSUE 13: the hvdlife gates --------------------------------------------
def test_horovod_tpu_tree_is_life_clean():
    """ISSUE 13 acceptance: zero unsuppressed HVD701-705 on the tree —
    hvdlife rides the same single-parse driver run (--life).  Every
    intentional process-lifetime hold lives in the reviewed
    LIFECYCLE_ALLOWED manifest (analysis/hvdlife/life.py), not in
    inline suppressions."""
    from horovod_tpu.analysis.lint import lint_paths_timed
    violations, findings, stats = lint_paths_timed([TREE], life=True)
    assert violations == [], "\n".join(v.text() for v in violations)
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(f.text() for f in errors)
    assert stats["files"] > 50


def test_cli_life_flag_and_sarif_shape(capsys):
    """--life rides the shared driver with the shared emitters: JSON
    grows a 'life' list, SARIF results carry the HVD7xx rule ids."""
    life_fixture = os.path.join(FIXTURES, "life", "unjoined_thread.py")
    rc = main([life_fixture, "--life", "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in payload["life"]] == ["HVD701"] * 3
    assert payload["violations"] == [] and payload["san"] == [] \
        and payload["flow"] == []
    rc = main([life_fixture, "--life", "--format", "sarif"])
    assert rc == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    assert {r["ruleId"] for r in sarif["runs"][0]["results"]} == \
        {"HVD701"}
    assert {r["id"] for r in
            sarif["runs"][0]["tool"]["driver"]["rules"]} == {"HVD701"}


def test_cli_life_changed_only_smoke(capsys):
    """--life composes with --changed-only (the fast CI gate shape);
    on an untouched fixture dir it must not crash and reports at most
    the changed subset."""
    rc = main([os.path.join(FIXTURES, "life"), "--life",
               "--changed-only", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc in (0, 1)
    assert payload["files"] <= len(os.listdir(
        os.path.join(FIXTURES, "life")))


# --- ISSUE 17: the hvdshard gates -------------------------------------------
def test_horovod_tpu_tree_is_shard_clean():
    """ISSUE 17 acceptance: zero unsuppressed HVD801-804 errors on the
    tree — hvdshard rides the same single-parse driver run (--shard).
    The sharding rule tables, spec literals and collective spec=
    streams the tree ships are mutually coherent."""
    from horovod_tpu.analysis.lint import lint_paths_timed
    violations, findings, stats = lint_paths_timed([TREE], shard=True)
    assert violations == [], "\n".join(v.text() for v in violations)
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(f.text() for f in errors)
    assert stats["files"] > 50


def test_cli_shard_flag_and_sarif_shape(capsys):
    """--shard rides the shared driver with the shared emitters: JSON
    grows a 'shard' list, SARIF results carry the HVD80x rule ids, and
    the other families stay in their own keys."""
    shard_fixture = os.path.join(FIXTURES, "shard", "divergent_spec.py")
    rc = main([shard_fixture, "--shard", "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in payload["shard"]] == ["HVD803"]
    # the rank-gated arms are ALSO per-line HVD101s — same parse, both
    # families report, each under its own JSON key
    assert [v["rule"] for v in payload["violations"]] == ["HVD101"] * 2
    assert payload["san"] == [] and payload["flow"] == []
    rc = main([shard_fixture, "--shard", "--format", "sarif"])
    assert rc == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    assert [r["ruleId"] for r in sarif["runs"][0]["results"]
            if r["ruleId"] == "HVD803"] == ["HVD803"]
    assert {r["id"] for r in
            sarif["runs"][0]["tool"]["driver"]["rules"]} == \
        {"HVD101", "HVD803"}


def test_cli_shard_changed_only_smoke(capsys):
    """--shard composes with --changed-only (the fast CI gate shape);
    on an untouched fixture dir it must not crash and reports at most
    the changed subset."""
    rc = main([os.path.join(FIXTURES, "shard"), "--shard",
               "--changed-only", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc in (0, 1)
    assert payload["files"] <= len(os.listdir(
        os.path.join(FIXTURES, "shard")))


# --- ISSUE 12: typed knob registry + generated docs --------------------------
def test_knobs_cli_emits_registry_table(capsys):
    rc = main(["--knobs"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.startswith("# Configuration")
    assert "| `HOROVOD_FUSION_THRESHOLD` | int |" in out
    assert "| `HOROVOD_RENDEZVOUS_EPOCH` | str |" in out


def test_configuration_md_in_sync_with_registry():
    """docs/configuration.md is GENERATED from the typed registry; CI
    asserts byte-identity so a new knob cannot land undocumented
    (regenerate: python -m horovod_tpu.analysis.lint --knobs >
    docs/configuration.md)."""
    from horovod_tpu.common.config import configuration_markdown
    path = os.path.join(REPO, "docs", "configuration.md")
    with open(path, encoding="utf-8") as f:
        on_disk = f.read()
    assert on_disk == configuration_markdown(), \
        "docs/configuration.md is stale — regenerate with " \
        "`python -m horovod_tpu.analysis.lint --knobs > " \
        "docs/configuration.md`"


def test_docs_analysis_rule_table_is_complete():
    """Generated-or-verified rule docs: every registered rule id (all
    families — hvdlint, hvdsan, hvdmc, hvdflow) has a row in
    docs/analysis.md, so a new rule cannot land undocumented."""
    from horovod_tpu.analysis.rules import undocumented_rules
    with open(os.path.join(REPO, "docs", "analysis.md"),
              encoding="utf-8") as f:
        doc = f.read()
    missing = undocumented_rules(doc)
    assert missing == [], f"rules missing from docs/analysis.md: {missing}"


def test_docs_observability_metric_table_is_complete():
    """ISSUE 19 satellite: every metric name the runtime can register
    (static AST sweep of the package — telemetry/catalog.py) has a table
    row in docs/observability.md, so a new metric cannot land
    undocumented.  Same contract as undocumented_rules above."""
    from horovod_tpu.telemetry.catalog import undocumented_metrics
    with open(os.path.join(REPO, "docs", "observability.md"),
              encoding="utf-8") as f:
        doc = f.read()
    missing = undocumented_metrics(doc)
    assert missing == [], \
        f"metrics missing from docs/observability.md: {missing}"


def test_rule_id_uniqueness_asserted_at_build():
    """The registry build raises on a duplicate id or slug — simulated
    here by replaying the build loop with a colliding rule."""
    import importlib
    from horovod_tpu.analysis import rules as rules_mod
    dup = rules_mod.Rule("HVD101", "some-new-slug", "collides by id")
    try:
        if dup.id in rules_mod.RULES:
            raise AssertionError(
                f"duplicate rule id {dup.id!r}: already registered")
    except AssertionError as exc:
        assert "duplicate rule id" in str(exc)
    else:
        raise AssertionError("collision was not detected")
    assert importlib.import_module(
        "horovod_tpu.analysis.rules") is rules_mod
