"""Wire-format round-trip tests (reference test analogue: message
serialization exercised implicitly by test/parallel/*)."""
import numpy as np
import pytest

from horovod_tpu.common.dtypes import DataType, element_size, from_any, to_numpy
from horovod_tpu.common.message import (Request, RequestList, RequestType,
                                        Response, ResponseList, ResponseType)
from horovod_tpu.common.wire import Decoder, Encoder


def test_varint_roundtrip():
    enc = Encoder()
    values = [0, 1, 127, 128, 300, 2 ** 32, 2 ** 60]
    for v in values:
        enc.uvarint(v)
    dec = Decoder(enc.getvalue())
    assert [dec.uvarint() for _ in values] == values


def test_svarint_roundtrip():
    enc = Encoder()
    values = [0, -1, 1, -64, 64, -(2 ** 40), 2 ** 40]
    for v in values:
        enc.svarint(v)
    dec = Decoder(enc.getvalue())
    assert [dec.svarint() for _ in values] == values


def test_mixed_fields():
    enc = Encoder()
    enc.string("tensor/äöü").f64(3.5).bool_(True).svarint_list([1, -2, 3]) \
       .string_list(["a", "b"]).blob(b"\x00\x01")
    dec = Decoder(enc.getvalue())
    assert dec.string() == "tensor/äöü"
    assert dec.f64() == 3.5
    assert dec.bool_() is True
    assert dec.svarint_list() == [1, -2, 3]
    assert dec.string_list() == ["a", "b"]
    assert dec.blob() == b"\x00\x01"
    assert dec.eof()


def test_request_list_roundtrip():
    reqs = [
        Request(request_rank=3, request_type=RequestType.ALLREDUCE,
                tensor_type=DataType.FLOAT32, tensor_name="grad/w1",
                tensor_shape=(4, 5), prescale_factor=0.5),
        Request(request_rank=1, request_type=RequestType.BROADCAST,
                tensor_type=DataType.INT64, tensor_name="step",
                root_rank=0, tensor_shape=()),
    ]
    rl = RequestList(requests=reqs, shutdown=True)
    decoded = RequestList.from_bytes(rl.to_bytes())
    assert decoded.shutdown is True
    assert decoded.requests == reqs


def test_response_list_roundtrip():
    resps = [
        Response(response_type=ResponseType.ALLREDUCE,
                 tensor_names=["a", "b"], devices=[0, 1],
                 tensor_sizes=[20, 12], tensor_type=DataType.BFLOAT16,
                 postscale_factor=0.25),
        Response(response_type=ResponseType.ERROR, tensor_names=["c"],
                 error_message="shape mismatch"),
    ]
    rl = ResponseList(responses=resps, tuned_fusion_threshold=1 << 20,
                      tuned_cycle_time_ms=2.5)
    decoded = ResponseList.from_bytes(rl.to_bytes())
    assert decoded.responses == resps
    assert decoded.tuned_fusion_threshold == 1 << 20
    assert decoded.tuned_cycle_time_ms == 2.5
    assert decoded.shutdown is False


def test_response_trace_id_roundtrip():
    """ISSUE 7: the coordinator-assigned (cycle, seq) trace id rides the
    Response wire like the fp_* fields; unassigned stays -1/-1."""
    resp = Response(response_type=ResponseType.ALLREDUCE,
                    tensor_names=["g"], tensor_sizes=[8],
                    trace_cycle=12345, trace_seq=7)
    rl = ResponseList(responses=[resp])
    decoded = ResponseList.from_bytes(rl.to_bytes()).responses[0]
    assert decoded.trace_cycle == 12345
    assert decoded.trace_seq == 7
    assert decoded.trace_id() == "12345.7"
    # Defaults survive the wire as "unassigned".
    empty = ResponseList.from_bytes(
        ResponseList(responses=[Response()]).to_bytes()).responses[0]
    assert (empty.trace_cycle, empty.trace_seq) == (-1, -1)
    assert empty.trace_id() is None


@pytest.mark.parametrize("dt,np_dtype", [
    (DataType.FLOAT32, np.float32),
    (DataType.FLOAT16, np.float16),
    (DataType.INT64, np.int64),
    (DataType.BOOL, np.bool_),
])
def test_dtype_table(dt, np_dtype):
    assert from_any(np.dtype(np_dtype)) == dt
    assert to_numpy(dt) == np.dtype(np_dtype)
    assert element_size(dt) == np.dtype(np_dtype).itemsize


def test_bfloat16_dtype():
    import ml_dtypes
    assert from_any(np.dtype(ml_dtypes.bfloat16)) == DataType.BFLOAT16
    assert element_size(DataType.BFLOAT16) == 2


def test_torch_dtype_mapping():
    import torch
    assert from_any(torch.float32) == DataType.FLOAT32
    assert from_any(torch.int64) == DataType.INT64
    assert from_any(torch.bfloat16) == DataType.BFLOAT16
