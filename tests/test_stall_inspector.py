"""StallInspector edge cases (common/stall_inspector.py).

The inspector is the slow-failure detector behind the fingerprint plane:
fingerprinting catches provable divergence immediately, the inspector
catches the remainder (a rank that is merely *absent*) on a timer.
"""
import contextlib
import logging
import time

import pytest

from horovod_tpu.common.logging import logger as hvd_logger
from horovod_tpu.common.response_cache import CacheCoordinator, ResponseCache
from horovod_tpu.common.stall_inspector import StallInspector


@contextlib.contextmanager
def _capture_warnings():
    """The repo logger does not propagate to pytest's caplog handler:
    attach one directly."""
    records: list[logging.LogRecord] = []

    class _Collector(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = _Collector(level=logging.WARNING)
    hvd_logger.addHandler(handler)
    old_level = hvd_logger.level
    hvd_logger.setLevel(logging.WARNING)
    try:
        yield records
    finally:
        hvd_logger.setLevel(old_level)
        hvd_logger.removeHandler(handler)


@pytest.fixture
def fast_inspector(monkeypatch):
    """Inspector with millisecond thresholds via the real env knobs."""
    monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "0.05")
    monkeypatch.setenv("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", "0.15")
    return StallInspector()


def test_disabled_mode_never_checks(monkeypatch):
    monkeypatch.setenv("HOROVOD_STALL_CHECK_DISABLE", "1")
    monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "0.0")
    insp = StallInspector()
    assert not insp.enabled
    assert not insp.should_check()
    time.sleep(0.01)
    assert not insp.should_check()
    # invalidate path is a no-op when disabled, even with stalled entries
    insp.record_cached_tensor("t0")
    insp._uncached["t0"] -= 100.0          # force "stalled for 100s"
    coordinator = CacheCoordinator(64)
    insp.invalidate_stalled_cached_tensors(coordinator, ResponseCache(64))
    assert coordinator.invalid_bits == set()
    assert not coordinator.uncached_in_queue


def test_submitted_then_removed_tensor_never_warns(fast_inspector):
    insp = fast_inspector
    insp.record_uncached_tensor("t0", rank=0)
    insp.remove_uncached_tensor("t0")       # completed before the check
    time.sleep(0.06)
    with _capture_warnings() as records:
        assert not insp.check_for_stalled_tensors(global_size=2)
    assert not any("Stalled op" in r.getMessage() for r in records)


def test_remove_unknown_tensor_is_harmless(fast_inspector):
    fast_inspector.remove_uncached_tensor("never-submitted")
    fast_inspector.remove_cached_tensor("never-submitted")


def test_warning_names_missing_ranks_and_fingerprint_hint(fast_inspector):
    insp = fast_inspector
    insp.record_uncached_tensor("grad/w", rank=0)
    insp.record_uncached_tensor("grad/w", rank=2)
    time.sleep(0.06)
    with _capture_warnings() as records:
        should_shutdown = insp.check_for_stalled_tensors(global_size=4)
    assert not should_shutdown              # warned, not yet past shutdown
    text = "\n".join(r.getMessage() for r in records)
    assert "grad/w" in text
    assert "missing ranks: 1, 3" in text
    # The warning routes operators to the analysis tooling.
    assert "HOROVOD_FINGERPRINT" in text


def test_shutdown_threshold_crossing(fast_inspector):
    insp = fast_inspector
    insp.record_uncached_tensor("t0", rank=0)
    time.sleep(0.06)
    assert not insp.check_for_stalled_tensors(global_size=2)  # warn only
    time.sleep(0.12)                        # now past 0.15s shutdown bound
    assert insp.check_for_stalled_tensors(global_size=2)


def test_shutdown_disabled_when_zero(monkeypatch):
    monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "0.01")
    monkeypatch.setenv("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", "0.0")
    insp = StallInspector()
    insp.record_uncached_tensor("t0", rank=0)
    time.sleep(0.05)
    assert not insp.check_for_stalled_tensors(global_size=2)


def test_should_check_paces_itself(fast_inspector):
    insp = fast_inspector
    assert not insp.should_check()          # just constructed
    time.sleep(0.06)
    assert insp.should_check()
    insp.check_for_stalled_tensors(global_size=2)
    assert not insp.should_check()          # timer reset by the check


def test_resubmission_keeps_first_seen_time(fast_inspector):
    """A tensor re-recorded by more ranks keeps its ORIGINAL first-seen
    time: lateness is measured from the first submission, not the last."""
    insp = fast_inspector
    insp.record_uncached_tensor("t0", rank=0)
    first, _ = insp._ready["t0"]
    time.sleep(0.02)
    insp.record_uncached_tensor("t0", rank=1)
    again, ranks = insp._ready["t0"]
    assert again == first
    assert ranks == {0, 1}


def test_invalidate_stalled_cached_tensor(monkeypatch):
    monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "0.01")
    insp = StallInspector()
    cache = ResponseCache(64)
    from horovod_tpu.common.message import (Request, RequestType, Response,
                                            ResponseType)
    req = Request(request_rank=0, request_type=RequestType.ALLREDUCE,
                  tensor_name="t0", tensor_shape=(4,))
    cache.put(Response(response_type=ResponseType.ALLREDUCE,
                       tensor_names=["t0"], tensor_sizes=[4]), req)
    insp.record_cached_tensor("t0")
    insp._uncached["t0"] -= 1.0             # stalled past the 0.01s bound
    coordinator = CacheCoordinator(64)
    insp.invalidate_stalled_cached_tensors(coordinator, cache)
    assert coordinator.uncached_in_queue    # forces renegotiation
    assert coordinator.invalid_bits == {cache.peek_cache_position("t0")}


def test_invalidate_survives_evicted_cache_entry(monkeypatch):
    """Tensor stalled locally but already evicted from the response cache
    (peek raises KeyError): the inspector must skip it, not crash the
    background loop."""
    monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", "0.01")
    insp = StallInspector()
    insp.record_cached_tensor("gone")
    insp._uncached["gone"] -= 1.0
    coordinator = CacheCoordinator(64)
    insp.invalidate_stalled_cached_tensors(coordinator, ResponseCache(64))
    assert coordinator.invalid_bits == set()
