"""Unit tests for bench.py's resumable accelerator-probe watcher.

The BENCH_r01-05 regression had two shapes: a transient rc=1 probe
crash was treated like "no accelerator" (burning a full probe interval
per crash), and the round window was wall-clock — a multi-hour tunnel
outage that also killed the bench process expired the window while
nobody was watching.  These tests drive ``_orchestrate`` with the
probe, the inner spawn, ``time.sleep`` and ``time.time`` stubbed, so
the schedule itself is under test (no jax, no subprocesses).
"""
import json
import os
import sys
import types

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402


class _Clock:
    def __init__(self, start=1000.0):
        self.now = start
        self.sleeps = []

    def time(self):
        return self.now

    def sleep(self, s):
        self.sleeps.append(s)
        self.now += s


@pytest.fixture
def clock(monkeypatch, tmp_path):
    clk = _Clock()
    monkeypatch.setattr(bench.time, "time", clk.time)
    monkeypatch.setattr(bench.time, "sleep", clk.sleep)
    monkeypatch.setenv("HOROVOD_BENCH_STATE_FILE",
                       str(tmp_path / "probe.json"))
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("HOROVOD_BENCH_PROBE_ATTEMPTS", raising=False)
    return clk


def _args():
    return types.SimpleNamespace(model="resnet50")


def test_probe_crash_is_retryable_with_capped_backoff(clock, monkeypatch,
                                                      capsys):
    """rc!=0 probe crashes retry on a 5s-doubling backoff capped at the
    probe interval — not one full interval per crash."""
    monkeypatch.setenv("HOROVOD_BENCH_WINDOW_SECONDS", "200")
    monkeypatch.setenv("HOROVOD_BENCH_PROBE_INTERVAL", "60")
    statuses = ["crash", "crash", "crash", "ok"]
    monkeypatch.setattr(bench, "_probe_backend_status",
                        lambda timeout: (statuses.pop(0), None))
    payload = {"metric": "resnet50_images_sec", "value": 1.0,
               "backend": "tpu"}
    monkeypatch.setattr(bench, "_spawn_inner",
                        lambda *a, **k: (0, dict(payload), "", False))
    emitted = []
    monkeypatch.setattr(bench, "_emit", emitted.append)
    assert bench._orchestrate(_args()) == 0
    # Backoff ladder 5, 10, 20 — NOT 60, 60, 60.
    assert clock.sleeps == [5.0, 10.0, 20.0]
    assert emitted and emitted[0]["attempts"] == 4
    # Success clears the checkpoint: the next round starts fresh.
    assert not os.path.exists(bench._probe_state_path())


def test_absent_probe_reprobes_immediately(clock, monkeypatch):
    """A single timed-out probe already burned its full probe budget of
    wall time — the watcher re-probes IMMEDIATELY to reach the 2-strike
    verdict fast (ISSUE 18), instead of sleeping a full interval; a
    recovery on the second probe resets the strike count."""
    monkeypatch.setenv("HOROVOD_BENCH_WINDOW_SECONDS", "200")
    monkeypatch.setenv("HOROVOD_BENCH_PROBE_INTERVAL", "60")
    statuses = ["absent", "ok"]
    monkeypatch.setattr(bench, "_probe_backend_status",
                        lambda timeout: (statuses.pop(0), None))
    monkeypatch.setattr(
        bench, "_spawn_inner",
        lambda *a, **k: (0, {"metric": "resnet50_images_sec",
                             "value": 1.0, "backend": "tpu"}, "", False))
    emitted = []
    monkeypatch.setattr(bench, "_emit", emitted.append)
    assert bench._orchestrate(_args()) == 0
    assert clock.sleeps == [0.0]
    assert emitted and emitted[0]["backend"] == "tpu"


def test_two_absent_probes_are_definitive(clock, monkeypatch):
    """TWO consecutive timed-out probes mean the accelerator is absent,
    not resetting: the watcher goes straight to the CPU fallback instead
    of re-timing-out across the whole round window (ISSUE 18)."""
    monkeypatch.setenv("HOROVOD_BENCH_WINDOW_SECONDS", "3600")
    monkeypatch.setenv("HOROVOD_BENCH_PROBE_INTERVAL", "60")
    probes = []
    monkeypatch.setattr(
        bench, "_probe_backend_status",
        lambda timeout: (probes.append(timeout), ("absent", None))[1])
    calls = []

    def _inner(args, extra_env, timeout):
        calls.append(dict(extra_env))
        return (0, {"metric": "resnet50_images_sec", "value": 0.5},
                "", False)

    monkeypatch.setattr(bench, "_spawn_inner", _inner)
    emitted = []
    monkeypatch.setattr(bench, "_emit", emitted.append)
    assert bench._orchestrate(_args()) == 0
    assert len(probes) == 2                  # the verdict, no ladder
    assert calls == [{"JAX_PLATFORMS": "cpu"}]
    assert emitted[0]["backend"] == "cpu-fallback"
    # The round's un-spent budget is checkpointed: a re-run RESUMES the
    # same window (the tunnel may come back mid-round).
    assert bench._load_probe_state(3600.0)["attempts"] == 2


def test_window_survives_multi_hour_process_death_gap(clock, monkeypatch):
    """A resumed watcher whose state file is hours old (the outage
    killed the driver too) continues the SAME round with its budget
    nearly intact: the gap charges at most ~one sleep of active time,
    and the next probe can still record a real payload."""
    monkeypatch.setenv("HOROVOD_BENCH_WINDOW_SECONDS", "3600")
    monkeypatch.setenv("HOROVOD_BENCH_PROBE_INTERVAL", "60")
    # A checkpoint from 5 wall-clock hours ago, 300s of budget spent.
    bench._save_probe_state({"window_start": clock.now - 5 * 3600.0,
                             "attempts": 7, "active_s": 300.0,
                             "last_seen": clock.now - 5 * 3600.0})
    monkeypatch.setattr(bench, "_probe_backend_status",
                        lambda timeout: ("ok", None))
    monkeypatch.setattr(
        bench, "_spawn_inner",
        lambda *a, **k: (0, {"metric": "resnet50_images_sec",
                             "value": 1.0, "backend": "tpu"}, "", False))
    emitted = []
    monkeypatch.setattr(bench, "_emit", emitted.append)
    assert bench._orchestrate(_args()) == 0
    # The same window resumed (attempts continue, not restart) and the
    # 5 h gap did not exhaust the 1 h budget.
    assert emitted and emitted[0]["attempts"] == 8
    assert emitted[0]["probe_active_s"] < 3600.0


def test_spent_budget_starts_next_round_fresh(clock, monkeypatch):
    monkeypatch.setenv("HOROVOD_BENCH_WINDOW_SECONDS", "600")
    bench._save_probe_state({"window_start": clock.now - 9999.0,
                             "attempts": 40, "active_s": 600.0,
                             "last_seen": clock.now - 9999.0})
    state = bench._load_probe_state(600.0)
    assert state["attempts"] == 0
    assert state["active_s"] == 0.0


def test_old_format_state_resumes_without_active_time(clock, monkeypatch):
    """Pre-active-time checkpoints ({window_start, attempts}) load with
    a zero spent budget instead of being discarded."""
    with open(bench._probe_state_path(), "w") as f:
        json.dump({"window_start": clock.now - 50.0, "attempts": 3}, f)
    state = bench._load_probe_state(3600.0)
    assert state["attempts"] == 3
    assert state["active_s"] == 0.0
    assert state["last_seen"] == clock.now - 50.0


def test_exhausted_budget_falls_back_to_cpu_once(clock, monkeypatch):
    """Transient probe crashes stay retryable (no 2-strike verdict), so
    a tunnel that crash-loops for the whole round window exhausts the
    budget on the backoff ladder and falls back to CPU exactly once."""
    monkeypatch.setenv("HOROVOD_BENCH_WINDOW_SECONDS", "100")
    monkeypatch.setenv("HOROVOD_BENCH_PROBE_INTERVAL", "60")
    monkeypatch.setattr(bench, "_probe_backend_status",
                        lambda timeout: ("crash", None))
    calls = []

    def _inner(args, extra_env, timeout):
        calls.append(dict(extra_env))
        return (0, {"metric": "resnet50_images_sec", "value": 0.5},
                "", False)

    monkeypatch.setattr(bench, "_spawn_inner", _inner)
    emitted = []
    monkeypatch.setattr(bench, "_emit", emitted.append)
    assert bench._orchestrate(_args()) == 0
    assert calls == [{"JAX_PLATFORMS": "cpu"}]
    assert emitted[0]["backend"] == "cpu-fallback"
    # The spent window is checkpointed: the NEXT invocation of
    # _load_probe_state starts round N+1 fresh.
    assert bench._load_probe_state(100.0)["attempts"] == 0
