"""Runtime collective-fingerprint tests (analysis/fingerprint.py).

The acceptance behavior (ISSUE 2): in a 2-rank world whose ranks submit
divergent collectives, every rank receives a structured Response.ERROR
naming the first divergent op — instead of the silent stall the
reference runtime exhibits (the stall inspector would only WARN after
60s, and the kv_barrier timeout after 300s).
"""
import numpy as np
import pytest

from horovod_tpu.analysis.fingerprint import (Divergence, FingerprintMode,
                                              FingerprintTracker, OpRecord,
                                              describe, find_divergence)
from horovod_tpu.common.dtypes import DataType
from horovod_tpu.common.message import (Request, RequestList, RequestType,
                                        ResponseType)

from util_world import InProcWorld, make_controller, run_ranks


def _req(rank, name, rtype=RequestType.ALLREDUCE, shape=(4,),
         dtype=DataType.FLOAT32, **kw):
    return Request(request_rank=rank, request_type=rtype, tensor_type=dtype,
                   tensor_name=name, tensor_shape=shape, **kw)


def _tracker(mode="cycle", window=64):
    return FingerprintTracker(mode, window)


# --- tracker unit behavior --------------------------------------------------
def test_mode_parsing_and_flags():
    assert FingerprintMode.parse("CYCLE") is FingerprintMode.CYCLE
    assert FingerprintMode.parse("bogus") is FingerprintMode.OFF
    assert not FingerprintTracker("off").enabled
    assert _tracker("cycle").enabled and not _tracker("cycle").strict
    assert _tracker("strict").enabled and _tracker("strict").strict


def test_fold_is_deterministic_and_order_sensitive():
    a, b = _tracker(), _tracker()
    for n in ("x", "y", "z"):
        a.fold(_req(0, n))
    for n in ("x", "y", "z"):
        b.fold(_req(1, n))          # request_rank is NOT part of the hash
    assert (a.seq, a.digest) == (b.seq, b.digest)

    c = _tracker()
    for n in ("x", "z", "y"):       # same ops, different order
        c.fold(_req(0, n))
    assert c.digest != a.digest


def test_fold_skips_join_and_refolds():
    t = _tracker()
    t.fold(_req(0, "__join__", rtype=RequestType.JOIN))
    assert t.seq == 0               # join is rank-asymmetric by design
    req = _req(0, "a")
    t.fold(req)
    t.fold(req)                     # re-popped cache-hit request
    assert t.seq == 1


def test_descriptor_covers_op_name_dtype_dims_codec():
    d = describe(_req(0, "g", shape=(2, 3), codec=2, codec_block_size=128))
    assert d == "ALLREDUCE|g|FLOAT32|2x3|2/128"
    # any component change changes the descriptor (and so the digest)
    assert describe(_req(0, "g", shape=(3, 2))) != d
    assert describe(_req(0, "g", shape=(2, 3), codec=1)) != d


def test_allgather_first_dim_is_rank_local_wildcard():
    """Uneven-row allgather (allgather_object payloads, the serving
    completion exchange) is the documented semantic: dim0 folds as a
    wildcard so strict mode never flags it, while trailing-dim or op
    drift still diverges."""
    a = describe(_req(0, "done", shape=(204,),
                      rtype=RequestType.ALLGATHER))
    b = describe(_req(0, "done", shape=(5,),
                      rtype=RequestType.ALLGATHER))
    assert a == b == "ALLGATHER|done|FLOAT32|*|0/0"
    assert describe(_req(0, "done", shape=(5, 2),
                         rtype=RequestType.ALLGATHER)) != a
    assert describe(_req(0, "done", shape=(204,))) != a   # ALLREDUCE


def test_window_bounds_tail():
    t = _tracker(window=4)
    for i in range(10):
        t.fold(_req(0, f"t{i}"))
    assert t.seq == 10
    assert [r.seq for r in t.snapshot()[2]] == [7, 8, 9, 10]


# --- divergence location ----------------------------------------------------
def _diverged_pair(ops0, ops1, window=64):
    a, b = _tracker(window=window), _tracker(window=window)
    for n in ops0:
        a.fold(_req(0, n))
    for n in ops1:
        b.fold(_req(1, n))
    return find_divergence([a.snapshot(), b.snapshot()])


def test_identical_streams_no_divergence():
    assert _diverged_pair(["a", "b"], ["a", "b"]) is None


def test_rank_ahead_is_not_divergence():
    # One rank legitimately ahead: consistency judged at the common head.
    assert _diverged_pair(["a", "b", "c"], ["a"]) is None


def test_first_divergent_op_is_named():
    div = _diverged_pair(["a", "b", "c"], ["a", "x", "c"])
    assert div is not None and div.exact and div.seq == 2
    assert div.tensor_names() == ["b", "x"]
    assert "op #2" in div.message()
    assert "rank 0: ALLREDUCE(b" in div.message()
    assert "rank 1: ALLREDUCE(x" in div.message()


def test_empty_streams_not_compared():
    assert _diverged_pair([], []) is None
    assert _diverged_pair(["a"], []) is None


def test_divergence_older_than_window_reported_inexact():
    ops0 = ["DIFF0"] + [f"t{i}" for i in range(20)]
    ops1 = ["DIFF1"] + [f"t{i}" for i in range(20)]
    div = _diverged_pair(ops0, ops1, window=4)
    assert div is not None and not div.exact
    assert "predates the fingerprint window" in div.message()


def test_divergence_mid_window_pinpointed():
    base = [f"t{i}" for i in range(10)]
    div = _diverged_pair(base + ["p", "q"], base + ["P", "q"], window=8)
    assert div is not None and div.exact and div.seq == 11


def test_report_once_per_tracker():
    t = _tracker()
    t.fold(_req(0, "a"))
    other = _tracker()
    other.fold(_req(1, "b"))
    triples = [t.snapshot(), other.snapshot()]
    assert t.check_gathered(triples) is not None
    assert t.check_gathered(triples) is None    # second report suppressed
    t.reset()
    assert t.check_gathered(triples) is not None


# --- wire format ------------------------------------------------------------
def test_requestlist_carries_fingerprint_over_wire():
    t = _tracker()
    for n in ("a", "b"):
        t.fold(_req(0, n))
    rl = RequestList(requests=[_req(0, "c")])
    rl.fp_seq, rl.fp_digest, tail = t.snapshot()
    rl.fp_tail_seqs = [r.seq for r in tail]
    rl.fp_tail_digests = [r.digest for r in tail]
    rl.fp_tail_descs = [r.descriptor for r in tail]
    back = RequestList.from_bytes(rl.to_bytes())
    assert (back.fp_seq, back.fp_digest) == (rl.fp_seq, rl.fp_digest)
    assert back.fp_tail_seqs == rl.fp_tail_seqs
    assert back.fp_tail_digests == rl.fp_tail_digests
    assert back.fp_tail_descs == rl.fp_tail_descs
    assert back.requests[0].tensor_name == "c"


def test_requestlist_defaults_stay_zero_when_off():
    back = RequestList.from_bytes(RequestList().to_bytes())
    assert back.fp_seq == 0 and back.fp_tail_seqs == []


# --- 2-rank world: structured error instead of a hang (acceptance) ----------
def _fingerprinted_controllers(size, mode="cycle", cache_capacity=0):
    world = InProcWorld(size)
    ctrls = [make_controller(r, size, world,
                             cache_capacity=cache_capacity)
             for r in range(size)]
    for c in ctrls:
        c.fingerprint = FingerprintTracker(mode)
    return world, ctrls


def test_two_rank_divergence_yields_structured_error():
    size = 2
    _, ctrls = _fingerprinted_controllers(size)

    def step(rank):
        ctrl = ctrls[rank]
        name = "grad/w" if rank == 0 else "grad/b"   # the seeded bug
        ctrl.tensor_queue.push_back_to_queue(_req(rank, name))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert len(rl.responses) == 1
        resp = rl.responses[0]
        assert resp.response_type == ResponseType.ERROR
        assert sorted(resp.tensor_names) == ["grad/b", "grad/w"]
        assert "op #1" in resp.error_message
        assert "grad/w" in resp.error_message
        assert "grad/b" in resp.error_message
        assert not rl.shutdown          # structured error, not a shutdown


def test_two_rank_order_divergence_detected():
    size = 2
    _, ctrls = _fingerprinted_controllers(size)

    def step(rank):
        ctrl = ctrls[rank]
        names = ("a", "b") if rank == 0 else ("b", "a")
        for n in names:
            ctrl.tensor_queue.push_back_to_queue(_req(rank, n))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        errors = [r for r in rl.responses
                  if r.response_type == ResponseType.ERROR]
        assert len(errors) == 1
        assert "op #1" in errors[0].error_message


def test_symmetric_ranks_unaffected_by_fingerprinting():
    size = 3
    _, ctrls = _fingerprinted_controllers(size, mode="strict")

    def step(rank):
        ctrl = ctrls[rank]
        ctrl.tensor_queue.push_back_to_queue(_req(rank, "t0"))
        return ctrl.compute_response_list()

    results = run_ranks(size, step)
    for rl in results:
        assert [r.response_type for r in rl.responses] == \
            [ResponseType.ALLREDUCE]


def _warm_two_tensors(size, mode):
    """Controllers with t0+t1 negotiated into every rank's cache.
    Fusion is disabled so each tensor caches as its own single-tensor
    response (fused responses are never cached as a unit)."""
    world = InProcWorld(size)
    ctrls = [make_controller(r, size, world, cache_capacity=64,
                             fusion_threshold=0) for r in range(size)]
    for c in ctrls:
        c.fingerprint = FingerprintTracker(mode)

    def warm(rank):
        ctrl = ctrls[rank]
        ctrl.tensor_queue.push_back_to_queue(_req(rank, "t0"))
        ctrl.tensor_queue.push_back_to_queue(_req(rank, "t1"))
        return ctrl.compute_response_list()

    run_ranks(size, warm)
    return world, ctrls


def _diverge_on_cached(ctrls):
    """Rank 0 submits cached t0, rank 1 submits cached t1: pure cache
    hits whose global AND simply clears both bits — NO negotiation is
    ever triggered, the classic silent stall (both ranks requeue and
    retry forever)."""
    def diverge(rank):
        ctrl = ctrls[rank]
        ctrl.tensor_queue.push_back_to_queue(
            _req(rank, "t0" if rank == 0 else "t1"))
        return ctrl.compute_response_list()

    return run_ranks(len(ctrls), diverge)


def test_strict_mode_detects_divergence_in_cache_steady_state():
    """Cache-steady-state divergence never ships a RequestList, so cycle
    mode stays blind; strict mode's forced negotiation heartbeat
    compares fingerprints every cycle and surfaces it immediately."""
    world, ctrls = _warm_two_tensors(2, "strict")
    gather_after_warm = world.gather_count

    results = _diverge_on_cached(ctrls)
    assert world.gather_count > gather_after_warm   # strict heartbeat ran
    for rl in results:
        errors = [r for r in rl.responses
                  if r.response_type == ResponseType.ERROR]
        assert errors, "strict mode must surface the divergence"
        assert sorted(errors[0].tensor_names) == ["t0", "t1"]
        assert "op #3" in errors[0].error_message


def test_cycle_mode_is_blind_in_cache_steady_state():
    """The documented blind spot that motivates strict mode: without the
    forced heartbeat no RequestList flows, so nothing is compared."""
    world, ctrls = _warm_two_tensors(2, "cycle")
    gather_after_warm = world.gather_count

    results = _diverge_on_cached(ctrls)
    assert world.gather_count == gather_after_warm  # no negotiation ran
    for rl in results:
        assert all(r.response_type != ResponseType.ERROR
                   for r in rl.responses)


def test_fingerprint_off_keeps_wire_quiet():
    size = 2
    world = InProcWorld(size)
    ctrls = [make_controller(r, size, world) for r in range(size)]

    def step(rank):
        ctrl = ctrls[rank]
        ctrl.tensor_queue.push_back_to_queue(_req(rank, "t0"))
        return ctrl.compute_response_list()

    run_ranks(size, step)
    for ctrl in ctrls:
        assert not ctrl.fingerprint.enabled
        assert ctrl.fingerprint.seq == 0
