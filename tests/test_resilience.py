"""resilience/ battery (ISSUE 5): failure detection, deadline-bounded
collectives, recovery policies, and the deterministic chaos harness.

Process-level acceptance (4-rank mp_worker batteries, each under the
hard SIGALRM guard below so a regression re-introducing a deadlock
fails FAST instead of eating the tier-1 budget):

- chaos SIGKILLs rank 2 mid-allreduce → all three survivors raise
  RanksFailedError(failed_ranks={2}) within 2x HOROVOD_FAULT_TIMEOUT
  (wall-clock bound asserted in-battery);
- delayed-send chaos blows the op deadline → HOROVOD_ON_FAILURE=retry
  succeeds with exponential backoff over rebuilt channels;
- frozen (wedged, still-heartbeating) rank → per-op deadline converts
  the survivor's wait;
- off mode: zero extra threads, no socket timeouts, no chaos engine.

Unit level: chaos spec grammar + deterministic counters, heartbeat
monitor staleness/dead-mark propagation, deadline-bounded PeerMesh
waits, RanksFailedError wire round-trip through Status and the poison
frame, kv_barrier missing-rank diagnostics, retry policy semantics, and
the elastic-driver shrink path resuming at world size 3.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
from collections import OrderedDict

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_multiprocess import _run_world  # noqa: E402

from horovod_tpu.common.exceptions import RanksFailedError  # noqa: E402
from horovod_tpu.common.status import Status  # noqa: E402
from horovod_tpu.resilience import chaos as chaos_mod  # noqa: E402
from horovod_tpu.resilience import policy as policy_mod  # noqa: E402
from horovod_tpu.resilience.context import ResilienceState  # noqa: E402
from horovod_tpu.resilience.heartbeat import HeartbeatMonitor  # noqa: E402

HARD_GUARD_SECONDS = 300


@pytest.fixture(autouse=True)
def hard_timeout_guard():
    """Every chaos test runs under a hard wall-clock guard (ISSUE 5
    CI satellite): a re-introduced deadlock fails this test in bounded
    time instead of stalling the tier-1 run until the outer timeout."""
    def _expired(signum, frame):
        raise TimeoutError(
            f"resilience test exceeded the {HARD_GUARD_SECONDS}s hard "
            f"guard — a blocking wait has lost its deadline")
    old = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(HARD_GUARD_SECONDS)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture()
def kv():
    from horovod_tpu.runner.network import (RendezvousClient,
                                            RendezvousServer)
    server = RendezvousServer()
    port = server.start()
    yield RendezvousClient("127.0.0.1", port, 10.0)
    server.stop()


class FakeMonitor:
    """Deterministic monitor for unit-level ResilienceState tests."""

    def __init__(self) -> None:
        self.failed: set[int] = set()
        self.confirmed: set[int] = set()
        self.marks: list[tuple[int, str, bool]] = []

    def failed_ranks(self):
        return frozenset(self.failed)

    def confirmed_failed_ranks(self):
        return frozenset(self.confirmed)

    def mark_failed(self, r, reason, confirmed=True):
        self.marks.append((r, reason, confirmed))
        self.failed.add(r)
        if confirmed:
            self.confirmed.add(r)

    def stop(self):
        pass


def _state(rank=0, size=2, fault_timeout=1.0, monitor=None):
    return ResilienceState(rank, size, monitor or FakeMonitor(),
                           fault_timeout=fault_timeout)


# ---------------------------------------------------------------------------
# Process-level acceptance batteries
# ---------------------------------------------------------------------------
def test_chaos_sigkill_converts_deadlock_4rank():
    """ISSUE 5 acceptance: SIGKILL of rank 2 mid-allreduce (HOROVOD_CHAOS
    kill:rank=2,op=3,sig=9) → all three survivors raise
    RanksFailedError(failed_ranks={2}) within 2x HOROVOD_FAULT_TIMEOUT;
    the wall-clock bound is asserted inside each surviving worker."""
    outputs = _run_world(4, "resilience_kill", timeout=150.0,
                         expected_rcs={2: -signal.SIGKILL})
    for r in (0, 1, 3):
        assert "RanksFailedError" in outputs[r], outputs[r]


def test_retry_policy_recovers_over_rebuilt_channels_4rank():
    """Delayed-send chaos (rank 1 -> rank 2, 9 s against a 3 s deadline)
    fails attempt 0 on every rank; the retry policy backs off, rebuilds
    every channel under a bumped rendezvous epoch, and the re-run
    (chaos count exhausted) produces the exact result."""
    outputs = _run_world(4, "resilience_retry", timeout=240.0)
    assert all("retry converged" in o for o in outputs), outputs


def test_frozen_rank_detected_by_deadline_2rank():
    """A wedged rank (chaos freeze, PID alive, heartbeat thread still
    beating) is only catchable by the per-op deadline — the survivor
    must convert within 2x the fault timeout."""
    outputs = _run_world(2, "resilience_freeze", timeout=120.0)
    assert "wedged peer converted" in outputs[0], outputs[0]


def test_off_mode_zero_overhead_2rank():
    """With HOROVOD_FAULT_TOLERANCE and HOROVOD_CHAOS unset: no monitor
    thread, no chaos engine, no socket timeouts, no resilience capture
    on any mesh/channel (asserted in-battery)."""
    _run_world(2, "resilience_off", timeout=90.0)


# ---------------------------------------------------------------------------
# RanksFailedError + Status + poison frame plumbing
# ---------------------------------------------------------------------------
def test_ranks_failed_error_wire_roundtrip():
    e = RanksFailedError({3, 1}, op="allreduce(grad.0…)", phase="recv",
                         message="rank 3 went away")
    w = e.to_wire()
    assert RanksFailedError.matches(w)
    back = RanksFailedError.from_wire(w)
    assert back.failed_ranks == frozenset({1, 3})
    assert back.op == "allreduce(grad.0…)"
    assert back.phase == "recv"
    assert "rank 3 went away" in str(back)


def test_ranks_failed_error_is_internal_and_connection_error():
    import horovod_tpu as hvd
    e = RanksFailedError({2})
    assert isinstance(e, hvd.HorovodInternalError)
    assert isinstance(e, ConnectionError)   # pre-resilience handlers


def test_status_reraises_typed_ranks_failed():
    status = Status.ranks_failed(RanksFailedError({2}, op="bc",
                                                  phase="send"))
    with pytest.raises(RanksFailedError) as exc_info:
        status.raise_if_error()
    assert exc_info.value.failed_ranks == frozenset({2})
    # An unrelated error string still raises the generic type.
    from horovod_tpu.common.exceptions import HorovodInternalError
    with pytest.raises(HorovodInternalError) as exc_info:
        Status.unknown_error("boom").raise_if_error()
    assert not isinstance(exc_info.value, RanksFailedError)


def test_poison_frame_prefix_detection():
    from horovod_tpu.common.tcp_transport import (POISON_MAGIC,
                                                  check_poison)
    e = RanksFailedError({1}, op="ar", phase="gather")
    frame = POISON_MAGIC + e.to_wire().encode()
    with pytest.raises(RanksFailedError) as exc_info:
        check_poison(frame)
    assert exc_info.value.failed_ranks == frozenset({1})
    check_poison(b"\x00\x00\x00\x02ok")   # ordinary frame: no raise
    check_poison(bytearray(b"\x01plain"))


# ---------------------------------------------------------------------------
# Chaos spec grammar + determinism
# ---------------------------------------------------------------------------
def test_chaos_spec_grammar():
    acts = chaos_mod.parse_spec(
        "kill:rank=2,op=5,sig=9; freeze:rank=1,op=3,ms=4000;"
        "fail:op=7,count=2;delay:rank=1,peer=0,send=3,ms=250,count=1;"
        "drop:peer=2,send=0;dup:peer=1,send=4,mesh=data")
    kinds = [a.kind for a in acts]
    assert kinds == ["kill", "freeze", "fail", "delay", "drop", "dup"]
    assert acts[0].sig == 9 and acts[0].rank == 2 and acts[0].op == 5
    assert acts[1].ms == 4000
    assert acts[2].count == 2 and acts[2].rank is None
    assert acts[5].mesh == "data"


@pytest.mark.parametrize("bad", [
    "nonsense:op=1", "kill:rank=2", "delay:rank=1,ms=5",
    "kill:rank2,op=3", "freeze", "fail:op",
])
def test_chaos_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        chaos_mod.parse_spec(bad)


def test_chaos_fail_action_symmetric_and_counted():
    eng = chaos_mod.ChaosEngine("fail:op=1,count=2", rank=3)
    assert eng.on_response(["a"]) is None        # op 0
    assert eng.on_response(["b"]) == "fail"      # op 1: fires
    assert eng.on_response(["b"]) is None        # count tracks op index,
    assert eng.actions[0].count == 1             # one firing consumed


def test_chaos_name_prefix_matching():
    eng = chaos_mod.ChaosEngine("fail:name=grad.,count=2", rank=0)
    assert eng.on_response(["loss"]) is None
    assert eng.on_response(["grad.3", "grad.4"]) == "fail"
    assert eng.on_response(["grad.5"]) == "fail"
    assert eng.on_response(["grad.6"]) is None   # count exhausted


def test_chaos_send_counters_are_per_scope_and_peer():
    eng = chaos_mod.ChaosEngine("drop:rank=0,peer=1,send=1,mesh=data",
                                rank=0)
    assert eng.on_send("data0", 1) is None       # send 0
    assert eng.on_send("data0", 2) is None       # other peer: own counter
    assert eng.on_send("ctrl0", 1) is None       # other mesh: no match
    assert eng.on_send("data0", 1) == "drop"     # send 1 on (data0, 1)
    assert eng.on_send("data0", 1) is None       # count exhausted


def test_chaos_prob_matcher_is_seed_deterministic():
    def fired(seed):
        eng = chaos_mod.ChaosEngine(
            f"drop:peer=0,prob=0.5,seed={seed},count=-1", rank=0)
        return [eng.on_send("m", 0) == "drop" for _ in range(32)]
    assert fired(7) == fired(7)                  # replayable
    assert fired(7) != fired(8)                  # and actually seeded
    assert any(fired(7)) and not all(fired(7))


def test_chaos_engine_survives_reconfigure_with_same_spec(monkeypatch):
    monkeypatch.setenv("HOROVOD_CHAOS", "fail:op=0,count=1")
    eng = chaos_mod.configure(0)
    assert eng.on_response(["x"]) == "fail"
    # Same spec (a retry's re-init): counters persist, op won't re-fail.
    eng2 = chaos_mod.configure(0)
    assert eng2 is eng
    monkeypatch.setenv("HOROVOD_CHAOS", "")
    assert chaos_mod.configure(0) is None


def test_chaos_fail_does_not_poison_response_cache(monkeypatch):
    """The fail action must REPLACE the response, never mutate it: the
    original object lives in the response cache, and an in-place flip
    to ERROR would fail every later cache hit of that tensor.  Here the
    re-enqueued op (count exhausted) must succeed from the cache."""
    import horovod_tpu as hvd
    monkeypatch.setenv("HOROVOD_CHAOS", "fail:op=0,count=1")
    hvd.init(rank=0, size=1)
    try:
        with pytest.raises(hvd.HorovodInternalError, match="chaos"):
            hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum, name="cf")
        for _ in range(3):   # renegotiated AND cache-hit paths both clean
            out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                                name="cf")
            np.testing.assert_allclose(out, np.ones(4))
    finally:
        hvd.shutdown()
        monkeypatch.setenv("HOROVOD_CHAOS", "")
        chaos_mod.configure(0)


# ---------------------------------------------------------------------------
# Heartbeat monitor
# ---------------------------------------------------------------------------
def test_heartbeat_staleness_declares_failure(kv):
    a = HeartbeatMonitor(0, 2, kv, "hb-t1", fault_timeout=0.4,
                         interval=0.1)
    b = HeartbeatMonitor(1, 2, kv, "hb-t1", fault_timeout=0.4,
                         interval=0.1)
    a._publish()
    b._publish()
    a._started_at = b._started_at = time.monotonic()
    a.poll_once()
    assert a.failed_ranks() == frozenset()
    # b stops beating; a keeps observing the same value.
    time.sleep(0.6)
    a.poll_once()
    assert a.failed_ranks() == frozenset({1})
    assert a.confirmed_failed_ranks() == frozenset({1})
    assert "silent" in a.failure_reason(1)


def test_heartbeat_progress_prevents_failure(kv):
    a = HeartbeatMonitor(0, 2, kv, "hb-t2", fault_timeout=0.4,
                         interval=0.1)
    b = HeartbeatMonitor(1, 2, kv, "hb-t2", fault_timeout=0.4,
                         interval=0.1)
    a._started_at = time.monotonic() - 10.0   # grace long over
    deadline = time.monotonic() + 0.9
    while time.monotonic() < deadline:
        b._publish()
        a.poll_once()
        time.sleep(0.1)
    assert a.failed_ranks() == frozenset()


def test_dead_mark_propagates_between_monitors(kv):
    a = HeartbeatMonitor(0, 3, kv, "hb-t3", fault_timeout=30.0,
                         interval=0.1)
    b = HeartbeatMonitor(1, 3, kv, "hb-t3", fault_timeout=30.0,
                         interval=0.1)
    for m in (a, b):
        m._publish()
    # a has direct socket evidence that rank 2 died.
    a.mark_failed(2, "connection lost: reset by peer")
    b.poll_once()
    assert b.failed_ranks() == frozenset({2})
    assert b.confirmed_failed_ranks() == frozenset({2})


def test_orderly_departure_bye_is_not_death(kv):
    """A rank that stops its monitor deliberately (shutdown, or an
    epoch rebuild mid-retry) leaves a bye stamp; peers must not read
    the ensuing heartbeat silence as confirmed death — that race made
    the retry policy refuse legitimate rebuilds."""
    a = HeartbeatMonitor(0, 2, kv, "hb-bye", fault_timeout=0.3,
                         interval=0.05)
    b = HeartbeatMonitor(1, 2, kv, "hb-bye", fault_timeout=0.3,
                         interval=0.05)
    for m in (a, b):
        m._publish()
    a._started_at = time.monotonic() - 10.0
    a.poll_once()
    b.stop()   # publishes the bye stamp
    time.sleep(0.5)
    a.poll_once()
    assert a.failed_ranks() == frozenset()
    assert "bye|" in (kv.get("hb", "hb-bye:1") or b"").decode()


def test_suspect_mark_is_not_confirmed(kv):
    a = HeartbeatMonitor(0, 3, kv, "hb-t4", fault_timeout=30.0,
                         interval=0.1)
    b = HeartbeatMonitor(1, 3, kv, "hb-t4", fault_timeout=30.0,
                         interval=0.1)
    for m in (a, b):
        m._publish()
    a.mark_failed(2, "deadline expiry", confirmed=False)
    b.poll_once()
    assert b.failed_ranks() == frozenset({2})
    assert b.confirmed_failed_ranks() == frozenset()
    # Later confirmed evidence upgrades the suspect.
    a.mark_failed(2, "pid gone", confirmed=True)
    b.poll_once()
    assert b.confirmed_failed_ranks() == frozenset({2})


def test_monitor_thread_starts_and_stops(kv):
    from census import assert_no_new_threads, assert_thread_absent, \
        thread_names
    m = HeartbeatMonitor(0, 2, kv, "hb-t5", fault_timeout=5.0,
                         interval=0.05)
    before = thread_names()
    m.start()
    assert "hvd-heartbeat" in thread_names()
    m.stop()
    time.sleep(0.05)
    assert_no_new_threads(before, context="monitor stop")
    assert_thread_absent("hvd-heartbeat")


def test_configure_off_returns_none(kv, monkeypatch):
    monkeypatch.delenv("HOROVOD_FAULT_TOLERANCE", raising=False)
    from horovod_tpu import resilience
    assert resilience.configure(0, 4, kv, "e") is None
    assert resilience.active_state() is None


# ---------------------------------------------------------------------------
# Deadline-bounded PeerMesh waits (in-proc two-rank worlds)
# ---------------------------------------------------------------------------
def _mesh_pair(kv, scope, states):
    from horovod_tpu.runner.network import PeerMesh
    meshes: list = [None, None]
    errs: list = []

    def form(r):
        try:
            meshes[r] = PeerMesh(r, 2, kv, scope=scope, timeout=10.0,
                                 resilience=states[r])
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=form, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20.0)
    assert not errs, errs
    return meshes


def test_recv_deadline_raises_ranks_failed(kv):
    states = [_state(r, 2, fault_timeout=0.8) for r in range(2)]
    m0, m1 = _mesh_pair(kv, "dl1", states)
    try:
        t0 = time.monotonic()
        with pytest.raises(RanksFailedError) as exc_info:
            m0.recv(1)   # rank 1 never sends
        elapsed = time.monotonic() - t0
        assert exc_info.value.failed_ranks == frozenset({1})
        assert exc_info.value.phase == "recv"
        assert 0.5 < elapsed < 5.0, elapsed
        # The deadline expiry marked the peer suspect, not confirmed.
        assert states[0].monitor.marks[-1][2] is False
    finally:
        for m in (m0, m1):
            m.close()


def test_recv_converts_closed_socket_to_ranks_failed(kv):
    states = [_state(r, 2, fault_timeout=5.0) for r in range(2)]
    m0, m1 = _mesh_pair(kv, "dl2", states)
    try:
        m1.close()
        with pytest.raises(RanksFailedError) as exc_info:
            m0.recv(1)
        assert 1 in exc_info.value.failed_ranks
        # Connection loss is SUSPECT evidence (an errored-but-alive peer
        # also closes its sockets); only heartbeat silence / PID death
        # confirm, so the retry policy stays able to rebuild.
        assert states[0].monitor.failed == {1}
        assert states[0].monitor.confirmed == set()
    finally:
        m0.close()


def test_monitor_declared_failure_converts_other_waits(kv):
    """A failure declared by the monitor (e.g. propagated via a dead
    mark from a distant rank) converts THIS rank's blocked recv within
    one poll slice — attribution beats the local deadline."""
    states = [_state(r, 2, fault_timeout=30.0) for r in range(2)]
    m0, m1 = _mesh_pair(kv, "dl3", states)
    try:
        def declare():
            time.sleep(0.3)
            states[0].monitor.failed.add(1)
            states[0].monitor.confirmed.add(1)
        threading.Thread(target=declare, daemon=True).start()
        t0 = time.monotonic()
        with pytest.raises(RanksFailedError):
            m0.recv(1)
        assert time.monotonic() - t0 < 5.0
    finally:
        for m in (m0, m1):
            m.close()


def test_progress_resets_recv_deadline(kv):
    """The deadline bounds SILENCE, not transfer time: a sender trickling
    bytes slower than the whole-payload deadline must not be killed."""
    states = [_state(r, 2, fault_timeout=0.6) for r in range(2)]
    m0, m1 = _mesh_pair(kv, "dl4", states)
    try:
        payload = np.arange(64, dtype=np.uint8).tobytes()

        def trickle():
            # Hand-frame the message, 8 bytes per 0.2 s: total 1.6 s+,
            # every gap well under the 0.6 s deadline.
            import struct
            raw = struct.pack(">I", len(payload)) + payload
            for i in range(0, len(raw), 8):
                m1._socks[0].sendall(raw[i:i + 8])
                time.sleep(0.2)
        th = threading.Thread(target=trickle, daemon=True)
        th.start()
        data = m0.recv(1)
        th.join(10.0)
        assert bytes(data) == payload
    finally:
        for m in (m0, m1):
            m.close()


def test_chaos_drop_then_deadline(kv, monkeypatch):
    """A chaos-dropped send leaves the receiver silent; the deadline
    converts the wait — the exact failure mode the drop action exists
    to exercise."""
    monkeypatch.setenv("HOROVOD_CHAOS", "drop:rank=1,peer=0,send=0,"
                                        "mesh=cd1,count=1")
    chaos_mod.configure(1)
    try:
        states = [_state(r, 2, fault_timeout=0.7) for r in range(2)]
        m0, m1 = _mesh_pair(kv, "cd1", states)
        try:
            m1.send(0, b"vanishes")            # dropped
            with pytest.raises(RanksFailedError):
                m0.recv(1)
            m1.send(0, b"arrives")             # count exhausted
            assert bytes(m0.recv(1)) == b"arrives"
        finally:
            for m in (m0, m1):
                m.close()
    finally:
        monkeypatch.setenv("HOROVOD_CHAOS", "")
        chaos_mod.configure(1)


def test_chaos_dup_duplicates_frame(kv, monkeypatch):
    monkeypatch.setenv("HOROVOD_CHAOS", "dup:rank=1,peer=0,send=0,"
                                        "mesh=cd2,count=1")
    chaos_mod.configure(1)
    try:
        m0, m1 = _mesh_pair(kv, "cd2", [None, None])
        try:
            m1.send(0, b"twice")
            assert bytes(m0.recv(1)) == b"twice"
            assert bytes(m0.recv(1)) == b"twice"   # the duplicate
        finally:
            for m in (m0, m1):
                m.close()
    finally:
        monkeypatch.setenv("HOROVOD_CHAOS", "")
        chaos_mod.configure(1)


def test_peer_channel_close_poisons_then_warns(kv, caplog):
    """Satellite: close() poisons the queue first and never silently
    leaks the sender thread — after close the lane thread is gone."""
    m0, m1 = _mesh_pair(kv, "cl1", [None, None])
    try:
        m1.send_async(0, b"x" * 1024)
        m1.flush()
        assert bytes(m0.recv(1)) == b"x" * 1024
        ch = m1._channels[0]
        assert ch._sender is not None and ch._sender.is_alive()
        sender = ch._sender
        m1.close()
        sender.join(2.0)
        assert not sender.is_alive(), "sender lane leaked at close"
    finally:
        m0.close()
        m1.close()


# ---------------------------------------------------------------------------
# kv_barrier missing-rank diagnostics (satellite)
# ---------------------------------------------------------------------------
def test_kv_barrier_timeout_names_missing_ranks(kv):
    from horovod_tpu.parallel import multihost
    saved = (multihost._initialized_here, multihost._world,
             multihost._barrier_seq)
    multihost._initialized_here = True
    multihost._world = (0, 3, kv, "diag")
    multihost._barrier_seq = 0
    try:
        # Rank 2 "arrives" at the barrier; rank 1 never does.
        kv.put("barrier", "diag:t:1:2", b"1")
        with pytest.raises(TimeoutError) as exc_info:
            multihost.kv_barrier("t", timeout=0.5)
        msg = str(exc_info.value)
        assert "missing ranks: [1]" in msg, msg
        assert "tag='t'" in msg
    finally:
        (multihost._initialized_here, multihost._world,
         multihost._barrier_seq) = saved


# ---------------------------------------------------------------------------
# Recovery policy
# ---------------------------------------------------------------------------
def test_run_with_recovery_raise_policy_propagates():
    calls = []

    def fn():
        calls.append(1)
        raise RanksFailedError({1})

    with pytest.raises(RanksFailedError):
        policy_mod.run_with_recovery(fn, policy="raise")
    assert len(calls) == 1


def test_run_with_recovery_rejects_unknown_policy():
    with pytest.raises(ValueError):
        policy_mod.run_with_recovery(lambda: None, policy="panic")


def test_run_with_recovery_retries_with_backoff(monkeypatch):
    rebuilds = []
    monkeypatch.setattr(policy_mod, "rebuild_world",
                        lambda attempt: rebuilds.append(attempt))
    attempts = []

    def fn():
        attempts.append(1)
        if len(attempts) < 3:
            raise RanksFailedError({1}, op="ar", phase="recv")
        return "ok"

    t0 = time.monotonic()
    out = policy_mod.run_with_recovery(fn, policy="retry",
                                       max_retries=5, base_backoff=0.05)
    elapsed = time.monotonic() - t0
    assert out == "ok"
    assert rebuilds == [1, 2]
    assert policy_mod.last_attempts == 3
    assert elapsed >= 0.05 + 0.10   # exponential: 0.05, then 0.10


def test_run_with_recovery_gives_up_after_max_retries(monkeypatch):
    monkeypatch.setattr(policy_mod, "rebuild_world", lambda attempt: None)

    def fn():
        raise RanksFailedError({1})

    with pytest.raises(RanksFailedError):
        policy_mod.run_with_recovery(fn, policy="retry", max_retries=2,
                                     base_backoff=0.01)
    assert policy_mod.last_attempts == 3   # initial + 2 retries


def test_run_with_recovery_refuses_confirmed_dead(monkeypatch):
    """Retry must not spin on a CONFIRMED-dead rank: the world cannot be
    rebuilt at the same size — that is shrink's job."""
    from horovod_tpu.resilience import context as ctx
    fake = FakeMonitor()
    fake.mark_failed(2, "pid gone", confirmed=True)
    monkeypatch.setattr(ctx, "_state", _state(0, 4, monitor=fake))
    monkeypatch.setattr(policy_mod, "rebuild_world",
                        lambda attempt: pytest.fail("must not rebuild"))

    def fn():
        raise RanksFailedError({2})

    with pytest.raises(RanksFailedError):
        policy_mod.run_with_recovery(fn, policy="retry", max_retries=5,
                                     base_backoff=0.01)


def test_retry_epoch_is_deterministic_and_non_accumulating():
    assert policy_mod._retry_epoch("abc", 1) == "abc~r1"
    assert policy_mod._retry_epoch("abc~r1", 2) == "abc~r2"
    assert policy_mod._retry_epoch("abc~r2", 3) == "abc~r3"


# ---------------------------------------------------------------------------
# Shrink policy → elastic driver resumes at world-size 3 (satellite)
# ---------------------------------------------------------------------------
def test_shrink_blacklists_host_and_driver_resumes_at_3():
    from horovod_tpu.elastic.discovery import FixedHostDiscovery
    from horovod_tpu.elastic.driver import ElasticDriver

    hosts = OrderedDict((f"h{i}", 1) for i in range(4))
    driver = ElasticDriver(FixedHostDiscovery(hosts), min_np=3, max_np=4,
                           timeout=20.0)
    release = threading.Event()
    driver.start(np=4, create_worker_fn=lambda slot:
                 0 if release.wait(30.0) else 1)
    try:
        assert driver.world_size() == 4
        epoch0 = driver.current_epoch
        slots = driver.rank_to_slot()

        # Rank 2 died: the resilience shrink policy maps the failed-rank
        # set onto hosts, blacklists them, and records the failures.
        shrunk = policy_mod.apply_shrink(driver, {2})
        assert shrunk == {2: slots[2].hostname}

        # The three survivors re-rendezvous (what hvd.elastic.run does
        # after RanksFailedError); the round resolves and the driver
        # resumes on the surviving host set.
        for r in (0, 1, 3):
            driver.record_ready(slots[r].hostname, slots[r].local_rank)
        deadline = time.monotonic() + 15.0
        while driver.current_epoch == epoch0 and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert driver.current_epoch > epoch0, "no new round formed"
        assert driver.world_size() == 3
        final_hosts = {s.split("[")[0]
                       for s in driver.final_slots().values()}
        assert slots[2].hostname not in final_hosts
    finally:
        release.set()
        driver.stop()
        driver.shutdown()


# ---------------------------------------------------------------------------
# ResilienceState semantics
# ---------------------------------------------------------------------------
def test_state_check_prefers_monitor_verdict_over_deadline():
    fake = FakeMonitor()
    st = _state(0, 4, fault_timeout=1.0, monitor=fake)
    st.check(3, waited=0.1, phase="recv")       # quiet: no raise
    fake.mark_failed(2, "dead")
    with pytest.raises(RanksFailedError) as exc_info:
        st.check(3, waited=0.1, phase="recv")
    assert exc_info.value.failed_ranks == frozenset({2})   # true culprit


def test_state_deadline_expiry_names_waited_peer():
    st = _state(0, 4, fault_timeout=0.5)
    with pytest.raises(RanksFailedError) as exc_info:
        st.check(3, waited=0.6, phase="send")
    assert exc_info.value.failed_ranks == frozenset({3})
    assert exc_info.value.phase == "send"


def test_op_scope_labels_errors():
    from horovod_tpu.resilience import current_op, op_scope
    assert current_op() == ""
    with op_scope("allreduce(x)"):
        assert current_op() == "allreduce(x)"
        st = _state(0, 2, fault_timeout=0.1)
        with pytest.raises(RanksFailedError) as exc_info:
            st.check(1, waited=1.0, phase="recv")
        assert exc_info.value.op == "allreduce(x)"
    assert current_op() == ""
