"""serving/ battery (ISSUE 9): continuous batching, admission control,
per-request deadline propagation, the chaos shrink mid-serve, and the
loadgen SLO harness.

Process-level acceptance (4-rank mp_worker "serving" battery under the
hard SIGALRM guard): chaos SIGKILLs rank 2 mid-serve; the world shrinks
4->3, every survivor completes every request it had admitted (zero
failed in-flight on survivors), accounting balances with bounded shed,
and a post-shrink hopeless-SLO burst is shed at admission — never
prefilled on any rank.

Unit level: bounded ingress queue with deadlines stamped at the door,
token-budgeted continuous batch assembly, admission verdicts
(expired / load shed / infeasible / admitted) keyed off live telemetry,
deadline_scope -> per-op deadline propagation, and the loadgen report
schema (the tier-1 smoke: --requests 64 --duration 5).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_multiprocess import _run_world  # noqa: E402

from horovod_tpu.serving.admission import AdmissionController  # noqa: E402
from horovod_tpu.serving.batcher import ContinuousBatcher  # noqa: E402
from horovod_tpu.serving.queue import RequestQueue, ServeRequest  # noqa: E402
from horovod_tpu.telemetry.registry import MetricsRegistry  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HARD_GUARD_SECONDS = 420


@pytest.fixture(autouse=True)
def hard_timeout_guard():
    """Serving tests exercise deadline machinery: a regression that
    re-introduces an unbounded wait must fail fast, not eat the tier-1
    budget (the resilience-suite convention)."""
    def _expired(signum, frame):
        raise TimeoutError(
            f"serving test exceeded the {HARD_GUARD_SECONDS}s hard "
            f"guard — a blocking wait has lost its deadline")
    old = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(HARD_GUARD_SECONDS)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _mkreq(rid=0, tokens=(1, 2, 3), max_new=4, slo_ms=1000.0,
           age_s=0.0) -> ServeRequest:
    now = time.monotonic()
    return ServeRequest(rid=rid, tokens=list(tokens),
                        max_new_tokens=max_new, arrival=now - age_s,
                        deadline=now - age_s + slo_ms / 1e3,
                        slo_ms=slo_ms)


class _AdmitAll:
    def __init__(self):
        self.counts = {}

    def admit(self, req, depth, now=None):
        self.count("admitted")
        return True, "admitted"

    def count(self, outcome, n=1):
        self.counts[outcome] = self.counts.get(outcome, 0) + n


# --- ingress queue ----------------------------------------------------------
def test_queue_bounded_and_deadline_stamped():
    reg = MetricsRegistry(0)
    q = RequestQueue(maxsize=2, default_slo_ms=500.0, registry=reg)
    t0 = time.monotonic()
    assert q.submit([1, 2], 4) == 0
    assert q.submit([3], 4, slo_ms=50.0) == 1
    # Full queue sheds at the door (never blocks, never buffers).
    assert q.submit([4], 4) is None
    assert reg.counter("horovod_serve_requests_total",
                       labels={"outcome": "rejected_full"}).value == 1
    assert reg.gauge("horovod_serve_queue_depth").value == 2
    ready, expired = q.pop_ready(10)
    assert [r.rid for r in ready] == [0, 1] and expired == []
    # Deadlines were stamped at ingress, per-request SLO honored.
    assert ready[0].deadline == pytest.approx(t0 + 0.5, abs=0.05)
    assert ready[1].deadline == pytest.approx(t0 + 0.05, abs=0.05)


def test_queue_expires_while_queued():
    q = RequestQueue(maxsize=8, default_slo_ms=1000.0,
                     registry=MetricsRegistry(0))
    q.submit([1], 2, slo_ms=1.0)     # expires in 1 ms
    q.submit([2], 2)                 # healthy
    time.sleep(0.02)
    ready, expired = q.pop_ready(10)
    assert [r.rid for r in expired] == [0]
    assert [r.rid for r in ready] == [1]


def test_queue_close_sheds_new_but_drains_old():
    q = RequestQueue(maxsize=8, registry=MetricsRegistry(0))
    assert q.submit([1], 2) == 0
    q.close()
    assert q.submit([2], 2) is None
    ready, _ = q.pop_ready(10)
    assert [r.rid for r in ready] == [0]


# --- continuous batcher -----------------------------------------------------
def test_batcher_fills_least_loaded_within_budget():
    reg = MetricsRegistry(0)
    q = RequestQueue(maxsize=64, registry=reg)
    adm = _AdmitAll()
    b = ContinuousBatcher(2, slots_per_replica=2, token_budget=8)
    for i in range(4):
        q.submit([1] * 3, 4)
    plan, expired = b.assemble(0, q, adm)
    assert expired == []
    # 2 replicas x 2 slots, 3 prefill tokens each within budget 8.
    assert len(plan.assign) == 4
    assert sorted(a.replica for a in plan.assign) == [0, 0, 1, 1]
    assert b.inflight_count() == 4
    # Slots full: nothing more is assembled until completions free them.
    q.submit([1] * 3, 4)
    plan2, _ = b.assemble(1, q, adm)
    assert plan2.assign == []
    b.note_done(plan.assign[0].rid)
    plan3, _ = b.assemble(2, q, adm)
    assert len(plan3.assign) == 1
    assert plan3.assign[0].replica == plan.assign[0].replica


def test_batcher_token_budget_defers_not_sheds():
    """A prompt that exceeds this step's remaining token budget is
    back-pressure: requeued at the head, admitted on a later step —
    never silently dropped."""
    reg = MetricsRegistry(0)
    q = RequestQueue(maxsize=64, registry=reg)
    adm = _AdmitAll()
    b = ContinuousBatcher(1, slots_per_replica=4, token_budget=10)
    q.submit([1] * 8, 4)
    q.submit([2] * 8, 4)             # 16 prefill tokens > budget 10
    plan, _ = b.assemble(0, q, adm)
    assert [a.rid for a in plan.assign] == [0]
    assert q.depth() == 1
    plan2, _ = b.assemble(1, q, adm)
    assert [a.rid for a in plan2.assign] == [1]


def test_batcher_rebuild_reports_lost():
    b = ContinuousBatcher(3, slots_per_replica=2, token_budget=64)
    b.inflight = {0: 0, 1: 1, 2: 2, 3: 2}
    b._active = [1, 1, 2]
    lost = b.rebuild([[0], [1]])     # replica 2 died with rids 2, 3
    assert lost == [2, 3]
    assert b.inflight == {0: 0, 1: 1}
    assert b._active == [1, 1]


def test_batcher_aging_rescues_starved_big_prompt():
    """ISSUE 14 starvation fix: an over-budget prompt requeued-at-head
    every step used to be bypassed indefinitely by smaller admissions.
    After HOROVOD_SERVE_MAX_DEFERRALS deferrals it turns urgent —
    bypasses the token budget and reserves the step (barrier) — so it
    lands as soon as a slot frees."""
    q = RequestQueue(maxsize=256, registry=MetricsRegistry(0))
    adm = _AdmitAll()
    b = ContinuousBatcher(1, slots_per_replica=2, token_budget=10,
                          max_deferrals=3)
    huge = q.submit([9] * 40, 4)         # 40 prefill tokens >> budget
    admitted_at = None
    for step in range(12):
        for _ in range(2):
            q.submit([1] * 3, 2)         # relentless small-prompt stream
        plan, _ = b.assemble(step, q, adm)
        for a in plan.assign:            # everything finishes instantly
            b.note_done(a.rid)
        if any(a.rid == huge for a in plan.assign):
            admitted_at = step
            break
    # Deferred steps 0..2 (budget), urgent at step 3: admitted there.
    assert admitted_at is not None and admitted_at <= 4, admitted_at


def test_batcher_urgent_barrier_reserves_the_step():
    """While an urgent prompt still lacks a slot, nothing behind it is
    admitted — smaller requests cannot keep stealing the capacity it
    is waiting for."""
    q = RequestQueue(maxsize=64, registry=MetricsRegistry(0))
    adm = _AdmitAll()
    b = ContinuousBatcher(1, slots_per_replica=1, token_budget=10,
                          max_deferrals=0)   # urgent immediately
    q.submit([9] * 40, 4)                    # needs the (occupied) slot
    q.submit([1] * 2, 2)
    blocker = q.submit([1] * 2, 2)
    del blocker
    # Occupy the only slot so even the urgent prompt cannot land.
    b.inflight[99] = 0
    b._active = [1]
    plan, _ = b.assemble(0, q, adm)
    assert plan.assign == []                 # barrier held everything
    b.note_done(99)
    plan, _ = b.assemble(1, q, adm)
    assert [a.tokens[0] for a in plan.assign] == [9]   # urgent first


def test_batcher_block_capacity_defers_admissions():
    """Paged mode: the batcher mirrors each replica's block pool and
    defers admissions whose worst-case reservation (prompt + max_new,
    + 1 block COW headroom) would not fit — reserve-at-admission is
    what makes mid-decode pool exhaustion impossible."""
    q = RequestQueue(maxsize=64, registry=MetricsRegistry(0))
    adm = _AdmitAll()
    b = ContinuousBatcher(1, slots_per_replica=8, token_budget=1000,
                          block_capacity=10, block_tokens=16)
    for _ in range(4):
        q.submit([1] * 16, 16)       # ceil(32/16)+1 = 3 blocks each
    plan, _ = b.assemble(0, q, adm)
    assert len(plan.assign) == 3 and b._blocks == [9]
    assert q.depth() == 1            # 4th deferred: 9 + 3 > 10
    b.note_done(plan.assign[0].rid)
    plan2, _ = b.assemble(1, q, adm)
    assert len(plan2.assign) == 1 and b._blocks == [9]


# --- admission control ------------------------------------------------------
def test_admission_verdicts():
    reg = MetricsRegistry(0)
    adm = AdmissionController(registry=reg, queue_depth_limit=10,
                              shed_fraction=0.5, step_ms_seed=10.0)
    # Already past its deadline: expired, never executed.
    ok, outcome = adm.admit(_mkreq(slo_ms=1.0, age_s=1.0), 0)
    assert (ok, outcome) == (False, "expired")
    # Queue pressure beyond the gauge threshold: load shed.
    ok, outcome = adm.admit(_mkreq(slo_ms=10000.0), 9)
    assert (ok, outcome) == (False, "shed")
    # Deadline-infeasible: 100 decode steps never fit 50 ms at ~10 ms
    # per step.
    ok, outcome = adm.admit(_mkreq(max_new=100, slo_ms=50.0), 0)
    assert (ok, outcome) == (False, "shed")
    # Feasible and unloaded: admitted.
    ok, outcome = adm.admit(_mkreq(max_new=4, slo_ms=10000.0), 0)
    assert (ok, outcome) == (True, "admitted")
    counts = {m["labels"]["outcome"]: m["value"]
              for m in reg.snapshot()["metrics"]
              if m["name"] == "horovod_serve_requests_total"
              and m["value"] > 0}
    assert counts == {"admitted": 1, "expired": 1, "shed": 2}


def test_admission_estimate_tracks_live_step_time():
    adm = AdmissionController(registry=MetricsRegistry(0),
                              queue_depth_limit=100, step_ms_seed=1.0)
    assert adm.step_ms() == pytest.approx(1.0)
    for _ in range(16):
        adm.observe_step_ms(40.0)
    # The shared Histogram.quantile path takes over from the EWMA seed.
    assert 20.0 < adm.step_ms() <= 40.0
    req = _mkreq(max_new=9)
    assert adm.estimate_completion_ms(req) >= 10 * 20.0


def test_admission_reads_straggler_gauge():
    reg = MetricsRegistry(0)
    reg.gauge("horovod_controller_straggler_lag_ms",
              labels={"stat": "mean"}).set(25.0)
    adm = AdmissionController(registry=reg, queue_depth_limit=100,
                              step_ms_seed=5.0)
    assert adm.straggler_lag_ms() == 25.0
    assert adm.estimate_completion_ms(_mkreq(max_new=1)) \
        == pytest.approx(2 * 30.0)


# --- deadline propagation into resilience ----------------------------------
class _FakeMonitor:
    def failed_ranks(self):
        return frozenset()

    def confirmed_failed_ranks(self):
        return frozenset()

    def mark_failed(self, r, reason, confirmed=True):
        pass

    def stop(self):
        pass


def test_deadline_scope_flows_into_per_op_timeout():
    from horovod_tpu.resilience.context import (ResilienceState,
                                                deadline_scope, op_scope,
                                                pending_deadline)
    state = ResilienceState(0, 2, _FakeMonitor(), fault_timeout=10.0)
    assert state.op_timeout() == 10.0
    # A propagated request deadline tightens the wait bound...
    with op_scope("serve.plan", deadline=time.monotonic() + 1.0):
        assert 0.5 < state.op_timeout() <= 1.01
        # ...and nests (inner scope wins, outer restored).
        with op_scope("inner", deadline=time.monotonic() + 0.6):
            assert state.op_timeout() <= 0.61
        assert 0.5 < state.op_timeout() <= 1.01
    assert state.op_timeout() == 10.0
    # A hopeless deadline floors at two poll slices: a late request
    # alone must never instantly declare a healthy peer wedged.
    with op_scope("serve.plan", deadline=time.monotonic() - 5.0):
        assert state.op_timeout() == pytest.approx(
            2.0 * state.poll_interval)
    # The caller-side half: deadline_scope parks the deadline for core's
    # enqueue stamping (TensorTableEntry.deadline).
    assert pending_deadline() is None
    with deadline_scope(123.0):
        assert pending_deadline() == 123.0
        with deadline_scope(None):
            assert pending_deadline() is None
        assert pending_deadline() == 123.0
    assert pending_deadline() is None


def test_entry_deadline_field_defaults_none():
    from horovod_tpu.common.tensor_queue import TensorTableEntry
    assert TensorTableEntry(tensor_name="x").deadline is None


# --- loadgen ----------------------------------------------------------------
def test_arrival_profiles_shape_rates():
    import random

    from horovod_tpu.serving import loadgen
    rng = random.Random(1)
    steady = loadgen.arrival_times(rng, 10000, 10.0, 100.0, "steady")
    assert 0 < len(steady) <= 10000
    assert steady == sorted(steady) and steady[-1] < 10.0
    rng = random.Random(1)
    burst = loadgen.arrival_times(rng, 10 ** 6, 10.0, 100.0, "burst")
    mid = [t for t in burst if 4.0 <= t < 6.0]
    rest = [t for t in burst if t < 4.0 or t >= 6.0]
    # 4x rate through the middle fifth: its per-second density dominates.
    assert len(mid) / 2.0 > 2.0 * len(rest) / 8.0
    rng = random.Random(1)
    ramp = loadgen.arrival_times(rng, 10 ** 6, 10.0, 100.0, "ramp")
    assert len([t for t in ramp if t >= 5.0]) > \
        2 * len([t for t in ramp if t < 5.0])


def _run_loadgen_inproc(tmp_path, argv):
    import horovod_tpu as hvd

    from horovod_tpu.serving import loadgen
    hvd.shutdown()                   # a clean single-rank world
    for var in ("HOROVOD_RANK", "HOROVOD_SIZE"):
        os.environ.pop(var, None)
    args = loadgen.make_parser().parse_args(
        argv + ["--output", str(tmp_path / "SERVE_r{rank}.json")])
    if args.slo_ms == 0.0:
        args.slo_ms = None
    return loadgen.run(args), tmp_path / "SERVE_r0.json"


def test_loadgen_report_schema(tmp_path):
    from horovod_tpu.serving import loadgen
    report, path = _run_loadgen_inproc(tmp_path, [
        "--requests", "6", "--duration", "3", "--rate", "50",
        "--max-new-tokens", "4", "--prompt-tokens", "6"])
    assert report["schema"] == loadgen.SCHEMA
    for key in ("offered", "served", "served_within_slo", "shed",
                "expired", "lost_on_failure", "latency_ms", "step_ms",
                "goodput_rps", "offered_rps", "world", "steps",
                "tokens_generated", "wall_s"):
        assert key in report, key
    assert report["offered"] == 6 == report["served"]
    assert report["shed"] == 0 and report["expired"] == 0
    assert report["latency_ms"]["p50"] > 0.0
    assert report["latency_ms"]["p999"] >= report["latency_ms"]["p99"] \
        >= report["latency_ms"]["p50"]
    assert report["step_ms"]["count"] > 0      # shared quantile path
    assert report["tokens_generated"] == 6 * 4
    on_disk = json.loads(path.read_text())
    assert on_disk["schema"] == loadgen.SCHEMA
    assert on_disk["served"] == 6


def test_loadgen_overload_sheds_at_admission(tmp_path):
    """Offered load beyond capacity with tight SLOs: requests that
    cannot meet their deadline are shed/expired at admission — goodput
    degrades by refusal, not by executing doomed work."""
    report, _ = _run_loadgen_inproc(tmp_path, [
        "--requests", "40", "--duration", "2", "--rate", "400",
        "--max-new-tokens", "64", "--prompt-tokens", "6",
        "--slo-ms", "40", "--max-batch", "2", "--token-budget", "16"])
    assert report["offered"] == 40
    assert report["shed"] + report["expired"] > 0
    assert report["served"] + report["shed"] + report["expired"] \
        + report["lost_on_failure"] == report["offered"]


def test_loadgen_smoke_cli(tmp_path):
    """The tier-1 loadgen smoke (ISSUE 9 CI satellite): the documented
    CLI drives a single-rank serve world end to end and writes the
    SERVE_r*.json report next to where the bench payloads land."""
    out = tmp_path / "SERVE_r{rank}.json"
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.serving.loadgen",
         "--requests", "64", "--duration", "5", "--rate", "40",
         "--max-new-tokens", "4", "--prompt-tokens", "8",
         "--output", str(out)],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads((tmp_path / "SERVE_r0.json").read_text())
    assert report["served"] > 0
    assert report["served"] + report["shed"] + report["expired"] \
        + report["lost_on_failure"] == report["offered"]
    assert report["latency_ms"]["p99"] >= report["latency_ms"]["p50"] > 0
    assert report["goodput_rps"] > 0
    assert "loadgen: report written" in proc.stdout


# --- paged KV end to end (single-rank worlds) -------------------------------
def _solo_world():
    import horovod_tpu as hvd
    hvd.shutdown()
    for var in ("HOROVOD_RANK", "HOROVOD_SIZE"):
        os.environ.pop(var, None)
    hvd.init()
    return hvd


class _Recorder:
    """Capture every completed slot's generated token stream (the
    completion record only carries counts)."""

    def __init__(self):
        self.streams = {}

    def install(self, ex):
        orig = ex._collect_completions

        def wrapped():
            for s in ex.slots:
                if s is not None and s.pending is None \
                        and s.remaining == 0:
                    self.streams[s.rid] = list(s.generated)
            orig()
        ex._collect_completions = wrapped


def _paged_cfg(**kw):
    from horovod_tpu.serving import ServeConfig
    base = dict(max_batch=2, token_budget=64, max_seq=64,
                slo_ms=60000.0, block_tokens=8)
    base.update(kw)
    return ServeConfig.from_env(**base)


def test_paged_serve_parity_prefix_hits_and_refcount_census():
    """ISSUE 14 acceptance (tier-1 half): for an identical admitted
    stream, paged decode produces token-for-token the dense output;
    repeated prompts hit the prefix cache (refcount bumps instead of
    re-prefill, COW on the first divergent write); and after the drain
    the pool's active count is ZERO — the refcount-leak census."""
    import random

    from horovod_tpu.serving import ReplicaExecutor

    streams = {}
    for paged in (False, True):
        hvd = _solo_world()
        ex = ReplicaExecutor(_paged_cfg(paged=paged))
        rec = _Recorder()
        rec.install(ex)
        rng = random.Random(7)
        prompts = [[rng.randrange(2, 256)
                    for _ in range(rng.randint(2, 12))]
                   for _ in range(4)]
        n = 12
        for i in range(n):
            ex.stats["offered"] += 1
            assert ex.queue.submit(prompts[i % 4], 6) is not None
        ex.serve_loop(stop_when=lambda: True)
        assert ex.stats["served"] == n
        if paged:
            kv = ex.kv_stats()
            assert kv["active"] == 0, kv          # refcount census
            assert kv["prefix_hits"] > 0, kv      # repeated prompts hit
            assert kv["cow_copies"] > 0, kv       # shared tails COWed
            assert kv["prefill_skipped"] > 0, kv  # full hits skip prefill
            assert kv["max_concurrent_seqs"] > ex.cfg.max_batch
        streams[paged] = dict(rec.streams)
        ex.close()
        hvd.shutdown()
    assert streams[False] == streams[True]        # bitwise token parity


def test_paged_eviction_then_readmission_stays_correct():
    """Cached prefix blocks evicted under pool pressure must not change
    behavior: a re-admitted prompt misses, re-prefills fresh and
    reproduces its original generation exactly."""
    import random

    from horovod_tpu.serving import ReplicaExecutor

    hvd = _solo_world()
    # Tiny pool: 2 in-flight sequences fit, but waves of distinct
    # prompts force LRU eviction of the cached ones.
    ex = ReplicaExecutor(_paged_cfg(paged=True, paged_slots=2,
                                    pool_blocks=8))
    rec = _Recorder()
    rec.install(ex)
    rng = random.Random(11)
    prompts = [[rng.randrange(2, 256) for _ in range(9)]
               for _ in range(4)]
    rid_prompt = {}
    for wave in (0, 1):
        for p in prompts:
            ex.stats["offered"] += 1
            rid = ex.queue.submit(p, 6)
            assert rid is not None
            rid_prompt[rid] = tuple(p)
        ex._stop_requested = False
        ex.serve_loop(stop_when=lambda: True)
    kv = ex.kv_stats()
    assert ex.stats["served"] == 8
    assert kv["evictions"] > 0, kv               # pressure really evicted
    assert kv["active"] == 0, kv
    # Re-admissions (same prompt, wave 2) reproduced wave-1 streams.
    by_prompt = {}
    for rid, stream in sorted(rec.streams.items()):
        by_prompt.setdefault(rid_prompt[rid], []).append(stream)
    for p, gens in by_prompt.items():
        assert len(gens) == 2 and gens[0] == gens[1], p
    ex.close()
    hvd.shutdown()


def test_loadgen_paged_report_carries_kv_section(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_SERVE_PAGED", "1")
    report, _ = _run_loadgen_inproc(tmp_path, [
        "--requests", "12", "--duration", "3", "--rate", "50",
        "--max-new-tokens", "4", "--prompt-tokens", "6",
        "--prompt-pool", "3"])
    assert report["served"] == 12
    kv = report["kv"]
    assert kv is not None and kv["active"] == 0
    assert kv["prefix_hits"] > 0                 # repeated-prompt pool
    assert report["max_concurrent_seqs"] >= 1
    assert report["config"]["paged"] is True


# --- the 4-rank chaos acceptance battery ------------------------------------
@pytest.mark.slow
def test_serving_chaos_shrink_4rank():
    """ISSUE 9 acceptance: chaos SIGKILLs rank 2 mid-serve (global
    collective index 11, ~16 requests in flight); the 4-rank world
    shrinks to 3, every survivor completes every admitted in-flight
    request (asserted in-battery), accounting balances with bounded
    shed, and a post-shrink hopeless-SLO burst is shed at admission
    without ever being prefilled.  Slow tier: the paged chaos battery
    below rides the same 4->3 shrink machinery (plus paged-KV checks)
    and stays in tier-1."""
    outputs = _run_world(4, "serving", timeout=360.0,
                         expected_rcs={2: -signal.SIGKILL})
    assert "shrink at step" in outputs[0], outputs[0]
    assert "shed at admission" in outputs[0], outputs[0]


def test_serving_paged_chaos_shrink_4rank():
    """ISSUE 14 acceptance: the paged-KV serving plane rides the same
    4->3 chaos shrink — block tables resynced from ground truth, zero
    failed admitted requests on survivors, prefix-cache hits under
    repeated prompts, and every survivor's pool passes the
    refcount-leak census after the drain."""
    outputs = _run_world(4, "serving_paged", timeout=360.0,
                         expected_rcs={2: -signal.SIGKILL})
    assert "shrink at step" in outputs[0], outputs[0]
    for r in (0, 1, 3):
        assert "kv census clean" in outputs[r], outputs[r]


def test_serving_disagg_prefill_decode_2rank():
    """ISSUE 14 disaggregation: rank 1 prefill-only, rank 0 decode;
    long prompts land on the decode replica via streamed KV blocks
    (zero local fallbacks) under the STRICT collective fingerprint —
    the split-role step loop provably never diverges on a
    collective."""
    outputs = _run_world(2, "serving_disagg", timeout=240.0)
    assert "served via streamed prefill" in outputs[0], outputs[0]
    assert "rank 1 streamed" in outputs[1], outputs[1]
