"""fleet/ — unified train+serve controller (ISSUE 20).

Three layers:

- **units** — FleetPolicy (pure logic: hysteresis, cooldown, floors,
  the oscillation bound), FleetController against a fake KV (journal
  lifecycle, failover-mid-migration resume/abort, deadline abort), and
  the WeightPublisher/WeightPuller round-trip (shards -> meta -> head
  ordering, digest verify-before-stage, torn-fetch retry, GC);
- **loadgen accounting** — the SERVE report's weight-version mix and
  staleness fields;
- **the 4-rank acceptance battery** — two live statesync worlds on one
  coordinator KV: a serving burst triggers a traffic-driven
  train->serve migration (orderly departure, peer-streamed join) AND a
  mid-run weight push lands on every serving replica at one broadcast
  plan boundary; the flight dumps replay through the hvdmc witness.
"""
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_multiprocess import _run_world  # noqa: E402
from test_statesync import _replay_witness, _witness_env  # noqa: E402

from horovod_tpu.fleet import (  # noqa: E402
    CTL_SCOPE, JOURNAL_SCOPE, PUB_SCOPE, SERVE_TO_TRAIN, TRAIN_TO_SERVE,
    FleetController, FleetPolicy, WeightPublisher, WeightPuller,
    mark_joined, poll_depart, publish_gauge)
from horovod_tpu.statesync.snapshot import (  # noqa: E402
    flatten_state, state_digest)


class FakeKV:
    """Dict-backed stand-in for the rendezvous KV client: the exact
    call surface the fleet modules use (put/put_many/get/get_scope/
    claim/delete)."""

    def __init__(self):
        self.data: dict = {}
        self.counters: dict = {}

    def put(self, scope, key, value):
        self.data[(scope, key)] = bytes(value)

    def put_many(self, records):
        for scope, key, value in records:
            self.put(scope, key, value)

    def get(self, scope, key):
        return self.data.get((scope, key))

    def get_scope(self, scope):
        return {k: v for (s, k), v in self.data.items() if s == scope}

    def claim(self, scope, key, **_kw):
        self.counters[(scope, key)] = \
            self.counters.get((scope, key), 0) + 1
        return self.counters[(scope, key)]

    def delete(self, scope, key):
        self.data.pop((scope, key), None)


# ---------------------------------------------------------------------------
# FleetPolicy
# ---------------------------------------------------------------------------
def _policy(**kw):
    base = dict(min_train=1, min_serve=1, up_shed_rate=0.05,
                up_queue_fraction=0.5, idle_queue_fraction=0.2,
                train_lag_ms=50.0, hysteresis_rounds=3,
                cooldown_rounds=0, queue_depth_limit=10)
    base.update(kw)
    return FleetPolicy(**base)


def test_policy_hysteresis_requires_consecutive_rounds():
    p = _policy(hysteresis_rounds=3)
    assert p.observe(4, 2, queue_depth=10.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is None
    # A cold round breaks the streak: the count starts over.
    assert p.observe(4, 2, queue_depth=0.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is None
    d = p.observe(4, 2, queue_depth=10.0)
    assert d is not None and d.direction == TRAIN_TO_SERVE and d.n == 1


def test_policy_shed_rate_alone_marks_serving_hot():
    p = _policy(hysteresis_rounds=1)
    d = p.observe(4, 2, shed_rate=0.10, queue_depth=0.0)
    assert d is not None and d.direction == TRAIN_TO_SERVE
    assert "shed" in d.reason


def test_policy_cooldown_silences_after_decision():
    p = _policy(hysteresis_rounds=1, cooldown_rounds=2)
    assert p.observe(4, 2, queue_depth=10.0) is not None
    # Two cooldown rounds: hot gauges are ignored entirely.
    assert p.observe(4, 2, queue_depth=10.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is not None


def test_policy_reverse_direction_needs_idle_serving():
    p = _policy(hysteresis_rounds=1)
    # Trainer drags but serving is NOT idle: no move.
    assert p.observe(4, 2, queue_depth=5.0,
                     straggler_lag_ms=200.0) is None
    d = p.observe(4, 2, queue_depth=0.0, straggler_lag_ms=200.0)
    assert d is not None and d.direction == SERVE_TO_TRAIN


def test_policy_floors_are_hard():
    p = _policy(hysteresis_rounds=1, min_train=2, min_serve=2)
    # train at the floor: the hot serving gauge proposes nothing.
    for _ in range(5):
        assert p.observe(2, 2, queue_depth=10.0) is None
    # serve at the floor: the starved trainer proposes nothing.
    for _ in range(5):
        assert p.observe(4, 2, queue_depth=0.0,
                         straggler_lag_ms=200.0) is None
    assert p.decisions == 0
    assert p.observe(3, 2, queue_depth=10.0) is not None


def test_policy_oscillation_bound_under_adversarial_gauges():
    """Migrations in any window of R rounds are bounded by
    R / (hysteresis + cooldown) no matter how the gauges flap."""
    hys, cool, rounds = 2, 3, 120
    p = _policy(hysteresis_rounds=hys, cooldown_rounds=cool)
    decisions = 0
    for i in range(rounds):
        if (i // 2) % 2 == 0:          # flap every two rounds
            d = p.observe(4, 4, queue_depth=10.0)
        else:
            d = p.observe(4, 4, queue_depth=0.0,
                          straggler_lag_ms=200.0)
        decisions += d is not None
    assert decisions == p.decisions
    assert decisions <= rounds // (hys + cool) + 1, decisions


# ---------------------------------------------------------------------------
# FleetController: journal lifecycle + failover
# ---------------------------------------------------------------------------
def _controller(kv, **kw):
    # Cooldown matters here: the gauges in the KV stay hot after a
    # migration settles, and without it the very next tick would fire
    # a second one.
    base = dict(policy=_policy(hysteresis_rounds=1, cooldown_rounds=100),
                interval_s=0.01, migrate_timeout_s=60.0)
    base.update(kw)
    ctl = FleetController(kv, **base)
    ctl.recover()
    return ctl


def test_controller_full_migration_lifecycle():
    kv = FakeKV()
    ctl = _controller(kv)
    publish_gauge(kv, "train", 4, straggler_lag_ms=0.0)
    publish_gauge(kv, "serve", 2, shed_rate=0.0, queue_depth=10.0)
    rec = ctl.tick()
    assert rec is not None and rec["state"] == "departing"
    assert rec["direction"] == TRAIN_TO_SERVE and rec["rank"] == 3
    # The directive is addressed to the donor world's highest rank.
    directive = poll_depart(kv, "train", 3)
    assert directive is not None and directive["mid"] == rec["mid"]
    assert poll_depart(kv, "train", 2) is None
    # One move settles before the next is considered.
    assert ctl.tick() is None
    mark_joined(kv, rec["mid"], rank=2, size=3)
    ctl.tick()
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert journal["state"] == "done"
    assert poll_depart(kv, "train", 3) is None   # directive withdrawn
    # A closed migration leaves nothing in the actuation scope.
    assert kv.get(CTL_SCOPE, f"joined:{rec['mid']}") is None
    assert ctl.stats["completed"] == 1 and not ctl.open


def test_controller_deadline_aborts_wedged_migration():
    """Deadline expiry first only REQUESTS the abort: the directive is
    withdrawn and the journal moves to 'aborting' (the donor may have
    already consumed the directive); silence through the grace window
    finalises it."""
    kv = FakeKV()
    ctl = _controller(kv, migrate_timeout_s=0.0)
    publish_gauge(kv, "train", 4)
    publish_gauge(kv, "serve", 2, queue_depth=10.0)
    rec = ctl.tick()
    assert rec is not None
    ctl.tick()                          # past the (zero) deadline
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert journal["state"] == "aborting"
    assert poll_depart(kv, "train", 3) is None   # directive withdrawn
    assert ctl.stats["aborted"] == 0
    assert rec["mid"] in ctl.open       # still watching for a late join
    ctl.tick()                          # past the (zero) abort grace
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert journal["state"] == "aborted"
    assert ctl.stats["aborted"] == 1 and not ctl.open


def test_controller_abort_request_reconciles_late_join():
    """The deadline-abort race the journal must not lie about: the
    donor consumed the directive just before the controller withdrew
    it, so the rank really departs and its joined mark lands inside the
    abort grace.  The record reconciles to done — an 'aborted' journal
    here would leak the joined record and let the policy double-shrink
    the donor."""
    kv = FakeKV()
    ctl = _controller(kv, migrate_timeout_s=0.0)
    publish_gauge(kv, "train", 4)
    publish_gauge(kv, "serve", 2, queue_depth=10.0)
    rec = ctl.tick()
    assert rec is not None
    ctl.tick()                          # deadline -> aborting
    assert json.loads(kv.get(
        JOURNAL_SCOPE, f"mig:{rec['mid']}"))["state"] == "aborting"
    mark_joined(kv, rec["mid"], rank=2, size=3)   # the late arrival
    ctl.tick()
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert journal["state"] == "done"
    assert "reconciled" in journal["why"]
    assert ctl.stats["completed"] == 1 and ctl.stats["aborted"] == 0
    assert kv.get(CTL_SCOPE, f"joined:{rec['mid']}") is None
    assert not ctl.open


def test_controller_directive_uses_membership_size_over_stale_gauge():
    """The donor gauge says 4 ranks but the statesync membership record
    (refreshed at every world transition) says the world already shrank
    to 3: the directive must address rank 2, not the nonexistent rank 3
    (which would wedge until the deadline abort)."""
    kv = FakeKV()
    ctl = _controller(kv)
    kv.put("statesync", "train",
           json.dumps({"epoch": "e1", "size": 3, "seq": 7}).encode())
    publish_gauge(kv, "train", 4)       # stale: published pre-shrink
    publish_gauge(kv, "serve", 2, queue_depth=10.0)
    rec = ctl.tick()
    assert rec is not None and rec["rank"] == 2
    assert poll_depart(kv, "train", 2) is not None
    assert poll_depart(kv, "train", 3) is None


def test_controller_failover_resumes_departing_migration():
    """The crash window AFTER the directive was published: a successor
    adopts the journal record under its own claimed epoch and keeps
    waiting for the mover's joined mark."""
    kv = FakeKV()
    a = _controller(kv)
    publish_gauge(kv, "train", 4)
    publish_gauge(kv, "serve", 2, queue_depth=10.0)
    rec = a.tick()
    assert rec is not None              # journal=departing, directive up
    b = _controller(kv)                 # controller A dies; B recovers
    assert b.epoch > a.epoch
    assert b.stats["resumed"] == 1 and rec["mid"] in b.open
    adopted = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert adopted["state"] == "departing"
    assert adopted["epoch"] == b.epoch
    # The mover (possibly mid-join through the whole failover) arrives:
    # B closes the record it never opened.
    mark_joined(kv, rec["mid"], rank=2, size=3)
    b.tick()
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert journal["state"] == "done" and b.stats["completed"] == 1


def test_controller_failover_aborts_planned_migration():
    """The crash window BETWEEN journal(planned) and the directive: no
    rank can be acting on the record, so the successor aborts it."""
    kv = FakeKV()
    a = _controller(kv)
    mid = kv.claim(JOURNAL_SCOPE, "seq")
    kv.put(JOURNAL_SCOPE, f"mig:{mid}", json.dumps(
        {"mid": mid, "direction": TRAIN_TO_SERVE, "world": "train",
         "rank": 3, "state": "planned", "epoch": a.epoch,
         "ts": 0.0, "deadline": 1e18}).encode())
    b = _controller(kv)
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{mid}"))
    assert journal["state"] == "aborted"
    assert "failover" in journal["why"]
    assert b.stats["aborted"] == 1 and not b.open
    assert poll_depart(kv, "train", 3) is None


# ---------------------------------------------------------------------------
# WeightPublisher / WeightPuller round-trip
# ---------------------------------------------------------------------------
def _pub_tree(n=24, fill=1.0):
    return {"params": {"w": np.full(n, fill, np.float32)}}


def _drive(pub):
    """Run the publisher's queued work synchronously (no thread)."""
    while pub._work:
        version, step, image = pub._work.pop(0)
        pub._publish(version, step, image)


def test_publish_pull_roundtrip_with_digest_verify():
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=2, chunk_bytes=16, keep=2)
    assert pub.maybe_publish(1, _pub_tree()) is None   # off-cadence
    assert pub.maybe_publish(2, _pub_tree(fill=2.0)) == 1
    _drive(pub)
    meta = json.loads(kv.get(PUB_SCOPE, "meta:1"))
    assert meta["shards"] > 1                          # really chunked
    assert kv.get(PUB_SCOPE, "head") == b"1"
    staged = []
    pul = WeightPuller(kv, lambda v, img, m: staged.append((v, img, m)))
    assert pul.poll_once() == 1
    assert pul.poll_once() is None                     # no news
    (v, img, m), = staged
    assert v == 1 and m == meta
    assert state_digest(img) == meta["digest"]
    tree = _pub_tree(fill=2.0)
    assert bytes(flatten_state(tree)) == bytes(img)
    assert pul.pulled == 1 and pul.verify_failures == 0


def test_puller_rejects_corrupt_shard_before_staging():
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=1, chunk_bytes=16, keep=2)
    pub.maybe_publish(1, _pub_tree())
    _drive(pub)
    corrupt = bytearray(kv.get(PUB_SCOPE, "shard:1.0"))
    corrupt[0] ^= 0xFF
    kv.put(PUB_SCOPE, "shard:1.0", bytes(corrupt))
    staged = []
    pul = WeightPuller(kv, lambda *a: staged.append(a))
    assert pul.poll_once() is None
    assert pul.verify_failures == 1 and staged == []
    assert pul.seen == 0               # will retry, never staged


def test_puller_retries_torn_fetch():
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=1, chunk_bytes=16, keep=2)
    pub.maybe_publish(1, _pub_tree())
    _drive(pub)
    shard = kv.get(PUB_SCOPE, "shard:1.1")
    kv.delete(PUB_SCOPE, "shard:1.1")  # head visible, shard not yet
    staged = []
    pul = WeightPuller(kv, lambda *a: staged.append(a))
    assert pul.poll_once() is None
    assert pul.verify_failures == 0 and staged == []
    kv.put(PUB_SCOPE, "shard:1.1", shard)
    assert pul.poll_once() == 1 and len(staged) == 1


def test_publisher_gc_keeps_newest_versions():
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=1, chunk_bytes=16, keep=2)
    for step in range(1, 4):
        # Drive each version through: the pending slot coalesces, so
        # only versions that actually commit exercise the GC.
        pub.maybe_publish(step, _pub_tree(fill=float(step)))
        _drive(pub)
    assert kv.get(PUB_SCOPE, "head") == b"3"
    assert kv.get(PUB_SCOPE, "meta:1") is None
    assert not [k for k in kv.get_scope(PUB_SCOPE)
                if k.startswith("shard:1.")]
    for v in (2, 3):
        meta = json.loads(kv.get(PUB_SCOPE, f"meta:{v}"))
        assert all(kv.get(PUB_SCOPE, f"shard:{v}.{i}") is not None
                   for i in range(meta["shards"]))


def test_publisher_pending_queue_is_bounded_and_coalesces():
    """The hand-off to the publisher thread holds AT MOST ONE pending
    image: KV commits running slower than the publish cadence must not
    accumulate full flattened param images on the trainer host.  A
    superseded pending version is simply never published — pullers only
    want the newest."""
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=1, chunk_bytes=16, keep=2)
    for step in range(1, 4):            # thread not started: all pend
        pub.maybe_publish(step, _pub_tree(fill=float(step)))
    assert len(pub._work) == 1          # bounded: newest image only
    assert pub.coalesced == 2
    _drive(pub)
    assert kv.get(PUB_SCOPE, "head") == b"3"
    assert pub.published == 1           # v1/v2 were never committed
    assert kv.get(PUB_SCOPE, "meta:1") is None
    assert kv.get(PUB_SCOPE, "meta:2") is None


def test_puller_stage_refusal_keeps_watermark_and_retries():
    """A stage callback returning False (the replica's staging window
    is full) leaves the puller's watermark untouched: the version is
    delayed, never dropped — the next poll offers the then-current head
    again."""
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=1, chunk_bytes=16, keep=2)
    pub.maybe_publish(1, _pub_tree())
    _drive(pub)
    staged = []
    accept = [False]
    pul = WeightPuller(
        kv, lambda v, img, m: staged.append((v, img)) or accept[0])
    assert pul.poll_once() is None      # refused: window full
    assert pul.seen == 0 and pul.pulled == 0
    accept[0] = True
    assert pul.poll_once() == 1         # retried, now staged
    assert pul.seen == 1 and pul.pulled == 1
    assert [v for v, _ in staged] == [1, 1]


# ---------------------------------------------------------------------------
# --fleet runtime wiring (fleet/wiring.py)
# ---------------------------------------------------------------------------
def test_fleet_wiring_gates_on_flag_and_publishes_serve_gauges(
        monkeypatch):
    """HOROVOD_FLEET off -> attach_replica is inert; on -> the replica
    gets the puller KV attached and the front gauge hook publishes
    size + queue depth + per-interval shed rate computed from the
    admission outcome counters."""
    from horovod_tpu.fleet import wiring

    class _Q:
        def depth(self):
            return 3

    class _B:
        def inflight_count(self):
            return 2

    class _Adm:
        totals = {"shed": 0, "expired": 0, "served": 0}

        def outcome_totals(self):
            return dict(self.totals)

    class _Ex:
        size = 2
        queue = _Q()
        batcher = _B()

        def __init__(self):
            self.admission = _Adm()
            self.attached = None

        def attach_fleet(self, kv):
            self.attached = kv

    ex = _Ex()
    monkeypatch.delenv("HOROVOD_FLEET", raising=False)
    assert wiring.attach_replica(ex) is None       # flag off: inert
    assert ex.attached is None
    monkeypatch.setenv("HOROVOD_FLEET", "1")
    kv = FakeKV()
    monkeypatch.setattr(wiring, "_fleet_kv", lambda: kv)
    rt = wiring.attach_replica(ex)
    assert rt is not None and ex.attached is kv
    ex.admission.totals = {"shed": 2, "expired": 1, "served": 7}
    ex._fleet_gauge(ex)
    gauge = json.loads(kv.get("fleet.gauges", "serve"))
    assert gauge["size"] == 2
    assert gauge["queue_depth"] == 5.0             # queued + in-flight
    assert gauge["shed_rate"] == pytest.approx(0.3)
    # No controller/publisher on the serving side to tear down.
    assert rt.controller is None and rt.publisher is None
    rt.close()


# ---------------------------------------------------------------------------
# replica staging + boundary swap (unit level: no serving world)
# ---------------------------------------------------------------------------
def _bare_replica():
    """A ReplicaExecutor skeleton with exactly the state the fleet
    staging/swap path touches — no serving world, no threads."""
    import threading

    from horovod_tpu.serving.replica import ReplicaExecutor

    ex = object.__new__(ReplicaExecutor)
    ex._fleet_lock = threading.Lock()
    ex._fleet_staged = {}
    ex._fleet_reported = set()
    ex.weight_version = 0
    ex._weight_step = 0
    ex._step = 0
    ex.stats = {"weight_swaps": []}
    ex.params = {"w": np.zeros(6, np.float32)}
    return ex


def test_replica_swaps_exactly_the_scheduled_version():
    """The boundary swap applies EXACTLY the version the front
    broadcast — never "newest staged locally", which can differ across
    ranks when a puller staged a newer image after the completions
    exchange (mixed weights inside one sharded replica group)."""
    ex = _bare_replica()
    trees = {v: {"w": np.full(6, float(v), np.float32)}
             for v in (1, 2, 3)}
    ex._fleet_staged = {v: (trees[v], 10 * v, v) for v in (1, 2, 3)}
    ex._fleet_swap(2)                   # v3 staged, but 2 is scheduled
    assert ex.weight_version == 2 and ex._weight_step == 20
    assert np.allclose(np.asarray(ex.params["w"]), 2.0)
    # Superseded v1 pruned at swap time; newer v3 stays staged.
    assert ex._fleet_staged_versions() == (3,)
    ex._fleet_swap(3)
    assert ex.weight_version == 3
    assert ex._fleet_staged_versions() == ()
    ex._fleet_swap(9)                   # not staged (local restart):
    assert ex.weight_version == 3       # keep serving, no crash
    assert [s["version"] for s in ex.stats["weight_swaps"]] == [2, 3]


def test_replica_stage_window_evicts_unreported_refuses_reported():
    """The staging window is bounded by _FLEET_STAGE_CAP.  At the cap,
    a version never reported in a completions exchange is evicted for
    a newer one (the front cannot have scheduled what it never saw —
    and while the serve loop pauses for a grow resync, refusal would
    wedge the group on versions the publisher GCs).  Once every staged
    version HAS been reported the callback refuses (False -> the
    puller retries): a reported version may be scheduled, so only the
    swap path may drop it."""
    ex = _bare_replica()
    image = bytes(flatten_state({"params": ex.params}))
    cap = ex._FLEET_STAGE_CAP
    for v in range(1, cap + 1):        # serve loop paused: no reports
        assert ex._fleet_stage(v, image, {"step": v, "digest": v})
    # Full of UNREPORTED versions: the oldest is evicted, not refused.
    assert ex._fleet_stage(cap + 1, image, {"step": 9, "digest": 9})
    assert ex._fleet_staged_versions() == tuple(range(2, cap + 2))
    # That call reported the window: now every slot is load-bearing.
    assert ex._fleet_stage(cap + 2, image, {"step": 9, "digest": 9}) \
        is False
    assert ex._fleet_staged_versions() == tuple(range(2, cap + 2))
    # Duplicates and stale versions report success without staging.
    assert ex._fleet_stage(cap, image, {"step": 9, "digest": 9}) is True
    ex.weight_version = 2
    assert ex._fleet_stage(2, image, {"step": 9, "digest": 9}) is True
    # The swap path is what frees a reported window.
    ex._fleet_swap(cap + 1)
    assert ex._fleet_staged_versions() == ()
    assert ex._fleet_stage(cap + 2, image, {"step": 9, "digest": 9})


# ---------------------------------------------------------------------------
# loadgen staleness accounting
# ---------------------------------------------------------------------------
def test_loadgen_weights_report_versions_and_staleness():
    from horovod_tpu.serving.loadgen import _weights_report

    class _Ex:
        weight_version = 2
        completed = {
            1: {"weights": 1, "weights_stale_steps": 0},
            2: {"weights": 1, "weights_stale_steps": 5},
            3: {"weights": 2, "weights_stale_steps": 3},
        }
        stats = {"weight_swaps": [
            {"version": 1, "step": 4, "digest": 7, "at": 0.0},
            {"version": 2, "step": 9, "digest": 8, "at": 1.0},
        ]}

    rep = _weights_report(_Ex())
    assert rep["final_version"] == 2
    assert rep["versions"] == {"1": 2, "2": 1}
    assert rep["max_staleness_steps"] == 5
    assert rep["swaps"] == [{"version": 1, "step": 4},
                            {"version": 2, "step": 9}]


# ---------------------------------------------------------------------------
# the 4-rank acceptance battery
# ---------------------------------------------------------------------------
def test_fleet_battery_4rank():
    """ISSUE 20 acceptance: launch ranks 0-2 train (world size 3),
    launch rank 3 serves (world size 1) — both statesync worlds on ONE
    coordinator KV (HOROVOD_STATESYNC_WORLD namespacing).  The serving
    burst drives the controller's policy over its hysteresis window;
    rank 2 departs the training world at a statesync boundary (no
    RanksFailedError anywhere), joins the serving world via
    peer-streamed state, and the journal record closes as done.  The
    trainer's published snapshots roll out to BOTH serving replicas at
    one broadcast plan boundary (digest-asserted against the live
    params on each), with zero failed admitted requests and goodput
    phases recorded.  The flight dumps replay through the hvdmc
    witness against the fleet + membership models."""
    outputs = _run_world(4, "fleet", timeout=360.0,
                         extra_env=_witness_env("fleet", 4))
    assert "fleet front:" in outputs[3], outputs[3]
    assert "across 1->2" in outputs[3], outputs[3]
    assert "fleet mover: joined serving" in outputs[2], outputs[2]
    assert "digest verified" in outputs[2], outputs[2]
    for r in (0, 1):
        assert "no RanksFailedError anywhere" in outputs[r], outputs[r]
    assert "migration journal closed" in outputs[0], outputs[0]
    _replay_witness(outputs, {"fleet-migrate", "fleet-depart",
                              "fleet-join", "fleet-publish",
                              "fleet-pull", "fleet-swap", "departed"})
