"""fleet/ — unified train+serve controller (ISSUE 20).

Three layers:

- **units** — FleetPolicy (pure logic: hysteresis, cooldown, floors,
  the oscillation bound), FleetController against a fake KV (journal
  lifecycle, failover-mid-migration resume/abort, deadline abort), and
  the WeightPublisher/WeightPuller round-trip (shards -> meta -> head
  ordering, digest verify-before-stage, torn-fetch retry, GC);
- **loadgen accounting** — the SERVE report's weight-version mix and
  staleness fields;
- **the 4-rank acceptance battery** — two live statesync worlds on one
  coordinator KV: a serving burst triggers a traffic-driven
  train->serve migration (orderly departure, peer-streamed join) AND a
  mid-run weight push lands on every serving replica at one broadcast
  plan boundary; the flight dumps replay through the hvdmc witness.
"""
import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_multiprocess import _run_world  # noqa: E402
from test_statesync import _replay_witness, _witness_env  # noqa: E402

from horovod_tpu.fleet import (  # noqa: E402
    CTL_SCOPE, JOURNAL_SCOPE, PUB_SCOPE, SERVE_TO_TRAIN, TRAIN_TO_SERVE,
    FleetController, FleetPolicy, WeightPublisher, WeightPuller,
    mark_joined, poll_depart, publish_gauge)
from horovod_tpu.statesync.snapshot import (  # noqa: E402
    flatten_state, state_digest)


class FakeKV:
    """Dict-backed stand-in for the rendezvous KV client: the exact
    call surface the fleet modules use (put/put_many/get/get_scope/
    claim/delete)."""

    def __init__(self):
        self.data: dict = {}
        self.counters: dict = {}

    def put(self, scope, key, value):
        self.data[(scope, key)] = bytes(value)

    def put_many(self, records):
        for scope, key, value in records:
            self.put(scope, key, value)

    def get(self, scope, key):
        return self.data.get((scope, key))

    def get_scope(self, scope):
        return {k: v for (s, k), v in self.data.items() if s == scope}

    def claim(self, scope, key, **_kw):
        self.counters[(scope, key)] = \
            self.counters.get((scope, key), 0) + 1
        return self.counters[(scope, key)]

    def delete(self, scope, key):
        self.data.pop((scope, key), None)


# ---------------------------------------------------------------------------
# FleetPolicy
# ---------------------------------------------------------------------------
def _policy(**kw):
    base = dict(min_train=1, min_serve=1, up_shed_rate=0.05,
                up_queue_fraction=0.5, idle_queue_fraction=0.2,
                train_lag_ms=50.0, hysteresis_rounds=3,
                cooldown_rounds=0, queue_depth_limit=10)
    base.update(kw)
    return FleetPolicy(**base)


def test_policy_hysteresis_requires_consecutive_rounds():
    p = _policy(hysteresis_rounds=3)
    assert p.observe(4, 2, queue_depth=10.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is None
    # A cold round breaks the streak: the count starts over.
    assert p.observe(4, 2, queue_depth=0.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is None
    d = p.observe(4, 2, queue_depth=10.0)
    assert d is not None and d.direction == TRAIN_TO_SERVE and d.n == 1


def test_policy_shed_rate_alone_marks_serving_hot():
    p = _policy(hysteresis_rounds=1)
    d = p.observe(4, 2, shed_rate=0.10, queue_depth=0.0)
    assert d is not None and d.direction == TRAIN_TO_SERVE
    assert "shed" in d.reason


def test_policy_cooldown_silences_after_decision():
    p = _policy(hysteresis_rounds=1, cooldown_rounds=2)
    assert p.observe(4, 2, queue_depth=10.0) is not None
    # Two cooldown rounds: hot gauges are ignored entirely.
    assert p.observe(4, 2, queue_depth=10.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is None
    assert p.observe(4, 2, queue_depth=10.0) is not None


def test_policy_reverse_direction_needs_idle_serving():
    p = _policy(hysteresis_rounds=1)
    # Trainer drags but serving is NOT idle: no move.
    assert p.observe(4, 2, queue_depth=5.0,
                     straggler_lag_ms=200.0) is None
    d = p.observe(4, 2, queue_depth=0.0, straggler_lag_ms=200.0)
    assert d is not None and d.direction == SERVE_TO_TRAIN


def test_policy_floors_are_hard():
    p = _policy(hysteresis_rounds=1, min_train=2, min_serve=2)
    # train at the floor: the hot serving gauge proposes nothing.
    for _ in range(5):
        assert p.observe(2, 2, queue_depth=10.0) is None
    # serve at the floor: the starved trainer proposes nothing.
    for _ in range(5):
        assert p.observe(4, 2, queue_depth=0.0,
                         straggler_lag_ms=200.0) is None
    assert p.decisions == 0
    assert p.observe(3, 2, queue_depth=10.0) is not None


def test_policy_oscillation_bound_under_adversarial_gauges():
    """Migrations in any window of R rounds are bounded by
    R / (hysteresis + cooldown) no matter how the gauges flap."""
    hys, cool, rounds = 2, 3, 120
    p = _policy(hysteresis_rounds=hys, cooldown_rounds=cool)
    decisions = 0
    for i in range(rounds):
        if (i // 2) % 2 == 0:          # flap every two rounds
            d = p.observe(4, 4, queue_depth=10.0)
        else:
            d = p.observe(4, 4, queue_depth=0.0,
                          straggler_lag_ms=200.0)
        decisions += d is not None
    assert decisions == p.decisions
    assert decisions <= rounds // (hys + cool) + 1, decisions


# ---------------------------------------------------------------------------
# FleetController: journal lifecycle + failover
# ---------------------------------------------------------------------------
def _controller(kv, **kw):
    # Cooldown matters here: the gauges in the KV stay hot after a
    # migration settles, and without it the very next tick would fire
    # a second one.
    base = dict(policy=_policy(hysteresis_rounds=1, cooldown_rounds=100),
                interval_s=0.01, migrate_timeout_s=60.0)
    base.update(kw)
    ctl = FleetController(kv, **base)
    ctl.recover()
    return ctl


def test_controller_full_migration_lifecycle():
    kv = FakeKV()
    ctl = _controller(kv)
    publish_gauge(kv, "train", 4, straggler_lag_ms=0.0)
    publish_gauge(kv, "serve", 2, shed_rate=0.0, queue_depth=10.0)
    rec = ctl.tick()
    assert rec is not None and rec["state"] == "departing"
    assert rec["direction"] == TRAIN_TO_SERVE and rec["rank"] == 3
    # The directive is addressed to the donor world's highest rank.
    directive = poll_depart(kv, "train", 3)
    assert directive is not None and directive["mid"] == rec["mid"]
    assert poll_depart(kv, "train", 2) is None
    # One move settles before the next is considered.
    assert ctl.tick() is None
    mark_joined(kv, rec["mid"], rank=2, size=3)
    ctl.tick()
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert journal["state"] == "done"
    assert poll_depart(kv, "train", 3) is None   # directive withdrawn
    assert ctl.stats["completed"] == 1 and not ctl.open


def test_controller_deadline_aborts_wedged_migration():
    kv = FakeKV()
    ctl = _controller(kv, migrate_timeout_s=0.0)
    publish_gauge(kv, "train", 4)
    publish_gauge(kv, "serve", 2, queue_depth=10.0)
    rec = ctl.tick()
    assert rec is not None
    ctl.tick()                          # past the (zero) deadline
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert journal["state"] == "aborted"
    assert poll_depart(kv, "train", 3) is None   # directive withdrawn
    assert ctl.stats["aborted"] == 1


def test_controller_failover_resumes_departing_migration():
    """The crash window AFTER the directive was published: a successor
    adopts the journal record under its own claimed epoch and keeps
    waiting for the mover's joined mark."""
    kv = FakeKV()
    a = _controller(kv)
    publish_gauge(kv, "train", 4)
    publish_gauge(kv, "serve", 2, queue_depth=10.0)
    rec = a.tick()
    assert rec is not None              # journal=departing, directive up
    b = _controller(kv)                 # controller A dies; B recovers
    assert b.epoch > a.epoch
    assert b.stats["resumed"] == 1 and rec["mid"] in b.open
    adopted = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert adopted["state"] == "departing"
    assert adopted["epoch"] == b.epoch
    # The mover (possibly mid-join through the whole failover) arrives:
    # B closes the record it never opened.
    mark_joined(kv, rec["mid"], rank=2, size=3)
    b.tick()
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{rec['mid']}"))
    assert journal["state"] == "done" and b.stats["completed"] == 1


def test_controller_failover_aborts_planned_migration():
    """The crash window BETWEEN journal(planned) and the directive: no
    rank can be acting on the record, so the successor aborts it."""
    kv = FakeKV()
    a = _controller(kv)
    mid = kv.claim(JOURNAL_SCOPE, "seq")
    kv.put(JOURNAL_SCOPE, f"mig:{mid}", json.dumps(
        {"mid": mid, "direction": TRAIN_TO_SERVE, "world": "train",
         "rank": 3, "state": "planned", "epoch": a.epoch,
         "ts": 0.0, "deadline": 1e18}).encode())
    b = _controller(kv)
    journal = json.loads(kv.get(JOURNAL_SCOPE, f"mig:{mid}"))
    assert journal["state"] == "aborted"
    assert "failover" in journal["why"]
    assert b.stats["aborted"] == 1 and not b.open
    assert poll_depart(kv, "train", 3) is None


# ---------------------------------------------------------------------------
# WeightPublisher / WeightPuller round-trip
# ---------------------------------------------------------------------------
def _pub_tree(n=24, fill=1.0):
    return {"params": {"w": np.full(n, fill, np.float32)}}


def _drive(pub):
    """Run the publisher's queued work synchronously (no thread)."""
    while pub._work:
        version, step, image = pub._work.pop(0)
        pub._publish(version, step, image)


def test_publish_pull_roundtrip_with_digest_verify():
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=2, chunk_bytes=16, keep=2)
    assert pub.maybe_publish(1, _pub_tree()) is None   # off-cadence
    assert pub.maybe_publish(2, _pub_tree(fill=2.0)) == 1
    _drive(pub)
    meta = json.loads(kv.get(PUB_SCOPE, "meta:1"))
    assert meta["shards"] > 1                          # really chunked
    assert kv.get(PUB_SCOPE, "head") == b"1"
    staged = []
    pul = WeightPuller(kv, lambda v, img, m: staged.append((v, img, m)))
    assert pul.poll_once() == 1
    assert pul.poll_once() is None                     # no news
    (v, img, m), = staged
    assert v == 1 and m == meta
    assert state_digest(img) == meta["digest"]
    tree = _pub_tree(fill=2.0)
    assert bytes(flatten_state(tree)) == bytes(img)
    assert pul.pulled == 1 and pul.verify_failures == 0


def test_puller_rejects_corrupt_shard_before_staging():
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=1, chunk_bytes=16, keep=2)
    pub.maybe_publish(1, _pub_tree())
    _drive(pub)
    corrupt = bytearray(kv.get(PUB_SCOPE, "shard:1.0"))
    corrupt[0] ^= 0xFF
    kv.put(PUB_SCOPE, "shard:1.0", bytes(corrupt))
    staged = []
    pul = WeightPuller(kv, lambda *a: staged.append(a))
    assert pul.poll_once() is None
    assert pul.verify_failures == 1 and staged == []
    assert pul.seen == 0               # will retry, never staged


def test_puller_retries_torn_fetch():
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=1, chunk_bytes=16, keep=2)
    pub.maybe_publish(1, _pub_tree())
    _drive(pub)
    shard = kv.get(PUB_SCOPE, "shard:1.1")
    kv.delete(PUB_SCOPE, "shard:1.1")  # head visible, shard not yet
    staged = []
    pul = WeightPuller(kv, lambda *a: staged.append(a))
    assert pul.poll_once() is None
    assert pul.verify_failures == 0 and staged == []
    kv.put(PUB_SCOPE, "shard:1.1", shard)
    assert pul.poll_once() == 1 and len(staged) == 1


def test_publisher_gc_keeps_newest_versions():
    kv = FakeKV()
    pub = WeightPublisher(kv, publish_steps=1, chunk_bytes=16, keep=2)
    for step in range(1, 4):
        pub.maybe_publish(step, _pub_tree(fill=float(step)))
    _drive(pub)
    assert kv.get(PUB_SCOPE, "head") == b"3"
    assert kv.get(PUB_SCOPE, "meta:1") is None
    assert not [k for k in kv.get_scope(PUB_SCOPE)
                if k.startswith("shard:1.")]
    for v in (2, 3):
        meta = json.loads(kv.get(PUB_SCOPE, f"meta:{v}"))
        assert all(kv.get(PUB_SCOPE, f"shard:{v}.{i}") is not None
                   for i in range(meta["shards"]))


# ---------------------------------------------------------------------------
# loadgen staleness accounting
# ---------------------------------------------------------------------------
def test_loadgen_weights_report_versions_and_staleness():
    from horovod_tpu.serving.loadgen import _weights_report

    class _Ex:
        weight_version = 2
        completed = {
            1: {"weights": 1, "weights_stale_steps": 0},
            2: {"weights": 1, "weights_stale_steps": 5},
            3: {"weights": 2, "weights_stale_steps": 3},
        }
        stats = {"weight_swaps": [
            {"version": 1, "step": 4, "digest": 7, "at": 0.0},
            {"version": 2, "step": 9, "digest": 8, "at": 1.0},
        ]}

    rep = _weights_report(_Ex())
    assert rep["final_version"] == 2
    assert rep["versions"] == {"1": 2, "2": 1}
    assert rep["max_staleness_steps"] == 5
    assert rep["swaps"] == [{"version": 1, "step": 4},
                            {"version": 2, "step": 9}]


# ---------------------------------------------------------------------------
# the 4-rank acceptance battery
# ---------------------------------------------------------------------------
def test_fleet_battery_4rank():
    """ISSUE 20 acceptance: launch ranks 0-2 train (world size 3),
    launch rank 3 serves (world size 1) — both statesync worlds on ONE
    coordinator KV (HOROVOD_STATESYNC_WORLD namespacing).  The serving
    burst drives the controller's policy over its hysteresis window;
    rank 2 departs the training world at a statesync boundary (no
    RanksFailedError anywhere), joins the serving world via
    peer-streamed state, and the journal record closes as done.  The
    trainer's published snapshots roll out to BOTH serving replicas at
    one broadcast plan boundary (digest-asserted against the live
    params on each), with zero failed admitted requests and goodput
    phases recorded.  The flight dumps replay through the hvdmc
    witness against the fleet + membership models."""
    outputs = _run_world(4, "fleet", timeout=360.0,
                         extra_env=_witness_env("fleet", 4))
    assert "fleet front:" in outputs[3], outputs[3]
    assert "across 1->2" in outputs[3], outputs[3]
    assert "fleet mover: joined serving" in outputs[2], outputs[2]
    assert "digest verified" in outputs[2], outputs[2]
    for r in (0, 1):
        assert "no RanksFailedError anywhere" in outputs[r], outputs[r]
    assert "migration journal closed" in outputs[0], outputs[0]
    _replay_witness(outputs, {"fleet-migrate", "fleet-depart",
                              "fleet-join", "fleet-publish",
                              "fleet-pull", "fleet-swap", "departed"})
