"""LightningModule-protocol model for LightningEstimator tests.

Module-level (not defined inside the test) because torch.save pickles the
class by reference and the spawned estimator workers must import it.
Deliberately torch-only: the point of the estimator's design is that the
protocol — training_step / configure_optimizers / on_train_epoch_end —
needs no pytorch_lightning import; a real LightningModule provides the
same surface.
"""
import torch


class LinearLit(torch.nn.Module):
    def __init__(self, in_features: int = 3):
        super().__init__()
        self.net = torch.nn.Linear(in_features, 1)
        self.epochs_ended = 0

    def forward(self, x):
        return self.net(x)[..., 0]

    def training_step(self, batch, batch_idx):
        x, y = batch
        loss = torch.nn.functional.mse_loss(self(x), y)
        return {"loss": loss}

    def configure_optimizers(self):
        # The ([optimizers], [schedulers]) return shape PL also allows.
        opt = torch.optim.SGD(self.parameters(), lr=0.2)
        sched = torch.optim.lr_scheduler.StepLR(opt, step_size=10,
                                                gamma=0.5)
        return [opt], [sched]

    def on_train_epoch_end(self):
        self.epochs_ended += 1


class DictLit(LinearLit):
    """PL's most common configure_optimizers shape: a config dict with a
    {"scheduler": ...} entry."""

    def configure_optimizers(self):
        opt = torch.optim.SGD(self.parameters(), lr=0.2)
        sched = torch.optim.lr_scheduler.StepLR(opt, step_size=10,
                                                gamma=0.5)
        return {"optimizer": opt,
                "lr_scheduler": {"scheduler": sched, "interval": "epoch"}}


class FreezeAfterOneLit(LinearLit):
    """Scheduler zeroes the LR after the first epoch — training must
    visibly STOP, proving the scheduler drives the optimizer that
    actually steps (schedulers bound to the pre-wrap optimizer are
    silently inert)."""

    def configure_optimizers(self):
        opt = torch.optim.SGD(self.parameters(), lr=0.2)
        sched = torch.optim.lr_scheduler.LambdaLR(
            opt, lr_lambda=lambda epoch: 0.0 if epoch >= 1 else 1.0)
        return [opt], [sched]
