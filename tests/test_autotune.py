"""Autotuner stack: Gaussian process, Bayesian optimization, the
ParameterManager sampling loop, and end-to-end parameter propagation
across a 2-process world.

Reference: horovod/common/parameter_manager.{cc,h}:42-120 +
common/optim/{bayesian_optimization,gaussian_process}.cc — the reference
scores (fusion threshold, cycle time) settings by bytes/sec and
broadcasts the winner from the coordinator.
"""
from __future__ import annotations

import numpy as np
import pytest

from horovod_tpu.common.optim.bayesian_optimization import (
    BayesianOptimization)
from horovod_tpu.common.optim.gaussian_process import GaussianProcess


def test_gaussian_process_interpolates():
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(12, 1))
    y = np.sin(3 * x[:, 0])
    gp = GaussianProcess(alpha=1e-8)
    gp.fit(x, y)
    mean, std = gp.predict(x)
    np.testing.assert_allclose(mean, y, atol=1e-3)
    assert np.all(std >= -1e-9)
    # Uncertainty grows away from the data.
    _, std_far = gp.predict(np.array([[5.0]]))
    assert std_far[0] > np.max(std) - 1e-9


def test_gaussian_process_fits_hyperparameters():
    """The length scale adapts to the data via log-marginal-likelihood
    maximization, and target normalization makes large-scale noisy
    bytes/sec targets regress correctly (VERDICT r3 item 7; reference:
    gaussian_process.cc L-BFGS hyperparameter fit)."""
    x = np.linspace(0, 1, 14)[:, None]
    # Wiggly function on a large offset/scale — mimics bytes/sec scores.
    y = 5e8 * np.sin(2 * np.pi * x[:, 0]) + 3e9
    gp = GaussianProcess(length_scale=1.0, alpha=1e-4)
    gp.fit(x, y)
    assert gp.length_scale < 0.6, gp.length_scale   # adapted down from 1.0
    assert gp.last_lml is not None and np.isfinite(gp.last_lml)
    mu, _ = gp.predict(np.array([[0.375]]))
    truth = 5e8 * np.sin(2 * np.pi * 0.375) + 3e9
    assert abs(mu[0] - truth) < 0.05 * 5e8, (mu[0], truth)

    # The fitted scale's LML beats a grossly mis-specified fixed scale.
    fixed = GaussianProcess(length_scale=8.0, alpha=1e-4, optimize=False)
    fixed.fit(x, y)
    assert gp.last_lml > fixed.last_lml


def test_gaussian_process_noisy_recovery():
    """With realistic observation noise the fitted GP still ranks the true
    optimum region above the edges (the property the autotuner relies on
    for convergence on real step-time jitter)."""
    rng = np.random.default_rng(7)
    x = rng.uniform(0, 1, size=(18, 1))
    clean = -((x[:, 0] - 0.6) ** 2) * 4e9 + 2e9
    y = clean + rng.normal(0, 2e8, size=len(x))   # 5% noise
    gp = GaussianProcess(alpha=0.8)               # the autotuner default
    gp.fit(x, y)
    mu_best, _ = gp.predict(np.array([[0.6]]))
    mu_edge, _ = gp.predict(np.array([[0.05]]))
    assert mu_best[0] > mu_edge[0]


def test_bayesian_optimization_finds_peak():
    bo = BayesianOptimization([(0.0, 1.0)], alpha=1e-4)

    def objective(x: float) -> float:
        return -(x - 0.3) ** 2

    for _ in range(20):
        (x,) = bo.suggest_next()
        assert 0.0 <= x <= 1.0
        bo.add_sample([x], objective(x))
    (best_x,), best_y = bo.best()
    assert abs(best_x - 0.3) < 0.15, (best_x, best_y)


class _FakeController:
    tensor_fusion_threshold = 64 * 1024 * 1024
    pending_tuned_params = None


def test_parameter_manager_samples_and_converges(monkeypatch, tmp_path):
    log = tmp_path / "autotune.csv"
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "2")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "3")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_LOG", str(log))

    from horovod_tpu.common.parameter_manager import ParameterManager

    ctrl = _FakeController()
    pm = ParameterManager(ctrl, active=True)
    # warmup sample (2 steps) + 3 scored samples (2 steps each)
    for _ in range(2 * 4):
        pm.observe(["t"], 1 << 20)
    assert pm._done
    threshold, cycle = ctrl.pending_tuned_params
    assert (1 << 20) <= threshold <= (1 << 28)
    assert 1.0 <= cycle <= 25.0
    lines = log.read_text().strip().splitlines()
    assert lines[0].startswith("timestamp")
    # header + the scored samples + the converged row
    assert len(lines) == 1 + 3 + 1
    assert lines[-1].endswith(",converged")
    assert all(line.endswith(",sample") for line in lines[1:-1])


def test_parameter_manager_inactive_never_proposes():
    from horovod_tpu.common.parameter_manager import ParameterManager

    ctrl = _FakeController()
    pm = ParameterManager(ctrl, active=False)
    for _ in range(100):
        pm.observe(["t"], 1 << 20)
    assert ctrl.pending_tuned_params is None


def test_autotune_propagates_across_ranks():
    """2-process world with HOROVOD_AUTOTUNE=1: the coordinator's tuned
    (threshold, cycle) must reach the non-coordinator through the
    ResponseList tuned_* fields (reference: controller.cc:39-53)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_multiprocess import _run_world
    _run_world(2, "autotune", timeout=120.0)


def test_algo_sweep_propagates_across_ranks():
    """2-process world with the pipeline sweep on: the coordinator's
    algo x tree-threshold winner must reach every rank's live
    TcpCollectives through ResponseList.tuned_algo /
    tuned_tree_threshold, applied BEFORE dispatch (ISSUE 18)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_multiprocess import _run_world
    _run_world(2, "algotune", timeout=120.0)
