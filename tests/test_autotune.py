"""Autotuner stack: Gaussian process, Bayesian optimization, the
ParameterManager sampling loop, and end-to-end parameter propagation
across a 2-process world.

Reference: horovod/common/parameter_manager.{cc,h}:42-120 +
common/optim/{bayesian_optimization,gaussian_process}.cc — the reference
scores (fusion threshold, cycle time) settings by bytes/sec and
broadcasts the winner from the coordinator.
"""
from __future__ import annotations

import numpy as np
import pytest

from horovod_tpu.common.optim.bayesian_optimization import (
    BayesianOptimization)
from horovod_tpu.common.optim.gaussian_process import GaussianProcess


def test_gaussian_process_interpolates():
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=(12, 1))
    y = np.sin(3 * x[:, 0])
    gp = GaussianProcess(alpha=1e-8)
    gp.fit(x, y)
    mean, std = gp.predict(x)
    np.testing.assert_allclose(mean, y, atol=1e-3)
    assert np.all(std >= -1e-9)
    # Uncertainty grows away from the data.
    _, std_far = gp.predict(np.array([[5.0]]))
    assert std_far[0] > np.max(std) - 1e-9


def test_bayesian_optimization_finds_peak():
    bo = BayesianOptimization([(0.0, 1.0)], alpha=1e-4)

    def objective(x: float) -> float:
        return -(x - 0.3) ** 2

    for _ in range(20):
        (x,) = bo.suggest_next()
        assert 0.0 <= x <= 1.0
        bo.add_sample([x], objective(x))
    (best_x,), best_y = bo.best()
    assert abs(best_x - 0.3) < 0.15, (best_x, best_y)


class _FakeController:
    tensor_fusion_threshold = 64 * 1024 * 1024
    pending_tuned_params = None


def test_parameter_manager_samples_and_converges(monkeypatch, tmp_path):
    log = tmp_path / "autotune.csv"
    monkeypatch.setenv("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", "1")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", "2")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "3")
    monkeypatch.setenv("HOROVOD_AUTOTUNE_LOG", str(log))

    from horovod_tpu.common.parameter_manager import ParameterManager

    ctrl = _FakeController()
    pm = ParameterManager(ctrl, active=True)
    # warmup sample (2 steps) + 3 scored samples (2 steps each)
    for _ in range(2 * 4):
        pm.observe(["t"], 1 << 20)
    assert pm._done
    threshold, cycle = ctrl.pending_tuned_params
    assert (1 << 20) <= threshold <= (1 << 28)
    assert 1.0 <= cycle <= 25.0
    lines = log.read_text().strip().splitlines()
    assert lines[0].startswith("timestamp")
    assert len(lines) == 1 + 3        # header + the scored samples


def test_parameter_manager_inactive_never_proposes():
    from horovod_tpu.common.parameter_manager import ParameterManager

    ctrl = _FakeController()
    pm = ParameterManager(ctrl, active=False)
    for _ in range(100):
        pm.observe(["t"], 1 << 20)
    assert ctrl.pending_tuned_params is None


def test_autotune_propagates_across_ranks():
    """2-process world with HOROVOD_AUTOTUNE=1: the coordinator's tuned
    (threshold, cycle) must reach the non-coordinator through the
    ResponseList tuned_* fields (reference: controller.cc:39-53)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_multiprocess import _run_world
    _run_world(2, "autotune", timeout=120.0)
