"""Single-process (size-1) end-to-end API semantics, including the full
background thread + handle plumbing (reference test analogue:
test/parallel/test_torch.py run at np=1)."""
import numpy as np
import pytest

import horovod_tpu as hvd


@pytest.fixture(scope="module", autouse=True)
def _init():
    hvd.init()
    yield
    hvd.shutdown()


def test_rank_size():
    assert hvd.rank() == 0
    assert hvd.size() == 1
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 1
    assert hvd.is_homogeneous()


def test_allreduce_sum_identity():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = hvd.allreduce(x, op=hvd.Sum)
    np.testing.assert_array_equal(out, x)
    assert out.shape == (3, 4)


def test_allreduce_average_identity():
    x = np.ones((5,), dtype=np.float32) * 3
    out = hvd.allreduce(x, op=hvd.Average)
    np.testing.assert_allclose(out, x)


def test_allreduce_prescale_postscale():
    x = np.ones(4, dtype=np.float32)
    out = hvd.allreduce(x, op=hvd.Sum, prescale_factor=2.0,
                        postscale_factor=3.0)
    np.testing.assert_allclose(out, x * 6.0)


def test_allreduce_async_poll_synchronize():
    x = np.ones(4, dtype=np.float64)
    handle = hvd.allreduce_async(x, op=hvd.Sum, name="async0")
    out = hvd.synchronize(handle)
    assert hvd.poll(handle)
    np.testing.assert_array_equal(out, x)


def test_grouped_allreduce():
    xs = [np.full((3,), i, dtype=np.float32) for i in range(4)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Sum)
    assert len(outs) == 4
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(out, xs[i])


def test_allgather_identity():
    x = np.arange(6, dtype=np.int32).reshape(2, 3)
    out = hvd.allgather(x)
    np.testing.assert_array_equal(out, x)


def test_broadcast_identity():
    x = np.arange(5, dtype=np.float32)
    out = hvd.broadcast(x, root_rank=0)
    np.testing.assert_array_equal(out, x)


def test_alltoall_identity():
    x = np.arange(8, dtype=np.float32)
    out = hvd.alltoall(x)
    np.testing.assert_array_equal(out, x)


def test_alltoall_splits_validation():
    """Split tables are validated before any plane touches bytes
    (reference: operations.cc:1176 rejects splits inconsistent with dim 0):
    wrong length, negative entries, and sum != dim0 are structured errors,
    not silent truncation/stale reads."""
    from horovod_tpu.backend.base import CollectiveBackend
    from horovod_tpu.common.status import Status
    from horovod_tpu.common.tensor_queue import TensorTableEntry

    def resolve(splits, dim0=8, world=4):
        e = TensorTableEntry(tensor_name="t")
        e.splits = splits or []
        return CollectiveBackend.resolve_alltoall_splits(e, dim0, world)

    assert resolve([2, 2, 2, 2]) == [2, 2, 2, 2]
    assert resolve([0, 8, 0, 0]) == [0, 8, 0, 0]
    # even default when no splits given
    assert resolve(None) == [2, 2, 2, 2]
    assert isinstance(resolve([2, 2, 2]), Status)           # wrong length
    assert isinstance(resolve([2, 2, 2, -2]), Status)       # negative
    assert isinstance(resolve([2, 2, 2, 4]), Status)        # sum > dim0
    assert isinstance(resolve([1, 1, 1, 1]), Status)        # sum < dim0
    assert isinstance(resolve(None, dim0=7), Status)        # indivisible


def test_alltoall_bad_splits_structured_error():
    """End-to-end: a bad split table surfaces as a raised error through
    the public API, on whatever plane is active."""
    x = np.arange(8, dtype=np.float32)
    with pytest.raises(Exception, match="splits"):
        hvd.alltoall(x, splits=[3, 3, 3, 3])   # single rank: len != 1


def test_barrier():
    hvd.barrier()


def test_join_single():
    assert hvd.join() == 0


def test_duplicate_names_rejected():
    import horovod_tpu.core as core
    x = np.ones(1 << 12, dtype=np.float32)
    h1 = hvd.allreduce_async(x, name="dup", op=hvd.Sum)
    h2 = hvd.allreduce_async(x, name="dup", op=hvd.Sum)
    # One of them must fail with the duplicate-name error unless the first
    # already completed; accept either ordering but require both to resolve.
    s1 = h1.wait()
    s2 = h2.wait()
    assert s1.ok_p() or s2.ok_p()


def test_torch_tensor_roundtrip():
    import torch
    x = torch.arange(10, dtype=torch.float32)
    out = hvd.allreduce(x, op=hvd.Sum)
    assert isinstance(out, torch.Tensor)
    assert torch.equal(out, x)


def test_jax_array_roundtrip():
    import jax.numpy as jnp
    x = jnp.arange(10, dtype=jnp.float32)
    out = hvd.allreduce(x, op=hvd.Sum)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_broadcast_object():
    obj = {"lr": 0.1, "step": 7, "name": "resnet"}
    assert hvd.broadcast_object(obj, root_rank=0) == obj


def test_error_status_raises():
    from horovod_tpu.common.status import Status
    st = Status.precondition_error("boom")
    with pytest.raises(hvd.HorovodInternalError):
        st.raise_if_error()
