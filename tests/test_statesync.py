"""statesync/ battery (ISSUE 10): zero-downtime elastic world grow —
peer-to-peer live state streaming, preemption grace, the autoscale
policy loop, and the ring-sharded checkpoint round trip.

Process-level acceptance (mp_worker batteries under the hard SIGALRM
guard):

- 4-rank chaos battery rides 4->3->4: SIGKILL of rank 2 mid-training →
  survivors shrink with zero failed post-shrink steps → a replacement
  process joins via peer streaming (zero failed incumbent steps,
  catch-up wall bounded by ~one donor-stream, streamed state
  digest-identical to the donors' snapshot);
- SIGTERM-grace battery: the preempted rank departs with its ``bye|``
  stamp inside the grace window and survivors shrink proactively — no
  RanksFailedError anywhere;
- serving variant (slow): a joiner replica enters mid-serve, the
  loadgen report records world.grows and goodput before/during/after.

Unit level: snapshot flatten/digest/stamp semantics, the streaming
protocol over real PeerMesh channels (including resume across a donor
death and torn/corrupt-round rejection), ring-shard re-layout math and
the checkpoint round trip at changed world sizes (parity vs the
replicated optimizer), autoscale hysteresis, blacklist re-admission,
the chaos ``preempt`` action, and the HVD1007 lint rule.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_multiprocess import _run_world  # noqa: E402

from horovod_tpu.common.tcp_transport import (  # noqa: E402
    STATE_DATA, STATE_META, pack_state_frame, unpack_state_frame)
from horovod_tpu.runner.network import (  # noqa: E402
    RendezvousClient, RendezvousServer)
from horovod_tpu.statesync import (  # noqa: E402
    AutoscaleController, AutoscalePolicy, DonorServer, JoinerPuller,
    Snapshot, SnapshotStamp, StreamError, TornSnapshotError,
    concat_ring_shards, flatten_state, reshard_ring_state,
    shard_for_rank, state_digest, unflatten_state)
from horovod_tpu.statesync.stream import StreamGuard  # noqa: E402

HARD_GUARD_SECONDS = 420


@pytest.fixture(autouse=True)
def hard_timeout_guard():
    """A re-introduced membership deadlock must fail fast, not eat the
    tier-1 budget (the resilience-suite discipline)."""
    def _expired(signum, frame):
        raise TimeoutError(
            f"statesync test exceeded the {HARD_GUARD_SECONDS}s hard "
            f"guard — a blocking wait has lost its deadline")
    old = signal.signal(signal.SIGALRM, _expired)
    signal.alarm(HARD_GUARD_SECONDS)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


# ---------------------------------------------------------------------------
# Process-level acceptance batteries
# ---------------------------------------------------------------------------
def _witness_env(battery: str, size: int) -> dict:
    """A deep flight ring so the membership transitions survive the
    per-step enqueue/dispatch churn until the end-of-battery witness
    dump (mp_worker routes the dump files themselves to launch-rank-
    keyed /tmp paths); stale dumps from earlier runs are removed."""
    import glob
    for stale in glob.glob(f"/tmp/hvd_witness_{battery}{size}"
                           f".launch*.json"):
        os.unlink(stale)
    return {"HOROVOD_FLIGHT_EVENTS": "4096"}


def _replay_witness(outputs, expect_kinds):
    """ISSUE 11 acceptance: the battery's flight/event logs replay
    through the hvdmc trace witness and every observed membership
    transition exists in the model (problems == unsound spec)."""
    from horovod_tpu.analysis import hvdmc

    dumps = sorted({line.split(" ", 1)[1].strip()
                    for out in outputs for line in out.splitlines()
                    if line.startswith("WITNESS_DUMP ")})
    assert dumps, "no battery wrote a witness dump"
    report = hvdmc.witness_check(hvdmc.load_dumps(dumps))
    assert report.problems == [], "\n".join(report.problems)
    assert expect_kinds <= set(report.observed), \
        (sorted(report.observed), expect_kinds)
    return report


def test_statesync_grow_rides_4_3_4():
    """ISSUE 10 acceptance: SIGKILL a rank mid-training, survivors
    shrink with zero failed steps, a replacement joins via peer
    streaming with zero failed incumbent steps, catch-up wall bounded,
    streamed state digest-verified bit-identical (all asserted
    in-battery; the joiner's lifecycle is owned by launch rank 0).
    ISSUE 11: the observed flight events replay through the hvdmc
    trace witness against the grow model."""
    outputs = _run_world(4, "statesync_grow", timeout=240.0,
                         expected_rcs={2: -signal.SIGKILL},
                         extra_env=_witness_env("statesync_grow", 4))
    for r in (0, 1, 3):
        assert "rode 4->3->4" in outputs[r], outputs[r]
    assert "joiner: catch-up" in outputs[0], outputs[0]
    _replay_witness(outputs, {"shrink", "donate", "grow",
                              "join-announce", "join-ready",
                              "join-entered"})


def test_statesync_preempt_grace_3rank():
    """ISSUE 10 SIGTERM-grace acceptance: the preempted rank departs
    with bye| inside the grace window (exit 0 — never a signal death)
    and survivors shrink proactively with no RanksFailedError raised
    anywhere (the battery runs its collectives bare: any structured
    failure is a worker failure here).  ISSUE 11: the observed flight
    events replay through the hvdmc trace witness."""
    outputs = _run_world(3, "statesync_preempt", timeout=150.0,
                         extra_env=_witness_env("statesync_preempt", 3))
    assert "departed with bye| stamp" in outputs[1], outputs[1]
    for r in (0, 2):
        assert "no RanksFailedError anywhere" in outputs[r], outputs[r]
    _replay_witness(outputs, {"sigterm-grace", "departed",
                              "shrink-proactive"})


@pytest.mark.slow
def test_statesync_serving_grow_2rank():
    """Grow mid-serve: a joiner replica streams the incumbents'
    perturbed params, enters at a step boundary, and the grown world
    serves a second wave — world.grows and goodput phases recorded."""
    outputs = _run_world(2, "statesync_serve", timeout=420.0)
    assert "serving grow: 36 served across 2->3" in outputs[0], \
        outputs[0]


# ---------------------------------------------------------------------------
# Snapshot / stamp semantics
# ---------------------------------------------------------------------------
def _tree(n=64, seed=3):
    rng = np.random.default_rng(seed)
    return {"params": rng.standard_normal(n).astype(np.float32),
            "opt": rng.standard_normal(n).astype(np.float32),
            "step": np.int64(17)}


class TestSnapshot:
    def test_flatten_unflatten_roundtrip(self):
        tree = _tree()
        out = unflatten_state(flatten_state(tree), tree)
        for k in tree:
            np.testing.assert_array_equal(out[k], tree[k])

    def test_snapshot_is_a_copy(self):
        """COW semantics: training mutates live arrays freely while a
        donor streams the frozen image."""
        tree = _tree()
        snap = Snapshot(tree, "e", 1)
        before = bytes(snap.data)
        tree["params"] += 1.0
        assert bytes(snap.data) == before

    def test_digest_changes_on_any_flip(self):
        buf = flatten_state(_tree(n=100000))
        d = state_digest(buf)
        for pos in (0, 70000, len(buf) - 1):
            tampered = bytearray(buf)
            tampered[pos] ^= 1
            assert state_digest(tampered) != d

    def test_unflatten_rejects_size_mismatch(self):
        tree = _tree()
        with pytest.raises(ValueError, match="does not match"):
            unflatten_state(flatten_state(tree)[:-4], tree)

    def test_stamp_meta_roundtrip(self):
        s = SnapshotStamp("ep~g1", 42, 0xdeadbeef, 1024)
        assert SnapshotStamp.from_meta(s.as_meta()) == s


# ---------------------------------------------------------------------------
# The state-frame wire verb
# ---------------------------------------------------------------------------
class TestStateFrames:
    def test_roundtrip_with_payload(self):
        raw = pack_state_frame(STATE_DATA, {"o": 8, "crc": 5}, b"pay")
        kind, meta, payload = unpack_state_frame(raw)
        assert (kind, meta, bytes(payload)) == \
            (STATE_DATA, {"o": 8, "crc": 5}, b"pay")

    def test_meta_only_frame(self):
        kind, meta, payload = unpack_state_frame(
            pack_state_frame(STATE_META, {"step": 3}))
        assert kind == STATE_META and meta == {"step": 3}
        assert payload.nbytes == 0

    def test_rejects_foreign_frame(self):
        with pytest.raises(ValueError, match="bad magic"):
            unpack_state_frame(b"\x00\x01\x02 not a state frame")


# ---------------------------------------------------------------------------
# Streaming protocol over real PeerMesh channels (in-process donors)
# ---------------------------------------------------------------------------
@pytest.fixture()
def kv_server():
    srv = RendezvousServer()
    port = srv.start()
    yield RendezvousClient("127.0.0.1", port, 20.0)
    srv.stop()


def _spawn_donors(kv, scope, snap, num_donors, donor_cls=DonorServer,
                  dying=()):
    donors = []
    for r in range(num_donors):
        cls = donor_cls if r in dying else DonorServer
        d = cls(kv, scope, r, num_donors, chunk_bytes=32768,
                timeout=15.0)
        d.offer_snapshot(0, snap)
        d.start()
        donors.append(d)
    return donors


class TestStreaming:
    def test_bulk_round_bit_identical(self, kv_server):
        snap = Snapshot(_tree(n=200000), "e0", 5)
        donors = _spawn_donors(kv_server, "sssync.u.0", snap, 3)
        p = JoinerPuller(kv_server, "sssync.u.0", 3, timeout=15.0)
        p.connect()
        image, stamp = p.pull_round(0)
        assert bytes(image) == bytes(snap.data)
        assert stamp == snap.stamp
        # Every donor served a DISJOINT shard (bytes sum to the image).
        assert sum(b for b, _ in p.donor_stats.values()) == len(image)
        p.close()
        for d in donors:
            d.join(10.0)
            assert d.error is None

    def test_second_round_streams_fresh_snapshot(self, kv_server):
        tree = _tree(n=50000)
        snap0 = Snapshot(tree, "e0", 5)
        donors = _spawn_donors(kv_server, "sssync.u.1", snap0, 2)
        p = JoinerPuller(kv_server, "sssync.u.1", 2, timeout=15.0)
        p.connect()
        img0, st0 = p.pull_round(0)
        tree["params"] *= 2.0
        snap1 = Snapshot(tree, "e0", 9)
        for d in donors:
            d.offer_snapshot(1, snap1)
        img1, st1 = p.pull_round(1)
        assert bytes(img1) == bytes(snap1.data) != bytes(img0)
        assert st1.step == 9
        p.close()

    def test_resume_across_donor_death(self, kv_server):
        """A donor dying mid-range (channel closed) reassigns its
        unfinished tail to the survivors; the assembled image still
        digest-verifies bit-identical."""
        class DyingDonor(DonorServer):
            def _serve_range(self, mesh, joiner, snap, offset, length,
                             counter):
                import zlib
                view = memoryview(snap.data)
                n = min(self.chunk_bytes, length)
                chunk = view[offset:offset + n]
                mesh.send(joiner, pack_state_frame(
                    STATE_DATA,
                    {"o": offset, "n": n, "crc": zlib.crc32(chunk)},
                    chunk))
                raise StreamError("unit-test chaos: donor dies")

        snap = Snapshot(_tree(n=300000), "e0", 5)
        _spawn_donors(kv_server, "sssync.u.2", snap, 3,
                      donor_cls=DyingDonor, dying={1})
        p = JoinerPuller(kv_server, "sssync.u.2", 3, timeout=10.0)
        p.connect()
        image, stamp = p.pull_round(0)
        assert bytes(image) == bytes(snap.data)
        assert 1 in p._dead
        p.close()

    def test_torn_snapshot_rejected(self, kv_server):
        """Donors stamped at different steps = a torn snapshot: the
        round is rejected before a single byte is interpreted."""
        t = _tree(n=4096)
        snap_a = Snapshot(t, "e0", 5)
        t["params"] += 1.0
        snap_b = Snapshot(t, "e0", 6)
        d0 = DonorServer(kv_server, "sssync.u.3", 0, 2,
                         chunk_bytes=1024, timeout=10.0)
        d1 = DonorServer(kv_server, "sssync.u.3", 1, 2,
                         chunk_bytes=1024, timeout=10.0)
        d0.offer_snapshot(0, snap_a)
        d1.offer_snapshot(0, snap_b)
        d0.start()
        d1.start()
        p = JoinerPuller(kv_server, "sssync.u.3", 2, timeout=10.0)
        p.connect()
        with pytest.raises(TornSnapshotError, match="torn snapshot"):
            p.pull_round(0)
        p.close()

    def test_verify_round_rejects_corrupt_image(self):
        snap = Snapshot(_tree(), "e0", 5)
        image = bytearray(snap.data)
        image[3] ^= 0xff
        with pytest.raises(TornSnapshotError, match="stale or corrupt"):
            JoinerPuller.verify_round(image, snap.stamp)

    def test_stream_guard_bounds_waits(self):
        guard = StreamGuard(0.2)
        guard.check(0, 0.1, "recv")   # under the deadline: no raise
        with pytest.raises(StreamError, match="no bytes"):
            guard.check(0, 0.3, "recv")


# ---------------------------------------------------------------------------
# Ring-shard re-layout + checkpoint round trip
# ---------------------------------------------------------------------------
class TestRingReshard:
    def test_shard_concat_roundtrip(self):
        full = np.arange(23, dtype=np.float32)
        for world in (1, 2, 3, 4, 5):
            shards = [shard_for_rank(full, 23, world, r)
                      for r in range(world)]
            np.testing.assert_array_equal(
                concat_ring_shards(shards, 23), full)

    def test_reshard_preserves_values_any_world(self):
        import optax

        n = 37
        tx = optax.adam(1e-2)
        full_m = np.arange(n, dtype=np.float32) * 3 + 1
        full_v = np.arange(n, dtype=np.float32) * 7 + 2
        import jax.numpy as jnp

        from horovod_tpu.statesync.snapshot import ring_chunk
        world_old = 4
        chunk_old = ring_chunk(n, world_old)
        shards = []
        for r in range(world_old):
            st = tx.init(jnp.zeros((chunk_old,), jnp.float32))
            st = (st[0]._replace(
                count=jnp.int32(9),
                mu=jnp.asarray(shard_for_rank(full_m, n, world_old, r)),
                nu=jnp.asarray(shard_for_rank(full_v, n, world_old, r))),
                st[1])
            shards.append(st)
        for new_world in (1, 2, 5):
            for nr in range(new_world):
                out = reshard_ring_state(shards, n, new_world, nr)
                np.testing.assert_array_equal(
                    out[0].mu, shard_for_rank(full_m, n, new_world, nr))
                np.testing.assert_array_equal(
                    out[0].nu, shard_for_rank(full_v, n, new_world, nr))
                assert int(out[0].count) == 9

    def test_reshard_rejects_torn_replicated_leaf(self):
        import optax
        import jax.numpy as jnp

        from horovod_tpu.statesync.snapshot import ring_chunk
        tx = optax.adam(1e-2)
        chunk = ring_chunk(8, 2)
        s0 = tx.init(jnp.zeros((chunk,), jnp.float32))
        s1 = (s0[0]._replace(count=jnp.int32(3)), s0[1])
        with pytest.raises(ValueError, match="differs across shards"):
            reshard_ring_state([s0, s1], 8, 1, 0)


class TestRingCheckpoint:
    def _run_ring_steps(self, world, steps, tx, params, grads_by_step,
                        cfg):
        """Drive sync_and_apply on a virtual device mesh; returns
        (params, stacked per-rank opt state) after `steps` steps."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as P

        from horovod_tpu.common.jax_compat import shard_map
        from horovod_tpu.parallel import (init_ring_optimizer_state,
                                          sync_and_apply)

        mesh = Mesh(np.array(jax.devices()[:world]), ("dp",))
        os0 = init_ring_optimizer_state(tx, params, world, cfg)
        os_stacked = jax.tree_util.tree_map(
            lambda leaf: jnp.broadcast_to(leaf, (world,) + leaf.shape)
            if getattr(leaf, "ndim", 0) >= 1 else leaf, os0)
        os_specs = jax.tree_util.tree_map(
            lambda leaf: P("dp") if getattr(leaf, "ndim", 0) >= 2
            else P(), os_stacked)
        p_stacked = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(jnp.asarray(x),
                                       (world,) + x.shape), params)

        def step(g, p, s):
            p_local = jax.tree_util.tree_map(lambda x: x[0], p)
            s_local = jax.tree_util.tree_map(
                lambda leaf: leaf[0] if getattr(leaf, "ndim", 0) >= 2
                else leaf, s)
            new_p, new_s = sync_and_apply(tx, g, p_local, s_local, cfg)
            return (jax.tree_util.tree_map(lambda x: x[None], new_p),
                    jax.tree_util.tree_map(
                        lambda leaf: leaf[None]
                        if getattr(leaf, "ndim", 0) >= 1 else leaf,
                        new_s))

        fn = jax.jit(shard_map(step, mesh=mesh,
                               in_specs=(P("dp"), P("dp"), os_specs),
                               out_specs=(P("dp"), os_specs),
                               check_vma=False))
        for k in range(steps):
            p_stacked, os_stacked = fn(grads_by_step[k], p_stacked,
                                       os_stacked)
        params_out = jax.tree_util.tree_map(
            lambda x: np.asarray(x)[0], p_stacked)
        return params_out, os_stacked

    def test_round_trip_across_world_sizes_matches_replicated(
            self, tmp_path):
        """The satellite's parity criterion: ring shards saved at world
        4 restore at worlds 1/2/3 bit-identical to the re-cut layout,
        and the world-1 restore equals the REPLICATED optimizer state
        of the same training prefix (flat-padded layout)."""
        import jax.numpy as jnp
        import optax

        from horovod_tpu import checkpoint as ck
        from horovod_tpu.parallel import GradSyncConfig
        from horovod_tpu.statesync.snapshot import ring_chunk

        world = 4
        rng = np.random.default_rng(7)
        params = {"w": rng.standard_normal(11).astype(np.float32)}
        grads = [{"w": np.tile(
            rng.standard_normal(11).astype(np.float32), (world, 1))}
            for _ in range(2)]
        tx = optax.adam(1e-2)
        cfg = GradSyncConfig(axes=("dp",), op="average",
                             optimizer_in_ring=True)
        _, os_stacked = self._run_ring_steps(world, 2, tx, params,
                                             grads, cfg)
        import jax

        for r in range(world):
            shard = jax.tree_util.tree_map(
                lambda leaf, r=r: np.asarray(leaf)[r]
                if getattr(leaf, "ndim", 0) >= 2 else np.asarray(leaf),
                os_stacked)
            ck.save_ring_checkpoint(str(tmp_path), shard, rank=r,
                                    world=world, n_params=11, step=2)
        # Parity vs the replicated path: the same two updates applied
        # by a replicated optimizer over the padded flat buffer.
        n = 11
        chunk1 = ring_chunk(n, 1)
        rep_state = tx.init(jnp.zeros((chunk1,), jnp.float32))
        for g in grads:
            flat = np.zeros(chunk1, np.float32)
            flat[:n] = np.asarray(g["w"]).mean(axis=0)
            upd, rep_state = tx.update(jnp.asarray(flat), rep_state,
                                       jnp.zeros((chunk1,),
                                                 jnp.float32))
        restored1, step = ck.restore_ring_checkpoint(
            str(tmp_path), tx, rank=0, world=1, n_params=n)
        assert step == 2
        np.testing.assert_allclose(np.asarray(restored1[0].mu)[:n],
                                   np.asarray(rep_state[0].mu)[:n],
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(restored1[0].nu)[:n],
                                   np.asarray(rep_state[0].nu)[:n],
                                   rtol=1e-6, atol=1e-7)
        assert int(restored1[0].count) == int(rep_state[0].count) == 2
        # Restores at other world sizes are exact re-cuts of world 1.
        full_mu = np.asarray(restored1[0].mu)
        for new_world in (2, 3):
            for nr in range(new_world):
                st, _ = ck.restore_ring_checkpoint(
                    str(tmp_path), tx, rank=nr, world=new_world,
                    n_params=n)
                np.testing.assert_array_equal(
                    np.asarray(st[0].mu),
                    shard_for_rank(full_mu[:n], n, new_world, nr))

    def test_restore_rejects_corrupt_and_torn(self, tmp_path):
        import optax

        from horovod_tpu import checkpoint as ck
        from horovod_tpu.statesync.snapshot import ring_chunk
        import jax.numpy as jnp

        tx = optax.adam(1e-2)
        chunk = ring_chunk(6, 2)
        for r in range(2):
            ck.save_ring_checkpoint(
                str(tmp_path), tx.init(jnp.zeros((chunk,), jnp.float32)),
                rank=r, world=2, n_params=6, step=r)   # torn: steps 0,1
        with pytest.raises(ValueError, match="torn ring checkpoint"):
            ck.restore_ring_checkpoint(str(tmp_path), tx, rank=0,
                                       world=2, n_params=6)
        # Corrupt one shard's bytes: the digest check refuses.
        victim = os.path.join(str(tmp_path), "ring-1-of-2.state")
        blob = bytearray(open(victim, "rb").read())
        blob[0] ^= 0xff
        open(victim, "wb").write(bytes(blob))
        with pytest.raises(ValueError, match="digest check"):
            ck.restore_ring_checkpoint(str(tmp_path), tx, rank=0,
                                       world=2, n_params=6)


# ---------------------------------------------------------------------------
# Autoscale policy + controller
# ---------------------------------------------------------------------------
class TestAutoscale:
    def _policy(self, **kw):
        kw.setdefault("up_shed_rate", 0.05)
        kw.setdefault("up_queue_fraction", 0.5)
        kw.setdefault("down_lag_ms", 50.0)
        kw.setdefault("hysteresis_rounds", 3)
        kw.setdefault("queue_depth_limit", 100)
        return AutoscalePolicy(2, 8, **kw)

    def test_scale_up_needs_sustained_overload(self):
        p = self._policy()
        assert p.observe(4, shed_rate=0.5) is None
        assert p.observe(4, shed_rate=0.5) is None
        d = p.observe(4, shed_rate=0.5)
        assert d is not None and d.direction == "up" and d.target == 5

    def test_one_burst_never_flaps(self):
        p = self._policy()
        assert p.observe(4, shed_rate=0.5) is None
        assert p.observe(4, shed_rate=0.0) is None   # streak broken
        assert p.observe(4, shed_rate=0.5) is None
        assert p.observe(4, shed_rate=0.5) is None
        assert p.observe(4, shed_rate=0.5) is not None

    def test_cooldown_after_decision(self):
        p = self._policy(hysteresis_rounds=1)
        assert p.observe(4, shed_rate=0.5).direction == "up"
        # Cooldown: the next interval cannot fire even under overload.
        assert p.observe(5, shed_rate=0.9) is None

    def test_scale_down_on_idle_straggler(self):
        p = self._policy(hysteresis_rounds=2)
        assert p.observe(4, straggler_lag_ms=80.0) is None
        d = p.observe(4, straggler_lag_ms=80.0)
        assert d is not None and d.direction == "down" and d.target == 3

    def test_no_scale_down_under_load(self):
        """A dragging rank under active shedding is an overload signal
        (scale up wins), never a scale-down."""
        p = self._policy(hysteresis_rounds=1)
        d = p.observe(4, straggler_lag_ms=80.0, shed_rate=0.2)
        assert d is not None and d.direction == "up"

    def test_bounds_respected(self):
        p = self._policy(hysteresis_rounds=1)
        assert p.observe(8, shed_rate=0.9) is None       # at max_np
        p2 = self._policy(hysteresis_rounds=1)
        assert p2.observe(2, straggler_lag_ms=99.0) is None   # at min_np

    def test_controller_drives_driver_and_metrics(self):
        class StubDriver:
            def __init__(self):
                self.targets = []

            def world_size(self):
                return 4

            def set_target_np(self, n):
                self.targets.append(n)

        gauges = {"queue_depth": 0.0, "shed_rate": 0.4,
                  "straggler_lag_ms": 0.0}
        driver = StubDriver()
        ctl = AutoscaleController(
            driver, lambda: dict(gauges),
            self._policy(hysteresis_rounds=2), interval=999.0)
        assert ctl.tick() is None
        d = ctl.tick()
        assert d is not None and driver.targets == [5]
        assert ctl.decisions == [d]


# ---------------------------------------------------------------------------
# Elastic driver: blacklist re-admission + autoscale target
# ---------------------------------------------------------------------------
class TestBlacklistReadmission:
    def _mgr(self, slots=2, cooldown=None):
        from collections import OrderedDict

        from horovod_tpu.elastic.discovery import (FixedHostDiscovery,
                                                   HostManager)
        return HostManager(
            FixedHostDiscovery(OrderedDict(a=slots, b=2)),
            blacklist_cooldown=cooldown)

    def test_manual_clear_readmits_with_fresh_slots(self):
        from collections import OrderedDict

        from horovod_tpu.elastic.discovery import (FixedHostDiscovery,
                                                   HostManager)
        disc = FixedHostDiscovery(OrderedDict(a=2, b=2))
        mgr = HostManager(disc)
        mgr.update_available_hosts()
        mgr.blacklist("a")
        mgr.update_available_hosts()
        assert "a" not in mgr.current_hosts
        # The host returns with a DIFFERENT slot count; clearing must
        # pick up the refreshed count, not any remembered one.
        disc._hosts["a"] = 4
        assert mgr.clear_blacklist("a") is True
        assert not mgr.is_blacklisted("a")
        mgr.update_available_hosts()
        assert mgr.current_hosts["a"] == 4

    def test_clear_unknown_host_is_noop(self):
        mgr = self._mgr()
        assert mgr.clear_blacklist("nope") is False

    def test_cooldown_expiry_readmits(self):
        mgr = self._mgr(cooldown=0.05)
        mgr.update_available_hosts()
        mgr.blacklist("a")
        assert mgr.is_blacklisted("a")
        mgr.update_available_hosts()
        assert "a" not in mgr.current_hosts
        time.sleep(0.08)
        mgr.update_available_hosts()
        assert "a" in mgr.current_hosts
        assert not mgr.blacklisted_hosts

    def test_explicit_cooldown_overrides_default(self):
        mgr = self._mgr(cooldown=None)
        mgr.blacklist("a", cooldown=0.05)
        time.sleep(0.08)
        assert not mgr.is_blacklisted("a")

    def test_forever_without_cooldown(self):
        mgr = self._mgr()
        mgr.blacklist("a")
        time.sleep(0.05)
        assert mgr.is_blacklisted("a")

    def test_driver_target_np_clamped(self):
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.elastic.discovery import FixedHostDiscovery
        from collections import OrderedDict

        driver = ElasticDriver(FixedHostDiscovery(OrderedDict(a=8)),
                               min_np=2, max_np=6)
        driver.set_target_np(99)
        assert driver.target_np() == 6
        driver.set_target_np(1)
        assert driver.target_np() == 2
        driver.set_target_np(4)
        assert driver.target_np() == 4


# ---------------------------------------------------------------------------
# Chaos preempt action
# ---------------------------------------------------------------------------
class TestChaosPreempt:
    def test_parse_and_defaults(self):
        from horovod_tpu.resilience.chaos import parse_spec

        act = parse_spec("preempt:rank=2,op=7")[0]
        assert act.kind == "preempt"
        assert act.rank == 2 and act.op == 7
        assert act.count == 1   # one notice, not a repeating signal

    def test_delivers_sigterm_and_survives(self):
        """The preempt action sends SIGTERM and KEEPS RUNNING — the
        grace path owns the departure."""
        from horovod_tpu.resilience.chaos import ChaosEngine

        hits = []
        old = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
        try:
            eng = ChaosEngine("preempt:rank=0,op=1", rank=0)
            assert eng.on_response(["t0"]) is None
            assert not hits
            assert eng.on_response(["t1"]) is None   # op 1: fires
            assert hits == [signal.SIGTERM]
            assert eng.on_response(["t2"]) is None   # count exhausted
            assert hits == [signal.SIGTERM]
        finally:
            signal.signal(signal.SIGTERM, old)

    def test_launch_rank_identity_survives_renumbering(self):
        """The PR 9 kill-fix discipline holds for preempt: the engine
        (and its rank identity) is reused across a re-init as long as
        the spec is unchanged."""
        from horovod_tpu.resilience import chaos as chaos_mod

        os.environ["HOROVOD_CHAOS"] = "preempt:rank=1,op=99"
        try:
            e1 = chaos_mod.configure(1)
            e2 = chaos_mod.configure(0)   # renumbered after a shrink
            assert e1 is e2 and e2.rank == 1
        finally:
            del os.environ["HOROVOD_CHAOS"]
            chaos_mod.configure(0)


# ---------------------------------------------------------------------------
# Donation + lint rule
# ---------------------------------------------------------------------------
class TestDonation:
    def test_fetch_donation_verifies_digest(self, kv_server):
        from horovod_tpu.statesync.service import (_donate_scope,
                                                   fetch_donation)

        tree = {"shard": np.arange(32, dtype=np.float32)}
        image = flatten_state(tree)
        kv_server.put(_donate_scope("ep"), "1.meta", json.dumps(
            {"digest": state_digest(image), "nbytes": len(image),
             "seq": 3}).encode())
        kv_server.put(_donate_scope("ep"), "1", bytes(image))
        out = fetch_donation("ep", 1, {"shard": np.zeros(32, np.float32)},
                             kv=kv_server)
        np.testing.assert_array_equal(out["shard"], tree["shard"])
        # Tampered payload: rejected, never unflattened.
        kv_server.put(_donate_scope("ep"), "1",
                      bytes(bytearray([image[0] ^ 0xff]) + image[1:]))
        assert fetch_donation("ep", 1,
                              {"shard": np.zeros(32, np.float32)},
                              kv=kv_server) is None

    def test_missing_donation_is_none(self, kv_server):
        from horovod_tpu.statesync.service import fetch_donation

        assert fetch_donation("ep", 7, {"x": np.zeros(1)},
                              kv=kv_server) is None

    def test_kv_delete_consumes_marks(self, kv_server):
        """RendezvousClient.delete: a failed join attempt consumes its
        stale announcement so no watcher ever replays it."""
        kv_server.put("ssgrow.e", "join:0", b"{}")
        assert kv_server.get("ssgrow.e", "join:0") == b"{}"
        kv_server.delete("ssgrow.e", "join:0")
        assert kv_server.get("ssgrow.e", "join:0") is None


class TestHttpSource:
    def test_scrapes_exposition_and_deltas(self):
        import http.server
        import threading as _threading

        from horovod_tpu.statesync.autoscale import http_source

        body = [(b"# HELP x\n"
                 b'horovod_serve_requests_total{outcome="served"} 10\n'
                 b'horovod_serve_requests_total{outcome="shed"} 0\n'
                 b"horovod_serve_queue_depth 12\n"
                 b"horovod_controller_straggler_lag_ms 7.5\n")]

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", str(len(body[0])))
                self.end_headers()
                self.wfile.write(body[0])

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        _threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            src = http_source(
                f"http://127.0.0.1:{srv.server_address[1]}/")
            s1 = src()
            assert s1["queue_depth"] == 12.0
            assert s1["straggler_lag_ms"] == 7.5
            # Second scrape: 10 more served, 10 shed -> shed_rate 0.5.
            body[0] = (
                b'horovod_serve_requests_total{outcome="served"} 20\n'
                b'horovod_serve_requests_total{outcome="shed"} 10\n')
            s2 = src()
            assert s2["shed_rate"] == pytest.approx(0.5)
        finally:
            srv.shutdown()
            srv.server_close()

    def test_unreachable_endpoint_reads_idle(self):
        from horovod_tpu.statesync.autoscale import http_source

        src = http_source("http://127.0.0.1:1/", timeout=0.2)
        s = src()
        assert s == {"queue_depth": 0.0, "shed_rate": 0.0,
                     "straggler_lag_ms": 0.0}


class TestLintRule:
    FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures", "lint", "statesync",
                           "unverified_frame.py")

    def test_fixture_flags_unverified_reads_only(self):
        from horovod_tpu.analysis.lint import lint_paths

        violations = [v for v in lint_paths([self.FIXTURE])
                      if v.rule.id == "HVD1007"]
        assert len(violations) == 2, violations
        # The verified forms (digest in scope / pull_round) pass.
        texts = "\n".join(v.text() for v in violations)
        assert "apply_streamed_state" in texts
        assert "apply_chunk_blind" in texts
        assert "apply_verified_state" not in texts
        assert "pull_and_apply" not in texts

    def test_statesync_tree_is_hvd1007_clean(self):
        from horovod_tpu.analysis.lint import LintConfig, lint_paths

        cfg = LintConfig(select={"HVD1007"})
        violations = lint_paths(
            [os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "horovod_tpu",
                "statesync")], cfg)
        assert violations == [], "\n".join(v.text() for v in violations)
