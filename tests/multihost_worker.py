"""Worker for the multi-host JAX world test.

Each worker is one "host": 4 virtual CPU devices, its own process. hvd.init
forms the JAX world via the rendezvous KV (parallel/multihost.py — the
analogue of GlooContext rendezvous, reference: gloo/gloo_context.cc:136-152),
after which jax.devices() spans both processes and the Trainer's dp axis
crosses the process boundary.

Usage: python multihost_worker.py <rank> <size> <rendezvous_port> [n_local]
      [mode]
Modes (VERDICT r2 item 8 multi-host depth):
- ``dp``   flat dp=8 Trainer.step loop (the original test);
- ``hier`` dp=2 x sp=4 hybrid mesh with HIERARCHICAL grad sync
           (reduce-scatter local → cross allreduce → all-gather local,
           the NCCLHierarchicalAllreduce split) — multi-process runs lay
           dp across the 2-process DCN granule boundary;
- ``fit``  a short multi-host Trainer.fit (2 epochs x 2 batches).

Prints the final loss as `LOSS <float>` for the parent to compare. The
single-process baseline is the same script with size=1 and n_local=8, so
both runs shard identically and losses must match.
"""
import os
import sys


def main() -> int:
    rank, size, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    n_local = int(sys.argv[4]) if len(sys.argv) > 4 else 4
    mode = sys.argv[5] if len(sys.argv) > 5 else "dp"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        .replace("--xla_force_host_platform_device_count=8", "")
        + f" --xla_force_host_platform_device_count={n_local}").strip()
    os.environ.update({
        "HOROVOD_RANK": str(rank), "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": "0", "HOROVOD_LOCAL_SIZE": "1",
        "HOROVOD_CROSS_RANK": str(rank), "HOROVOD_CROSS_SIZE": str(size),
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
        "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
        "HOROVOD_JAX_DISTRIBUTED": "1",
        "HOROVOD_GLOO_TIMEOUT_SECONDS": "60",
    })
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import jax
    jax.config.update("jax_platforms", "cpu")

    import horovod_tpu as hvd
    hvd.init()
    try:
        if size > 1:
            assert jax.process_count() == size, jax.process_count()
        n_global = len(jax.devices())
        assert n_global == n_local * size, n_global

        import numpy as np
        import optax
        from jax.sharding import PartitionSpec as P

        from horovod_tpu import models, training
        from horovod_tpu.parallel import (GradSyncConfig, MeshSpec,
                                          build_mesh, multihost)

        import jax.numpy as jnp

        if mode == "hier":
            # 2-granule hybrid mesh: dp(=2) rides DCN across the process
            # boundary, sp(=4) stays on the intra-process "ICI" leg; the
            # sync does the reference's RS → cross-AR → AG split.
            mesh = build_mesh(MeshSpec(dp=2, sp=n_global // 2))
            sync = GradSyncConfig(axes=("dp", "sp"), op="average",
                                  hierarchical=True)
            batch_spec = P(("dp", "sp"))
        else:
            mesh = build_mesh(MeshSpec(dp=n_global))
            sync = GradSyncConfig(axes=("dp",), op="average")
            batch_spec = P("dp")
        model = models.ResNet(stage_sizes=(1,),
                              block_cls=models.resnet.BottleneckBlock,
                              num_classes=8, num_filters=8,
                              dtype=jnp.float32)
        trainer = training.Trainer(
            model, optax.sgd(0.1, momentum=0.9), mesh, sync=sync,
            batch_spec=batch_spec)

        rng = np.random.default_rng(0)

        def make_batch(seed: int) -> dict:
            g = np.random.default_rng(seed)
            batch = {
                "image": g.standard_normal(
                    (n_global * 2, 16, 16, 3)).astype(np.float32),
                "label": g.integers(0, 8, size=(n_global * 2,)),
            }
            return multihost.make_global_batch(mesh, batch_spec, batch)

        if mode == "fit":
            data = [make_batch(0), make_batch(1)]
            state = trainer.init(jax.random.key(0), data[0])
            state, history = trainer.fit(state, data, epochs=2)
            print(f"LOSS {history[-1]['loss']:.10f}", flush=True)
        else:
            global_batch = make_batch(0)
            state = trainer.init(jax.random.key(0), global_batch)
            for _ in range(3):
                state, metrics = trainer.step(state, global_batch)
            print(f"LOSS {float(metrics['loss']):.10f}", flush=True)
    finally:
        hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
