"""hvdlife tests (ISSUE 13): the resource-lifecycle pass over seeded
fixtures and the live tree, the LIFECYCLE_ALLOWED manifest contract,
the hvdsan/hvdlife shared thread universe, and the runtime census
witness (including the seeded epoch-leak fixture caught BOTH ways at
unit scale — the 4-rank battery proves it across a real 4->3->4
cycle)."""
from __future__ import annotations

import importlib.util
import json
import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from horovod_tpu.analysis.hvdlife import (  # noqa: E402
    LIFECYCLE_ALLOWED, CensusWitness, analyze_paths, census_diff,
    take_census)
from horovod_tpu.analysis.hvdlife.census import (  # noqa: E402
    _normalize_thread, check_dumps, dump_census)
from horovod_tpu.analysis.hvdlife.life import LifeAnalysis  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TREE = os.path.join(REPO, "horovod_tpu")
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint", "life")


def _fx(name: str) -> str:
    return os.path.join(FIXTURES, name)


def _ids(analysis: LifeAnalysis):
    return [(f.rule.id, f.line) for f in analysis.findings]


# ---------------------------------------------------------------------------
# Seeded fixtures: every rule detected, the clean file silent
# ---------------------------------------------------------------------------
def test_fixture_unjoined_thread():
    out = analyze_paths([_fx("unjoined_thread.py")])
    assert _ids(out) == [("HVD701", 9), ("HVD701", 12), ("HVD701", 27)]
    # the fire-and-forget shape gets the no-handle message
    assert "without keeping a handle" in out.findings[2].message


def test_fixture_unreleased_channel():
    out = analyze_paths([_fx("unreleased_channel.py")])
    assert _ids(out) == [("HVD702", 8), ("HVD702", 9)]


def test_fixture_unreleased_region():
    out = analyze_paths([_fx("unreleased_region.py")])
    assert _ids(out) == [("HVD703", 8), ("HVD703", 9)]


def test_fixture_epoch_leak_names_site_and_teardown_path():
    """ISSUE 13 acceptance: the HVD704 finding names the acquisition
    site AND the teardown path the release is missing from."""
    out = analyze_paths([_fx("epoch_leak.py")])
    assert _ids(out) == [("HVD704", 28)]
    msg = out.findings[0].message
    assert "epoch_leak.py:28" in msg           # the acquisition site
    assert "init/reinit_world" in msg          # the formation path
    assert "shutdown/reinit_world" in msg      # the missing teardown


def test_fixture_kv_block_pool_leak():
    """ISSUE 14: the KV-block pool is a taxonomy channel — an executor
    whose teardown drops the pool handle without close() leaks the
    residency accounting (and the HBM rows its ids index) once per
    elastic reinit cycle."""
    out = analyze_paths([_fx("kv_block_leak.py")])
    assert _ids(out) == [("HVD704", 10)]
    msg = out.findings[0].message
    assert "KVBlockPool" in msg
    assert "init/reinit_world" in msg


def test_fixture_wal_and_replicator_leak():
    """ISSUE 15: the rendezvous WAL writer and log-tail replicator are
    taxonomy channels — a replica whose teardown drops the handles
    without close() leaks the WAL fd + fsync lane and the tail thread
    once per elastic reinit cycle."""
    out = analyze_paths([_fx("wal_leak.py")])
    ids = _ids(out)
    assert ("HVD702", 11) in ids or ("HVD704", 11) in ids, ids
    assert ("HVD702", 12) in ids or ("HVD704", 12) in ids, ids
    msgs = " | ".join(f.message for f in out.findings)
    assert "WalWriter" in msgs and "Replicator" in msgs


def test_fixture_blocked_no_wakeup():
    out = analyze_paths([_fx("blocked_no_wakeup.py")])
    assert _ids(out) == [("HVD705", 12)]
    assert "poison" in out.findings[0].message


def test_fixture_clean_zero_findings():
    """Every sanctioned shape — with-managed, resources registration,
    same-function formation release, loop release, alias release,
    poison-then-join THROUGH A HELPER (the interprocedural
    release-via-helper case), cancelled timer, justified suppression —
    reports nothing."""
    out = analyze_paths([_fx("clean.py")])
    assert out.findings == [], [f.text() for f in out.findings]


def test_suppression_silences_at_acquisition_site(tmp_path):
    src = open(_fx("unreleased_channel.py")).read()
    src = src.replace(
        "self._listener = socket.socket()                      "
        "# HVD702",
        "self._listener = socket.socket()  # hvdlint: "
        "disable=HVD702 -- tool beacon, process lifetime")
    p = tmp_path / "suppressed.py"
    p.write_text(src)
    out = analyze_paths([str(p)])
    assert [f.rule.id for f in out.findings] == ["HVD702"]
    assert out.findings[0].line == 8        # only the other one left


def test_whole_fixture_dir():
    out = analyze_paths([FIXTURES])
    assert sorted({f.rule.id for f in out.findings}) == \
        ["HVD701", "HVD702", "HVD703", "HVD704", "HVD705"]


# ---------------------------------------------------------------------------
# The live tree
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tree_life() -> LifeAnalysis:
    return analyze_paths([TREE])


def test_tree_is_lifecycle_clean(tree_life):
    errors = [f for f in tree_life.findings if f.severity == "error"]
    assert errors == [], "\n".join(f.text() for f in errors)


def test_tree_harvest_covers_the_fabric(tree_life):
    """The harvest sees the long-lived machinery the motivation names:
    background thread, sender lanes, exporter server, statesync
    watcher+timer+donors, shm regions, per-epoch meshes."""
    keys = {a.key for a in tree_life.life.acquisitions}
    assert "core.background_thread" in keys or \
        "core._global.background_thread" in keys or \
        any(k.endswith("background_thread") for k in keys), keys
    for expect in ("runner.network._PeerChannel._sender",
                   "telemetry.exporter.MetricsExporter._thread",
                   "telemetry.exporter.MetricsExporter._httpd",
                   "statesync.service.StateSyncService._watcher",
                   "statesync.service.StateSyncService._grace_timer",
                   "statesync.service.StateSyncService._donors",
                   "resilience.heartbeat.HeartbeatMonitor._thread"):
        assert expect in keys, expect
    kinds = {a.kind for a in tree_life.life.acquisitions}
    assert {"thread", "timer", "channel", "socket", "mmap", "file",
            "signal"} <= kinds


def test_lifecycle_allowances_resolve_and_matched(tree_life):
    """Every manifest allowance carries a real justification AND
    matches a live acquisition at head — a stale entry would silently
    blanket future code (the LOCK_HOLD_ALLOWED review discipline)."""
    acq_keys = {a.key for a in tree_life.life.acquisitions}
    matched = {k for k, _ in tree_life.allowed_hits}
    for key, why in LIFECYCLE_ALLOWED.items():
        assert len(why) > 40, key
        assert key in acq_keys, f"stale allowance {key}"
        assert key in matched, f"allowance {key} never consulted"


def test_thread_universe_agreement_with_hvdsan(tree_life):
    """ISSUE 13 satellite: hvdsan and hvdlife share ONE root manifest
    (ownership.THREAD_ROOTS) and must agree on the thread universe —
    every thread body hvdlife harvests resolves in hvdsan's roots and
    vice versa."""
    from horovod_tpu.analysis.hvdsan.lockgraph import analyze_paths \
        as san_analyze
    san = san_analyze([TREE])
    life_bodies = set(tree_life.thread_roots)
    san_bodies = set(san.thread_roots)
    assert life_bodies == san_bodies, (
        sorted(life_bodies - san_bodies),
        sorted(san_bodies - life_bodies))
    # and the names agree too (census normalization keys on them)
    for key in life_bodies:
        assert tree_life.thread_roots[key] == san.thread_roots[key]


def test_tree_thread_roots_are_named(tree_life):
    """Unnamed roots defeat census normalization; the harvest satellite
    named the stragglers (mesh acceptor, probe/rpc servers)."""
    unnamed = [name for name in tree_life.thread_roots.values()
               if name.startswith("thread@")]
    assert unnamed == [], unnamed
    assert {"hvd-mesh-accept", "hvd-probe", "hvd-statesync-donor-*",
            "hvd-background"} <= set(tree_life.thread_roots.values())


# ---------------------------------------------------------------------------
# Runtime census
# ---------------------------------------------------------------------------
class TestCensus:
    def test_take_census_shape(self):
        c = take_census("t")
        assert c["label"] == "t"
        assert c["fds"] > 0
        assert "MainThread" in c["threads"]
        assert c["fds"] >= c["sockets"] + c["shm_fds"] + c["pipes"]

    def test_thread_name_normalization(self):
        assert _normalize_thread("hvd-send-3") == "hvd-send-*"
        assert _normalize_thread("Thread-12") == "Thread-*"
        assert _normalize_thread("hvd-stream-0") == "hvd-stream-*"
        assert _normalize_thread("MainThread") == "MainThread"
        assert _normalize_thread("serve-ingress") == "serve-ingress"

    def test_normalized_counts_merge(self):
        stop = threading.Event()
        threads = [threading.Thread(target=stop.wait, daemon=True,
                                    name=f"fx-census-{i}")
                   for i in range(3)]
        for t in threads:
            t.start()
        try:
            c = take_census()
            assert c["threads"]["fx-census-*"] == 3
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5.0)

    def test_census_diff_reports_both_directions(self):
        a = {"threads": {"x": 1}, "sockets": 3, "shm_fds": 0,
             "shm_maps": 0}
        b = {"threads": {"x": 2, "y": 1}, "sockets": 2, "shm_fds": 0,
             "shm_maps": 0}
        problems = census_diff(a, b)
        assert any("threads[x]: 1 -> 2" in p for p in problems)
        assert any("threads[y]: 0 -> 1" in p for p in problems)
        assert any("sockets: 3 -> 2" in p for p in problems)
        assert census_diff(a, dict(a)) == []

    def test_witness_off_by_default(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_LIFE_CENSUS", raising=False)
        import horovod_tpu.analysis.hvdlife.census as census_mod
        monkeypatch.setattr(census_mod, "_witness", None)
        w = census_mod.witness()
        assert not w.enabled
        assert w.note("x") is None and w.snapshots == []

    def test_witness_dump_and_check(self, tmp_path, monkeypatch):
        import horovod_tpu.analysis.hvdlife.census as census_mod
        w = CensusWitness(enabled=True)
        w.note("baseline:world4", rank=2)
        w.note("transition:shrink")
        w.note("baseline:world4-again")
        monkeypatch.setattr(census_mod, "_witness", w)
        path = dump_census(str(tmp_path / "c_{rank}.json"))
        assert path == str(tmp_path / "c_2.json")
        payload = json.load(open(path))
        assert payload["rank"] == 2
        assert [s["label"] for s in payload["snapshots"]] == \
            ["baseline:world4", "transition:shrink",
             "baseline:world4-again"]
        # identical process state between the notes: no drift
        assert check_dumps([payload]) == []
        # seed a drift and the check names it, rank-stamped
        payload["snapshots"][2]["sockets"] += 3
        problems = check_dumps([payload])
        assert problems and "rank 2" in problems[0] and \
            "sockets" in problems[0]


def test_epoch_leak_fixture_caught_both_ways():
    """The acceptance seed at unit scale: the SAME fixture file is
    flagged statically by HVD704 and, when exercised, drifts the
    runtime census by exactly its leaked sockets."""
    out = analyze_paths([_fx("epoch_leak.py")])
    assert [f.rule.id for f in out.findings] == ["HVD704"]

    spec = importlib.util.spec_from_file_location("epoch_leak_fx",
                                                  _fx("epoch_leak.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    try:
        baseline = take_census("baseline")
        mod.init()
        for _ in range(3):
            mod.reinit_world()
        mod.shutdown()          # the seeded teardown releases nothing
        assert mod.leaked_count() == 4
        now = take_census("after 4 epochs")
        problems = census_diff(baseline, now)
        assert any("sockets: " in p and "+4" in p for p in problems), \
            problems
    finally:
        mod.release_all()
    time.sleep(0)               # fd table settles synchronously
    assert census_diff(take_census(), take_census()) == []


# ---------------------------------------------------------------------------
# The 4-rank grow-shrink acceptance battery
# ---------------------------------------------------------------------------
def test_census_battery_4_3_4_with_seeded_leak():
    """ISSUE 13 acceptance: the 4-rank battery rides 4->3->4 via
    statesync (chaos SIGKILL of rank 2, peer-streamed rejoin) with the
    seeded HVD704 fixture armed.  Every survivor must (a) catch the
    seeded leak in its census diff — exactly +2 sockets, one per world
    transition — and (b) census baseline-equal once the seed is
    released.  The driver then re-checks the rank-stamped witness
    dumps offline, exactly like the hvdsan witness flow; the STATIC
    half of the acceptance (HVD704 on the same fixture file, naming
    the acquisition site and the missing teardown path) is asserted in
    test_fixture_epoch_leak_names_site_and_teardown_path."""
    import glob
    import signal

    from test_multiprocess import _run_world

    for stale in glob.glob("/tmp/hvd_census_statesync_life4*"):
        os.unlink(stale)
    outputs = _run_world(4, "statesync_life", timeout=240.0,
                         expected_rcs={2: -signal.SIGKILL})
    for r in (0, 1, 3):
        assert "census caught the seeded epoch leak" in outputs[r], \
            outputs[r]
        assert "census baseline-equal after 4->3->4" in outputs[r], \
            outputs[r]
    # Offline witness check over the rank-stamped dumps.
    dumps = sorted({line.split(" ", 1)[1].strip()
                    for out in outputs for line in out.splitlines()
                    if line.startswith("CENSUS_DUMP ")})
    assert len(dumps) == 3, dumps            # one per survivor
    from horovod_tpu.analysis.hvdlife.census import load_census_dumps
    payloads = load_census_dumps(dumps)
    assert check_dumps(payloads) == []
    for payload in payloads:
        labels = [s["label"] for s in payload["snapshots"]]
        # the battery's labeled points plus core's transition notes
        assert any(lb.startswith("baseline:world4") for lb in labels)
        assert any(lb.startswith("armed:world4") for lb in labels)
        assert any(lb.startswith("world:") and lb.endswith(":3")
                   for lb in labels), labels   # the shrunk world
        assert any(lb.startswith("down:") for lb in labels)
        base = next(s for s in payload["snapshots"]
                    if s["label"].startswith("baseline"))
        armed = next(s for s in payload["snapshots"]
                     if s["label"].startswith("armed"))
        drift = census_diff(base, armed)
        assert drift == [f"sockets: {base['sockets']} -> "
                         f"{base['sockets'] + 2} (+2)"], drift


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def test_cli_json_and_exit_codes(capsys):
    from horovod_tpu.analysis.hvdlife.__main__ import main
    rc = main([_fx("unjoined_thread.py"), "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in payload["life"]] == ["HVD701"] * 3
    assert payload["wall_ms"] > 0
    rc = main([_fx("clean.py"), "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0 and payload["life"] == []


def test_cli_census_drift_fails(tmp_path, capsys):
    from horovod_tpu.analysis.hvdlife.__main__ import main
    base = take_census("baseline:w")
    drifted = dict(take_census("baseline:w2"))
    drifted["sockets"] += 1
    dump = tmp_path / "c.json"
    dump.write_text(json.dumps(
        {"rank": 0, "snapshots": [base, drifted]}))
    rc = main([_fx("clean.py"), "--census", str(dump)])
    out = capsys.readouterr().out
    assert rc == 1 and "CENSUS DRIFT" in out


def test_cli_module_entrypoint():
    import subprocess
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.hvdlife", TREE],
        capture_output=True, text=True, cwd=REPO, timeout=240)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "allowed-hold" in proc.stdout
