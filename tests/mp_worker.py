"""Worker script for multi-process parallel tests.

The analogue of the reference's test/parallel/* files, which are plain
pytest files executed under `mpirun -np 2` (SURVEY §4).  Here each worker
process runs the same battery of cross-rank semantic assertions; the parent
test spawns N of them against one rendezvous server and checks exit codes.

Usage: python mp_worker.py <rank> <size> <rendezvous_port> [battery]
"""
import os
import sys
import traceback

import numpy as np


def battery_collectives(hvd, rank, size):
    # -- allreduce sum ---------------------------------------------------
    x = np.arange(16, dtype=np.float32) + rank
    expected = np.arange(16, dtype=np.float32) * size + sum(range(size))
    out = hvd.allreduce(x, op=hvd.Sum, name="ar_sum")
    np.testing.assert_allclose(out, expected, rtol=1e-6)

    # -- allreduce average ----------------------------------------------
    out = hvd.allreduce(x, op=hvd.Average, name="ar_avg")
    np.testing.assert_allclose(out, expected / size, rtol=1e-6)

    # -- pre/postscale ----------------------------------------------------
    out = hvd.allreduce(np.ones(8, dtype=np.float32), op=hvd.Sum,
                        name="ar_scale", prescale_factor=2.0,
                        postscale_factor=0.5)
    np.testing.assert_allclose(out, np.full(8, float(size)), rtol=1e-6)

    # -- 16-bit dtypes ----------------------------------------------------
    for dt, tag in ((np.float16, "fp16"), (np.float64, "fp64"),
                    (np.int32, "i32"), (np.int64, "i64")):
        v = (np.ones(32) * (rank + 1)).astype(dt)
        out = hvd.allreduce(v, op=hvd.Sum, name=f"ar_{tag}")
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float64),
            np.full(32, sum(range(1, size + 1)), dtype=np.float64))

    import ml_dtypes
    v = np.ones(32, dtype=ml_dtypes.bfloat16) * (rank + 1)
    out = hvd.allreduce(v, op=hvd.Sum, name="ar_bf16")
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.full(32, sum(range(1, size + 1))))

    # -- grouped allreduce ------------------------------------------------
    xs = [np.full((4,), rank + i, dtype=np.float32) for i in range(3)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Sum, name="gar")
    for i, out in enumerate(outs):
        np.testing.assert_allclose(
            out, np.full((4,), sum(r + i for r in range(size))))

    # -- allgather (variable first dim) ----------------------------------
    local = np.full((rank + 1, 3), rank, dtype=np.float32)
    out = hvd.allgather(local, name="ag")
    expected_rows = []
    for r in range(size):
        expected_rows.append(np.full((r + 1, 3), r, dtype=np.float32))
    np.testing.assert_array_equal(out, np.concatenate(expected_rows))

    # -- allgather burst: async submissions land in one cycle and fuse
    # (controller allgather fusion); correctness must hold either way,
    # with mixed trailing shapes sharing the packed exchange.
    handles = [hvd.allgather_async(
        np.full((rank + 1, i + 2), 10.0 * rank + i, np.float32),
        name=f"ag_burst{i}") for i in range(4)]
    for i, h in enumerate(handles):
        out = hvd.synchronize(h)
        expected = np.concatenate([np.full((r + 1, i + 2), 10.0 * r + i,
                                           np.float32)
                                   for r in range(size)])
        np.testing.assert_array_equal(out, expected)

    # -- broadcast --------------------------------------------------------
    root = size - 1
    v = np.arange(6, dtype=np.float64) * (rank + 1)
    out = hvd.broadcast(v, root_rank=root, name="bc")
    np.testing.assert_array_equal(out,
                                  np.arange(6, dtype=np.float64) * (root + 1))

    # -- alltoall ---------------------------------------------------------
    splits = [2] * size
    v = np.arange(2 * size, dtype=np.float32) + 100 * rank
    out, recv_splits = hvd.alltoall(v, splits=splits, name="a2a")
    expected = np.concatenate(
        [np.arange(2 * r, 2 * r + 2, dtype=np.float32)
         + 100 * r + (2 * rank - 2 * r) for r in range(size)])
    # rank r sends rows [2*dest, 2*dest+2) to dest; we receive from each
    # peer their slice targeted at us.
    expected = np.concatenate(
        [np.arange(2 * rank, 2 * rank + 2, dtype=np.float32) + 100 * r
         for r in range(size)])
    np.testing.assert_array_equal(out, expected)
    np.testing.assert_array_equal(np.asarray(recv_splits), np.array([2] * size))

    # -- barrier ----------------------------------------------------------
    hvd.barrier()

    # -- steady-state cache loop -----------------------------------------
    for _ in range(5):
        out = hvd.allreduce(np.ones(4, dtype=np.float32), op=hvd.Sum,
                            name="steady")
        np.testing.assert_allclose(out, np.full(4, float(size)))


def battery_matrix(hvd, rank, size):
    """Reference-scale semantic sweep (VERDICT r2 item 6; modeled on the
    grid in /root/reference/test/parallel/test_torch.py, 2448 LoC): every
    wire dtype x {allreduce, grouped, allgather, broadcast, alltoall},
    prescale/postscale on floats, 64-bit exactness through the TCP plane,
    and grouped mismatch error cases."""
    import ml_dtypes

    int_dtypes = [np.int8, np.uint8, np.int32, np.int64]
    float_dtypes = [np.float16, ml_dtypes.bfloat16, np.float32, np.float64]

    # -- allreduce: every dtype, odd length (exercises ring chunking) ----
    for dt in int_dtypes + float_dtypes:
        tag = np.dtype(dt).name
        v = (np.arange(17) % 5 + rank + 1).astype(dt)
        out = hvd.allreduce(v, op=hvd.Sum, name=f"mx_ar_{tag}")
        expected = sum(
            (np.arange(17) % 5 + r + 1).astype(np.float64)
            for r in range(size))
        assert np.asarray(out).dtype == np.dtype(dt), (tag, out.dtype)
        np.testing.assert_allclose(np.asarray(out, np.float64), expected,
                                   rtol=1e-2 if np.dtype(dt).itemsize <= 2
                                   else 1e-6, err_msg=f"allreduce {tag}")

    # bool rides as logical-or under summation semantics.
    v = np.array([rank == 0, True, False])
    out = hvd.allreduce(v, op=hvd.Sum, name="mx_ar_bool")
    np.testing.assert_array_equal(np.asarray(out),
                                  np.array([True, True, False]))

    # -- 64-bit exactness: values that fp32 canonicalization would break
    # (the XLA plane must decline; the TCP ring is exact) ----------------
    big = np.array([2 ** 40 + rank, -(2 ** 50) + rank], dtype=np.int64)
    out = hvd.allreduce(big, op=hvd.Sum, name="mx_i64_exact")
    np.testing.assert_array_equal(
        np.asarray(out),
        np.array([size * 2 ** 40 + sum(range(size)),
                  -size * 2 ** 50 + sum(range(size))], dtype=np.int64))
    fine = np.array([1.0 + rank * 2.0 ** -40], dtype=np.float64)
    out = hvd.allreduce(fine, op=hvd.Sum, name="mx_f64_exact")
    np.testing.assert_array_equal(
        np.asarray(out),
        np.array([size * 1.0 + sum(range(size)) * 2.0 ** -40]))

    # -- prescale/postscale + average on every float dtype ---------------
    for dt in float_dtypes:
        tag = np.dtype(dt).name
        out = hvd.allreduce(np.ones(9, dt), op=hvd.Sum,
                            name=f"mx_scale_{tag}",
                            prescale_factor=2.0, postscale_factor=0.25)
        np.testing.assert_allclose(np.asarray(out, np.float64),
                                   np.full(9, size / 2.0), rtol=1e-2,
                                   err_msg=f"pre/post {tag}")
        out = hvd.allreduce((np.ones(9) * (rank + 1)).astype(dt),
                            op=hvd.Average, name=f"mx_avg_{tag}")
        np.testing.assert_allclose(
            np.asarray(out, np.float64),
            np.full(9, sum(range(1, size + 1)) / size), rtol=1e-2,
            err_msg=f"average {tag}")

    # -- grouped allreduce per dtype --------------------------------------
    for dt in (np.int32, np.float32, np.float64):
        tag = np.dtype(dt).name
        xs = [np.full(5 + i, rank + i + 1).astype(dt) for i in range(3)]
        outs = hvd.grouped_allreduce(xs, op=hvd.Sum, name=f"mx_gar_{tag}")
        for i, out in enumerate(outs):
            np.testing.assert_allclose(
                np.asarray(out, np.float64),
                np.full(5 + i, sum(r + i + 1 for r in range(size))),
                err_msg=f"grouped {tag}[{i}]")

    # -- allgather (ragged first dim) per dtype ---------------------------
    for dt in (np.uint8, np.int64, np.float16, np.float32, np.float64):
        tag = np.dtype(dt).name
        local = np.full((rank + 1, 2), rank + 1).astype(dt)
        out = hvd.allgather(local, name=f"mx_ag_{tag}")
        expected = np.concatenate([np.full((r + 1, 2), r + 1)
                                   for r in range(size)])
        np.testing.assert_array_equal(np.asarray(out, np.float64),
                                      expected, err_msg=f"allgather {tag}")

    # -- broadcast per dtype ----------------------------------------------
    root = size - 1
    for dt in (np.int8, np.int64, ml_dtypes.bfloat16, np.float64):
        tag = np.dtype(dt).name
        v = (np.arange(7) * (rank + 1)).astype(dt)
        out = hvd.broadcast(v, root_rank=root, name=f"mx_bc_{tag}")
        np.testing.assert_array_equal(
            np.asarray(out, np.float64),
            (np.arange(7) * (root + 1)).astype(dt).astype(np.float64),
            err_msg=f"broadcast {tag}")

    # -- alltoall (uneven splits) per dtype -------------------------------
    for dt in (np.int32, np.int64, np.float32):
        tag = np.dtype(dt).name
        splits = [rank + 1] * size
        v = (np.arange((rank + 1) * size) + 10 * rank).astype(dt)
        out, recv = hvd.alltoall(v, splits=splits, name=f"mx_a2a_{tag}")
        expected = np.concatenate(
            [(np.arange(rank * (r + 1), (rank + 1) * (r + 1))
              + 10 * r).astype(dt) for r in range(size)])
        np.testing.assert_array_equal(out, expected,
                                      err_msg=f"alltoall {tag}")
        np.testing.assert_array_equal(
            np.asarray(recv), np.arange(1, size + 1))

    # -- reducescatter: dtypes + the empty-chunk ragged edge --------------
    for dt in (np.int32, np.float32, np.float64):
        tag = np.dtype(dt).name
        x = (np.arange(2 * size * 2).reshape(2 * size, 2)
             * (rank + 1)).astype(dt)
        out = hvd.reducescatter(x, op=hvd.Sum, name=f"mx_rs_{tag}")
        total = (np.arange(2 * size * 2).reshape(2 * size, 2)
                 .astype(np.float64) * sum(r + 1 for r in range(size)))
        np.testing.assert_allclose(np.asarray(out, np.float64),
                                   total[rank * 2:(rank + 1) * 2],
                                   err_msg=f"reducescatter {tag}")
    if size > 1:
        # Fewer rows than ranks: the last rank's chunk is empty.
        y = np.ones((size - 1, 3), np.float32) * (rank + 1)
        out = hvd.reducescatter(y, op=hvd.Sum, name="mx_rs_empty")
        rows = 1 if rank < size - 1 else 0
        assert out.shape == (rows, 3), out.shape
        if rows:
            np.testing.assert_allclose(
                out, np.ones((1, 3)) * sum(r + 1 for r in range(size)))

    # -- grouped mismatch: shape desync inside a group must produce a
    # structured error on every rank, and the world must survive ---------
    shapes = [(4,), (5,) if rank == 0 else (6,)]
    try:
        hvd.grouped_allreduce(
            [np.ones(s, np.float32) for s in shapes],
            op=hvd.Sum, name="mx_gar_mismatch")
    except hvd.HorovodInternalError as e:
        assert "shape" in str(e).lower(), e
    else:
        raise AssertionError("expected HorovodInternalError (shape)")

    # dtype desync is likewise a structured error.
    dt = np.float32 if rank == 0 else np.float64
    try:
        hvd.allreduce(np.ones(4, dt), op=hvd.Sum, name="mx_dtype_mismatch")
    except hvd.HorovodInternalError as e:
        assert "type" in str(e).lower(), e
    else:
        raise AssertionError("expected HorovodInternalError (dtype)")

    # world still functional after both errors
    out = hvd.allreduce(np.ones(3, np.float32), op=hvd.Sum, name="mx_after")
    np.testing.assert_allclose(out, np.full(3, float(size)))


def battery_autotune(hvd, rank, size):
    """Autotuned (fusion threshold, cycle time) propagate from the
    coordinator to every rank via the ResponseList tuned_* fields
    (reference: Controller::SynchronizeParameters, controller.cc:39-53)."""
    from horovod_tpu.core import _global

    # warmup 1 sample x 2 steps + 3 scored samples x 2 steps, plus slack;
    # every allreduce is one counted cycle.
    for i in range(30):
        hvd.allreduce(np.ones(256, dtype=np.float32), op=hvd.Sum,
                      name=f"tune_{i % 3}")
    if rank == 0:
        assert _global.parameter_manager is not None
        assert _global.parameter_manager._done
        assert _global.controller.pending_tuned_params is None
    # The search may legitimately CONVERGE BACK to the default (the
    # initial setting is one of the scored samples), so assert liveness +
    # cross-rank consistency, not inequality; the deterministic
    # propagation check lives in test_controller.py.
    hvd.barrier()
    tuned = _global.controller.tensor_fusion_threshold
    assert (1 << 20) <= tuned <= (1 << 28), tuned
    gathered = hvd.allgather(np.array([[float(tuned)]]), name="tune_thr")
    assert np.all(np.asarray(gathered) == float(tuned)), \
        (rank, tuned, np.asarray(gathered))


def battery_algotune(hvd, rank, size):
    """ISSUE 18 acceptance (the negotiated half): the autotuner's
    algo x tree-threshold sweep proposes every candidate through
    ResponseList.tuned_algo / tuned_tree_threshold and pins the winner
    on EVERY rank's live TcpCollectives — selection inputs stay
    rank-symmetric end to end (the deadlock-freedom invariant)."""
    from horovod_tpu.core import _global

    # Window ladder at WARMUP=1, STEPS_PER_SAMPLE=1, BO_MAX_SAMPLES=1:
    # 1 warmup + 5 pipeline (4 candidates + pin) + 3 fused + 5 algo
    # + 1 BO ~= 15 counted cycles; 70 allreduces give generous slack.
    for i in range(70):
        hvd.allreduce(np.ones(256, dtype=np.float32), op=hvd.Sum,
                      name=f"algotune_{i % 3}")
    if rank == 0:
        pm = _global.parameter_manager
        assert pm is not None and pm._done
        assert pm._algo_candidates == []          # sweep ran to the end
        assert len(pm._algo_scores) == 4, pm._algo_scores
        assert _global.controller.pending_tuned_algo is None
    hvd.barrier()
    # The pinned winner reached every rank's dispatch layer identically
    # (tuned_algo is applied BEFORE dispatch on the broadcast cycle).
    from horovod_tpu.common.topology import ALGO_NAMES, algo_index
    colls = _global.tcp_collectives
    assert colls, "TCP data plane expected (HOROVOD_SHM_OPERATIONS=0)"
    algo, thr = colls[0].algo, colls[0].tree_threshold
    assert algo in ALGO_NAMES, algo
    assert all((c.algo, c.tree_threshold) == (algo, thr) for c in colls)
    gathered = np.asarray(hvd.allgather(
        np.array([[float(algo_index(algo)), float(thr)]]),
        name="algotune_verdict"))
    assert np.all(gathered == gathered[0]), (rank, algo, thr, gathered)


def battery_stall(hvd, rank, size):
    """Stall inspector end-to-end (reference: test/integration/
    test_stall.py + stall_inspector.cc): rank 0 submits a collective that
    rank 1 never joins; past HOROVOD_STALL_SHUTDOWN_TIME_SECONDS the
    coordinator aborts the job with a structured error instead of letting
    the world hang forever."""
    import time as _time

    if rank == 0:
        try:
            hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                          name="lonely")
        except hvd.HorovodInternalError:
            return
        raise AssertionError("stalled collective completed?!")
    # Other ranks: never submit; the shutdown must arrive on its own.
    deadline = _time.time() + 20
    from horovod_tpu.core import _global
    while _time.time() < deadline:
        if not _global.initialized or _global.shutdown_requested:
            return
        _time.sleep(0.2)
    raise AssertionError("stall shutdown never propagated to idle rank")


def battery_flow(hvd, rank, size):
    """ISSUE 12 acceptance (the runtime half): the seeded rank-gated
    collective from tests/fixtures/lint/flow/divergent_battery.py — the
    very file hvdflow flags with HVD601, naming the tainted branch and
    the two arms' fingerprint streams — is caught by strict-mode
    fingerprinting as a structured divergence ERROR on EVERY rank,
    naming the divergent op, within one negotiation cycle."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "fixtures", "lint", "flow"))
    import divergent_battery

    t = np.ones(64, np.float32)
    for i in range(3):
        out = hvd.allreduce(t, op=hvd.Sum, name=f"flow_warm{i}")
        np.testing.assert_allclose(np.asarray(out), t * size)
    seed = int(os.environ.get("HOROVOD_FLOW_SEED_RANK", "2"))
    try:
        divergent_battery.rank_gated_step(hvd, t, rank, seed)
    except Exception as exc:
        msg = str(exc)
        assert "fingerprint divergence" in msg.lower(), msg
        assert "flow_extra" in msg or "flow_step" in msg, msg
        print(f"FLOW_DIVERGENCE_CAUGHT rank={rank} {msg[:200]}",
              flush=True)
        return
    raise AssertionError("rank-gated collective completed without a "
                         "fingerprint divergence ERROR")


def battery_shard(hvd, rank, size):
    """ISSUE 17 acceptance (the runtime half): the seeded
    spec-divergent collective from tests/fixtures/lint/shard/
    divergent_spec_battery.py — the very file hvdshard flags with
    HVD803 — is caught by strict-mode op×name×dtype×dims×spec
    fingerprinting as a structured divergence ERROR on EVERY rank,
    naming the first spec-divergent op and both ranks' spec tokens."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "fixtures", "lint", "shard"))
    import divergent_spec_battery

    t = np.ones(64, np.float32)
    # Warm-up: a rank-INVARIANT spec folds identically everywhere —
    # annotated collectives must stay fingerprint-green.
    for i in range(3):
        out = hvd.allreduce(t, op=hvd.Sum, name=f"shard_warm{i}",
                            spec="(dp,*)")
        np.testing.assert_allclose(np.asarray(out), t * size)
    seed = int(os.environ.get("HOROVOD_SHARD_SEED_RANK", "1"))
    try:
        for _ in range(4):
            divergent_spec_battery.spec_gated_step(hvd, t, rank, seed)
    except Exception as exc:
        msg = str(exc)
        assert "fingerprint divergence" in msg.lower(), msg
        assert "shard_step" in msg, msg
        assert "spec=(dp,*)" in msg or "spec=(tp,*)" in msg, msg
        assert "--shard" in msg, msg          # the HVD803 cross-hint
        print(f"SHARD_DIVERGENCE_CAUGHT rank={rank} {msg[:240]}",
              flush=True)
        return
    raise AssertionError("spec-divergent collective completed without "
                         "a fingerprint divergence ERROR")


def battery_shard_compat(hvd, rank, size):
    """ISSUE 17 mixed-world leg: rank 1 pins wire proto 2 (pre-sharding
    schema), so every mesh negotiates FEATURE_SHARDING off — sp_spec is
    blanked at the wire and the fingerprint folds the 5-column identity
    on EVERY rank symmetrically.  The same spec-divergent step that
    kills the native-proto world must stay fingerprint-green here, with
    correct numerics."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "fixtures", "lint", "shard"))
    import divergent_spec_battery

    from horovod_tpu import core as _core
    from horovod_tpu.common import wire as _wire
    from horovod_tpu.runner.network import PeerMesh as _PeerMesh

    meshes = [r for r in _core.global_state().resources
              if isinstance(r, _PeerMesh)]
    assert meshes, "no TCP meshes formed"
    for m in meshes:
        assert m.negotiated_proto == 2, m.negotiated_proto
        assert not (m.negotiated_features & _wire.FEATURE_SHARDING), \
            m.negotiated_features

    t = np.ones(64, np.float32) * (rank + 1)
    want = np.ones(64, np.float32) * (size + 1) / 2   # default op: average
    for i in range(4):
        out = divergent_spec_battery.spec_gated_step(hvd, t, rank, 1)
        np.testing.assert_allclose(np.asarray(out), want)
    print(f"SHARD_COMPAT_GREEN rank={rank} proto=2", flush=True)


def battery_errors(hvd, rank, size):
    # Shape mismatch must raise a structured error on every rank, not hang.
    shape = (4,) if rank == 0 else (5,)
    try:
        hvd.allreduce(np.ones(shape, dtype=np.float32), op=hvd.Sum,
                      name="mismatch")
    except hvd.HorovodInternalError as e:
        assert "shape" in str(e).lower()
    else:
        raise AssertionError("expected HorovodInternalError")
    # The world must still be usable afterwards.
    out = hvd.allreduce(np.ones(4, dtype=np.float32), op=hvd.Sum,
                        name="after_mismatch")
    np.testing.assert_allclose(out, np.full(4, float(size)))


def battery_join(hvd, rank, size):
    # Uneven steps: every rank does `rank+1` allreduces, then joins.
    total = None
    for step in range(rank + 1):
        out = hvd.allreduce(np.ones(4, dtype=np.float32), op=hvd.Sum,
                            name=f"uneven_{step}")
        total = out
    joined_last = hvd.join()
    # Last step only ranks >= step participated... every completed allreduce
    # sums over all ranks still present; with zero stand-ins from joined
    # ranks the result is the count of non-joined participants — but rank
    # ordering of join is asynchronous, so only check the join result and
    # that the world survives.
    assert 0 <= joined_last < size
    out = hvd.allreduce(np.ones(2, dtype=np.float32), op=hvd.Sum,
                        name="after_join")
    np.testing.assert_allclose(out, np.full(2, float(size)))

    # Cached allgather + join: warm the cache, then have rank size-1
    # join while the others resubmit the cached name.  The joined rank
    # must NOT assert the cached allgather bit (it cannot fabricate a
    # shaped block) — it invalidates it, peers renegotiate, and
    # ConstructResponse surfaces the structured join-unsupported error
    # on the submitting ranks instead of a hang or a phantom execution.
    for _ in range(2):   # insert + steady-state hit
        hvd.allgather(np.full((rank + 1, 2), rank, np.float32),
                      name="join_ag")
    if rank == size - 1:
        hvd.join()
    else:
        try:
            hvd.allgather(np.full((rank + 1, 2), rank, np.float32),
                          name="join_ag")
            raise SystemExit("cached allgather with a joined rank "
                             "must error")
        except hvd.HorovodInternalError as e:
            assert "join" in str(e).lower(), e
        hvd.join()
    out = hvd.allreduce(np.ones(2, dtype=np.float32), op=hvd.Sum,
                        name="after_join2")
    np.testing.assert_allclose(out, np.full(2, float(size)))


def battery_adasum_np(hvd, rank, size):
    """Numpy-only Adasum VHDD semantics (no torch/TF imports — the
    framework delta-optimizer halves run at size 2 only; spinning up
    torch AND tensorflow in 4 more workers adds ~1 min of pure import
    serialization on 1-CPU CI for no extra coverage)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from horovod_tpu.ops.adasum import adasum_reference
    vecs = [np.linspace(0.1 * (r + 1), 1.0 * (r + 1), 16,
                        dtype=np.float64) for r in range(size)]
    out = hvd.allreduce(vecs[rank], op=hvd.Adasum, name="adasum0")
    expected = adasum_reference(vecs)
    np.testing.assert_allclose(out, expected, rtol=1e-10)


def battery_adasum(hvd, rank, size):
    battery_adasum_np(hvd, rank, size)
    from horovod_tpu.ops.adasum import adasum_reference

    # -- torch Adasum delta-optimizer (VERDICT r2 item 3; reference:
    #    torch/optimizer.py:335-503): one step must equal
    #    p0 + adasum([-lr * grad_r for each rank]).
    import torch
    import horovod_tpu.torch as hvt

    lr = 0.2
    torch.manual_seed(5)
    model = torch.nn.Linear(6, 3)
    hvt.broadcast_parameters(model.state_dict(), root_rank=0)
    p0 = {k: v.detach().clone() for k, v in model.named_parameters()}

    g = torch.Generator().manual_seed(17)
    X = torch.randn(4 * size, 6, generator=g)
    Y = torch.randn(4 * size, 3, generator=g)
    xs = X[rank * 4:(rank + 1) * 4]
    ys = Y[rank * 4:(rank + 1) * 4]

    opt = hvt.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=lr),
        named_parameters=model.named_parameters(), op=hvt.Adasum)
    loss = ((model(xs) - ys) ** 2).mean()
    loss.backward()
    opt.step()

    # Serial expectation: per-rank grads at p0 → deltas → adasum combine.
    ref = torch.nn.Linear(6, 3)
    ref.load_state_dict({k: v for k, v in p0.items()}, strict=False)
    per_rank_grads = {k: [] for k in p0}
    for r in range(size):
        ref.zero_grad()
        rl = ((ref(X[r * 4:(r + 1) * 4]) - Y[r * 4:(r + 1) * 4]) ** 2).mean()
        rl.backward()
        for k, v in ref.named_parameters():
            per_rank_grads[k].append(v.grad.detach().numpy().copy())
    for k, p in model.named_parameters():
        deltas = [(-lr * gr).reshape(-1).astype(np.float64)
                  for gr in per_rank_grads[k]]
        want = p0[k].numpy().reshape(-1) + adasum_reference(deltas)
        np.testing.assert_allclose(p.detach().numpy().reshape(-1), want,
                                   rtol=1e-5, atol=1e-7,
                                   err_msg=f"torch adasum param {k}")

    # backward_passes_per_step accumulation path runs end-to-end (fresh
    # model: hooks from the first optimizer stay registered on `model`).
    torch.manual_seed(6)
    model2 = torch.nn.Linear(6, 3)
    hvt.broadcast_parameters(model2.state_dict(), root_rank=0)
    opt2 = hvt.DistributedOptimizer(
        torch.optim.SGD(model2.parameters(), lr=0.05),
        named_parameters=model2.named_parameters(), op=hvt.Adasum,
        backward_passes_per_step=2)
    for _ in range(2):
        loss = ((model2(xs) - ys) ** 2).mean()
        loss.backward()
    opt2.step()
    opt2.zero_grad()

    # -- TF Adasum delta-optimizer (reference: tensorflow/__init__.py:
    #    504-598): same one-step semantic check.
    import tensorflow as tf
    import horovod_tpu.tensorflow as htf

    w0 = np.linspace(0.5, 1.5, 4).astype(np.float32)
    x_r = np.linspace(1.0, 2.0, 4).astype(np.float32) * (rank + 1)
    y_r = np.linspace(0.0, 1.0, 4).astype(np.float32) * (rank + 1)
    w = tf.Variable(w0)
    topt = htf.DistributedOptimizer(tf.keras.optimizers.SGD(lr),
                                    op=htf.Adasum)
    with tf.GradientTape() as tape:
        tf_loss = tf.reduce_mean((w * x_r - y_r) ** 2)
    (gw,) = tape.gradient(tf_loss, [w])
    topt.apply_gradients([(gw, w)])

    deltas = []
    for r in range(size):
        xr = np.linspace(1.0, 2.0, 4).astype(np.float64) * (r + 1)
        yr = np.linspace(0.0, 1.0, 4).astype(np.float64) * (r + 1)
        grad_r = 2.0 * xr * (w0.astype(np.float64) * xr - yr) / 4.0
        deltas.append(-lr * grad_r)
    want = w0.astype(np.float64) + adasum_reference(deltas)
    np.testing.assert_allclose(w.numpy().astype(np.float64), want,
                               rtol=1e-5, atol=1e-6,
                               err_msg="tf adasum variable")


def battery_torch(hvd, rank, size):
    """DistributedOptimizer end-to-end: sharded-batch DP training matches a
    single-process run on the full batch (the reference's core semantic,
    torch/optimizer.py)."""
    import torch
    import horovod_tpu.torch as hvt

    def make_model():
        torch.manual_seed(7)
        return torch.nn.Sequential(
            torch.nn.Linear(8, 16), torch.nn.Tanh(), torch.nn.Linear(16, 4))

    g = torch.Generator().manual_seed(42)
    X = torch.randn(4 * size, 8, generator=g)
    Y = torch.randn(4 * size, 4, generator=g)
    xs, ys = X[rank * 4:(rank + 1) * 4], Y[rank * 4:(rank + 1) * 4]

    def train(model, opt, inputs, targets, steps=3):
        for _ in range(steps):
            opt.zero_grad()
            loss = ((model(inputs) - targets) ** 2).mean()
            loss.backward()
            opt.step()

    # Distributed: per-rank shard + averaged gradients.
    model = make_model()
    opt = hvt.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    hvt.broadcast_parameters(model.state_dict(), root_rank=0)
    train(model, opt, xs, ys)

    # Serial baseline on the full batch (equal shards → full-batch grad ==
    # average of shard grads).
    serial = make_model()
    train(serial, torch.optim.SGD(serial.parameters(), lr=0.1), X, Y)

    for (name, p), (_, q) in zip(model.named_parameters(),
                                 serial.named_parameters()):
        np.testing.assert_allclose(p.detach().numpy(), q.detach().numpy(),
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"param {name} diverged")

    # Replicas must agree bit-for-bit with each other.
    for name, p in model.named_parameters():
        flat = p.detach().flatten().unsqueeze(0)
        gathered = hvt.allgather(flat, name=f"agree.{name}")
        for r in range(size):
            np.testing.assert_array_equal(gathered[r].numpy(),
                                          flat[0].numpy())

    # -- torch reducescatter: summed dim-0 slice --------------------------
    t = torch.arange(4 * size * 2, dtype=torch.float32).reshape(4 * size, 2) \
        * (rank + 1)
    out = hvt.reducescatter(t, op=hvt.Sum, name="t_rs")
    full = torch.arange(4 * size * 2, dtype=torch.float32) \
        .reshape(4 * size, 2) * sum(r + 1 for r in range(size))
    np.testing.assert_allclose(out.numpy(),
                               full[rank * 4:(rank + 1) * 4].numpy(),
                               rtol=1e-6)

    # Grouped + fp16-compressed + backward_passes_per_step variant runs.
    model2 = make_model()
    opt2 = hvt.DistributedOptimizer(
        torch.optim.SGD(model2.parameters(), lr=0.05),
        named_parameters=model2.named_parameters(),
        compression=hvt.Compression.fp16, backward_passes_per_step=2,
        groups=2)
    hvt.broadcast_parameters(model2.state_dict(), root_rank=0)
    for _ in range(2):  # 2 backward passes per step
        loss = ((model2(xs) - ys) ** 2).mean()
        loss.backward()
    opt2.step()
    opt2.zero_grad()

    # Optimizer-state broadcast: momentum buffers diverge (per-rank data),
    # then broadcast must reconcile them to rank 0's.
    m3 = make_model()
    opt3 = torch.optim.SGD(m3.parameters(), lr=0.1, momentum=0.9)
    loss = ((m3(xs) - ys) ** 2).mean()
    loss.backward()
    opt3.step()
    hvt.broadcast_optimizer_state(opt3, root_rank=0)
    for sid, s in sorted(opt3.state_dict()["state"].items()):
        for k, v in sorted(s.items()):
            if isinstance(v, torch.Tensor):
                flat = v.detach().flatten().unsqueeze(0)
                gathered = hvt.allgather(flat, name=f"opt3.{sid}.{k}")
                for r in range(size):
                    np.testing.assert_array_equal(gathered[r].numpy(),
                                                  gathered[0].numpy())


def battery_sparse(hvd, rank, size):
    """Gather-based sparse gradient reduction (reference: torch sparse
    path): embedding-style sparse grads with overlapping indices."""
    import torch
    import horovod_tpu.torch as hvt

    # Overlapping rows across ranks: row `rank` and row 0.
    idx = torch.tensor([[0, rank + 1]])
    val = torch.ones(2, 4) * (rank + 1)
    sp = torch.sparse_coo_tensor(idx, val, size=(size + 2, 4))
    out = hvt.sparse_allreduce(sp, name="sp0", op=hvt.Sum)
    dense = out.to_dense().numpy()
    np.testing.assert_allclose(dense[0], np.full(4, sum(
        r + 1 for r in range(size))))
    for r in range(size):
        np.testing.assert_allclose(dense[r + 1], np.full(4, float(r + 1)))

    # End-to-end: DistributedOptimizer with a sparse-grad embedding.
    torch.manual_seed(3)
    emb = torch.nn.Embedding(8, 4, sparse=True)
    opt = hvt.DistributedOptimizer(
        torch.optim.SGD(emb.parameters(), lr=0.1),
        named_parameters=emb.named_parameters())
    hvt.broadcast_parameters(emb.state_dict(), root_rank=0)
    before = emb.weight.detach().clone()
    tokens = torch.tensor([rank, rank])
    loss = emb(tokens).sum()
    opt.zero_grad()
    loss.backward()
    opt.step()
    after = emb.weight.detach()
    # Every rank must apply the identical averaged sparse update.
    gathered = hvd.allgather(after.numpy().reshape(1, -1), name="sp_w")
    for r in range(size):
        np.testing.assert_allclose(np.asarray(gathered)[r],
                                   after.numpy().reshape(-1), rtol=1e-6)
    assert not torch.allclose(before[rank], after[rank])


def battery_tensorflow(hvd, rank, size):
    """TF binding semantics across ranks (reference: test/parallel/
    test_tensorflow.py core cases): allreduce, broadcast_variables, and
    DistributedGradientTape gradient averaging."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as htf

    x = tf.constant(np.arange(8, dtype=np.float32) * (rank + 1))
    out = htf.allreduce(x, average=False, name="tf_ar")
    expected = np.arange(8, dtype=np.float32) * sum(
        r + 1 for r in range(size))
    np.testing.assert_allclose(out.numpy(), expected, rtol=1e-6)

    v = tf.Variable(np.full(4, float(rank), np.float32))
    htf.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), np.zeros(4))

    w = tf.Variable([float(rank + 1)])
    with tf.GradientTape() as tape:
        loss = w * w
    dtape = htf.DistributedGradientTape(tape)
    (g,) = dtape.gradient(loss, [w])
    expected_grad = np.mean([2.0 * (r + 1) for r in range(size)])
    np.testing.assert_allclose(g.numpy(), [expected_grad], rtol=1e-6)

    gathered = htf.allgather(tf.constant([float(rank)]), name="tf_ag")
    np.testing.assert_allclose(gathered.numpy(),
                               np.arange(size, dtype=np.float32))

    # reducescatter: summed dim-0 slice + gradient round-trip.
    t = tf.constant(np.arange(2 * size * 3, dtype=np.float32)
                    .reshape(2 * size, 3) * (rank + 1))
    with tf.GradientTape() as tape:
        tape.watch(t)
        rs = htf.reducescatter(t, op=htf.Sum, name="tf_rs")
        loss = tf.reduce_sum(rs)
    full = np.arange(2 * size * 3, dtype=np.float32).reshape(2 * size, 3) \
        * sum(r + 1 for r in range(size))
    np.testing.assert_allclose(rs.numpy(),
                               full[rank * 2:(rank + 1) * 2], rtol=1e-6)
    g = tape.gradient(loss, t)
    np.testing.assert_allclose(g.numpy(), np.ones((2 * size, 3)),
                               rtol=1e-6)


def battery_tf_function(hvd, rank, size):
    """Graph-mode TF binding (VERDICT r1 item 4): collectives must survive
    tf.function tracing, gradients must be registered, model.fit with
    DistributedOptimizer must match serial, backward_passes_per_step must
    aggregate, sync-BN must use global moments, and Keras elastic state
    must commit/restore/sync."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as htf

    # -- collective inside tf.function (compiled twice = steady state) ---
    @tf.function
    def compiled_ar(x):
        return htf.allreduce(x, op=htf.Sum, name="tff_ar")

    for _ in range(2):
        out = compiled_ar(tf.constant([1.0, 2.0]) * (rank + 1))
    np.testing.assert_allclose(
        out.numpy(), np.array([1.0, 2.0]) * sum(r + 1 for r in range(size)),
        rtol=1e-6)

    # -- compiled model.fit parity with serial ---------------------------
    def make_model():
        tf.keras.utils.set_random_seed(11)
        return tf.keras.Sequential([
            tf.keras.layers.Input(shape=(6,)),
            tf.keras.layers.Dense(8, activation="tanh"),
            tf.keras.layers.Dense(3)])

    rng = np.random.default_rng(5)
    X = rng.standard_normal((8 * size, 6)).astype(np.float32)
    Y = rng.standard_normal((8 * size, 3)).astype(np.float32)
    xs, ys = X[rank * 8:(rank + 1) * 8], Y[rank * 8:(rank + 1) * 8]

    model = make_model()
    opt = htf.DistributedOptimizer(tf.keras.optimizers.SGD(0.1))
    model.compile(optimizer=opt, loss="mse")
    model.fit(xs, ys, batch_size=8, epochs=3, shuffle=False, verbose=0,
              callbacks=[htf.BroadcastGlobalVariablesCallback(0)])

    serial = make_model()
    serial.compile(optimizer=tf.keras.optimizers.SGD(0.1), loss="mse")
    serial.fit(X, Y, batch_size=8 * size, epochs=3, shuffle=False,
               verbose=0)
    for p, q in zip(model.get_weights(), serial.get_weights()):
        np.testing.assert_allclose(p, q, rtol=1e-4, atol=1e-5)

    # -- backward_passes_per_step aggregation (eager apply path) ---------
    v = tf.Variable([10.0])
    agg_opt = htf.DistributedOptimizer(
        tf.keras.optimizers.SGD(1.0), backward_passes_per_step=2)
    agg_opt.apply_gradients([(tf.constant([1.0]), v)])
    np.testing.assert_allclose(v.numpy(), [10.0])   # accumulated only
    agg_opt.apply_gradients([(tf.constant([3.0]), v)])
    # applied: lr * avg-of-2-passes allreduced average = (1+3)/2 = 2
    np.testing.assert_allclose(v.numpy(), [8.0], rtol=1e-6)

    # -- sparse IndexedSlices allreduce ----------------------------------
    sp = tf.IndexedSlices(
        values=tf.constant([[1.0, 2.0]]) * (rank + 1),
        indices=tf.constant([rank], dtype=tf.int64),
        dense_shape=tf.constant([size + 1, 2], dtype=tf.int64))
    red = htf.allreduce(sp, op=htf.Average, name="tff_sparse")
    dense = tf.math.unsorted_segment_sum(
        red.values, red.indices, size + 1).numpy()
    for r in range(size):
        np.testing.assert_allclose(
            dense[r], np.array([1.0, 2.0]) * (r + 1) / size, rtol=1e-6)

    # -- SyncBatchNormalization: global moments --------------------------
    g = np.random.default_rng(3)
    full = g.standard_normal((4 * size, 5)).astype(np.float32)
    local = full[rank * 4:(rank + 1) * 4]
    sbn = htf.SyncBatchNormalization(momentum=0.5, epsilon=1e-3)
    out = sbn(tf.constant(local), training=True).numpy()
    mean, var = full.mean(axis=0), full.var(axis=0)
    expected = (local - mean) / np.sqrt(var + 1e-3)
    np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-4)

    # -- Keras elastic state ---------------------------------------------
    state = htf.TensorFlowKerasState(model, opt, epoch=0)
    state.save()
    w0 = [w.copy() for w in model.get_weights()]
    model.set_weights([w * 0 for w in w0])
    state.restore()
    for a, b in zip(model.get_weights(), w0):
        np.testing.assert_array_equal(a, b)
    # Divergent weights re-sync to rank 0's.
    model.set_weights([w + rank for w in w0])
    state.sync()
    for a, b in zip(model.get_weights(), w0):
        np.testing.assert_allclose(a, b)


def battery_syncbn(hvd, rank, size):
    """SyncBatchNorm forward/backward == single-process BN on the full
    batch (reference: torch/sync_batch_norm.py semantics)."""
    import torch
    import horovod_tpu.torch as hvt

    g = torch.Generator().manual_seed(3)
    X = torch.randn(2 * size, 5, 4, 4, generator=g)
    xs = X[rank * 2:(rank + 1) * 2].clone().requires_grad_(True)

    bn = hvt.SyncBatchNorm(5)
    bn.train()
    out = bn(xs)
    loss = (out ** 2).mean() * size  # scale: serial mean is over size× rows
    loss.backward()

    ref_x = X.clone().requires_grad_(True)
    ref_bn = torch.nn.BatchNorm2d(5)
    ref_bn.train()
    ref_out = ref_bn(ref_x)
    ref_loss = (ref_out ** 2).mean()
    ref_loss.backward()

    np.testing.assert_allclose(
        out.detach().numpy(),
        ref_out[rank * 2:(rank + 1) * 2].detach().numpy(),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        xs.grad.numpy(), ref_x.grad[rank * 2:(rank + 1) * 2].numpy(),
        rtol=1e-3, atol=1e-6)
    np.testing.assert_allclose(bn.running_mean.numpy(),
                               ref_bn.running_mean.numpy(),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(bn.running_var.numpy(),
                               ref_bn.running_var.numpy(),
                               rtol=1e-3, atol=1e-5)


def battery_xla(hvd, rank, size):
    """XLA/ICI data plane (VERDICT r1 item 3): the eager core's op chain
    must select the XlaBackend when the JAX world spans the ranks, execute
    device collectives, and fall back to TCP for unsupported ops
    (reference: operations.cc:143-252 Enabled()-priority)."""
    import jax

    assert jax.process_count() == size, jax.process_count()
    from horovod_tpu.core import _global
    names = [b.name for b in _global.op_manager.backends]
    assert names[0] == "xla", names

    x = np.arange(32, dtype=np.float32) + rank
    out = hvd.allreduce(x, op=hvd.Sum, name="xla_ar")
    np.testing.assert_allclose(
        out, np.arange(32, dtype=np.float32) * size + sum(range(size)),
        rtol=1e-6)
    # The XLA backend must actually have executed (compiled-program cache
    # is the lazy-communicator analogue, nccl_operations.cc:61-94).
    xla_backend = _global.op_manager.backends[0]
    assert xla_backend.comm._cache, "xla backend never executed"

    # fp16 rides the widened fp32 accumulation path.
    v = np.ones(16, dtype=np.float16) * (rank + 1)
    out = hvd.allreduce(v, op=hvd.Sum, name="xla_fp16")
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.full(16, sum(range(1, size + 1))))

    # Average + prescale go through the same fused program.
    out = hvd.allreduce(x, op=hvd.Average, name="xla_avg")
    np.testing.assert_allclose(
        out, (np.arange(32, dtype=np.float32) * size
              + sum(range(size))) / size, rtol=1e-6)

    # Broadcast on-device. float64 broadcast falls through to TCP unless
    # x64 is enabled — use float32 to stay on the device plane.
    b = np.arange(8, dtype=np.float32) * (rank + 1)
    out = hvd.broadcast(b, root_rank=1, name="xla_bc")
    np.testing.assert_array_equal(out, np.arange(8, dtype=np.float32) * 2)

    # Ragged allgather rides the device plane (VERDICT r2 item 2: the
    # NCCLAllgather analogue, nccl_operations.cc:434-559).
    gathered = hvd.allgather(np.full((rank + 1, 2), rank, np.float32),
                             name="xla_ag")
    expected = np.concatenate([np.full((r + 1, 2), r, np.float32)
                               for r in range(size)])
    np.testing.assert_array_equal(gathered, expected)
    assert any(k[0] == "allgather" for k in xla_backend.comm._cache), \
        "allgather did not ride the XLA plane"

    # Fused allgather on the device plane: a multi-entry response moves
    # every entry's packed bytes in ONE padded all-gather (direct
    # lockstep call, as in the shm/hierarchical batteries).
    from horovod_tpu.common.dtypes import from_any
    from horovod_tpu.common.message import Response, ResponseType
    from horovod_tpu.common.tensor_queue import TensorTableEntry
    fents = [TensorTableEntry(
        tensor_name=f"xla_fag{i}",
        tensor=np.full((rank + 1, i + 1), 10.0 * rank + i, np.float32))
        for i in range(2)]
    fsizes = []
    for i in range(2):
        fsizes.extend(r + 1 for r in range(size))
    fresp = Response(response_type=ResponseType.ALLGATHER,
                     tensor_names=[e.tensor_name for e in fents],
                     tensor_type=from_any(np.dtype(np.float32)),
                     tensor_sizes=fsizes)
    fst = xla_backend.allgather(fresp, fents)
    assert fst.ok_p(), fst
    for i, e in enumerate(fents):
        expected = np.concatenate([np.full((r + 1, i + 1), 10.0 * r + i,
                                           np.float32)
                                   for r in range(size)])
        np.testing.assert_array_equal(e.output, expected)

    # Ragged alltoall on-device (NCCLAlltoall analogue).
    splits = [rank + 1] * size
    v = np.arange((rank + 1) * size, dtype=np.float32) + 1000 * rank
    out, recv = hvd.alltoall(v, splits=splits, name="xla_a2a")
    expected = np.concatenate(
        [np.arange(rank * (r + 1), (rank + 1) * (r + 1), dtype=np.float32)
         + 1000 * r for r in range(size)])
    np.testing.assert_array_equal(out, expected)
    np.testing.assert_array_equal(np.asarray(recv),
                                  np.array([r + 1 for r in range(size)]))
    assert any(k[0] == "alltoall" for k in xla_backend.comm._cache), \
        "alltoall did not ride the XLA plane"

    # Even reducescatter on-device (true reduce-scatter, half the bytes of
    # allreduce+slice); ragged dim-0 falls through to TCP.
    x = np.arange(4 * size * 3, dtype=np.float32).reshape(4 * size, 3) \
        * (rank + 1)
    out = hvd.reducescatter(x, op=hvd.Sum, name="xla_rs")
    full = np.arange(4 * size * 3, dtype=np.float32).reshape(4 * size, 3) \
        * sum(r + 1 for r in range(size))
    np.testing.assert_allclose(out, full[rank * 4:(rank + 1) * 4],
                               rtol=1e-6)
    assert any(k[0] == "reducescatter" for k in xla_backend.comm._cache), \
        "reducescatter did not ride the XLA plane"

    ragged = np.ones((size + 1, 2), dtype=np.float32) * (rank + 1)
    out = hvd.reducescatter(ragged, op=hvd.Sum, name="xla_rs_ragged")
    rows = (size + 1) // size + (1 if rank < (size + 1) % size else 0)
    np.testing.assert_allclose(
        out, np.ones((rows, 2), np.float32) * sum(
            r + 1 for r in range(size)), rtol=1e-6)

    # Steady-state cached cycles stay on the device plane.
    for _ in range(5):
        out = hvd.allreduce(np.ones(4, dtype=np.float32), op=hvd.Sum,
                            name="xla_steady")
        np.testing.assert_allclose(out, np.full(4, float(size)))



def battery_mxnet(hvd, rank, size):
    """MXNet binding semantics against the stub module (reference:
    test/parallel/test_mxnet1.py / test_mxnet2.py patterns)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import mxnet_stub
    mx = mxnet_stub.install()
    import horovod_tpu.mxnet as hmx

    # -- average allreduce (out-of-place NDArray) -------------------------
    x = mx.nd.array(np.arange(8, dtype=np.float32) + rank)
    out = hmx.allreduce(x, average=True, name="mx_avg")
    np.testing.assert_allclose(
        out.asnumpy(), np.arange(8, dtype=np.float32) + (size - 1) / 2)

    # -- in-place sum with prescale --------------------------------------
    y = mx.nd.array(np.ones(4, dtype=np.float32) * (rank + 1))
    hmx.allreduce_(y, average=False, name="mx_sum", prescale_factor=0.5)
    np.testing.assert_allclose(
        y.asnumpy(), np.full(4, 0.5 * sum(range(1, size + 1))))

    # -- allgather (variable first dim) ----------------------------------
    g = mx.nd.array(np.full((rank + 1, 2), rank, dtype=np.float32))
    out = hmx.allgather(g, name="mx_ag")
    assert out.shape == (sum(r + 1 for r in range(size)), 2), out.shape

    # -- broadcast --------------------------------------------------------
    b = mx.nd.array(np.full(3, rank, dtype=np.float32))
    out = hmx.broadcast(b, root_rank=0, name="mx_bc")
    np.testing.assert_allclose(out.asnumpy(), np.zeros(3))

    # -- alltoall (equal splits) -----------------------------------------
    a = mx.nd.array(np.arange(size * 2, dtype=np.float32) + 100 * rank)
    out = hmx.alltoall(a, name="mx_a2a")
    exp = np.concatenate([np.arange(2, dtype=np.float32) + 2 * rank + 100 * r
                          for r in range(size)])
    np.testing.assert_allclose(out.asnumpy(), exp)

    # -- grouped in-place -------------------------------------------------
    gs = [mx.nd.array(np.full(4, rank + i, dtype=np.float32))
          for i in range(3)]
    hmx.grouped_allreduce_(gs, average=False, name="mx_gar")
    for i, t in enumerate(gs):
        np.testing.assert_allclose(
            t.asnumpy(), np.full(4, float(sum(r + i for r in range(size)))))

    # -- DistributedTrainer: weights agree and equal mean-gradient SGD ----
    params = [mx.gluon.Parameter(f"w{i}", np.ones(4, dtype=np.float32)
                                 * (i + 1)) for i in range(3)]
    for i, p in enumerate(params):
        p.list_grad()[0][:] = np.full(4, (rank + 1) * (i + 1),
                                      dtype=np.float32)
    trainer = hmx.DistributedTrainer(
        params, "sgd", optimizer_params={"learning_rate": 0.1})
    trainer.step(batch_size=1)
    for i, p in enumerate(params):
        mean = np.mean([(r + 1) * (i + 1) for r in range(size)])
        np.testing.assert_allclose(
            p.data().asnumpy(), np.ones(4) * (i + 1) - 0.1 * mean,
            rtol=1e-5)

    # -- num_groups grouped path -----------------------------------------
    params2 = [mx.gluon.Parameter(f"v{i}", np.zeros(2, dtype=np.float32))
               for i in range(4)]
    for i, p in enumerate(params2):
        p.list_grad()[0][:] = np.full(2, float(rank + i), dtype=np.float32)
    tr2 = hmx.DistributedTrainer(
        params2, "sgd", optimizer_params={"learning_rate": 1.0},
        prefix="g2", num_groups=2)
    tr2.step(batch_size=1)
    for i, p in enumerate(params2):
        mean = np.mean([r + i for r in range(size)])
        np.testing.assert_allclose(p.data().asnumpy(),
                                   np.full(2, -mean), rtol=1e-5)

    # -- DistributedOptimizer: sum-allreduce + rescale fold ---------------
    opt = hmx.DistributedOptimizer(
        mx.optimizer.SGD(learning_rate=0.5, rescale_grad=1.0))
    w = mx.nd.array(np.zeros(3, dtype=np.float32))
    gr = mx.nd.array(np.full(3, float(rank + 1), dtype=np.float32))
    opt.update(7, w, gr, None)
    exp_w = -0.5 * (1.0 / size) * sum(range(1, size + 1))
    np.testing.assert_allclose(w.asnumpy(), np.full(3, exp_w), rtol=1e-5)

    # -- broadcast_parameters --------------------------------------------
    pd = {f"p{i}": mx.gluon.Parameter(
        f"p{i}", np.full(2, float(rank * (i + 1)), dtype=np.float32))
        for i in range(2)}
    hmx.broadcast_parameters(pd, root_rank=0)
    for i in range(2):
        np.testing.assert_allclose(pd[f"p{i}"].data().asnumpy(),
                                   np.zeros(2))

    # -- deferred-init param: broadcast rides the post-init hook ----------
    dp = mx.gluon.Parameter("deferred")          # no data yet
    hmx.broadcast_parameters({"d": dp}, root_rank=0)
    dp._init_impl(np.full(3, float(rank + 1), dtype=np.float32))
    np.testing.assert_allclose(dp.data().asnumpy(), np.ones(3))



def battery_shm(hvd, rank, size):
    """Same-host shared-memory data plane (reference parity: Gloo shm
    transport / MPI shared-memory windows): the op chain must select the
    shm backend for allreduce on a same-host world, produce flat-path
    results, fall through to TCP above the region capacity, and keep the
    lockstep consistent across a mixed op stream."""
    from horovod_tpu.core import _global

    names = [b.name for b in _global.op_manager.backends]
    assert "shm" in names and names.index("shm") < names.index("tcp"), names
    shm = _global.op_manager.backends[names.index("shm")]
    assert shm.world.formed

    import ml_dtypes
    for dt, rtol in ((np.float32, 1e-6), (np.float64, 0),
                     (np.int64, 0), (ml_dtypes.bfloat16, 1e-2),
                     (np.float16, 1e-2)):
        v = (np.arange(1001) % 7 + rank + 1).astype(dt)
        out = hvd.allreduce(v, op=hvd.Sum, name=f"shm_{np.dtype(dt).name}")
        expected = sum((np.arange(1001) % 7 + r + 1).astype(np.float64)
                       for r in range(size))
        assert np.asarray(out).dtype == np.dtype(dt)
        np.testing.assert_allclose(np.asarray(out, np.float64), expected,
                                   rtol=rtol)
    # bool rides logical-or semantics like the TCP plane.
    out = hvd.allreduce(np.array([rank == 0, False]), op=hvd.Sum,
                        name="shm_bool")
    np.testing.assert_array_equal(np.asarray(out), [True, False])

    executed = shm.ops_executed
    assert executed >= 6, executed

    # Average + scales ride the same path.
    out = hvd.allreduce(np.ones(17, np.float32) * (rank + 1),
                        op=hvd.Average, name="shm_avg")
    np.testing.assert_allclose(out,
                               np.full(17, (size + 1) / 2), rtol=1e-6)

    # Grouped/fused multi-entry response through pack/unpack.
    xs = [np.full((3 + i,), rank + i, dtype=np.float32) for i in range(3)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Sum, name="shm_gar")
    for i, o in enumerate(outs):
        np.testing.assert_allclose(
            o, np.full((3 + i,), sum(r + i for r in range(size))))

    # Above-capacity payload falls through to the TCP ring (capacity is
    # pinned to 1 MB by the battery env below).
    before = shm.ops_executed
    big = np.ones((1 << 20) // 2, dtype=np.float32) * (rank + 1)  # 2 MB
    out = hvd.allreduce(big, op=hvd.Sum, name="shm_big")
    np.testing.assert_allclose(out[:8],
                               np.full(8, sum(range(1, size + 1))))
    assert shm.ops_executed == before, "oversized op must ride TCP"

    # Broadcast rides shm (root writes once, peers read the region).
    before = shm.ops_executed
    root = size - 1
    v = np.arange(12, dtype=np.float64).reshape(3, 4) * (rank + 1)
    out = hvd.broadcast(v, root_rank=root, name="shm_bc")
    np.testing.assert_array_equal(
        out, np.arange(12, dtype=np.float64).reshape(3, 4) * (root + 1))
    assert shm.ops_executed == before + 1, "broadcast must ride shm"

    # Scalar broadcast keeps 0-d shape ON EVERY RANK (regression: numpy
    # ascontiguousarray promotes 0-d to 1-d, which broke TF's
    # BroadcastGlobalVariables on the optimizer iteration counter).
    s = hvd.broadcast(np.float32(7.5 * (rank + 1)), root_rank=0,
                      name="shm_bc_scalar")
    assert np.asarray(s).shape == (), np.asarray(s).shape
    assert float(np.asarray(s)) == 7.5
    assert shm.ops_executed == before + 2, "scalar bcast must ride shm"

    # Ragged allgather rides shm (per-rank blocks from owners' regions).
    g = hvd.allgather(np.full((rank + 1, 2), rank, np.float32),
                      name="shm_ag")
    expected = np.concatenate([np.full((r + 1, 2), r, np.float32)
                               for r in range(size)])
    np.testing.assert_array_equal(g, expected)
    assert shm.ops_executed == before + 3, "allgather must ride shm"

    # Fused allgather rides shm in ONE staging pass: the response packs
    # three tensors (entry-major per rank), yet ops_executed moves by 1.
    from horovod_tpu.common.dtypes import from_any
    from horovod_tpu.common.message import Response, ResponseType
    from horovod_tpu.common.tensor_queue import TensorTableEntry
    before = shm.ops_executed
    ents = [TensorTableEntry(
        tensor_name=f"shm_fag{i}",
        tensor=np.full((rank + 1, i + 1), 10.0 * rank + i, np.float32))
        for i in range(3)]
    fsizes = []
    for i in range(3):
        fsizes.extend(r + 1 for r in range(size))
    fresp = Response(response_type=ResponseType.ALLGATHER,
                     tensor_names=[e.tensor_name for e in ents],
                     tensor_type=from_any(np.dtype(np.float32)),
                     tensor_sizes=fsizes)
    assert shm.enabled(fresp, ents), "fused allgather must ride shm"
    st = shm.allgather(fresp, ents)
    assert st.ok_p(), st
    for i, e in enumerate(ents):
        expected = np.concatenate([np.full((r + 1, i + 1), 10.0 * r + i,
                                           np.float32)
                                   for r in range(size)])
        np.testing.assert_array_equal(e.output, expected)
    assert shm.ops_executed == before + 1, "fused allgather is ONE shm op"

    # Alltoall rides shm (uneven splits; receivers pull their slice from
    # each sender's region using the header split table).
    before = shm.ops_executed
    splits = [rank + 1] * size
    v = (np.arange((rank + 1) * size, dtype=np.float32) + 10 * rank)
    a2a, recv = hvd.alltoall(v, splits=splits, name="shm_a2a")
    expected = np.concatenate(
        [(np.arange(rank * (r + 1), (rank + 1) * (r + 1))
          + 10 * r).astype(np.float32) for r in range(size)])
    np.testing.assert_array_equal(a2a, expected)
    np.testing.assert_array_equal(np.asarray(recv),
                                  np.arange(1, size + 1))
    assert shm.ops_executed == before + 1, "alltoall must ride shm"

    # Reducescatter rides shm (uneven rows; last rank may get fewer).
    before = shm.ops_executed
    x = (np.arange((2 * size + 1) * 3, dtype=np.float32)
         .reshape(2 * size + 1, 3) * (rank + 1))
    out = hvd.reducescatter(x, op=hvd.Sum, name="shm_rs")
    total = (np.arange((2 * size + 1) * 3, dtype=np.float64)
             .reshape(2 * size + 1, 3) * sum(r + 1 for r in range(size)))
    base, rem = divmod(2 * size + 1, size)
    starts = [r * base + min(r, rem) for r in range(size + 1)]
    np.testing.assert_allclose(np.asarray(out, np.float64),
                               total[starts[rank]:starts[rank + 1]],
                               rtol=1e-6)
    assert shm.ops_executed == before + 1, "reducescatter must ride shm"

    # Oversized alltoall (2 MB > the 1 MB battery capacity): every rank
    # delegates to the TCP exchange mid-protocol via the header flag.
    rows_per_dst = (2 << 20) // 4 // size + 1   # ~2 MB total buffer
    v = np.arange(rows_per_dst * size, dtype=np.float32) + 1000 * rank
    a2a, recv = hvd.alltoall(v, splits=[rows_per_dst] * size,
                             name="shm_a2a_big")
    expected = np.concatenate(
        [np.arange(rank * rows_per_dst, (rank + 1) * rows_per_dst,
                   dtype=np.float32) + 1000 * r for r in range(size)])
    np.testing.assert_array_equal(a2a, expected)
    assert shm.ops_executed == before + 1, "oversized a2a must delegate"

    for i in range(5):
        out = hvd.allreduce(np.ones(4, np.float32), op=hvd.Sum,
                            name="shm_steady")
        np.testing.assert_allclose(out, np.full(4, float(size)))


def battery_hierarchical(hvd, rank, size):
    """Two-level eager allreduce/allgather (VERDICT r3 item 3; reference:
    NCCLHierarchicalAllreduce, nccl_operations.cc:187-398, and
    MPIHierarchicalAllgather): with HOROVOD_HIERARCHICAL_* set the op
    chain must select the hierarchical backend, produce results equal to
    the flat path, and actually execute the two-leg schedule (per-leg
    byte counters prove the path taken — the cross leg must carry only
    1/local_size of the payload)."""
    from horovod_tpu.core import _global

    names = [b.name for b in _global.op_manager.backends]
    assert "tcp-hierarchical" in names, names
    assert names.index("tcp-hierarchical") < names.index("tcp"), names
    hier = _global.op_manager.backends[names.index("tcp-hierarchical")]
    lsize = hvd.local_size()
    if os.environ.get("HOROVOD_SHM_OPERATIONS") == "0":
        assert hier.shm_local is None   # TCP local legs under test
    else:
        # Localhost "hosts" share one memory domain: the intra-host legs
        # must ride the per-host shm world.
        assert hier.shm_local is not None and hier.shm_local.formed

    # -- allreduce sum, odd length (uneven shard bounds) ------------------
    x = np.arange(17, dtype=np.float32) + rank
    out = hvd.allreduce(x, op=hvd.Sum, name="h_ar")
    flat_expected = np.arange(17, dtype=np.float32) * size + sum(range(size))
    np.testing.assert_allclose(out, flat_expected, rtol=1e-6)
    assert hier.leg_ops["local_rs"] == 1, hier.leg_ops
    assert hier.leg_ops["cross_ar"] == 1, hier.leg_ops
    assert hier.leg_ops["local_ag"] == 1, hier.leg_ops

    # -- average + pre/postscale -----------------------------------------
    out = hvd.allreduce(x, op=hvd.Average, name="h_avg")
    np.testing.assert_allclose(out, flat_expected / size, rtol=1e-6)
    out = hvd.allreduce(np.ones(8, dtype=np.float32), op=hvd.Sum,
                        name="h_scale", prescale_factor=2.0,
                        postscale_factor=0.5)
    np.testing.assert_allclose(out, np.full(8, float(size)), rtol=1e-6)

    # -- cross leg carries exactly 1/local_size of an even payload --------
    before_rs = hier.leg_bytes["local_rs"]
    before_ar = hier.leg_bytes["cross_ar"]
    out = hvd.allreduce(np.ones(64 * lsize, dtype=np.float32), op=hvd.Sum,
                        name="h_ratio")
    np.testing.assert_allclose(out, np.full(64 * lsize, float(size)))
    d_rs = hier.leg_bytes["local_rs"] - before_rs
    d_ar = hier.leg_bytes["cross_ar"] - before_ar
    assert d_rs == 64 * lsize * 4 and d_ar == 64 * 4, (d_rs, d_ar)

    # -- grouped (fused multi-entry response through pack/unpack) ---------
    xs = [np.full((5 + i,), rank + i, dtype=np.float32) for i in range(3)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Sum, name="h_gar")
    for i, o in enumerate(outs):
        np.testing.assert_allclose(
            o, np.full((5 + i,), sum(r + i for r in range(size))))

    # -- 16-bit wire dtypes ------------------------------------------------
    import ml_dtypes
    for dt, tag in ((np.float16, "fp16"), (ml_dtypes.bfloat16, "bf16")):
        v = np.ones(33, dtype=dt) * (rank + 1)
        out = hvd.allreduce(v, op=hvd.Sum, name=f"h_{tag}")
        np.testing.assert_allclose(np.asarray(out, np.float64),
                                   np.full(33, sum(range(1, size + 1))))

    # -- tiny tensor: empty shards on some local ranks --------------------
    out = hvd.allreduce(np.array([float(rank)], np.float32), op=hvd.Sum,
                        name="h_tiny")
    np.testing.assert_allclose(out, [float(sum(range(size)))])

    # -- hierarchical allgather (ragged first dims) -----------------------
    local = np.full((rank + 1, 3), rank, dtype=np.float32)
    out = hvd.allgather(local, name="h_ag")
    expected = np.concatenate([np.full((r + 1, 3), r, np.float32)
                               for r in range(size)])
    np.testing.assert_array_equal(out, expected)
    assert hier.leg_ops["local_gather"] >= 1, hier.leg_ops
    assert hier.leg_ops["cross_gather"] >= 1, hier.leg_ops

    # -- fused allgather: N entries ride TWO collectives with leg spans --
    # Direct lockstep call (every rank executes the same fused response
    # at the same program point — the identical-response-order invariant
    # the background loop provides for real fused responses).
    from horovod_tpu.common.dtypes import from_any
    from horovod_tpu.common.message import Response, ResponseType
    from horovod_tpu.common.tensor_queue import TensorTableEntry

    tl_path = f"/tmp/h_tl_{os.environ['HOROVOD_RENDEZVOUS_EPOCH']}.json"
    if rank == 0:
        hvd.start_timeline(tl_path)
    before = dict(hier.leg_ops)
    ents = [TensorTableEntry(
        tensor_name=f"h_fag{i}",
        tensor=np.full((rank + 1, i + 1), 10 * rank + i, np.float32))
        for i in range(3)]
    sizes = []
    for i in range(3):
        sizes.extend(r + 1 for r in range(size))
    resp = Response(response_type=ResponseType.ALLGATHER,
                    tensor_names=[e.tensor_name for e in ents],
                    tensor_type=from_any(np.dtype(np.float32)),
                    tensor_sizes=sizes)
    st = hier.allgather(resp, ents)
    assert st.ok_p(), st
    for i, e in enumerate(ents):
        expected = np.concatenate([np.full((r + 1, i + 1), 10 * r + i,
                                           np.float32)
                                   for r in range(size)])
        np.testing.assert_array_equal(e.output, expected)
    # 3 fused tensors -> exactly one local gather + one cross exchange.
    assert hier.leg_ops["local_gather"] == before["local_gather"] + 1, \
        hier.leg_ops
    assert hier.leg_ops["cross_gather"] == before["cross_gather"] + 1, \
        hier.leg_ops
    if rank == 0:
        hvd.stop_timeline()
        import json
        names = {ev.get("name", "")
                 for ev in json.load(open(tl_path))}
        assert "LOCAL_GATHER" in names, names
        assert "CROSS_GATHER" in names, names
        os.unlink(tl_path)

    # -- adasum is NOT claimed: falls through to the flat backend ---------
    from horovod_tpu.ops.adasum import adasum_reference
    vecs = [np.linspace(0.1 * (r + 1), 1.0 * (r + 1), 8,
                        dtype=np.float64) for r in range(size)]
    before = dict(hier.leg_ops)
    out = hvd.allreduce(vecs[rank], op=hvd.Adasum, name="h_adasum")
    np.testing.assert_allclose(out, adasum_reference(vecs), rtol=1e-10)
    assert hier.leg_ops == before, "adasum must not ride hierarchical"

    # -- steady state (response cache) keeps the hierarchical path --------
    before_n = hier.leg_ops["local_rs"]
    for _ in range(5):
        out = hvd.allreduce(np.ones(4, dtype=np.float32), op=hvd.Sum,
                            name="h_steady")
        np.testing.assert_allclose(out, np.full(4, float(size)))
    assert hier.leg_ops["local_rs"] == before_n + 5, hier.leg_ops


def battery_peerdeath(hvd, rank, size):
    """Hard peer death mid-run (SURVEY §5.3 failure detection): the last
    rank os._exit()s between collectives; every survivor's next
    collective must raise HorovodInternalError within the transport
    timeout — a hang here is the failure mode this battery guards."""
    small = np.ones(4, np.float32)
    hvd.allreduce(small, op=hvd.Sum, name="warm")   # world fully formed
    if rank == size - 1:
        os._exit(37)
    try:
        for i in range(1000):
            hvd.allreduce(small, op=hvd.Sum, name=f"after{i}")
    except hvd.HorovodInternalError:
        print("peer death surfaced as HorovodInternalError")
        return
    raise AssertionError("collectives kept succeeding after peer death")



def battery_resilience_kill(hvd, rank, size):
    """ISSUE 5 acceptance: chaos SIGKILLs rank 2 mid-allreduce (global
    collective index 3); every survivor must raise RanksFailedError
    naming rank 2 within 2x HOROVOD_FAULT_TIMEOUT (wall-clock bound
    asserted) — the deadlock-to-error conversion, end to end."""
    import time as _time

    small = np.ones(8, np.float32)
    for i in range(3):   # collectives 0..2: world healthy
        out = hvd.allreduce(small, op=hvd.Sum, name=f"warm{i}")
        np.testing.assert_allclose(out, np.full(8, float(size)))
    fault_timeout = float(os.environ["HOROVOD_FAULT_TIMEOUT"])
    t0 = _time.monotonic()
    try:
        for i in range(50):   # collective 3 kills rank 2 pre-dispatch
            hvd.allreduce(small, op=hvd.Sum, name=f"after{i}")
    except hvd.RanksFailedError as e:
        elapsed = _time.monotonic() - t0
        assert 2 in e.failed_ranks, e
        assert elapsed < 2 * fault_timeout, (elapsed, fault_timeout)
        # ISSUE 7 acceptance: every survivor's conversion dumped the
        # flight recorder, and the dump's tail names the in-flight op
        # (the 'after*' allreduce this rank dispatched and never
        # completed).
        import json as _json
        from horovod_tpu.telemetry import flight as _flight
        rec = _flight.recorder()
        assert rec.enabled and rec.dumps >= 1, \
            (rec.enabled, getattr(rec, "dumps", None))
        # Another failure conversion (controller poison + data plane
        # both dump) may still be REWRITING the file when this thread
        # reads it — retry briefly instead of decoding a half-written
        # dump (a rare but real tier-1 flake).
        for _ in range(40):
            try:
                payload = _json.load(open(rec.last_dump_path))
                break
            except ValueError:
                _time.sleep(0.05)
        else:
            raise AssertionError(
                f"flight dump at {rec.last_dump_path} never became "
                f"valid JSON")
        assert payload["rank"] == rank
        events = payload["events"]
        kinds = [ev["kind"] for ev in events]
        assert "ranks-failed" in kinds, kinds
        dispatched = [ev for ev in events if ev["kind"] == "dispatch"
                      and ev["name"].startswith("after")]
        assert dispatched, kinds
        assert dispatched[-1]["trace"], dispatched[-1]
        # The tail IS the failure: nothing after the last in-flight
        # dispatch except failure records (no 'done' for it).
        last_dispatch = max(i for i, ev in enumerate(events)
                            if ev["kind"] == "dispatch"
                            and ev["name"].startswith("after"))
        assert not any(ev["kind"] == "done"
                       and ev["name"] == events[last_dispatch]["name"]
                       for ev in events[last_dispatch:]), events[-4:]
        print(f"survivor {rank}: RanksFailedError("
              f"{sorted(e.failed_ranks)}) in {elapsed:.2f}s "
              f"op={e.op!r} phase={e.phase!r} "
              f"flight={rec.last_dump_path}")
        return
    raise AssertionError("collectives kept succeeding after chaos kill")


def battery_resilience_retry(hvd, rank, size):
    """Delayed-send chaos (rank 1's first data-mesh send to rank 2 held
    for longer than the fault timeout) blows the op deadline on attempt
    0 on EVERY rank; HOROVOD_ON_FAILURE=retry rebuilds all channels
    under a bumped rendezvous epoch with exponential backoff and the
    re-run succeeds (the chaos action's count=1 is exhausted)."""
    from horovod_tpu import resilience
    from horovod_tpu.resilience import policy as _policy

    ones = np.ones(16, np.float32)
    out = resilience.run_with_recovery(
        lambda: hvd.allreduce(ones, op=hvd.Sum, name="retry0"),
        policy="retry", max_retries=3, base_backoff=0.2)
    np.testing.assert_allclose(out, np.full(16, float(size)))
    assert _policy.last_attempts >= 2, \
        f"chaos delay never triggered a retry (attempts=" \
        f"{_policy.last_attempts})"
    # The rebuilt world is fully healthy.
    out = hvd.allreduce(ones * (rank + 1), op=hvd.Sum, name="after_retry")
    np.testing.assert_allclose(out, np.full(16, float(sum(
        r + 1 for r in range(size)))))
    print(f"rank {rank}: retry converged after {_policy.last_attempts} "
          f"attempt(s)")


def battery_resilience_freeze(hvd, rank, size):
    """Wedged-rank detection: chaos freezes rank 1 for far longer than
    the fault timeout at collective 1.  Its PID lives and its heartbeat
    thread keeps beating — only the per-op DEADLINE can convert rank
    0's wait, which must raise RanksFailedError naming rank 1 within
    2x the timeout."""
    import time as _time

    small = np.ones(4, np.float32)
    hvd.allreduce(small, op=hvd.Sum, name="fwarm")   # collective 0
    fault_timeout = float(os.environ["HOROVOD_FAULT_TIMEOUT"])
    if rank == 1:
        # This rank freezes pre-dispatch of collective 1; whatever the
        # world looks like when it thaws (peer may have exited), any
        # structured error is acceptable — only a hang is a failure.
        try:
            hvd.allreduce(small, op=hvd.Sum, name="frozen")
            hvd.allreduce(small, op=hvd.Sum, name="thawed")
        except hvd.HorovodInternalError as e:
            print(f"thawed rank: structured error after freeze: {e}")
        return
    t0 = _time.monotonic()
    try:
        hvd.allreduce(small, op=hvd.Sum, name="frozen")
        hvd.allreduce(small, op=hvd.Sum, name="thawed")
    except hvd.RanksFailedError as e:
        elapsed = _time.monotonic() - t0
        assert 1 in e.failed_ranks, e
        assert elapsed < 2 * fault_timeout, (elapsed, fault_timeout)
        print(f"rank {rank}: wedged peer converted in {elapsed:.2f}s")
        return
    raise AssertionError("frozen peer never converted to an error")


def battery_resilience_off(hvd, rank, size):
    """Zero-overhead off mode: with HOROVOD_FAULT_TOLERANCE unset and
    HOROVOD_CHAOS unset there must be NO monitor thread, NO chaos
    engine, NO socket timeouts and NO resilience state captured by the
    meshes — byte-identical hot paths to the pre-resilience tree."""
    from census import assert_thread_absent

    from horovod_tpu import resilience
    from horovod_tpu.core import _global

    assert resilience.active_state() is None
    assert resilience.chaos.active() is None
    assert _global.chaos is None
    assert_thread_absent("heartbeat")
    for coll in _global.tcp_collectives:
        mesh = coll.mesh
        assert mesh._resilience is None and mesh._chaos is None
        for ch in mesh._channels.values():
            assert ch._res is None
            # Dialed sockets historically keep the formation connect
            # timeout (create_connection); off mode must only never
            # install the SHORT resilience poll timeout.
            t = ch.sock.gettimeout()
            assert t is None or t >= 10.0, \
                f"off mode must not install poll timeouts (got {t})"
    out = hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum, name="off0")
    np.testing.assert_allclose(out, np.full(8, float(size)))
    # Still none after traffic (lazy paths must not re-resolve).
    assert_thread_absent("heartbeat")


def battery_torch_grid(hvd, rank, size):
    """Torch-binding semantic grid (modeled on the dtype x op x variant
    sweep of /root/reference/test/parallel/test_torch.py): every wire
    dtype through the torch surface, in-place variants, async handles
    with poll/synchronize, scales, and splits-alltoall with received
    splits."""
    import torch
    import horovod_tpu.torch as hvt

    int_dtypes = [torch.uint8, torch.int8, torch.int32, torch.int64]
    float_dtypes = [torch.float16, torch.bfloat16, torch.float32,
                    torch.float64]

    # -- allreduce out-of-place + in-place, every dtype -------------------
    for dt in int_dtypes + float_dtypes:
        tag = str(dt).split(".")[-1]
        base = torch.arange(17) % 4 + rank + 1
        expected = sum((np.arange(17) % 4 + r + 1).astype(np.float64)
                       for r in range(size))
        rtol = 1e-2 if dt in (torch.float16, torch.bfloat16) else 1e-6
        out = hvt.allreduce(base.to(dt), op=hvt.Sum, name=f"tg_ar_{tag}")
        assert out.dtype == dt, (tag, out.dtype)
        np.testing.assert_allclose(out.to(torch.float64).numpy(),
                                   expected, rtol=rtol, err_msg=tag)
        t2 = base.to(dt).clone()
        ret = hvt.allreduce_(t2, op=hvt.Sum, name=f"tg_ari_{tag}")
        assert ret is t2   # in-place returns the same tensor
        np.testing.assert_allclose(t2.to(torch.float64).numpy(),
                                   expected, rtol=rtol,
                                   err_msg=f"inplace {tag}")

    # -- prescale/postscale through the torch surface ---------------------
    out = hvt.allreduce(torch.ones(9), op=hvt.Sum, name="tg_scale",
                        prescale_factor=2.0, postscale_factor=0.25)
    np.testing.assert_allclose(out.numpy(), np.full(9, size / 2.0),
                               rtol=1e-6)

    # -- async handles: enqueue several, poll, synchronize out of order --
    handles = [hvt.allreduce_async(torch.ones(4) * (rank + i),
                                   op=hvt.Sum, name=f"tg_async_{i}")
               for i in range(3)]
    for i in reversed(range(3)):
        out = hvt.synchronize(handles[i])
        assert hvt.poll(handles[i])
        np.testing.assert_allclose(
            out.numpy(), np.full(4, float(sum(r + i for r in range(size)))),
            rtol=1e-6, err_msg=f"async {i}")

    # -- grouped in-place per dtype ---------------------------------------
    for dt in (torch.int32, torch.float32, torch.float64):
        tag = str(dt).split(".")[-1]
        ts = [torch.full((5 + i,), float(rank + i)).to(dt)
              for i in range(3)]
        hvt.grouped_allreduce_(ts, op=hvt.Sum, name=f"tg_gar_{tag}")
        for i, t in enumerate(ts):
            np.testing.assert_allclose(
                t.to(torch.float64).numpy(),
                np.full(5 + i, float(sum(r + i for r in range(size)))),
                err_msg=f"grouped {tag}[{i}]")

    # -- broadcast_ in place ----------------------------------------------
    t = torch.full((3,), float(rank))
    hvt.broadcast_(t, root_rank=size - 1, name="tg_bc")
    np.testing.assert_allclose(t.numpy(), np.full(3, float(size - 1)))

    # -- alltoall with uneven splits + received splits ---------------------
    # Sender r sends (d+1) rows to destination d, all rows carrying r.
    rows = sum(d + 1 for d in range(size))
    t = torch.full((rows, 2), float(rank))
    splits = torch.tensor([d + 1 for d in range(size)], dtype=torch.int32)
    out, recv = hvt.alltoall(t, splits=splits, name="tg_a2a")
    np.testing.assert_array_equal(recv.numpy(),
                                  np.full(size, rank + 1, np.int32))
    expected_rows = np.concatenate(
        [np.full(((rank + 1), 2), float(r)) for r in range(size)])
    np.testing.assert_allclose(out.numpy(), expected_rows)



def battery_tf_grid(hvd, rank, size):
    """TF-surface dtype grid (reference: test/parallel/test_tensorflow.py
    dtype sweep): every wire dtype through the tf binding, scales, and
    uneven-splits alltoall."""
    import tensorflow as tf
    import horovod_tpu.tensorflow as htf

    dtypes = [tf.uint8, tf.int8, tf.int32, tf.int64, tf.float16,
              tf.bfloat16, tf.float32, tf.float64]
    for dt in dtypes:
        tag = dt.name
        base = tf.cast(tf.range(17) % 4 + rank + 1, dt)
        expected = sum((np.arange(17) % 4 + r + 1).astype(np.float64)
                      for r in range(size))
        rtol = 1e-2 if dt in (tf.float16, tf.bfloat16) else 1e-6
        out = htf.allreduce(base, average=False, name=f"tfg_ar_{tag}")
        assert out.dtype == dt, (tag, out.dtype)
        np.testing.assert_allclose(
            tf.cast(out, tf.float64).numpy(), expected, rtol=rtol,
            err_msg=tag)

    # prescale/postscale
    out = htf.allreduce(tf.ones(9), average=False, name="tfg_scale",
                        prescale_factor=2.0, postscale_factor=0.25)
    np.testing.assert_allclose(out.numpy(), np.full(9, size / 2.0),
                               rtol=1e-6)

    # allgather variable first dim per dtype
    for dt in (tf.int64, tf.float16, tf.float64):
        local = tf.cast(tf.fill((rank + 1, 2), rank + 1), dt)
        out = htf.allgather(local, name=f"tfg_ag_{dt.name}")
        assert out.shape == (sum(r + 1 for r in range(size)), 2)

    # broadcast from the last rank
    out = htf.broadcast(tf.fill((3,), float(rank)), root_rank=size - 1,
                        name="tfg_bc")
    np.testing.assert_allclose(out.numpy(), np.full(3, float(size - 1)))

    # alltoall with uneven splits: sender r sends (d+1) rows to dest d
    rows = sum(d + 1 for d in range(size))
    t = tf.fill((rows, 2), float(rank))
    out = htf.alltoall(t, splits=[d + 1 for d in range(size)],
                       name="tfg_a2a")
    got = out[0] if isinstance(out, (tuple, list)) else out
    expected_rows = np.concatenate(
        [np.full(((rank + 1), 2), float(r)) for r in range(size)])
    np.testing.assert_allclose(np.asarray(got), expected_rows)


def _compress_reference(size, n=4096, seed=123):
    """Deterministic per-rank payloads + their exact fp32 sum (identical
    on every rank: same seed)."""
    rng = np.random.default_rng(seed)
    data = rng.standard_normal((size, n)).astype(np.float32) * 2.0
    return data, data.sum(axis=0)


def _compress_error_bound(data, codec, block_size):
    """Documented bound for the eager quantized allreduce: every rank's
    input quantization error, plus one requantization of the reduced
    chunk (half a block step of the reduced values under the owner-chunk
    split, widened by the input error the accumulator already carries)."""
    from horovod_tpu.compress import chunk_bounds, roundtrip_error_bound
    size = data.shape[0]
    input_bound = sum(roundtrip_error_bound(data[r], codec, block_size)
                      for r in range(size))
    ref = data.sum(axis=0)
    b = chunk_bounds(ref.size, size)
    requant = np.concatenate(
        [roundtrip_error_bound(ref[b[r]:b[r + 1]], codec, block_size)
         for r in range(size)])
    return 2 * input_bound + requant + 1e-5


def battery_compress(hvd, rank, size):
    """Quantized-collective subsystem over the TCP plane: int8/uint4
    equivalence within the documented bound, measurably fewer wire
    bytes than fp32 for the same payload (the plane's byte counters),
    fp16 cast codec, and the codec-mismatch structured ERROR."""
    from horovod_tpu.backend.tcp import TcpBackend
    from horovod_tpu.compress import CompressionCodec
    from horovod_tpu.core import _global

    block_size = 256   # the HOROVOD_COMPRESSION_BLOCK_SIZE default
    data, ref = _compress_reference(size)
    x = data[rank]
    tcp = next(b for b in _global.op_manager.backends
               if isinstance(b, TcpBackend))
    mesh = tcp.coll.mesh

    base = mesh.bytes_sent
    out32 = hvd.allreduce(x.copy(), op=hvd.Sum, name="c_fp32")
    fp32_bytes = mesh.bytes_sent - base
    np.testing.assert_allclose(out32, ref, rtol=1e-5, atol=1e-5)
    assert fp32_bytes > 0, "fp32 allreduce moved no counted bytes"

    for codec_name, codec, min_ratio in (
            ("int8", CompressionCodec.INT8, 3.0),
            ("uint4", CompressionCodec.UINT4, 5.0)):
        base = mesh.bytes_sent
        out_q = hvd.allreduce(x.copy(), op=hvd.Sum,
                              name=f"c_{codec_name}",
                              compression=codec_name)
        q_bytes = mesh.bytes_sent - base
        bound = _compress_error_bound(data, codec, block_size)
        err = np.abs(np.asarray(out_q, np.float64) - ref)
        assert np.all(err <= bound), \
            (codec_name, float(err.max()), float(bound.max()))
        # The acceptance criterion: the tcp plane transmits measurably
        # fewer bytes for the same bucket.
        assert q_bytes * min_ratio < fp32_bytes, \
            (codec_name, q_bytes, fp32_bytes)

    # Cast codec: half the wire bytes, fp16-grade accuracy.
    base = mesh.bytes_sent
    out16 = hvd.allreduce(x.copy(), op=hvd.Sum, name="c_fp16",
                          compression="fp16")
    fp16_bytes = mesh.bytes_sent - base
    np.testing.assert_allclose(out16, ref, rtol=2e-2, atol=2e-2)
    assert fp16_bytes * 1.8 < fp32_bytes, (fp16_bytes, fp32_bytes)

    # Averaging composes through the postscale factor.
    out_avg = hvd.allreduce(x.copy(), op=hvd.Average, name="c_avg8",
                            compression="int8")
    bound = _compress_error_bound(data, CompressionCodec.INT8,
                                  block_size) / size
    assert np.all(np.abs(np.asarray(out_avg, np.float64) - ref / size)
                  <= bound)

    # Codec mismatch across ranks -> structured ERROR, never a hang or
    # a corrupted reduce; the world stays usable afterwards.
    try:
        hvd.allreduce(x.copy(), op=hvd.Sum, name="c_mismatch",
                      compression="int8" if rank == 0 else None)
    except hvd.HorovodInternalError as e:
        assert "codec" in str(e).lower(), str(e)
    else:
        raise AssertionError("expected HorovodInternalError")
    out_after = hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum,
                              name="c_after")
    np.testing.assert_allclose(out_after, np.full(8, float(size)))

    # Adasum + quantized codec is rejected with a structured error too.
    try:
        hvd.allreduce(x.copy(), op=hvd.Adasum, name="c_adasum8",
                      compression="int8")
    except hvd.HorovodInternalError as e:
        assert "adasum" in str(e).lower(), str(e)
    else:
        raise AssertionError("expected HorovodInternalError")


def battery_compress_shm(hvd, rank, size):
    """Quantized allreduce over the same-host shm plane: the shm backend
    must claim it (quantized staging fits the region), reconstruct
    within the shared bound, and fall through to TCP when the region is
    too small for the staged quantized chunks."""
    from horovod_tpu.compress import CompressionCodec
    from horovod_tpu.core import _global

    names = [b.name for b in _global.op_manager.backends]
    assert "shm" in names, names
    shm = _global.op_manager.backends[names.index("shm")]
    assert shm.world.formed

    block_size = 256
    data, ref = _compress_reference(size)
    executed = shm.ops_executed
    out_q = hvd.allreduce(data[rank].copy(), op=hvd.Sum, name="s_int8",
                          compression="int8")
    assert shm.ops_executed == executed + 1, "shm plane did not claim it"
    bound = _compress_error_bound(data, CompressionCodec.INT8, block_size)
    assert np.all(np.abs(np.asarray(out_q, np.float64) - ref) <= bound)

    # Oversized quantized payload falls through to the TCP ring with the
    # same numerics (capacity is 1 MB in this battery; 2M floats stage
    # ~2 MB even quantized).
    big, big_ref = _compress_reference(size, n=2_000_000, seed=7)
    executed = shm.ops_executed
    out_big = hvd.allreduce(big[rank].copy(), op=hvd.Sum, name="s_big8",
                            compression="int8")
    assert shm.ops_executed == executed, "oversized op must not ride shm"
    bound = _compress_error_bound(big, CompressionCodec.INT8, block_size)
    assert np.all(np.abs(np.asarray(out_big, np.float64) - big_ref)
                  <= bound)


def battery_compress_xla(hvd, rank, size):
    """Quantized allreduce over the XLA device plane: the xla backend
    claims the response, the device program dequantizes+sums the int8
    payload, and the reconstruction stays within the shared bound."""
    from horovod_tpu.backend.xla import XlaBackend
    from horovod_tpu.compress import CompressionCodec
    from horovod_tpu.core import _global

    xla = next(b for b in _global.op_manager.backends
               if isinstance(b, XlaBackend))
    claimed = []
    orig = xla.allreduce

    def counting_allreduce(resp, entries):
        claimed.append(resp.tensor_names[0])
        return orig(resp, entries)

    xla.allreduce = counting_allreduce
    block_size = 256
    data, ref = _compress_reference(size)
    out_q = hvd.allreduce(data[rank].copy(), op=hvd.Sum, name="x_int8",
                          compression="int8")
    assert any("x_int8" in nm for nm in claimed), claimed
    bound = _compress_error_bound(data, CompressionCodec.INT8, block_size)
    assert np.all(np.abs(np.asarray(out_q, np.float64) - ref) <= bound)

    out4 = hvd.allreduce(data[rank].copy(), op=hvd.Average, name="x_u4",
                         compression="uint4")
    bound = _compress_error_bound(data, CompressionCodec.UINT4,
                                  block_size) / size
    assert np.all(np.abs(np.asarray(out4, np.float64) - ref / size)
                  <= bound)


def battery_streams(hvd, rank, size):
    """Multi-stream response dispatch (HOROVOD_NUM_STREAMS=2, fusion off
    so a burst of async allreduces becomes several responses round-robined
    across streams): exact results, per-stream channel traffic, mixed
    codecs, and a steady-state thread census."""
    import threading

    from horovod_tpu import core
    from horovod_tpu.compress import CompressionCodec
    st = core.global_state()
    assert st.stream_dispatcher is not None, "dispatcher not formed"
    assert st.stream_dispatcher.num_streams == 2
    assert len(st.op_managers) == 2 and len(st.tcp_collectives) == 2

    def burst(tag):
        handles = [hvd.allreduce_async(
            np.arange(4096, dtype=np.float32) * (i + 1) + rank,
            op=hvd.Sum, name=f"{tag}{i}") for i in range(6)]
        for i, h in enumerate(handles):
            out = hvd.synchronize(h)
            expected = np.arange(4096, dtype=np.float32) * (i + 1) * size \
                + sum(range(size))
            np.testing.assert_array_equal(out, expected)

    burst("first")           # negotiated path
    for cycle in range(3):   # response-cache steady state
        burst(f"c{cycle}")

    # Stream isolation: BOTH per-stream channel sets carried payload.
    for s, coll in enumerate(st.tcp_collectives):
        assert coll.mesh.bytes_received > 0, f"stream {s} never used"

    # Mixed ops across streams in one cycle (broadcast is stream-safe on
    # the TCP plane; values exact).
    handles = [hvd.allreduce_async(np.full(1024, float(rank + i),
                                           np.float32),
                                   op=hvd.Sum, name=f"mix_ar{i}")
               for i in range(2)]
    bh = hvd.broadcast_async(np.arange(64, dtype=np.float64) * (rank + 1),
                             root_rank=0, name="mix_bc")
    for i, h in enumerate(handles):
        np.testing.assert_array_equal(
            hvd.synchronize(h),
            np.full(1024, float(sum(range(size)) + size * i), np.float32))
    np.testing.assert_array_equal(hvd.synchronize(bh),
                                  np.arange(64, dtype=np.float64))

    # Cast + quantized codecs ride the per-stream channels too; small
    # integer values are exact through the bf16 wire, int8 within the
    # block-quantization bound.
    v = np.arange(2048, dtype=np.float32) % 97
    out = hvd.allreduce(v, op=hvd.Sum, name="s_bf16", compression="bf16")
    np.testing.assert_array_equal(out, v * size)
    data = np.stack([(np.arange(2048, dtype=np.float32) % 53) + r
                     for r in range(size)])
    out_q = hvd.allreduce(data[rank].copy(), op=hvd.Sum, name="s_int8",
                          compression="int8")
    bound = _compress_error_bound(data, CompressionCodec.INT8, 256)
    assert np.all(np.abs(np.asarray(out_q, np.float64) - data.sum(0))
                  <= bound)

    # Steady-state census: cached multi-stream cycles spawn no threads.
    before = threading.active_count()
    burst("census")
    assert threading.active_count() <= before, \
        (before, threading.active_count())


def battery_telemetry(hvd, rank, size):
    """Observability layer end-to-end (ISSUE 4 acceptance): a 4-rank
    HOROVOD_METRICS=on world serves a real Prometheus scrape with
    per-plane latency histograms and per-peer byte counters, and with
    rank size-1 delayed 50 ms per step the coordinator names that rank
    as the straggler within two aggregation windows (window=8 via env)."""
    import time as _time
    import urllib.request

    from horovod_tpu.core import _global
    from horovod_tpu.telemetry import MetricsExporter

    assert _global.telemetry.enabled
    delayed = size - 1

    # Unique names force one negotiation per step — the wire the arrival
    # times and per-rank snapshots ride.  The delayed rank submits 50 ms
    # behind its peers every step.
    for step in range(20):
        if rank == delayed:
            _time.sleep(0.05)
        out = hvd.allreduce(np.ones(64, np.float32), op=hvd.Sum,
                            name=f"tm_{step}")
        np.testing.assert_allclose(out, np.full(64, float(size)))

    if rank == 0:
        agg = _global.controller.straggler
        assert agg is not None
        assert agg.windows_completed >= 2, agg.windows_completed
        assert agg.last_straggler == delayed, \
            (agg.last_straggler, agg.last_skew_ms)
        assert agg.last_skew_ms > 20.0, agg.last_skew_ms
        g = _global.telemetry.gauge("horovod_controller_straggler_rank")
        assert g.value == float(delayed), g.value

    # Cached steady state exercises the hit counter + per-plane latency.
    for _ in range(5):
        hvd.allreduce(np.ones(32, np.float32), op=hvd.Sum,
                      name="tm_steady")
    assert _global.controller._m_cache_hit.value >= 3

    # Real HTTP scrape of this rank's exporter.
    exporter = next(r for r in _global.resources
                    if isinstance(r, MetricsExporter))
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{exporter.port}/metrics",
        timeout=10).read().decode()
    assert "horovod_collective_latency_ms_bucket" in body
    assert 'plane="tcp"' in body, body[:2000]
    assert "horovod_tcp_bytes_sent_total" in body
    assert "horovod_tcp_bytes_received_total" in body
    if rank == 0:
        # Coordinator re-exports every rank's snapshot + the straggler.
        assert "horovod_controller_straggler_rank" in body
        assert "horovod_rank_cycle_ms" in body
    hvd.barrier()
    # The JSON dump itself is written at shutdown; the parent test
    # (test_multiprocess.test_telemetry_observability_4rank) asserts its
    # contents after the world exits.


def battery_perfscope(hvd, rank, size):
    """perfscope smoke (ISSUE 19): a 2-rank HOROVOD_METRICS=on world
    runs allreduces spanning three size buckets; every rank's registry
    must carry busbw cells whose roofline-relative efficiency lands in
    (0, 1.05] with a known algorithm label (at 2 ranks every schedule
    degenerates to the ring).  The parent test merges the shutdown
    dumps through the perf CLI and gates them with perfcheck."""
    from horovod_tpu.core import _global
    from horovod_tpu.telemetry import perfmodel

    assert _global.telemetry.enabled
    # 2 KiB / 32 KiB / 512 KiB payloads → 4KiB / 64KiB / 1MiB buckets.
    for step in range(4):
        for tag, n in (("s", 512), ("m", 8192), ("l", 131072)):
            out = hvd.allreduce(np.ones(n, np.float32), op=hvd.Sum,
                                name=f"pf_{tag}_{step}")
            np.testing.assert_allclose(out, np.full(n, float(size)))
    hvd.barrier()

    ledger = perfmodel.build_ledger([_global.telemetry.snapshot()])
    rows = ledger.get("busbw", [])
    assert rows, "no busbw cells in the local registry"
    buckets = {r["size_bucket"] for r in rows}
    assert {"4KiB", "64KiB", "1MiB"} <= buckets, buckets
    for r in rows:
        assert 0.0 < r["efficiency"] <= 1.05, r
        assert r["algo"] in ("ring", "tree", "rhd", "torus",
                             "hierarchical"), r
    # The degenerate 2-rank world keeps the ring fast path everywhere.
    assert {r["algo"] for r in rows} == {"ring"}, rows
    # The shutdown JSON dump (asserted by the parent) rides hvd.shutdown.


def battery_trace(hvd, rank, size):
    """ISSUE 7 acceptance (4-rank, in-battery half): uniquely-named
    allreduces under per-rank HOROVOD_TIMELINE files while chaos
    freezes rank size-1 for 120 ms before dispatching every tr_*
    collective (the PR 5 deterministic delay injection).  The parent
    test (test_multiprocess.test_trace_merge_and_critical_path_4rank)
    merges the four files and asserts flow-linked spans + critical-path
    attribution naming the delayed rank."""
    from horovod_tpu.core import _global

    assert _global.timeline is not None and _global.timeline.enabled
    assert _global.flight.enabled   # default-on flight recorder
    delayed = size - 1
    if rank != 0:
        # Worker ranks probed a real clock offset against rank 0.
        assert _global.timeline._clock_offset_us is not None
        assert _global.timeline._clock_rtt_us > 0.0
    for step in range(12):
        out = hvd.allreduce(np.ones(32, np.float32), op=hvd.Sum,
                            name=f"tr_{step}")
        np.testing.assert_allclose(out, np.full(32, float(size)))
    # Trace ids advanced monotonically with the lockstep cycles.
    assert _global.controller._trace_cycle > 0
    if rank == delayed:
        assert _global.chaos is not None
        assert any(a.fired for a in _global.chaos.actions)
    hvd.barrier()


def battery_san(hvd, rank, size):
    """ISSUE 8 acceptance (in-battery half): the HOROVOD_SAN runtime
    witness is live, collectives stay exact under the lock wrappers,
    per-thread acquisition-order edges were recorded — including the
    init-time controller<->transport edge (core._init_lock held while
    the clock-offset probes touch the ctrl mesh's counter lock) — and
    first observations rode the flight-recorder ring.  The parent test
    (test_multiprocess.test_lock_witness_matches_static_graph) diffs
    the shutdown dumps against the static lock graph."""
    from horovod_tpu.analysis.hvdsan import san
    from horovod_tpu.core import _global

    assert san.enabled(), "HOROVOD_SAN=1 did not enable the witness"
    w = san.witness()
    assert w is not None
    for step in range(6):
        out = hvd.allreduce(np.ones(16, np.float32), op=hvd.Sum,
                            name=f"san_{step}")
        np.testing.assert_allclose(out, np.full(16, float(size)))
    hvd.barrier()
    snap = w.snapshot()
    edges = {(e["src"], e["dst"]) for e in snap["edges"]}
    assert edges, "witness recorded no acquisition-order edges"
    assert any(s.startswith("horovod_tpu/core.py:")
               and d.startswith("horovod_tpu/runner/network.py:")
               for s, d in edges), sorted(edges)
    # First edge observations land in the flight ring (ISSUE 8).
    kinds = {e["kind"] for e in _global.flight.snapshot()}
    assert "lock-order" in kinds, kinds


def battery_serving(hvd, rank, size):
    """ISSUE 9 acceptance (4-rank): continuous-batching serving with a
    chaos SIGKILL of rank 2 mid-serve.  The world shrinks 4->3; every
    survivor finishes every request it had admitted (zero failed
    in-flight on survivors), the front end's accounting balances
    (served + lost == offered, bounded shed), and a post-shrink burst
    of hopeless-SLO requests is shed at admission — never prefilled."""
    import random as _random
    import time as _time

    from horovod_tpu.serving import ReplicaExecutor, ServeConfig

    ex = ReplicaExecutor(ServeConfig.from_env(
        max_batch=4, token_budget=64, max_seq=64, slo_ms=120000.0))
    assert ex.num_groups == size
    n_requests = 24
    if rank == 0:
        rng = _random.Random(7)
        for _ in range(n_requests):
            toks = [rng.randrange(2, ex.model.cfg.vocab_size)
                    for _ in range(rng.randint(2, 10))]
            ex.stats["offered"] += 1
            assert ex.queue.submit(toks, 12) is not None

    t0 = _time.monotonic()
    ex.serve_loop(stop_when=lambda: True)   # drain then stop
    phase1_wall = _time.monotonic() - t0

    # --- phase-1 assertions: the kill happened and survivors absorbed it
    assert ex.size == size - 1, (ex.size, size)
    assert ex.stats["shrinks"] and \
        ex.stats["shrinks"][0]["dead"] == [2], ex.stats["shrinks"]
    missing = ex.prefilled - set(ex.completed)
    assert not missing, \
        f"survivor {rank} failed admitted in-flight requests: {missing}"
    phase1_prefilled = len(ex.prefilled)
    if rank == 0:
        st = ex.stats
        assert st["served"] + st["lost"] == n_requests, st
        assert st["lost"] <= 4, st          # at most rank 2's slots
        assert st["expired"] == 0, st       # generous SLOs: bounded shed
        assert ex.admission._m_outcome["shed"].value == 0
        lat = st["latencies_ms"]
        assert len(lat) == st["served"] and min(lat) > 0.0
        fault_timeout = float(os.environ["HOROVOD_FAULT_TIMEOUT"])
        # The shrink detour is bounded: detection (<= 2x fault timeout)
        # + confirmation polling (<= 2x) + rebuild, with wide margin.
        assert phase1_wall < 10 * fault_timeout, phase1_wall
        print(f"serving: {st['served']}/{n_requests} served, "
              f"{st['lost']} lost with rank 2, shrink at step "
              f"{st['shrinks'][0]['step']} in {phase1_wall:.1f}s")

    # --- phase 2: overload with hopeless SLOs -> shed at admission,
    # never executed (no new prefill on ANY survivor).
    served_before = ex.stats["served"]
    if ex.rank == ex.front:
        for _ in range(8):
            # Deadline passes while queued -> 'expired' at pop.
            assert ex.queue.submit([3, 4, 5], 4, slo_ms=0.5) is not None
        for _ in range(4):
            # Feasibility shed: 200 decode steps can never fit 3 ms.
            assert ex.queue.submit([3] * 8, 200, slo_ms=3.0) is not None
    ex._stop_requested = False
    ex.serve_loop(stop_when=lambda: True)
    assert len(ex.prefilled) == phase1_prefilled, \
        "hopeless-SLO requests must never be executed"
    assert ex.stats["served"] == served_before
    if ex.rank == ex.front:
        shed_total = (ex.stats["expired"]
                      + ex.admission._m_outcome["shed"].value)
        assert shed_total == 12, \
            (ex.stats["expired"], ex.admission._m_outcome["shed"].value)
        print(f"serving: post-shrink hopeless burst shed at admission "
              f"(expired={ex.stats['expired']}, "
              f"shed={ex.admission._m_outcome['shed'].value:g})")
    hvd.barrier()


def battery_serving_paged(hvd, rank, size):
    """ISSUE 14 acceptance (4-rank): paged-KV continuous serving rides
    the same chaos SIGKILL of rank 2 mid-serve as the dense battery.
    The world shrinks 4->3 with block tables resynced from ground
    truth, every survivor finishes every admitted request (zero failed
    in-flight), repeated prompts hit the prefix cache, and after the
    drain every survivor's pool passes the refcount-leak census
    (active blocks == 0)."""
    import random as _random
    import time as _time

    from horovod_tpu.serving import ReplicaExecutor, ServeConfig

    ex = ReplicaExecutor(ServeConfig.from_env(
        max_batch=4, token_budget=64, max_seq=64, slo_ms=120000.0,
        paged=True, block_tokens=8))
    assert ex.num_groups == size
    assert ex.cfg.slots == 8 and ex.pool is not None
    n_requests = 24
    if rank == 0:
        rng = _random.Random(7)
        # A pool of 6 prompts offered 4x each: the repeated-prompt
        # profile the prefix cache exists for.
        prompts = [[rng.randrange(2, ex.model.cfg.vocab_size)
                    for _ in range(rng.randint(2, 10))]
                   for _ in range(6)]
        for i in range(n_requests):
            ex.stats["offered"] += 1
            assert ex.queue.submit(prompts[i % 6], 12) is not None

    t0 = _time.monotonic()
    ex.serve_loop(stop_when=lambda: True)   # drain then stop
    phase1_wall = _time.monotonic() - t0

    # --- the kill happened, survivors absorbed it with paged KV intact
    assert ex.size == size - 1, (ex.size, size)
    assert ex.stats["shrinks"] and \
        ex.stats["shrinks"][0]["dead"] == [2], ex.stats["shrinks"]
    missing = ex.prefilled - set(ex.completed)
    assert not missing, \
        f"survivor {rank} failed admitted in-flight requests: {missing}"
    kv = ex.kv_stats()
    assert kv["active"] == 0, f"rank {rank} leaked KV blocks: {kv}"
    print(f"serving_paged: rank {rank} kv census clean "
          f"(hits={kv['prefix_hits']:g} cow={kv['cow_copies']:g})")
    if rank == 0:
        st = ex.stats
        assert st["served"] + st["lost"] == n_requests, st
        assert st["lost"] <= 8, st          # at most rank 2's slots
        assert st["expired"] == 0, st
        assert kv["prefix_hits"] > 0, kv    # repeated prompts hit
        # Block-table resync: after the drain the front end's block
        # mirror is empty again — reservations freed exactly once.
        assert ex.batcher.inflight == {} and \
            all(b == 0 for b in ex.batcher._blocks), \
            (ex.batcher.inflight, ex.batcher._blocks)
        fault_timeout = float(os.environ["HOROVOD_FAULT_TIMEOUT"])
        assert phase1_wall < 10 * fault_timeout, phase1_wall
        print(f"serving_paged: {st['served']}/{n_requests} served, "
              f"{st['lost']} lost with rank 2, shrink at step "
              f"{st['shrinks'][0]['step']} in {phase1_wall:.1f}s, "
              f"max_concurrent={ex.batcher.max_concurrent}")
    ex.close()
    hvd.barrier()


def battery_serving_disagg(hvd, rank, size):
    """ISSUE 14 acceptance (2-rank, strict fingerprint): disaggregated
    prefill/decode — rank 1 is a prefill-only rank streaming finished
    KV blocks to the rank-0 decode replica over the kvstream mesh.
    Every long prompt is prefilled OFF the decode rank (zero local
    fallbacks), everything offered is served, and the strict-mode
    collective fingerprint stays clean over the split-role step loop
    (any divergence would abort the battery with a structured ERROR)."""
    import random as _random

    from horovod_tpu.serving import ReplicaExecutor, ServeConfig

    ex = ReplicaExecutor(ServeConfig.from_env(
        max_batch=4, token_budget=256, max_seq=64, slo_ms=120000.0,
        paged=True, block_tokens=8, prefill_ranks=1))
    assert ex.decode_size == 1 and ex.prefill_rank_list == [1]
    assert ex.is_prefill == (rank == 1)
    n_requests = 12
    if rank == 0:
        rng = _random.Random(5)
        for _ in range(n_requests):
            # Long prompts (3-5 blocks): the traffic whose prefill
            # used to stall co-scheduled decode steps.
            toks = [rng.randrange(2, ex.model.cfg.vocab_size)
                    for _ in range(rng.randint(24, 40))]
            ex.stats["offered"] += 1
            assert ex.queue.submit(toks, 8) is not None

    ex.serve_loop(stop_when=lambda: True)

    if rank == 0:
        st = ex.stats
        kv = ex.kv_stats()
        assert st["served"] == n_requests, st
        assert kv["prefill_fallbacks"] == 0, kv
        assert kv["active"] == 0, kv
        assert ex.batcher.inflight == {}, ex.batcher.inflight
        print(f"serving_disagg: {st['served']}/{n_requests} served via "
              f"streamed prefill, zero local fallbacks")
    else:
        assert ex.stats["prefill_streams"] == n_requests, ex.stats
        from horovod_tpu import telemetry
        sent = telemetry.metrics().counter(
            "horovod_serve_prefill_stream_bytes_total",
            labels={"role": "sent"}).value
        assert sent > 0, "prefill rank streamed no bytes"
        print(f"serving_disagg: rank 1 streamed "
              f"{ex.stats['prefill_streams']} prefills "
              f"({sent:g} payload bytes)")
    ex.close()
    hvd.barrier()


def _statesync_state(n=1 << 18):
    """Deterministic replicated training state: params/opt evolve by the
    (identical-on-every-rank) allreduce output, so donors' snapshots are
    coherent and digests comparable."""
    return {"params": np.zeros(n, np.float32),
            "opt": np.zeros(n, np.float32),
            "step": np.zeros((), np.int64)}


def _statesync_train_step(hvd, state):
    """One lockstep training step; returns the reduced output after
    applying the deterministic symmetric update."""
    n = state["params"].size
    my = np.full(n, float(hvd.rank() + 1), np.float32)
    out = hvd.allreduce(my, op=hvd.Sum,
                        name=f"sst.train.{int(state['step'])}")
    expected = hvd.size() * (hvd.size() + 1) / 2.0
    np.testing.assert_allclose(out[:8], np.full(8, expected))
    state["params"] += 0.01 * out
    state["opt"] += out * out
    state["step"] += 1
    return out


def _statesync_witness_dump(tag, launch_rank):
    """End-of-battery flight dump for the hvdmc trace witness: the
    driver test replays every WITNESS_DUMP file through
    horovod_tpu.analysis.hvdmc.witness and fails on any observed
    membership transition the model does not know.  Keyed by LAUNCH
    rank, not world rank — elastic renumbering would otherwise collide
    a departed rank's dump with a renumbered survivor's."""
    from horovod_tpu.telemetry import flight

    rec = flight.recorder()
    if not rec.enabled:
        return
    epoch0 = os.environ["HOROVOD_RENDEZVOUS_EPOCH"].split("~", 1)[0]
    rec.path = f"/tmp/hvd_witness_{epoch0}.launch{launch_rank}.json"
    path = rec.dump(reason=f"hvdmc witness ({tag})")
    if path:
        print(f"WITNESS_DUMP {path}")


def _statesync_digest_check(hvd, state):
    """Every rank's state must be bit-identical after a grow."""
    from horovod_tpu import statesync

    digest = statesync.state_digest(statesync.flatten_state(state))
    views = hvd.allgather_object(digest,
                                 name=f"sst.digest.{int(state['step'])}")
    assert len(set(views)) == 1, f"post-grow state divergence: {views}"
    return digest


def battery_rolling(hvd, rank, size):
    """ISSUE 15 rolling-upgrade battery: rank 1 advertises wire proto 1
    (the still-old framework version; HOROVOD_PROTO_COMPAT set in main
    before init) — the world negotiates the min common schema at every
    mesh HELLO and completes training steps with zero failed steps and
    zero fingerprint divergence under strict mode; then the lagging
    rank "upgrades" (compat lifted) and the whole world rejoins under a
    fresh epoch, negotiating the native schema again."""
    from horovod_tpu import core as _core
    from horovod_tpu.common import wire as _wire
    from horovod_tpu.runner.network import PeerMesh as _PeerMesh

    def _meshes():
        return [r for r in _core.global_state().resources
                if isinstance(r, _PeerMesh)]

    def _steps(tag):
        t = np.ones(256, np.float32) * (rank + 1)
        want = np.ones(256, np.float32) * (size * (size + 1) / 2)
        for i in range(4):
            out = hvd.allreduce(t, op=hvd.Sum, name=f"{tag}{i}")
            np.testing.assert_allclose(np.asarray(out), want)

    meshes = _meshes()
    assert meshes, "no TCP meshes formed"
    for m in meshes:
        assert m.negotiated_proto == 1, m.negotiated_proto
        assert m.negotiated_features == 0, m.negotiated_features
        assert m.peer_protos, m.peer_protos
    _steps("rollold")

    # The old rank upgrades: drain, lift the compat pin, rejoin at N+1.
    hvd.shutdown()
    os.environ.pop("HOROVOD_PROTO_COMPAT", None)
    os.environ["HOROVOD_RENDEZVOUS_EPOCH"] = \
        os.environ.get("HOROVOD_RENDEZVOUS_EPOCH", "0") + "~u1"
    hvd.init()
    meshes = _meshes()
    assert meshes
    for m in meshes:
        assert m.negotiated_proto == _wire.PROTO_VERSION
        assert m.negotiated_features == _wire.FEATURES_ALL
    _steps("rollnew")
    print(f"ROLLING_OK rank={rank} proto "
          f"1->{_wire.PROTO_VERSION}", flush=True)


def battery_statesync_grow(hvd, rank, size):
    """ISSUE 10 acceptance (4-rank, rides 4->3->4): chaos SIGKILLs rank
    2 mid-training; survivors shrink with zero failed steps after the
    conversion, then launch-rank 0 spawns a replacement process that
    joins via peer state streaming — incumbents never fail a step while
    it catches up, and after the grow every rank's state is
    bit-identical (digest-exchanged in-battery)."""
    import subprocess as _subprocess
    import sys as _sys
    import time as _time

    from horovod_tpu import statesync

    state = _statesync_state()
    svc = statesync.StateSyncService(lambda: state)
    shrunk = grown = False
    stop_at = None
    joiner_proc = None
    launch_rank = rank
    deadline = _time.monotonic() + 150.0
    while _time.monotonic() < deadline:
        try:
            _statesync_train_step(hvd, state)
            change = svc.step_boundary()
        except hvd.RanksFailedError as exc:
            assert not shrunk, f"step failed AFTER the shrink: {exc}"
            change = svc.shrink_on_failure(exc)
        if change is not None and change.kind == "shrink":
            shrunk = True
            assert hvd.size() == size - 1, hvd.size()
            assert 2 in change.dead, change
            # Realign replicated state: survivors may have caught the
            # kill on different steps (one applied the last update, one
            # did not) — the most-advanced rank is the authority.
            state = statesync.resync_replicated(state,
                                                int(state["step"]))
            if hvd.rank() == 0:
                env = dict(os.environ)
                for k in ("HOROVOD_CHAOS", "HOROVOD_RANK",
                          "HOROVOD_SIZE"):
                    env.pop(k, None)
                joiner_proc = _subprocess.Popen(
                    [_sys.executable, os.path.abspath(__file__),
                     "0", "0",
                     os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"],
                     "statesync_joiner"],
                    env=env, stdout=_subprocess.PIPE,
                    stderr=_subprocess.STDOUT)
        elif change is not None and change.kind == "grow":
            grown = True
            assert shrunk, "grew before the shrink?"
            assert hvd.size() == size, hvd.size()
            stop_at = int(state["step"]) + 3
        if stop_at is not None and int(state["step"]) >= stop_at:
            break
    assert shrunk and grown, (shrunk, grown)
    _statesync_digest_check(hvd, state)
    _statesync_witness_dump("grow battery", launch_rank)
    svc.close()
    if joiner_proc is not None:
        out, _ = joiner_proc.communicate(timeout=60.0)
        text = out.decode(errors="replace")
        print("--- joiner output ---\n" + text)
        assert joiner_proc.returncode == 0, \
            f"joiner failed rc={joiner_proc.returncode}:\n{text}"
        assert "joiner: catch-up" in text
    print(f"launch rank {launch_rank}: rode {size}->{size - 1}->{size} "
          f"to step {int(state['step'])} with zero failed "
          f"post-shrink steps")


def battery_statesync_joiner(port):
    """The replacement rank of the grow battery: runs BEFORE hvd.init —
    join_world streams state from the live donors, verifies it, and
    enters the world; then it trains in lockstep with the incumbents."""
    import time as _time

    from horovod_tpu import statesync

    t0 = _time.monotonic()
    template = _statesync_state()
    tree, info = statesync.join_world(template)
    import horovod_tpu as hvd

    assert hvd.is_initialized() and hvd.rank() == info.rank
    # Bit-identical to the donors' snapshot: recompute the digest of
    # the assembled state against the unanimous stamp (the acceptance
    # criterion's independent check; pull_round verified it once).
    image = statesync.flatten_state(tree)
    assert statesync.state_digest(image) == info.stamp.digest
    # Bounded catch-up: the bulk transfer from N donors in parallel
    # must cost no more than ~one donor's own streaming time (x2 +
    # formation slack) — the sharded-stream win over a single source.
    max_donor_s = max((w for _, w in info.donor_stats.values()),
                      default=0.0)
    bulk_s = info.catch_up_ms / 1e3
    assert bulk_s < 2.0 * max_donor_s + 10.0, \
        (bulk_s, max_donor_s, info.donor_stats)
    state = tree
    svc = statesync.StateSyncService(lambda: state)
    stop_at = int(state["step"]) + 3
    while int(state["step"]) < stop_at:
        _statesync_train_step(hvd, state)
        svc.step_boundary()
    _statesync_digest_check(hvd, state)
    _statesync_witness_dump("grow battery joiner", "J")
    if os.environ.get("HOROVOD_LIFE_CENSUS") == "1":
        # The life battery's census-done sync: incumbents census their
        # fabric after the last training step; this rank must not tear
        # the shared world down under them (see battery_statesync_life).
        hvd.allgather_object("J", name="life.census.done")
    svc.close()
    print(f"joiner: catch-up {info.catch_up_ms:.0f} ms for "
          f"{info.bulk_bytes} bytes from {len(info.donor_stats)} "
          f"donors; entered as rank {info.rank}/{info.size} at step "
          f"{stop_at - 3}; total wall "
          f"{_time.monotonic() - t0:.1f}s")
    hvd.shutdown()
    return 0


def battery_statesync_preempt(hvd, rank, size):
    """ISSUE 10 SIGTERM-grace acceptance (3-rank): chaos delivers
    SIGTERM to rank 1 mid-training.  The preempted rank finishes its
    in-flight step, announces departure through the boundary check,
    fast-donates its opt state, writes bye| and exits 0; survivors
    shrink PROACTIVELY at the same boundary — no RanksFailedError is
    ever raised, and the heartbeat monitor never declares rank 1
    failed."""
    import time as _time

    from horovod_tpu import resilience, statesync
    from horovod_tpu.runner.network import RendezvousClient

    state = _statesync_state(n=1 << 12)
    svc = statesync.StateSyncService(
        lambda: state,
        donate_provider=lambda: {"shard": state["opt"]})
    kv = RendezvousClient("127.0.0.1",
                          int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]),
                          20.0)
    launch_rank = rank
    shrunk_at = None
    pre_epoch = os.environ["HOROVOD_RENDEZVOUS_EPOCH"]
    deadline = _time.monotonic() + 60.0
    while _time.monotonic() < deadline:
        prev_epoch = os.environ["HOROVOD_RENDEZVOUS_EPOCH"]
        # No try/except: ANY RanksFailedError here fails the battery —
        # the whole point of grace is that survivors never see one.
        _statesync_train_step(hvd, state)
        change = svc.step_boundary()
        if change is not None and change.kind == "departed":
            assert launch_rank == 1, launch_rank
            raw = kv.get("hb", f"{prev_epoch}:1")
            assert raw is not None and raw.startswith(b"bye|"), raw
            _statesync_witness_dump("preempt battery departed",
                                    launch_rank)
            print("preempted rank: departed with bye| stamp inside "
                  "the grace window")
            return
        if change is not None and change.kind == "shrink":
            assert change.dead == (1,), change
            assert hvd.size() == size - 1
            shrunk_at = int(state["step"])
            # The departed rank's fast-donated opt shard is fetchable
            # and digest-verified.
            donated = statesync.fetch_donation(
                prev_epoch, 1, {"shard": np.zeros_like(state["opt"])},
                kv=kv)
            assert donated is not None
            state = statesync.resync_replicated(state,
                                                int(state["step"]))
        if shrunk_at is not None and int(state["step"]) >= shrunk_at + 3:
            break
    assert shrunk_at is not None, "the preemption never happened"
    st = resilience.active_state()
    assert st is None or not st.failed_ranks(), \
        f"proactive shrink must beat the heartbeat: {st.failed_ranks()}"
    assert os.environ["HOROVOD_RENDEZVOUS_EPOCH"] != pre_epoch
    _statesync_witness_dump("preempt battery survivor", launch_rank)
    svc.close()
    print(f"survivor {launch_rank}: proactive shrink at step "
          f"{shrunk_at}, no RanksFailedError anywhere")


def battery_statesync_life(hvd, rank, size):
    """ISSUE 13 acceptance battery (4-rank, rides 4->3->4 via
    statesync): every survivor censuses its live thread/fd/socket/mmap
    fabric before and after one full grow-shrink cycle, with the
    seeded HVD704 epoch-leak fixture ARMED — one real socket leaks per
    world transition.  The runtime census witness must (a) catch
    EXACTLY the seeded drift (+2 sockets on survivors, nothing else),
    proving the dynamic half fires on the same leak the static rule
    flags, and (b) census baseline-equal once the seed is released,
    proving the product fabric itself leaks nothing across elastic
    reinit cycles."""
    import importlib.util
    import subprocess as _subprocess
    import sys as _sys
    import time as _time

    from census import settle_census, stable_snapshot

    from horovod_tpu import statesync
    from horovod_tpu.analysis.hvdlife import census as life_census

    fixture_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "fixtures", "lint", "life", "epoch_leak.py")
    spec = importlib.util.spec_from_file_location("epoch_leak_fx",
                                                  fixture_path)
    leak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(leak)
    # NOT armed yet: the first leak.reinit_world() below fires at the
    # first world transition, so the baseline census predates every
    # leaked socket and release_all() returns exactly to it.

    state = _statesync_state()
    svc = statesync.StateSyncService(lambda: state)
    launch_rank = rank
    # Warm the fabric so the lazy machinery (sender lanes) exists on
    # both sides of the comparison, then baseline.
    for _ in range(3):
        _statesync_train_step(hvd, state)
        svc.step_boundary()
    baseline = stable_snapshot(f"baseline:world{size}")
    w = life_census.witness()
    assert w.enabled, "battery must run under HOROVOD_LIFE_CENSUS=1"
    w.snapshots.append(baseline)
    w.rank = launch_rank

    shrunk = grown = False
    stop_at = None
    joiner_proc = None
    transitions = 0
    deadline = _time.monotonic() + 150.0
    while _time.monotonic() < deadline:
        try:
            _statesync_train_step(hvd, state)
            change = svc.step_boundary()
        except hvd.RanksFailedError as exc:
            assert not shrunk, f"step failed AFTER the shrink: {exc}"
            change = svc.shrink_on_failure(exc)
        if change is not None and change.kind in ("shrink", "grow"):
            # The seeded leak: one unreleased socket per world epoch.
            leak.reinit_world()
            transitions += 1
        if change is not None and change.kind == "shrink":
            shrunk = True
            assert hvd.size() == size - 1, hvd.size()
            state = statesync.resync_replicated(state,
                                                int(state["step"]))
            if hvd.rank() == 0:
                env = dict(os.environ)
                for k in ("HOROVOD_CHAOS", "HOROVOD_RANK",
                          "HOROVOD_SIZE"):
                    env.pop(k, None)
                joiner_proc = _subprocess.Popen(
                    [_sys.executable, os.path.abspath(__file__),
                     "0", "0",
                     os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"],
                     "statesync_joiner"],
                    env=env, stdout=_subprocess.PIPE,
                    stderr=_subprocess.STDOUT)
        elif change is not None and change.kind == "grow":
            grown = True
            assert hvd.size() == size, hvd.size()
            stop_at = int(state["step"]) + 3
        if stop_at is not None and int(state["step"]) >= stop_at:
            break
    assert shrunk and grown and transitions == 2, \
        (shrunk, grown, transitions)
    _statesync_digest_check(hvd, state)

    # (a) The census catches the seeded leak — and ONLY it: the diff
    # against the size-4 baseline settles to exactly the two leaked
    # sockets (threads, shm, and the product's own sockets all
    # returned; the watcher/heartbeat KV polls flicker a transient
    # socket, which settle_census rides out).
    leak.shutdown()                  # the seeded teardown: releases nothing
    expected_drift = (f"sockets: {baseline['sockets']} -> "
                      f"{baseline['sockets'] + 2} (+2)",)
    armed = settle_census(baseline, expect=expected_drift,
                          label=f"armed:world{size}",
                          context=f"launch rank {launch_rank}")
    w.snapshots.append(armed)
    assert leak.leaked_count() == 2
    print(f"launch rank {launch_rank}: census caught the seeded "
          f"epoch leak: {expected_drift[0]}")

    # (b) Release the seed: the fabric itself is baseline-equal after
    # a full 4->3->4 cycle.
    leak.release_all()
    final = settle_census(baseline, expect=(),
                          label=f"baseline:world{size}:final",
                          context=f"4->{size - 1}->4 cycle, launch "
                                  f"rank {launch_rank}")
    w.snapshots.append(final)
    # Census-done sync: until EVERY rank (joiner included) has taken
    # its final census, nobody may start shutdown — a peer's shutdown
    # broadcast retires this rank's background loop mid-census and the
    # settle loop would read it as a lost thread.
    hvd.allgather_object(launch_rank, name="life.census.done")
    path = life_census.dump_census()
    if path:
        print(f"CENSUS_DUMP {path}")
    svc.close()
    if joiner_proc is not None:
        out, _ = joiner_proc.communicate(timeout=60.0)
        text = out.decode(errors="replace")
        print("--- joiner output ---\n" + text)
        assert joiner_proc.returncode == 0, \
            f"joiner failed rc={joiner_proc.returncode}:\n{text}"
    print(f"launch rank {launch_rank}: census baseline-equal after "
          f"{size}->{size - 1}->{size} at step {int(state['step'])}")


_SERVE_GROW_CFG = dict(max_batch=4, token_budget=64, max_seq=64,
                       slo_ms=120000.0)


def _serve_grow_submit(ex, seed, count):
    import random as _random

    rng = _random.Random(seed)
    for _ in range(count):
        toks = [rng.randrange(2, ex.model.cfg.vocab_size)
                for _ in range(rng.randint(2, 10))]
        ex.stats["offered"] += 1
        assert ex.queue.submit(toks, 10) is not None


def battery_statesync_serve(hvd, rank, size):
    """Serving grow mid-serve (2->3): a joiner replica enters via param
    streaming while requests are in flight (the incumbents' params are
    perturbed away from the seed, so the stream is the only way to
    match them), then a second request wave is served by the grown
    world — the front end's report records world.grows and positive
    goodput before/during/after."""
    import subprocess as _subprocess
    import sys as _sys

    import jax
    import jax.numpy as jnp

    from horovod_tpu import statesync
    from horovod_tpu.serving import ReplicaExecutor, ServeConfig
    from horovod_tpu.serving.loadgen import _goodput_phases
    from horovod_tpu.serving.replica import serving_params_template

    cfg = ServeConfig.from_env(**_SERVE_GROW_CFG)
    tmpl = serving_params_template(cfg)
    params = jax.tree_util.tree_map(lambda a: jnp.asarray(a + 0.25),
                                    tmpl["params"])
    ex = ReplicaExecutor(cfg, params=params)
    service = statesync.StateSyncService(state_provider=ex.state_tree,
                                         static_state=True)
    ex.attach_statesync(service)
    joiner_proc = None
    if rank == 0:
        _serve_grow_submit(ex, 11, 24)
        env = dict(os.environ)
        for k in ("HOROVOD_RANK", "HOROVOD_SIZE"):
            env.pop(k, None)
        joiner_proc = _subprocess.Popen(
            [_sys.executable, os.path.abspath(__file__), "0", "0",
             os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"],
             "statesync_serve_joiner"],
            env=env, stdout=_subprocess.PIPE, stderr=_subprocess.STDOUT)
    # Phase 1: serve the first wave until the joiner has entered (the
    # front end keeps assembling plans while it streams — goodput never
    # goes to zero) and the wave drained.
    ex.serve_loop(stop_when=lambda: bool(ex.stats["grows"]))
    assert ex.stats["grows"], "the joiner never entered"
    assert ex.size == size + 1, ex.size
    assert not ex.stats["shrinks"]
    # Phase 2: a post-grow wave, served by the grown world (the joiner
    # runs the same second serve_loop and exits on its plan.stop).
    ex._stop_requested = False
    if ex.rank == ex.front:
        _serve_grow_submit(ex, 13, 12)
    ex.serve_loop(stop_when=lambda: True)
    if rank == 0:
        st = ex.stats
        assert st["served"] == st["offered"] == 36, st
        assert st["lost"] == 0 and st["expired"] == 0, st
        phases = _goodput_phases(ex, 1.0)
        assert phases is not None and phases["after_rps"] > 0.0, phases
        g = st["grows"][0]
        assert g["from"] == size and g["to"] == size + 1, g
        out, _ = joiner_proc.communicate(timeout=60.0)
        text = out.decode(errors="replace")
        print("--- serve joiner output ---\n" + text)
        assert joiner_proc.returncode == 0, text
        assert "streamed params verified" in text
        print(f"serving grow: {st['served']} served across "
              f"{size}->{size + 1}; goodput phases {phases}")
    service.close()


def battery_statesync_serve_joiner(port):
    """The serving joiner: streams the incumbents' perturbed params,
    enters mid-serve, and serves both phases until the front drains."""
    import jax
    import numpy as _np

    from horovod_tpu.serving import ServeConfig
    from horovod_tpu.serving.replica import (join_serving_world,
                                             serving_params_template)

    cfg = ServeConfig.from_env(**_SERVE_GROW_CFG)
    ex = join_serving_world(cfg)
    # The streamed params must be the incumbents' PERTURBED values —
    # the seed template plus 0.25 — not anything derivable locally.
    mine = _np.asarray(jax.tree_util.tree_leaves(ex.params)[0])
    seed = _np.asarray(jax.tree_util.tree_leaves(
        serving_params_template(cfg)["params"])[0])
    _np.testing.assert_allclose(mine, seed + 0.25, rtol=0, atol=1e-6)
    print("serve joiner: streamed params verified (seed + 0.25)")
    import horovod_tpu as hvd

    ex.serve_loop()                    # phase 1: exits on plan.stop
    ex._stop_requested = False
    ex.serve_loop()                    # phase 2
    print(f"serve joiner: entered as rank {ex.rank}/{ex.size}, "
          f"served group {ex.group}, completed "
          f"{len(ex.completed)} locally")
    ex.statesync.close()
    hvd.shutdown()
    return 0


def _battery_fleet_train(port):
    """ISSUE 20 fleet battery, training side (launch ranks 0-2, world
    size 3): rank 0 hosts the FleetController + WeightPublisher; the
    serving burst drives a train->serve migration of rank 2 (orderly
    statesync departure — no RanksFailedError), survivors keep
    training and publishing snapshots until the serving front posts
    the done flag."""
    import time as _time

    import jax

    from horovod_tpu import statesync
    from horovod_tpu.fleet import (FleetController, FleetPolicy,
                                   WeightPublisher, poll_depart,
                                   publish_gauge)
    from horovod_tpu.runner.network import RendezvousClient

    launch_rank = int(sys.argv[1])
    os.environ["HOROVOD_SIZE"] = "3"
    os.environ["HOROVOD_STATESYNC_WORLD"] = "train"
    import horovod_tpu as hvd

    hvd.init()
    kv = RendezvousClient("127.0.0.1", port, 20.0)
    state = _statesync_state(n=1 << 10)
    svc = statesync.StateSyncService(
        lambda: state,
        donate_provider=lambda: {"shard": state["opt"]})
    ctl = pub = ptree = None
    if launch_rank == 0:
        from horovod_tpu.serving import ServeConfig
        from horovod_tpu.serving.replica import serving_params_template

        # The continuously-deployed params are serving-model-shaped:
        # the publisher's snapshot must unflatten into the replicas'
        # param template bit-for-bit.
        ptree = serving_params_template(
            ServeConfig.from_env(**_SERVE_GROW_CFG))
        policy = FleetPolicy(min_train=2, min_serve=1,
                             hysteresis_rounds=2, cooldown_rounds=1000,
                             up_shed_rate=0.05, up_queue_fraction=0.25,
                             idle_queue_fraction=0.01,
                             train_lag_ms=1e9, queue_depth_limit=8)
        ctl = FleetController(kv, policy, interval_s=0.25,
                              migrate_timeout_s=240.0)
        ctl.start()
        pub = WeightPublisher(kv, publish_steps=5, chunk_bytes=1 << 14,
                              keep=10)
        pub.start()
    directive = None
    shrunk = False
    departed = False
    step = 0
    deadline = _time.monotonic() + 300.0
    while _time.monotonic() < deadline:
        # Bare collectives: any RanksFailedError fails the battery —
        # the migration must ride the orderly-departure boundary.
        _statesync_train_step(hvd, state)
        change = svc.step_boundary()
        step += 1
        if change is not None and change.kind == "departed":
            departed = True
            break
        if change is not None and change.kind == "shrink":
            assert change.dead == (2,), change
            assert hvd.size() == 2
            shrunk = True
            state = statesync.resync_replicated(state,
                                                int(state["step"]))
        if launch_rank == 0:
            ptree = {"params": jax.tree_util.tree_map(
                lambda a: np.asarray(a) + np.float32(0.001),
                ptree["params"])}
            pub.maybe_publish(step, ptree)
            publish_gauge(kv, "train", hvd.size(),
                          straggler_lag_ms=0.0)
        if directive is None:
            directive = poll_depart(kv, "train", hvd.rank())
            if directive is not None:
                svc.request_depart()
        if shrunk and kv.get("fleet.test", "done") is not None:
            break
        _time.sleep(0.1)
    if departed:
        assert launch_rank == 2 and directive is not None, \
            (launch_rank, directive)
        svc.close()
        hvd.shutdown()
        return _battery_fleet_mover(port, int(directive["mid"]))
    assert shrunk, "the migration never happened"
    if launch_rank == 0:
        # The controller observed the joined mark and closed the
        # journal record (done) — one migration, zero aborts.
        ctl_deadline = _time.monotonic() + 60.0
        while not ctl.stats["completed"] \
                and _time.monotonic() < ctl_deadline:
            _time.sleep(0.1)
        assert ctl.stats["migrations"] == 1, ctl.stats
        assert ctl.stats["completed"] == 1, ctl.stats
        assert ctl.stats["aborted"] == 0, ctl.stats
        assert pub.published >= 2, pub.published
        pub.drain()
        pub.close()
        ctl.stop()
        print(f"fleet trainer 0: migration journal closed "
              f"{ctl.stats}; {pub.published} snapshots published")
    _statesync_witness_dump("fleet battery trainer", launch_rank)
    svc.close()
    print(f"fleet trainer {launch_rank}: survived 3->2 at step "
          f"{int(state['step'])}, no RanksFailedError anywhere")
    return 0


def _battery_fleet_mover(port, mid):
    """The moved rank's second life: after the orderly train-world
    departure it joins the serving world via peer-streamed state,
    writes the joined mark that closes the controller's journal
    record, and serves until the front drains — swapping in published
    weights at the same broadcast plan boundaries as the incumbent."""
    import jax

    from horovod_tpu.fleet import mark_joined
    from horovod_tpu.runner.network import RendezvousClient
    from horovod_tpu.serving import ServeConfig
    from horovod_tpu.serving.replica import join_serving_world
    from horovod_tpu.statesync.snapshot import (flatten_state,
                                                state_digest)

    base = os.environ["HOROVOD_RENDEZVOUS_EPOCH"].split("~", 1)[0]
    os.environ["HOROVOD_RENDEZVOUS_EPOCH"] = f"{base}~serve"
    os.environ["HOROVOD_STATESYNC_WORLD"] = "serve"
    os.environ.pop("HOROVOD_RANK", None)
    os.environ.pop("HOROVOD_SIZE", None)
    kv = RendezvousClient("127.0.0.1", port, 20.0)
    cfg = ServeConfig.from_env(**_SERVE_GROW_CFG)
    ex = join_serving_world(cfg)
    mark_joined(kv, mid, rank=ex.rank, size=ex.size)
    ex.attach_fleet(kv, interval_s=0.1)
    import horovod_tpu as hvd

    ex.serve_loop()                    # exits on the front's plan.stop
    assert ex.weight_version >= 1, \
        "no weight push landed on the moved replica"
    last = ex.stats["weight_swaps"][-1]
    assert last["version"] == ex.weight_version, ex.stats
    image = flatten_state({"params": jax.tree_util.tree_map(
        np.asarray, ex.params)})
    assert state_digest(image) == last["digest"], \
        "post-swap params diverge from the published snapshot digest"
    print(f"fleet mover: joined serving as rank {ex.rank}/{ex.size} "
          f"(mig {mid}), swapped to v{ex.weight_version}, digest "
          f"verified")
    _statesync_witness_dump("fleet battery mover", 2)
    ex.close()
    ex.statesync.close()
    hvd.shutdown()
    return 0


def _battery_fleet_serve(port):
    """ISSUE 20 fleet battery, serving side (launch rank 3 = the
    size-1 serving world's front): a request burst overloads the
    queue gauge, the controller migrates a trainer rank in (1->2
    grow mid-serve), and the continuously-deployed weights roll out
    to every replica at one broadcast plan boundary — zero failed
    admitted requests, goodput phases recorded."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu import statesync
    from horovod_tpu.fleet import publish_gauge
    from horovod_tpu.runner.network import RendezvousClient
    from horovod_tpu.serving import ReplicaExecutor, ServeConfig
    from horovod_tpu.serving.loadgen import _goodput_phases
    from horovod_tpu.serving.replica import serving_params_template

    base = os.environ["HOROVOD_RENDEZVOUS_EPOCH"]
    os.environ["HOROVOD_RENDEZVOUS_EPOCH"] = f"{base}~serve"
    os.environ["HOROVOD_RANK"] = "0"
    os.environ["HOROVOD_SIZE"] = "1"
    os.environ["HOROVOD_STATESYNC_WORLD"] = "serve"
    import horovod_tpu as hvd

    hvd.init()
    kv = RendezvousClient("127.0.0.1", port, 20.0)
    cfg = ServeConfig.from_env(**_SERVE_GROW_CFG)
    tmpl = serving_params_template(cfg)
    ex = ReplicaExecutor(cfg, params=jax.tree_util.tree_map(
        jnp.asarray, tmpl["params"]))
    service = statesync.StateSyncService(state_provider=ex.state_tree,
                                         static_state=True)
    ex.attach_statesync(service)
    ex.attach_fleet(kv, interval_s=0.1)
    _serve_grow_submit(ex, 11, 24)     # the traffic burst
    progress = {"v_at_grow": None, "wave": 100}

    def tick():
        # The front's per-step gauge publish IS the policy's input:
        # outstanding work (queued + in-flight) over the configured
        # depth limit is what the controller's policy thresholds.
        depth = float(ex.queue.depth() + ex.batcher.inflight_count())
        publish_gauge(kv, "serve", ex.size, shed_rate=0.0,
                      queue_depth=depth)
        if not ex.stats["grows"]:
            if depth < 4:
                # Keep the burst hot until the migration lands — the
                # policy needs the overload to hold across its
                # hysteresis window.
                progress["wave"] += 1
                _serve_grow_submit(ex, progress["wave"], 4)
            return False
        if progress["v_at_grow"] is None:
            progress["v_at_grow"] = ex.weight_version
            _serve_grow_submit(ex, 13, 12)   # post-migration wave
        # Drain only after a weight push landed post-grow: the swap
        # is scheduled at min(staged) across ranks, so reaching it
        # proves the rollout hit the moved replica too.
        return ex.weight_version > progress["v_at_grow"]

    ex.serve_loop(stop_when=tick)
    st = ex.stats
    assert ex.size == 2 and st["grows"], (ex.size, st["grows"])
    g = st["grows"][0]
    assert g["from"] == 1 and g["to"] == 2, g
    assert st["offered"] >= 36, st
    assert st["served"] == st["offered"], st
    assert st["lost"] == 0 and st["expired"] == 0, st
    phases = _goodput_phases(ex, 1.0)
    assert phases is not None and phases["after_rps"] > 0.0, phases
    assert st["weight_swaps"], st
    last = st["weight_swaps"][-1]
    assert last["version"] == ex.weight_version \
        > progress["v_at_grow"], (last, progress)
    image = statesync.flatten_state({"params": jax.tree_util.tree_map(
        np.asarray, ex.params)})
    assert statesync.state_digest(image) == last["digest"], \
        "post-swap params diverge from the published snapshot digest"
    kv.put("fleet.test", "done", b"1")
    print(f"fleet front: {st['served']} served across 1->2 with "
          f"rollout to v{ex.weight_version}; goodput phases {phases}")
    dump_dir = os.environ.get("HOROVOD_FLEET_DUMP_DIR")
    if dump_dir:
        # Console-fixture capture (tests/fixtures/console/regen_fleet
        # .py): the front's loadgen report is the goodput/weights
        # evidence the == fleet == panel renders.
        from horovod_tpu.serving import loadgen

        report = loadgen.build_report(
            ex, offered=st["offered"], wall_s=1.0,
            args_echo={"battery": "fleet"})
        loadgen.write_report(
            report, os.path.join(dump_dir, "SERVE_r{rank}.json"), 0)
    _statesync_witness_dump("fleet battery front", 3)
    ex.close()
    service.close()
    hvd.shutdown()
    return 0


def battery_fleet(port):
    """ISSUE 20 acceptance (4 launch ranks, PRE-INIT): two statesync
    worlds on ONE coordinator KV — launch ranks 0-2 train, launch
    rank 3 serves.  A serving burst triggers a traffic-driven
    train->serve migration AND a mid-run weight push lands on every
    serving replica at one broadcast plan boundary."""
    launch_rank = int(sys.argv[1])
    if launch_rank == 3:
        return _battery_fleet_serve(port)
    return _battery_fleet_train(port)


BATTERIES = {
    "collectives": battery_collectives,
    "serving": battery_serving,
    "serving_paged": battery_serving_paged,
    "serving_disagg": battery_serving_disagg,
    "san": battery_san,
    "trace": battery_trace,
    "telemetry": battery_telemetry,
    "perfscope": battery_perfscope,
    "streams": battery_streams,
    "matrix": battery_matrix,
    "autotune": battery_autotune,
    "stall": battery_stall,
    "xla": battery_xla,
    "errors": battery_errors,
    "join": battery_join,
    "adasum": battery_adasum,
    "adasum_np": battery_adasum_np,
    "torch": battery_torch,
    "torch_grid": battery_torch_grid,
    "syncbn": battery_syncbn,
    "tensorflow": battery_tensorflow,
    "tf_grid": battery_tf_grid,
    "tf_function": battery_tf_function,
    "sparse": battery_sparse,
    # Merged one-world batteries: the torch/TF imports (~8-12 s per
    # spawned rank) dominated separate 2-rank worlds, so the 2-rank
    # coverage shares one spin-up per framework (the reference CI
    # likewise groups framework tests per container,
    # .buildkite/gen-pipeline.sh); the 3- and 4-rank worlds stay
    # separate.
    "torch_all": lambda hvd, rank, size: [
        battery_torch(hvd, rank, size),
        battery_torch_grid(hvd, rank, size),
        battery_sparse(hvd, rank, size),
        battery_syncbn(hvd, rank, size)],
    "tensorflow_all": lambda hvd, rank, size: [
        battery_tensorflow(hvd, rank, size),
        battery_tf_grid(hvd, rank, size),
        battery_tf_function(hvd, rank, size)],
    "rolling": battery_rolling,
    "hierarchical": battery_hierarchical,
    "shm": battery_shm,
    "compress": battery_compress,
    "compress_shm": battery_compress_shm,
    "compress_xla": battery_compress_xla,
    "mxnet": battery_mxnet,
    "peerdeath": battery_peerdeath,
    # resilience/ chaos batteries (ISSUE 5): every one runs under the
    # hard timeout guard in tests/test_resilience.py so a regression
    # re-introducing a deadlock fails fast.
    "resilience_kill": battery_resilience_kill,
    "resilience_retry": battery_resilience_retry,
    "resilience_freeze": battery_resilience_freeze,
    "resilience_off": battery_resilience_off,
    # statesync/ elastic-grow batteries (ISSUE 10).  The *_joiner
    # entries are PRE-INIT batteries: main() dispatches them before
    # hvd.init — join_world performs its own world entry.
    "statesync_grow": battery_statesync_grow,
    "statesync_preempt": battery_statesync_preempt,
    "statesync_serve": battery_statesync_serve,
    # hvdlife runtime census witness (ISSUE 13): the 4->3->4 cycle must
    # census baseline-equal on every survivor, and the seeded HVD704
    # fixture must be caught by the census diff.
    "statesync_life": battery_statesync_life,
    # hvdflow runtime cross-check (ISSUE 12): the seeded rank-gated
    # collective must die as a structured fingerprint ERROR, not a hang.
    "flow": battery_flow,
    # hvdshard runtime cross-check (ISSUE 17): the seeded spec-divergent
    # collective dies under op×spec identity; the proto-2 mixed world
    # negotiates sp_* off and stays green on the same step.
    "shard": battery_shard,
    "shard_compat": battery_shard_compat,
    # ISSUE 18: autotuned algo x tree-threshold sweep, negotiated
    # end-to-end through ResponseList.tuned_algo.
    "algotune": battery_algotune,
}

def battery_fleetsim(port):
    """ISSUE 16 fleet-scale acceptance: ONE worker process hosts the
    whole virtual fleet — hundreds of protocol-only ranks running the
    real rendezvous client / heartbeat / membership paths against the
    external (possibly replicated) control plane, with chaos from
    HOROVOD_CHAOS composing unchanged.  Pre-init: the fleet never calls
    hvd.init (no tensor data plane).  Prints the FLEETSIM_SUMMARY line
    the test asserts on; rc 0 iff zero failed steps."""
    from horovod_tpu.fleetsim.__main__ import main as fleet_main
    return fleet_main()


PREINIT_BATTERIES = {
    "statesync_joiner": battery_statesync_joiner,
    "statesync_serve_joiner": battery_statesync_serve_joiner,
    # ISSUE 20: unified train+serve fleet — launch ranks enter their
    # own worlds (two statesync worlds, one coordinator KV), and the
    # moved rank re-enters the other world mid-battery.
    "fleet": battery_fleet,
    # ISSUE 16: the rank-virtualized fleet harness (one process = the
    # whole fleet; `size` counts host processes, not virtual ranks).
    "fleetsim": battery_fleetsim,
}


def main() -> int:
    rank, size, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    battery = sys.argv[4] if len(sys.argv) > 4 else "collectives"
    os.environ["HOROVOD_RANK"] = str(rank)
    os.environ["HOROVOD_SIZE"] = str(size)
    # A replicated-control-plane harness passes a multi-endpoint seed
    # list through the env; plain worlds get the localhost default
    # (test_multiprocess._run_world pops any stale inherited value).
    os.environ.setdefault("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
    os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port)
    # Generous under CI load: a peer may still be importing torch/tf when
    # this rank reaches rendezvous.
    os.environ.setdefault("HOROVOD_GLOO_TIMEOUT_SECONDS", "90")
    if battery == "fleetsim":
        # The whole fleet lives in THIS process: metrics + flight on so
        # the episode leaves console-renderable rank-stamped evidence.
        os.environ.setdefault("HOROVOD_METRICS", "on")
        _dump = os.environ.get("HOROVOD_FLEETSIM_DUMP_DIR")
        if _dump:
            # The dump dir owns the episode's evidence: force the
            # flight file into it (an inherited default — e.g. the
            # pytest conftest's — would strand the flight dump outside
            # the directory the console is pointed at).
            os.environ["HOROVOD_FLIGHT_FILE"] = \
                os.path.join(_dump, "flight.json")
    if battery == "stall":
        os.environ["HOROVOD_STALL_CHECK_TIME_SECONDS"] = "1"
        os.environ["HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"] = "3"
    if battery == "rolling":
        # Rank 1 is the still-old framework version: it advertises wire
        # proto 1, so every mesh negotiates the base schema until the
        # battery lifts the pin mid-run (the rolling upgrade).  Strict
        # fingerprinting turns any schema asymmetry into a structured
        # divergence ERROR within one cycle.
        if rank == 1:
            os.environ["HOROVOD_PROTO_COMPAT"] = "1"
        os.environ.setdefault("HOROVOD_FINGERPRINT", "strict")
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
    if battery == "flow":
        # Strict mode: divergence surfaces within one forced
        # negotiation heartbeat even in cache steady state.
        os.environ.setdefault("HOROVOD_FINGERPRINT", "strict")
        os.environ.setdefault("HOROVOD_FLOW_SEED_RANK", "2")
    if battery in ("shard", "shard_compat"):
        # Strict mode so the op×spec divergence (or, in the compat
        # world, its negotiated absence) is judged every cycle.
        os.environ.setdefault("HOROVOD_FINGERPRINT", "strict")
    if battery == "shard_compat":
        # Rank 1 is the pre-sharding framework version: proto 2 carries
        # fp_/tm_/trace_ but not sp_*, so every mesh negotiates
        # FEATURE_SHARDING off and both ranks fold 5-column identity.
        if rank == 1:
            os.environ["HOROVOD_PROTO_COMPAT"] = "2"
    if battery == "autotune":
        os.environ["HOROVOD_AUTOTUNE"] = "1"
        os.environ["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"] = "1"
        os.environ["HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"] = "2"
        os.environ["HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"] = "3"
    if battery == "algotune":
        os.environ["HOROVOD_AUTOTUNE"] = "1"
        os.environ["HOROVOD_AUTOTUNE_PIPELINE"] = "1"
        os.environ["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"] = "1"
        os.environ["HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"] = "1"
        os.environ["HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"] = "1"
        # Pin the TCP plane: the algo verdict lands on TcpCollectives.
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
    if battery == "telemetry":
        os.environ["HOROVOD_METRICS"] = "on"
        os.environ["HOROVOD_METRICS_WINDOW"] = "8"
        os.environ["HOROVOD_STRAGGLER_THRESHOLD_MS"] = "10"
        os.environ["HOROVOD_METRICS_PORT"] = "19730"   # +rank; ephemeral fallback
        os.environ["HOROVOD_METRICS_FILE"] = \
            f"/tmp/hvd_tm_{os.environ['HOROVOD_RENDEZVOUS_EPOCH']}.json"
        # Pin the TCP plane so the per-peer byte counters see the traffic.
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
    if battery == "perfscope":
        os.environ["HOROVOD_METRICS"] = "on"
        os.environ["HOROVOD_METRICS_FILE"] = \
            f"/tmp/hvd_perf_{os.environ['HOROVOD_RENDEZVOUS_EPOCH']}.json"
        # Pin the TCP plane so the busbw cells land on one plane; fusion
        # off keeps each named payload its own size-bucket sample.
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        os.environ["HOROVOD_FUSION_THRESHOLD"] = "0"
    if battery == "streams":
        # Two dispatch streams over the TCP plane; fusion off so async
        # bursts negotiate into SEVERAL responses per cycle (the unit the
        # round-robin stream assignment distributes).
        os.environ["HOROVOD_NUM_STREAMS"] = "2"
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        os.environ["HOROVOD_FUSION_THRESHOLD"] = "0"
    if battery == "shm":
        os.environ["HOROVOD_SHM_OPERATIONS"] = "1"   # require formation
        os.environ["HOROVOD_SHM_CAPACITY"] = str(1 << 20)
    if battery == "san":
        # Runtime lock-order witness (ISSUE 8): must be in the env
        # BEFORE horovod_tpu imports so the wrappers install ahead of
        # every package lock creation.  TCP plane pinned so the
        # controller<->transport edge is deterministic.
        os.environ["HOROVOD_SAN"] = "1"
        os.environ["HOROVOD_SAN_FILE"] = \
            f"/tmp/hvd_san_{os.environ['HOROVOD_RENDEZVOUS_EPOCH']}.json"
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
    if battery == "trace":
        epoch = os.environ["HOROVOD_RENDEZVOUS_EPOCH"]
        os.environ["HOROVOD_TIMELINE"] = f"/tmp/hvd_trace_{epoch}.json"
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        # PR 5 deterministic delay injection: the last rank freezes
        # 120 ms before dispatching every tr_* collective.
        os.environ["HOROVOD_CHAOS"] = \
            f"freeze:rank={size - 1},name=tr_,ms=120"
        os.environ["HOROVOD_FLIGHT_FILE"] = \
            f"/tmp/hvd_flight_{epoch}.json"
    if battery.startswith("statesync"):
        # Elastic-grow batteries: TCP plane pinned (worlds rebuild at
        # several sizes; shm formation at each would dominate wall
        # time), flight dumps in /tmp, generous per-round deadline for
        # CI load.
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        os.environ["HOROVOD_FLIGHT_FILE"] = \
            f"/tmp/hvd_flight_{os.environ['HOROVOD_RENDEZVOUS_EPOCH']}.json"
        os.environ.setdefault("HOROVOD_STATESYNC_TIMEOUT_SECONDS", "45")
        os.environ.setdefault("HOROVOD_FAULT_TOLERANCE", "1")
    if battery == "fleet":
        # ISSUE 20: two statesync worlds (train + serve) share one
        # coordinator KV.  TCP plane pinned, flight dumps for the
        # hvdmc witness, generous deadlines — the moved rank compiles
        # the serving model mid-migration.
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        os.environ["HOROVOD_FLIGHT_FILE"] = \
            f"/tmp/hvd_flight_{os.environ['HOROVOD_RENDEZVOUS_EPOCH']}.json"
        os.environ.setdefault("HOROVOD_STATESYNC_TIMEOUT_SECONDS", "120")
        os.environ.setdefault("HOROVOD_FAULT_TOLERANCE", "1")
        os.environ.setdefault("HOROVOD_FAULT_TIMEOUT", "30")
        os.environ.setdefault("HOROVOD_METRICS", "on")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if battery == "statesync_grow":
        os.environ.setdefault("HOROVOD_FAULT_TIMEOUT", "5")
        # Real SIGKILL of rank 2 mid-training (~step 4: each step costs
        # three responses — the train allreduce + the two halves of the
        # membership allgather).
        os.environ.setdefault("HOROVOD_CHAOS", "kill:rank=2,op=13,sig=9")
    if battery == "statesync_life":
        os.environ.setdefault("HOROVOD_FAULT_TIMEOUT", "5")
        os.environ.setdefault("HOROVOD_CHAOS", "kill:rank=2,op=13,sig=9")
        # The runtime census witness around every world transition,
        # dumped rank-stamped to /tmp for the driver's check_dumps.
        os.environ["HOROVOD_LIFE_CENSUS"] = "1"
        os.environ["HOROVOD_LIFE_CENSUS_FILE"] = \
            f"/tmp/hvd_census_" \
            f"{os.environ['HOROVOD_RENDEZVOUS_EPOCH']}.json"
    if battery == "statesync_preempt":
        # Grace must beat the heartbeat: generous fault timeout, SIGTERM
        # at collective 6, 20 s to reach the next step boundary.
        os.environ.setdefault("HOROVOD_FAULT_TIMEOUT", "30")
        os.environ["HOROVOD_PREEMPT_GRACE_S"] = "20"
        os.environ.setdefault("HOROVOD_CHAOS", "preempt:rank=1,op=6")
    if battery in ("statesync_serve", "statesync_serve_joiner"):
        os.environ.setdefault("HOROVOD_FAULT_TIMEOUT", "10")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if battery.startswith("resilience"):
        # Chaos batteries pin the TCP plane so the socket-level deadline
        # guards are the ones exercised (the shm plane has its own).
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        # Pin the flat ring schedule: the chaos scripts target specific
        # ring edges (e.g. rank 1 -> rank 2 delayed-send), which the
        # small-tensor tree leg (ISSUE 18) would never traverse.
        os.environ["HOROVOD_TREE_THRESHOLD_BYTES"] = "0"
        # Flight dumps land in /tmp, not the repo working directory.
        os.environ["HOROVOD_FLIGHT_FILE"] = \
            f"/tmp/hvd_flight_{os.environ['HOROVOD_RENDEZVOUS_EPOCH']}.json"
    if battery in ("resilience_kill", "resilience_retry",
                   "resilience_freeze"):
        os.environ["HOROVOD_FAULT_TOLERANCE"] = "1"
    if battery in ("serving", "serving_paged"):
        # ISSUE 9: data-parallel serving over the TCP plane with chaos
        # SIGKILL of rank 2 mid-serve (global collective index 11 = the
        # completion exchange of serve step 2, with ~16 requests
        # in-flight).  Fault tolerance on so survivors convert the dead
        # peer and shrink; metrics on so admission keys off live gauges.
        # serving_paged (ISSUE 14) rides the identical chaos with the
        # paged KV plane under it.
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        os.environ["HOROVOD_FAULT_TOLERANCE"] = "1"
        os.environ["HOROVOD_FAULT_TIMEOUT"] = "5"
        os.environ["HOROVOD_METRICS"] = "on"
        os.environ["HOROVOD_CHAOS"] = "kill:rank=2,op=11,sig=9"
        os.environ["HOROVOD_FLIGHT_FILE"] = \
            f"/tmp/hvd_flight_{os.environ['HOROVOD_RENDEZVOUS_EPOCH']}.json"
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if battery == "serving_disagg":
        # ISSUE 14 split-role loop under the STRICT fingerprint: a
        # rank-divergent collective anywhere in the prefill/decode role
        # split would surface as a structured ERROR within one cycle.
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        os.environ["HOROVOD_METRICS"] = "on"
        os.environ["HOROVOD_FINGERPRINT"] = "strict"
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if battery == "resilience_kill":
        os.environ["HOROVOD_FAULT_TIMEOUT"] = "5"
        # Real SIGKILL mid-allreduce at global collective index 3
        # (ISSUE 5 acceptance criterion).
        os.environ["HOROVOD_CHAOS"] = "kill:rank=2,op=3,sig=9"
    if battery == "resilience_retry":
        os.environ["HOROVOD_FAULT_TIMEOUT"] = "3"
        os.environ["HOROVOD_ON_FAILURE"] = "retry"
        # Hold rank 1's FIRST data-mesh send to rank 2 for 9 s: over the
        # 3 s deadline on attempt 0, exhausted (count=1) on the retry.
        os.environ["HOROVOD_CHAOS"] = \
            "delay:rank=1,mesh=data,peer=2,send=0,ms=9000,count=1"
    if battery == "resilience_freeze":
        os.environ["HOROVOD_FAULT_TIMEOUT"] = "3"
        os.environ["HOROVOD_CHAOS"] = "freeze:rank=1,op=1,ms=12000"
    if battery == "compress":
        # Pin the TCP plane so its byte counters see the traffic, and
        # the ring schedule so the asserted 2(N-1)/N wire-byte fractions
        # hold (the small-tensor tree of ISSUE 18 trades bytes for
        # latency: whole-buffer contributions gather to the root).
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        os.environ["HOROVOD_TREE_THRESHOLD_BYTES"] = "0"
    if battery == "compress_shm":
        os.environ["HOROVOD_SHM_OPERATIONS"] = "1"
        os.environ["HOROVOD_SHM_CAPACITY"] = str(1 << 20)
    if battery == "compress_xla":
        os.environ["HOROVOD_JAX_DISTRIBUTED"] = "1"
        os.environ["HOROVOD_XLA_OPERATIONS"] = "1"
        os.environ["HOROVOD_GLOO_TIMEOUT_SECONDS"] = "60"
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    if battery == "hierarchical_tcp":
        os.environ["HOROVOD_SHM_OPERATIONS"] = "0"
        battery = "hierarchical"
    if battery == "hierarchical":
        # Two hosts x two slots, homogeneous host-major layout (what the
        # launcher assigns); both knobs on.
        local_size = 2
        os.environ["HOROVOD_LOCAL_RANK"] = str(rank % local_size)
        os.environ["HOROVOD_LOCAL_SIZE"] = str(local_size)
        os.environ["HOROVOD_CROSS_RANK"] = str(rank // local_size)
        os.environ["HOROVOD_CROSS_SIZE"] = str(size // local_size)
        os.environ["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
        os.environ["HOROVOD_HIERARCHICAL_ALLGATHER"] = "1"
    if battery == "xla":
        # Form the JAX world + device data plane (CPU multi-process).
        os.environ["HOROVOD_JAX_DISTRIBUTED"] = "1"
        os.environ["HOROVOD_XLA_OPERATIONS"] = "1"
        os.environ["HOROVOD_GLOO_TIMEOUT_SECONDS"] = "60"
        os.environ["JAX_PLATFORMS"] = "cpu"
        # Env alone is too late when a sitecustomize already imported jax
        # (the axon tunnel probes — and can wedge — during discovery).
        import jax
        jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    if battery in PREINIT_BATTERIES:
        # Joiner batteries enter the world themselves (join_world runs
        # core.init after its streamed state verifies).
        try:
            return PREINIT_BATTERIES[battery](port)
        except BaseException:
            traceback.print_exc()
            return 1

    import horovod_tpu as hvd

    hvd.init()
    try:
        assert hvd.rank() == rank
        assert hvd.size() == size
        BATTERIES[battery](hvd, rank, size)
    except BaseException:
        traceback.print_exc()
        return 1
    finally:
        hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
