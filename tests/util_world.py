"""In-process fake multi-rank world for controller unit tests.

SURVEY §4 rebuild guidance: single-process unit tests drive the controller /
fusion / cache logic against a fake in-process transport (the analogue of
the reference's mocked-out MPI in test/single/).  N controllers run in N
threads; the transport synchronises them with barriers over shared dicts.
"""
from __future__ import annotations

import threading
from typing import Callable

from horovod_tpu.common.controller import Controller, Transport
from horovod_tpu.common.message import RequestList, ResponseList


class InProcWorld:
    """Shared state for `size` in-process ranks."""

    def __init__(self, size: int) -> None:
        self.size = size
        self._deposits: dict[int, object] = {}
        self._result: object = None
        self._clear = threading.Barrier(size, action=self._do_clear)
        self._full = threading.Barrier(size)
        self.gather_count = 0
        self.sync_count = 0

    def _do_clear(self) -> None:
        self._deposits = {}
        self._result = None

    def transport(self, rank: int) -> "InProcTransport":
        return InProcTransport(self, rank)


class InProcTransport(Transport):
    def __init__(self, world: InProcWorld, rank: int) -> None:
        self.world = world
        self.rank = rank

    def _exchange(self, value, combine: Callable[[dict], object]):
        w = self.world
        w._deposits[self.rank] = value
        w._full.wait()          # all deposited
        if self.rank == 0:
            w._result = combine(dict(w._deposits))
        w._full.wait()          # result ready
        result = w._result
        w._clear.wait()         # all read; clears shared state
        return result

    def bitwise_sync(self, and_word: int, or_word: int):
        self.world.sync_count += 1

        def combine(deposits: dict) -> tuple[int, int]:
            a, o = -1, 0   # -1 = all ones
            for aw, ow in deposits.values():
                a &= aw
                o |= ow
            return a, o

        return self._exchange((and_word, or_word), combine)

    def gather_requests(self, request_list: RequestList):
        self.world.gather_count += 1

        def combine(deposits: dict) -> list[RequestList]:
            return [deposits[r] for r in sorted(deposits)]

        gathered = self._exchange(request_list, combine)
        return gathered if self.rank == 0 else None

    def broadcast_responses(self, response_list):
        def combine(deposits: dict):
            rl = deposits[0]
            assert rl is not None
            # serialize/deserialize so ranks never share mutable responses
            return rl.to_bytes()

        raw = self._exchange(response_list if self.rank == 0 else None,
                             combine)
        return response_list if self.rank == 0 \
            else ResponseList.from_bytes(raw)

    def barrier(self) -> None:
        self._exchange(None, lambda d: None)


def run_ranks(size: int, fn: Callable[[int], object],
              timeout: float = 30.0) -> list:
    """Run fn(rank) on `size` threads; re-raise the first failure."""
    results: list = [None] * size
    errors: list = []

    def _worker(r: int) -> None:
        try:
            results[r] = fn(r)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=_worker, args=(r,), daemon=True)
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "rank thread hung"
    if errors:
        raise errors[0]
    return results


def make_controller(rank: int, size: int, world: InProcWorld,
                    cache_capacity: int = 0,
                    fusion_threshold: int | None = None) -> Controller:
    from horovod_tpu.common.group_table import GroupTable
    from horovod_tpu.common.response_cache import ResponseCache
    from horovod_tpu.common.stall_inspector import StallInspector
    from horovod_tpu.common.tensor_queue import TensorQueue

    ctrl = Controller(
        rank=rank, size=size, transport=world.transport(rank),
        tensor_queue=TensorQueue(), group_table=GroupTable(),
        response_cache=ResponseCache(cache_capacity),
        stall_inspector=StallInspector())
    if fusion_threshold is not None:
        ctrl.tensor_fusion_threshold = fusion_threshold
    return ctrl
