"""Data-loader tests: sharding, async prefetch, device prefetch."""
from __future__ import annotations

import numpy as np

from horovod_tpu.data import (AsyncDataLoaderMixin, ShardedBatchLoader,
                              prefetch_to_device)


def _dataset(n=32):
    return {"image": np.arange(n * 4, dtype=np.float32).reshape(n, 4),
            "label": np.arange(n, dtype=np.int32)}


class TestShardedBatchLoader:
    def test_batches_cover_dataset(self):
        loader = ShardedBatchLoader(_dataset(), batch_size=8, shuffle=False)
        batches = list(loader)
        assert len(batches) == len(loader) == 4
        seen = np.concatenate([b["label"] for b in batches])
        np.testing.assert_array_equal(np.sort(seen), np.arange(32))

    def test_rank_sharding_is_disjoint_and_complete(self):
        loaders = [ShardedBatchLoader(_dataset(), batch_size=4,
                                      shuffle=True, seed=7, rank=r,
                                      num_replicas=2) for r in range(2)]
        seen = [np.concatenate([b["label"] for b in ld]) for ld in loaders]
        assert set(seen[0]) & set(seen[1]) == set()
        assert set(seen[0]) | set(seen[1]) == set(range(32))

    def test_epoch_changes_shuffle(self):
        loader = ShardedBatchLoader(_dataset(), batch_size=32, seed=1)
        first = next(iter(loader))["label"].copy()
        loader.set_epoch(1)
        second = next(iter(loader))["label"]
        assert not np.array_equal(first, second)

    def test_drop_last(self):
        loader = ShardedBatchLoader(_dataset(30), batch_size=8,
                                    shuffle=False, drop_last=True)
        assert len(loader) == 3
        assert sum(1 for _ in loader) == 3


class TestAsyncPrefetch:
    def test_same_batches_as_sync(self):
        class AsyncLoader(AsyncDataLoaderMixin, ShardedBatchLoader):
            pass

        sync = ShardedBatchLoader(_dataset(), batch_size=8, shuffle=False)
        async_ = AsyncLoader(_dataset(), batch_size=8, shuffle=False,
                             async_loader_queue_size=2)
        for a, b in zip(sync, async_):
            np.testing.assert_array_equal(a["label"], b["label"])

    def test_producer_error_propagates(self, monkeypatch):
        import pytest

        class AsyncLoader(AsyncDataLoaderMixin, ShardedBatchLoader):
            pass

        orig = ShardedBatchLoader._iterate

        def failing(self):
            yield from orig(self)
            raise RuntimeError("boom")

        monkeypatch.setattr(ShardedBatchLoader, "_iterate", failing)
        loader = AsyncLoader(_dataset(4), batch_size=2, shuffle=False,
                             async_loader_queue_size=2)
        with pytest.raises(RuntimeError, match="boom"):
            list(loader)


def test_prefetch_to_device_roundtrip():
    import jax

    loader = ShardedBatchLoader(_dataset(), batch_size=8, shuffle=False)
    batches = list(prefetch_to_device(loader, size=2))
    assert len(batches) == 4
    assert all(isinstance(b["image"], jax.Array) for b in batches)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b["label"]) for b in batches]),
        np.arange(32))


def test_len_matches_iteration_with_uneven_shards():
    """Regression: __len__ must count the strided shard exactly."""
    for n, replicas, bs, drop in [(33, 2, 16, False), (33, 2, 16, True),
                                  (30, 4, 4, False), (31, 3, 5, True)]:
        data = {"label": np.arange(n)}
        for rank in range(replicas):
            loader = ShardedBatchLoader(data, batch_size=bs, shuffle=False,
                                        drop_last=drop, rank=rank,
                                        num_replicas=replicas)
            assert len(loader) == sum(1 for _ in loader), \
                (n, replicas, bs, drop, rank)
