"""Data-loader tests: sharding, async prefetch, device prefetch."""
from __future__ import annotations

import numpy as np

from horovod_tpu.data import (AsyncDataLoaderMixin, ShardedBatchLoader,
                              prefetch_to_device)


def _dataset(n=32):
    return {"image": np.arange(n * 4, dtype=np.float32).reshape(n, 4),
            "label": np.arange(n, dtype=np.int32)}


class TestShardedBatchLoader:
    def test_batches_cover_dataset(self):
        loader = ShardedBatchLoader(_dataset(), batch_size=8, shuffle=False)
        batches = list(loader)
        assert len(batches) == len(loader) == 4
        seen = np.concatenate([b["label"] for b in batches])
        np.testing.assert_array_equal(np.sort(seen), np.arange(32))

    def test_rank_sharding_is_disjoint_and_complete(self):
        loaders = [ShardedBatchLoader(_dataset(), batch_size=4,
                                      shuffle=True, seed=7, rank=r,
                                      num_replicas=2) for r in range(2)]
        seen = [np.concatenate([b["label"] for b in ld]) for ld in loaders]
        assert set(seen[0]) & set(seen[1]) == set()
        assert set(seen[0]) | set(seen[1]) == set(range(32))

    def test_epoch_changes_shuffle(self):
        loader = ShardedBatchLoader(_dataset(), batch_size=32, seed=1)
        first = next(iter(loader))["label"].copy()
        loader.set_epoch(1)
        second = next(iter(loader))["label"]
        assert not np.array_equal(first, second)

    def test_drop_last(self):
        loader = ShardedBatchLoader(_dataset(30), batch_size=8,
                                    shuffle=False, drop_last=True)
        assert len(loader) == 3
        assert sum(1 for _ in loader) == 3


class TestAsyncPrefetch:
    def test_same_batches_as_sync(self):
        class AsyncLoader(AsyncDataLoaderMixin, ShardedBatchLoader):
            pass

        sync = ShardedBatchLoader(_dataset(), batch_size=8, shuffle=False)
        async_ = AsyncLoader(_dataset(), batch_size=8, shuffle=False,
                             async_loader_queue_size=2)
        for a, b in zip(sync, async_):
            np.testing.assert_array_equal(a["label"], b["label"])

    def test_producer_error_propagates(self, monkeypatch):
        import pytest

        class AsyncLoader(AsyncDataLoaderMixin, ShardedBatchLoader):
            pass

        orig = ShardedBatchLoader._iterate

        def failing(self):
            yield from orig(self)
            raise RuntimeError("boom")

        monkeypatch.setattr(ShardedBatchLoader, "_iterate", failing)
        loader = AsyncLoader(_dataset(4), batch_size=2, shuffle=False,
                             async_loader_queue_size=2)
        with pytest.raises(RuntimeError, match="boom"):
            list(loader)


def test_prefetch_to_device_roundtrip():
    import jax

    loader = ShardedBatchLoader(_dataset(), batch_size=8, shuffle=False)
    batches = list(prefetch_to_device(loader, size=2))
    assert len(batches) == 4
    assert all(isinstance(b["image"], jax.Array) for b in batches)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b["label"]) for b in batches]),
        np.arange(32))


def test_len_matches_iteration_with_uneven_shards():
    """Regression: __len__ must count the strided shard exactly."""
    for n, replicas, bs, drop in [(33, 2, 16, False), (33, 2, 16, True),
                                  (30, 4, 4, False), (31, 3, 5, True)]:
        data = {"label": np.arange(n)}
        for rank in range(replicas):
            loader = ShardedBatchLoader(data, batch_size=bs, shuffle=False,
                                        drop_last=drop, rank=rank,
                                        num_replicas=replicas)
            assert len(loader) == sum(1 for _ in loader), \
                (n, replicas, bs, drop, rank)


class TestStoreShardReader:
    """Petastorm-reader-slot coverage (reference:
    spark/data_loaders/pytorch_data_loaders.py): shard round-trip through
    a Store, exactly-once row coverage across ranks, per-epoch reshuffle,
    and O(shard) residency via both store families."""

    def _dataset(self, n=40):
        return {"x": np.arange(n * 3, dtype=np.float32).reshape(n, 3),
                "y": np.arange(n, dtype=np.int32)}

    def _roundtrip(self, store):
        from horovod_tpu.data import StoreShardReader, write_dataset_shards

        data = self._dataset()
        base = store.get_train_data_path(store.new_run_id())
        keys = write_dataset_shards(store, base, data, num_shards=4)
        assert len(keys) == 4

        # Two ranks together see every row exactly once per epoch
        # (drop_last=False keeps the tail; shards of 10 divide evenly
        # over 2 ranks so no padding duplicates appear).
        seen = []
        for rank in range(2):
            reader = StoreShardReader(store, keys, batch_size=4,
                                      shuffle=True, seed=7, rank=rank,
                                      num_replicas=2, drop_last=False)
            n_batches = 0
            for batch in reader:
                assert batch["x"].shape[1] == 3
                assert len(batch["y"]) <= 4
                seen.extend(batch["y"].tolist())
                n_batches += 1
            assert n_batches == len(reader)
        assert sorted(seen) == list(range(40))

        # Epoch bump reshuffles deterministically.
        reader = StoreShardReader(store, keys, batch_size=4, shuffle=True,
                                  seed=7, rank=0, num_replicas=1,
                                  drop_last=False)
        first = [b["y"].tolist() for b in reader]
        again = [b["y"].tolist() for b in reader]
        assert first == again              # same epoch → same order
        reader.set_epoch(1)
        second = [b["y"].tolist() for b in reader]
        assert first != second
        flat = [y for b in second for y in b]
        assert sorted(flat) == list(range(40))

    def test_lockstep_step_counts_with_uneven_shards(self, tmp_path):
        """Rows not divisible by num_replicas: padding (wrapped indices,
        the DistributedSampler contract) must keep every rank's batch
        count IDENTICAL — a rank with an extra batch would hang the world
        in its collective."""
        from horovod_tpu.data import StoreShardReader, write_dataset_shards
        from horovod_tpu.spark import FilesystemStore

        store = FilesystemStore(str(tmp_path / "s"))
        data = {"y": np.arange(23, dtype=np.int64)}   # 3 ragged shards
        keys = write_dataset_shards(
            store, store.get_train_data_path(store.new_run_id()), data,
            num_shards=3)
        counts, rows_seen = [], []
        for rank in range(4):
            reader = StoreShardReader(store, keys, batch_size=1,
                                      shuffle=True, seed=3, rank=rank,
                                      num_replicas=4, drop_last=False)
            batches = list(reader)
            counts.append(len(batches))
            assert len(batches) == len(reader)
            rows_seen.extend(b["y"][0] for b in batches)
        assert len(set(counts)) == 1, counts     # lockstep
        assert set(rows_seen) == set(range(23))  # full coverage (+ pads)

    def test_filesystem_store(self, tmp_path):
        from horovod_tpu.spark import FilesystemStore
        self._roundtrip(FilesystemStore(str(tmp_path / "s")))

    def test_remote_kv_store(self):
        from horovod_tpu.runner.network import RendezvousServer
        from horovod_tpu.spark import KVBlobClient, RemoteBlobStore
        server = RendezvousServer()
        port = server.start()
        try:
            self._roundtrip(
                RemoteBlobStore(KVBlobClient("127.0.0.1", port), "ds"))
        finally:
            server.stop()

    def test_async_composition(self, tmp_path):
        from horovod_tpu.data import (AsyncDataLoaderMixin,
                                      StoreShardReader,
                                      write_dataset_shards)
        from horovod_tpu.spark import FilesystemStore

        class AsyncReader(AsyncDataLoaderMixin, StoreShardReader):
            pass

        store = FilesystemStore(str(tmp_path / "s"))
        keys = write_dataset_shards(
            store, store.get_train_data_path(store.new_run_id()),
            self._dataset(), num_shards=3)
        reader = AsyncReader(store, keys, batch_size=8, shuffle=False,
                             drop_last=False)
        rows = [y for b in reader for y in b["y"].tolist()]
        assert sorted(rows) == list(range(40))
